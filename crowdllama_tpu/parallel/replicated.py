"""Leader-replicated dispatch: the async serving engine on a multi-host
mesh (parallel/multihost.py's driving model, made real).

JAX's multi-controller rule: every process must issue the SAME jitted
calls in the SAME order, or the first cross-host collective deadlocks.
The serving engine is an asyncio scheduler making load-dependent
decisions (admission order, chunk sizes, slot placement) — so those
decisions are made ONCE, on process 0, and replicated as a stream of
fixed-shape command frames:

- the leader's engine wraps its runner in :class:`ReplicatedRunner`,
  which broadcasts one frame (op + scalar args + padded prompt + PRNG
  key data) before delegating each device-touching call to the real
  runner;
- every follower process runs :func:`run_follower`: build the identical
  runner (same config, same params — checkpoint bytes or seeded init),
  then replay frames forever.  Host-side bookkeeping (buckets, repeat
  rings, page growth) is derived only from frame contents, so it stays
  bit-identical everywhere.

Frames ride ``multihost_utils.broadcast_one_to_all`` — the same DCN
control plane as the mesh itself, no extra sockets.  Decode tokens come
back via a tiled ``process_allgather`` (collective, so it appears in the
frame stream symmetrically); that readback is synchronous, which gives
up the single-host double-buffered chunk overlap — the documented v1
cost of multi-host serving.

Scope: EVERY runner the single-host matrix serves — contiguous, paged,
and the speculative runners.  All replicated host state (the paged
allocator's free-page list / prefix-cache index / LRU ticks, the spec
runners' hist rows and per-slot prompt lengths, the draft model's
cache) is derived ONLY from the op stream, so replaying frames keeps
every process bit-identical: pre_decode_check growth and the warmup
ctx-prefill compile broadcast as their own ops, batch embeddings ride
one length-prefixed EMBED frame, and the spec runners' packed
[K, 2+J, B] emission block rides the same collective readback as plain
tokens.  The reference has no analog at any scope — its worker is
always one host (/root/reference/pkg/peer/peer.go:42-68).
"""

from __future__ import annotations

import logging

import numpy as np

log = logging.getLogger("crowdllama.parallel.replicated")

_OP_NOOP = 0
_OP_INIT = 1
_OP_PREFILL = 2
_OP_INSERT = 3
_OP_RELEASE = 4
_OP_DECODE = 5
_OP_PREFILL_BEGIN = 6
_OP_PREFILL_STEP = 7
_OP_PREFILL_FINISH = 8
_OP_STOP = 9
_OP_PREFILL_ABORT = 10
_OP_EMBED = 11
_OP_PRE_DECODE = 12
_OP_WARMUP_CTX = 13

_NI, _NF, _NK = 8, 4, 4  # frame scalar-int / float / key-word capacities

# Which header slot carries the prompt length for ops that stream one.
# EMBED streams a length-prefixed FLAT batch ([len0, t0.., len1, t1..]);
# slot 1 holds the flat array's total length (slot 0 = prompt count).
_PROMPT_LEN_SLOT = {_OP_PREFILL: 0, _OP_PREFILL_BEGIN: 0, _OP_INSERT: 4,
                    _OP_EMBED: 1}


def _prompt_len_of(op: int, i32) -> int:
    slot = _PROMPT_LEN_SLOT.get(int(op))
    return 0 if slot is None else int(i32[slot])


def _key_words(key) -> np.ndarray:
    import jax

    try:
        raw = np.asarray(jax.random.key_data(key))
    except TypeError:  # raw legacy uint32 key array
        raw = np.asarray(key)
    out = np.zeros((_NK,), np.uint32)
    out[: raw.size] = raw.ravel().astype(np.uint32)
    return out


_KEY_SIZE: int | None = None


def _default_key_size() -> int:
    """Word count of the configured PRNG impl's key (2 for threefry,
    4 for rbg) — identical on leader and followers (same jax config)."""
    global _KEY_SIZE
    if _KEY_SIZE is None:
        import jax

        probe = jax.random.PRNGKey(0)
        try:
            probe = jax.random.key_data(probe)
        except TypeError:
            pass
        _KEY_SIZE = int(np.asarray(probe).size)
    return _KEY_SIZE


def _key_from_words(words):
    import jax.numpy as jnp

    size = _default_key_size()
    return jnp.asarray(np.asarray(words)[:size].astype(np.uint32))


class ReplicatedRunner:
    """Leader-side proxy: broadcast a frame, then run the real call.

    Implements exactly the runner surface the Scheduler uses
    (engine/scheduler.py): init_state, prefill, prefill_begin/step/
    finish, insert, release, decode_steps_device — plus attribute
    passthrough for max_slots/max_seq/cfg/mesh.
    """

    defer_release = True  # releases broadcast; scheduler defers them
    # Adaptive draft-length retuning is leader-local state; followers
    # replay decode frames traced with their construction-time draft_len,
    # so a leader-side set_draft_len would silently diverge the replicated
    # programs.  Explicit class attribute (not __getattr__ passthrough)
    # so the scheduler's feature gate sees False even when the inner
    # runner supports it.
    supports_adaptive_draft = False
    # Ragged chunked prefill dispatches are leader-local (no replay frame
    # op yet); same explicit-False pattern keeps the scheduler on the
    # monolithic/legacy-chunked path for replicated engines.
    supports_ragged = False
    # Megastep decode (docs/MEGASTEP.md) has no replay frame op either,
    # and its done-flag early exit depends on leader-local eos/budget
    # inputs followers never see.  Explicit False — __getattr__ would
    # otherwise leak the inner runner's True.
    supports_megastep = False

    def __init__(self, inner):
        self.inner = inner
        if not hasattr(inner, "pre_decode_check"):
            # The scheduler feature-gates on this attribute being present
            # and non-None; shadow the class method for contiguous inners
            # (instance attribute wins the lookup).
            self.pre_decode_check = None

    def __getattr__(self, name):
        return getattr(self.inner, name)

    # ------------------------------------------------------------ frames

    def _bcast(self, op: int, ints=(), floats=(), key=None, prompt=()):
        """Two-phase frame: a fixed ~100-byte header always, the prompt
        as a second exact-length broadcast ONLY for ops that carry one —
        a max_seq-wide buffer on every decode dispatch would put 100s of
        KB of zeros on the DCN hot path at long contexts.  Both sides
        derive the second broadcast's shape from the header
        (_prompt_len_of), so the collective shapes always agree."""
        from crowdllama_tpu.parallel.multihost import broadcast_from_leader

        i32 = np.zeros((_NI,), np.int32)
        i32[: len(ints)] = list(ints)
        f32 = np.zeros((_NF,), np.float32)
        f32[: len(floats)] = list(floats)
        kw = _key_words(key) if key is not None else np.zeros((_NK,),
                                                             np.uint32)
        broadcast_from_leader({
            "op": np.int32(op), "i32": i32, "f32": f32, "key": kw,
        })
        n = _prompt_len_of(op, i32)
        if n:
            assert len(prompt) == n, (op, len(prompt), n)
            broadcast_from_leader(np.asarray(list(prompt), np.int32))

    def shutdown(self) -> None:
        """Release follower loops (engine stop)."""
        self._bcast(_OP_STOP)

    # ----------------------------------------------------- runner surface

    def init_state(self, seed: int = 0):
        self._bcast(_OP_INIT, ints=(int(seed),))
        return self.inner.init_state(seed)

    def prefill(self, prompt_ids, temperature, top_p, key, state=None,
                top_k: int = 0, repeat_penalty: float = 1.0):
        self._bcast(_OP_PREFILL, ints=(len(prompt_ids), int(top_k)),
                    floats=(float(temperature), float(top_p),
                            float(repeat_penalty)),
                    key=key, prompt=prompt_ids)
        return self.inner.prefill(prompt_ids, temperature, top_p, key,
                                  state=state, top_k=top_k,
                                  repeat_penalty=repeat_penalty)

    def prefill_begin(self, prompt_ids, state=None):
        self._bcast(_OP_PREFILL_BEGIN, ints=(len(prompt_ids),),
                    prompt=prompt_ids)
        return self.inner.prefill_begin(prompt_ids, state=state)

    def prefill_step(self, job) -> bool:
        self._bcast(_OP_PREFILL_STEP)
        return self.inner.prefill_step(job)

    def prefill_finish(self, job, temperature, top_p, key, top_k: int = 0,
                       repeat_penalty: float = 1.0):
        self._bcast(_OP_PREFILL_FINISH, ints=(int(top_k),),
                    floats=(float(temperature), float(top_p),
                            float(repeat_penalty)), key=key)
        return self.inner.prefill_finish(job, temperature, top_p, key,
                                         top_k=top_k,
                                         repeat_penalty=repeat_penalty)

    def prefill_abort(self, job) -> None:
        """Leader abandoned a chunked prefill (client cancelled mid-
        admission): tell followers to drop the job, or they keep its KV
        accumulators pinned until the next PREFILL_BEGIN replaces them."""
        self._bcast(_OP_PREFILL_ABORT)

    def insert(self, state, slot, ks, vs, plen, first, temperature, top_p,
               prompt_tokens=None, slot_key=None, top_k: int = 0,
               repeat_penalty: float = 1.0):
        prompt = list(prompt_tokens or [])
        self._bcast(_OP_INSERT, ints=(int(slot), int(plen), int(first),
                                      int(top_k), len(prompt),
                                      1 if slot_key is not None else 0),
                    floats=(float(temperature), float(top_p),
                            float(repeat_penalty)),
                    key=slot_key, prompt=prompt)
        return self.inner.insert(state, slot, ks, vs, plen, first,
                                 temperature, top_p,
                                 prompt_tokens=prompt_tokens,
                                 slot_key=slot_key, top_k=top_k,
                                 repeat_penalty=repeat_penalty)

    def release(self, state, slot):
        self._bcast(_OP_RELEASE, ints=(int(slot),))
        return self.inner.release(state, slot)

    def decode_steps_device(self, state, num_steps: int = 1):
        from jax.experimental import multihost_utils

        self._bcast(_OP_DECODE, ints=(int(num_steps),))
        toks, state = self.inner.decode_steps_device(state, num_steps)
        # Collective readback: followers mirror this gather (see
        # run_follower).  Returning HOST tokens keeps the scheduler's
        # np.asarray retirement a no-op.
        host = np.asarray(
            multihost_utils.process_allgather(toks, tiled=True))
        return host, state

    def decode_steps(self, state, num_steps: int = 1):
        tokens, state = self.decode_steps_device(state, num_steps)
        return np.asarray(tokens), state

    def pre_decode_check(self, steps: int):
        """Paged page-table growth is dispatch-time HOST bookkeeping that
        allocates pool pages — followers must replay it in stream order or
        their free lists (and thus page ids) diverge from the leader's."""
        self._bcast(_OP_PRE_DECODE, ints=(int(steps),))
        return self.inner.pre_decode_check(steps)

    def warmup_ctx_prefill(self, state) -> None:
        """Engine warmup compiles the suffix-over-cached-context program —
        a device computation, so every process must issue it."""
        self._bcast(_OP_WARMUP_CTX)
        return self.inner.warmup_ctx_prefill(state)

    def embed_prompts(self, prompts):
        """Batch embeddings (multi-host v2): the whole batch rides one
        frame as a length-prefixed flat token stream, so the follower's
        inner call keeps the same per-bucket batching as the leader's."""
        if not prompts:
            # No frame for an empty batch: the follower's decode of the
            # flat stream assumes at least one length prefix.
            return self.inner.embed_prompts(prompts)
        flat: list[int] = []
        for ids in prompts:
            flat.append(len(ids))
            flat.extend(int(t) for t in ids)
        self._bcast(_OP_EMBED, ints=(len(prompts), len(flat)), prompt=flat)
        return self.inner.embed_prompts(prompts)

    def embed_prompt(self, prompt_ids):
        return self.embed_prompts([prompt_ids])[0]


def run_follower(config) -> None:
    """Follower main loop: build the identical runner, replay the
    leader's frame stream until STOP.

    ``config`` must match the leader's engine-relevant fields (model,
    model_path, mesh, slots, context, quantize) — params are identical by
    construction (same checkpoint bytes or same seeded init).
    """
    import jax
    from jax.experimental import multihost_utils

    from crowdllama_tpu.engine.factory import build_runner
    from crowdllama_tpu.engine.plan import resolve_serving_plan
    from crowdllama_tpu.engine.weights import (
        load_params_for,
        resolve_clamped_model_config,
    )
    from crowdllama_tpu.parallel.multihost import broadcast_from_leader

    # The SAME plan/config/params derivation as the leader's engine, via
    # the shared factory (engine/factory.py) — the frame protocol depends
    # on both sides building bit-identical runners (contiguous, paged,
    # or speculative; draft params come from the same seeded init or
    # checkpoint bytes).
    plan = resolve_serving_plan(config, len(jax.devices()),
                                n_processes=jax.process_count())
    cfg = resolve_clamped_model_config(config)
    params = load_params_for(config, cfg)
    runner = build_runner(config, plan, cfg, params)
    log.info("follower %d up: %s (%s) on %d global devices",
             jax.process_index(), cfg.name, plan.runner,
             len(jax.devices()))

    state = None
    pending = None  # last prefill result awaiting insert
    job = None      # current chunked-prefill job
    # Set when an op failed here: a DETERMINISTIC error is mirrored on the
    # leader, whose recovery broadcasts INIT as its very next frame — so a
    # poisoned follower accepts only INIT (and NOOP/STOP).  Any other op
    # means the failure was follower-local (transient device error, local
    # OOM): per-shard state has diverged, and replaying frames against it
    # would make every collectively-computed decode silently corrupt the
    # tokens the LEADER serves.  Fail loudly instead — terminating the
    # follower turns the leader's next broadcast into a distributed-runtime
    # error rather than wrong output (ADVICE r4 medium).
    poisoned = False
    zero = {"op": np.int32(0), "i32": np.zeros((_NI,), np.int32),
            "f32": np.zeros((_NF,), np.float32),
            "key": np.zeros((_NK,), np.uint32)}
    while True:
        frame = broadcast_from_leader(zero)
        op = int(frame["op"])
        i32 = np.asarray(frame["i32"])
        f32 = np.asarray(frame["f32"])
        n_prompt = _prompt_len_of(op, i32)
        if n_prompt:
            frame = dict(frame)
            frame["prompt"] = np.asarray(broadcast_from_leader(
                np.zeros((n_prompt,), np.int32)))
        if op == _OP_STOP:
            log.info("follower %d: stop", jax.process_index())
            return
        if op in (_OP_NOOP,):
            continue
        if poisoned and op != _OP_INIT:
            raise RuntimeError(
                f"follower {jax.process_index()} state diverged from the "
                f"leader (a local op failure was not mirrored — next frame "
                f"was op {op}, not INIT); terminating so the divergence "
                f"fails loudly instead of serving corrupted tokens")
        try:
            state, pending, job = _apply(runner, state, pending, job, op,
                                         frame, i32, f32)
            poisoned = False
        except ValueError:
            # Request-level deterministic error: the leader catches exactly
            # ValueError at its admission sites (engine/scheduler.py), fails
            # only that request, and does NOT broadcast INIT — so the same
            # error here is mirrored, state has not diverged, and the
            # follower must keep replaying (poisoning would kill the
            # cluster on the next frame).  Device-local transients raise
            # XlaRuntimeError/OOM classes, never ValueError.
            log.warning("follower op %d: request-level error (mirrored on "
                        "the leader); continuing", op, exc_info=True)
            pending = None
            job = None
        except Exception:
            # Engine-level error: IF it was deterministic, the leader's
            # loop recovery mirrors it and broadcasts INIT, which rebuilds
            # state here.  Mark poisoned and clear transient op state; the
            # check above decides on the NEXT frame whether the leader
            # actually mirrored the failure.
            log.exception("follower op %d failed; awaiting leader recovery",
                          op)
            poisoned = True
            pending = None
            job = None


def _apply(runner, state, pending, job, op, frame, i32, f32):
    """Execute one frame; returns the updated (state, pending, job)."""
    from jax.experimental import multihost_utils

    if op == _OP_INIT:
        state = runner.init_state(int(i32[0]))
    elif op == _OP_PREFILL:
        n, top_k = int(i32[0]), int(i32[1])
        prompt = [int(t) for t in np.asarray(frame.get("prompt", []))[:n]]
        pending = runner.prefill(
            prompt, float(f32[0]), float(f32[1]),
            _key_from_words(frame["key"]), state=state, top_k=top_k,
            repeat_penalty=float(f32[2]))
    elif op == _OP_PREFILL_BEGIN:
        n = int(i32[0])
        prompt = [int(t) for t in np.asarray(frame["prompt"])[:n]]
        job = runner.prefill_begin(prompt, state=state)
    elif op == _OP_PREFILL_STEP:
        runner.prefill_step(job)
    elif op == _OP_PREFILL_ABORT:
        job = None
    elif op == _OP_PREFILL_FINISH:
        pending = runner.prefill_finish(
            job, float(f32[0]), float(f32[1]),
            _key_from_words(frame["key"]), top_k=int(i32[0]),
            repeat_penalty=float(f32[2]))
        job = None
    elif op == _OP_INSERT:
        slot, plen, first = int(i32[0]), int(i32[1]), int(i32[2])
        n_prompt, has_key = int(i32[4]), int(i32[5])
        prompt = ([int(t) for t in np.asarray(frame["prompt"])[:n_prompt]]
                  if n_prompt else None)
        slot_key = _key_from_words(frame["key"]) if has_key else None
        _tok, ks, vs, _plen = pending
        state = runner.insert(state, slot, ks, vs, plen, first,
                              float(f32[0]), float(f32[1]),
                              prompt_tokens=prompt, slot_key=slot_key,
                              top_k=int(i32[3]),
                              repeat_penalty=float(f32[2]))
        pending = None
    elif op == _OP_RELEASE:
        state = runner.release(state, int(i32[0]))
    elif op == _OP_DECODE:
        toks, state = runner.decode_steps_device(state, int(i32[0]))
        multihost_utils.process_allgather(toks, tiled=True)
    elif op == _OP_PRE_DECODE:
        runner.pre_decode_check(int(i32[0]))
    elif op == _OP_WARMUP_CTX:
        runner.warmup_ctx_prefill(state)
    elif op == _OP_EMBED:
        n, total = int(i32[0]), int(i32[1])
        flat = ([int(t) for t in np.asarray(frame["prompt"])[:total]]
                if n else [])
        prompts, pos = [], 0
        for _ in range(n):
            ln = flat[pos]
            prompts.append(flat[pos + 1: pos + 1 + ln])
            pos += 1 + ln
        runner.embed_prompts(prompts)
    else:
        raise RuntimeError(f"unknown replicated op {op}")
    return state, pending, job
