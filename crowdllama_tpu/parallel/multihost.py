"""Multi-host (multi-process) mesh support over DCN.

The reference's distribution stops at whole-request routing between
independent workers (libp2p streams; /root/reference/pkg/peermanager/
manager.go:338-387) — every worker is one host.  TPU pods are different:
one LOGICAL worker can span several hosts, each owning a slice of the
chip mesh, with XLA collectives riding ICI within a host and DCN between
hosts.  JAX's multi-controller model makes that almost free at the
compute layer: after ``jax.distributed.initialize``, ``jax.devices()``
returns the GLOBAL device list, so every existing mesh builder
(parallel/mesh.py), sharding rule, and jitted step in this codebase
spans hosts unchanged — XLA partitions collectives over ICI/DCN by
device topology.

What multi-controller DOES demand is SPMD discipline on the host side:
every process must issue the same sequence of jitted calls with the same
shapes.  The serving engine's driving model for that is
leader-replicated dispatch:

- process 0 runs the public surfaces (gateway, peer runtime, scheduler)
  and makes every admission decision;
- all processes execute the same runner calls in the same order, with
  host-side inputs (prompt tokens, slot choices, chunk sizes) broadcast
  from process 0 via :func:`broadcast_from_leader` before each dispatch;
- per-host state (page-table bookkeeping, RNG seeding) is derived only
  from broadcast inputs, so it stays bit-identical everywhere.

This module is the initialization + synchronization toolkit for that
model.  It is exercised for real by ``tests/test_multihost.py``, which
runs a 2-process × 4-virtual-device global mesh on CPU.
"""

from __future__ import annotations

import logging

log = logging.getLogger("crowdllama.parallel.multihost")


def initialize_from_config(config) -> bool:
    """``jax.distributed.initialize`` from Configuration fields, if set.

    MUST run before any JAX backend initializes (the CLI calls it right
    after config parsing).  Returns True when distributed mode is active.
    Fields: ``dist_coordinator`` ("host:port" of process 0),
    ``dist_num_processes``, ``dist_process_id``.
    """
    coord = getattr(config, "dist_coordinator", "")
    if not coord:
        return False
    import jax

    n = int(getattr(config, "dist_num_processes", 0) or 0)
    pid = int(getattr(config, "dist_process_id", -1))
    kwargs = {"coordinator_address": coord}
    if n > 0:
        kwargs["num_processes"] = n
    if pid >= 0:
        kwargs["process_id"] = pid
    try:
        jax.distributed.initialize(**kwargs)
    except RuntimeError as e:
        # Already initialized (engine restart in-process) is fine; a
        # mis-configured cluster is not.  jax's message is
        # "distributed.initialize should only be called once." — match
        # both phrasings defensively across versions.
        msg = str(e).lower()
        if "once" in msg or "already" in msg:
            log.debug("jax.distributed already initialized: %s", e)
        else:
            raise
    log.info("multi-host: process %d/%d, %d global / %d local devices",
             jax.process_index(), jax.process_count(),
             len(jax.devices()), len(jax.local_devices()))
    return True


def is_leader() -> bool:
    """True on process 0 (or in single-process mode) — the process that
    owns the gateway/peer/scheduler surfaces."""
    import jax

    return jax.process_index() == 0


def process_count() -> int:
    import jax

    return jax.process_count()


def broadcast_from_leader(value):
    """Replicate a host-side pytree of arrays/scalars from process 0 to
    every process (the admission-decision primitive of the leader-
    replicated dispatch model).  No-op in single-process mode."""
    import jax

    if jax.process_count() == 1:
        return value
    from jax.experimental import multihost_utils

    return multihost_utils.broadcast_one_to_all(value)


def barrier(name: str = "crowdllama") -> None:
    """Block until every process reaches this point (shutdown ordering,
    checkpoint promotion)."""
    import jax

    if jax.process_count() == 1:
        return
    from jax.experimental import multihost_utils

    multihost_utils.sync_global_devices(name)
