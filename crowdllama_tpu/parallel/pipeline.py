"""Pipeline parallelism over the ``pp`` mesh axis (GPipe-style, TPU-native).

The layer stack is sharded on its leading axis (parallel/sharding.py puts
``pp`` first in every stacked layer param and in the KV cache), so each
pipeline stage owns a contiguous slice of layers and its slice of the cache.
Activations move stage-to-stage with ``lax.ppermute`` over ICI; microbatches
keep every stage busy after the fill bubble (utilization n_mb/(n_mb+pp-1)).

Implementation: one ``shard_map`` manual only over ``pp``
(``axis_names={"pp"}``) — dp/sp/ep/tp stay GSPMD-auto inside the stage body,
so tensor-parallel psums etc. continue to be derived by the compiler and
compose with the pipeline for free.  The stage body reuses the exact layer
scans from models/transformer.py.  Partial-manual shard_map requires a jit
context: call ``pp_prefill`` / ``pp_decode_step`` under ``jax.jit`` (the
engine always does).

The reference has no model parallelism of any kind (SURVEY §2 "zero
model-parallelism strategies"); this is part of the TPU-native superset
(BASELINE configs 3-5 demand multi-chip sharding).
"""

from __future__ import annotations

import inspect

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, PartitionSpec as P

from crowdllama_tpu.models import transformer as T
from crowdllama_tpu.models.config import ModelConfig
from crowdllama_tpu.parallel.mesh import AXIS_PP

Params = dict

# Partial-manual shard_map (axis_names=) landed with the new jax.shard_map
# API; pp cannot work without it, so fail fast with a clear message.
_HAS_PARTIAL_MANUAL = (
    hasattr(jax, "shard_map")
    and "axis_names" in inspect.signature(jax.shard_map).parameters
)


def _require_partial_manual() -> None:
    if not _HAS_PARTIAL_MANUAL:
        raise RuntimeError(
            "pipeline parallelism needs jax.shard_map with axis_names= "
            "(partial-manual mode); upgrade jax or use a pp=1 mesh")


def pick_n_microbatches(batch: int, pp: int) -> int:
    """Largest divisor of ``batch`` that is ≤ pp (pipeline utilization wants
    n_mb close to pp, correctness needs batch % n_mb == 0)."""
    for n in range(min(pp, batch), 0, -1):
        if batch % n == 0:
            return n
    return 1


def _stage_perm(npp: int) -> list[tuple[int, int]]:
    # Stage r feeds stage r+1; the last stage's output is dropped (collected
    # into `outs` before the rotate).
    return [(i, i + 1) for i in range(npp - 1)]


def _mb_slice(x: jnp.ndarray, mb: jnp.ndarray, mb_size: int) -> jnp.ndarray:
    """Dynamic microbatch slice along the leading (batch) dim."""
    start = (jnp.clip(mb, 0, x.shape[0] // mb_size - 1) * mb_size,) + (0,) * (
        x.ndim - 1)
    return jax.lax.dynamic_slice(x, start, (mb_size,) + x.shape[1:])


def pp_prefill(
    params: Params,
    cfg: ModelConfig,
    tokens: jnp.ndarray,     # [B, T] int32
    positions: jnp.ndarray,  # [B, T] int32
    mesh: Mesh,
    kv_valid: jnp.ndarray | None = None,
    n_microbatches: int = 0,  # 0 → min(pp, B)
) -> tuple[jnp.ndarray, jnp.ndarray, jnp.ndarray]:
    """Pipelined full-prompt forward: (logits [B,T,V], k, v [L,B,Hkv,T,Dh])."""
    out_x, ks, vs = _pp_forward(params, cfg, tokens, positions, mesh,
                                kv_valid, n_microbatches)
    logits = T._unembed(params, cfg, out_x)
    return logits, ks, vs


def pp_hidden_states(
    params: Params,
    cfg: ModelConfig,
    tokens: jnp.ndarray,     # [B, T] int32
    positions: jnp.ndarray,  # [B, T] int32
    mesh: Mesh,
    kv_valid: jnp.ndarray | None = None,
    n_microbatches: int = 0,
) -> jnp.ndarray:
    """Final-norm hidden states [B, T, D] via the microbatch pipeline — the
    embeddings forward on pp meshes (the per-stage KV is computed by the
    shared pipeline body and discarded; embedding batches are small)."""
    out_x, _, _ = _pp_forward(params, cfg, tokens, positions, mesh,
                              kv_valid, n_microbatches)
    return T.rms_norm(out_x, params["final_norm"], cfg.rms_norm_eps,
                      plus_one=cfg.family == "gemma2")


def _pp_forward(
    params: Params,
    cfg: ModelConfig,
    tokens: jnp.ndarray,
    positions: jnp.ndarray,
    mesh: Mesh,
    kv_valid: jnp.ndarray | None,
    n_microbatches: int,
) -> tuple[jnp.ndarray, jnp.ndarray, jnp.ndarray]:
    """Shared pipeline body: (pre-final-norm activations [B,T,D], k, v)."""
    _require_partial_manual()
    npp = mesh.shape[AXIS_PP]
    b, t = tokens.shape
    n_mb = n_microbatches or pick_n_microbatches(b, npp)
    assert b % n_mb == 0, f"batch {b} must divide into {n_mb} microbatches"
    assert cfg.num_layers % npp == 0, (
        f"{cfg.num_layers} layers not divisible by pp={npp}")
    mb_size = b // n_mb
    if kv_valid is None:
        kv_valid = jnp.ones((b, t), bool)

    x = T._embed(params, cfg, tokens)  # [B, T, D]
    windows = T.layer_sliding_windows(cfg)
    hkv, dh = cfg.num_kv_heads, cfg.resolved_head_dim()
    l_local = cfg.num_layers // npp

    def body(layers_local, windows_local, x, positions, kv_valid):
        r = jax.lax.axis_index(AXIS_PP)
        carry = jnp.zeros((mb_size,) + x.shape[1:], x.dtype)
        ks = jnp.zeros((l_local, b, hkv, t, dh), x.dtype)
        vs = jnp.zeros_like(ks)
        outs = jnp.zeros((b,) + x.shape[1:], jnp.float32)

        def step(s, st):
            carry, ks, vs, outs = st
            mb_here = s - r  # microbatch at this stage (may be out of range)
            valid = (mb_here >= 0) & (mb_here < n_mb)
            x_in = jnp.where(r == 0, _mb_slice(x, jnp.int32(s), mb_size),
                             carry)
            y, k_loc, v_loc = T.scan_prefill_layers(
                layers_local, windows_local, cfg, x_in,
                _mb_slice(positions, mb_here, mb_size),
                kv_valid=_mb_slice(kv_valid, mb_here, mb_size),
                n_shards=mesh.size,  # residual axes may shard operands
            )
            # Select at microbatch granularity (write back the old slice
            # when invalid) so the big buffers stay in-place DUS carries —
            # a full-buffer jnp.where would copy them every pipeline step.
            mb_start = jnp.clip(mb_here, 0, n_mb - 1) * mb_size
            k_start = (0, mb_start, 0, 0, 0)
            k_old = jax.lax.dynamic_slice(ks, k_start, k_loc.shape)
            ks = jax.lax.dynamic_update_slice(
                ks, jnp.where(valid, k_loc.astype(ks.dtype), k_old), k_start)
            v_old = jax.lax.dynamic_slice(vs, k_start, v_loc.shape)
            vs = jax.lax.dynamic_update_slice(
                vs, jnp.where(valid, v_loc.astype(vs.dtype), v_old), k_start)
            o_start = (mb_start,) + (0,) * (outs.ndim - 1)
            o_old = jax.lax.dynamic_slice(
                outs, o_start, (mb_size,) + outs.shape[1:])
            outs = jax.lax.dynamic_update_slice(
                outs,
                jnp.where(valid & (r == npp - 1), y.astype(outs.dtype), o_old),
                o_start)
            carry = jax.lax.ppermute(y, AXIS_PP, _stage_perm(npp))
            return carry, ks, vs, outs

        _, ks, vs, outs = jax.lax.fori_loop(
            0, n_mb + npp - 1, step, (carry, ks, vs, outs))
        # Only the last stage holds the final activations; replicate them.
        outs = jax.lax.psum(
            jnp.where(r == npp - 1, outs, jnp.zeros_like(outs)), AXIS_PP)
        return outs, ks, vs

    out_x, ks, vs = jax.shard_map(
        body, mesh=mesh,
        in_specs=(P(AXIS_PP), P(AXIS_PP), P(), P(), P()),
        out_specs=(P(), P(AXIS_PP), P(AXIS_PP)),
        axis_names={AXIS_PP},
        check_vma=False,
    )(params["layers"], windows, x, positions, kv_valid)
    return out_x.astype(x.dtype), ks, vs


def pp_decode_step(
    params: Params,
    cfg: ModelConfig,
    tokens: jnp.ndarray,     # [B] int32
    positions: jnp.ndarray,  # [B] int32
    k_cache: jnp.ndarray,    # [L, B, Hkv, S, Dh] (pp-sharded on L)
    v_cache: jnp.ndarray,
    seq_lens: jnp.ndarray,   # [B]
    mesh: Mesh,
    n_microbatches: int = 0,  # 0 → min(pp, B)
) -> tuple[jnp.ndarray, jnp.ndarray, jnp.ndarray]:
    """Pipelined decode: (logits [B,V], k_cache, v_cache).

    Microbatches over batch slots so all stages decode concurrently after
    the fill bubble; each stage updates only its local cache slice.
    """
    _require_partial_manual()
    npp = mesh.shape[AXIS_PP]
    b = tokens.shape[0]
    n_mb = n_microbatches or pick_n_microbatches(b, npp)
    assert b % n_mb == 0, f"batch {b} must divide into {n_mb} microbatches"
    assert cfg.num_layers % npp == 0, (
        f"{cfg.num_layers} layers not divisible by pp={npp}")
    mb_size = b // n_mb
    l_local = cfg.num_layers // npp

    x = T._embed(params, cfg, tokens)  # [B, D]
    windows = T.layer_sliding_windows(cfg)

    def body(layers_local, windows_local, x, positions, kc, vc, seq_lens):
        r = jax.lax.axis_index(AXIS_PP)
        carry = jnp.zeros((mb_size,) + x.shape[1:], x.dtype)
        outs = jnp.zeros((b,) + x.shape[1:], jnp.float32)

        def step(s, st):
            carry, kc, vc, outs = st
            mb_here = s - r
            valid = (mb_here >= 0) & (mb_here < n_mb)
            mb_start = jnp.clip(mb_here, 0, n_mb - 1) * mb_size
            x_in = jnp.where(r == 0, _mb_slice(x, jnp.int32(s), mb_size),
                             carry)
            kc_mb = jax.lax.dynamic_slice(
                kc, (0, mb_start, 0, 0, 0),
                (l_local, mb_size) + kc.shape[2:])
            vc_mb = jax.lax.dynamic_slice(
                vc, (0, mb_start, 0, 0, 0),
                (l_local, mb_size) + vc.shape[2:])
            y, kc_mb, vc_mb = T.scan_decode_layers(
                layers_local, windows_local, cfg, x_in,
                _mb_slice(positions, mb_here, mb_size),
                kc_mb, vc_mb, _mb_slice(seq_lens, mb_here, mb_size),
                n_shards=mesh.size,
            )
            # Microbatch-granular select (see pp_prefill): the cache is the
            # big buffer here — never jnp.where over the whole thing.
            kc_old = jax.lax.dynamic_slice(
                kc, (0, mb_start, 0, 0, 0), kc_mb.shape)
            vc_old = jax.lax.dynamic_slice(
                vc, (0, mb_start, 0, 0, 0), vc_mb.shape)
            kc = jax.lax.dynamic_update_slice(
                kc, jnp.where(valid, kc_mb, kc_old), (0, mb_start, 0, 0, 0))
            vc = jax.lax.dynamic_update_slice(
                vc, jnp.where(valid, vc_mb, vc_old), (0, mb_start, 0, 0, 0))
            o_old = jax.lax.dynamic_slice(
                outs, (mb_start, 0), (mb_size, outs.shape[1]))
            outs = jax.lax.dynamic_update_slice(
                outs,
                jnp.where(valid & (r == npp - 1), y.astype(outs.dtype), o_old),
                (mb_start, 0))
            carry = jax.lax.ppermute(y, AXIS_PP, _stage_perm(npp))
            return carry, kc, vc, outs

        _, kc, vc, outs = jax.lax.fori_loop(
            0, n_mb + npp - 1, step, (carry, kc, vc, outs))
        outs = jax.lax.psum(
            jnp.where(r == npp - 1, outs, jnp.zeros_like(outs)), AXIS_PP)
        return outs, kc, vc

    cache_spec = P(AXIS_PP)  # layer dim manual; others GSPMD-auto
    out_x, k_cache, v_cache = jax.shard_map(
        body, mesh=mesh,
        in_specs=(P(AXIS_PP), P(AXIS_PP), P(), P(), cache_spec, cache_spec,
                  P()),
        out_specs=(P(), cache_spec, cache_spec),
        axis_names={AXIS_PP},
        check_vma=False,
    )(params["layers"], windows, x, positions, k_cache, v_cache, seq_lens)
    logits = T._unembed(params, cfg, out_x.astype(x.dtype))
    return logits, k_cache, v_cache
