"""Peer manager: peer table, health state machine, worker scheduler.

Functional counterpart of /root/reference/pkg/peermanager/manager.go — the
most intricate logic in the reference, kept with its constants as defaults
(SURVEY §7 build order 4):

- PeerInfo records with failure counts (manager.go:106-116)
- add/update/remove with a 10-minute ``recently_removed`` quarantine against
  flapping re-adds (manager.go:179-274)
- worker/consumer filters (manager.go:287-307)
- scheduler: filter by supported model, maximize throughput/(1+load)
  (manager.go:338-387); extended with shard-group awareness for multi-worker
  models (only complete groups are routable)
- background loops: discovery, health probing with 3-strikes + linear
  backoff, stale cleanup (manager.go:440-622) — asyncio tasks instead of
  goroutines, intervals from config.Intervals (test-mode aware)
"""

from __future__ import annotations

import asyncio
import logging
import random
import time
from dataclasses import dataclass, field
from typing import Awaitable, Callable

from crowdllama_tpu.config import Intervals
from crowdllama_tpu.core.resource import Resource

log = logging.getLogger("crowdllama.peermanager")

# Async callback fetching fresh metadata for a peer id; raises on failure.
MetadataFetcher = Callable[[str], Awaitable[Resource]]
# Async callback running one discovery round, returning found resources.
DiscoveryFunc = Callable[[set[str]], Awaitable[list[Resource]]]


@dataclass
class PeerHealthConfig:
    """Mirrors DefaultPeerHealthConfig (manager.go:66-104), via Intervals."""

    intervals: Intervals = field(default_factory=Intervals.default)

    @property
    def stale_after(self) -> float:
        return self.intervals.stale_after

    @property
    def max_failed_attempts(self) -> int:
        return self.intervals.max_failed_attempts

    @property
    def backoff_base(self) -> float:
        return self.intervals.backoff_base


@dataclass
class PeerInfo:
    """One row of the peer table (cf. manager.go:106-116)."""

    peer_id: str
    resource: Resource
    last_seen: float = field(default_factory=time.monotonic)
    failed_attempts: int = 0
    is_healthy: bool = True
    next_check_at: float = 0.0

    @property
    def is_worker(self) -> bool:
        return self.resource.worker_mode


class PeerManager:
    def __init__(
        self,
        self_peer_id: str = "",
        config: PeerHealthConfig | None = None,
        metadata_fetcher: MetadataFetcher | None = None,
        discovery: DiscoveryFunc | None = None,
        on_peer_removed: Callable[[str], None] | None = None,
    ):
        self.self_peer_id = self_peer_id
        self.config = config or PeerHealthConfig()
        self.metadata_fetcher = metadata_fetcher
        self.discovery = discovery
        # Fired on eviction so other layers (e.g. the local DHT's provider
        # store, net/dht.py evict_peer) drop the dead peer immediately.
        self.on_peer_removed = on_peer_removed
        self.peers: dict[str, PeerInfo] = {}
        self.recently_removed: dict[str, float] = {}  # peer_id -> removed_at
        self._tasks: list[asyncio.Task] = []

    # ------------------------------------------------------------- mutation

    def add_or_update_peer(self, resource: Resource) -> None:
        pid = resource.peer_id
        if not pid or pid == self.self_peer_id:
            return
        if pid in self.recently_removed:
            # Quarantined: rejects flap re-adds unless genuinely fresh
            # (manager.go:254-274 unquarantines on new metadata).
            if resource.age_seconds > self.config.intervals.metadata_max_age:
                return
            del self.recently_removed[pid]
        info = self.peers.get(pid)
        if info is None:
            self.peers[pid] = PeerInfo(peer_id=pid, resource=resource)
        else:
            info.resource = resource
            info.last_seen = time.monotonic()
            info.failed_attempts = 0
            info.is_healthy = True

    def remove_peer(self, peer_id: str, quarantine: bool = True) -> None:
        if self.peers.pop(peer_id, None) is not None:
            if quarantine:
                self.recently_removed[peer_id] = time.monotonic()
            if self.on_peer_removed is not None:
                try:
                    self.on_peer_removed(peer_id)
                except Exception:
                    log.debug("on_peer_removed callback failed", exc_info=True)

    def mark_seen(self, peer_id: str) -> None:
        info = self.peers.get(peer_id)
        if info is not None:
            info.last_seen = time.monotonic()

    # -------------------------------------------------------------- queries

    def get_peer(self, peer_id: str) -> PeerInfo | None:
        return self.peers.get(peer_id)

    def get_healthy_peers(self) -> list[PeerInfo]:
        return [p for p in self.peers.values() if p.is_healthy]

    def get_workers(self) -> list[PeerInfo]:
        return [p for p in self.peers.values() if p.is_worker]

    def get_consumers(self) -> list[PeerInfo]:
        return [p for p in self.peers.values() if not p.is_worker]

    def is_peer_unhealthy(self, peer_id: str) -> bool:
        info = self.peers.get(peer_id)
        return info is not None and not info.is_healthy

    def skip_set(self) -> set[str]:
        """Peers discovery should skip: EVERY known peer plus the
        quarantine set (cf. discovery.go:292, which skips unhealthy).

        Known-healthy peers are skipped too because their metadata is
        already refreshed by the health loop (health_check_peer's live
        fetch) — re-fetching it each discovery round made steady-state
        control-plane streams O(N x providers) per round and was the
        dominant chatter term in the 16-worker scaling cliff.  Discovery's
        job here is finding NEW providers only."""
        return set(self.peers) | set(self.recently_removed)

    # ------------------------------------------------------------ scheduler

    def is_routable(self, peer_id: str, model: str,
                    _groups: set | None = None) -> "PeerInfo | None":
        """The PeerInfo for ``peer_id`` iff requests for ``model`` may be
        sent to it RIGHT NOW — the same predicate find_best_worker scores
        over (healthy worker, serves the model, complete shard group,
        group leader).  Used by affinity-style callers that want to pin a
        specific worker without bypassing routability.  ``_groups`` lets
        the scoring loop precompute the complete-group set once."""
        p = self.peers.get(peer_id)
        if p is None or not p.is_healthy or not p.is_worker:
            return None
        r = p.resource
        if model and model not in r.supported_models:
            return None
        if r.shard_group is not None:
            groups = (_groups if _groups is not None
                      else self._complete_groups(model))
            if r.shard_group.group_id not in groups:
                return None
            if r.shard_group.shard_index != 0:
                return None
        return p

    def find_best_worker(
        self, model: str, exclude: set[str] = frozenset(),
        require_embeddings: bool = False,
    ) -> PeerInfo | None:
        """Model-filtered best worker by throughput/(1+load)
        (manager.go:338-387).  Workers in an incomplete shard group are not
        routable (multi-worker models need the full group); ``exclude`` lets
        callers fail over past workers that just errored."""
        groups = self._complete_groups(model)
        best, best_score = [], -1.0
        for p in self.get_healthy_peers():
            if p.peer_id in exclude:
                continue
            if self.is_routable(p.peer_id, model, _groups=groups) is None:
                continue
            r = p.resource
            if require_embeddings and not r.embeddings:
                continue
            score = r.tokens_throughput / (1.0 + max(r.load, 0.0))
            if score > best_score:
                best, best_score = [p], score
            elif score == best_score:
                best.append(p)
        # Random tie-break: workers that advertise identical capability
        # (fresh swarms, uniform hardware) would otherwise ALL receive every
        # request at the same single worker until its load EMA moves.
        return random.choice(best) if best else None

    def group_members(self, group_id: str) -> list[PeerInfo]:
        return sorted(
            (p for p in self.get_healthy_peers()
             if p.resource.shard_group is not None
             and p.resource.shard_group.group_id == group_id),
            key=lambda p: p.resource.shard_group.shard_index,
        )

    def _complete_groups(self, model: str) -> set[str]:
        seen: dict[str, set[int]] = {}
        want: dict[str, int] = {}
        for p in self.get_healthy_peers():
            sg = p.resource.shard_group
            if sg is None or (model and sg.model != model):
                continue
            seen.setdefault(sg.group_id, set()).add(sg.shard_index)
            want[sg.group_id] = sg.shard_count
        return {
            gid for gid, idxs in seen.items()
            if len(idxs) == want[gid] and idxs == set(range(want[gid]))
        }

    # ------------------------------------------------------- health machine

    async def health_check_peer(self, info: PeerInfo) -> bool:
        """Active probe: live metadata fetch with timeout
        (manager.go:592-622).  3 strikes → unhealthy; linear backoff
        failed_attempts × backoff_base (manager.go:540-564)."""
        if self.metadata_fetcher is None:
            return info.is_healthy
        try:
            resource = await asyncio.wait_for(
                self.metadata_fetcher(info.peer_id),
                self.config.intervals.metadata_timeout,
            )
            info.resource = resource
            info.last_seen = time.monotonic()
            info.failed_attempts = 0
            info.is_healthy = True
            return True
        except Exception as e:
            info.failed_attempts += 1
            info.next_check_at = (
                time.monotonic() + info.failed_attempts * self.config.backoff_base
            )
            if info.failed_attempts >= self.config.max_failed_attempts:
                info.is_healthy = False
            log.debug("health probe failed for %s (%d/%d): %s",
                      info.peer_id[:8], info.failed_attempts,
                      self.config.max_failed_attempts, e)
            return False

    #: Concurrent health probes per tick: each probe is a full
    #: handshake-priced stream; an uncapped gather over a 16-peer table
    #: bursts them all at once and spikes event-loop lag on small hosts.
    _HEALTH_CONCURRENCY = 4

    async def perform_health_checks(self) -> None:
        now = time.monotonic()
        sem = asyncio.Semaphore(self._HEALTH_CONCURRENCY)

        async def probe(p):
            async with sem:
                await self.health_check_peer(p)

        await asyncio.gather(*(
            probe(p)
            for p in list(self.peers.values())
            if p.next_check_at <= now
        ))

    def perform_cleanup(self) -> None:
        """Evict peers unseen past stale_after; purge old quarantine entries
        (manager.go:568-589)."""
        now = time.monotonic()
        for pid, info in list(self.peers.items()):
            if now - info.last_seen > self.config.stale_after:
                log.info("evicting stale peer %s", pid[:8])
                self.remove_peer(pid)
        cutoff = now - self.config.intervals.quarantine
        self.recently_removed = {
            pid: t for pid, t in self.recently_removed.items() if t > cutoff
        }

    async def run_discovery_once(self) -> None:
        if self.discovery is None:
            return
        try:
            found = await self.discovery(self.skip_set())
        except Exception as e:
            log.debug("discovery round failed: %s", e)
            return
        for resource in found:
            self.add_or_update_peer(resource)

    # ----------------------------------------------------------- lifecycle

    def start(self) -> None:
        from crowdllama_tpu.utils.aio import run_every

        iv = self.config.intervals
        self._tasks = [
            asyncio.create_task(run_every(iv.discovery, self.run_discovery_once, log),
                                name="pm-discovery"),
            asyncio.create_task(run_every(iv.health_check, self.perform_health_checks, log),
                                name="pm-health"),
            asyncio.create_task(run_every(iv.cleanup, self.perform_cleanup, log),
                                name="pm-cleanup"),
        ]

    async def stop(self) -> None:
        for t in self._tasks:
            t.cancel()
        for t in self._tasks:
            try:
                await t
            except asyncio.CancelledError:
                pass
        self._tasks = []
