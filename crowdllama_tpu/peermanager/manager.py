"""Peer manager: peer table, health state machine, worker scheduler.

Functional counterpart of /root/reference/pkg/peermanager/manager.go — the
most intricate logic in the reference, kept with its constants as defaults
(SURVEY §7 build order 4):

- PeerInfo records with failure counts (manager.go:106-116)
- add/update/remove with a 10-minute ``recently_removed`` quarantine against
  flapping re-adds (manager.go:179-274)
- worker/consumer filters (manager.go:287-307)
- scheduler: filter by supported model, maximize throughput/(1+load)
  (manager.go:338-387); extended with shard-group awareness for multi-worker
  models (only complete groups are routable)
- background loops: discovery, health probing with 3-strikes + linear
  backoff, stale cleanup (manager.go:440-622) — asyncio tasks instead of
  goroutines, intervals from config.Intervals (test-mode aware)

Request hot path is O(1) in swarm size: ``find_best_worker`` scores over a
cached per-model ROUTING SNAPSHOT (the eligible-worker list, with each
worker's score precomputed) instead of re-filtering the whole peer table
per request.  The snapshot is invalidated by an epoch counter bumped only
on metadata/health EVENTS (add/update/remove, health flips, probe
refreshes), so N requests between two events pay one rebuild, not N table
scans — the O(N)-per-request term behind the round-5 16-worker
cpu_us_per_request growth (VERDICT r5 weak #1).
"""

from __future__ import annotations

import asyncio
import logging
import random
import time
from dataclasses import dataclass, field
from typing import Awaitable, Callable

from crowdllama_tpu.config import Intervals
from crowdllama_tpu.core.resource import Resource

log = logging.getLogger("crowdllama.peermanager")

# Async callback fetching fresh metadata for a peer id; raises on failure.
MetadataFetcher = Callable[[str], Awaitable[Resource]]
# Async callback running one discovery round, returning found resources.
DiscoveryFunc = Callable[[set[str]], Awaitable[list[Resource]]]


@dataclass
class PeerHealthConfig:
    """Mirrors DefaultPeerHealthConfig (manager.go:66-104), via Intervals."""

    intervals: Intervals = field(default_factory=Intervals.default)

    @property
    def stale_after(self) -> float:
        return self.intervals.stale_after

    @property
    def max_failed_attempts(self) -> int:
        return self.intervals.max_failed_attempts

    @property
    def backoff_base(self) -> float:
        return self.intervals.backoff_base


@dataclass
class PeerInfo:
    """One row of the peer table (cf. manager.go:106-116)."""

    peer_id: str
    resource: Resource
    last_seen: float = field(default_factory=time.monotonic)
    failed_attempts: int = 0
    is_healthy: bool = True
    next_check_at: float = 0.0

    @property
    def is_worker(self) -> bool:
        return self.resource.worker_mode


@dataclass
class _RouteSnapshot:
    """Cached routing view for one model: every worker a request for the
    model may be sent to RIGHT NOW, with its throughput/(1+load) score
    precomputed.  Valid while the manager's routing epoch is unchanged;
    entries hold live PeerInfo references, so a worker that dies between
    the triggering event and the epoch-check (or through a path that
    forgot to bump) is still skipped by the scan's is_healthy guard."""

    epoch: int
    entries: list[tuple[PeerInfo, float]]
    ids: frozenset[str]


class PeerManager:
    def __init__(
        self,
        self_peer_id: str = "",
        config: PeerHealthConfig | None = None,
        metadata_fetcher: MetadataFetcher | None = None,
        discovery: DiscoveryFunc | None = None,
        on_peer_removed: Callable[[str], None] | None = None,
        on_draining: Callable[[str], None] | None = None,
    ):
        self.self_peer_id = self_peer_id
        self.config = config or PeerHealthConfig()
        self.metadata_fetcher = metadata_fetcher
        self.discovery = discovery
        # Fired on eviction so other layers (e.g. the local DHT's provider
        # store, net/dht.py evict_peer) drop the dead peer immediately.
        self.on_peer_removed = on_peer_removed
        # Fired on a FIRST mark_draining so the replicated-gateway gossip
        # plane (swarm/gossip.py) can publish the quarantine to the other
        # replicas; one replica observing a MigrateFrame stops ALL
        # replicas routing to the drained worker within a gossip round.
        self.on_draining = on_draining
        self.peers: dict[str, PeerInfo] = {}
        self.recently_removed: dict[str, float] = {}  # peer_id -> removed_at
        self._tasks: list[asyncio.Task] = []
        # Routing-snapshot state (see module docstring): epoch bumps on
        # every event that can change routability or scores; snapshots are
        # lazily rebuilt per model on the first request after a bump.
        self._route_epoch = 0
        self._route_cache: dict[str, _RouteSnapshot] = {}
        self.route_snapshot_rebuilds = 0  # stat: rebuilds (not lookups)
        # Discovery idle backoff: consecutive rounds that found nothing
        # stretch the discovery cadence (capped), so a settled swarm stops
        # paying per-interval provider lookups that cannot find anyone new.
        self._discovery_idle_rounds = 0

    # ------------------------------------------------------------- mutation

    @property
    def routing_epoch(self) -> int:
        """Monotonic counter of routing-relevant events (metadata updates,
        peer add/remove, health flips).  Snapshots built at an older epoch
        are stale; equal epochs guarantee an identical eligible set."""
        return self._route_epoch

    def _bump_routing_epoch(self) -> None:
        self._route_epoch += 1

    def add_or_update_peer(self, resource: Resource) -> None:
        pid = resource.peer_id
        if not pid or pid == self.self_peer_id:
            return
        if pid in self.recently_removed:
            # Quarantined: rejects flap re-adds unless genuinely fresh
            # (manager.go:254-274 unquarantines on new metadata).
            if resource.age_seconds > self.config.intervals.metadata_max_age:
                return
            del self.recently_removed[pid]
        info = self.peers.get(pid)
        if info is None:
            self.peers[pid] = PeerInfo(peer_id=pid, resource=resource)
        else:
            info.resource = resource
            info.last_seen = time.monotonic()
            info.failed_attempts = 0
            info.is_healthy = True
        # Metadata carries the load/throughput the scores derive from:
        # every accepted update is a routing event.
        self._bump_routing_epoch()

    # Quarantine-map hard cap: a long-lived gateway under heavy churn must
    # not grow recently_removed without bound (entries only veto re-adds;
    # beyond the cap the OLDEST vetoes are the least useful, so those are
    # dropped first).  perform_cleanup() sweeps expired entries on its
    # normal cadence; this cap is the backstop between sweeps.
    _QUARANTINE_MAX = 4096

    def remove_peer(self, peer_id: str, quarantine: bool = True) -> None:
        if self.peers.pop(peer_id, None) is not None:
            if quarantine:
                self.recently_removed[peer_id] = time.monotonic()
                if len(self.recently_removed) > self._QUARANTINE_MAX:
                    excess = (len(self.recently_removed)
                              - self._QUARANTINE_MAX)
                    for pid in sorted(self.recently_removed,
                                      key=self.recently_removed.get
                                      )[:excess]:
                        del self.recently_removed[pid]
            self._bump_routing_epoch()
            # A shrinking table should search for replacements promptly.
            self._discovery_idle_rounds = 0
            if self.on_peer_removed is not None:
                try:
                    self.on_peer_removed(peer_id)
                except Exception:
                    log.debug("on_peer_removed callback failed", exc_info=True)

    def mark_seen(self, peer_id: str) -> None:
        info = self.peers.get(peer_id)
        if info is not None:
            info.last_seen = time.monotonic()

    def mark_draining(self, peer_id: str, reason: str = "drain") -> bool:
        """Quarantine ``peer_id`` from routing IMMEDIATELY (epoch bump).

        Called by the gateway the moment it sees a MigrateFrame or a
        ``draining`` reject — metadata propagation (the drained worker's
        final publish + our next health probe) confirms it within an
        interval, but new requests must stop landing on the worker NOW,
        not a probe later.  The peer stays in the table (healthy, still a
        KV donor); only the routing snapshot excludes it.

        ``reason`` records WHY the quarantine happened: ``"drain"`` for
        an announced graceful handoff, ``"wedged"`` when the gateway's
        per-stream progress watchdog caught a gray failure — a worker
        that still answers health probes but stopped making token
        progress, which the ordinary probe plane would never evict
        (docs/ROBUSTNESS.md)."""
        info = self.peers.get(peer_id)
        if info is None or getattr(info.resource, "draining", False):
            return False
        info.resource.draining = True
        info.resource.draining_reason = reason
        self._bump_routing_epoch()
        if self.on_draining is not None:
            try:
                self.on_draining(peer_id)
            except Exception:
                log.debug("on_draining callback failed", exc_info=True)
        return True

    # -------------------------------------------------------------- queries

    def get_peer(self, peer_id: str) -> PeerInfo | None:
        return self.peers.get(peer_id)

    def get_healthy_peers(self) -> list[PeerInfo]:
        return [p for p in self.peers.values() if p.is_healthy]

    def get_workers(self) -> list[PeerInfo]:
        return [p for p in self.peers.values() if p.is_worker]

    def get_consumers(self) -> list[PeerInfo]:
        return [p for p in self.peers.values() if not p.is_worker]

    def is_peer_unhealthy(self, peer_id: str) -> bool:
        info = self.peers.get(peer_id)
        return info is not None and not info.is_healthy

    def skip_set(self) -> set[str]:
        """Peers discovery should skip: EVERY known peer plus the
        quarantine set (cf. discovery.go:292, which skips unhealthy).

        Known-healthy peers are skipped too because their metadata is
        already refreshed by the health loop (health_check_peer's live
        fetch) — re-fetching it each discovery round made steady-state
        control-plane streams O(N x providers) per round and was the
        dominant chatter term in the 16-worker scaling cliff.  Discovery's
        job here is finding NEW providers only."""
        return set(self.peers) | set(self.recently_removed)

    # ------------------------------------------------------------ scheduler

    def _routing_snapshot(self, model: str) -> _RouteSnapshot:
        """The cached eligible-worker snapshot for ``model``, rebuilt only
        when the routing epoch moved since the last build.  The rebuild is
        the ONLY full-table scan on the request path; between events it is
        a dict lookup plus an int compare."""
        snap = self._route_cache.get(model)
        if snap is not None and snap.epoch == self._route_epoch:
            return snap
        groups = self._complete_groups(model)
        entries: list[tuple[PeerInfo, float]] = []
        for p in self.peers.values():
            if not p.is_healthy or not p.is_worker:
                continue
            r = p.resource
            # Draining workers are quarantined from NEW work but stay in
            # the table: they keep serving KV fetches for the streams that
            # migrated off them (docs/ROBUSTNESS.md).
            if getattr(r, "draining", False):
                continue
            if model and model not in r.supported_models:
                continue
            sg = r.shard_group
            if sg is not None and (sg.group_id not in groups
                                   or sg.shard_index != 0):
                continue
            entries.append((p, r.tokens_throughput / (1.0 + max(r.load, 0.0))))
        snap = _RouteSnapshot(epoch=self._route_epoch, entries=entries,
                              ids=frozenset(p.peer_id for p, _ in entries))
        if len(self._route_cache) >= 64:
            # Requests for arbitrary unknown model names must not grow the
            # cache without bound; real deployments serve a handful.
            self._route_cache.clear()
        self._route_cache[model] = snap
        self.route_snapshot_rebuilds += 1
        return snap

    def is_routable(self, peer_id: str, model: str) -> "PeerInfo | None":
        """The PeerInfo for ``peer_id`` iff requests for ``model`` may be
        sent to it RIGHT NOW — the same predicate find_best_worker scores
        over (healthy worker, serves the model, complete shard group,
        group leader).  Used by affinity-style callers that want to pin a
        specific worker without bypassing routability; answered from the
        routing snapshot, so it costs a set lookup per call."""
        p = self.peers.get(peer_id)
        if p is None or not p.is_healthy:
            return None
        if peer_id not in self._routing_snapshot(model).ids:
            return None
        return p

    def find_best_worker(
        self, model: str, exclude: set[str] = frozenset(),
        require_embeddings: bool = False,
    ) -> PeerInfo | None:
        """Model-filtered best worker by throughput/(1+load)
        (manager.go:338-387), served from the routing snapshot: one
        O(eligible) pass over precomputed scores, no per-call re-filter of
        the full peer table.  Workers in an incomplete shard group are not
        routable (multi-worker models need the full group); ``exclude``
        lets callers fail over past workers that just errored.

        Ties (fresh swarms advertising identical capability) break by
        power-of-two-choices: reservoir-sample TWO of the tied workers and
        send the request to the less loaded — the classic P2C result gives
        near-best-of-N load balance at O(1) extra cost, without the
        thundering-herd of always picking the first tied entry."""
        best: PeerInfo | None = None
        runner_up: PeerInfo | None = None
        best_score, n_tied = -1.0, 0
        for p, score in self._routing_snapshot(model).entries:
            if score < best_score:
                continue
            # Stale-snapshot guard: entries reference live PeerInfo rows,
            # so a worker that died since the rebuild is skipped here even
            # before any epoch bump lands.
            if not p.is_healthy or p.peer_id in exclude:
                continue
            if require_embeddings and not p.resource.embeddings:
                continue
            if score > best_score:
                best, runner_up, best_score, n_tied = p, None, score, 1
            else:  # tie: size-2 reservoir sample over the tied set
                n_tied += 1
                if runner_up is None:
                    runner_up = p
                else:
                    j = random.randrange(n_tied)
                    if j == 0:
                        best = p
                    elif j == 1:
                        runner_up = p
        if runner_up is not None:
            # P2C: of the two sampled tied workers, prefer the one whose
            # live load is lower (loads can drift apart between the
            # identical-score snapshot build and now).
            la = max(best.resource.load, 0.0)
            lb = max(runner_up.resource.load, 0.0)
            if lb < la or (lb == la and random.random() < 0.5):
                best = runner_up
        return best

    def group_members(self, group_id: str) -> list[PeerInfo]:
        return sorted(
            (p for p in self.get_healthy_peers()
             if p.resource.shard_group is not None
             and p.resource.shard_group.group_id == group_id),
            key=lambda p: p.resource.shard_group.shard_index,
        )

    def _complete_groups(self, model: str) -> set[str]:
        seen: dict[str, set[int]] = {}
        want: dict[str, int] = {}
        for p in self.get_healthy_peers():
            sg = p.resource.shard_group
            if sg is None or (model and sg.model != model):
                continue
            seen.setdefault(sg.group_id, set()).add(sg.shard_index)
            want[sg.group_id] = sg.shard_count
        return {
            gid for gid, idxs in seen.items()
            if len(idxs) == want[gid] and idxs == set(range(want[gid]))
        }

    # ------------------------------------------------------- health machine

    async def health_check_peer(self, info: PeerInfo) -> bool:
        """Active probe: live metadata fetch with timeout
        (manager.go:592-622).  3 strikes → unhealthy; linear backoff
        failed_attempts × backoff_base (manager.go:540-564)."""
        if self.metadata_fetcher is None:
            return info.is_healthy
        try:
            resource = await asyncio.wait_for(
                self.metadata_fetcher(info.peer_id),
                self.config.intervals.metadata_timeout,
            )
            info.resource = resource
            info.last_seen = time.monotonic()
            info.failed_attempts = 0
            info.is_healthy = True
            # Fresh metadata = fresh load/throughput: scores must rebuild.
            self._bump_routing_epoch()
            return True
        except Exception as e:
            was_healthy = info.is_healthy
            info.failed_attempts += 1
            info.next_check_at = (
                time.monotonic() + info.failed_attempts * self.config.backoff_base
            )
            if info.failed_attempts >= self.config.max_failed_attempts:
                info.is_healthy = False
                if was_healthy:
                    self._bump_routing_epoch()
            log.debug("health probe failed for %s (%d/%d): %s",
                      info.peer_id[:8], info.failed_attempts,
                      self.config.max_failed_attempts, e)
            return False

    #: Concurrent health probes per tick: each probe is a full
    #: handshake-priced stream; an uncapped gather over a 16-peer table
    #: bursts them all at once and spikes event-loop lag on small hosts.
    _HEALTH_CONCURRENCY = 4
    #: Probes per tick: the most-due peers only.  A 16-peer table probed
    #: in full every tick makes background AEAD/handshake cost scale with
    #: swarm size; capping amortizes it per INTERVAL (each peer is still
    #: probed well inside stale_after: 16 peers / 8 per tick = 2 ticks).
    _HEALTH_BATCH = 8

    async def perform_health_checks(self) -> None:
        now = time.monotonic()
        sem = asyncio.Semaphore(self._HEALTH_CONCURRENCY)

        async def probe(p):
            async with sem:
                await self.health_check_peer(p)

        due = [p for p in self.peers.values() if p.next_check_at <= now]
        if len(due) > self._HEALTH_BATCH:
            due.sort(key=lambda p: p.next_check_at)
            due = due[:self._HEALTH_BATCH]
        await asyncio.gather(*(probe(p) for p in due))

    def perform_cleanup(self) -> None:
        """Evict peers unseen past stale_after; purge old quarantine entries
        (manager.go:568-589)."""
        now = time.monotonic()
        for pid, info in list(self.peers.items()):
            if now - info.last_seen > self.config.stale_after:
                log.info("evicting stale peer %s", pid[:8])
                self.remove_peer(pid)
        cutoff = now - self.config.intervals.quarantine
        # Rebuild the quarantine map only when something actually expired
        # (steady state: nothing does — don't churn a dict every tick).
        if any(t <= cutoff for t in self.recently_removed.values()):
            self.recently_removed = {
                pid: t for pid, t in self.recently_removed.items()
                if t > cutoff
            }

    #: Discovery idle-backoff cap: after enough empty rounds the cadence
    #: stretches to idle_factor x intervals.discovery and stays there.
    _DISCOVERY_IDLE_MAX_FACTOR = 8

    async def run_discovery_once(self) -> None:
        if self.discovery is None:
            return
        try:
            found = await self.discovery(self.skip_set())
        except Exception as e:
            log.debug("discovery round failed: %s", e)
            return
        new = 0
        for resource in found:
            before = len(self.peers)
            self.add_or_update_peer(resource)
            new += len(self.peers) - before
        # Only genuinely NEW peers reset the idle backoff: the skip set
        # already filters known peers, so steady-state rounds return [].
        self._discovery_idle_rounds = (
            0 if new else self._discovery_idle_rounds + 1)

    def discovery_interval(self) -> float:
        """Current discovery cadence: the configured interval stretched by
        the idle backoff (2x per consecutive empty round, capped).  A
        settled 16-worker swarm converges to 1/8th the provider-lookup
        chatter; any membership change snaps it back to the base rate."""
        factor = min(2 ** self._discovery_idle_rounds,
                     self._DISCOVERY_IDLE_MAX_FACTOR)
        return self.config.intervals.discovery * factor

    async def _discovery_loop(self) -> None:
        """run_every with an adaptive interval (utils/aio.run_every takes a
        fixed one): jittered like every other background loop so swarm-wide
        ticks do not synchronize into handshake bursts."""
        iv = self.config.intervals
        await asyncio.sleep(random.random() * iv.discovery * 0.25)
        while True:
            try:
                await self.run_discovery_once()
            except asyncio.CancelledError:
                raise
            except Exception:
                log.error("background loop error (run_discovery_once)",
                          exc_info=True)
            sleep = self.discovery_interval()
            sleep *= 1 + 0.25 * (2 * random.random() - 1)
            await asyncio.sleep(sleep)

    # ----------------------------------------------------------- lifecycle

    def start(self) -> None:
        from crowdllama_tpu.utils.aio import run_every

        iv = self.config.intervals
        self._tasks = [
            asyncio.create_task(self._discovery_loop(), name="pm-discovery"),
            asyncio.create_task(run_every(iv.health_check, self.perform_health_checks, log),
                                name="pm-health"),
            asyncio.create_task(run_every(iv.cleanup, self.perform_cleanup, log),
                                name="pm-cleanup"),
        ]

    async def stop(self) -> None:
        for t in self._tasks:
            t.cancel()
        for t in self._tasks:
            try:
                await t
            except asyncio.CancelledError:
                pass
        self._tasks = []
