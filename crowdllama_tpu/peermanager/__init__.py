"""Peer table with health state machine and worker scheduling."""

from crowdllama_tpu.peermanager.manager import PeerHealthConfig, PeerInfo, PeerManager  # noqa: F401
