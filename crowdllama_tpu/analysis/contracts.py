"""contract-exhaustiveness checker: string-keyed cross-node contracts.

Four sub-checkers, all descriptor- or registry-driven so the source of
truth is the artifact itself, never a hand-copied list:

``oneof-*``
    The llama.v1 ``BaseMessage.message`` oneof (read from the compiled
    descriptor) vs. ``core/messages.py`` constructors/extractors and the
    ``peer/peer.py`` serve dispatch.  Adding a proto arm without wiring
    all three fails lint — the PR 8 "field-path that 500'd every
    /api/chat" bug class.

``fault-site-*``
    ``testing/faults.py``'s FAULT_SITES registry vs. the
    ``faults.inject("<site>")`` call sites actually instrumented in
    production code, and the site strings chaos tests build FaultRules
    from.  A typo'd site in a test now fails lint (and plan build)
    instead of silently never firing.

``metrics-*``
    Every ``crowdllama_*`` metric family named in code must be documented
    in ``docs/OBSERVABILITY.md`` (exact name, or a documented family
    prefix like ``crowdllama_gossip_``).  tests/test_metrics_lint.py
    closes the other half of the loop at runtime: every statically
    collected family must appear on a real scrape surface.

``config-*``
    CLI-flag/env parity in ``config.py``: every Configuration field is
    settable from the environment, every registered flag dest is a real
    field, and every dest is consumed by ``from_flags``.
"""

from __future__ import annotations

import ast
import re
from pathlib import Path

from crowdllama_tpu.analysis.base import Finding, dotted_name, load_sources

CHECKER = "contracts"

# Oneof arms that are responses on the wire (worker/donor -> caller).
# They need constructors + extractors but no serve-dispatch arm; a NEW
# arm that is neither dispatched in peer.py nor added here fails lint,
# which is exactly the forcing function we want.
RESPONSE_ARMS = frozenset({
    "generate_response", "embed_response", "kv_pages", "migrate_frame",
    "trace_spans", "metrics_snapshot", "verify_result",
})

# Configuration fields intentionally without a CROWDLLAMA_TPU_* env read.
CONFIG_ENV_EXEMPT = frozenset({
    "intervals",  # derived wholesale from CROWDLLAMA_TPU_TEST_MODE
})

_FAMILY_RE = re.compile(r"crowdllama_[a-z0-9_]+")
# Tokens that look like families but are package/protocol identifiers.
# `crowdllama_native` alone is the shared-library name; the REAL
# crowdllama_native_* metric families (obs/http.py native_metric_lines)
# are longer and must stay doc-checked.  `crowdllama_manifest` is the
# checkpoint-cache integrity dotfile (net/model_share.py MANIFEST_NAME),
# not an exposition family.
_FAMILY_JUNK_PREFIXES = ("crowdllama_tpu",)
_FAMILY_JUNK_EXACT = frozenset({"crowdllama_native",
                                "crowdllama_manifest"})


def _read(root: str, rel: str) -> str:
    return (Path(root) / rel).read_text(encoding="utf-8")


# ---------------------------------------------------------------- oneof

def _oneof_arms() -> list[str]:
    from crowdllama_tpu.core import llama_v1_pb2 as pb

    oneof = pb.BaseMessage.DESCRIPTOR.oneofs_by_name["message"]
    return [f.name for f in oneof.fields]


def check_oneof(root: str) -> list[Finding]:
    out: list[Finding] = []
    messages_src = _read(root, "crowdllama_tpu/core/messages.py")
    peer_src = _read(root, "crowdllama_tpu/peer/peer.py")
    arms = _oneof_arms()
    for arm in arms:
        if f"{arm}=" not in messages_src:
            out.append(Finding(
                CHECKER, "oneof-constructor", "crowdllama_tpu/core/messages.py",
                0, arm,
                f"oneof arm `{arm}` has no BaseMessage({arm}=...) "
                "constructor in core/messages.py"))
        if f'"{arm}"' not in messages_src:
            out.append(Finding(
                CHECKER, "oneof-extractor", "crowdllama_tpu/core/messages.py",
                0, arm,
                f"oneof arm `{arm}` has no WhichOneof-guarded extractor "
                "in core/messages.py"))
        if arm in RESPONSE_ARMS:
            continue
        dispatched = (f'which == "{arm}"' in peer_src
                      or f'which != "{arm}"' in peer_src)
        if not dispatched:
            out.append(Finding(
                CHECKER, "oneof-dispatch", "crowdllama_tpu/peer/peer.py",
                0, arm,
                f"request arm `{arm}` is not handled by the peer serve "
                "dispatch (_serve_one_inference) — wire it, or declare "
                "it a response arm in analysis/contracts.py RESPONSE_ARMS"))
    return out


# ---------------------------------------------------------- fault sites

def _inject_sites(root: str) -> dict[str, str]:
    """site literal -> 'path:line' for every faults.inject("<lit>") in
    production code (the faults module itself excluded)."""
    sites: dict[str, str] = {}
    for src in load_sources(root, ("",)):
        if src.path.endswith("testing/faults.py") \
                or src.path.startswith("crowdllama_tpu/analysis/"):
            continue
        for node in ast.walk(src.tree):
            if not isinstance(node, ast.Call):
                continue
            name = dotted_name(node.func)
            if not (name == "inject" or name.endswith(".inject")):
                continue
            if node.args and isinstance(node.args[0], ast.Constant) \
                    and isinstance(node.args[0].value, str):
                sites[node.args[0].value] = f"{src.path}:{node.lineno}"
    return sites


def _test_rule_sites(root: str) -> dict[str, str]:
    """site literal -> 'path:line' for every FaultRule(site="<lit>") under
    tests/ (and benchmarks/, which drive chaos phases too)."""
    sites: dict[str, str] = {}
    for sub in ("tests", "benchmarks"):
        d = Path(root) / sub
        if not d.is_dir():
            continue
        for f in sorted(d.rglob("*.py")):
            try:
                tree = ast.parse(f.read_text(encoding="utf-8"))
            except SyntaxError:
                continue
            rel = f.relative_to(root).as_posix()
            # Lines inside `with pytest.raises(...)` blocks hold
            # DELIBERATE bad-site fixtures (the registry's own tests);
            # a rule built there never reaches a plan.
            negative: list[tuple[int, int]] = []
            for node in ast.walk(tree):
                if isinstance(node, ast.With):
                    for item in node.items:
                        ctx = item.context_expr
                        if isinstance(ctx, ast.Call) and dotted_name(
                                ctx.func).endswith("raises"):
                            negative.append(
                                (node.lineno, node.end_lineno or node.lineno))
            for node in ast.walk(tree):
                if not isinstance(node, ast.Call):
                    continue
                if dotted_name(node.func).rsplit(".", 1)[-1] != "FaultRule":
                    continue
                if any(a <= node.lineno <= b for a, b in negative):
                    continue
                for kw in node.keywords:
                    if kw.arg == "site" \
                            and isinstance(kw.value, ast.Constant) \
                            and isinstance(kw.value.value, str):
                        sites[kw.value.value] = f"{rel}:{node.lineno}"
    return sites


def check_fault_sites(root: str) -> list[Finding]:
    from crowdllama_tpu.testing.faults import FAULT_SITES

    out: list[Finding] = []
    instrumented = _inject_sites(root)
    for site, where in instrumented.items():
        if site not in FAULT_SITES:
            out.append(Finding(
                CHECKER, "fault-site-unregistered",
                where.rsplit(":", 1)[0], int(where.rsplit(":", 1)[1]),
                site,
                f"faults.inject site `{site}` is not in the FAULT_SITES "
                "registry (testing/faults.py) — register it with a "
                "one-line description"))
    for site in FAULT_SITES:
        if site not in instrumented:
            out.append(Finding(
                CHECKER, "fault-site-uninstrumented",
                "crowdllama_tpu/testing/faults.py", 0, site,
                f"FAULT_SITES registers `{site}` but no production "
                "faults.inject call uses it — dead registry entry"))
    for site, where in _test_rule_sites(root).items():
        if site not in FAULT_SITES:
            out.append(Finding(
                CHECKER, "fault-site-unknown-in-test",
                where.rsplit(":", 1)[0], int(where.rsplit(":", 1)[1]),
                site,
                f"FaultRule(site=\"{site}\") names an unregistered site — "
                "the rule would never fire (FaultRule also rejects this "
                "at plan build now)"))
    return out


# -------------------------------------------------------------- metrics

def collect_metric_families(root: str) -> tuple[set[str], set[str]]:
    """(exact family names, dynamic family prefixes) read from string
    literals and f-string constant parts across the package.

    ``_bucket``/``_sum``/``_count`` exposition suffixes collapse onto the
    histogram family; junk tokens (module paths) are filtered.
    """
    exact: set[str] = set()
    prefixes: set[str] = set()

    def _add(token: str, dynamic_tail: bool) -> None:
        if token.startswith(_FAMILY_JUNK_PREFIXES) \
                or token in _FAMILY_JUNK_EXACT:
            return
        # A trailing-underscore token is a family-prefix fragment whether
        # it came from an f-string (f"crowdllama_engine_{key}") or a
        # regex/startswith literal (r"^crowdllama_engine_(...)") — valid
        # exposition names never end in "_".
        if dynamic_tail or token.endswith("_"):
            if token.endswith("_"):
                prefixes.add(token)
            return
        for suffix in ("_bucket", "_sum", "_count"):
            if token.endswith(suffix):
                token = token[: -len(suffix)]
        exact.add(token)

    for src in load_sources(root, ("",)):
        if src.path.startswith("crowdllama_tpu/analysis/"):
            continue
        for node in ast.walk(src.tree):
            if isinstance(node, ast.Constant) and isinstance(node.value, str):
                for m in _FAMILY_RE.finditer(node.value):
                    _add(m.group(0), dynamic_tail=False)
            elif isinstance(node, ast.JoinedStr):
                for i, part in enumerate(node.values):
                    if not (isinstance(part, ast.Constant)
                            and isinstance(part.value, str)):
                        continue
                    for m in _FAMILY_RE.finditer(part.value):
                        # A match running to the end of a constant part
                        # followed by a {format} field is a dynamic
                        # family prefix, e.g. f"crowdllama_kv_ship_{k}".
                        at_end = m.end() == len(part.value)
                        has_field = i + 1 < len(node.values)
                        _add(m.group(0), dynamic_tail=at_end and has_field)
    return exact, prefixes


def check_metrics_docs(root: str) -> list[Finding]:
    doc_path = "docs/OBSERVABILITY.md"
    doc = _read(root, doc_path)
    doc_tokens = set(_FAMILY_RE.findall(doc))
    doc_prefixes = {t for t in doc_tokens if t.endswith("_")}
    out: list[Finding] = []
    exact, prefixes = collect_metric_families(root)
    for fam in sorted(exact):
        documented = fam in doc_tokens or any(
            fam.startswith(p) for p in doc_prefixes)
        if not documented:
            out.append(Finding(
                CHECKER, "metrics-undocumented", doc_path, 0, fam,
                f"metric family `{fam}` is emitted in code but not "
                "documented in docs/OBSERVABILITY.md"))
    for pref in sorted(prefixes):
        documented = pref in doc_tokens or any(
            t.startswith(pref) for t in doc_tokens)
        if not documented:
            out.append(Finding(
                CHECKER, "metrics-undocumented", doc_path, 0, pref + "*",
                f"dynamic metric family `{pref}*` is emitted in code but "
                f"no `{pref}...` family appears in docs/OBSERVABILITY.md"))
    # Families documented but gone from code: stale docs mislead oncall.
    for tok in sorted(doc_tokens):
        if tok.startswith(_FAMILY_JUNK_PREFIXES) \
                or tok in _FAMILY_JUNK_EXACT or tok.endswith("_"):
            continue
        base = tok
        for suffix in ("_bucket", "_sum", "_count"):
            if base.endswith(suffix):
                base = base[: -len(suffix)]
        in_code = base in exact or any(base.startswith(p) for p in prefixes)
        if not in_code:
            out.append(Finding(
                CHECKER, "metrics-stale-doc", doc_path, 0, tok,
                f"docs/OBSERVABILITY.md documents `{tok}` but no code "
                "emits that family any more"))
    return out


# --------------------------------------------------------------- config

def _config_tree(root: str) -> ast.Module:
    return ast.parse(_read(root, "crowdllama_tpu/config.py"))


def _class_def(tree: ast.Module, name: str) -> ast.ClassDef:
    for node in tree.body:
        if isinstance(node, ast.ClassDef) and node.name == name:
            return node
    raise AssertionError(f"config.py: class {name} not found")


def _method(cls: ast.ClassDef, name: str) -> ast.FunctionDef:
    for node in cls.body:
        if isinstance(node, ast.FunctionDef) and node.name == name:
            return node
    raise AssertionError(f"config.py: {cls.name}.{name} not found")


def check_config_parity(root: str) -> list[Finding]:
    path = "crowdllama_tpu/config.py"
    tree = _config_tree(root)
    cls = _class_def(tree, "Configuration")
    fields = [n.target.id for n in cls.body
              if isinstance(n, ast.AnnAssign) and isinstance(n.target, ast.Name)]

    from_env = _method(cls, "from_environment")
    env_assigned: set[str] = set()
    for node in ast.walk(from_env):
        if isinstance(node, ast.Assign):
            for tgt in node.targets:
                if (isinstance(tgt, ast.Attribute)
                        and isinstance(tgt.value, ast.Name)
                        and tgt.value.id == "cfg"):
                    env_assigned.add(tgt.attr)

    add_flags = _method(cls, "add_flags")
    dests: dict[str, int] = {}
    for node in ast.walk(add_flags):
        if not (isinstance(node, ast.Call)
                and dotted_name(node.func).endswith("add_argument")):
            continue
        dest = ""
        for kw in node.keywords:
            if kw.arg == "dest" and isinstance(kw.value, ast.Constant):
                dest = kw.value.value
        if not dest:
            for arg in node.args:
                if isinstance(arg, ast.Constant) \
                        and str(arg.value).startswith("--"):
                    dest = str(arg.value)[2:].replace("-", "_")
        if dest:
            dests[dest] = node.lineno

    from_flags = _method(cls, "from_flags")
    flags_consumed: set[str] = set()
    for node in ast.walk(from_flags):
        if isinstance(node, ast.Constant) and isinstance(node.value, str):
            flags_consumed.add(node.value)

    out: list[Finding] = []
    for f in fields:
        if f in CONFIG_ENV_EXEMPT:
            continue
        if f not in env_assigned:
            out.append(Finding(
                CHECKER, "config-no-env", path, 0, f,
                f"Configuration.{f} cannot be set from the environment — "
                "add a CROWDLLAMA_TPU_* read in from_environment (env/"
                "flag parity keeps container deploys scriptable)"))
    for dest, line in dests.items():
        if dest not in fields:
            out.append(Finding(
                CHECKER, "config-unknown-dest", path, line, dest,
                f"flag dest `{dest}` is not a Configuration field — the "
                "flag parses and is silently dropped"))
        elif dest not in flags_consumed:
            out.append(Finding(
                CHECKER, "config-flag-unconsumed", path, line, dest,
                f"flag dest `{dest}` is registered in add_flags but "
                "never read by from_flags — the flag parses and is "
                "silently dropped"))
    return out


def check_contracts(root: str) -> list[Finding]:
    out: list[Finding] = []
    out.extend(check_oneof(root))
    out.extend(check_fault_sites(root))
    out.extend(check_metrics_docs(root))
    out.extend(check_config_parity(root))
    return out
