"""swarmlint — the repo-native static-analysis plane (docs/STATIC_ANALYSIS.md).

Nine PRs of hand-maintained invariants, enforced mechanically:

- :mod:`.async_hotpath`  — no blocking calls / lost coroutines / unlocked
  shared-state mutation inside the asyncio request plane (gateway, peer,
  peermanager, net, swarm, obs).
- :mod:`.jax_purity`     — no host syncs, Python RNG/wall-clock, or
  use-after-donate inside jit-traced / Pallas code (engine, ops,
  parallel, train).
- :mod:`.contracts`      — every string-keyed cross-node contract stays
  exhaustive: llama.v1 oneof arms vs constructors/extractors/dispatch,
  the FAULT_SITES registry vs instrumented ``faults.inject`` sites,
  ``crowdllama_*`` metric families vs docs, CLI-flag/env parity in
  config.py.
- :mod:`.ffi_contract`   — the native C ABI seam: every ``extern "C"``
  export in ``native/_src`` has a matching ctypes restype/argtypes
  declaration (and vice versa), with arity and return-type agreement.
  Zero waivers by policy.

Findings resolve against ``analysis/baseline.toml`` (each waiver carries a
one-line justification); anything NOT waived fails ``make lint`` and the
tier-1 ``tests/test_static_analysis.py`` module.  Run it as::

    make lint                                  # human-readable
    python -m crowdllama_tpu.analysis --format=json   # CI annotation
"""

from __future__ import annotations

from crowdllama_tpu.analysis.base import (
    Baseline,
    Finding,
    load_baseline,
    repo_root,
)


def all_checkers():
    """name -> callable(root) for every checker family, import deferred so
    ``import crowdllama_tpu.analysis`` stays cheap."""
    from crowdllama_tpu.analysis.async_hotpath import check_async_hotpath
    from crowdllama_tpu.analysis.contracts import check_contracts
    from crowdllama_tpu.analysis.ffi_contract import check_ffi_contract
    from crowdllama_tpu.analysis.jax_purity import check_jax_purity

    return {
        "async-hotpath": check_async_hotpath,
        "jax-purity": check_jax_purity,
        "contracts": check_contracts,
        "ffi-contract": check_ffi_contract,
    }


def run_all(root: str | None = None,
            baseline: Baseline | None = None) -> list[Finding]:
    """Run every checker over the package; returns NON-waived findings
    (pass an empty Baseline to see everything)."""
    root = root or repo_root()
    if baseline is None:
        baseline = load_baseline()
    findings: list[Finding] = []
    for name, fn in all_checkers().items():
        findings.extend(fn(root))
    return [f for f in findings if not baseline.waives(f)]


__all__ = ["Finding", "Baseline", "load_baseline", "repo_root",
           "all_checkers", "run_all"]
