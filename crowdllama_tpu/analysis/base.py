"""Shared swarmlint plumbing: findings, source loading, the baseline.

A :class:`Finding` is keyed by ``(checker, path, code, symbol)`` — line
numbers are carried for display but deliberately excluded from the waiver
key so a baseline entry survives unrelated edits above it.  The baseline
is a tiny TOML subset (``[[waiver]]`` tables of string keys) parsed by
hand because the container is Python 3.10 (no stdlib tomllib) and pulling
a dependency for four keys per entry is not worth it.
"""

from __future__ import annotations

import ast
import os
import re
from dataclasses import dataclass, field
from pathlib import Path


@dataclass(frozen=True)
class Finding:
    checker: str   # checker family: "async-hotpath" | "jax-purity" | ...
    code: str      # rule id inside the family, e.g. "blocking-call"
    path: str      # repo-relative posix path
    line: int      # 1-based; 0 for whole-file/contract findings
    symbol: str    # enclosing function / contract key — the stable anchor
    message: str

    def key(self) -> tuple[str, str, str, str]:
        return (self.checker, self.path, self.code, self.symbol)

    def render(self) -> str:
        loc = f"{self.path}:{self.line}" if self.line else self.path
        return f"{loc}: [{self.checker}/{self.code}] {self.symbol}: " \
               f"{self.message}"

    def as_json(self) -> dict:
        return {"checker": self.checker, "code": self.code,
                "path": self.path, "line": self.line,
                "symbol": self.symbol, "message": self.message}


def repo_root() -> str:
    """The directory holding the ``crowdllama_tpu`` package."""
    return str(Path(__file__).resolve().parents[2])


@dataclass
class SourceFile:
    path: str       # repo-relative posix
    text: str
    tree: ast.Module


def load_sources(root: str, subdirs: tuple[str, ...]) -> list[SourceFile]:
    """Parse every .py under ``crowdllama_tpu/<subdir>`` (or a bare file
    path ending in .py).  Syntax errors surface as exceptions: a file the
    linter cannot parse is itself a broken invariant."""
    out: list[SourceFile] = []
    base = Path(root)
    for sub in subdirs:
        p = base / "crowdllama_tpu" / sub
        files = [p] if p.suffix == ".py" else sorted(p.rglob("*.py"))
        for f in files:
            if not f.is_file():
                continue
            text = f.read_text(encoding="utf-8")
            rel = f.relative_to(base).as_posix()
            out.append(SourceFile(rel, text, ast.parse(text, filename=rel)))
    return out


def dotted_name(node: ast.AST) -> str:
    """``a.b.c`` for Name/Attribute chains, "" for anything else."""
    parts: list[str] = []
    while isinstance(node, ast.Attribute):
        parts.append(node.attr)
        node = node.value
    if isinstance(node, ast.Name):
        parts.append(node.id)
        return ".".join(reversed(parts))
    return ""


@dataclass
class Baseline:
    """Committed waivers.  ``waives`` consumes; ``stale`` reports entries
    that matched nothing this run (candidates for deletion)."""

    entries: list[dict] = field(default_factory=list)
    _hit: set[int] = field(default_factory=set)

    def waives(self, f: Finding) -> bool:
        for i, e in enumerate(self.entries):
            if (e.get("checker") == f.checker and e.get("code") == f.code
                    and e.get("path") == f.path
                    and e.get("symbol") == f.symbol):
                self._hit.add(i)
                return True
        return False

    def stale(self) -> list[dict]:
        return [e for i, e in enumerate(self.entries) if i not in self._hit]


_KV_RE = re.compile(r'^([A-Za-z_][A-Za-z0-9_]*)\s*=\s*"((?:[^"\\]|\\.)*)"\s*$')


def parse_baseline_toml(text: str) -> list[dict]:
    """Parse the ``[[waiver]]``-tables-of-strings TOML subset."""
    entries: list[dict] = []
    current: dict | None = None
    for ln, raw in enumerate(text.splitlines(), 1):
        line = raw.strip()
        if not line or line.startswith("#"):
            continue
        if line == "[[waiver]]":
            current = {}
            entries.append(current)
            continue
        m = _KV_RE.match(line)
        if m is None or current is None:
            raise ValueError(f"baseline.toml:{ln}: unparseable line {raw!r} "
                             "(only [[waiver]] tables of string keys)")
        current[m.group(1)] = m.group(2).replace('\\"', '"')
    for e in entries:
        missing = {"checker", "code", "path", "symbol", "reason"} - set(e)
        if missing:
            raise ValueError(f"baseline.toml: waiver {e} missing keys "
                             f"{sorted(missing)} — every waiver needs a "
                             "justification in `reason`")
        if not e["reason"].strip():
            raise ValueError(f"baseline.toml: waiver {e} has an empty "
                             "reason — justify it or fix the finding")
    return entries


def load_baseline(path: str | None = None) -> Baseline:
    if path is None:
        path = os.path.join(os.path.dirname(__file__), "baseline.toml")
    if not os.path.exists(path):
        return Baseline()
    return Baseline(parse_baseline_toml(
        Path(path).read_text(encoding="utf-8")))
