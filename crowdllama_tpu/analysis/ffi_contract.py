"""ffi-contract checker: the C ABI seam between the native plane and ctypes.

The native data plane (crowdllama_tpu/native/) is a hand-maintained FFI
contract: every ``extern "C"`` function in ``_src/*.cpp`` must have a
matching ``lib.<symbol>.restype`` / ``lib.<symbol>.argtypes`` declaration
in ``native/__init__.py``'s ``_declare``, and every ctypes declaration
must name a symbol the C++ source actually exports.  ctypes is the one
place the interpreter will happily smash the stack for you — an argtypes
list one entry short, or a ``c_int`` restype for a pointer-returning
function, corrupts memory instead of raising.  This checker makes the two
sides of the seam fail lint the moment they drift:

``ffi-undeclared``
    An ``extern "C"`` export with no (or only half of a) restype/argtypes
    declaration.  Undeclared functions default to ``int`` restype —
    pointer truncation on 64-bit.

``ffi-unknown-symbol``
    A ctypes declaration for a symbol the C++ source does not export —
    either a typo (the call would raise AttributeError at runtime) or a
    declaration left behind after the C function was removed.

``ffi-arity``
    ``len(argtypes)`` differs from the C parameter count — the classic
    silent-stack-garbage bug.

``ffi-restype``
    The declared restype disagrees with the C return type (void / void* /
    integer widths), the pointer-truncation bug class.

Zero waivers by policy: there is no legitimate reason for the two sides
of an ABI to disagree.
"""

from __future__ import annotations

import ast
import re
from pathlib import Path

from crowdllama_tpu.analysis.base import Finding, dotted_name

CHECKER = "ffi-contract"

CPP_DIR = "crowdllama_tpu/native/_src"
PY_DECL = "crowdllama_tpu/native/__init__.py"

# C return type -> acceptable ctypes restype tails (None = literal None).
# Only types actually usable at this seam are mapped; an unmapped C return
# type is itself a finding (the contract must stay expressible in ctypes).
_RETURN_MAP: dict[str, tuple[str | None, ...]] = {
    "void": (None,),
    "void*": ("c_void_p",),
    "long": ("c_long",),
    "int": ("c_int",),
    "int32_t": ("c_int32",),
    "int64_t": ("c_int64", "c_longlong"),
    "uint32_t": ("c_uint32",),
    "uint64_t": ("c_uint64", "c_ulonglong"),
    "size_t": ("c_size_t",),
    "double": ("c_double",),
    "float": ("c_float",),
}

# A function definition at extern-"C" block scope:  ret name(params) {
_FUNC_RE = re.compile(
    r"^[ \t]*([A-Za-z_][A-Za-z0-9_]*(?:\s*\*)?)\s+"   # return type
    r"(cl_[a-z0-9_]+)\s*\(([^)]*)\)\s*\{",            # name(params) {
    re.MULTILINE | re.DOTALL)


def _extern_c_blocks(text: str) -> list[tuple[int, str]]:
    """(start line, body) of every ``extern "C" { ... }`` block, by brace
    matching — the C++ side of the contract is whatever these export."""
    out: list[tuple[int, str]] = []
    for m in re.finditer(r'extern\s+"C"\s*\{', text):
        depth = 1
        i = m.end()
        while i < len(text) and depth:
            if text[i] == "{":
                depth += 1
            elif text[i] == "}":
                depth -= 1
            i += 1
        out.append((text.count("\n", 0, m.start()) + 1, text[m.end():i - 1]))
    return out


def _param_count(params: str) -> int:
    flat = " ".join(params.split())
    if not flat or flat == "void":
        return 0
    # No function-pointer params at this seam, so top-level commas are
    # exactly the separators.
    return flat.count(",") + 1


def c_exports(root: str) -> dict[str, tuple[str, int, str, int]]:
    """symbol -> (return type, param count, rel path, line) for every
    extern "C" function across the native C++ sources."""
    out: dict[str, tuple[str, int, str, int]] = {}
    d = Path(root) / CPP_DIR
    for f in sorted(d.glob("*.cpp")) if d.is_dir() else []:
        text = f.read_text(encoding="utf-8")
        rel = f.relative_to(root).as_posix()
        for start_line, body in _extern_c_blocks(text):
            for m in _FUNC_RE.finditer(body):
                ret = "".join(m.group(1).split())  # "void *" -> "void*"
                line = start_line + body.count("\n", 0, m.start())
                out[m.group(2)] = (ret, _param_count(m.group(3)), rel, line)
    return out


def py_declarations(root: str) -> dict[str, dict]:
    """symbol -> {"restype": tail|None|"<missing>", "argc": int|None,
    "line": int} from the ``lib.<sym>.restype/.argtypes = ...``
    assignments inside ``_declare``."""
    path = Path(root) / PY_DECL
    if not path.is_file():
        return {}
    tree = ast.parse(path.read_text(encoding="utf-8"))
    decl_fn = None
    for node in ast.walk(tree):
        if isinstance(node, ast.FunctionDef) and node.name == "_declare":
            decl_fn = node
            break
    if decl_fn is None:
        return {}
    decls: dict[str, dict] = {}

    def _entry(sym: str, line: int) -> dict:
        return decls.setdefault(
            sym, {"restype": "<missing>", "argc": None, "line": line})

    for node in ast.walk(decl_fn):
        if not isinstance(node, ast.Assign) or len(node.targets) != 1:
            continue
        tgt = node.targets[0]
        if not (isinstance(tgt, ast.Attribute)
                and tgt.attr in ("restype", "argtypes")
                and isinstance(tgt.value, ast.Attribute)):
            continue
        sym = tgt.value.attr
        e = _entry(sym, node.lineno)
        if tgt.attr == "restype":
            if isinstance(node.value, ast.Constant) \
                    and node.value.value is None:
                e["restype"] = None
            else:
                name = dotted_name(node.value)
                e["restype"] = name.rsplit(".", 1)[-1] if name else "<expr>"
        else:
            if isinstance(node.value, (ast.List, ast.Tuple)):
                e["argc"] = len(node.value.elts)
    return decls


def check_ffi_contract(root: str) -> list[Finding]:
    out: list[Finding] = []
    exports = c_exports(root)
    decls = py_declarations(root)
    for sym, (ret, argc, cpath, cline) in sorted(exports.items()):
        d = decls.get(sym)
        if d is None:
            out.append(Finding(
                CHECKER, "ffi-undeclared", PY_DECL, 0, sym,
                f"extern \"C\" `{sym}` ({cpath}:{cline}) has no ctypes "
                "restype/argtypes in _declare — undeclared foreign "
                "functions default to int restype (pointer truncation)"))
            continue
        if d["restype"] == "<missing>" or d["argc"] is None:
            half = "restype" if d["restype"] == "<missing>" else "argtypes"
            out.append(Finding(
                CHECKER, "ffi-undeclared", PY_DECL, d["line"], sym,
                f"`{sym}` is missing its {half} declaration in _declare — "
                "declare both halves of the signature"))
            continue
        if d["argc"] != argc:
            out.append(Finding(
                CHECKER, "ffi-arity", PY_DECL, d["line"], sym,
                f"argtypes declares {d['argc']} parameters but "
                f"`{sym}` ({cpath}:{cline}) takes {argc} — mismatched "
                "arity silently passes stack garbage"))
        expected = _RETURN_MAP.get(ret)
        if expected is None:
            out.append(Finding(
                CHECKER, "ffi-restype", cpath, cline, sym,
                f"`{sym}` returns `{ret}`, which has no known ctypes "
                "mapping — use a type from analysis/ffi_contract.py's "
                "_RETURN_MAP or extend it"))
        elif d["restype"] == "<expr>":
            out.append(Finding(
                CHECKER, "ffi-restype", PY_DECL, d["line"], sym,
                f"`{sym}` restype is a computed expression — declare a "
                "literal ctypes type so the contract stays checkable"))
        elif d["restype"] not in expected:
            want = " or ".join("None" if e is None else f"ctypes.{e}"
                               for e in expected)
            got = "None" if d["restype"] is None else d["restype"]
            out.append(Finding(
                CHECKER, "ffi-restype", PY_DECL, d["line"], sym,
                f"`{sym}` returns `{ret}` in C but restype is {got} — "
                f"expected {want} (wrong restype truncates or fabricates "
                "the return value)"))
    for sym, d in sorted(decls.items()):
        if sym not in exports:
            out.append(Finding(
                CHECKER, "ffi-unknown-symbol", PY_DECL, d["line"], sym,
                f"_declare configures `{sym}` but no extern \"C\" "
                "function of that name exists in the native sources — "
                "typo or stale declaration"))
    return out
