"""jax-purity checker: traced code must stay pure and on-device.

Finds every jit entry point in engine/, ops/, parallel/, train/ —
``@jax.jit`` / ``@partial(jax.jit, ...)`` decorated functions, functions
wrapped via ``jax.jit(fn, ...)`` assignments, and Pallas kernels (first
argument of ``pl.pallas_call``) — and flags, inside the traced bodies:

``host-sync``
    Escapes that force a device round-trip or break tracing:
    ``.block_until_ready()``, ``.item()``, ``.tolist()``,
    ``jax.device_get``, ``np.asarray``/``np.array`` (static shape math
    uses ``np.sqrt``/``np.prod`` on Python ints, never ``asarray``), and
    ``float()``/``int()``/``bool()`` applied to a traced *parameter* of
    the jitted function.  The kernel-looping direction (PAPERS, arXiv
    2410.23668) only pays off if no hidden host sync sneaks into the
    decode loop — this is its tripwire.

``impure-host-state``
    Python-side wall-clock or RNG inside traced code: ``time.time`` /
    ``perf_counter``, ``random.*``, ``np.random.*``.  A jitted function
    reading these bakes one sample into the compiled program — the value
    never changes again, which is a miserable bug to find at runtime.

``use-after-donate``
    For callables jitted with ``donate_argnums``, a read of the donated
    buffer after the call (without the call's result being assigned back
    to that name) — the buffer's memory was handed to XLA, its contents
    are garbage (jax guides: buffer donation).

``host-sync-in-decode-loop``
    A ``for``/``while`` loop that both dispatches decode work
    (``decode_steps_device`` / ``decode_megastep`` / ``ragged_step`` /
    ``ragged_megastep`` / ``decode_steps``) and materializes device
    values on the host
    (``np.asarray``/``np.array`` — called directly or handed to
    ``run_in_executor`` — or ``.item()``/``.tolist()``).  A per-step
    readback inside the dispatch loop serializes host and device and is
    exactly what the megastep exists to remove (docs/MEGASTEP.md): read
    the packed ``[K, B]`` block back ONCE per flight with
    ``jax.device_get`` instead.  Unlike the other rules this walks every
    function, not just traced ones — the scheduler's dispatch loop is
    plain async Python.
"""

from __future__ import annotations

import ast

from crowdllama_tpu.analysis.base import (
    Finding,
    SourceFile,
    dotted_name,
    load_sources,
)

CHECKER = "jax-purity"

SUBDIRS = ("engine", "ops", "parallel", "train")

_HOST_SYNC_ATTRS = frozenset({"block_until_ready", "item", "tolist"})
_HOST_SYNC_CALLS = frozenset({
    "jax.device_get", "np.asarray", "np.array", "numpy.asarray",
    "numpy.array", "onp.asarray", "onp.array",
})
_IMPURE_PREFIXES = ("time.", "random.", "np.random.", "numpy.random.",
                    "datetime.")

# host-sync-in-decode-loop: decode dispatch entry points (the device-side
# flights the scheduler's loop launches) and the host-materializing calls
# that must not share a loop body with them.  ragged_megastep is the
# fused ragged flight (K unified steps per dispatch) — a per-flight sync
# creep there forfeits exactly the dispatches the fusion reclaimed.
_DISPATCH_CALLS = frozenset({
    "decode_steps_device", "decode_megastep", "ragged_step",
    "ragged_megastep", "decode_steps",
})
_LOOP_SYNC_NAMES = frozenset({
    "np.asarray", "np.array", "numpy.asarray", "numpy.array",
    "onp.asarray", "onp.array",
})


def _is_jax_jit(node: ast.AST) -> bool:
    return dotted_name(node) in ("jax.jit", "jit")


def _jit_decorated(fn: ast.FunctionDef) -> bool:
    """@jax.jit or @(functools.)partial(jax.jit, ...)."""
    for dec in fn.decorator_list:
        if _is_jax_jit(dec):
            return True
        if isinstance(dec, ast.Call):
            if _is_jax_jit(dec.func):
                return True
            if dotted_name(dec.func).endswith("partial") and dec.args \
                    and _is_jax_jit(dec.args[0]):
                return True
    return False


def _decorator_donate(fn: ast.FunctionDef) -> tuple[int, ...]:
    for dec in fn.decorator_list:
        if isinstance(dec, ast.Call):
            for kw in dec.keywords:
                if kw.arg == "donate_argnums":
                    return _int_tuple(kw.value)
    return ()


def _int_tuple(node: ast.AST) -> tuple[int, ...]:
    if isinstance(node, ast.Constant) and isinstance(node.value, int):
        return (node.value,)
    if isinstance(node, (ast.Tuple, ast.List)):
        out = []
        for elt in node.elts:
            if isinstance(elt, ast.Constant) and isinstance(elt.value, int):
                out.append(elt.value)
        return tuple(out)
    return ()


def _local_functions(tree: ast.Module) -> dict[str, ast.FunctionDef]:
    """Every plain function/method in the module by name (last wins —
    name collisions across classes are rare and benign here)."""
    out: dict[str, ast.FunctionDef] = {}
    for node in ast.walk(tree):
        if isinstance(node, ast.FunctionDef):
            out[node.name] = node
    return out


def _traced_functions(src: SourceFile) -> list[ast.FunctionDef]:
    """Functions whose bodies run under trace: jit-decorated, passed to
    jax.jit(...), or passed to pl.pallas_call(...) as the kernel."""
    local = _local_functions(src.tree)
    traced: dict[int, ast.FunctionDef] = {}
    for fn in local.values():
        if _jit_decorated(fn):
            traced[id(fn)] = fn
    for node in ast.walk(src.tree):
        if not isinstance(node, ast.Call):
            continue
        name = dotted_name(node.func)
        target: ast.AST | None = None
        if _is_jax_jit(node.func) and node.args:
            target = node.args[0]
        elif name.endswith("pallas_call") and node.args:
            target = node.args[0]
        if target is None:
            continue
        tname = dotted_name(target)
        tname = tname.rsplit(".", 1)[-1] if tname else ""
        fn = local.get(tname)
        if fn is not None:
            traced[id(fn)] = fn
    return list(traced.values())


def _param_names(fn: ast.FunctionDef) -> frozenset[str]:
    args = fn.args
    names = [a.arg for a in (args.posonlyargs + args.args
                             + args.kwonlyargs)]
    if args.vararg:
        names.append(args.vararg.arg)
    return frozenset(n for n in names if n != "self")


def _root_name(node: ast.AST) -> str:
    """The leftmost Name of an expr chain (a.b[c].d -> 'a'), or "" when
    the chain passes through static metadata (`.shape`/`.ndim`/`.size`/
    `.dtype`) — `int(x.shape[0])` is trace-time Python, not a sync."""
    while isinstance(node, (ast.Attribute, ast.Subscript)):
        if isinstance(node, ast.Attribute) and node.attr in (
                "shape", "ndim", "size", "dtype"):
            return ""
        node = node.value
    return node.id if isinstance(node, ast.Name) else ""


def _purity_findings(src: SourceFile, fn: ast.FunctionDef) -> list[Finding]:
    out: list[Finding] = []
    params = _param_names(fn)
    for node in ast.walk(fn):
        if not isinstance(node, ast.Call):
            continue
        name = dotted_name(node.func)
        if isinstance(node.func, ast.Attribute) \
                and node.func.attr in _HOST_SYNC_ATTRS:
            out.append(Finding(
                CHECKER, "host-sync", src.path, node.lineno, fn.name,
                f"`.{node.func.attr}()` inside traced code forces a "
                "device->host sync (or fails under trace)"))
        elif name in _HOST_SYNC_CALLS:
            out.append(Finding(
                CHECKER, "host-sync", src.path, node.lineno, fn.name,
                f"`{name}(...)` materializes a traced value on the host"))
        elif name in ("float", "int", "bool") and node.args \
                and _root_name(node.args[0]) in params:
            out.append(Finding(
                CHECKER, "host-sync", src.path, node.lineno, fn.name,
                f"`{name}(...)` on traced argument "
                f"`{_root_name(node.args[0])}` concretizes it — "
                "ConcretizationTypeError at best, silent sync at worst"))
        elif name and (name.startswith(_IMPURE_PREFIXES)
                       or name in ("time.time", "time.perf_counter")):
            out.append(Finding(
                CHECKER, "impure-host-state", src.path, node.lineno,
                fn.name,
                f"`{name}(...)` inside traced code bakes ONE host value "
                "into the compiled program — it never updates again"))
    return out


def _donating_wrappers(src: SourceFile) -> dict[str, tuple[int, ...]]:
    """Callable attribute/function names that donate buffers, mapped to
    CALL-SITE positional indices of the donated args.

    ``self._f = jax.jit(self._f_impl, donate_argnums=(1,))`` wraps the
    *bound* method: index 1 is call-site arg 1.  A ``@partial(jax.jit,
    static_argnums=0, donate_argnums=(6, 7))`` *unbound method* counts
    ``self`` as arg 0, so call sites see indices shifted down by one.
    """
    out: dict[str, tuple[int, ...]] = {}
    for node in ast.walk(src.tree):
        if isinstance(node, ast.Assign) and isinstance(node.value, ast.Call):
            call = node.value
            if not _is_jax_jit(call.func):
                continue
            donate: tuple[int, ...] = ()
            for kw in call.keywords:
                if kw.arg == "donate_argnums":
                    donate = _int_tuple(kw.value)
            if not donate:
                continue
            for tgt in node.targets:
                tname = dotted_name(tgt)
                if tname:
                    out[tname.rsplit(".", 1)[-1]] = donate
        elif isinstance(node, ast.FunctionDef):
            donate = _decorator_donate(node)
            if donate and _jit_decorated(node):
                is_method = bool(node.args.args) \
                    and node.args.args[0].arg == "self"
                if is_method:
                    donate = tuple(i - 1 for i in donate if i >= 1)
                out[node.name] = donate
    return out


def _use_after_donate(src: SourceFile) -> list[Finding]:
    donors = _donating_wrappers(src)
    if not donors:
        return []
    out: list[Finding] = []
    for fn in ast.walk(src.tree):
        if not isinstance(fn, (ast.FunctionDef, ast.AsyncFunctionDef)):
            continue
        # Lexical liveness scan.  Event ordering within a line mirrors
        # runtime order for the `x = self._f(x, ...)` idiom: the call's
        # args are READ first, the buffer dies when the call runs (its
        # end line), and the assignment REVIVES the name after the whole
        # statement — so a rebound donated buffer is live again.
        dead: dict[str, int] = {}
        events: list[tuple[int, int, str, str]] = []  # (line, prio, kind, name)
        for node in ast.walk(fn):
            if isinstance(node, ast.Call):
                cname = dotted_name(node.func).rsplit(".", 1)[-1]
                donate = donors.get(cname)
                if donate is None:
                    continue
                kill_line = node.end_lineno or node.lineno
                for idx in donate:
                    if idx < len(node.args):
                        dn = dotted_name(node.args[idx])
                        if dn and dn != "self":
                            events.append((kill_line, 1, "kill", dn))
            elif isinstance(node, (ast.Assign, ast.AugAssign)):
                tgts = node.targets if isinstance(node, ast.Assign) \
                    else [node.target]
                store_line = node.end_lineno or node.lineno
                for tgt in tgts:
                    dn = dotted_name(tgt)
                    if dn:
                        events.append((store_line, 2, "store", dn))
                    elif isinstance(tgt, ast.Tuple):
                        for elt in tgt.elts:
                            edn = dotted_name(elt)
                            if edn:
                                events.append((store_line, 2, "store", edn))
            elif isinstance(node, (ast.Name, ast.Attribute)) \
                    and isinstance(getattr(node, "ctx", None), ast.Load):
                dn = dotted_name(node)
                if dn:
                    events.append((node.lineno, 0, "load", dn))
        events.sort(key=lambda e: (e[0], e[1]))
        events = [(line, kind, name) for line, _, kind, name in events]
        for line, kind, name in events:
            if kind == "kill":
                dead[name] = line
            elif kind == "store":
                dead.pop(name, None)
            elif kind == "load" and name in dead and line > dead[name]:
                out.append(Finding(
                    CHECKER, "use-after-donate", src.path, line, fn.name,
                    f"`{name}` was donated to XLA at line {dead[name]} — "
                    "its buffer is invalid; rebind the call's result"))
                dead.pop(name)  # one finding per death, not per read
    return out


def _loop_sync_findings(src: SourceFile) -> list[Finding]:
    """host-sync-in-decode-loop: see the module docstring.  One finding
    per (function, sync line) — nested loops both containing the pair
    collapse to a single report anchored at the first sync."""
    out: list[Finding] = []
    seen: set[tuple[str, int]] = set()

    def visit(node: ast.AST, fname: str) -> None:
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            fname = node.name
        if isinstance(node, (ast.For, ast.AsyncFor, ast.While)):
            dispatches = False
            syncs: list[tuple[int, str]] = []
            for sub in ast.walk(node):
                if isinstance(sub, ast.Attribute) \
                        and sub.attr in _DISPATCH_CALLS:
                    dispatches = True
                elif isinstance(sub, ast.Name) \
                        and sub.id in _DISPATCH_CALLS:
                    dispatches = True
                if isinstance(sub, ast.Call) \
                        and isinstance(sub.func, ast.Attribute) \
                        and sub.func.attr in ("item", "tolist"):
                    syncs.append((sub.lineno, f".{sub.func.attr}()"))
                elif isinstance(sub, ast.Attribute) \
                        and dotted_name(sub) in _LOOP_SYNC_NAMES:
                    # Catches both the direct call and the bare reference
                    # handed to run_in_executor (a call's func node IS an
                    # Attribute, so no separate Call case is needed).
                    syncs.append((sub.lineno, dotted_name(sub)))
            if dispatches and syncs:
                line, what = min(syncs)
                if (fname, line) not in seen:
                    seen.add((fname, line))
                    out.append(Finding(
                        CHECKER, "host-sync-in-decode-loop", src.path,
                        line, fname,
                        f"`{what}` in the same loop as a decode dispatch "
                        "serializes host and device per step — read the "
                        "packed [K, B] block back once per flight with "
                        "jax.device_get (docs/MEGASTEP.md)"))
        for child in ast.iter_child_nodes(node):
            visit(child, fname)

    visit(src.tree, "<module>")
    return out


def check_jax_purity(root: str,
                     subdirs: tuple[str, ...] = SUBDIRS) -> list[Finding]:
    out: list[Finding] = []
    for src in load_sources(root, subdirs):
        for fn in _traced_functions(src):
            out.extend(_purity_findings(src, fn))
        out.extend(_use_after_donate(src))
        out.extend(_loop_sync_findings(src))
    return out
