"""async-hotpath checker: the asyncio request plane must not block.

Walks every ``async def`` in the request-plane packages (gateway, peer,
peermanager, net, swarm, obs) and flags:

``blocking-call``
    Synchronous calls that stall the event loop: ``time.sleep``,
    ``subprocess.run``-family, sync socket/DNS helpers, sync file IO via
    ``open(...)``, and ``.result()`` on futures.  Bodies of *nested sync
    functions* are exempt — that is the ``run_in_executor`` idiom (the
    blocking work runs on a thread, e.g. engine.capture_profile).

``unawaited-coroutine``
    A bare expression statement calling a function whose every definition
    in the repo is ``async def`` — the coroutine is created and dropped,
    so the work silently never runs (the PR 6 ``engine.obs`` fan-out bug
    class).  Calls wrapped in ``create_task`` / ``ensure_future`` /
    ``gather`` are fine; names that also have sync definitions anywhere
    are skipped (cannot tell which binding this is without types).

``unlocked-mutation``
    Lock-consistency inference, per class: if an attribute is mutated
    under ``async with self.<lock>`` in one coroutine method, mutating the
    same attribute in another coroutine of that class *outside* the lock
    is flagged.  The guard relation is discovered from the code itself, so
    there is no hand-maintained attribute list to rot.
"""

from __future__ import annotations

import ast

from crowdllama_tpu.analysis.base import (
    Finding,
    SourceFile,
    dotted_name,
    load_sources,
)

CHECKER = "async-hotpath"

SUBDIRS = ("gateway", "peer", "peermanager", "net", "swarm", "obs")

# Dotted-name suffixes that block the loop when called from a coroutine.
BLOCKING_CALLS = frozenset({
    "time.sleep",
    "subprocess.run", "subprocess.call", "subprocess.check_call",
    "subprocess.check_output",
    "os.system", "os.wait", "os.waitpid",
    "socket.create_connection", "socket.getaddrinfo",
    "socket.gethostbyname",
    "urllib.request.urlopen",
    "requests.get", "requests.post", "requests.request",
    "shutil.rmtree", "shutil.copytree",
})

# Wrappers that legitimately consume a coroutine object.
_TASK_WRAPPERS = frozenset({
    "create_task", "ensure_future", "gather", "wait", "wait_for",
    "run_coroutine_threadsafe", "shield", "run", "as_completed",
})


def _call_name(call: ast.Call) -> str:
    return dotted_name(call.func)


def collect_async_defs(sources: list[SourceFile]) -> dict[str, list[bool]]:
    """function/method name -> [is_async per definition] across the repo.
    Used to decide which bare calls certainly create a coroutine."""
    defs: dict[str, list[bool]] = {}
    for src in sources:
        for node in ast.walk(src.tree):
            if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                defs.setdefault(node.name, []).append(
                    isinstance(node, ast.AsyncFunctionDef))
    return defs


def _iter_async_body(fn: ast.AsyncFunctionDef):
    """Yield nodes of the coroutine body WITHOUT descending into nested
    sync defs/lambdas (executor bodies) or nested async defs (they are
    visited as coroutines of their own by the outer walk)."""
    stack: list[ast.AST] = list(ast.iter_child_nodes(fn))
    while stack:
        node = stack.pop()
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef,
                             ast.Lambda)):
            continue
        yield node
        stack.extend(ast.iter_child_nodes(node))


def _blocking_findings(src: SourceFile, fn: ast.AsyncFunctionDef,
                       qual: str) -> list[Finding]:
    out: list[Finding] = []
    for node in _iter_async_body(fn):
        if not isinstance(node, ast.Call):
            continue
        name = _call_name(node)
        if name and any(name == b or name.endswith("." + b)
                        for b in BLOCKING_CALLS):
            out.append(Finding(
                CHECKER, "blocking-call", src.path, node.lineno, qual,
                f"`{name}(...)` blocks the event loop; await an async "
                "equivalent or push it through run_in_executor"))
        elif name == "open":
            out.append(Finding(
                CHECKER, "blocking-call", src.path, node.lineno, qual,
                "sync file IO `open(...)` on the event loop; use "
                "run_in_executor (or accept+waive tiny startup reads)"))
        elif (isinstance(node.func, ast.Attribute)
              and node.func.attr == "result" and not node.args
              and not node.keywords):
            out.append(Finding(
                CHECKER, "blocking-result", src.path, node.lineno, qual,
                "`.result()` on a future blocks (or raises "
                "InvalidStateError) — await it instead"))
    return out


def _unawaited_findings(src: SourceFile, fn: ast.AsyncFunctionDef,
                        qual: str,
                        async_only: frozenset[str]) -> list[Finding]:
    out: list[Finding] = []
    for node in _iter_async_body(fn):
        if not (isinstance(node, ast.Expr)
                and isinstance(node.value, ast.Call)):
            continue
        call = node.value
        func = call.func
        callee = func.attr if isinstance(func, ast.Attribute) else (
            func.id if isinstance(func, ast.Name) else "")
        if callee in async_only and callee not in _TASK_WRAPPERS:
            out.append(Finding(
                CHECKER, "unawaited-coroutine", src.path, node.lineno, qual,
                f"call to coroutine `{callee}` is neither awaited nor "
                "wrapped in create_task — the work never runs"))
    return out


class _ClassLocks(ast.NodeVisitor):
    """Per class: which self attributes hold asyncio locks, which
    attributes are mutated under which lock, and every mutation site."""

    def __init__(self) -> None:
        self.locks: set[str] = set()
        # attr -> set of lock names it was seen guarded by
        self.guarded: dict[str, set[str]] = {}
        # (attr, lineno, qualname, locks_held_at_site)
        self.mutations: list[tuple[str, int, str, frozenset[str]]] = []


def _self_attr(node: ast.AST) -> str:
    """'x' for a bare ``self.x`` access."""
    if (isinstance(node, ast.Attribute)
            and isinstance(node.value, ast.Name)
            and node.value.id == "self"):
        return node.attr
    return ""


def _lock_attrs(cls: ast.ClassDef) -> set[str]:
    locks: set[str] = set()
    for node in ast.walk(cls):
        if not isinstance(node, ast.Assign):
            continue
        value_name = dotted_name(node.value.func) \
            if isinstance(node.value, ast.Call) else ""
        if value_name.endswith("asyncio.Lock") or value_name == "Lock":
            for tgt in node.targets:
                attr = _self_attr(tgt)
                if attr:
                    locks.add(attr)
    return locks


def _mutated_attr(stmt: ast.AST) -> list[str]:
    """self attributes a statement mutates (assignment or augmented)."""
    out = []
    if isinstance(stmt, ast.Assign):
        for tgt in stmt.targets:
            a = _self_attr(tgt)
            if a:
                out.append(a)
    elif isinstance(stmt, (ast.AugAssign, ast.AnnAssign)):
        a = _self_attr(stmt.target)
        if a:
            out.append(a)
    return out


def _walk_with_locks(body, held: frozenset[str], qual: str,
                     info: _ClassLocks) -> None:
    for stmt in body:
        if isinstance(stmt, (ast.FunctionDef, ast.AsyncFunctionDef,
                             ast.Lambda)):
            continue
        for attr in _mutated_attr(stmt):
            if attr in info.locks:
                continue  # assigning the lock itself (construction)
            info.mutations.append((attr, stmt.lineno, qual, held))
            for lk in held:
                info.guarded.setdefault(attr, set()).add(lk)
        if isinstance(stmt, ast.AsyncWith):
            new = set(held)
            for item in stmt.items:
                lk = _self_attr(item.context_expr)
                if lk in info.locks:
                    new.add(lk)
            _walk_with_locks(stmt.body, frozenset(new), qual, info)
            continue
        # Recurse into compound statements, keeping the held-lock set.
        for field_body in ("body", "orelse", "finalbody", "handlers"):
            sub = getattr(stmt, field_body, None)
            if not sub:
                continue
            if field_body == "handlers":
                for h in sub:
                    _walk_with_locks(h.body, held, qual, info)
            else:
                _walk_with_locks(sub, held, qual, info)


def _unlocked_findings(src: SourceFile) -> list[Finding]:
    out: list[Finding] = []
    for cls in ast.walk(src.tree):
        if not isinstance(cls, ast.ClassDef):
            continue
        info = _ClassLocks()
        info.locks = _lock_attrs(cls)
        if not info.locks:
            continue
        for fn in cls.body:
            if isinstance(fn, ast.AsyncFunctionDef):
                _walk_with_locks(fn.body, frozenset(),
                                 f"{cls.name}.{fn.name}", info)
        for attr, line, qual, held in info.mutations:
            needed = info.guarded.get(attr, set())
            if needed and not (held & needed):
                out.append(Finding(
                    CHECKER, "unlocked-mutation", src.path, line, qual,
                    f"`self.{attr}` is mutated under `async with "
                    f"self.{sorted(needed)[0]}` elsewhere in {cls.name} "
                    "but not here — racy across awaits"))
    return out


def check_async_hotpath(root: str,
                        subdirs: tuple[str, ...] = SUBDIRS) -> list[Finding]:
    sources = load_sources(root, subdirs)
    # The exclusively-async name set spans the WHOLE package, not just the
    # hot-path dirs, so `engine.handle(...)` dropped in peer code is seen.
    all_sources = load_sources(root, ("",))
    defs = collect_async_defs(all_sources)
    async_only = frozenset(
        name for name, kinds in defs.items() if all(kinds))
    out: list[Finding] = []
    for src in sources:
        for node in ast.walk(src.tree):
            if not isinstance(node, ast.AsyncFunctionDef):
                continue
            qual = node.name
            out.extend(_blocking_findings(src, node, qual))
            out.extend(_unawaited_findings(src, node, qual, async_only))
        out.extend(_unlocked_findings(src))
    return out
