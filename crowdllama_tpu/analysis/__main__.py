"""swarmlint CLI: ``python -m crowdllama_tpu.analysis``.

Exit 0 when every finding is waived by analysis/baseline.toml, 1 on any
new violation, 2 on usage/baseline errors.  ``--format=json`` emits a
machine-readable report for CI annotation.
"""

from __future__ import annotations

import argparse
import json
import sys
import time

from crowdllama_tpu.analysis import (
    all_checkers,
    load_baseline,
    repo_root,
)


def main(argv: list[str] | None = None) -> int:
    ap = argparse.ArgumentParser(
        prog="python -m crowdllama_tpu.analysis",
        description="swarmlint: async-hotpath / jax-purity / contract "
                    "checkers (docs/STATIC_ANALYSIS.md)")
    ap.add_argument("--format", choices=("text", "json"), default="text")
    ap.add_argument("--baseline", default=None,
                    help="waiver file (default: analysis/baseline.toml)")
    ap.add_argument("--root", default=None,
                    help="repo root (default: autodetected)")
    ap.add_argument("--checker", choices=sorted(all_checkers()) + ["all"],
                    default="all", help="run one checker family only")
    args = ap.parse_args(argv)

    root = args.root or repo_root()
    try:
        baseline = load_baseline(args.baseline)
    except ValueError as e:
        print(f"swarmlint: {e}", file=sys.stderr)
        return 2

    t0 = time.perf_counter()
    findings = []
    checkers = all_checkers()
    selected = checkers if args.checker == "all" else \
        {args.checker: checkers[args.checker]}
    for name, fn in selected.items():
        findings.extend(fn(root))
    new = [f for f in findings if not baseline.waives(f)]
    waived = len(findings) - len(new)
    elapsed = time.perf_counter() - t0

    if args.format == "json":
        print(json.dumps({
            "findings": [f.as_json() for f in new],
            "waived": waived,
            "stale_waivers": baseline.stale(),
            "elapsed_s": round(elapsed, 3),
            "checkers": sorted(selected),
        }, indent=2))
    else:
        for f in sorted(new, key=lambda f: (f.path, f.line)):
            print(f.render())
        stale = baseline.stale()
        for e in stale:
            print(f"swarmlint: stale waiver (matched nothing): "
                  f"{e['checker']}/{e['code']} {e['path']} {e['symbol']}",
                  file=sys.stderr)
        print(f"swarmlint: {len(new)} finding(s), {waived} waived, "
              f"{len(stale)} stale waiver(s), {elapsed:.1f}s")
    return 1 if new else 0


if __name__ == "__main__":
    sys.exit(main())
