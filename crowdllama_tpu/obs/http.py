"""Worker-side observability HTTP server: /metrics + /debug/trace.

Workers have no consumer-facing HTTP surface (that is the gateway's job),
but the tracing plane needs every node scrapeable: :class:`ObsServer` is a
minimal aiohttp listener serving the same metric families as the gateway
(``crowdllama_request_seconds`` / ``crowdllama_ttft_seconds`` /
``crowdllama_decode_step_seconds`` + engine gauges + host stream counters)
and the node's trace ring buffer as JSON.

Enabled via ``--worker-metrics-port`` (0 = disabled, the default; tests
pass ``port=0`` explicitly through ``ObsServer`` to bind an ephemeral
port).
"""

from __future__ import annotations

import logging

from aiohttp import web

from crowdllama_tpu.obs.metrics import (
    ENGINE_TELEMETRY,
    device_memory_lines,
    engine_gauge_lines,
)

log = logging.getLogger("crowdllama.obs")


def native_metric_lines() -> list[str]:
    """Native data-plane health (docs/NATIVE.md): a gauge for whether the
    C++ fast path is active in this process, plus a per-component counter
    of every degradation to the pure-Python path.  A fleet where
    ``crowdllama_native_enabled`` is 0 (or fallbacks are climbing) is
    silently paying ~an order of magnitude more CPU per request — these
    series make that visible instead of a mystery regression."""
    from crowdllama_tpu import native

    st = native.stats()
    lines = [
        "# TYPE crowdllama_native_enabled gauge",
        f"crowdllama_native_enabled {1 if st['enabled'] else 0}",
        "# TYPE crowdllama_native_fallbacks_total counter",
    ]
    # Always-present component labels so dashboards can rate() without
    # sparse-series gaps; extra components recorded at runtime still show.
    components = {"aead": 0, "envelope": 0, "frame_scan": 0}
    components.update(st["fallbacks"])
    for comp, v in sorted(components.items()):
        lines.append(
            f'crowdllama_native_fallbacks_total{{component="{comp}"}} {v}')
    return lines


def host_stat_lines(host) -> list[str]:
    """Host stream-path counters, identical series on gateway and worker."""
    lines = ["# TYPE crowdllama_host_streams_total counter"]
    for k, v in sorted(host.stats.items()):
        if k.startswith("streams_"):
            lines.append(f'crowdllama_host_streams_total{{kind="{k}"}} {v}')
    lines.append("# TYPE crowdllama_host_rejected_total counter")
    lines.append(
        f"crowdllama_host_rejected_total {host.stats.get('rejected', 0)}")
    lines.append("# TYPE crowdllama_host_handshake_seconds_total counter")
    lines.append(
        f"crowdllama_host_handshake_seconds_total "
        f"{host.stats.get('handshake_ns', 0) / 1e9:.6f}")
    # Dial-ladder outcomes (docs/OBSERVABILITY.md): one counter per
    # (rung, outcome) the connect path attempted — direct, then the relay
    # escalation ladder (reverse / punch / splice).  Always present at
    # zero for the rungs a node never climbs, so dashboards can rate()
    # without sparse-series gaps.
    lines.append("# TYPE crowdllama_dial_ladder_attempts_total counter")
    ladder = getattr(host, "dial_ladder", {})
    for rung in ("direct", "reverse", "punch", "splice"):
        for outcome in ("ok", "fail"):
            v = ladder.get((rung, outcome), 0)
            lines.append(
                f'crowdllama_dial_ladder_attempts_total'
                f'{{rung="{rung}",outcome="{outcome}"}} {v}')
    return lines


def node_metric_lines(peer) -> list[str]:
    """The full worker-side exposition — the exact lines ObsServer's
    /metrics serves AND the payload a MetricsSnapshot carries over the p2p
    plane (docs/OBSERVABILITY.md swarm observatory): one composition, so
    the two scrape surfaces cannot drift."""
    obs = peer.obs
    lines = obs.metrics.expose()
    engine = getattr(peer, "engine", None)
    if engine is not None:
        try:
            lines.extend(engine_gauge_lines(engine.obs_gauges()))
        except Exception as e:  # a sick engine must not break the scrape
            log.debug("engine gauges unavailable: %s", e)
    # XLA compile/padding telemetry + device memory (PR 8): process
    # singletons, real numbers on the node that actually compiles.
    lines.extend(ENGINE_TELEMETRY.expose())
    lines.extend(device_memory_lines())
    lines.extend(host_stat_lines(peer.host))
    lines.extend(native_metric_lines())
    return lines


class ObsServer:
    """Per-worker metrics/trace endpoint, mirroring the gateway's."""

    def __init__(self, peer, host: str = "127.0.0.1", port: int = 0) -> None:
        self.peer = peer
        self.host = host
        self.port = port
        self._runner: web.AppRunner | None = None
        self.app = web.Application()
        self.app.router.add_get("/metrics", self.handle_metrics)
        self.app.router.add_get("/debug/trace", self.handle_trace)
        # Operator drain hook (docs/ROBUSTNESS.md): same graceful path as
        # SIGTERM, for orchestrators that reach workers over HTTP (e.g.
        # a preStop hook) instead of signaling the process.
        self.app.router.add_post("/drain", self.handle_drain)

    async def start(self) -> None:
        self._runner = web.AppRunner(self.app, access_log=None)
        await self._runner.setup()
        site = web.TCPSite(self._runner, self.host, self.port)
        await site.start()
        # Resolve the bound port (port=0 binds ephemeral).
        self.port = self._runner.addresses[0][1]
        log.info("worker obs endpoint on %s:%d", self.host, self.port)

    async def stop(self) -> None:
        if self._runner is not None:
            await self._runner.cleanup()
            self._runner = None

    async def handle_metrics(self, request: web.Request) -> web.Response:
        return web.Response(
            text="\n".join(node_metric_lines(self.peer)) + "\n",
            content_type="text/plain")

    async def handle_trace(self, request: web.Request) -> web.Response:
        """``?trace_id=`` filters to one trace, ``?limit=N`` keeps the N
        newest records (PR 8 satellite — same contract as the gateway's)."""
        try:
            limit = max(0, int(request.query.get("limit", "0") or 0))
        except ValueError:
            limit = 0
        return web.json_response(self.peer.obs.trace.snapshot(
            trace_id=request.query.get("trace_id", ""), limit=limit))

    async def handle_drain(self, request: web.Request) -> web.Response:
        drain = getattr(self.peer, "drain", None)
        if drain is None:
            return web.json_response(
                {"error": "peer does not support drain"}, status=501)
        already = bool(getattr(self.peer, "_draining", False))
        migrated = await drain()
        return web.json_response({
            "draining": True,
            "already_draining": already,
            "migrated_streams": migrated,
        })
