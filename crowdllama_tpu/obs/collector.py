"""Swarm-wide trace stitching + flight recorder (PR 8 tentpole).

A request's spans are scattered across every process that touched it —
gateway replica, relay host, worker(s) — each holding a fragment in its
own :class:`~crowdllama_tpu.obs.trace.TraceBuffer` under the trace id the
``llama.v1.BaseMessage`` envelope carried.  :class:`TraceCollector` turns
that id back into one story: it takes the gateway's own fragment as the
root, fans a ``TraceFetch`` out over the authenticated inference-stream
protocol to every node the gateway knows (nodes without the id answer
``found=false`` — the fan-out IS the index), and assembles the fragments
into a single clock-aligned span tree.

Clock alignment: every node's span ``start_us`` offsets count from that
node's own monotonic t0.  Fragments are first placed on the gateway
timeline by wall-clock delta (``started_at``), then clamped so each
fragment's window NESTS inside the gateway's request window — the
envelope's send happens after the gateway admitted and its recv before
the gateway finished, so a fragment sticking out past either end is clock
skew by construction, not causality.

:class:`FlightRecorder` is the always-on incident memory: a separate
bounded ring that keeps COMPLETE stitched traces, but only for
interesting requests (latency above the rolling p99, failovers,
migrations, sheds, kv-ship fallbacks), so the evidence for a tail-latency
spike survives long after the general ring wrapped.
"""

from __future__ import annotations

import asyncio
import json
import logging
import threading
import time
from collections import OrderedDict
from typing import Any

log = logging.getLogger("crowdllama.obs.collector")

# Per-node fetch budget: a trace fetch is a debugging aid — a dead or
# wedged peer must cost seconds, not the request_timeout.
FETCH_TIMEOUT_S = 3.0
# Fan-out bound: the collector queries at most this many peers per fetch
# (newest-seen first); beyond that a swarm is big enough that the
# operator should be sharding traces into a real backend.
MAX_FANOUT = 32


async def fetch_fragment(peer, peer_id: str, trace_id: str,
                         timeout: float = FETCH_TIMEOUT_S) -> dict | None:
    """Fetch one node's span fragment over the p2p plane.

    Returns the decoded trace record (the node's /debug/trace shape) with
    a ``node`` key injected, None when the node has no spans for the id
    (or cannot be reached — a collector must degrade, not fail).
    """
    from crowdllama_tpu.core import wire
    from crowdllama_tpu.core.messages import (
        extract_trace_spans,
        trace_fetch_msg,
    )
    from crowdllama_tpu.core.protocol import INFERENCE_PROTOCOL

    s = None
    try:
        contact = await peer.dht.find_peer(peer_id)
        if contact is None:
            return None
        s = await peer.host.new_stream(contact, INFERENCE_PROTOCOL,
                                       timeout=timeout)
        msg = trace_fetch_msg(trace_id)
        await wire.write_length_prefixed_pb(s.writer, msg)
        reply = await wire.read_length_prefixed_pb(s.reader, timeout=timeout)
        ts = extract_trace_spans(reply)
        if not ts.found or not ts.payload:
            return None
        record = json.loads(ts.payload.decode("utf-8"))
        record["node"] = ts.node or f"peer:{peer_id[:8]}"
        return record
    except asyncio.CancelledError:
        raise
    except Exception as e:
        log.debug("trace fetch from %s failed: %s", peer_id[:8], e)
        return None
    finally:
        if s is not None:
            s.close()


class TraceCollector:
    """Gateway-side cross-node trace assembly."""

    def __init__(self, peer, obs, timeout: float = FETCH_TIMEOUT_S) -> None:
        self.peer = peer  # the gateway's Peer (host + dht + peer_manager)
        self.obs = obs    # the gateway's NodeObs (root fragments)
        self.timeout = timeout

    def _targets(self) -> list[str]:
        """Peers worth asking: every worker the manager knows (healthy or
        not — a drained donor still holds spans), newest-seen first."""
        pm = self.peer.peer_manager
        if pm is None:
            return []
        peers = sorted(pm.get_workers(), key=lambda p: -p.last_seen)
        return [p.peer_id for p in peers[:MAX_FANOUT]
                if p.peer_id != self.peer.peer_id]

    async def collect(self, trace_id: str) -> dict[str, Any] | None:
        """One stitched cross-node trace, or None when NOBODY has spans."""
        root = self.obs.trace.get(trace_id)
        if root is not None:
            root = dict(root)
            root["node"] = "gateway"
        results = await asyncio.gather(
            *(fetch_fragment(self.peer, pid, trace_id, self.timeout)
              for pid in self._targets()),
            return_exceptions=True)
        fragments = [r for r in results
                     if isinstance(r, dict) and r is not None]
        if root is None and not fragments:
            return None
        return stitch(trace_id, root, fragments)


def stitch(trace_id: str, root: dict | None,
           fragments: list[dict]) -> dict[str, Any]:
    """Assemble fragments into one span tree on the root's timeline.

    Output spans carry ``node`` plus a synthetic per-node root span named
    after the node, parented under the gateway root, so the tree has no
    orphans: fragment spans whose recorded parent is the cross-node
    ``"gateway"`` link (or is missing from their own fragment) re-parent
    onto their node's root.
    """
    if root is None:
        # Degenerate: gateway ring already wrapped — promote the earliest
        # fragment to root so the operator still gets a tree.
        fragments = sorted(fragments,
                           key=lambda f: f.get("started_at", 0.0))
        root, fragments = dict(fragments[0]), fragments[1:]
    t0_wall = float(root.get("started_at", 0.0))
    total_us = float(root.get("total_us", 0.0))
    root_node = str(root.get("node", "gateway"))

    out_spans: list[dict] = [{
        "node": root_node, "name": root_node, "start_us": 0.0,
        "dur_us": total_us, "parent": "",
    }]
    names_by_node: dict[str, set[str]] = {root_node: {root_node}}

    def add_fragment(frag: dict, parent: str) -> None:
        node = str(frag.get("node", "?"))
        spans = list(frag.get("spans", []))
        frag_end = max([float(s.get("start_us", 0.0))
                        + float(s.get("dur_us", 0.0)) for s in spans]
                       + [float(frag.get("total_us", 0.0))] or [0.0])
        # Coarse wall-clock placement, then nest inside the root window
        # (see module docstring): skew cannot push a hop before admission
        # or past completion.
        off_us = (float(frag.get("started_at", t0_wall)) - t0_wall) * 1e6
        if total_us > 0:
            off_us = max(0.0, min(off_us, max(0.0, total_us - frag_end)))
        else:
            off_us = max(0.0, off_us)
        node_root = {
            "node": node, "name": node,
            "start_us": round(off_us, 1),
            "dur_us": round(frag_end, 1),
            "parent": parent,
        }
        if frag.get("meta"):
            node_root["meta"] = frag["meta"]
        out_spans.append(node_root)
        local_names = {str(s.get("name", "")) for s in spans}
        names_by_node[node] = local_names | {node}
        for s in spans:
            sp = {
                "node": node,
                "name": str(s.get("name", "")),
                "start_us": round(off_us + float(s.get("start_us", 0.0)), 1),
                "dur_us": float(s.get("dur_us", 0.0)),
                "parent": str(s.get("parent", "")),
            }
            # Re-parent the fragment-local tree: a span pointing at the
            # cross-node link (the sender's parent_span, e.g. "gateway")
            # or at a name this fragment never recorded hangs off the
            # node root instead of dangling as an orphan.
            if sp["parent"] not in local_names or sp["parent"] == sp["name"]:
                sp["parent"] = node
            if s.get("meta"):
                sp["meta"] = s["meta"]
            out_spans.append(sp)

    # Root fragment's own spans keep their recorded parents when those
    # resolve; anything else hangs off the root span.
    root_names = {str(s.get("name", "")) for s in root.get("spans", [])}
    for s in root.get("spans", []):
        sp = {
            "node": root_node,
            "name": str(s.get("name", "")),
            "start_us": float(s.get("start_us", 0.0)),
            "dur_us": float(s.get("dur_us", 0.0)),
            "parent": str(s.get("parent", "")),
        }
        if (sp["parent"] != root_node and sp["parent"] not in root_names) \
                or sp["parent"] == sp["name"]:
            sp["parent"] = root_node
        if s.get("meta"):
            sp["meta"] = s["meta"]
        out_spans.append(sp)

    for frag in sorted(fragments, key=lambda f: f.get("started_at", 0.0)):
        add_fragment(frag, root_node)

    leaf_sum = sum(s["dur_us"] for s in out_spans[1:]
                   if s["name"] not in names_by_node)
    return {
        "trace_id": trace_id,
        "stitched": True,
        "started_at": round(t0_wall, 3),
        "total_us": round(total_us, 1),
        "done": bool(root.get("done", False)),
        "meta": root.get("meta", {}),
        "nodes": [root_node] + [str(f.get("node", "?")) for f in fragments],
        "span_sum_us": round(leaf_sum, 1),
        "spans": out_spans,
    }


def render_waterfall(stitched: dict, width: int = 48) -> str:
    """Indented text waterfall of a stitched trace (the ``crowdllama-tpu
    trace <id>`` CLI output).  One line per span: tree indentation, a bar
    positioned on the request timeline, duration, and meta."""
    total = max(1.0, float(stitched.get("total_us", 0.0)))
    spans = stitched.get("spans", [])
    children: dict[str, list[dict]] = {}
    by_name: dict[str, dict] = {}
    for s in spans:
        by_name.setdefault(s["name"], s)
        children.setdefault(s.get("parent", ""), []).append(s)

    def fmt_us(us: float) -> str:
        if us >= 1e6:
            return f"{us / 1e6:.2f}s"
        if us >= 1e3:
            return f"{us / 1e3:.1f}ms"
        return f"{us:.0f}us"

    lines = [
        f"trace {stitched.get('trace_id', '?')}"
        f"  ·  nodes: {', '.join(stitched.get('nodes', []))}"
        f"  ·  total {fmt_us(total)}"
        + ("" if stitched.get("done") else "  ·  IN FLIGHT"),
    ]
    meta = stitched.get("meta") or {}
    if meta:
        lines.append("  " + " ".join(f"{k}={v}" for k, v in
                                     sorted(meta.items())))

    seen: set[int] = set()

    def bar(start_us: float, dur_us: float) -> str:
        lo = int(width * min(1.0, max(0.0, start_us / total)))
        hi = int(width * min(1.0, max(0.0, (start_us + dur_us) / total)))
        hi = max(hi, lo + 1)
        return " " * lo + "▇" * (hi - lo) + " " * (width - hi)

    def walk(span: dict, depth: int) -> None:
        if id(span) in seen:  # defensive: malformed parent cycles
            return
        seen.add(id(span))
        label = ("  " * depth) + span["name"]
        extra = ""
        if span.get("meta"):
            extra = "  " + ",".join(
                f"{k}={v}" for k, v in sorted(span["meta"].items()))
        lines.append(f"  {label:<28.28} |{bar(span['start_us'], span['dur_us'])}"
                     f"| {fmt_us(span['dur_us']):>8}{extra}")
        kids = [c for c in children.get(span["name"], []) if c is not span]
        for c in sorted(kids, key=lambda x: x["start_us"]):
            walk(c, depth + 1)

    roots = [s for s in spans if not s.get("parent")]
    for r in roots:
        walk(r, 0)
    return "\n".join(lines)


class FlightRecorder:
    """Bounded ring of complete stitched traces for interesting requests.

    Separate from the general trace ring on purpose: under load the
    general ring wraps in seconds, but the three requests that crossed
    p99 during an incident must still be there when the operator arrives.
    Thread-safe; capture is last-writer-wins per trace id.
    """

    def __init__(self, capacity: int = 32) -> None:
        self.capacity = max(1, int(capacity))
        self._lock = threading.Lock()
        self._ring: OrderedDict[str, dict] = OrderedDict()
        self.captured_total = 0

    def capture(self, trace_id: str, reasons: list[str],
                stitched: dict) -> None:
        if not trace_id or not reasons:
            return
        entry = {
            "trace_id": trace_id,
            "captured_at": round(time.time(), 3),
            "reasons": sorted(set(reasons)),
            "trace": stitched,
        }
        with self._lock:
            if trace_id in self._ring:
                self._ring.pop(trace_id)
            self._ring[trace_id] = entry
            self.captured_total += 1
            while len(self._ring) > self.capacity:
                self._ring.popitem(last=False)

    def get(self, trace_id: str) -> dict | None:
        with self._lock:
            return self._ring.get(trace_id)

    def snapshot(self) -> dict[str, Any]:
        with self._lock:
            return {"capacity": self.capacity,
                    "captured_total": self.captured_total,
                    "traces": list(self._ring.values())}
