"""Cluster metric fan-in (PR 13 swarm observatory, docs/OBSERVABILITY.md).

The gateway answers "what is the swarm doing right now" by scraping every
worker's metric families over the authenticated p2p plane — a
``MetricsFetch`` fan-out with the same shape as the trace collector's
``TraceFetch`` (bounded fan-out, per-node timeout, a dead or wedged worker
degrades the snapshot instead of failing it) — and re-exporting the
result at ``GET /metrics/cluster``:

- every worker family, re-labeled with ``worker="<peer-id-head>"``
  (LabelGuard-capped, same 16-char head as the gateway's
  ``crowdllama_worker_*`` ``peer`` label so the two join);
- pre-aggregated swarm rollups (``crowdllama_cluster_*``: total
  tokens/s, mean occupancy, mean KV utilization, summed inflight);
- the gateway's own per-worker routing gauges, so one scrape feeds the
  ``crowdllama-tpu top`` table.

The fan-out runs per scrape hit — this is an operator surface, not a hot
path; Prometheus at a 15s interval costs each worker one small reply on a
pooled stream.
"""

from __future__ import annotations

import asyncio
import logging
import re

from crowdllama_tpu.obs.metrics import LabelGuard, _fmt

log = logging.getLogger("crowdllama.obs.cluster")

# Per-node scrape budget: mirrors the trace collector's — a dead worker
# must cost seconds, not the whole scrape.
FETCH_TIMEOUT_S = 3.0
# Fan-out bound, shared rationale with obs/collector.py: beyond this the
# operator should shard scraping into a real metrics backend.
MAX_FANOUT = 32

_SAMPLE_RE = re.compile(r"^([a-zA-Z_:][a-zA-Z0-9_:]*)(\{[^}]*\})? (.*)$")

# Worker gauges the rollups aggregate: (family suffix, how to combine).
_ROLLUP_MEAN = ("batch_occupancy", "kv_cache_utilization")
_ROLLUP_SUM = ("active_slots", "pending_depth")


async def fetch_metrics(peer, peer_id: str, families: tuple[str, ...] = (),
                        timeout: float = FETCH_TIMEOUT_S
                        ) -> tuple[str, str] | None:
    """Scrape one worker over the p2p plane.

    Returns ``(node_tag, exposition_text)``, or None when the worker
    cannot be reached or answers found=false — a cluster scrape must
    degrade to a partial snapshot, never fail.
    """
    from crowdllama_tpu.core import wire
    from crowdllama_tpu.core.messages import (
        extract_metrics_snapshot,
        metrics_fetch_msg,
    )
    from crowdllama_tpu.core.protocol import INFERENCE_PROTOCOL
    from crowdllama_tpu.testing import faults

    s = None
    try:
        # Chaos choke point (testing/faults.py): a worker dying mid-scrape
        # is what the partial-snapshot contract defends against.
        await faults.inject("obs.scrape", worker=peer_id)
        contact = await peer.dht.find_peer(peer_id)
        if contact is None:
            return None
        s = await peer.host.new_stream(contact, INFERENCE_PROTOCOL,
                                       timeout=timeout)
        await wire.write_length_prefixed_pb(
            s.writer, metrics_fetch_msg(families))
        reply = await wire.read_length_prefixed_pb(s.reader, timeout=timeout)
        snap = extract_metrics_snapshot(reply)
        if not snap.found:
            return None
        return (snap.node or f"peer:{peer_id[:8]}",
                snap.payload.decode("utf-8", "replace"))
    except asyncio.CancelledError:
        raise
    except Exception as e:
        log.debug("metrics scrape from %s failed: %s", peer_id[:8], e)
        return None
    finally:
        if s is not None:
            s.close()


class ClusterScraper:
    """Gateway-side swarm scrape + worker-labeled re-export."""

    def __init__(self, peer, timeout: float = FETCH_TIMEOUT_S) -> None:
        self.peer = peer  # the gateway's Peer (host + dht + peer_manager)
        self.timeout = timeout
        # One label value per scraped worker; MAX_FANOUT bounds the
        # fan-out, +1 headroom keeps churn from collapsing a live worker
        # to the fallback before old ids age out of the allow-set.
        self._worker_guard = LabelGuard(max_values=2 * MAX_FANOUT)
        self.scrapes_total = 0
        self.scrape_misses_total = 0  # targets that answered nothing

    def _targets(self) -> list:
        """Workers worth scraping: newest-seen first, bounded, never self
        (same policy as the trace collector's fan-out)."""
        pm = self.peer.peer_manager
        if pm is None:
            return []
        peers = sorted(pm.get_workers(), key=lambda p: -p.last_seen)
        return [p for p in peers[:MAX_FANOUT]
                if p.peer_id != self.peer.peer_id]

    async def scrape(self, families: tuple[str, ...] = ()
                     ) -> list[tuple[str, str, str]]:
        """Fan out; returns [(worker_label, node_tag, exposition_text)]
        for every worker that answered (partial on any failure)."""
        targets = self._targets()
        results = await asyncio.gather(
            *(fetch_metrics(self.peer, p.peer_id, families, self.timeout)
              for p in targets),
            return_exceptions=True)
        out: list[tuple[str, str, str]] = []
        seen: set[str] = set()
        for p, r in zip(targets, results):
            self.scrapes_total += 1
            if not isinstance(r, tuple):
                self.scrape_misses_total += 1
                continue
            label = self._worker_guard.value(p.peer_id[:16])
            if label in seen:
                # Guard fallback collision: dropping the extra worker's
                # samples keeps the exposition free of duplicate series.
                self.scrape_misses_total += 1
                continue
            seen.add(label)
            out.append((label, r[0], r[1]))
        return out

    async def render(self, families: tuple[str, ...] = ()) -> str:
        """The full /metrics/cluster exposition text."""
        snapshots = await self.scrape(families)
        lines = self._rollup_lines(snapshots)
        lines.extend(self._worker_lines())
        lines.extend(merge_snapshots(snapshots))
        return "\n".join(lines) + "\n"

    def _worker_lines(self) -> list[str]:
        """The gateway's own routing view per worker (advertised
        throughput/load/health) — same families and ``peer`` label head as
        the gateway /metrics block, so `top` reads one surface."""
        pm = self.peer.peer_manager
        if pm is None:
            return []
        lines = [
            "# TYPE crowdllama_worker_throughput_tokens_per_sec gauge",
            "# TYPE crowdllama_worker_load gauge",
            "# TYPE crowdllama_worker_healthy gauge",
        ]
        for p in pm.get_workers():
            pid = p.peer_id[:16]
            r = p.resource
            lines.append(
                f'crowdllama_worker_throughput_tokens_per_sec{{'
                f'peer="{pid}"}} {r.tokens_throughput}')
            lines.append(f'crowdllama_worker_load{{peer="{pid}"}} {r.load}')
            lines.append(f'crowdllama_worker_healthy{{peer="{pid}"}} '
                         f'{1 if p.is_healthy else 0}')
        return lines

    def _rollup_lines(self, snapshots: list[tuple[str, str, str]]
                      ) -> list[str]:
        """Pre-aggregated swarm gauges, computed from the scraped
        snapshots (occupancy/KV/inflight) and the routing plane's
        advertised throughput (tokens/s — workers do not self-report a
        rate family, the resource ad is the swarm-wide source)."""
        acc: dict[str, list[float]] = {}
        for _, _, text in snapshots:
            for key in _ROLLUP_MEAN + _ROLLUP_SUM:
                m = re.search(
                    rf"^crowdllama_engine_{key} ([0-9.eE+-]+)\s*$",
                    text, re.M)
                if m:
                    acc.setdefault(key, []).append(float(m.group(1)))
        pm = self.peer.peer_manager
        workers = pm.get_workers() if pm is not None else []
        tokens = sum(p.resource.tokens_throughput for p in workers)
        n = max(1, len(snapshots))
        inflight = sum(acc.get("active_slots", [])) \
            + sum(acc.get("pending_depth", []))
        lines = [
            "# TYPE crowdllama_cluster_workers_total gauge",
            f"crowdllama_cluster_workers_total {len(workers)}",
            "# TYPE crowdllama_cluster_workers_scraped gauge",
            f"crowdllama_cluster_workers_scraped {len(snapshots)}",
            "# TYPE crowdllama_cluster_scrapes_total counter",
            f"crowdllama_cluster_scrapes_total {self.scrapes_total}",
            "# TYPE crowdllama_cluster_scrape_misses_total counter",
            f"crowdllama_cluster_scrape_misses_total "
            f"{self.scrape_misses_total}",
            "# TYPE crowdllama_cluster_tokens_per_second gauge",
            f"crowdllama_cluster_tokens_per_second {_fmt(float(tokens))}",
            "# TYPE crowdllama_cluster_batch_occupancy gauge",
            f"crowdllama_cluster_batch_occupancy "
            f"{_fmt(sum(acc.get('batch_occupancy', [0.0])) / n)}",
            "# TYPE crowdllama_cluster_kv_cache_utilization gauge",
            f"crowdllama_cluster_kv_cache_utilization "
            f"{_fmt(sum(acc.get('kv_cache_utilization', [0.0])) / n)}",
            "# TYPE crowdllama_cluster_inflight gauge",
            f"crowdllama_cluster_inflight {_fmt(inflight)}",
        ]
        # Autopilot rollup (docs/AUTOTUNE.md): swarm-wide dial-move count
        # — one number that says whether the fleet's tuners have settled.
        moves = 0.0
        for _, _, text in snapshots:
            m = re.search(r"^crowdllama_autotune_moves_total ([0-9.eE+-]+)"
                          r"\s*$", text, re.M)
            if m:
                moves += float(m.group(1))
        lines += [
            "# TYPE crowdllama_cluster_autotune_moves_total counter",
            f"crowdllama_cluster_autotune_moves_total {_fmt(moves)}",
        ]
        return lines


def merge_snapshots(snapshots: list[tuple[str, str, str]]) -> list[str]:
    """Merge per-worker expositions into one worker-labeled exposition.

    Each family's ``# TYPE`` is declared once (the families are identical
    code on every worker; the first declaration wins and conflicting
    redeclarations are dropped); every sample line gains a leading
    ``worker`` label.  Exemplars are stripped — a trace id is meaningful
    against the worker that minted it, not a merged surface.
    """
    types: dict[str, str] = {}
    by_family: dict[str, list[str]] = {}
    order: list[str] = []
    for label, _, text in snapshots:
        for line in text.splitlines():
            line = line.strip()
            if not line:
                continue
            if line.startswith("#"):
                parts = line.split()
                if len(parts) == 4 and parts[:2] == ["#", "TYPE"]:
                    fam, kind = parts[2], parts[3]
                    if fam not in types:
                        types[fam] = kind
                        order.append(fam)
                continue
            if " # " in line:  # strip OpenMetrics exemplar suffix
                line = line.partition(" # ")[0]
            m = _SAMPLE_RE.match(line)
            if m is None:
                continue
            name, labels, value = m.groups()
            inner = (labels or "{}")[1:-1]
            merged = f'worker="{label}"' + ("," + inner if inner else "")
            fam = _base_family(name, types)
            by_family.setdefault(fam, []).append(
                f"{name}{{{merged}}} {value}")
    out: list[str] = []
    for fam in order:
        samples = by_family.pop(fam, [])
        if not samples:
            continue
        out.append(f"# TYPE {fam} {types[fam]}")
        out.extend(samples)
    # Samples whose TYPE never appeared (malformed worker) are dropped —
    # the lint contract on this surface is "declared or absent".
    return out


def _base_family(name: str, types: dict[str, str]) -> str:
    for suffix in ("_bucket", "_sum", "_count"):
        if name.endswith(suffix) and name[: -len(suffix)] in types:
            return name[: -len(suffix)]
    return name
