"""Swarm-wide observability plane: request tracing + histogram metrics.

Every node (gateway and worker) owns one :class:`NodeObs` holding

- a bounded :class:`~crowdllama_tpu.obs.trace.TraceBuffer` of per-request
  span trees, exposed as JSON at ``GET /debug/trace``;
- a :class:`~crowdllama_tpu.obs.metrics.NodeMetrics` bundle of the three
  fixed-bucket histograms (``crowdllama_request_seconds``,
  ``crowdllama_ttft_seconds``, ``crowdllama_decode_step_seconds``)
  rendered into the Prometheus text exposition on ``GET /metrics``.

Trace ids ride the ``llama.v1.BaseMessage`` envelope (``trace_id`` /
``parent_span``, proto fields 5/6 outside the oneof) so one id follows a
request gateway -> stream pool -> worker peer -> engine, including across
the relay splice (the splice forwards sealed ciphertext, so the fields
cross it untouched).  See docs/OBSERVABILITY.md for the span taxonomy and
the ``/debug/trace`` schema.
"""

from __future__ import annotations

from crowdllama_tpu.obs.metrics import (  # noqa: F401
    DECODE_STEP_BUCKETS,
    REQUEST_BUCKETS,
    TTFT_BUCKETS,
    Histogram,
    HistogramVec,
    LabelGuard,
    NodeMetrics,
)
from crowdllama_tpu.obs.trace import Span, TraceBuffer, new_trace_id  # noqa: F401

GATEWAY_ROOT_SPAN = "gateway"

# Engine/scheduler gauge keys every Engine.obs_gauges() returns; the
# exposition layer maps them to crowdllama_engine_<key> gauges on both the
# gateway and the worker /metrics endpoints.
ENGINE_GAUGES = (
    "pending_depth",
    "active_slots",
    "batch_occupancy",
    "kv_cache_utilization",
)


class NodeObs:
    """One node's tracing + metrics state (gateway or worker).

    ``trace_ttl`` (seconds, 0 = off) age-evicts span fragments so the
    trace collector never stitches stale data; ``exemplars`` enables the
    OpenMetrics trace_id exemplar suffix on the request-path histograms.
    """

    def __init__(self, trace_capacity: int = 64, node: str = "",
                 trace_ttl: float = 0.0, exemplars: bool = False) -> None:
        self.node = node
        self.trace = TraceBuffer(capacity=trace_capacity, node=node,
                                 ttl=trace_ttl)
        self.metrics = NodeMetrics(exemplars=exemplars)

    def observe_generate(self, trace_id: str, parent: str, model: str,
                         queue_ns: int, prefill_ns: int, decode_ns: int,
                         steps: int, total_ns: int, **meta) -> None:
        """Record one served generate exchange: worker-side spans + histograms.

        Called at the Engine seam so FakeEngine and JaxEngine produce the
        same span taxonomy (worker_queue / prefill / decode_step).
        """
        self.metrics.request_seconds.labels(model).observe(
            total_ns / 1e9, exemplar=trace_id)
        self.metrics.ttft_seconds.observe(
            (queue_ns + prefill_ns) / 1e9, exemplar=trace_id)
        if trace_id:
            t = self.trace
            t.begin(trace_id, model=model, **meta)
            t.record(trace_id, "worker_queue", queue_ns, parent=parent)
            t.record(trace_id, "prefill", prefill_ns, parent=parent)
            t.record(trace_id, "decode_step", decode_ns, parent=parent,
                     steps=steps)
            t.finish(trace_id, total_ns)
