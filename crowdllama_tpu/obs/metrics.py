"""Fixed-bucket histograms + label hygiene for the /metrics exposition.

Replaces the counters-only exposition of PR 1 with latency distributions:

- ``crowdllama_request_seconds``     end-to-end per request, labeled by model
- ``crowdllama_ttft_seconds``        time to first token
- ``crowdllama_decode_step_seconds`` per decode step

Both the gateway and the worker-side ObsServer render the same families
through :class:`NodeMetrics`, so a scraper sees one schema swarm-wide.

:class:`LabelGuard` is the generalized form of the gateway's path
allowlist: every labeled series (paths, model names, phase names) goes
through a guard so a client cannot mint unbounded series by varying a
request field (label-cardinality DoS on the scrape pipeline).
"""

from __future__ import annotations

import re
import threading
import time
from typing import Iterable

# Bucket upper bounds in seconds.  Request/TTFT cover loopback FakeEngine
# (sub-ms) through big-model TPU prefill (tens of seconds); decode steps
# cover fused-kernel steps (sub-ms) through CPU-interpreted tiny models.
REQUEST_BUCKETS = (0.005, 0.01, 0.025, 0.05, 0.1, 0.25, 0.5,
                   1.0, 2.5, 5.0, 10.0, 30.0, 60.0)
TTFT_BUCKETS = (0.005, 0.01, 0.025, 0.05, 0.1, 0.25, 0.5,
                1.0, 2.5, 5.0, 10.0)
DECODE_STEP_BUCKETS = (0.0005, 0.001, 0.0025, 0.005, 0.01, 0.025,
                       0.05, 0.1, 0.25, 0.5, 1.0)
# XLA compile wall time per (program, bucket) first dispatch: CPU-jitted
# tiny test models compile in tens of ms, big-model TPU prefill programs in
# minutes.
COMPILE_BUCKETS = (0.01, 0.025, 0.05, 0.1, 0.25, 0.5, 1.0, 2.5, 5.0,
                   10.0, 30.0, 60.0, 120.0)

_LABEL_VALUE_RE = re.compile(r"^[A-Za-z0-9_.:/\-]{1,64}$")

# The scheduler's decode dispatch classes (docs/OBSERVABILITY.md duty
# cycle): how a flight reached the device — plain per-step chunk,
# kernel-looped megastep, unified ragged step, or speculative verify.
DISPATCH_CLASSES = ("plain", "megastep", "ragged", "spec")


def _fmt(v: float) -> str:
    """Exposition number format: integers without a trailing .0."""
    if isinstance(v, float) and v.is_integer():
        return str(int(v))
    return repr(float(v))


class LabelGuard:
    """Bound the value space of one metric label.

    A value passes when it matches the explicit allowlist (if given) or,
    with no allowlist, when it looks like a sane identifier AND the number
    of distinct values seen so far is under ``max_values``.  Everything
    else collapses to ``fallback`` so series cardinality stays bounded no
    matter what strings arrive from the network.
    """

    def __init__(self, allowed: Iterable[str] | None = None,
                 max_values: int = 64, fallback: str = "other") -> None:
        self._allowed = frozenset(allowed) if allowed is not None else None
        self._max = max(1, int(max_values))
        self._fallback = fallback
        self._seen: set[str] = set()
        self._lock = threading.Lock()

    def value(self, raw: object) -> str:
        s = str(raw) if raw else ""
        if self._allowed is not None:
            return s if s in self._allowed else self._fallback
        if not _LABEL_VALUE_RE.match(s):
            return self._fallback
        with self._lock:
            if s not in self._seen:
                if len(self._seen) >= self._max:
                    return self._fallback
                self._seen.add(s)
        return s


class Histogram:
    """Fixed-bucket histogram, rendered cumulatively at exposition time.

    Observations may carry a trace_id *exemplar* — the last one lands on
    the bucket it fell into and, when exemplar rendering is enabled, is
    emitted in OpenMetrics syntax (`` # {trace_id="..."} <value>``) so a
    dashboard spike links straight to a stitched trace."""

    def __init__(self, buckets: Iterable[float]) -> None:
        self.buckets = tuple(sorted(float(b) for b in buckets))
        if not self.buckets:
            raise ValueError("histogram needs at least one bucket")
        self._counts = [0] * (len(self.buckets) + 1)  # +1 = overflow (+Inf)
        self._exemplars: list[tuple[str, float] | None] = \
            [None] * (len(self.buckets) + 1)
        self._sum = 0.0
        self._lock = threading.Lock()

    def observe(self, value: float, exemplar: str = "") -> None:
        v = float(value)
        idx = len(self.buckets)
        for i, b in enumerate(self.buckets):
            if v <= b:
                idx = i
                break
        with self._lock:
            self._counts[idx] += 1
            self._sum += v
            if exemplar:
                self._exemplars[idx] = (exemplar, v)

    @property
    def count(self) -> int:
        with self._lock:
            return sum(self._counts)

    @property
    def sum(self) -> float:
        with self._lock:
            return self._sum

    def snapshot_counts(self) -> list[int]:
        """Non-cumulative per-bucket counts (last = overflow); benchmarks
        diff two snapshots to get a per-window distribution."""
        with self._lock:
            return list(self._counts)

    def quantile(self, q: float) -> float:
        """Bucket-resolution quantile estimate (PromQL histogram_quantile
        semantics).  Benchmarks read their percentiles from here so the
        published number is the same one a dashboard would compute from
        the scraped series."""
        return quantile_from_counts(self.buckets, self.snapshot_counts(), q)

    def lines(self, name: str, labels: str = "",
              exemplars: bool = False) -> list[str]:
        """Series lines (no TYPE header) for one child of a family.

        ``labels`` is a pre-rendered ``key="value"`` list without braces.
        With ``exemplars`` each bucket that captured one gets the
        OpenMetrics exemplar suffix on its _bucket line.
        """
        with self._lock:
            counts = list(self._counts)
            exs = list(self._exemplars)
            total_sum = self._sum
        sep = "," if labels else ""

        def _ex(i: int) -> str:
            if not exemplars or exs[i] is None:
                return ""
            tid, v = exs[i]
            return f' # {{trace_id="{tid}"}} {_fmt(v)}'

        out: list[str] = []
        cum = 0
        for i, (b, c) in enumerate(zip(self.buckets, counts)):
            cum += c
            out.append(f'{name}_bucket{{{labels}{sep}le="{_fmt(b)}"}} '
                       f'{cum}{_ex(i)}')
        cum += counts[-1]
        out.append(f'{name}_bucket{{{labels}{sep}le="+Inf"}} '
                   f'{cum}{_ex(len(counts) - 1)}')
        out.append(f"{name}_sum{{{labels}}} {_fmt(total_sum)}"
                   if labels else f"{name}_sum {_fmt(total_sum)}")
        out.append(f"{name}_count{{{labels}}} {cum}"
                   if labels else f"{name}_count {cum}")
        return out


def quantile_from_counts(buckets: tuple[float, ...], counts: list[int],
                         q: float) -> float:
    """Quantile of a (buckets, non-cumulative counts) pair: linear
    interpolation inside the bucket, the overflow bucket clamps to the
    highest finite bound.  Counts may be a DELTA of two snapshots."""
    q = min(1.0, max(0.0, float(q)))
    total = sum(counts)
    if total == 0:
        return 0.0
    rank = q * total
    cum = 0
    lo = 0.0
    for b, c in zip(buckets, counts):
        cum += c
        if cum >= rank:
            if c == 0:
                return b
            return lo + (b - lo) * (1 - (cum - rank) / c)
        lo = b
    return buckets[-1]


class HistogramVec:
    """Histogram family keyed by one guarded label."""

    def __init__(self, buckets: Iterable[float], label: str,
                 guard: LabelGuard | None = None) -> None:
        self._buckets = tuple(buckets)
        self._label = label
        self._guard = guard or LabelGuard(max_values=32)
        self._children: dict[str, Histogram] = {}
        self._lock = threading.Lock()

    def labels(self, value: object) -> Histogram:
        key = self._guard.value(value)
        with self._lock:
            h = self._children.get(key)
            if h is None:
                h = Histogram(self._buckets)
                self._children[key] = h
            return h

    def expose(self, name: str, exemplars: bool = False) -> list[str]:
        out = [f"# TYPE {name} histogram"]
        with self._lock:
            children = sorted(self._children.items())
        for key, h in children:
            out.extend(h.lines(name, f'{self._label}="{key}"',
                               exemplars=exemplars))
        return out


class NodeMetrics:
    """The three per-node histogram families, one instance per node."""

    def __init__(self, exemplars: bool = False) -> None:
        # OpenMetrics trace_id exemplars on the request-path histograms
        # (--metrics-exemplars): off by default — classic Prometheus text
        # parsers reject the suffix.
        self.exemplars = bool(exemplars)
        self.model_guard = LabelGuard(max_values=32)
        self.request_seconds = HistogramVec(
            REQUEST_BUCKETS, "model", self.model_guard)
        self.ttft_seconds = Histogram(TTFT_BUCKETS)
        self.decode_step_seconds = Histogram(DECODE_STEP_BUCKETS)
        # KV shipping (docs/KV_TRANSFER.md): fetch latency observed by the
        # fetching worker; bytes/fetches/fallbacks count page traffic on
        # whichever side moved it (a donor's exports land in the same
        # families).  Part of NodeMetrics so every node — gateway included —
        # exposes the series at zero rather than absent.
        self.kv_fetch_seconds = Histogram(TTFT_BUCKETS)
        self.kv_ship = {"bytes": 0, "fetches": 0, "fallbacks": 0,
                        "retries": 0}
        # Graceful drain + live migration (docs/ROBUSTNESS.md): drain_*
        # count control-plane events on the node that drained; the two
        # flat families count the request plane's view of migration —
        # migrated_streams on whichever side moved a stream (the gateway
        # re-routing it, the worker handing it off),
        # replayed_prefill_tokens on the successor worker: prompt tokens a
        # migrate-flagged request recomputed even though the donor could
        # have served them (0 == the KV handoff was complete).
        self.drain = {"initiated": 0, "migrated_slots": 0,
                      "rejected_requests": 0}
        self.migrated_streams = 0
        self.replayed_prefill_tokens = 0
        # Replicated gateway plane (docs/ROBUSTNESS.md "replicated
        # gateway"): gossip anti-entropy traffic + LWW map health, and
        # per-tenant admission outcomes.  In NodeMetrics (not gateway-only
        # state) so both scrape surfaces — gateway /metrics and the
        # worker-side ObsServer — expose the families at zero.
        self.gossip = {"frames_sent": 0, "frames_received": 0,
                       "entries_applied": 0, "entries_stale": 0,
                       "full_syncs": 0, "send_failures": 0,
                       "snapshot_saves": 0,
                       # gauges
                       "map_entries": 0, "snapshot_entries_loaded": 0}
        self.tenant_guard = LabelGuard(max_values=32)
        self.tenant_admitted: dict[str, int] = {}
        self.tenant_shed: dict[str, int] = {}
        self.tenant_inflight: dict[str, int] = {}

    def kv_ship_inc(self, key: str, n: int = 1) -> None:
        self.kv_ship[key] = self.kv_ship.get(key, 0) + int(n)

    def drain_inc(self, key: str, n: int = 1) -> None:
        self.drain[key] = self.drain.get(key, 0) + int(n)

    def gossip_inc(self, key: str, n: int = 1) -> None:
        self.gossip[key] = self.gossip.get(key, 0) + int(n)

    def tenant_inc(self, family: dict, tenant: str, n: int = 1) -> None:
        key = self.tenant_guard.value(tenant or "default")
        family[key] = family.get(key, 0) + int(n)

    def expose(self) -> list[str]:
        ex = self.exemplars
        out = self.request_seconds.expose("crowdllama_request_seconds",
                                          exemplars=ex)
        out.append("# TYPE crowdllama_ttft_seconds histogram")
        out.extend(self.ttft_seconds.lines("crowdllama_ttft_seconds",
                                           exemplars=ex))
        out.append("# TYPE crowdllama_decode_step_seconds histogram")
        out.extend(self.decode_step_seconds.lines(
            "crowdllama_decode_step_seconds", exemplars=ex))
        for key in ("bytes", "fetches", "fallbacks", "retries"):
            name = f"crowdllama_kv_ship_{key}_total"
            out.append(f"# TYPE {name} counter")
            out.append(f"{name} {self.kv_ship.get(key, 0)}")
        out.append("# TYPE crowdllama_kv_fetch_seconds histogram")
        out.extend(self.kv_fetch_seconds.lines("crowdllama_kv_fetch_seconds"))
        for key in ("initiated", "migrated_slots", "rejected_requests"):
            name = f"crowdllama_drain_{key}_total"
            out.append(f"# TYPE {name} counter")
            out.append(f"{name} {self.drain.get(key, 0)}")
        out.append("# TYPE crowdllama_migrated_streams_total counter")
        out.append(f"crowdllama_migrated_streams_total "
                   f"{self.migrated_streams}")
        out.append("# TYPE crowdllama_replayed_prefill_tokens_total counter")
        out.append(f"crowdllama_replayed_prefill_tokens_total "
                   f"{self.replayed_prefill_tokens}")
        for key in ("frames_sent", "frames_received", "entries_applied",
                    "entries_stale", "full_syncs", "send_failures",
                    "snapshot_saves"):
            name = f"crowdllama_gossip_{key}_total"
            out.append(f"# TYPE {name} counter")
            out.append(f"{name} {self.gossip.get(key, 0)}")
        for key in ("map_entries", "snapshot_entries_loaded"):
            name = f"crowdllama_gossip_{key}"
            out.append(f"# TYPE {name} gauge")
            out.append(f"{name} {self.gossip.get(key, 0)}")
        for fam, kind, series in (
            ("crowdllama_tenant_admitted_total", "counter",
             self.tenant_admitted),
            ("crowdllama_tenant_shed_total", "counter", self.tenant_shed),
            ("crowdllama_tenant_inflight", "gauge", self.tenant_inflight),
        ):
            out.append(f"# TYPE {fam} {kind}")
            if not series:
                out.append(f'{fam}{{tenant="default"}} 0')
            for tenant in sorted(series):
                out.append(f'{fam}{{tenant="{tenant}"}} {series[tenant]}')
        return out


def engine_gauge_lines(gauges: dict) -> list[str]:
    """Render Engine.obs_gauges() as crowdllama_engine_* series.

    Keys are gauges except ``*_total``, which declare as counters (the
    Prometheus suffix convention — e.g. host_dispatches_total counts
    device programs launched and only ever grows).  A ``base|label=value``
    key renders as a labeled child of the ``base`` family (one TYPE line
    per family) — the duty-cycle gauges use this to keep one family
    across the four dispatch classes."""
    out: list[str] = []
    typed: set[str] = set()
    for key in sorted(gauges):
        try:
            val = float(gauges[key])
        except (TypeError, ValueError):
            continue
        base, _, label = key.partition("|")
        # Autopilot keys are their own exposition plane (ISSUE 17,
        # docs/AUTOTUNE.md): crowdllama_autotune_* rather than an
        # engine_-prefixed family, because the dials belong to the
        # control loop, not the batch-shape gauges dashboards rate().
        name = (f"crowdllama_{base}" if base.startswith("autotune_")
                else f"crowdllama_engine_{base}")
        kind = "counter" if base.endswith("_total") else "gauge"
        if name not in typed:
            typed.add(name)
            out.append(f"# TYPE {name} {kind}")
        if label:
            lname, _, lval = label.partition("=")
            out.append(f'{name}{{{lname}="{lval}"}} {_fmt(val)}')
        else:
            out.append(f"{name} {_fmt(val)}")
    return out


class EngineTelemetry:
    """Process-wide XLA compile + padding accounting (PR 8 tentpole).

    Module-level (like net/secure's aead counters) rather than hung off
    NodeObs: the runners compile during engine construction and warmup,
    BEFORE the peer wires ``engine.obs`` — a per-node object would miss
    exactly the compiles the operator most wants to see.  Thread-safe:
    the scheduler's jax-dispatch thread records while the event loop
    scrapes.

    Compile detection is first-dispatch timing: the first call of a jitted
    program per static signature (program name + bucket) pays trace +
    lower + XLA compile synchronously, so its wall time IS the compile
    cost to within one dispatch — deterministic, backend-agnostic, and
    exactly the recompile-storm signal (a retuned spec draft_len or an
    unexpected prefill bucket shows up as a new (program, bucket) count).
    """

    def __init__(self) -> None:
        self._lock = threading.Lock()
        self.compile_seconds = Histogram(COMPILE_BUCKETS)
        self.program_guard = LabelGuard(max_values=64)
        self.bucket_guard = LabelGuard(max_values=256)
        self._compiles: dict[tuple[str, str], int] = {}
        self._seen: set[tuple[str, str]] = set()
        # Cached-hit witness (ISSUE 17 satellite): dispatches whose
        # (program, bucket) signature was already claimed — the proof
        # that flipping a dial BACK is free (no recompile).  Keyed by
        # program only: the interesting fact is "this entry point reused
        # a signature", not which bucket did.
        self._cache_hits: dict[str, int] = {}
        self._padding = {"waste": 0, "useful": 0}
        # Unified ragged batch (docs/RAGGED_BATCH.md): wall time per
        # prefill chunk carried inside a decode dispatch.  Engine-plane
        # like the compile histogram (the scheduler's dispatch loop
        # records it), rendered on both scrape surfaces.
        self.prefill_chunk_seconds = Histogram(DECODE_STEP_BUCKETS)
        # Decode duty-cycle profiler (PR 13, docs/OBSERVABILITY.md): the
        # host-side gap between one flight's retire and the next flight's
        # dispatch, per dispatch class.  Children pre-created so every
        # class renders a zero histogram from the first scrape (absent()-
        # style alerts, and the fixed allowlist IS the LabelGuard).
        self.host_gap_seconds = HistogramVec(
            DECODE_STEP_BUCKETS, "dispatch",
            LabelGuard(allowed=DISPATCH_CLASSES))
        for cls in DISPATCH_CLASSES:
            self.host_gap_seconds.labels(cls)

    def _key(self, program: str, bucket: object) -> tuple[str, str]:
        return (self.program_guard.value(program),
                self.bucket_guard.value(str(bucket)))

    def compile_begin(self, program: str, bucket: object) -> float:
        """0.0 when (program, bucket) already dispatched; otherwise claim
        the signature and return a perf_counter() start for compile_end.
        The membership probe is the only cost on the steady-state path."""
        key = self._key(program, bucket)
        with self._lock:
            if key in self._seen:
                self._cache_hits[key[0]] = \
                    self._cache_hits.get(key[0], 0) + 1
                return 0.0
            self._seen.add(key)
        return time.perf_counter()

    def compile_end(self, program: str, bucket: object, t0: float) -> None:
        if not t0:
            return
        dt = max(0.0, time.perf_counter() - t0)
        key = self._key(program, bucket)
        with self._lock:
            self._compiles[key] = self._compiles.get(key, 0) + 1
        self.compile_seconds.observe(dt)

    def padding_inc(self, useful: int, waste: int) -> None:
        """Account one padded dispatch: ``useful`` real tokens rode it,
        ``waste`` were padding (bucket rounding, inactive decode slots)."""
        with self._lock:
            self._padding["useful"] += max(0, int(useful))
            self._padding["waste"] += max(0, int(waste))

    def snapshot_compiles(self) -> dict[tuple[str, str], int]:
        """(program, bucket) -> count; tests diff two snapshots to assert
        e.g. a draft_len retune added exactly one new decode bucket."""
        with self._lock:
            return dict(self._compiles)

    def snapshot_cache_hits(self) -> dict[str, int]:
        """program -> cached-signature dispatch count; the retune test
        diffs two snapshots to prove a dial revert recompiled nothing."""
        with self._lock:
            return dict(self._cache_hits)

    def padding_snapshot(self) -> dict[str, int]:
        with self._lock:
            return dict(self._padding)

    def expose(self) -> list[str]:
        out = ["# TYPE crowdllama_xla_compile_seconds histogram"]
        out.extend(self.compile_seconds.lines(
            "crowdllama_xla_compile_seconds"))
        with self._lock:
            compiles = sorted(self._compiles.items())
            padding = dict(self._padding)
            cache_hits = sorted(self._cache_hits.items())
        out.append("# TYPE crowdllama_xla_compiles_total counter")
        if not compiles:
            out.append('crowdllama_xla_compiles_total{program="none",'
                       'bucket="0"} 0')
        for (program, bucket), n in compiles:
            out.append(f'crowdllama_xla_compiles_total{{'
                       f'program="{program}",bucket="{bucket}"}} {n}')
        # Cached-hit witness (docs/AUTOTUNE.md): signature reuse per jit
        # entry point — a dial revert shows up here instead of as a new
        # crowdllama_xla_compiles_total child.
        out.append("# TYPE crowdllama_xla_compile_cache_hits_total counter")
        if not cache_hits:
            out.append('crowdllama_xla_compile_cache_hits_total{'
                       'program="none"} 0')
        for program, n in cache_hits:
            out.append(f'crowdllama_xla_compile_cache_hits_total{{'
                       f'program="{program}"}} {n}')
        out.append("# TYPE crowdllama_padding_waste_tokens_total counter")
        out.append(f"crowdllama_padding_waste_tokens_total "
                   f"{padding['waste']}")
        out.append("# TYPE crowdllama_useful_tokens_total counter")
        out.append(f"crowdllama_useful_tokens_total {padding['useful']}")
        out.append("# TYPE crowdllama_prefill_chunk_seconds histogram")
        out.extend(self.prefill_chunk_seconds.lines(
            "crowdllama_prefill_chunk_seconds"))
        out.extend(self.host_gap_seconds.expose(
            "crowdllama_host_gap_seconds"))
        return out


# The process-wide engine profiling plane; runners and schedulers record
# into it directly, both scrape surfaces render it.
ENGINE_TELEMETRY = EngineTelemetry()


def device_memory_lines() -> list[str]:
    """Per-device memory gauges from jax.local_devices()[*].memory_stats(),
    sampled at scrape time.  Platforms without the API (CPU) report zeros —
    the series must exist for absent()-style alerts either way."""
    devices = []
    try:
        import jax

        devices = jax.local_devices()
    except Exception:
        pass
    out = ["# TYPE crowdllama_device_memory_bytes_in_use gauge",
           "# TYPE crowdllama_device_memory_bytes_limit gauge"]
    if not devices:
        out.append('crowdllama_device_memory_bytes_in_use{device="0"} 0')
        out.append('crowdllama_device_memory_bytes_limit{device="0"} 0')
        return out
    for i, d in enumerate(devices):
        stats: dict = {}
        try:
            stats = d.memory_stats() or {}
        except Exception:
            pass
        in_use = int(stats.get("bytes_in_use") or 0)
        limit = int(stats.get("bytes_limit")
                    or stats.get("bytes_reservable_limit") or 0)
        out.append(f'crowdllama_device_memory_bytes_in_use{{'
                   f'device="{i}"}} {in_use}')
        out.append(f'crowdllama_device_memory_bytes_limit{{'
                   f'device="{i}"}} {limit}')
    return out
