"""SLO burn-rate engine (PR 13 swarm observatory, docs/OBSERVABILITY.md).

Classic multi-window burn-rate tracking (the SRE-workbook shape) over the
gateway's two latency objectives:

- **TTFT** (``--slo-ttft-ms``): time from admission to the worker's first
  token frame — observed where the gateway's TTFB histogram is fed.
- **decode p95** (``--slo-decode-ms``): per decode-step gap on streamed
  responses — observed in the gateway's stream-forward loop.

Each observation is classified good/bad against the objective; the burn
rate over a window is ``bad_fraction / error_budget`` — 1.0 means the
budget is being spent exactly as provisioned, N means N× too fast.  Two
rolling windows (5m fast / 1h slow) catch both a sharp regression and a
slow leak; *fast burn* (both windows over the threshold, the
page-worthy condition) flips an edge-triggered episode flag the flight
recorder uses to auto-capture the requests that breached.

Pure host-side math over bucketed rolling counters — bounded memory, a
monotonic clock injected for unit tests, no JAX, no asyncio.
"""

from __future__ import annotations

import threading
import time

# Rolling windows: (label, seconds).  The short window is the fast-burn
# trigger; the long one confirms it is not a blip (SRE workbook's
# multiwindow, multi-burn-rate alert shape).
WINDOWS = (("5m", 300.0), ("1h", 3600.0))
# Counter bucketing: one (good, bad) cell per this many seconds — 10s
# cells keep the 1h window at 360 cells per objective.
BUCKET_S = 10.0
# Error budget: fraction of requests allowed to breach the objective.
# burn = bad_fraction / budget, so with 5% budget a 100%-bad outage burns
# at 20×.
DEFAULT_BUDGET = 0.05
# Both windows at/above this burn rate = fast burn (with a 5% budget this
# is ~70% of requests breaching — an incident, not noise).
FAST_BURN = 14.0


class BurnRateTracker:
    """Good/bad classification + multi-window burn rates for ONE
    objective.  Thread-safe: the gateway observes from request handlers
    while /metrics renders from another task."""

    def __init__(self, name: str, objective_ms: float,
                 budget: float = DEFAULT_BUDGET,
                 clock=time.monotonic) -> None:
        self.name = name
        self.objective_ms = float(objective_ms)
        self.budget = min(1.0, max(1e-6, float(budget)))
        self._clock = clock
        self._lock = threading.Lock()
        # Rolling cells: bucket start -> [good, bad], pruned past the
        # longest window on every observe.
        self._cells: dict[float, list[int]] = {}
        self.good_total = 0
        self.bad_total = 0

    def observe(self, seconds: float) -> bool:
        """Record one request; returns True when it breached."""
        bad = seconds * 1000.0 > self.objective_ms
        now = self._clock()
        bucket = now - (now % BUCKET_S)
        horizon = now - max(w for _, w in WINDOWS) - BUCKET_S
        with self._lock:
            cell = self._cells.setdefault(bucket, [0, 0])
            cell[1 if bad else 0] += 1
            if bad:
                self.bad_total += 1
            else:
                self.good_total += 1
            for b in [b for b in self._cells if b < horizon]:
                del self._cells[b]
        return bad

    def burn_rates(self) -> dict[str, float]:
        """{window label: burn rate} — 0.0 for an idle window."""
        now = self._clock()
        out: dict[str, float] = {}
        with self._lock:
            for label, span in WINDOWS:
                good = bad = 0
                for b, (g, n) in self._cells.items():
                    if b >= now - span:
                        good += g
                        bad += n
                total = good + bad
                out[label] = (bad / total / self.budget) if total else 0.0
        return out

    def in_fast_burn(self) -> bool:
        rates = self.burn_rates()
        return all(r >= FAST_BURN for r in rates.values())


class WindowBurn:
    """Burn-rate math over retire WINDOWS instead of wall-clock buckets —
    the engine autopilot's worker-local SLO signal (engine/autotune.py,
    docs/AUTOTUNE.md).

    The scheduler retires a window every few milliseconds under load and
    not at all when idle, so wall-clock cells (BurnRateTracker) would
    read empty exactly when a bad dial move stalls the loop.  Counting
    the last N windows instead makes the signal traffic-relative: each
    observation is one window's per-token latency classified against
    ``objective_ms``; burn over a deque is ``bad_fraction / budget``;
    *fast burn* needs the short deque FULL and both deques at/above
    FAST_BURN — the same multiwindow shape as the gateway tracker, with
    the same page-worthy threshold."""

    def __init__(self, objective_ms: float = 0.0, short: int = 8,
                 long: int = 32, budget: float = DEFAULT_BUDGET) -> None:
        import collections

        self.objective_ms = float(objective_ms)
        self.budget = min(1.0, max(1e-6, float(budget)))
        self._short: "collections.deque" = collections.deque(
            maxlen=max(1, int(short)))
        self._long: "collections.deque" = collections.deque(
            maxlen=max(1, int(long)))
        self.breaches_total = 0

    def observe(self, ms: float) -> bool:
        """Record one window's per-token latency; True when it breached.
        With no objective configured yet every window counts good (the
        tuner derives an objective from its first baseline phase)."""
        bad = self.objective_ms > 0.0 and ms > self.objective_ms
        self._short.append(1 if bad else 0)
        self._long.append(1 if bad else 0)
        if bad:
            self.breaches_total += 1
        return bad

    def _rate(self, dq) -> float:
        return (sum(dq) / len(dq) / self.budget) if dq else 0.0

    def burn(self) -> float:
        """The long-window burn rate — the score penalty input."""
        return self._rate(self._long)

    def in_fast_burn(self) -> bool:
        return (len(self._short) == self._short.maxlen
                and self._rate(self._short) >= FAST_BURN
                and self._rate(self._long) >= FAST_BURN)


class SloEngine:
    """The gateway's objectives + the edge-triggered fast-burn episode
    flag.  An objective set to 0 is disabled (no tracker, no gauges)."""

    def __init__(self, ttft_ms: float = 0.0, decode_ms: float = 0.0,
                 budget: float = DEFAULT_BUDGET,
                 clock=time.monotonic) -> None:
        self.trackers: dict[str, BurnRateTracker] = {}
        if ttft_ms > 0:
            self.trackers["ttft"] = BurnRateTracker(
                "ttft", ttft_ms, budget, clock)
        if decode_ms > 0:
            self.trackers["decode"] = BurnRateTracker(
                "decode", decode_ms, budget, clock)
        self._in_episode = False
        self.fast_burn_episodes_total = 0

    @property
    def enabled(self) -> bool:
        return bool(self.trackers)

    def observe_ttft(self, seconds: float) -> bool:
        t = self.trackers.get("ttft")
        return t.observe(seconds) if t is not None else False

    def observe_decode(self, seconds: float) -> bool:
        t = self.trackers.get("decode")
        return t.observe(seconds) if t is not None else False

    def fast_burn(self) -> bool:
        """Level signal: ANY enabled objective is fast-burning on both
        windows.  Also advances the edge-triggered episode counter."""
        burning = any(t.in_fast_burn() for t in self.trackers.values())
        if burning and not self._in_episode:
            self.fast_burn_episodes_total += 1
        self._in_episode = burning
        return burning

    def expose(self) -> list[str]:
        """``crowdllama_slo_*`` families for the gateway /metrics.  The
        burn-rate gauge is the series the PR 6 autoscaler's parse_gauges
        consumes (swarm/autoscale.py)."""
        if not self.enabled:
            return []
        lines = [
            "# TYPE crowdllama_slo_objective_ms gauge",
        ]
        for name, t in sorted(self.trackers.items()):
            lines.append(
                f'crowdllama_slo_objective_ms{{objective="{name}"}} '
                f"{t.objective_ms:g}")
        lines.append("# TYPE crowdllama_slo_requests_total counter")
        for name, t in sorted(self.trackers.items()):
            lines.append(
                f'crowdllama_slo_requests_total{{objective="{name}",'
                f'verdict="good"}} {t.good_total}')
            lines.append(
                f'crowdllama_slo_requests_total{{objective="{name}",'
                f'verdict="bad"}} {t.bad_total}')
        lines.append("# TYPE crowdllama_slo_burn_rate gauge")
        for name, t in sorted(self.trackers.items()):
            for label, rate in t.burn_rates().items():
                lines.append(
                    f'crowdllama_slo_burn_rate{{objective="{name}",'
                    f'window="{label}"}} {rate:.4f}')
        lines.append("# TYPE crowdllama_slo_fast_burn gauge")
        lines.append(
            f"crowdllama_slo_fast_burn {1 if self.fast_burn() else 0}")
        lines.append("# TYPE crowdllama_slo_fast_burn_episodes_total counter")
        lines.append(
            f"crowdllama_slo_fast_burn_episodes_total "
            f"{self.fast_burn_episodes_total}")
        return lines
