"""Bounded per-node ring buffer of request span trees.

One :class:`TraceBuffer` per node; the gateway records route/dial/serde/
aead/io_wait/stream_flush spans, the worker records worker_queue/prefill/
decode_step/stream_flush.  Both sides key spans by the ``trace_id`` carried
on the ``llama.v1.BaseMessage`` envelope, so joining the two nodes'
``/debug/trace`` outputs on that id reconstructs the full request path.

Thread-safe: the gateway records from the event loop while a JaxEngine's
scheduler thread may record concurrently on a worker.
"""

from __future__ import annotations

import os
import threading
import time
from collections import OrderedDict
from dataclasses import dataclass, field
from typing import Any


def new_trace_id() -> str:
    """64-bit random hex id, minted at the gateway per inference request."""
    return os.urandom(8).hex()


@dataclass
class Span:
    name: str
    dur_ns: int
    parent: str = ""
    start_ns: int = 0  # offset from trace start (monotonic), best-effort
    meta: dict = field(default_factory=dict)

    def to_json(self) -> dict[str, Any]:
        d: dict[str, Any] = {
            "name": self.name,
            "dur_us": round(self.dur_ns / 1e3, 1),
            "start_us": round(self.start_ns / 1e3, 1),
        }
        if self.parent:
            d["parent"] = self.parent
        if self.meta:
            d["meta"] = self.meta
        return d


class _TraceRecord:
    __slots__ = ("trace_id", "started_unix", "t0_ns", "total_ns", "meta",
                 "spans", "done")

    def __init__(self, trace_id: str, meta: dict) -> None:
        self.trace_id = trace_id
        self.started_unix = time.time()
        self.t0_ns = time.monotonic_ns()
        self.total_ns = 0
        self.meta = meta
        self.spans: list[Span] = []
        self.done = False

    def to_json(self) -> dict[str, Any]:
        return {
            "trace_id": self.trace_id,
            "started_at": round(self.started_unix, 3),
            "total_us": round(self.total_ns / 1e3, 1),
            "done": self.done,
            "meta": self.meta,
            "spans": [s.to_json() for s in self.spans],
        }


# Spans per trace are bounded so a pathological request (or a decode loop
# recording per-step spans by mistake) cannot grow a record without limit.
_MAX_SPANS_PER_TRACE = 64


class TraceBuffer:
    """Bounded ring of the last N requests' span trees, oldest evicted.

    ``ttl`` (seconds, 0 = off) additionally age-evicts: a long-lived,
    lightly-loaded worker must not serve week-old fragments to the trace
    collector as if they described the request being debugged."""

    def __init__(self, capacity: int = 64, node: str = "",
                 ttl: float = 0.0) -> None:
        self.capacity = max(1, int(capacity))
        self.node = node
        self.ttl = max(0.0, float(ttl))
        self._lock = threading.Lock()
        self._traces: OrderedDict[str, _TraceRecord] = OrderedDict()

    def _evict_expired(self) -> None:
        """Drop records older than the TTL (caller holds the lock).  The
        ring is insertion-ordered, so expiry scans stop at the first
        still-fresh record."""
        if not self.ttl:
            return
        cutoff = time.time() - self.ttl
        while self._traces:
            oldest = next(iter(self._traces.values()))
            if oldest.started_unix >= cutoff:
                break
            self._traces.popitem(last=False)

    def _get_or_create(self, trace_id: str, meta: dict) -> _TraceRecord:
        self._evict_expired()
        rec = self._traces.get(trace_id)
        if rec is None:
            rec = _TraceRecord(trace_id, meta)
            self._traces[trace_id] = rec
            while len(self._traces) > self.capacity:
                self._traces.popitem(last=False)
        elif meta:
            rec.meta.update(meta)
        return rec

    def begin(self, trace_id: str, **meta) -> None:
        if not trace_id:
            return
        with self._lock:
            self._get_or_create(trace_id, meta)

    def record(self, trace_id: str, name: str, dur_ns: int | float,
               parent: str = "", start_ns: int | None = None, **meta) -> None:
        """Append one span; creates the trace record if begin() was skipped.

        ``start_ns`` is the span's absolute monotonic_ns start; when omitted
        the span is assumed to have just ended (offset = now - dur - t0).
        """
        if not trace_id:
            return
        dur = max(0, int(dur_ns))
        now = time.monotonic_ns()
        with self._lock:
            rec = self._get_or_create(trace_id, {})
            if len(rec.spans) >= _MAX_SPANS_PER_TRACE:
                return
            abs_start = now - dur if start_ns is None else int(start_ns)
            rec.spans.append(Span(name=name, dur_ns=dur, parent=parent,
                                  start_ns=max(0, abs_start - rec.t0_ns),
                                  meta=dict(meta) if meta else {}))

    def finish(self, trace_id: str, total_ns: int | float = 0, **meta) -> None:
        if not trace_id:
            return
        with self._lock:
            rec = self._traces.get(trace_id)
            if rec is None:
                return
            rec.done = True
            rec.total_ns = int(total_ns) or (time.monotonic_ns() - rec.t0_ns)
            if meta:
                rec.meta.update(meta)

    def get(self, trace_id: str) -> dict[str, Any] | None:
        with self._lock:
            self._evict_expired()
            rec = self._traces.get(trace_id)
            return rec.to_json() if rec is not None else None

    def snapshot(self, trace_id: str = "",
                 limit: int = 0) -> dict[str, Any]:
        """JSON-ready dump, oldest first, for ``GET /debug/trace``.

        ``trace_id`` filters to one trace; ``limit`` keeps only the N
        NEWEST records (the ones a debugging operator is after)."""
        with self._lock:
            self._evict_expired()
            if trace_id:
                rec = self._traces.get(trace_id)
                traces = [rec.to_json()] if rec is not None else []
            else:
                traces = [rec.to_json() for rec in self._traces.values()]
        if limit > 0:
            traces = traces[-limit:]
        return {"node": self.node, "capacity": self.capacity,
                "traces": traces}
