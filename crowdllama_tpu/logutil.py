"""Logging factory.

Counterpart of /root/reference/pkg/logutil/logutil.go:10-33: one place that
builds the application logger — colored console output, an ``app`` field on
every record, INFO level unless verbose.
"""

from __future__ import annotations

import logging
import sys

_COLORS = {
    logging.DEBUG: "\x1b[36m",
    logging.INFO: "\x1b[32m",
    logging.WARNING: "\x1b[33m",
    logging.ERROR: "\x1b[31m",
    logging.CRITICAL: "\x1b[35m",
}
_RESET = "\x1b[0m"


class _ConsoleFormatter(logging.Formatter):
    def __init__(self, app: str, color: bool):
        super().__init__()
        self.app = app
        self.color = color

    def format(self, record: logging.LogRecord) -> str:
        level = record.levelname
        if self.color:
            level = f"{_COLORS.get(record.levelno, '')}{level}{_RESET}"
        base = (
            f"{self.formatTime(record, '%H:%M:%S')} {level} "
            f"[{self.app}] {record.name}: {record.getMessage()}"
        )
        if record.exc_info:
            base += "\n" + self.formatException(record.exc_info)
        return base


def new_app_logger(app: str, verbose: bool = False) -> logging.Logger:
    """Build (or rebuild) the root logger for one application component."""
    logger = logging.getLogger(app)
    logger.setLevel(logging.DEBUG if verbose else logging.INFO)
    logger.propagate = False
    if not logger.handlers:
        handler = logging.StreamHandler(sys.stderr)
        handler.setFormatter(_ConsoleFormatter(app, color=sys.stderr.isatty()))
        logger.addHandler(handler)
    return logger
