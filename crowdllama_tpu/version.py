"""Version information.

The reference injects Version/CommitHash/BuildDate via Go ldflags
(/root/reference/pkg/version/version.go:9-18); here the analogous knobs are env
vars set by packaging, with sane dev defaults.  The version string doubles as
the ``version`` field of advertised peer metadata, mirroring
/root/reference/pkg/peer/peer.go:335.
"""

from __future__ import annotations

import os

VERSION = os.environ.get("CROWDLLAMA_TPU_VERSION", "0.1.0-dev")
COMMIT_HASH = os.environ.get("CROWDLLAMA_TPU_COMMIT", "unknown")
BUILD_DATE = os.environ.get("CROWDLLAMA_TPU_BUILD_DATE", "unknown")


def version_string() -> str:
    """Human-readable version banner (cf. reference version.go:39-47)."""
    return f"crowdllama-tpu {VERSION} (commit {COMMIT_HASH}, built {BUILD_DATE})"
