"""Ollama-compatible HTTP gateway."""

from crowdllama_tpu.gateway.gateway import Gateway  # noqa: F401
