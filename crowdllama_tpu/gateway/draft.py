"""Gateway-side speculative drafting (docs/SPECULATIVE.md).

PR 4 put the draft model next to the verifier; this module puts it next
to the CLIENT.  The gateway runs the distilled draft checkpoint locally,
streams draft-token chunks ahead of the worker over the authenticated
inference stream (``DraftChunk`` frames), and the worker batch-verifies
each chunk with the hosted spec program — so the swarm RTT is paid once
per pipeline window instead of once per token.

Three pieces, all single-stream-scoped:

- :class:`GatewayDrafter` — the loaded draft model (params + jitted
  prefill/step), shared across streams; one per gateway process.
- :class:`DraftSession` — per-stream drafting state: the committed
  sequence, the outstanding speculative rollout, and a contiguous KV
  cache kept in lockstep (rejected-tail KV is masked by position and
  overwritten, the same contract the worker's draft cache uses).
- :class:`SpecPipelinePump` — per-stream flow control: keeps
  ``min(controller depth, worker depth_hint)`` chunks in flight, feeds
  the RTT/step/acceptance estimators from VerifyResult arrivals, and
  degrades to pure-ack credits (worker-draft pacing) when there is no
  drafter or the acceptance controller pauses.

Correctness never depends on any of this: the worker's verify is exact
(the client stream is byte-identical to plain greedy decode), drafts
only decide how many tokens each round emits.
"""

from __future__ import annotations

import logging
import time

from crowdllama_tpu.core import wire
from crowdllama_tpu.core.messages import draft_chunk_msg
from crowdllama_tpu.core.spec_pipeline import PipelineDepthController

log = logging.getLogger("crowdllama.gateway.draft")


def _bucket(n: int, lo: int = 16) -> int:
    b = lo
    while b < n:
        b *= 2
    return b


class GatewayDrafter:
    """The gateway's local draft model: one native checkpoint, jitted
    prefill + greedy decode step, shared by every stream's session."""

    def __init__(self, params, cfg, max_seq: int = 2048):
        import jax

        self.params = params
        self.cfg = cfg
        self.max_seq = int(max_seq)
        self._prefill = jax.jit(self._prefill_impl)
        self._step = jax.jit(self._step_impl, donate_argnums=(2, 3))

    @classmethod
    def from_checkpoint(cls, path: str, max_seq: int = 2048,
                        seed: int = 0) -> "GatewayDrafter":
        """Load a draft checkpoint dir (native layout from train/distill,
        or HF safetensors) exactly the way the worker engine would."""
        from crowdllama_tpu.engine.weights import (
            config_from_hf_dir,
            is_native_checkpoint,
            load_or_init_params,
            native_config_from_dir,
        )

        if is_native_checkpoint(path):
            cfg = native_config_from_dir(path)
        else:
            cfg = config_from_hf_dir(path)
        params = load_or_init_params(cfg, path, seed=seed)
        return cls(params, cfg, max_seq=max_seq)

    def _prefill_impl(self, tokens, plen):
        """tokens [1, T] zero-padded; returns (next token predicted after
        position plen-1, KV cache [L, 1, Hkv, max_seq, Dh])."""
        import jax
        import jax.numpy as jnp

        from crowdllama_tpu.models import transformer as T

        t = tokens.shape[1]
        positions = jnp.minimum(jnp.arange(t)[None, :], plen - 1)
        kv_valid = (jnp.arange(t) < plen)[None, :]
        logits, ks, vs = T.prefill(self.params, self.cfg, tokens,
                                   positions, kv_valid=kv_valid)
        nxt = jnp.argmax(logits[0, plen - 1], axis=-1).astype(jnp.int32)
        num_l, _, num_h, _, dh = ks.shape
        k = jnp.zeros((num_l, 1, num_h, self.max_seq, dh), ks.dtype)
        v = jnp.zeros_like(k)
        k = jax.lax.dynamic_update_slice(k, ks, (0, 0, 0, 0, 0))
        v = jax.lax.dynamic_update_slice(v, vs, (0, 0, 0, 0, 0))
        return nxt, k, v

    def _step_impl(self, tok, pos, k, v):
        """Ingest ``tok`` at position ``pos``; returns the greedy next
        token and the extended cache."""
        import jax.numpy as jnp

        from crowdllama_tpu.models import transformer as T

        logits, k, v = T.decode_step(
            self.params, self.cfg, tok[None], pos[None], k, v,
            (pos + 1)[None])
        return jnp.argmax(logits[0], axis=-1).astype(jnp.int32), k, v

    def session(self, prompt_ids, first_token: int) -> "DraftSession":
        return DraftSession(self, prompt_ids, first_token)


class DraftSession:
    """Per-stream draft state.

    ``seq`` is the committed sequence (prompt + every token the worker
    has verified), ``spec`` the outstanding greedy rollout beyond it, and
    ``sent`` how far into ``spec`` chunks have already been shipped.
    Chunk i+1 is positioned assuming chunk i fully accepts: the worker's
    generative emit after a full accept is the rollout's next token, so
    the pointer skips one drafted token per shipped chunk.  A partial
    accept invalidates the rollout (``observe`` drops it and rewinds the
    KV watermark); the in-flight tail comes back as stale nacks and the
    pump re-drafts from the corrected prefix.
    """

    def __init__(self, drafter: GatewayDrafter, prompt_ids,
                 first_token: int):
        self.d = drafter
        self.prompt_len = len(prompt_ids)
        self.seq = [int(t) for t in prompt_ids] + [int(first_token)]
        self.spec: list[int] = []
        self.sent = 0
        self.kv = None  # (k, v) device arrays, allocated on first draft
        self.ingested = 0  # tokens whose KV is in the cache
        self._next = None  # predicted token after position ingested-1

    def observe(self, tokens) -> None:
        """Fold one verify round's emitted tokens into the state."""
        import numpy as _np  # noqa: F401  (kept jax-free on this path)

        for t in tokens:
            t = int(t)
            if self.spec and self.spec[0] == t:
                self.spec.pop(0)
                self.sent = max(0, self.sent - 1)
            else:
                # Rollout diverged from the model: everything speculative
                # is garbage, including its KV tail (masked by position,
                # overwritten on the next catch-up).
                self.spec = []
                self.sent = 0
                self.ingested = min(self.ingested, len(self.seq))
                self._next = None
            self.seq.append(t)

    def _extend(self, n: int) -> None:
        import jax.numpy as jnp
        import numpy as np

        toks = self.seq + self.spec
        room = self.d.max_seq - len(toks) - 1
        n = min(n, room)
        if n <= 0:
            return
        if self.kv is None:
            b = _bucket(len(toks))
            padded = np.zeros((1, b), np.int32)
            padded[0, :len(toks)] = toks
            self._next, k, v = self.d._prefill(jnp.asarray(padded),
                                               jnp.int32(len(toks)))
            self.kv = (k, v)
            self.ingested = len(toks)
        while self.ingested < len(toks):
            self._next, k, v = self.d._step(
                jnp.int32(toks[self.ingested]), jnp.int32(self.ingested),
                *self.kv)
            self.kv = (k, v)
            self.ingested += 1
        for _ in range(n):
            t = int(self._next)
            self.spec.append(t)
            self._next, k, v = self.d._step(
                jnp.int32(t), jnp.int32(self.ingested), *self.kv)
            self.kv = (k, v)
            self.ingested += 1

    def next_chunk(self, k: int) -> tuple[int, list[int]]:
        """(position, tokens) for the next chunk of up to ``k`` drafts.
        Position is the worker's expected generated-count at consumption
        (pipelined: assumes every in-flight chunk fully accepts).  Out of
        context room → empty tokens (the chunk degrades to an ack)."""
        want = self.sent + int(k)
        if len(self.spec) < want + 1:
            # +1: the predicted generative token the pointer skips.
            self._extend(want + 1 - len(self.spec))
        toks = list(self.spec[self.sent:want])
        position = (len(self.seq) - self.prompt_len) + self.sent
        if toks:
            self.sent += len(toks) + 1
        return position, toks


class SpecPipelinePump:
    """Flow control for one remote-draft stream.

    The gateway's recv loop calls :meth:`on_verify` for every
    VerifyResult frame; the pump folds the observation into the depth
    controller and tops the outstanding window back up.  ``send`` is the
    async whole-frame writer for the worker stream.  With no drafter
    (worker-draft mode, or the checkpoint failed to load) every chunk is
    a pure ack credit — worker-paced speculation over the same wire.
    """

    def __init__(self, model: str, send, drafter: GatewayDrafter | None,
                 controller: PipelineDepthController | None = None):
        self.model = model
        self._send = send
        self.drafter = drafter
        self.session: DraftSession | None = None
        self.ctrl = controller or PipelineDepthController()
        self._inflight: dict[int, tuple[float, int]] = {}
        self._next_id = 1
        self._last_verify_at = 0.0
        self.worker_k = 0
        self.worker_depth = 1
        # Telemetry (gateway /metrics: crowdllama_draft_chunk_* families).
        self.chunks_sent = 0
        self.acks_sent = 0
        self.nacks = 0
        self.tokens_accepted = 0
        self.tokens_offered = 0

    async def fill(self) -> None:
        depth = min(self.ctrl.depth(), max(1, self.worker_depth))
        if self.session is None:
            # No drafter: a pure-ack credit predicts nothing, so there is
            # nothing useful to keep in flight — stay at the stop-and-wait
            # baseline (one verify round per RTT, exactly the cost the
            # gateway-draft pipeline exists to hide).
            depth = 1
        while len(self._inflight) < depth:
            k = 0
            if self.session is not None:
                k = self.ctrl.draft_k(self.worker_k)
            pos, toks = (self.session.next_chunk(k)
                         if (self.session is not None and k > 0)
                         else (0, []))
            cid = self._next_id
            self._next_id += 1
            self._inflight[cid] = (time.monotonic(), len(toks))
            if toks:
                self.chunks_sent += 1
                self.tokens_offered += len(toks)
            else:
                self.acks_sent += 1
            await self._send(wire.encode_frame(draft_chunk_msg(
                model=self.model, chunk_id=cid, position=pos,
                tokens=toks)))

    async def on_verify(self, vr) -> None:
        now = time.monotonic()
        self.worker_k = max(0, int(vr.draft_k))
        self.worker_depth = max(1, int(vr.depth_hint))
        if int(vr.chunk_id) == 0:
            # Handshake (never a real credit): prompt ids + first token
            # seed the drafter's session before the first text frame.
            if self.drafter is not None and vr.prompt_ids and vr.tokens:
                try:
                    self.session = self.drafter.session(
                        list(vr.prompt_ids), int(vr.tokens[0]))
                except Exception as e:
                    log.warning("draft session init failed (%s); "
                                "degrading to ack pacing", e)
                    self.session = None
            await self.fill()
            return
        meta = self._inflight.pop(int(vr.chunk_id), None)
        if self._last_verify_at and self._inflight:
            # Pipe still busy: verify arrivals are spaced one worker
            # round apart — the step-time estimator's natural sample.
            self.ctrl.observe_step(now - self._last_verify_at)
        self._last_verify_at = now
        if meta is not None:
            sent_at, offered = meta
            elapsed = now - sent_at
            if self.ctrl.step_ewma > 0.0:
                # Queued rounds ahead of this chunk are step time, not
                # wire time — subtract them out of the RTT sample.
                q = len(self._inflight) * self.ctrl.step_ewma
                self.ctrl.observe_rtt(max(0.0, elapsed - q))
            else:
                # Cold start (stop-and-wait): elapsed is rtt + one step,
                # unsplittable yet — halve it so neither estimate stays
                # zero and the window can start growing; later busy-pipe
                # samples correct both.
                self.ctrl.observe_step(elapsed / 2.0)
                self.ctrl.observe_rtt(elapsed / 2.0)
            if offered:
                acc = max(0, int(vr.accepted))
                self.ctrl.observe_accept(acc, offered)
                self.tokens_accepted += min(acc, offered)
                if not vr.tokens:
                    self.nacks += 1  # stale chunk flushed unverified
        if self.session is not None and vr.tokens:
            self.session.observe(list(vr.tokens))
        await self.fill()
