"""Consumer-facing HTTP gateway (Ollama-compatible API).

Counterpart of /root/reference/pkg/gateway/gateway.go: ``POST /api/chat``
accepts Ollama-style JSON {model, messages[], stream, options}
(gateway.go:31-41,168-231), routes to the best worker via the peer manager
(:191,346-348), forwards the request over an inference stream, and converts
the protobuf reply back to Ollama-shaped JSON (:209-230).  ``GET /api/health``
dumps the per-worker health map (:426-461).  Request logging middleware with
real durations (:107-135).

Supersets over the reference: ``stream: true`` actually streams — NDJSON
chunks exactly like Ollama's own API — and worker-side failures retry once on
the next-best worker (the reference surfaces them directly, gateway.go:210-217).
The stock ``ollama`` Python client works against this server
(examples/chat.py, cf. reference examples/chat/chat.py).
"""

from __future__ import annotations

import asyncio
import json
import logging
import os
import random
import time
from collections import OrderedDict

from aiohttp import web

from crowdllama_tpu.core import wire
from crowdllama_tpu.core.messages import (
    create_embed_request,
    create_generate_request,
    extract_embed_response,
    extract_generate_response,
)
from crowdllama_tpu.core.protocol import INFERENCE_PROTOCOL
from crowdllama_tpu.obs import GATEWAY_ROOT_SPAN, NodeObs, new_trace_id
from crowdllama_tpu.obs.http import host_stat_lines, native_metric_lines
from crowdllama_tpu.obs.metrics import (
    ENGINE_TELEMETRY,
    LabelGuard,
    device_memory_lines,
    engine_gauge_lines,
)
from crowdllama_tpu.peer.peer import Peer

log = logging.getLogger("crowdllama.gateway")

# Gateway span phases recorded per request (docs/OBSERVABILITY.md): the
# always-present quartet + dial/stream_flush when the request paid them.
_GW_PHASES = ("route", "serde", "aead", "io_wait")
_GW_OPT_PHASES = ("dial", "stream_flush")


def _now_rfc3339() -> str:
    return time.strftime("%Y-%m-%dT%H:%M:%S", time.gmtime()) + "Z"


class _StreamStarted(Exception):
    """The CLIENT side of a streamed response failed (disconnect, write
    error) or the terminal frame already went out: not retryable, not
    failover-able — the response object is final as-is.

    Worker-side mid-stream failures deliberately do NOT raise this any
    more: they propagate as ordinary exceptions so _route can fail the
    stream over to the next-best worker and resume it (docs/ROBUSTNESS.md).
    """

    def __init__(self, response: "web.StreamResponse", cause: Exception):
        super().__init__(str(cause))
        self.response = response
        self.cause = cause


class _BudgetExhausted(Exception):
    """The request's wall-clock budget expired (pre- or mid-stream)."""


class _WorkerDraining(Exception):
    """The worker announced it is draining: either it rejected the request
    up front (typed ``draining`` terminal frame) or it handed off an
    in-flight stream with a MigrateFrame.  _route treats this as a
    MIGRATION, not a failure: the drained worker is quarantined from the
    routing snapshot but attached to the retry as a KV donor with
    ``migrate=True``, so the successor imports the prompt's pages instead
    of re-running prefill (docs/ROBUSTNESS.md)."""

    def __init__(self, worker_id: str, migrated: bool = False,
                 delivered_tokens: int = 0):
        super().__init__(
            f"worker {worker_id[:8]} draining"
            + (" (mid-stream handoff)" if migrated else ""))
        self.worker_id = worker_id
        self.migrated = migrated  # True: MigrateFrame, stream was in flight
        self.delivered_tokens = delivered_tokens


class _StreamStalled(Exception):
    """The worker stopped making token progress past the stall budget
    while holding the transport OPEN: the gray failure.  There is no EOF
    and no error frame to react to — only the per-stream progress
    watchdog (``--stream-stall-ms``, docs/ROBUSTNESS.md) notices.
    _route tears the stream down, quarantines the worker as ``wedged``
    (it may still answer health probes) and fails the stream over."""

    def __init__(self, worker_id: str, phase: str):
        super().__init__(
            f"worker {worker_id[:8]} stalled (no {phase} progress)")
        self.worker_id = worker_id
        self.phase = phase  # "ttft" | "decode"


class _StreamCtx:
    """Client-side state of ONE streamed response, surviving failover.

    Created per routed request; ``out``/``sent_text`` carry the prepared
    response and every char already delivered across worker attempts, and
    the OpenAI envelope state (rid/created/chunk ordinal) stays stable so
    a failover does not re-send the role delta or change the stream id."""

    __slots__ = ("out", "sent_text", "rid", "created", "nth", "winner")

    def __init__(self, shape: str):
        self.out: web.StreamResponse | None = None
        self.sent_text = ""
        self.rid = ("chatcmpl-" if shape == "openai-chat" else "cmpl-") \
            + os.urandom(12).hex()
        self.created = int(time.time())
        self.nth = 0
        # Hedged dispatch: the worker that actually served the stream
        # (may differ from the one _route picked when the hedge won).
        self.winner = ""


class Gateway:
    def __init__(self, peer: Peer, port: int = 9001, host: str = "0.0.0.0",
                 trace_buffer: int = 64, request_timeout: float = 600.0,
                 admission_max_inflight: int = 0,
                 retry_after_s: float = 1.0, kv_ship: bool = False,
                 gossip=None, tenant_quotas=None, flight_recorder: int = 32,
                 trace_ttl: float = 0.0, metrics_exemplars: bool = False,
                 slo_ttft_ms: float = 0.0, slo_decode_ms: float = 0.0,
                 stream_stall_ms: float = 0.0, hedge_ttft_ms: float = 0.0,
                 profile_dir: str = "", spec_pipeline: str = "off",
                 spec_draft_path: str = ""):
        self.peer = peer
        self.port = port
        self.host = host
        # Replicated gateway plane (docs/ROBUSTNESS.md): the swarm/gossip.py
        # GossipNode sharing affinity pins + quarantines with the other
        # replicas (None = single-gateway, everything stays process-local),
        # and the per-tenant token buckets replacing the global shed.
        self.gossip = gossip
        self.tenant_quotas = tenant_quotas
        # KV shipping (docs/KV_TRANSFER.md): on an affinity MISS, hint the
        # remembered worker as a page donor so the chosen worker fetches
        # the shared prefix instead of recomputing it.
        self.kv_ship = bool(kv_ship)
        # Robustness plane (docs/ROBUSTNESS.md): total wall-clock budget
        # per request, charged across retries and failovers (a client may
        # lower it per request via X-Request-Timeout); gateway-side
        # admission cap (0 = off); Retry-After hint on shed 503s.
        self.request_timeout = max(0.1, float(request_timeout))
        self.admission_max_inflight = max(0, int(admission_max_inflight))
        self.retry_after_s = max(0.0, float(retry_after_s))
        self._inflight = 0  # routed inference requests currently in flight
        self._runner: web.AppRunner | None = None
        self.app = web.Application(middlewares=[self._log_middleware])
        self.app.router.add_post("/api/chat", self.handle_chat)
        self.app.router.add_post("/api/generate", self.handle_generate)
        self.app.router.add_get("/api/health", self.handle_health)
        self.app.router.add_get("/api/tags", self.handle_tags)
        self.app.router.add_get("/api/version", self.handle_version)
        self.app.router.add_post("/api/show", self.handle_show)
        self.app.router.add_get("/api/ps", self.handle_ps)
        self.app.router.add_post("/api/embed", self.handle_embed)
        self.app.router.add_post("/api/embeddings", self.handle_embeddings)
        self.app.router.add_post("/api/pull", self.handle_pull)
        # OpenAI-compatible surface (Ollama serves the same aliases; stock
        # openai clients pointed at the gateway work unchanged).
        self.app.router.add_post("/v1/chat/completions",
                                 self.handle_openai_chat)
        self.app.router.add_post("/v1/completions",
                                 self.handle_openai_completions)
        self.app.router.add_get("/v1/models", self.handle_openai_models)
        self.app.router.add_post("/v1/embeddings",
                                 self.handle_openai_embeddings)
        self.app.router.add_get("/metrics", self.handle_metrics)
        self.app.router.add_get("/debug/trace", self.handle_trace)
        # Cross-node trace assembly + flight recorder (PR 8): the stitched
        # endpoint fans TraceFetch out over the p2p plane per hit, so it is
        # a debugging surface, not a hot path.
        self.app.router.add_get("/debug/trace/{trace_id}",
                                self.handle_trace_stitched)
        self.app.router.add_get("/debug/flightrecorder",
                                self.handle_flightrecorder)
        # Swarm observatory (PR 13, docs/OBSERVABILITY.md): cluster-wide
        # metric fan-in over the p2p plane, and an on-demand jax.profiler
        # trace window.  Both are operator surfaces hit per request, never
        # on the inference hot path.
        self.app.router.add_get("/metrics/cluster",
                                self.handle_metrics_cluster)
        self.app.router.add_get("/debug/profile", self.handle_profile)
        for route in ("/api/delete", "/api/create", "/api/copy", "/api/push"):
            self.app.router.add_route("*", route, self.handle_unsupported)
        # Prometheus-style counters fed by the logging middleware
        # ((path, status) -> count / summed seconds).  The reference has no
        # metrics surface at all (SURVEY §5: "No Prometheus/metrics
        # endpoint") — this is part of the TPU-native superset.
        self._req_count: dict[tuple[str, int], int] = {}
        self._req_seconds: dict[tuple[str, int], float] = {}
        # Streamed-inference time-to-first-frame histogram (Prometheus
        # buckets, seconds): the gateway-side TTFT the operator actually
        # controls — from admission to the worker's first token frame.
        self._ttfb_le = (0.025, 0.05, 0.1, 0.25, 0.5, 1.0, 2.5, 5.0, 10.0)
        self._ttfb_buckets = [0] * (len(self._ttfb_le) + 1)
        self._ttfb_sum = 0.0
        self._ttfb_count = 0
        # Label hygiene: only registered routes become label values —
        # scanner probes of arbitrary paths must not grow the counter maps
        # without bound or inject quotes into the exposition format.  The
        # guard itself lives in obs/ (LabelGuard) so worker-side metrics
        # apply the same policy to their labels.
        self._known_paths = {r.resource.canonical
                             for r in self.app.router.routes()
                             if r.resource is not None}
        self._path_guard = LabelGuard(allowed=self._known_paths)
        # Tracing + histogram plane (obs/): trace ids minted per routed
        # request, spans recorded into the ring served at /debug/trace,
        # histograms rendered into /metrics alongside the PR 1 counters.
        self.obs = NodeObs(trace_capacity=trace_buffer, node="gateway",
                           trace_ttl=trace_ttl, exemplars=metrics_exemplars)
        # Swarm-stitched traces + flight recorder (PR 8): the collector
        # assembles this gateway's fragment with every remote node's via
        # TraceFetch fan-out; the recorder keeps complete stitched traces
        # for interesting requests (p99 tail, failover, migrate, shed,
        # kv-ship fallback) in its own ring so they outlive the general one.
        from crowdllama_tpu.obs.collector import FlightRecorder, TraceCollector

        self.collector = TraceCollector(peer, self.obs)
        self.flight = FlightRecorder(capacity=flight_recorder)
        # Rolling-p99 capture needs a floor of observations before the
        # quantile means anything; below it only event triggers capture.
        self._flight_min_count = 30
        # A 5xx storm (mass shedding) must not fan a stitch out per failed
        # request: captures beyond this many in flight are dropped — the
        # ring only keeps the newest N complete traces anyway.
        self._flight_inflight = 0
        self._flight_max_inflight = 4
        # Autopilot backoff capture (docs/AUTOTUNE.md): the tuner's
        # process-wide backoff log is edge-checked per finished request —
        # the first request retired after a hard back-off carries the
        # offending dial move into the flight-recorder ring.
        from crowdllama_tpu.engine.autotune import BACKOFF_LOG

        self._autotune_backoffs_seen = BACKOFF_LOG.snapshot()[0]
        # Swarm observatory (PR 13): the /metrics/cluster scraper, the SLO
        # burn-rate engine (objectives in ms; 0 = disabled), and the
        # /debug/profile artifact dir ("" = endpoint answers 501).
        from crowdllama_tpu.obs.cluster import ClusterScraper
        from crowdllama_tpu.obs.slo import SloEngine

        self.cluster = ClusterScraper(peer)
        self.slo = SloEngine(ttft_ms=float(slo_ttft_ms),
                             decode_ms=float(slo_decode_ms))
        self.profile_dir = str(profile_dir or "")
        self._profiling = False  # /debug/profile single-flight latch
        # Inference-stream pool: a request to a worker reuses an idle
        # encrypted stream instead of paying TCP connect + signed-hello
        # handshake (Ed25519 sign/verify + X25519) per request — the
        # per-request analog of the reference's O(1) routing
        # (manager.go:338-387; libp2p reuses connections the same way).
        # Workers loop on the stream (peer._handle_inference_stream) with
        # an idle window outlasting the pool's, so one stream serves many
        # sequential requests; stale entries (worker restarted) are
        # detected by the first failed roundtrip and retried fresh.
        from crowdllama_tpu.net.host import StreamPool

        # max_per_key matches typical per-worker request concurrency (the
        # scaling bench drives 8 clients): with only 4 slots, a 1-worker
        # swarm under 8-way concurrency redials on half its requests and
        # the "small swarm" points pay handshakes the 16-worker points
        # don't — skewing any cross-size CPU comparison.
        self._stream_pool = StreamPool(max_per_key=8)
        # Per-phase CPU attribution for the request hot path (monotonic
        # perf_counter_ns sums; exposed in /metrics and hotpath_snapshot):
        #   route_ns   — worker selection (affinity probe + snapshot scan)
        #   serde_ns   — protobuf encode/decode
        #   io_wait_ns — awaiting socket readiness/frames (includes the
        #                secure layer's inline seal/open, which is ALSO
        #                broken out process-wide as aead_us — subtract to
        #                isolate pure socket wait)
        # requests counts routed inference/embed requests (not every HTTP
        # hit), so per-request figures divide cleanly.
        self._perf = {"route_ns": 0, "serde_ns": 0, "io_wait_ns": 0,
                      "requests": 0}
        # Robustness counters (exposed in /metrics): mid-stream failovers,
        # replayed-and-trimmed chunks during them, shed requests (gateway
        # admission cap + worker "overloaded" rejections), and wall-clock
        # budget exhaustions.
        self._robust = {"failovers": 0, "replayed_chunks": 0, "shed": 0,
                        "budget_exhausted": 0,
                        # Gray-failure immunity (docs/ROBUSTNESS.md):
                        # streams torn down by the progress watchdog,
                        # workers quarantined as wedged for it, and the
                        # hedged-dispatch exactly-once ledger (launched ==
                        # won + cancelled, asserted by the chaos soak).
                        "stalled_streams": 0, "wedge_quarantines": 0,
                        "hedge_launched": 0, "hedge_won": 0,
                        "hedge_cancelled": 0}
        # Per-stream progress watchdog + hedged first-token dispatch
        # (docs/ROBUSTNESS.md): both default OFF; the live SLO objectives
        # raise the stall budget, the live TTFT p95 raises the hedge
        # threshold, so neither knob can fire tighter than the swarm's
        # actual promised/observed latency.
        self.stream_stall_ms = max(0.0, float(stream_stall_ms))
        self.hedge_ttft_ms = max(0.0, float(hedge_ttft_ms))
        # Prefix-affinity routing: multi-turn chats replay their history
        # verbatim, so turn N shares its leading tokens with turn 1 — the
        # engine's automatic prefix cache only pays if the continuation
        # lands on the SAME worker.  Conversation fingerprint (model +
        # first message head) -> (worker_id, ts); honored while the
        # worker is healthy and not near-saturated, otherwise scoring
        # wins (affinity is a tiebreak on top of manager.go:338-387's
        # throughput/(1+load), never a replacement for health).
        # Bounded LRU (same policy PeerManager.recently_removed got):
        # get/put move the key to the MRU end, inserts at capacity evict
        # the LRU entry — O(1), no sort-half stalls under churn.
        self._affinity: OrderedDict[str, tuple[str, float]] = OrderedDict()
        self._affinity_hits = 0
        self._affinity_evicted = 0
        self._affinity_repointed = 0
        self._kv_hints = 0
        # Cross-replica affinity: continuations whose pin came from the
        # gossip map rather than this process's own LRU (the number the
        # multi_gateway bench reports as cross-replica hit-rate).
        self._gossip_affinity_hits = 0
        # Per-tenant inflight (weighted-fair admission): tenant -> count.
        self._tenant_inflight: dict[str, int] = {}
        # Gateway-drafted speculative pipeline (docs/SPECULATIVE.md):
        # "off" routes plain streams; "gateway" drafts locally from
        # spec_draft_path and streams DraftChunk frames ahead of the
        # worker; "worker" sends pure ack credits (worker-paced remote
        # speculation — the RTT-linear baseline the bench compares
        # against).  The drafter loads lazily on first use so a gateway
        # that never sees a remote-draft stream never touches jax.
        if spec_pipeline not in ("off", "gateway", "worker"):
            raise ValueError(
                f"spec_pipeline must be off|gateway|worker, "
                f"got {spec_pipeline!r}")
        self.spec_pipeline = spec_pipeline
        self.spec_draft_path = str(spec_draft_path or "")
        self._spec_drafter = None
        self._spec_drafter_tried = False
        # crowdllama_draft_chunk_* counter family (handle_metrics).
        self._spec_stats = {"chunks": 0, "acks": 0, "nacks": 0,
                            "accepted": 0, "offered": 0}
        # Warm-start cache for the depth controller: RTT and worker round
        # time are properties of the WIRE to a worker, not of one stream,
        # but the pump is per-stream — without this every short chat
        # spends its first RTTs re-learning the window from stop-and-wait.
        self._spec_wire: dict[str, tuple[float, float]] = {}

    # ----------------------------------------------------------- lifecycle

    async def start(self) -> None:
        pm = self.peer.peer_manager
        if pm is not None:
            # Affinity hygiene rides the manager's eviction hook — CHAINED,
            # not replaced: the DHT's provider-store eviction (net/dht.py)
            # may have registered first and must keep firing.
            prev = pm.on_peer_removed

            def _on_removed(peer_id: str) -> None:
                if prev is not None:
                    prev(peer_id)
                self._affinity_drop_worker(peer_id)

            pm.on_peer_removed = _on_removed
            if self.gossip is not None:
                # Quarantine publication: OUR observation of a drain
                # (mark_draining) enters the replicated map, so the other
                # replicas stop routing to the worker within one gossip
                # round instead of a probe interval later.
                prev_drain = pm.on_draining

                def _on_draining(peer_id: str) -> None:
                    if prev_drain is not None:
                        prev_drain(peer_id)
                    self.gossip.record_quarantine(peer_id)

                pm.on_draining = _on_draining
        if self.gossip is not None:
            # Remote entries applied by anti-entropy: another replica's
            # quarantine decision quarantines the worker HERE (split-brain
            # safe — mark_draining is idempotent and versioned entries
            # can't regress).  Affinity entries need no eager action: the
            # routing path consults the gossip map on local miss.
            from crowdllama_tpu.swarm.gossip import QUARANTINE_PREFIX

            def _on_entry(entry) -> None:
                if entry.tombstone \
                        or not entry.key.startswith(QUARANTINE_PREFIX):
                    return
                pm2 = self.peer.peer_manager
                if pm2 is not None:
                    pm2.mark_draining(entry.key[len(QUARANTINE_PREFIX):])

            self.gossip.on_entry = _on_entry
        self._runner = web.AppRunner(self.app, access_log=None)
        await self._runner.setup()
        site = web.TCPSite(self._runner, self.host, self.port)
        await site.start()
        log.info("gateway listening on %s:%d", self.host, self.port)

    async def stop(self) -> None:
        if self._runner is not None:
            await self._runner.cleanup()
            self._runner = None
        # The pool stays a null sink afterwards: an in-flight request
        # finishing post-stop closes its stream instead of repooling it.
        self._stream_pool.close()

    # ------------------------------------------------------- stream pool

    def _pool_get(self, worker_id: str):
        """Pop a live pooled stream for ``worker_id`` (None on miss)."""
        return self._stream_pool.get(worker_id)

    def _pool_put(self, worker_id: str, s) -> None:
        """Return a stream whose last request completed CLEANLY (a
        mid-response abort leaves unread frames — close those instead)."""
        self._stream_pool.put(worker_id, s)

    async def _dial(self, worker_id: str, acc: dict | None = None,
                    timeout: float | None = None, trace_id: str = ""):
        """``timeout`` caps the dial + handshake at the request's remaining
        budget (never above the protocol's own handshake timeout).
        ``trace_id`` rides a relay-splice fallback's connect frame so the
        relay node records a relay_splice span the collector can stitch."""
        from crowdllama_tpu.net.host import HANDSHAKE_TIMEOUT

        t0 = time.perf_counter_ns()
        contact = await self.peer.dht.find_peer(worker_id)
        if contact is None:
            raise LookupError(f"worker {worker_id[:8]} not resolvable")
        hs = (HANDSHAKE_TIMEOUT if timeout is None
              else max(0.05, min(HANDSHAKE_TIMEOUT, timeout)))
        s = await self.peer.host.new_stream(contact, INFERENCE_PROTOCOL,
                                            timeout=hs, trace_id=trace_id)
        if acc is not None:
            acc["dial_ns"] = acc.get("dial_ns", 0) \
                + time.perf_counter_ns() - t0
        return s

    # ------------------------------------------------- budgets and shedding

    def _budget(self, request: web.Request) -> float:
        """Per-request wall-clock budget in seconds: the configured ceiling,
        lowered by a valid ``X-Request-Timeout`` header."""
        hdr = request.headers.get("X-Request-Timeout", "")
        if hdr:
            try:
                v = float(hdr)
            except ValueError:
                v = 0.0
            if v > 0:
                return min(v, self.request_timeout)
        return self.request_timeout

    def _shed_headers(self) -> dict:
        # Jittered Retry-After in [base, 2*base]: a constant value tells
        # every shed client to come back at the SAME instant, so a
        # recovering gateway eats its own retry stampede.  Integer seconds
        # (the HTTP-date alternative is the only other legal form).
        base = self.retry_after_s
        return {"Retry-After": str(max(1, round(random.uniform(base,
                                                               2 * base))))}

    def _shed_response(self, shape: str, model: str,
                       message: str) -> web.Response:
        """503 + Retry-After: the uniform load-shedding response."""
        self._robust["shed"] += 1
        # Flight-recorder shed capture (PR 13): shedding happens before a
        # trace id is minted, so mint one here — the recorded trace is a
        # single gateway-side "shed" span, enough to see WHEN and WHY the
        # gateway refused (the message carries cap/quota context).
        tid = new_trace_id()
        self.obs.trace.record(tid, "shed", 0, parent=GATEWAY_ROOT_SPAN,
                              detail=message[:120], model=model)
        self.obs.trace.finish(tid, 1, status=503)
        self._flight_capture(tid, ["shed"])
        headers = self._shed_headers()
        if shape.startswith("openai"):
            return self._openai_error(message, 503, "server_error",
                                      headers=headers)
        return web.json_response({"error": message, "model": model},
                                 status=503, headers=headers)

    # ------------------------------------------------- hot-path attribution
    #
    # Each helper charges the SAME timing to the process-wide _perf counters
    # (PR 1 exposition, hotpath_snapshot) and — when the caller passes a
    # per-request accumulator ``acc`` — to that request's trace spans, so
    # bench phase numbers and /debug/trace spans are one instrumentation.

    def _encode_frame(self, msg, acc: dict | None = None) -> bytes:
        """Serialize a request ONCE per _route attempt; the same bytes are
        reused if the pooled stream turns out stale and the request redials
        (previously the protobuf was re-encoded per send)."""
        t0 = time.perf_counter_ns()
        frame = wire.encode_frame(msg)
        dt = time.perf_counter_ns() - t0
        self._perf["serde_ns"] += dt
        if acc is not None:
            acc["serde_ns"] = acc.get("serde_ns", 0) + dt
        return frame

    async def _send_frame(self, s, frame: bytes,
                          acc: dict | None = None) -> None:
        # write() is synchronous buffering (+ inline seal, counted by the
        # secure layer's aead counters); only the drain is socket wait.
        s.writer.write(frame)
        t0 = time.perf_counter_ns()
        await s.writer.drain()
        dt = time.perf_counter_ns() - t0
        self._perf["io_wait_ns"] += dt
        if acc is not None:
            acc["io_wait_ns"] = acc.get("io_wait_ns", 0) + dt

    async def _recv_pb(self, s, timeout: float = 600,
                       acc: dict | None = None):
        t0 = time.perf_counter_ns()
        payload = await wire.read_frame_payload(s.reader, timeout=timeout)
        t1 = time.perf_counter_ns()
        # Fast path: the native strict decoder handles the GenerateResponse
        # arm (the per-chunk hot case); anything else falls back to the
        # real parser inside decode_payload_fast with identical semantics.
        reply = wire.decode_payload_fast(payload)
        t2 = time.perf_counter_ns()
        self._perf["io_wait_ns"] += t1 - t0
        self._perf["serde_ns"] += t2 - t1
        if acc is not None:
            acc["io_wait_ns"] = acc.get("io_wait_ns", 0) + (t1 - t0)
            acc["serde_ns"] = acc.get("serde_ns", 0) + (t2 - t1)
        return reply

    def hotpath_snapshot(self) -> dict:
        """Point-in-time hot-path counters; benches diff two snapshots to
        attribute CPU per request phase (route/serde/aead/io_wait)."""
        from crowdllama_tpu.net import secure

        aead_ns, aead_ops = secure.aead_stats()
        pm = self.peer.peer_manager
        return {
            "requests": self._perf["requests"],
            "route_us": self._perf["route_ns"] / 1e3,
            "serde_us": self._perf["serde_ns"] / 1e3,
            "io_wait_us": self._perf["io_wait_ns"] / 1e3,
            "aead_us": aead_ns / 1e3,  # process-wide (see net/secure.py)
            "aead_ops": aead_ops,
            "pool_hits": self._stream_pool.hits,
            "pool_misses": self._stream_pool.misses,
            "route_snapshot_rebuilds": (
                pm.route_snapshot_rebuilds if pm is not None else 0),
        }

    # ---------------------------------------------------------- middleware

    @web.middleware
    async def _log_middleware(self, request: web.Request, handler):
        t0 = time.monotonic()
        status = 0
        try:
            resp = await handler(request)
            status = resp.status
            return resp
        except web.HTTPException as e:
            # aiohttp delivers router 404/405s (and handler short-circuits)
            # by raising — record their real status, not 0.
            status = e.status
            raise
        finally:
            dt = time.monotonic() - t0
            log.info("%s %s -> %.0fms", request.method, request.path,
                     dt * 1000)
            path = self._path_guard.value(request.path)
            key = (path, status)
            self._req_count[key] = self._req_count.get(key, 0) + 1
            self._req_seconds[key] = self._req_seconds.get(key, 0.0) + dt

    # ------------------------------------------------------------ handlers

    async def handle_chat(self, request: web.Request) -> web.StreamResponse:
        """POST /api/chat — Ollama chat API (gateway.go:168-231)."""
        try:
            body = await request.json()
        except json.JSONDecodeError:
            return web.json_response({"error": "invalid JSON"}, status=400)
        model = body.get("model", "")
        messages = body.get("messages", [])
        if not model or not isinstance(messages, list) or not messages:
            return web.json_response(
                {"error": "model and messages are required"}, status=400)
        stream = bool(body.get("stream", False))
        options = body.get("options", {}) or {}
        return await self._route(
            request, model, stream, options, messages=messages,
            shape="chat")

    async def handle_generate(self, request: web.Request) -> web.StreamResponse:
        """POST /api/generate — Ollama completion API (prompt in, text out)."""
        try:
            body = await request.json()
        except json.JSONDecodeError:
            return web.json_response({"error": "invalid JSON"}, status=400)
        model = body.get("model", "")
        prompt = body.get("prompt", "")
        if not model or not prompt:
            return web.json_response(
                {"error": "model and prompt are required"}, status=400)
        stream = bool(body.get("stream", False))
        options = body.get("options", {}) or {}
        return await self._route(
            request, model, stream, options, prompt=prompt,
            shape="generate")

    async def handle_health(self, request: web.Request) -> web.Response:
        """GET /api/health — per-worker health map (gateway.go:426-461)."""
        pm = self.peer.peer_manager
        workers = {}
        if pm is not None:
            for p in pm.get_workers():
                r = p.resource
                workers[p.peer_id] = {
                    "is_healthy": p.is_healthy,
                    "last_seen": time.time() - (time.monotonic() - p.last_seen),
                    "failed_attempts": p.failed_attempts,
                    "supported_models": r.supported_models,
                    "tokens_throughput": r.tokens_throughput,
                    "load": r.load,
                    "accelerator": r.accelerator,
                    "tpu_chip_count": r.tpu_chip_count,
                    "ici_topology": r.ici_topology,
                    "version": r.version,
                }
        return web.json_response({
            "status": "ok",
            "peer_id": self.peer.peer_id,
            "worker_count": len(workers),
            "workers": workers,
        })

    async def handle_tags(self, request: web.Request) -> web.Response:
        """GET /api/tags — available models (Ollama client handshake)."""
        pm = self.peer.peer_manager
        models: dict[str, dict] = {}
        if pm is not None:
            for p in pm.get_healthy_peers():
                if not p.is_worker:
                    continue
                for m in p.resource.supported_models:
                    models.setdefault(m, {"name": m, "model": m})
        return web.json_response({"models": list(models.values())})

    async def handle_version(self, request: web.Request) -> web.Response:
        """GET /api/version — Ollama client handshake."""
        from crowdllama_tpu.version import VERSION

        return web.json_response({"version": VERSION})

    async def handle_show(self, request: web.Request) -> web.Response:
        """POST /api/show — model details (registry config + swarm view)."""
        try:
            body = await request.json()
        except json.JSONDecodeError:
            return web.json_response({"error": "invalid JSON"}, status=400)
        name = body.get("model") or body.get("name") or ""
        if not name:
            return web.json_response({"error": "model is required"}, status=400)
        pm = self.peer.peer_manager
        serving = [p.peer_id for p in (pm.get_healthy_peers() if pm else [])
                   if p.is_worker and name in p.resource.supported_models]
        details: dict = {"format": "safetensors"}
        model_info: dict = {}
        try:
            from crowdllama_tpu.models.config import get_config

            cfg = get_config(name)
            details.update({
                "family": cfg.family,
                "families": [cfg.family],
                "parameter_size": f"{cfg.param_count() / 1e9:.1f}B",
            })
            model_info = {
                "general.architecture": cfg.family,
                "general.parameter_count": cfg.param_count(),
                f"{cfg.family}.context_length": cfg.max_context_length,
                f"{cfg.family}.embedding_length": cfg.hidden_size,
                f"{cfg.family}.block_count": cfg.num_layers,
                f"{cfg.family}.attention.head_count": cfg.num_heads,
                f"{cfg.family}.attention.head_count_kv": cfg.num_kv_heads,
                f"{cfg.family}.vocab_size": cfg.vocab_size,
            }
            if cfg.is_moe:
                model_info[f"{cfg.family}.expert_count"] = cfg.num_experts
                model_info[f"{cfg.family}.expert_used_count"] = (
                    cfg.num_experts_per_tok)
        except KeyError:
            if not serving:
                return web.json_response(
                    {"error": f"model {name!r} not found"}, status=404)
        return web.json_response({
            "model": name,
            "details": details,
            "model_info": model_info,
            "workers_serving": serving,
        })

    async def handle_ps(self, request: web.Request) -> web.Response:
        """GET /api/ps — models currently loaded across the swarm."""
        pm = self.peer.peer_manager
        models: dict[str, dict] = {}
        if pm is not None:
            for p in pm.get_healthy_peers():
                if not p.is_worker:
                    continue
                for m in p.resource.supported_models:
                    entry = models.setdefault(m, {
                        "name": m, "model": m, "workers": 0,
                        "tokens_throughput": 0.0,
                    })
                    entry["workers"] += 1
                    entry["tokens_throughput"] += p.resource.tokens_throughput
        return web.json_response({"models": list(models.values())})

    async def handle_embed(self, request: web.Request) -> web.Response:
        """POST /api/embed — Ollama embeddings API: {model, input: str|[str]}
        → {model, embeddings: [[...]]}.  The reference delegates this surface
        to Ollama wholesale; here it routes over the swarm like chat."""
        try:
            body = await request.json()
        except json.JSONDecodeError:
            return web.json_response({"error": "invalid JSON"}, status=400)
        model = body.get("model", "")
        inputs = body.get("input", "")
        if isinstance(inputs, str):
            inputs = [inputs]
        if not model or not isinstance(inputs, list) or not inputs \
                or not all(isinstance(t, str) for t in inputs):
            return web.json_response(
                {"error": "model and input are required"}, status=400)
        truncate = bool(body.get("truncate", True))
        resp, status = await self._route_embed(model, inputs, truncate)
        return web.json_response(resp, status=status)

    async def handle_embeddings(self, request: web.Request) -> web.Response:
        """POST /api/embeddings — legacy Ollama surface: {model, prompt}
        → {embedding: [...]}."""
        try:
            body = await request.json()
        except json.JSONDecodeError:
            return web.json_response({"error": "invalid JSON"}, status=400)
        model = body.get("model", "")
        prompt = body.get("prompt", "")
        if not model or not prompt or not isinstance(prompt, str):
            return web.json_response(
                {"error": "model and prompt (a string) are required"},
                status=400)
        resp, status = await self._route_embed(
            model, [prompt], bool(body.get("truncate", True)))
        if status == 200:
            resp = {"embedding": resp["embeddings"][0]}
        return web.json_response(resp, status=status)

    async def _route_embed(self, model: str, inputs: list[str],
                           truncate: bool = True) -> tuple[dict, int]:
        msg = create_embed_request(model, inputs, truncate=truncate)
        from crowdllama_tpu.net import secure

        tid = new_trace_id()
        msg.trace_id = tid
        msg.parent_span = GATEWAY_ROOT_SPAN
        t0 = time.monotonic()
        self._perf["requests"] += 1
        acc: dict = {}
        self.obs.trace.begin(tid, node="gateway", model=model,
                             path="/api/embed")
        aead0 = secure.aead_stats()[0]
        status = 503
        served_by = ""
        try:
            tried: set[str] = set()
            last_err = "no workers available for model"
            for _attempt in range(2):  # retry once on next-best worker
                worker = self._find_worker(model, exclude=tried,
                                           require_embeddings=True, acc=acc)
                if worker is None:
                    break
                tried.add(worker.peer_id)
                try:
                    reply = await self._roundtrip(worker.peer_id, msg,
                                                  acc=acc)
                    resp = extract_embed_response(reply)
                    if resp.error.startswith("invalid:"):
                        # Deterministic client error (e.g. truncate=false
                        # input over the context window): 400, no retry.
                        status = 400
                        served_by = worker.peer_id
                        return {"error":
                                resp.error[len("invalid:"):].strip(),
                                "model": model}, 400
                    if resp.error:
                        raise RuntimeError(resp.error)
                    status = 200
                    served_by = worker.peer_id
                    return {
                        "model": model,
                        "embeddings": [list(e.values)
                                       for e in resp.embeddings],
                        "total_duration": resp.total_duration,
                        "prompt_eval_count": resp.prompt_tokens,
                        "worker_id": resp.worker_id,
                    }, 200
                except Exception as e:
                    last_err = str(e)
                    log.warning("embed via %s failed: %s",
                                worker.peer_id[:8], e)
            return {"error": f"embeddings failed: {last_err}",
                    "model": model}, 503
        finally:
            acc["aead_ns"] = max(0, secure.aead_stats()[0] - aead0)
            self._finish_trace(tid, acc, model, t0, status, served_by)

    async def _roundtrip(self, worker_id: str, msg, timeout: float = 600,
                         acc: dict | None = None,
                         frame: bytes | None = None):
        """Request/reply over a pooled (or fresh) inference stream.

        A pooled stream can be stale (worker idled it out or restarted):
        generation/embedding requests are stateless, so the failed attempt
        retries once on a fresh dial — reusing the ALREADY-ENCODED frame
        bytes — before surfacing the error.  ``frame`` lets _route pass
        natively pre-encoded request bytes (zero pb serialization here)."""
        if frame is None:
            frame = self._encode_frame(msg, acc=acc)
        s = self._pool_get(worker_id)
        if s is not None:
            try:
                await self._send_frame(s, frame, acc=acc)
                reply = await self._recv_pb(s, timeout=timeout, acc=acc)
                self._pool_put(worker_id, s)
                return reply
            except asyncio.CancelledError:
                s.close()
                raise
            except Exception as e:
                s.close()
                log.debug("pooled stream to %s stale (%s); redialing",
                          worker_id[:8], e)
        s = await self._dial(worker_id, acc=acc, trace_id=msg.trace_id)
        try:
            await self._send_frame(s, frame, acc=acc)
            reply = await self._recv_pb(s, timeout=timeout, acc=acc)
        except BaseException:
            s.close()
            raise
        self._pool_put(worker_id, s)
        return reply

    async def handle_pull(self, request: web.Request) -> web.Response:
        """POST /api/pull — Ollama clients call this when a model is absent.

        Resolution order: a healthy worker already serves the model →
        success immediately; otherwise the gateway PROXIES the pull to a
        worker (net/model_share.py "pull" op): that worker acquires the
        checkpoint peer-to-peer from whoever shares it and hot-registers
        it.  Only when no worker can acquire it does a clear error explain
        how models appear here."""
        try:
            body = await request.json()
        except json.JSONDecodeError:
            return web.json_response({"error": "invalid JSON"}, status=400)
        name = body.get("model") or body.get("name") or ""
        if not name:
            return web.json_response({"error": "model is required"}, status=400)

        def _success(extra_lines=()):
            if not body.get("stream", True):
                # Non-streaming clients (ollama-python default) parse ONE
                # JSON body.
                return web.json_response({"status": "success"})
            lines = [{"status": "pulling manifest"}, *extra_lines,
                     {"status": "success"}]
            return web.Response(
                text="".join(json.dumps(line) + "\n" for line in lines),
                content_type="application/x-ndjson")

        # Same predicate routing uses: pull must not report success for a
        # model /api/chat would then 503 on.
        if self._find_worker(name) is not None:
            return _success()

        # Proxy to a worker that could acquire and serve it (best-scored
        # worker regardless of model; it pulls from whichever peer shares
        # the checkpoint — the swarm-native `ollama pull`).
        pull_err = "no workers available"
        pm = self.peer.peer_manager
        target = pm.find_best_worker("") if pm else None
        if target is not None:
            from crowdllama_tpu.net.model_share import request_pull

            try:
                contact = await self.peer.dht.find_peer(target.peer_id)
                if contact is None:
                    raise RuntimeError(
                        f"cannot resolve worker {target.peer_id[:8]}")
                path = await request_pull(self.peer.host, contact, name)
                return _success([{"status": f"pulled to {path} on worker "
                                            f"{target.peer_id[:8]}"}])
            except Exception as e:
                pull_err = str(e)
                log.warning("proxied pull of %s via %s failed: %s",
                            name, target.peer_id[:8], e)
        return web.json_response({
            "error": f"model {name!r} is not served by any worker and the "
                     f"swarm pull failed ({pull_err}); models are provided "
                     "by swarm workers (start one with "
                     f"--worker-mode --model {name})"}, status=404)

    async def handle_metrics(self, request: web.Request) -> web.Response:
        """GET /metrics — Prometheus text exposition of gateway + swarm
        state.  The machine-readable twin of /api/health (which mirrors the
        reference's JSON health map, gateway.go:426-461); the reference has
        no metrics endpoint."""
        lines = [
            "# TYPE crowdllama_gateway_requests_total counter",
        ]
        for (path, status), n in sorted(self._req_count.items()):
            lines.append(
                f'crowdllama_gateway_requests_total{{path="{path}",'
                f'status="{status}"}} {n}')
        lines.append("# TYPE crowdllama_gateway_request_seconds_total counter")
        for (path, status), s in sorted(self._req_seconds.items()):
            lines.append(
                f'crowdllama_gateway_request_seconds_total{{path="{path}",'
                f'status="{status}"}} {s:.6f}')
        pm = self.peer.peer_manager
        if pm is not None:
            workers = pm.get_workers()
            healthy = [p for p in workers if p.is_healthy]
            lines += [
                "# TYPE crowdllama_workers_total gauge",
                f"crowdllama_workers_total {len(workers)}",
                "# TYPE crowdllama_workers_healthy gauge",
                f"crowdllama_workers_healthy {len(healthy)}",
                "# TYPE crowdllama_worker_throughput_tokens_per_sec gauge",
                "# TYPE crowdllama_worker_load gauge",
                "# TYPE crowdllama_worker_healthy gauge",
            ]
            for p in workers:
                pid = p.peer_id[:16]
                r = p.resource
                lines.append(
                    f'crowdllama_worker_throughput_tokens_per_sec{{'
                    f'peer="{pid}"}} {r.tokens_throughput}')
                lines.append(
                    f'crowdllama_worker_load{{peer="{pid}"}} {r.load}')
                lines.append(
                    f'crowdllama_worker_healthy{{peer="{pid}"}} '
                    f'{1 if p.is_healthy else 0}')
        # Stream-path counters (host-level): how this node's streams
        # actually traveled — direct, relay-spliced, or reversed
        # (net/relay.py connection reversal).
        # Time-to-first-frame histogram for streamed inference, emitted
        # unconditionally (zeros before the first streamed request): an
        # absent series breaks absent()-style alerts and rate() windows
        # across restarts.
        lines.append("# TYPE crowdllama_gateway_ttfb_seconds histogram")
        acc = 0
        for le, n in zip(self._ttfb_le, self._ttfb_buckets):
            acc += n
            lines.append(
                f'crowdllama_gateway_ttfb_seconds_bucket{{le="{le}"}} '
                f"{acc}")
        lines.append(
            f'crowdllama_gateway_ttfb_seconds_bucket{{le="+Inf"}} '
            f"{self._ttfb_count}")
        lines.append(
            f"crowdllama_gateway_ttfb_seconds_sum {self._ttfb_sum:.6f}")
        lines.append(
            f"crowdllama_gateway_ttfb_seconds_count {self._ttfb_count}")
        lines.append("# TYPE crowdllama_gateway_stream_pool_hits_total counter")
        lines.append(
            f"crowdllama_gateway_stream_pool_hits_total "
            f"{self._stream_pool.hits}")
        lines.append(
            "# TYPE crowdllama_gateway_stream_pool_misses_total counter")
        lines.append(
            f"crowdllama_gateway_stream_pool_misses_total "
            f"{self._stream_pool.misses}")
        lines.append("# TYPE crowdllama_gateway_affinity_hits_total counter")
        lines.append(
            f"crowdllama_gateway_affinity_hits_total {self._affinity_hits}")
        lines.append(
            "# TYPE crowdllama_gateway_affinity_evicted_total counter")
        lines.append(
            f"crowdllama_gateway_affinity_evicted_total "
            f"{self._affinity_evicted}")
        lines.append(
            "# TYPE crowdllama_gateway_affinity_repointed_total counter")
        lines.append(
            f"crowdllama_gateway_affinity_repointed_total "
            f"{self._affinity_repointed}")
        lines.append("# TYPE crowdllama_gateway_kv_hints_total counter")
        lines.append(
            f"crowdllama_gateway_kv_hints_total {self._kv_hints}")
        lines.append(
            "# TYPE crowdllama_gateway_gossip_affinity_hits_total counter")
        lines.append(
            f"crowdllama_gateway_gossip_affinity_hits_total "
            f"{self._gossip_affinity_hits}")
        # Robustness plane (docs/ROBUSTNESS.md): failover/replay/shed/budget
        # counters plus dead-transport pool evictions.
        lines.append("# TYPE crowdllama_gateway_failovers_total counter")
        lines.append(
            f"crowdllama_gateway_failovers_total {self._robust['failovers']}")
        lines.append(
            "# TYPE crowdllama_gateway_replayed_chunks_total counter")
        lines.append(
            f"crowdllama_gateway_replayed_chunks_total "
            f"{self._robust['replayed_chunks']}")
        lines.append("# TYPE crowdllama_gateway_shed_total counter")
        lines.append(
            f"crowdllama_gateway_shed_total {self._robust['shed']}")
        lines.append(
            "# TYPE crowdllama_gateway_budget_exhausted_total counter")
        lines.append(
            f"crowdllama_gateway_budget_exhausted_total "
            f"{self._robust['budget_exhausted']}")
        lines.append(
            "# TYPE crowdllama_gateway_pool_evicted_dead_total counter")
        lines.append(
            f"crowdllama_gateway_pool_evicted_dead_total "
            f"{self._stream_pool.evicted_dead}")
        # Gray-failure immunity plane (docs/ROBUSTNESS.md): stalled-stream
        # watchdog teardowns, wedged-worker quarantines, and the hedged
        # first-token dispatch ledger (launched == won + cancelled is the
        # exactly-once conservation law the chaos soak asserts).
        lines.append(
            "# TYPE crowdllama_stall_aborted_streams_total counter")
        lines.append(
            f"crowdllama_stall_aborted_streams_total "
            f"{self._robust['stalled_streams']}")
        lines.append("# TYPE crowdllama_wedge_quarantines_total counter")
        lines.append(
            f"crowdllama_wedge_quarantines_total "
            f"{self._robust['wedge_quarantines']}")
        lines.append("# TYPE crowdllama_hedge_launched_total counter")
        lines.append(
            f"crowdllama_hedge_launched_total "
            f"{self._robust['hedge_launched']}")
        lines.append("# TYPE crowdllama_hedge_won_total counter")
        lines.append(
            f"crowdllama_hedge_won_total {self._robust['hedge_won']}")
        lines.append("# TYPE crowdllama_hedge_cancelled_total counter")
        lines.append(
            f"crowdllama_hedge_cancelled_total "
            f"{self._robust['hedge_cancelled']}")
        # Gateway-drafted speculative pipeline (docs/SPECULATIVE.md):
        # chunks/acks/nacks over the DraftChunk sub-protocol, plus the
        # offered-vs-accepted draft-token ledger (acceptance rate is
        # rate(accepted)/rate(offered)).
        lines.append("# TYPE crowdllama_draft_chunk_sent_total counter")
        lines.append(
            f"crowdllama_draft_chunk_sent_total "
            f"{self._spec_stats['chunks']}")
        lines.append("# TYPE crowdllama_draft_chunk_acks_total counter")
        lines.append(
            f"crowdllama_draft_chunk_acks_total "
            f"{self._spec_stats['acks']}")
        lines.append("# TYPE crowdllama_draft_chunk_nacked_total counter")
        lines.append(
            f"crowdllama_draft_chunk_nacked_total "
            f"{self._spec_stats['nacks']}")
        lines.append(
            "# TYPE crowdllama_draft_chunk_tokens_total counter")
        for outcome, key in (("offered", "offered"),
                             ("accepted", "accepted")):
            lines.append(
                f'crowdllama_draft_chunk_tokens_total{{outcome='
                f'"{outcome}"}} {self._spec_stats[key]}')
        # Request hot-path CPU attribution (ISSUE 1 tentpole d): cumulative
        # microseconds per phase; rate(phase)/rate(requests) is the
        # per-request cost.  aead_us is process-wide (net/secure.py).
        hp = self.hotpath_snapshot()
        lines.append(
            "# TYPE crowdllama_gateway_hotpath_us_total counter")
        for phase in ("route_us", "serde_us", "aead_us", "io_wait_us"):
            lines.append(
                f'crowdllama_gateway_hotpath_us_total{{phase='
                f'"{phase[:-3]}"}} {hp[phase]:.1f}')
        lines.append(
            "# TYPE crowdllama_gateway_hotpath_requests_total counter")
        lines.append(
            f"crowdllama_gateway_hotpath_requests_total {hp['requests']}")
        lines.append(
            "# TYPE crowdllama_route_snapshot_rebuilds_total counter")
        lines.append(
            f"crowdllama_route_snapshot_rebuilds_total "
            f"{hp['route_snapshot_rebuilds']}")
        # Swarm-uniform families (obs/): request/TTFT/decode-step
        # histograms + engine gauges — the same series a worker's
        # ObsServer exposes, so one dashboard reads every node.
        lines.extend(self.obs.metrics.expose())
        engine = getattr(self.peer, "engine", None)
        if engine is not None:
            try:
                lines.extend(engine_gauge_lines(engine.obs_gauges()))
            except Exception as e:
                log.debug("engine gauges unavailable: %s", e)
        # Engine compile/padding telemetry + device memory (PR 8): process
        # singletons, so a gateway co-located with an engine reports real
        # numbers and a pure consumer reports the zero series (present
        # families keep absent()-style alerts working).
        lines.extend(ENGINE_TELEMETRY.expose())
        lines.extend(device_memory_lines())
        lines.extend(host_stat_lines(self.peer.host))
        lines.extend(native_metric_lines())
        # SLO burn-rate plane (PR 13): objective/burn-rate/fast-burn
        # gauges — the series swarm/autoscale.py parse_gauges consumes.
        lines.extend(self.slo.expose())
        return web.Response(text="\n".join(lines) + "\n",
                            content_type="text/plain")

    async def handle_metrics_cluster(self,
                                     request: web.Request) -> web.Response:
        """GET /metrics/cluster — the swarm-wide exposition (PR 13).

        Fans MetricsFetch out to every reachable worker over the
        authenticated p2p plane and re-exports each worker's families
        re-labeled with ``worker=``, plus pre-aggregated
        ``crowdllama_cluster_*`` rollups.  A dead or wedged worker costs a
        per-node timeout and one missing block — the snapshot is partial,
        never a 500.  ``?family=prefix`` (repeatable) narrows the scrape."""
        families = tuple(request.query.getall("family", []))
        text = await self.cluster.render(families)
        return web.Response(text=text, content_type="text/plain")

    async def handle_profile(self, request: web.Request) -> web.Response:
        """GET /debug/profile?seconds=N — capture a jax.profiler trace
        window into the artifact dir and return its path (PR 13).

        Gated on --profile-dir (501 when unset) and single-flight (409
        while a capture is already running): profiler overhead is real,
        an operator gets one window at a time."""
        if not self.profile_dir:
            return web.json_response(
                {"error": "profiling disabled: start the gateway with "
                          "--profile-dir to enable /debug/profile"},
                status=501)
        if self._profiling:
            return web.json_response(
                {"error": "a profile capture is already in flight"},
                status=409)
        try:
            seconds = float(request.query.get("seconds", "3") or 3)
        except ValueError:
            seconds = 3.0
        seconds = min(60.0, max(0.1, seconds))
        path = os.path.join(
            self.profile_dir, f"profile-{int(time.time())}")
        self._profiling = True
        try:
            import jax

            jax.profiler.start_trace(path)
            try:
                await asyncio.sleep(seconds)
            finally:
                jax.profiler.stop_trace()
        except Exception as e:
            return web.json_response(
                {"error": f"profiler capture failed: {e}"}, status=500)
        finally:
            self._profiling = False
        return web.json_response({"artifact": path, "seconds": seconds})

    async def handle_trace(self, request: web.Request) -> web.Response:
        """GET /debug/trace — JSON dump of the span ring buffer.

        ``?trace_id=`` filters to one trace, ``?limit=N`` keeps the N
        newest records (this node's fragment only — the stitched
        cross-node view lives at /debug/trace/<trace_id>)."""
        try:
            limit = max(0, int(request.query.get("limit", "0") or 0))
        except ValueError:
            limit = 0
        return web.json_response(self.obs.trace.snapshot(
            trace_id=request.query.get("trace_id", ""), limit=limit))

    async def handle_trace_stitched(self,
                                    request: web.Request) -> web.Response:
        """GET /debug/trace/<trace_id> — one clock-aligned cross-node span
        tree: this gateway's fragment as the root, plus every fragment a
        TraceFetch fan-out pulls from the swarm (workers, relay hosts)."""
        tid = request.match_info.get("trace_id", "")
        stitched = await self.collector.collect(tid)
        if stitched is None:
            return web.json_response(
                {"error": f"trace {tid!r} not found on any reachable node"},
                status=404)
        return web.json_response(stitched)

    async def handle_flightrecorder(self,
                                    request: web.Request) -> web.Response:
        """GET /debug/flightrecorder — the captured stitched traces of
        recent interesting requests, newest last."""
        return web.json_response(self.flight.snapshot())

    async def handle_unsupported(self, request: web.Request) -> web.Response:
        """Model management (delete/create/copy/push) has no meaning at the
        gateway: each worker owns its weights."""
        return web.json_response({
            "error": f"{request.path} is not supported: models are owned by "
                     "swarm workers, not the gateway"}, status=501)

    # -------------------------------------------------------------- routing

    def _find_worker(self, model: str, exclude: set[str] = frozenset(),
                     require_embeddings: bool = False,
                     acc: dict | None = None):
        pm = self.peer.peer_manager
        if pm is None:
            return None
        t0 = time.perf_counter_ns()
        try:
            return pm.find_best_worker(model, exclude=exclude,
                                       require_embeddings=require_embeddings)
        finally:
            dt = time.perf_counter_ns() - t0
            self._perf["route_ns"] += dt
            if acc is not None:
                acc["route_ns"] = acc.get("route_ns", 0) + dt

    # --------------------------------------------------- OpenAI-compat v1

    @staticmethod
    def _openai_error(message: str, status: int,
                      err_type: str = "invalid_request_error",
                      headers: dict | None = None):
        return web.json_response(
            {"error": {"message": message, "type": err_type,
                       "param": None, "code": None}}, status=status,
            headers=headers)

    @staticmethod
    def _openai_options(body: dict) -> dict:
        """OpenAI top-level params → Ollama-style options dict.

        Raises ``ValueError`` on wrong-typed params (handlers turn it into
        a 400 invalid_request_error, never an aiohttp 500).  Explicit
        ``null`` means "use the OpenAI default" — note `or`-folding would
        also clobber a legitimate temperature of 0."""
        def num(key, default, cast):
            v = body.get(key)
            return default if v is None else cast(v)

        stops = body.get("stop") or []
        if isinstance(stops, str):
            stops = [stops]
        elif not (isinstance(stops, list)
                  and all(isinstance(x, str) for x in stops)):
            raise ValueError("stop must be a string or list of strings")
        if num("n", 1, int) != 1:
            raise ValueError("only n=1 is supported")
        return {
            "num_predict": (num("max_completion_tokens", 0, int)
                            or num("max_tokens", 0, int)),
            # OpenAI's defaults (temperature 1, nucleus off).
            "temperature": num("temperature", 1.0, float),
            "top_p": num("top_p", 1.0, float),
            "seed": num("seed", 0, int),
            "stop": stops,
        }

    @staticmethod
    def _openai_message_text(content) -> str:
        """OpenAI message content may be a string OR a list of typed parts
        ([{"type": "text", "text": ...}, ...]) — flatten to text."""
        if isinstance(content, str):
            return content
        if isinstance(content, list):
            parts = []
            for p in content:
                if isinstance(p, dict) and p.get("type") == "text":
                    parts.append(str(p.get("text", "")))
                elif not isinstance(p, dict):
                    raise ValueError("invalid content part")
                else:
                    raise ValueError(
                        f"unsupported content part type "
                        f"{p.get('type')!r} (text only)")
            return "".join(parts)
        raise ValueError("message content must be a string or parts list")

    async def handle_openai_chat(self, request: web.Request):
        """POST /v1/chat/completions — the OpenAI chat API (Ollama serves
        the same alias; stock openai clients work against the gateway)."""
        try:
            body = await request.json()
        except json.JSONDecodeError:
            return self._openai_error("invalid JSON body", 400)
        model = body.get("model", "")
        messages = body.get("messages", [])
        if not model or not isinstance(messages, list) or not messages:
            return self._openai_error("model and messages are required", 400)
        try:
            options = self._openai_options(body)
            messages = [
                {"role": str(m.get("role", "user")),
                 "content": self._openai_message_text(m.get("content", ""))}
                for m in messages if isinstance(m, dict)]
        except (ValueError, TypeError) as e:
            return self._openai_error(str(e), 400)
        if not messages:
            return self._openai_error("messages are required", 400)
        return await self._route(
            request, model, bool(body.get("stream", False)),
            options, messages=messages, shape="openai-chat")

    async def handle_openai_completions(self, request: web.Request):
        """POST /v1/completions — the legacy OpenAI completion API."""
        try:
            body = await request.json()
        except json.JSONDecodeError:
            return self._openai_error("invalid JSON body", 400)
        model = body.get("model", "")
        prompt = body.get("prompt", "")
        if isinstance(prompt, list):
            if len(prompt) != 1 or not isinstance(prompt[0], str):
                return self._openai_error(
                    "only a single string prompt is supported", 400)
            prompt = prompt[0]
        if not model or not prompt:
            return self._openai_error("model and prompt are required", 400)
        try:
            options = self._openai_options(body)
        except (ValueError, TypeError) as e:
            return self._openai_error(str(e), 400)
        return await self._route(
            request, model, bool(body.get("stream", False)),
            options, prompt=prompt, shape="openai-completion")

    async def handle_openai_models(self, request: web.Request):
        """GET /v1/models — swarm-served models, OpenAI list shape."""
        pm = self.peer.peer_manager
        names: set[str] = set()
        if pm is not None:
            for p in pm.get_healthy_peers():
                if p.is_worker:
                    names.update(p.resource.supported_models)
        now = int(time.time())
        return web.json_response({
            "object": "list",
            "data": [{"id": m, "object": "model", "created": now,
                      "owned_by": "crowdllama"} for m in sorted(names)],
        })

    async def handle_openai_embeddings(self, request: web.Request):
        """POST /v1/embeddings — OpenAI embeddings shape over the swarm."""
        try:
            body = await request.json()
        except json.JSONDecodeError:
            return self._openai_error("invalid JSON body", 400)
        model = body.get("model", "")
        inputs = body.get("input", "")
        if isinstance(inputs, str):
            inputs = [inputs]
        if not model or not isinstance(inputs, list) or not inputs \
                or not all(isinstance(t, str) for t in inputs):
            return self._openai_error("model and input are required", 400)
        resp, status = await self._route_embed(model, inputs)
        if status != 200:
            return self._openai_error(
                str(resp.get("error", "failed")), status,
                "invalid_request_error" if status < 500 else "server_error")
        return web.json_response({
            "object": "list",
            "model": model,
            "data": [{"object": "embedding", "index": i, "embedding": e}
                     for i, e in enumerate(resp["embeddings"])],
            "usage": {"prompt_tokens": resp.get("prompt_eval_count", 0),
                      "total_tokens": resp.get("prompt_eval_count", 0)},
        })

    # ------------------------------------------------------------- routing

    # --------------------------------------------------- prefix affinity

    _AFFINITY_TTL_S = 600.0  # engine prefix pages churn on LRU anyway
    _AFFINITY_MAX = 4096
    _AFFINITY_LOAD_CAP = 0.9

    @staticmethod
    def _affinity_key(model: str, messages, prompt: str):
        """Conversation fingerprint + whether this request is a
        CONTINUATION.

        The key hashes model + first message head + FIRST USER message
        head: a shared system prompt alone must not collapse every
        distinct conversation (and the scaling benchmark's identical
        single-message requests) onto one worker — different users of the
        same app differ in their first user turn, which every later turn
        of that conversation replays verbatim.  Affinity is only APPLIED
        to continuations (a second non-system turn exists): turn 1 has no
        cached prefix to reuse, so it routes by scoring and merely
        records where the conversation landed."""
        import hashlib

        if messages:
            m0 = messages[0]
            head = f"{m0.get('role', '')}:{str(m0.get('content', ''))[:256]}"
            users = [m for m in messages
                     if m.get("role", "") != "system"]
            if users:
                head += f"|u0:{str(users[0].get('content', ''))[:256]}"
            continuation = len(users) >= 2
        else:
            # /api/generate carries no turn structure: a key here would be
            # write-only (never consulted) and its churn would evict live
            # chat conversations from the bounded map.
            return None, False
        if not head:
            return None, False
        return (hashlib.sha1(f"{model}|{head}".encode()).hexdigest(),
                continuation)

    def _affinity_get(self, akey: str | None, model: str):
        """The remembered worker for this conversation, if it is still a
        routable (healthy, complete-group leader), non-saturated server
        of ``model``.  On a local miss the gossip map is consulted: a
        continuation whose first turns went through ANOTHER replica still
        routes to the worker holding its KV (the pin is seeded into the
        local LRU so later turns hit locally)."""
        if akey is None:
            return None
        entry = self._affinity.get(akey)
        if entry is None or time.monotonic() - entry[1] > self._AFFINITY_TTL_S:
            self._affinity.pop(akey, None)
            entry = None
            if self.gossip is not None:
                remote = self.gossip.lookup_affinity(
                    akey, max_age_s=self._AFFINITY_TTL_S)
                if remote is not None:
                    self._affinity_put(akey, remote[0])
                    self._gossip_affinity_hits += 1
                    entry = self._affinity.get(akey)
            if entry is None:
                return None
        self._affinity.move_to_end(akey)  # LRU touch: live conversation
        pm = self.peer.peer_manager
        cand = pm.is_routable(entry[0], model) if pm is not None else None
        if (cand is not None
                and getattr(cand.resource, "load", 0.0)
                < self._AFFINITY_LOAD_CAP):
            return cand
        return None

    def _affinity_put(self, akey: str | None, worker_id: str) -> None:
        if akey is None:
            return
        if akey not in self._affinity and \
                len(self._affinity) >= self._AFFINITY_MAX:
            self._affinity.popitem(last=False)
            self._affinity_evicted += 1
        self._affinity[akey] = (worker_id, time.monotonic())
        self._affinity.move_to_end(akey)
        if self.gossip is not None:
            # Mirror the pin into the replicated map so the OTHER
            # replicas route this conversation's continuations here too.
            self.gossip.record_affinity(akey, worker_id)

    def _affinity_drop_worker(self, worker_id: str,
                              successor: str = "") -> None:
        """Affinity hygiene on drain/removal: entries pinned to a worker
        that is leaving either re-point to its migration successor (whose
        cache holds the imported pages) or evict outright — a stale pin
        would burn a routing attempt per continuation until its TTL."""
        if not worker_id:
            return
        now = time.monotonic()
        for akey in [k for k, v in self._affinity.items()
                     if v[0] == worker_id]:
            if successor:
                self._affinity[akey] = (successor, now)
                self._affinity_repointed += 1
                if self.gossip is not None:
                    self.gossip.record_affinity(akey, successor)
            else:
                del self._affinity[akey]
                if self.gossip is not None:
                    self.gossip.drop_affinity(akey)

    def _kv_donor_for(self, akey: str | None, model: str,
                      chosen_worker: str) -> str:
        """Donor hint for a continuation that is NOT landing on its
        remembered worker: that worker's paged cache still holds the
        conversation's prefix, so the chosen worker can fetch the pages
        instead of recomputing them (docs/KV_TRANSFER.md).  Only a
        still-routable peer qualifies — hinting a dead donor would burn
        the fetch timeout on every request it's attached to."""
        if not self.kv_ship or akey is None:
            return ""
        entry = self._affinity.get(akey)
        if entry is None or time.monotonic() - entry[1] > self._AFFINITY_TTL_S:
            # Local miss: a donor hint remembered by ANOTHER replica is
            # just as good — its worker holds the conversation's pages.
            entry = None
            if self.gossip is not None:
                remote = self.gossip.lookup_affinity(
                    akey, max_age_s=self._AFFINITY_TTL_S)
                if remote is not None:
                    entry = (remote[0], time.monotonic())
            if entry is None:
                return ""
        if entry[0] == chosen_worker:
            return ""
        pm = self.peer.peer_manager
        if pm is None or pm.is_routable(entry[0], model) is None:
            return ""
        return entry[0]

    def _tenant_of(self, request: web.Request) -> str:
        """Tenant key for admission: the X-Tenant header, bounded through
        the same label hygiene as every exposition label (an attacker
        varying the header must not mint unbounded buckets/series)."""
        raw = request.headers.get("X-Tenant", "") or "default"
        return self.obs.metrics.tenant_guard.value(raw)

    async def _route(self, request, model, stream, options,
                     messages=None, prompt="",
                     shape="chat") -> web.StreamResponse:
        """Admission gate + inflight accounting around _route_admitted.

        Shedding happens BEFORE a trace id is minted or a worker touched:
        an overloaded gateway must answer 503 + Retry-After from pure
        in-memory state (docs/ROBUSTNESS.md).  With tenant quotas
        configured the global shed becomes per-tenant: a token bucket
        bounds each tenant's rate CLUSTER-WIDE (remote replicas' admits
        arrive as gossiped usage digests and drain the same buckets), and
        under inflight pressure a tenant at/above its weighted fair share
        of the cap is shed while lighter tenants keep being admitted —
        one hot tenant cannot starve the rest no matter which replica it
        hits."""
        tq = self.tenant_quotas
        tenant = self._tenant_of(request) if tq is not None else ""
        if self.admission_max_inflight \
                and self._inflight >= self.admission_max_inflight:
            if tq is not None:
                tq.shed_total += 1
                self.obs.metrics.tenant_inc(
                    self.obs.metrics.tenant_shed, tenant)
            return self._shed_response(
                shape, model,
                f"overloaded: {self._inflight} requests in flight "
                f"(admission cap {self.admission_max_inflight})")
        if tq is not None:
            if not tq.try_admit(tenant):
                self.obs.metrics.tenant_inc(
                    self.obs.metrics.tenant_shed, tenant)
                return self._shed_response(
                    shape, model,
                    f"tenant {tenant!r} over quota "
                    f"({tq.quotas.get(tenant, tq.quotas.get('default', 0))}"
                    f" req/s)")
            cap = self.admission_max_inflight
            if cap:
                active = {t for t, n in self._tenant_inflight.items()
                          if n > 0}
                share = tq.fair_share(tenant, cap, active)
                if self._tenant_inflight.get(tenant, 0) >= share:
                    self.obs.metrics.tenant_inc(
                        self.obs.metrics.tenant_shed, tenant)
                    return self._shed_response(
                        shape, model,
                        f"tenant {tenant!r} over fair share "
                        f"({share:.1f} of {cap} inflight)")
            self.obs.metrics.tenant_inc(
                self.obs.metrics.tenant_admitted, tenant)
        self._inflight += 1
        if tq is not None:
            self._tenant_inflight[tenant] = \
                self._tenant_inflight.get(tenant, 0) + 1
            self.obs.metrics.tenant_inflight[tenant] = \
                self._tenant_inflight[tenant]
        try:
            return await self._route_admitted(
                request, model, stream, options, messages=messages,
                prompt=prompt, shape=shape)
        finally:
            self._inflight -= 1
            if tq is not None:
                self._tenant_inflight[tenant] -= 1
                self.obs.metrics.tenant_inflight[tenant] = \
                    self._tenant_inflight[tenant]

    async def _route_admitted(self, request, model, stream, options,
                              messages=None, prompt="",
                              shape="chat") -> web.StreamResponse:
        req_kwargs = dict(
            model=model,
            prompt=prompt,
            stream=stream,
            messages=messages or (),
            max_tokens=int(options.get("num_predict", 0)),
            temperature=float(options.get("temperature", 0.0)),
            top_p=float(options.get("top_p", 1.0)),
            # Negative seeds are the conventional "random" sentinel
            # (clients commonly send -1) — map to 0 (unseeded) rather than
            # masking into a fixed reproducible value; oversize values clamp
            # into the proto's uint64 range instead of raising.
            seed=min(max(0, int(options.get("seed", 0))),
                     0xFFFFFFFFFFFFFFFF),
            # Ollama accepts a string or a list for options.stop.
            stop=([stops] if isinstance(
                stops := options.get("stop") or [], str) else
                [str(x) for x in stops]),
            # Clamp like seed: out-of-range/null client values must not
            # escape as proto setter errors.
            top_k=min(max(0, int(options.get("top_k", 0) or 0)), 2**31 - 1),
            repeat_penalty=max(0.0, float(
                options.get("repeat_penalty", 1.0) or 1.0)),
        )
        # pb-object construction is serde work: time it into serde_ns so
        # the native arm (scalar->frame, no pb build on the frame path)
        # and the pb arm attribute the same phase identically.
        t_build = time.perf_counter_ns()
        msg = create_generate_request(**req_kwargs)
        self._perf["serde_ns"] += time.perf_counter_ns() - t_build
        from crowdllama_tpu.net import secure

        # Mint the trace id here — the admission point every hop downstream
        # (stream pool, worker peer, engine, relay splice) inherits it from.
        tid = new_trace_id()
        msg.trace_id = tid
        msg.parent_span = GATEWAY_ROOT_SPAN
        # Speculative pipeline (docs/SPECULATIVE.md): flag streamed
        # generations as remote-draft so the worker opens the VerifyResult
        # sub-protocol.  Workers that don't support it (FakeEngine, old
        # builds) nack every chunk — the stream degrades to plain decode.
        if stream and self.spec_pipeline != "off":
            msg.generate_request.remote_draft = True

        # Size-aware dispatch (see wire.NATIVE_ENVELOPE_MIN_BYTES): short
        # prompts serialize faster through upb than through the ctypes
        # marshalling floor; both paths emit identical bytes.
        _req_payload_len = len(prompt) + sum(
            len(str(m.get("content", ""))) for m in (messages or ()))

        def _native_req_frame(kv_donor: str = "",
                              migrate: bool = False) -> bytes | None:
            """Pre-encode the request wire frame from the admission scalars
            (native fast path; byte-identical to _encode_frame(msg)).
            None → the per-attempt send falls back to pb serialization."""
            if _req_payload_len < wire.NATIVE_ENVELOPE_MIN_BYTES:
                return None
            t_enc = time.perf_counter_ns()
            try:
                f = wire.encode_genreq_frame(
                    **req_kwargs, kv_donor=kv_donor, migrate=migrate,
                    trace_id=tid, parent_span=GATEWAY_ROOT_SPAN)
            except wire.WireError:
                # Oversize raises at the same boundary on the pb path —
                # let _encode_frame produce the identical error there.
                return None
            dt = time.perf_counter_ns() - t_enc
            if f is not None:
                self._perf["serde_ns"] += dt
                acc["serde_ns"] = acc.get("serde_ns", 0) + dt
            return f
        t0 = time.monotonic()  # TTFB measures from ADMISSION, retries included
        # Total wall-clock budget, charged across every retry/failover this
        # request pays (docs/ROBUSTNESS.md): routing, dials, handshakes and
        # decode all race the same deadline.
        budget = self._budget(request)
        deadline = t0 + budget
        self._perf["requests"] += 1
        acc: dict = {}
        # Encode the request frame once at admission (native path); the
        # common attempt (no donor, no migrate) reuses it verbatim and
        # skips per-attempt pb serialization entirely.
        base_frame = _native_req_frame()
        self.obs.trace.begin(tid, node="gateway", model=model,
                             path=request.path, stream=stream)
        aead0 = secure.aead_stats()[0]
        status = 503
        served_by = ""
        sctx = _StreamCtx(shape)
        budget_out = False
        prev_worker = ""
        died_at = 0.0
        try:
            tr = time.perf_counter_ns()
            akey, continuation = self._affinity_key(model, messages, prompt)
            self._perf["route_ns"] += time.perf_counter_ns() - tr
            acc["route_ns"] = acc.get("route_ns", 0) \
                + time.perf_counter_ns() - tr
            tried: set[str] = set()
            last_err = "no workers available for model"
            attempt = 0
            max_attempts = 2  # retry once on next-best worker
            # Live migration (docs/ROBUSTNESS.md): a worker that announced
            # drain becomes the successor's KV donor, and the handoff is
            # granted ONE extra attempt beyond the ordinary retry budget.
            forced_donor = ""
            drained_worker = ""
            drain_extra_granted = False
            while attempt < max_attempts:
                attempt += 1
                now = time.monotonic()
                if now >= deadline:
                    budget_out = True
                    break
                worker = None
                used_affinity = False
                tr = time.perf_counter_ns()
                affine = (self._affinity_get(akey, model)
                          if continuation else None)
                dt_aff = time.perf_counter_ns() - tr
                self._perf["route_ns"] += dt_aff
                acc["route_ns"] = acc.get("route_ns", 0) + dt_aff
                if affine is not None and affine.peer_id not in tried:
                    worker = affine
                    used_affinity = True
                if worker is None:
                    worker = self._find_worker(model, exclude=tried, acc=acc)
                if worker is None:
                    break
                tried.add(worker.peer_id)
                # Affinity miss on a continuation: attach the remembered
                # worker as a KV donor so the chosen one fetches the shared
                # prefix's pages instead of recomputing them.  Reset per
                # attempt — a failover target may BE the donor.
                msg.generate_request.kv_donor = ""
                msg.generate_request.migrate = False
                if forced_donor and forced_donor != worker.peer_id:
                    # MIGRATION: the drained worker stays alive as a KV
                    # donor through its drain window, so the successor
                    # fetches the prompt's pages instead of re-running
                    # prefill (fetch-instead-of-recompute).
                    msg.generate_request.kv_donor = forced_donor
                    msg.generate_request.migrate = True
                elif continuation and not used_affinity:
                    donor = self._kv_donor_for(akey, model, worker.peer_id)
                    if donor:
                        msg.generate_request.kv_donor = donor
                        self._kv_hints += 1
                        self.obs.trace.record(
                            tid, "kv_hint", 0, parent=GATEWAY_ROOT_SPAN,
                            donor=donor[:8], worker=worker.peer_id[:8])
                gr = msg.generate_request
                if sctx.out is not None and getattr(gr, "remote_draft",
                                                    False):
                    # Failover replay runs plain: the in-flight draft
                    # window died with the worker, and the replay-trim
                    # contract only covers text frames.  Token replay
                    # resynchronizes the client; a fresh request would
                    # re-enter the pipeline from scratch.
                    gr.remote_draft = False
                req_frame = None
                if not getattr(gr, "remote_draft", False):
                    # The native encoder has no remote_draft field — a
                    # remote-draft request must take the pb path so the
                    # flag survives serialization.
                    req_frame = (base_frame
                                 if not gr.kv_donor and not gr.migrate
                                 else _native_req_frame(gr.kv_donor,
                                                        gr.migrate))
                if sctx.out is not None:
                    # MID-STREAM FAILOVER: headers (and sent_text chars)
                    # already reached the client from a worker that then
                    # died — replay on the next-best worker and resume the
                    # same response (docs/ROBUSTNESS.md).  The trace id is
                    # reused on purpose: one client request, one trace.
                    self._robust["failovers"] += 1
                    self.obs.trace.record(
                        tid, "failover",
                        int(max(0.0, now - died_at) * 1e9),
                        parent=GATEWAY_ROOT_SPAN,
                        from_worker=prev_worker[:8],
                        to_worker=worker.peer_id[:8])
                    log.warning(
                        "failing stream over %s -> %s (replaying %d "
                        "delivered chars)", prev_worker[:8],
                        worker.peer_id[:8], len(sctx.sent_text))
                try:
                    resp = await self._forward(request, worker.peer_id, msg,
                                               stream, shape, t0, acc=acc,
                                               ctx=sctx, deadline=deadline,
                                               req_frame=req_frame)
                    # Hedged dispatch may have delivered the stream from a
                    # different worker than the one routing picked — pin
                    # the affinity (and attribute the trace) to whoever
                    # actually produced the tokens.
                    winner_id = sctx.winner or worker.peer_id
                    self._affinity_put(akey, winner_id)
                    if drained_worker and drained_worker != winner_id:
                        # Every conversation pinned to the drained worker
                        # re-points to the successor that absorbed the
                        # handoff (satellite: affinity hygiene).
                        self._affinity_drop_worker(drained_worker,
                                                   successor=winner_id)
                    if used_affinity and winner_id == worker.peer_id:
                        # Counted only when the pinned route actually
                        # served: a failed forward falls back to scoring
                        # and must not inflate the hit counter.
                        self._affinity_hits += 1
                    served_by = winner_id
                    status = resp.status
                    return resp
                except _StreamStarted as e:
                    # The CLIENT side of the stream failed (disconnect,
                    # write error): no retry, no failover, no second
                    # response — nobody is listening.  The prefill still
                    # populated this worker's prefix cache, so the
                    # affinity record stays useful.
                    winner_id = sctx.winner or worker.peer_id
                    self._affinity_put(akey, winner_id)
                    if used_affinity and winner_id == worker.peer_id:
                        self._affinity_hits += 1
                    log.warning("stream to client aborted mid-flight: %s",
                                e.cause)
                    served_by = winner_id
                    status = e.response.status
                    return e.response
                except _BudgetExhausted as e:
                    last_err = str(e) or "request budget exhausted"
                    budget_out = True
                    break
                except _WorkerDraining as e:
                    # A drain is a deliberate handoff, not a failure:
                    # quarantine the worker from routing immediately (epoch
                    # bump derails other in-flight routing at the snapshot),
                    # grant the handoff one extra attempt, and carry the
                    # drained worker forward as the successor's KV donor.
                    last_err = str(e)
                    pm = self.peer.peer_manager
                    mark = getattr(pm, "mark_draining", None)
                    if mark is not None:
                        mark(e.worker_id)
                    forced_donor = e.worker_id
                    drained_worker = e.worker_id
                    if not drain_extra_granted:
                        drain_extra_granted = True
                        max_attempts += 1
                    if e.migrated:
                        self.obs.metrics.migrated_streams += 1
                    self.obs.trace.record(
                        tid, "migrate", 0, parent=GATEWAY_ROOT_SPAN,
                        from_worker=e.worker_id[:8],
                        mid_stream=e.migrated,
                        delivered_tokens=e.delivered_tokens)
                    prev_worker = e.worker_id
                    died_at = time.monotonic()
                    log.info(
                        "worker %s draining; re-routing with KV handoff "
                        "(mid_stream=%s, delivered_tokens=%d)",
                        e.worker_id[:8], e.migrated, e.delivered_tokens)
                except _StreamStalled as e:
                    # GRAY FAILURE: the worker holds the transport open
                    # but stopped producing frames past the stall budget.
                    # Unlike a crash there is no EOF — the watchdog turns
                    # silence into an actionable death: quarantine the
                    # worker as WEDGED (it may still answer health
                    # probes, so an ordinary probe would never evict it)
                    # and fail the stream over like any worker death.
                    last_err = str(e)
                    self._robust["stalled_streams"] += 1
                    pm = self.peer.peer_manager
                    mark = getattr(pm, "mark_draining", None)
                    if mark is not None and mark(e.worker_id,
                                                 reason="wedged"):
                        self._robust["wedge_quarantines"] += 1
                    self.obs.trace.record(
                        tid, "wedged", 0, parent=GATEWAY_ROOT_SPAN,
                        worker=e.worker_id[:8], phase=e.phase)
                    prev_worker = e.worker_id
                    died_at = time.monotonic()
                    log.warning(
                        "worker %s stalled (%s phase); quarantined as "
                        "wedged, failing stream over", e.worker_id[:8],
                        e.phase)
                except Exception as e:
                    # Worker-side failure (pre- OR mid-stream): eligible
                    # for retry/failover on the next-best worker.
                    last_err = str(e)
                    prev_worker = worker.peer_id
                    died_at = time.monotonic()
                    log.warning("worker %s failed: %s", worker.peer_id[:8], e)
            if budget_out:
                self._robust["budget_exhausted"] += 1
            if sctx.out is not None:
                # Headers already out and every attempt exhausted: finish
                # the started stream with a terminal error frame instead
                # of dropping the connection mid-body.
                status = sctx.out.status
                detail = (f"request budget exhausted after {budget:.1f}s"
                          if budget_out else f"inference failed: {last_err}")
                served_by = prev_worker
                return await self._terminal_error_frame(
                    sctx, shape, model, detail)
            if budget_out:
                status = 504
                detail = (f"deadline exceeded: request budget "
                          f"{budget:.1f}s exhausted ({last_err})")
                if shape.startswith("openai"):
                    return self._openai_error(detail, 504, "server_error")
                return web.json_response(
                    {"error": detail, "model": model}, status=504)
            if "overloaded" in last_err:
                # Worker-side admission rejection (scheduler pending depth
                # over threshold): shed with the same 503 + Retry-After
                # contract as the gateway's own cap.
                status = 503
                return self._shed_response(
                    shape, model, f"inference failed: {last_err}")
            if shape.startswith("openai"):
                return self._openai_error(
                    f"inference failed: {last_err}", 503, "server_error")
            return web.json_response(
                {"error": f"inference failed: {last_err}", "model": model},
                status=503)
        finally:
            acc["aead_ns"] = max(0, secure.aead_stats()[0] - aead0)
            self._finish_trace(tid, acc, model, t0, status, served_by)

    def _finish_trace(self, tid: str, acc: dict, model: str, t0: float,
                      status: int, worker_id: str = "") -> None:
        """Flush one routed request's accumulated phase timings into its
        trace record and the request_seconds histogram.  The aead figure is
        a process-wide delta over the request window (net/secure.py keeps
        module counters), so concurrent requests' seal/open time can bleed
        into each other's span — fine for attribution, not for billing."""
        total_ns = int((time.monotonic() - t0) * 1e9)
        tr = self.obs.trace
        for phase in _GW_PHASES:
            tr.record(tid, phase, acc.get(phase + "_ns", 0),
                      parent=GATEWAY_ROOT_SPAN)
        for phase in _GW_OPT_PHASES:
            if acc.get(phase + "_ns"):
                tr.record(tid, phase, acc[phase + "_ns"],
                          parent=GATEWAY_ROOT_SPAN)
        tr.finish(tid, total_ns, status=status,
                  worker=worker_id[:8] if worker_id else "")
        hist = self.obs.metrics.request_seconds.labels(model)
        total_s = total_ns / 1e9
        # Flight-recorder decision BEFORE observing this request: a tail
        # request must be compared against the p99 of everything before it,
        # not a distribution it already dragged upward.
        reasons = self._flight_reasons(tid, hist, total_s, status)
        hist.observe(total_s, exemplar=tid)
        if reasons:
            self._flight_capture(tid, reasons)

    def _flight_reasons(self, tid: str, hist, total_s: float,
                        status: int) -> list[str]:
        """Why this request is interesting enough for the flight recorder
        (empty = it is not).  Gateway-visible triggers only; worker-side
        kv-ship fallbacks are confirmed post-stitch in _flight_capture."""
        reasons: list[str] = []
        if hist.count >= self._flight_min_count \
                and total_s > hist.quantile(0.99):
            reasons.append("p99_latency")
        if status >= 500:
            reasons.append(f"status_{status}")
        if status == 504:
            # Budget exhaustion gets its own reason on top of status_504
            # so the recorder ring is filterable by failure mode.
            reasons.append("budget_exhausted")
        if self.slo.enabled:
            # Edge-triggered: only the request that TIPS the SLO into
            # fast burn is captured, not every request inside an episode.
            before = self.slo.fast_burn_episodes_total
            if self.slo.fast_burn() \
                    and self.slo.fast_burn_episodes_total > before:
                reasons.append("slo_fast_burn")
        from crowdllama_tpu.engine.autotune import BACKOFF_LOG

        backoffs, _ = BACKOFF_LOG.snapshot()
        if backoffs > self._autotune_backoffs_seen:
            # Edge-triggered like slo_fast_burn: only the first request
            # retired after an autopilot hard back-off is captured, and
            # _flight_capture attaches the offending dial move.
            self._autotune_backoffs_seen = backoffs
            reasons.append("autotune_backoff")
        rec = self.obs.trace.get(tid)
        if rec is not None:
            names = {s.get("name", "") for s in rec.get("spans", [])}
            if "failover" in names:
                reasons.append("failover")
            if "migrate" in names:
                reasons.append("migrate")
            if "wedged" in names:
                # A gray failure the progress watchdog converted into a
                # failover: the stitched trace shows WHERE the stream
                # stalled (ttft vs decode) and which worker was
                # quarantined (docs/ROBUSTNESS.md).
                reasons.append("wedged")
            if "kv_hint" in names:
                # Candidate only: kept iff the stitched worker fragment
                # shows the donor fetch actually fell back.
                reasons.append("kv_hint")
        return reasons

    def _flight_capture(self, tid: str, reasons: list[str]) -> None:
        """Stitch + capture asynchronously: the fan-out must never sit on
        the request path (we are inside _route's finally)."""
        if (self.flight.get(tid) is not None
                or self._flight_inflight >= self._flight_max_inflight):
            return
        self._flight_inflight += 1

        async def _go() -> None:
            try:
                stitched = await self.collector.collect(tid)
            except Exception as e:
                log.debug("flight-recorder stitch for %s failed: %s",
                          tid, e)
                return
            finally:
                self._flight_inflight -= 1
            if stitched is None:
                return
            final = list(reasons)
            if "autotune_backoff" in final:
                # Attach the offending dial move so the captured trace
                # explains WHICH knob tripped the fast-burn guard.
                from crowdllama_tpu.engine.autotune import BACKOFF_LOG

                last = BACKOFF_LOG.snapshot()[1]
                if last:
                    stitched = dict(stitched)
                    stitched["autotune_backoff"] = dict(last)
            if "kv_hint" in final:
                final.remove("kv_hint")
                if any(s.get("name") == "kv_fetch"
                       and (s.get("meta", {}).get("fallback")
                            or s.get("meta", {}).get("error"))
                       for s in stitched.get("spans", [])):
                    final.append("kv_ship_fallback")
            if final:
                self.flight.capture(tid, final, stitched)

        asyncio.ensure_future(_go())

    def _observe_ttfb(self, dt: float, tid: str = "") -> None:
        for i, le in enumerate(self._ttfb_le):
            if dt <= le:
                self._ttfb_buckets[i] += 1
                break
        else:
            self._ttfb_buckets[-1] += 1
        self._ttfb_sum += dt
        self._ttfb_count += 1
        self.obs.metrics.ttft_seconds.observe(dt, exemplar=tid)
        self.slo.observe_ttft(dt)

    async def _terminal_error_frame(self, ctx: _StreamCtx, shape: str,
                                    model: str,
                                    message: str) -> web.StreamResponse:
        """Every attempt exhausted AFTER headers went out: end the started
        stream with a well-formed terminal error frame (Ollama NDJSON error
        line / OpenAI SSE error event + [DONE]) instead of dropping the
        connection mid-body.  Client write failures here are moot — nobody
        is listening — hence the blanket suppress."""
        out = ctx.out
        try:
            if shape.startswith("openai"):
                line = json.dumps({"error": {
                    "message": message, "type": "server_error"}}).encode()
                await out.write(b"data: " + line + b"\n\n")
                await out.write(b"data: [DONE]\n\n")
            else:
                line = json.dumps({
                    "model": model,
                    "created_at": _now_rfc3339(),
                    "done": True, "done_reason": "error",
                    "error": message,
                }).encode()
                await out.write(line + b"\n")
            await out.write_eof()
        except Exception:
            pass
        return out

    # ------------------------------------- gray-failure immunity plane

    def _stall_budget(self, phase: str) -> float:
        """Seconds of token-progress silence tolerated in ``phase``
        ("ttft" | "decode") before the stream is declared stalled
        (0.0 = watchdog off).  The live SLO objective raises the floor:
        a stall deadline must never be tighter than the latency the
        operator promised clients for the same phase."""
        if self.stream_stall_ms <= 0:
            return 0.0
        ms = self.stream_stall_ms
        tr = self.slo.trackers.get(phase)
        if tr is not None and tr.objective_ms > ms:
            ms = tr.objective_ms
        return ms / 1000.0

    def _hedge_threshold(self) -> float:
        """Seconds of first-token silence before a hedge launches
        (0.0 = hedging off).  The LIVE TTFT p95 raises the configured
        floor once the histogram has enough mass (same observation floor
        the flight recorder uses), falling back to the SLO TTFT
        objective — so "slow" always means slow RELATIVE TO THE SWARM,
        and a uniformly slow model does not trigger a hedge storm."""
        if self.hedge_ttft_ms <= 0:
            return 0.0
        thr = self.hedge_ttft_ms / 1000.0
        hist = self.obs.metrics.ttft_seconds
        if hist.count >= self._flight_min_count:
            thr = max(thr, hist.quantile(0.95))
        else:
            tr = self.slo.trackers.get("ttft")
            if tr is not None:
                thr = max(thr, tr.objective_ms / 1000.0)
        return thr

    def _classify_frame(self, raw, worker_id: str):
        """Decode one inference-stream frame, surfacing drain/handoff
        frames as _WorkerDraining so _route re-routes with the drained
        worker attached as KV donor (checked BEFORE the generate
        extraction: a MigrateFrame is a different oneof arm)."""
        if raw.WhichOneof("message") == "migrate_frame":
            mf = raw.migrate_frame
            raise _WorkerDraining(worker_id, migrated=True,
                                  delivered_tokens=mf.delivered_tokens)
        resp = extract_generate_response(raw)
        if resp.done and resp.done_reason == "draining":
            raise _WorkerDraining(worker_id)
        return resp

    async def _open_stream(self, worker_id: str, msg, frame: bytes,
                           deadline: float | None, stall_ttft: float,
                           acc: dict, use_pool: bool = True,
                           vsink: list | None = None):
        """Open an inference stream to ``worker_id``, send the encoded
        ``frame`` and read the FIRST response frame; returns
        ``(stream, first_resp)`` with the caller owning the stream.

        Pooled stream first (a stale one — worker idled it out or
        restarted — gets ONE fresh redial), fresh dial otherwise.  Every
        receive is clamped to ``stall_ttft`` when the progress watchdog
        is armed: a worker that accepted the request and went silent
        surfaces as _StreamStalled rather than a redial — a second dial
        would burn another full stall budget on the same wedged worker.
        Cancellation (hedge race lost) closes the stream before any of
        its frames can reach a client."""
        def remaining() -> float:
            return (deadline - time.monotonic()) if deadline is not None \
                else 600.0

        def _recv_timeout() -> float:
            t = max(0.05, min(600.0, remaining()))
            return min(t, stall_ttft) if stall_ttft > 0 else t

        async def _first(s):
            """First NON-verify frame: a remote-draft worker yields the
            VerifyResult handshake before its first text frame — divert
            those into vsink for the pump instead of classifying them."""
            while True:
                raw = await self._recv_pb(s, timeout=_recv_timeout(),
                                          acc=acc)
                if (vsink is not None
                        and raw.WhichOneof("message") == "verify_result"):
                    vsink.append(raw.verify_result)
                    continue
                return self._classify_frame(raw, worker_id)

        s = self._pool_get(worker_id) if use_pool else None
        if s is not None:
            try:
                await self._send_frame(s, frame, acc=acc)
                return s, await _first(s)
            except (asyncio.CancelledError, _WorkerDraining):
                # A draining reject is a DELIBERATE answer, not a stale
                # pooled stream: no redial (it would get the same
                # reject).  A cancel means the hedge race was lost.
                s.close()
                raise
            except asyncio.TimeoutError as e:
                s.close()
                if remaining() <= 0:
                    raise _BudgetExhausted(
                        "budget exhausted on pooled attempt") from e
                if stall_ttft > 0:
                    raise _StreamStalled(worker_id, "ttft") from e
                raise
            except Exception as e:
                s.close()
                if remaining() <= 0:
                    raise _BudgetExhausted(
                        "budget exhausted on pooled attempt") from e
                log.debug("pooled stream to %s stale (%s); redialing",
                          worker_id[:8], e)
        s = await self._dial(worker_id, acc=acc,
                             timeout=(remaining()
                                      if deadline is not None else None),
                             trace_id=msg.trace_id)
        try:
            await self._send_frame(s, frame, acc=acc)
            return s, await _first(s)
        except BaseException as e:
            s.close()
            if (isinstance(e, (asyncio.TimeoutError, OSError))
                    and remaining() <= 0):
                raise _BudgetExhausted(
                    "budget exhausted during dial/first frame") from e
            if isinstance(e, asyncio.TimeoutError) and stall_ttft > 0:
                raise _StreamStalled(worker_id, "ttft") from e
            raise

    async def _hedge_race(self, primary_id: str, msg, frame: bytes,
                          deadline: float | None, stall_ttft: float,
                          acc: dict, hedge_thr: float):
        """Hedged first-token dispatch (docs/ROBUSTNESS.md): give the
        primary worker ``hedge_thr`` seconds to produce a first frame;
        past it, speculatively dispatch the SAME request to the
        second-best worker and deliver whichever stream wins the race.

        EXACTLY-ONCE: _open_stream returns at the first frame — nothing
        reaches the client until a single winner is chosen, and every
        loser is cancelled/closed before its first byte could be
        written.  Counter conservation (asserted by the chaos soak):
        hedge_launched == hedge_won + hedge_cancelled.

        Returns ``(stream, first_resp, winner_worker_id)``."""
        tid = msg.trace_id
        p_task = asyncio.ensure_future(self._open_stream(
            primary_id, msg, frame, deadline, stall_ttft, acc))
        tasks: dict[asyncio.Task, str] = {p_task: primary_id}
        launched = False
        try:
            done, _ = await asyncio.wait({p_task}, timeout=hedge_thr)
            if not done:
                # First token is late relative to the swarm: launch the
                # hedge on the next-best worker.  Never pooled — the
                # pool hands out per-worker streams, but this request
                # may be abandoned mid-frame by a cancel, which poisons
                # a reusable transport.
                alt = self._find_worker(msg.generate_request.model,
                                        exclude={primary_id}, acc=acc)
                if alt is not None:
                    launched = True
                    self._robust["hedge_launched"] += 1
                    self.obs.trace.record(
                        tid, "hedge", 0, parent=GATEWAY_ROOT_SPAN,
                        primary=primary_id[:8], hedge=alt.peer_id[:8])
                    tasks[asyncio.ensure_future(self._open_stream(
                        alt.peer_id, msg, frame, deadline, stall_ttft,
                        acc, use_pool=False))] = alt.peer_id
            winner = None
            primary_err: BaseException | None = None
            while tasks and winner is None:
                done, _ = await asyncio.wait(
                    set(tasks), return_when=asyncio.FIRST_COMPLETED)
                for t in done:
                    wid = tasks.pop(t)
                    err = t.exception()
                    if err is None:
                        if winner is None:
                            winner = (await t, wid)
                        else:
                            # Two first frames landed in the same wait
                            # round: the second is a loser like any
                            # other — close before any byte escapes.
                            (await t)[0].close()
                        continue
                    if isinstance(err, _WorkerDraining):
                        # A loser's drain announcement must still
                        # quarantine it — the observation is real even
                        # though the race discards the attempt.
                        pm = self.peer.peer_manager
                        mark = getattr(pm, "mark_draining", None)
                        if mark is not None:
                            mark(err.worker_id)
                    if wid == primary_id:
                        primary_err = err
            if winner is None:
                # Both sides failed: the hedge did not win, account it
                # as cancelled (conservation) and surface the PRIMARY's
                # error so _route's ladder sees the same failure mode an
                # unhedged attempt would have produced.
                if launched:
                    self._robust["hedge_cancelled"] += 1
                if primary_err is not None:
                    raise primary_err
                raise RuntimeError("hedged dispatch failed on every leg")
            (s, first_resp), wid = winner
            if launched:
                if wid == primary_id:
                    self._robust["hedge_cancelled"] += 1
                else:
                    self._robust["hedge_won"] += 1
            return s, first_resp, wid
        finally:
            # Tear down every leg still racing — the loser BEFORE its
            # first byte reaches the client — and reap a straggler that
            # completed between the winner landing and the cancel.
            for t in tasks:
                t.cancel()
            if tasks:
                reaped = await asyncio.gather(*tasks,
                                              return_exceptions=True)
                for r in reaped:
                    if isinstance(r, tuple):
                        r[0].close()

    def _drafter(self):
        """The gateway's local draft model, loaded lazily on the first
        remote-draft stream.  Returns None in "worker" mode or when the
        checkpoint is unusable — the pump then sends pure ack credits and
        the stream still paces the worker (worker-draft speculation)."""
        if self.spec_pipeline != "gateway":
            return None
        if self._spec_drafter is None and not self._spec_drafter_tried:
            self._spec_drafter_tried = True
            if not self.spec_draft_path:
                log.warning("spec_pipeline=gateway with no draft "
                            "checkpoint; degrading to ack pacing")
            else:
                try:
                    from crowdllama_tpu.gateway.draft import GatewayDrafter

                    self._spec_drafter = GatewayDrafter.from_checkpoint(
                        self.spec_draft_path)
                    log.info("gateway draft model loaded from %s",
                             self.spec_draft_path)
                except Exception as e:
                    log.warning("gateway draft load failed (%s); "
                                "degrading to ack pacing", e)
        return self._spec_drafter

    def _spec_pump(self, s, msg, acc: dict, worker_id: str = ""):
        """Build the per-stream draft pump wired to ``s``'s writer,
        warm-starting its depth controller from the last stream to the
        same worker (the wire doesn't change between streams)."""
        from crowdllama_tpu.gateway.draft import SpecPipelinePump

        async def _send(frame: bytes) -> None:
            await self._send_frame(s, frame, acc=acc)

        pump = SpecPipelinePump(model=msg.generate_request.model,
                                send=_send, drafter=self._drafter())
        wire = self._spec_wire.get(worker_id)
        if wire is not None:
            pump.ctrl.rtt_ewma, pump.ctrl.step_ewma = wire
        return pump

    async def _forward(self, request, worker_id: str, msg, stream: bool,
                       shape: str, t0: float,
                       acc: dict | None = None,
                       ctx: _StreamCtx | None = None,
                       deadline: float | None = None,
                       req_frame: bytes | None = None) -> web.StreamResponse:
        """Open an inference stream to the worker and relay the reply
        (gateway.go:243-298).  ``shape`` picks the client dialect:
        Ollama NDJSON ("chat"/"generate") or OpenAI SSE ("openai-*").
        ``t0`` is the _route admission time: the TTFB histogram must
        charge failed-worker retries to the request, not reset on them.
        ``acc`` is the per-request phase accumulator from _route.

        ``ctx`` carries the client-side stream state across worker
        attempts: on a FAILOVER call (ctx.out already prepared) the reply
        is replayed and trimmed against ctx.sent_text so the client never
        sees a duplicated or missing character.  ``deadline`` is the
        absolute monotonic cutoff from the request's wall-clock budget —
        every dial/handshake/recv below is clamped to what remains of it,
        and expiry surfaces as _BudgetExhausted."""
        if acc is None:
            acc = {}
        if ctx is None:
            ctx = _StreamCtx(shape)
        openai = shape.startswith("openai")

        def remaining() -> float:
            return (deadline - time.monotonic()) if deadline is not None \
                else 600.0

        def _recv_timeout(stall: float = 0.0) -> float:
            t = max(0.05, min(600.0, remaining()))
            return min(t, stall) if stall > 0 else t

        def render(resp, final: bool) -> dict:
            if openai:
                d = self._openai_json(resp, shape, final, stream, ctx.rid,
                                      ctx.created, first=ctx.nth == 0)
                ctx.nth += 1
                return d
            return self._ollama_json(resp, shape == "chat", final=final)

        def classify(raw):
            # Late-bound worker_id on purpose: a hedge win reassigns it
            # to the worker actually serving the decode loop.
            return self._classify_frame(raw, worker_id)

        if not stream:
            resp = classify(await self._roundtrip(
                worker_id, msg, timeout=_recv_timeout(), acc=acc,
                frame=req_frame))
            if resp.done_reason == "error":
                raise RuntimeError(resp.response)
            return web.json_response(render(resp, final=True))

        # Streamed: one NDJSON line (Ollama) or SSE data event (OpenAI)
        # per chunk.  The FIRST frame is read before sending headers
        # (_open_stream), so a worker that dies immediately is still
        # retryable by _route — and a STALE pooled stream is detected
        # while a fresh redial is still possible.  When the per-stream
        # progress watchdog is armed, every receive below is clamped to
        # the phase's stall budget: a worker holding the transport open
        # without producing frames surfaces as _StreamStalled instead of
        # hanging until the request budget dies (docs/ROBUSTNESS.md).
        stall_ttft = self._stall_budget("ttft")
        stall_decode = self._stall_budget("decode")
        if remaining() <= 0:
            raise _BudgetExhausted("budget exhausted before dial")
        frame = req_frame if req_frame is not None \
            else self._encode_frame(msg, acc=acc)
        # Speculative pipeline (docs/SPECULATIVE.md): a remote-draft
        # stream interleaves VerifyResult frames with the text frames.
        # Those feed the draft pump (which answers with DraftChunk
        # frames) and never reach the client; hedging is disabled —
        # a raced duplicate would double-consume the draft window — and
        # the stream is never pooled (the sub-protocol is one-shot on
        # the worker side too).
        rd = bool(getattr(msg.generate_request, "remote_draft", False))
        vsink: list | None = [] if rd else None
        # Hedged first-token dispatch: only on the FIRST attempt of a
        # stream — a failover replay already has client bytes out, and
        # failover itself covers that tail.
        hedge_thr = (self._hedge_threshold()
                     if (ctx.out is None and not rd) else 0.0)
        if hedge_thr > 0:
            s, first, worker_id = await self._hedge_race(
                worker_id, msg, frame, deadline, stall_ttft, acc,
                hedge_thr)
        else:
            s, first = await self._open_stream(
                worker_id, msg, frame, deadline, stall_ttft, acc,
                vsink=vsink)
        ctx.winner = worker_id
        pump = None
        if rd:
            pump = self._spec_pump(s, msg, acc, worker_id=worker_id)
            for vr in vsink:
                await pump.on_verify(vr)
            vsink.clear()
        # Pool the stream back only after the worker's terminal frame was
        # READ (a mid-response abort leaves frames in flight — closing is
        # the only safe disposal).
        clean = False
        try:
            if first.done_reason == "error":
                raise RuntimeError(first.response)
            if ctx.out is None:
                self._observe_ttfb(time.monotonic() - t0,
                                   tid=msg.trace_id)
                out = web.StreamResponse(
                    status=200,
                    headers={"Content-Type": ("text/event-stream" if openai
                                              else "application/x-ndjson")},
                )
                await out.prepare(request)
                ctx.out = out
            out = ctx.out

            async def write_frame(payload: dict) -> None:
                # A client-side write failure is final (_StreamStarted):
                # there is no one left to fail over for.
                line = json.dumps(payload).encode()
                tw = time.perf_counter_ns()
                try:
                    if openai:
                        await out.write(b"data: " + line + b"\n\n")
                    else:
                        await out.write(line + b"\n")
                except Exception as e:
                    raise _StreamStarted(out, e) from e
                acc["stream_flush_ns"] = acc.get("stream_flush_ns", 0) \
                    + time.perf_counter_ns() - tw

            # Replay trim (failover only): the re-sent request regenerates
            # from the prompt, so the first len(ctx.sent_text) chars of the
            # new reply were ALREADY delivered — skip them by count, and
            # log once if the replay text diverges from what the client
            # holds (non-greedy sampling without a seed can differ).
            skip = len(ctx.sent_text)
            replay_pos = 0
            diverged = False

            resp = first
            # Inter-frame receive gap ≈ worker decode step + wire, as seen
            # from the gateway — the consumer-side decode_step histogram.
            t_prev = time.perf_counter_ns()
            while True:
                if resp.done_reason == "error":
                    raise RuntimeError(resp.response)
                text = resp.response
                trimmed_empty = False
                if skip > 0 and text:
                    take = min(skip, len(text))
                    if (not diverged
                            and ctx.sent_text[replay_pos:replay_pos + take]
                            != text[:take]):
                        diverged = True
                        log.warning(
                            "failover replay diverged from delivered text "
                            "at char %d (request %s); resuming by count",
                            replay_pos, ctx.rid)
                    replay_pos += take
                    skip -= take
                    text = text[take:]
                    resp.response = text
                    self._robust["replayed_chunks"] += 1
                    trimmed_empty = not text
                if resp.done or not trimmed_empty:
                    ctx.sent_text += text
                    await write_frame(render(resp, final=resp.done))
                if resp.done:
                    clean = True  # terminal frame read: stream reusable
                    break
                if remaining() <= 0:
                    raise _BudgetExhausted("budget exhausted mid-stream")
                try:
                    while True:
                        raw = await self._recv_pb(
                            s, timeout=_recv_timeout(stall_decode),
                            acc=acc)
                        if (pump is not None
                                and raw.WhichOneof("message")
                                == "verify_result"):
                            await pump.on_verify(raw.verify_result)
                            continue
                        break
                    resp = classify(raw)
                except asyncio.TimeoutError as e:
                    if remaining() <= 0:
                        raise _BudgetExhausted(
                            "budget exhausted mid-stream") from e
                    if stall_decode > 0:
                        # Mid-decode stall: frames stopped arriving past
                        # the watchdog budget with the transport still
                        # open — tear down and fail over (the replay
                        # trim resumes the client byte-identically).
                        raise _StreamStalled(worker_id, "decode") from e
                    raise
                t_now = time.perf_counter_ns()
                self.obs.metrics.decode_step_seconds.observe(
                    (t_now - t_prev) / 1e9, exemplar=msg.trace_id)
                self.slo.observe_decode((t_now - t_prev) / 1e9)
                t_prev = t_now
            if openai:
                try:
                    await out.write(b"data: [DONE]\n\n")
                except Exception as e:
                    raise _StreamStarted(out, e) from e
            try:
                await out.write_eof()
            except Exception as e:
                raise _StreamStarted(out, e) from e
            return out
        finally:
            if pump is not None:
                self._spec_stats["chunks"] += pump.chunks_sent
                self._spec_stats["acks"] += pump.acks_sent
                self._spec_stats["nacks"] += pump.nacks
                self._spec_stats["accepted"] += pump.tokens_accepted
                self._spec_stats["offered"] += pump.tokens_offered
                if pump.ctrl.rtt_ewma > 0.0 and pump.ctrl.step_ewma > 0.0:
                    self._spec_wire[worker_id] = (pump.ctrl.rtt_ewma,
                                                  pump.ctrl.step_ewma)
            if clean and pump is None:
                self._pool_put(worker_id, s)
            else:
                # Remote-draft streams are one-shot on both sides: the
                # worker's reader task may still own half a frame.
                s.close()

    @staticmethod
    def _ollama_json(resp, chat: bool, final: bool) -> dict:
        """PB → Ollama-shaped JSON (gateway.go:220-230)."""
        d: dict = {
            "model": resp.model,
            "created_at": _now_rfc3339(),
            "done": resp.done,
        }
        if chat:
            d["message"] = {"role": "assistant", "content": resp.response}
        else:
            d["response"] = resp.response
        if final:
            d["done_reason"] = resp.done_reason or "stop"
            d["total_duration"] = resp.total_duration
            d["prompt_eval_count"] = resp.prompt_tokens
            d["eval_count"] = resp.completion_tokens
            d["worker_id"] = resp.worker_id
        return d

    @staticmethod
    def _openai_json(resp, shape: str, final: bool, stream: bool,
                     rid: str, created: int, first: bool = False) -> dict:
        """PB → OpenAI-shaped JSON (chat.completion[.chunk] /
        text_completion)."""
        chat = shape == "openai-chat"
        finish = ({"stop": "stop", "length": "length"}.get(
            resp.done_reason or "stop", "stop") if final else None)
        if chat:
            if stream:
                delta: dict = {}
                if first:
                    # OpenAI's first-chunk contract: the role arrives on
                    # the opening delta (clients accumulate it).
                    delta["role"] = "assistant"
                    delta["content"] = ""
                if resp.response:
                    delta["content"] = resp.response
                choice: dict = {"index": 0, "delta": delta,
                                "finish_reason": finish}
            else:
                choice = {"index": 0,
                          "message": {"role": "assistant",
                                      "content": resp.response},
                          "finish_reason": finish}
            obj = "chat.completion.chunk" if stream else "chat.completion"
        else:
            choice = {"index": 0, "text": resp.response,
                      "finish_reason": finish}
            obj = "text_completion"
        d = {"id": rid, "object": obj, "created": created,
             "model": resp.model, "choices": [choice]}
        if final:
            d["usage"] = {
                "prompt_tokens": resp.prompt_tokens,
                "completion_tokens": resp.completion_tokens,
                "total_tokens": resp.prompt_tokens + resp.completion_tokens,
            }
        return d
