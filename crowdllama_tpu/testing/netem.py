"""Network emulation for benchmarks and tests: injected-latency relays.

One :class:`DelayProxy` — a transparent TCP relay delivering every chunk
a fixed one-way delay after it was read — shared by every harness that
sweeps synthetic RTT (benchmarks/ep_dispatch.py, benchmarks/kv_transfer.py,
benchmarks/spec_rtt.py, and RTT-sensitive tests).  It used to live inside
ep_dispatch.py with kv_transfer importing across benchmark modules; the
speculative-pipeline RTT harness made it a three-way copy, so it moved
here.

The relay is deliberately dumb: no bandwidth shaping, no loss, no
reordering — injected RTT is the one variable the swarm benchmarks sweep,
and everything else staying ideal keeps the sweep attributable.
"""

from __future__ import annotations

import asyncio


class DelayProxy:
    """Transparent TCP relay that delivers every chunk ``delay_s`` after it
    was read, per direction (injected RTT = 2 * delay_s per round trip).

    Delivery is timestamp-scheduled (reader task enqueues, writer task
    sleeps until due), so reads never stall behind the sleep: a multi-chunk
    message pays the delay ONCE, not once per chunk."""

    def __init__(self, target_port: int, delay_s: float,
                 host: str = "127.0.0.1"):
        self._target = target_port
        self._delay = delay_s
        self._host = host
        self._server: asyncio.base_events.Server | None = None
        self._tasks: set[asyncio.Task] = set()

    async def start(self) -> int:
        self._server = await asyncio.start_server(
            self._on_conn, self._host, 0)
        return self._server.sockets[0].getsockname()[1]

    async def close(self) -> None:
        if self._server is not None:
            self._server.close()
            await self._server.wait_closed()
        for t in list(self._tasks):
            t.cancel()
        await asyncio.gather(*self._tasks, return_exceptions=True)

    def _track(self, coro) -> None:
        t = asyncio.create_task(coro)
        self._tasks.add(t)
        t.add_done_callback(self._tasks.discard)

    async def _on_conn(self, reader, writer):
        try:
            up_r, up_w = await asyncio.open_connection(
                self._host, self._target)
        except OSError:
            writer.close()
            return
        self._track(self._pump(reader, up_w))
        self._track(self._pump(up_r, writer))

    async def _pump(self, reader, writer):
        loop = asyncio.get_running_loop()
        q: asyncio.Queue = asyncio.Queue()

        async def drain_delayed():
            while True:
                item = await q.get()
                if item is None:
                    break
                due, data = item
                dt = due - loop.time()
                if dt > 0:
                    await asyncio.sleep(dt)
                try:
                    writer.write(data)
                    await writer.drain()
                except (ConnectionError, OSError):
                    return
            try:
                if writer.can_write_eof():
                    writer.write_eof()  # propagate half-close
            except (ConnectionError, OSError):
                pass

        w = asyncio.create_task(drain_delayed())
        try:
            while True:
                chunk = await reader.read(65536)
                if not chunk:
                    break
                q.put_nowait((loop.time() + self._delay, chunk))
        except (ConnectionError, OSError):
            pass
        finally:
            q.put_nowait(None)
            try:
                await w
            except asyncio.CancelledError:
                w.cancel()
                raise
