"""Seeded chaos soak (docs/ROBUSTNESS.md, `make chaos-soak`).

Boots a REAL loopback swarm — bootstrap DHT node, N echo workers, one
consumer gateway — runs every prompt once fault-free (the control run),
then re-runs the exact same prompts under a seeded :class:`FaultPlan`
mixing every failure shape the request plane claims to survive:

- ``kill_stream`` — worker crash mid-stream (EOF, no error frame)
- ``stall_stream`` — gray failure: transport open, silence (only the
  per-stream progress watchdog can see it)
- ``slow_stream`` — a worker decoding at a fraction of its speed
- ``delay`` at first token — late TTFT, the hedged-dispatch trigger
- ``drain`` — live migration mid-stream
- ``error`` at ``host.new_stream`` — dial-plane partition flaps

and asserts the end-to-end invariants on EVERY stream:

1. byte-identical to its control run (implies zero lost tokens),
2. exactly one terminal frame, ``done_reason == "stop"`` (implies zero
   duplicated streams / no error surfaced to the client),
3. stalled-stream recovery bounded by stall budget + failover slack,
4. counter conservation: ``hedge_launched == hedge_won +
   hedge_cancelled``, internal counters == /metrics exposition,
5. the flight recorder captured a ``reason=wedged`` trace.

The schedule is SEEDED: the plan's rules fire at fixed pass indices and
the jitter RNG is seeded, so a red soak replays with the same seed.
Which concurrent stream absorbs a given fault depends on interleaving,
but every invariant above is interleaving-independent by construction.

Artifact: ``benchmarks/results/SOAK_seed<seed>.json``.

Run: ``make chaos-soak`` (wired into ``make test``) or::

    JAX_PLATFORMS=cpu python -m crowdllama_tpu.testing.soak \
        --seed 42 --streams 200 --workers 4
"""

from __future__ import annotations

import argparse
import asyncio
import json
import sys
import time
from pathlib import Path

import aiohttp

from crowdllama_tpu.config import Configuration, Intervals
from crowdllama_tpu.core.protocol import INFERENCE_PROTOCOL
from crowdllama_tpu.engine.engine import FakeEngine
from crowdllama_tpu.gateway.gateway import Gateway
from crowdllama_tpu.net.discovery import new_host_and_dht
from crowdllama_tpu.peer.peer import Peer
from crowdllama_tpu.testing import faults
from crowdllama_tpu.testing.faults import FaultPlan, FaultRule
from crowdllama_tpu.utils.crypto_compat import Ed25519PrivateKey

MODEL = "tiny-test"
STALL_MS = 500.0  # progress-watchdog budget (both phases)
HEDGE_TTFT_MS = 150.0  # hedge launch threshold
# A stalled stream must recover within the stall budget plus this much
# failover work (teardown + replay dial + re-stream + run-queue jitter).
# Generous against CI noise but far below any client-visible hang.
FAILOVER_SLACK_S = 10.0


class SoakFailure(AssertionError):
    """An invariant did not hold; the JSON artifact records which."""


def _check(report: dict, name: str, ok: bool, detail: str) -> None:
    report["invariants"].append(
        {"name": name, "ok": bool(ok), "detail": detail})
    print(f"  [{'ok' if ok else 'FAIL'}] {name}: {detail}")


def build_plan(seed: int) -> FaultPlan:
    """The mixed fault schedule, phrased as pass indices through the
    instrumented sites.  A ~10-word echo prompt crosses
    ``engine.stream_chunk`` ~11 times, so 200 streams give >2000 passes
    — every rule below is guaranteed to exhaust its ``times``."""
    return FaultPlan(seed=seed, rules=[
        # Late first tokens: delay > hedge threshold but < stall budget,
        # so the hedge plane (not the stall watchdog) absorbs them.
        FaultRule(site="engine.stream_chunk", action="delay",
                  match={"index": 0}, delay_s=0.25, after=0, times=4),
        # Dial-plane partition flaps, absorbed by the pre-stream retry.
        FaultRule(site="host.new_stream",
                  match={"protocol": INFERENCE_PROTOCOL},
                  action="error", after=10, times=3),
        # Worker crashes mid-stream.  Pinned to chunk 4 so every firing
        # is guaranteed MID-stream (tokens already delivered → the
        # token-replay failover path, not a cheap pre-stream retry), and
        # SPACED as single-shot rules: a failover replay re-crosses
        # chunk 4, so one `times=5` rule would cascade all five kills
        # onto a single stream until it ran out of workers.
        *[FaultRule(site="engine.stream_chunk", action="kill_stream",
                    match={"index": 4}, after=20 + 40 * i, times=1)
          for i in range(5)],
        # A degraded worker pacing every chunk it serves for a while.
        FaultRule(site="engine.stream_chunk", action="slow_stream",
                  delay_s=0.002, jitter_s=0.003, after=300, times=40),
        # Gray failures: silence mid-DECODE (chunk 6: the first frame is
        # long gone, so only the decode-phase watchdog can see it).
        # Spaced for the same replay-cascade reason as the kills.
        FaultRule(site="engine.stream_chunk", action="stall_stream",
                  match={"index": 6}, after=100, times=1),
        FaultRule(site="engine.stream_chunk", action="stall_stream",
                  match={"index": 6}, after=140, times=1),
        # One live migration (graceful drain mid-stream).
        FaultRule(site="engine.stream_chunk", action="drain",
                  match={"index": 2}, after=170, times=1),
    ])


async def _wait_for(cond, timeout=30.0, interval=0.1, what="condition"):
    loop = asyncio.get_running_loop()
    deadline = loop.time() + timeout
    while loop.time() < deadline:
        if cond():
            return
        await asyncio.sleep(interval)
    raise SoakFailure(f"timed out waiting for {what}")


async def _swarm(n_workers: int):
    """Bootstrap + N echo workers + consumer gateway on real loopback
    sockets (same shape as tests/test_chaos.py, package-local so the
    soak is runnable outside pytest)."""
    boot_host, _ = await new_host_and_dht(
        Ed25519PrivateKey.generate(), listen_host="127.0.0.1")
    bootstrap = f"127.0.0.1:{boot_host.listen_port}"

    def cfg():
        return Configuration(listen_host="127.0.0.1",
                             bootstrap_peers=[bootstrap],
                             intervals=Intervals.default())

    workers = [Peer(Ed25519PrivateKey.generate(), cfg(),
                    engine=FakeEngine(models=[MODEL]), worker_mode=True)
               for _ in range(n_workers)]
    for w in workers:
        await w.start()
    consumer = Peer(Ed25519PrivateKey.generate(), cfg(),
                    engine=FakeEngine(models=[]), worker_mode=False)
    await consumer.start()
    gateway = Gateway(consumer, port=0, host="127.0.0.1",
                      stream_stall_ms=STALL_MS, hedge_ttft_ms=HEDGE_TTFT_MS)
    await gateway.start()
    gw_port = gateway._runner.addresses[0][1]

    await _wait_for(
        lambda: len({p.peer_id for p in
                     consumer.peer_manager.get_healthy_peers()
                     if p.is_worker}) == n_workers,
        what=f"all {n_workers} workers discovered")

    async def teardown():
        faults.clear()
        await gateway.stop()
        await consumer.stop()
        for w in workers:
            try:
                await w.stop()
            except Exception:
                pass
        await boot_host.close()

    return workers, consumer, gateway, gw_port, teardown


async def _one_stream(session: aiohttp.ClientSession, url: str,
                      idx: int) -> dict:
    """Drive one streamed chat; return its byte content and terminal
    shape.  Never raises — a transport-level surprise is itself an
    invariant violation the phase check reports."""
    body = {"model": MODEL, "stream": True,
            "messages": [{"role": "user",
                          "content": f"soak stream {idx:03d} tell the "
                                     "swarm a story about its peers "
                                     "and pages"}]}
    t0 = time.monotonic()
    try:
        async with session.post(url, json=body) as resp:
            status = resp.status
            raw = await resp.text()
    except Exception as e:  # noqa: BLE001 — recorded, judged later
        return {"idx": idx, "status": -1, "content": "", "terminals": 0,
                "done_reason": f"transport: {e}",
                "elapsed_s": time.monotonic() - t0}
    lines = [json.loads(l) for l in raw.splitlines() if l.strip()]
    return {
        "idx": idx,
        "status": status,
        "content": "".join(l.get("message", {}).get("content", "")
                           for l in lines),
        "terminals": sum(1 for l in lines if l.get("done")),
        "done_reason": lines[-1].get("done_reason") if lines else "empty",
        "error": next((l["error"] for l in lines if "error" in l), None),
        "elapsed_s": time.monotonic() - t0,
    }


async def _phase(url: str, n_streams: int, concurrency: int) -> list[dict]:
    sem = asyncio.Semaphore(concurrency)
    conn = aiohttp.TCPConnector(limit=concurrency)
    async with aiohttp.ClientSession(connector=conn) as session:

        async def bounded(i):
            async with sem:
                return await _one_stream(session, url, i)

        return list(await asyncio.gather(
            *(bounded(i) for i in range(n_streams))))


def _judge(report: dict, control: list[dict], chaos: list[dict],
           plan: FaultPlan, gateway) -> None:
    """Apply every soak invariant; append to report['invariants']."""
    fired = {}
    for _site, _attrs, action in plan.log:
        fired[action] = fired.get(action, 0) + 1
    report["faults_fired"] = fired
    _check(report, "schedule_exhausted",
           fired.get("kill_stream") == 5 and fired.get("stall_stream") == 2
           and fired.get("drain") == 1 and fired.get("error") == 3,
           f"fired={fired}")

    bad_control = [r for r in control
                   if r["status"] != 200 or r["terminals"] != 1
                   or r["done_reason"] != "stop"]
    _check(report, "control_clean", not bad_control,
           f"{len(control) - len(bad_control)}/{len(control)} clean"
           + (f"; first bad: {bad_control[0]}" if bad_control else ""))

    bad_terminal = [r for r in chaos
                    if r["status"] != 200 or r["terminals"] != 1
                    or r["done_reason"] != "stop" or r.get("error")]
    _check(report, "exactly_one_clean_terminal_per_stream", not bad_terminal,
           f"{len(chaos) - len(bad_terminal)}/{len(chaos)} clean"
           + (f"; first bad: {bad_terminal[0]}" if bad_terminal else ""))

    by_idx = {r["idx"]: r for r in control}
    mismatched = [r["idx"] for r in chaos
                  if r["content"] != by_idx[r["idx"]]["content"]]
    _check(report, "byte_identical_zero_lost_or_dup_tokens", not mismatched,
           f"{len(chaos) - len(mismatched)}/{len(chaos)} byte-identical"
           + (f"; mismatched idx {mismatched[:5]}" if mismatched else ""))

    # Stalled-stream recovery bound: the watchdog fires at the stall
    # budget and failover replays from there — no stream, stalled or
    # not, may take longer than budget + slack.
    bound = STALL_MS / 1000.0 + FAILOVER_SLACK_S
    slowest = max(r["elapsed_s"] for r in chaos)
    report["chaos_slowest_s"] = round(slowest, 3)
    report["recovery_bound_s"] = bound
    _check(report, "stalled_recovery_bounded",
           slowest <= bound,
           f"slowest stream {slowest:.2f}s <= {bound:.2f}s "
           f"(stall {STALL_MS:.0f}ms + failover slack)")

    r = gateway._robust
    report["gateway_counters"] = {k: r[k] for k in (
        "failovers", "replayed_chunks", "stalled_streams",
        "wedge_quarantines", "hedge_launched", "hedge_won",
        "hedge_cancelled")}
    _check(report, "hedge_conservation",
           r["hedge_launched"] == r["hedge_won"] + r["hedge_cancelled"]
           and r["hedge_launched"] >= 1,
           f"launched {r['hedge_launched']} == won {r['hedge_won']} + "
           f"cancelled {r['hedge_cancelled']}")
    _check(report, "stall_watchdog_counters",
           r["stalled_streams"] == 2 and 1 <= r["wedge_quarantines"] <= 2
           and r["failovers"] >= 7,
           f"stalled {r['stalled_streams']}, quarantined "
           f"{r['wedge_quarantines']}, failovers {r['failovers']} "
           "(>= 5 kills + 2 stalls)")

    wedged_traces = [e for e in gateway.flight.snapshot()["traces"]
                     if "wedged" in e["reasons"]]
    _check(report, "flight_recorder_captured_wedged",
           len(wedged_traces) >= 1,
           f"{len(wedged_traces)} trace(s) with reason=wedged")


async def _conservation_check(report: dict, gateway, gw_port: int) -> None:
    """Internal counters must equal the /metrics exposition (a divergence
    means a counter was bumped off the render path or vice versa)."""
    async with aiohttp.ClientSession() as s:
        async with s.get(f"http://127.0.0.1:{gw_port}/metrics") as resp:
            text = await resp.text()
    exposed = {}
    for line in text.splitlines():
        if line.startswith("#") or " " not in line:
            continue
        name, _, val = line.partition(" ")
        exposed[name] = val
    r = gateway._robust
    pairs = [
        ("crowdllama_gateway_failovers_total", r["failovers"]),
        ("crowdllama_stall_aborted_streams_total", r["stalled_streams"]),
        ("crowdllama_wedge_quarantines_total", r["wedge_quarantines"]),
        ("crowdllama_hedge_launched_total", r["hedge_launched"]),
        ("crowdllama_hedge_won_total", r["hedge_won"]),
        ("crowdllama_hedge_cancelled_total", r["hedge_cancelled"]),
    ]
    diverged = [(n, exposed.get(n), v) for n, v in pairs
                if exposed.get(n) != str(v)]
    _check(report, "metrics_exposition_conserved", not diverged,
           "internal counters == /metrics" if not diverged
           else f"diverged: {diverged}")


async def run_soak(seed: int, n_streams: int, n_workers: int,
                   concurrency: int, out_dir: Path) -> dict:
    t_start = time.monotonic()
    report: dict = {"seed": seed, "streams": n_streams,
                    "workers": n_workers, "concurrency": concurrency,
                    "stall_ms": STALL_MS, "hedge_ttft_ms": HEDGE_TTFT_MS,
                    "invariants": []}
    print(f"chaos soak: seed={seed} streams={n_streams} "
          f"workers={n_workers} concurrency={concurrency}")
    workers, consumer, gateway, gw_port, teardown = await _swarm(n_workers)
    try:
        url = f"http://127.0.0.1:{gw_port}/api/chat"

        print("phase 1/2: control (fault-free baseline)...")
        t0 = time.monotonic()
        control = await _phase(url, n_streams, concurrency)
        report["control_s"] = round(time.monotonic() - t0, 2)

        print("phase 2/2: chaos (seeded mixed-fault schedule)...")
        plan = build_plan(seed)
        t0 = time.monotonic()
        with faults.installed(plan):
            chaos = await _phase(url, n_streams, concurrency)
        report["chaos_s"] = round(time.monotonic() - t0, 2)

        # The flight recorder stitches its captures asynchronously —
        # give it a bounded window before judging (the invariant check
        # below still fails hard if nothing ever lands).
        try:
            await _wait_for(
                lambda: any("wedged" in e["reasons"]
                            for e in gateway.flight.snapshot()["traces"]),
                timeout=10.0, what="flight-recorder wedged capture")
        except SoakFailure:
            pass

        print("invariants:")
        _judge(report, control, chaos, plan, gateway)
        await _conservation_check(report, gateway, gw_port)
    finally:
        await teardown()

    report["elapsed_s"] = round(time.monotonic() - t_start, 2)
    report["pass"] = all(c["ok"] for c in report["invariants"])
    out_dir.mkdir(parents=True, exist_ok=True)
    out = out_dir / f"SOAK_seed{seed}.json"
    out.write_text(json.dumps(report, indent=2, sort_keys=True) + "\n")
    print(f"{'PASS' if report['pass'] else 'FAIL'} in "
          f"{report['elapsed_s']}s — artifact: {out}")
    if not report["pass"]:
        failed = [c["name"] for c in report["invariants"] if not c["ok"]]
        raise SoakFailure(f"soak seed={seed} violated: {', '.join(failed)}")
    return report


def main(argv: list[str] | None = None) -> int:
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("--seed", type=int, default=42)
    ap.add_argument("--streams", type=int, default=200)
    # 5: two wedge quarantines + one drained worker still leave TWO
    # healthy targets, so a kill replay always has somewhere to go.
    ap.add_argument("--workers", type=int, default=5)
    ap.add_argument("--concurrency", type=int, default=16)
    ap.add_argument("--out-dir", type=Path,
                    default=Path("benchmarks/results"))
    args = ap.parse_args(argv)
    if args.workers < 3:
        ap.error("--workers must be >= 3 (two stalls quarantine two)")
    try:
        asyncio.run(run_soak(args.seed, args.streams, args.workers,
                             args.concurrency, args.out_dir))
    except SoakFailure as e:
        print(f"error: {e}", file=sys.stderr)
        return 1
    return 0


if __name__ == "__main__":
    sys.exit(main())
