"""Synthetic test-scale checkpoints with engineered decode behaviour.

Random-init tiny models have ulp-scale logit gaps, so greedy argmax flips
between numerically distinct-but-equivalent paths (prefill vs decode_step
vs the paged verify program) — any harness asserting byte-identity or
acceptance rates across paths turns into a numeric lottery.  The
generators here build weights whose margins are O(1) by construction, so
path-stable greedy decode is a property of the checkpoint, not luck.

Shared by benchmarks/spec_rtt.py and the speculative-pipeline chaos
tests; jax is imported lazily so the module stays importable from
accelerator-free test collection.
"""

from __future__ import annotations


def permutation_params(mcfg) -> dict:
    """Test-scale weights implementing a confident next-token permutation.

    Attention and MLP block outputs are zeroed (wo = w_down = 0), so the
    residual stream is exactly the input token's embedding; the
    unembedding column for pi(t) is the unit embedding of t, making
    greedy decode walk a fixed permutation cycle over the non-special
    vocabulary with O(1) logit margins — immune to cross-path argmax
    flips, never emitting EOS.  pi is verified dominant before returning.
    """
    import jax
    import jax.numpy as jnp
    import numpy as np

    from crowdllama_tpu.engine.tokenizer import get_tokenizer
    from crowdllama_tpu.models import transformer as T

    params = T.init_params(mcfg, jax.random.PRNGKey(0), dtype=jnp.float32)
    dim, vocab = mcfg.hidden_size, mcfg.vocab_size
    rng = np.random.default_rng(0)
    emb = rng.standard_normal((vocab, dim)).astype(np.float32)
    emb /= np.linalg.norm(emb, axis=1, keepdims=True)

    tok = get_tokenizer("")
    specials = sorted({tok.pad_id, tok.bos_id, tok.eos_id} - {-1})
    allowed = [t for t in range(vocab) if t not in specials]
    nxt = {t: allowed[(i + 1) % len(allowed)]
           for i, t in enumerate(allowed)}
    # Specials stay unmapped: BOS/PAD rows never drive an emitted
    # prediction (prompts end in a regular byte), and single-contributor
    # unembedding columns keep every margin wide.
    lm = np.zeros((dim, vocab), np.float32)
    for t in allowed:
        lm[:, nxt[t]] += emb[t]
    # Margin check: RMSNorm(emb[t]) @ lm must argmax at pi(t) for every
    # token that can appear in a generated sequence.
    h = emb * np.sqrt(dim)  # rows are unit vectors -> rms = 1/sqrt(dim)
    logits = h[allowed] @ lm
    assert (logits.argmax(axis=1) == np.array(
        [nxt[t] for t in allowed])).all(), "permutation not dominant"

    params["embed"] = jnp.asarray(emb)
    params["lm_head"] = jnp.asarray(lm)
    params["final_norm"] = jnp.ones((dim,), jnp.float32)
    params["layers"]["wo"] = jnp.zeros_like(params["layers"]["wo"])
    params["layers"]["w_down"] = jnp.zeros_like(params["layers"]["w_down"])
    return params


def permutation_checkpoint(model: str, out_dir, max_context: int = 256):
    """Write a native checkpoint of :func:`permutation_params` for
    ``model`` into ``out_dir`` and return its path as a string."""
    from crowdllama_tpu.engine.weights import save_params
    from crowdllama_tpu.models.config import get_config

    mcfg = get_config(model, max_context_length=max_context)
    save_params(mcfg, permutation_params(mcfg), out_dir,
                {"note": "permutation test model (testing/modelgen.py)"})
    return str(out_dir)
