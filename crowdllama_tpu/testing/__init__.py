"""Test-support machinery importable from production code paths.

Only ``faults`` lives here: deterministic fault injection hooks that are
inert (one module-global ``None`` check) unless a test installs a plan.
"""
