"""Deterministic fault injection for chaos tests (docs/ROBUSTNESS.md).

Production code calls ``await faults.inject("<site>", **attrs)`` at a few
named choke points; with no plan installed the call is a no-op costing one
module-global read.  A test installs a :class:`FaultPlan` — a seeded list
of :class:`FaultRule`s — and every rule fires at a DETERMINISTIC pass
index, so a failure like "kill the serving worker at token 3" replays
identically run after run (the seed drives only delay jitter).

Sites wired in this repo:

====================  =====================================================
site                  attrs / where
====================  =====================================================
``engine.request``    non-streamed inference entry (engine/engine.py
                      ``Engine.handle``): ``worker``, ``model``
``engine.stream_chunk``  before the worker yields chunk N of a streamed
                      response (``Engine.handle_streaming``): ``worker``,
                      ``model``, ``index``
``scheduler.ragged_chunk``  before the scheduler dispatches a unified
                      ragged prefill-chunk step (engine/scheduler.py):
                      ``done`` (prompt tokens already in the pool),
                      ``total`` (prompt length) — the mid-chunked-prefill
                      drain trigger (docs/RAGGED_BATCH.md)
``host.new_stream``   before a dial + handshake (net/host.py): ``peer``
                      (empty for bare addresses), ``protocol``
``relay.op``          relay service op dispatch (net/relay.py): ``op``
``relay.splice``      before a relay starts its bidirectional copy loop
``kv.fetch``          before a worker dials a KV-page donor
                      (engine/engine.py ``_kv_fetch_once``): ``worker``,
                      ``donor``
``kv.serve``          donor side, before a KvFetchRequest is served
                      (peer.py ``_serve_kv_fetch``): ``worker`` (the
                      donor), ``model``
``gossip.send``       before a gateway replica pushes an anti-entropy
                      frame (swarm/gossip.py ``GossipNode._exchange``):
                      ``src`` (sender peer id), ``dst`` (target address)
``gossip.recv``       before an inbound gossip frame is merged
                      (``GossipNode.handle_frame``): ``src`` (origin peer
                      id), ``dst`` (receiver peer id).  A partition is a
                      pair of ``error`` rules matching both directions;
                      ``delay`` models gossip latency.
``spec.draft_chunk``  after the worker's remote-draft reader task takes a
                      DraftChunk off the stream (peer/peer.py
                      ``_read_draft_chunks``): ``worker``, ``chunk_id``
``spec.verify``       before a VerifyResult frame is written — the engine's
                      verify emission (engine/engine.py
                      ``handle_streaming_frames``) and the peer's
                      unsupported-engine nack: ``worker``, ``chunk_id``.
                      ``kill_stream`` here is the mid-verify worker death
                      the failover chaos test drives.
====================  =====================================================

Actions:

- ``"error"`` — raise :class:`FaultError` (a generic failure the caller's
  normal error handling sees: failed dial, failed request, ...).
- ``"kill_stream"`` — raise :class:`KillStream`.  The worker's serve loop
  treats it specially: it closes the transport WITHOUT writing an error
  frame, which is exactly what a crashed worker process looks like from
  the gateway (mid-stream EOF) — the trigger for mid-stream failover.
- ``"delay"`` — ``asyncio.sleep(delay_s + seeded jitter)`` then continue.
- ``"drain"`` — raise :class:`DrainRequested`.  Only meaningful at
  ``engine.stream_chunk`` and ``scheduler.ragged_chunk``: the worker
  reacts by starting its own graceful drain (as if SIGTERM / POST /drain
  arrived mid-stream, or mid-chunked-prefill) and the request continues
  until the scheduler hands it off with a MigrateFrame — the chaos
  trigger for live request migration (docs/ROBUSTNESS.md).
- ``"stall_stream"`` — raise :class:`StallStream`.  The worker's serve
  loop holds the transport OPEN but never writes another frame: the gray
  failure.  Unlike ``kill_stream`` there is no EOF to react to — only the
  gateway's per-stream progress watchdog (``--stream-stall-ms``,
  docs/ROBUSTNESS.md) notices, tears the stream down, and fails over.
- ``"slow_stream"`` — ``asyncio.sleep(delay_s + seeded jitter)`` then
  continue, like ``delay`` but intended with ``times=0`` on a stream
  site: every chunk is paced, modeling a worker decoding at a fraction
  of its normal speed (the second gray-failure shape).

Usage::

    plan = FaultPlan(seed=42, rules=[
        FaultRule(site="engine.stream_chunk", action="kill_stream",
                  after=3, times=1),
    ])
    with faults.installed(plan):
        ... drive a request ...
    assert plan.log  # fired events, in order
"""

from __future__ import annotations

import asyncio
import random
from contextlib import contextmanager
from dataclasses import dataclass, field


# The site registry: every choke point production code instruments with
# ``await faults.inject("<site>", ...)``, with a one-line description.
# FaultRule rejects unknown names at plan-build time, so a typo'd site in
# a chaos test fails loudly instead of silently never firing; swarmlint
# (crowdllama_tpu/analysis/contracts.py) cross-checks this dict against
# the inject call sites actually present in code, both directions.
FAULT_SITES: dict[str, str] = {
    "engine.request": "non-streamed inference entry (engine/engine.py)",
    "engine.stream_chunk": "before the worker yields chunk N of a stream",
    "scheduler.ragged_chunk": "before a unified ragged prefill-chunk step",
    "host.new_stream": "before a dial + handshake (net/host.py)",
    "relay.op": "relay service op dispatch (net/relay.py)",
    "relay.splice": "before a relay starts its bidirectional copy loop",
    "kv.fetch": "before a worker dials a KV-page donor",
    "kv.serve": "donor side, before a KvFetchRequest is served",
    "gossip.send": "before a gateway replica pushes an anti-entropy frame",
    "gossip.recv": "before an inbound gossip frame is merged",
    "obs.scrape": "before the gateway fetches one worker's metric snapshot",
    "spec.draft_chunk": "after the worker reads a DraftChunk off a "
                        "remote-draft stream (peer/peer.py)",
    "spec.verify": "before a VerifyResult frame is written (engine "
                   "emission and the peer's unsupported-engine nack)",
}


class FaultError(RuntimeError):
    """An injected failure (generic: dial failed, request failed, ...)."""


class KillStream(FaultError):
    """Injected hard death: the serving side must drop the transport with
    no error frame, so the peer observes an unexplained EOF."""


class DrainRequested(FaultError):
    """Injected graceful drain: the worker catching it starts its own
    drain (equivalent to SIGTERM / POST /drain landing mid-stream) and
    keeps streaming until the scheduler migrates the request."""


class StallStream(FaultError):
    """Injected gray failure: the serving side must hold the transport
    open but never write another frame — no EOF, no error, just silence.
    Only a progress watchdog on the consuming side can detect it."""


@dataclass
class FaultRule:
    """One deterministic trigger: fires at pass index >= ``after`` through
    its ``site`` (counting only passes whose attrs satisfy ``match``), at
    most ``times`` times (0 = unlimited)."""

    site: str
    # "error" | "kill_stream" | "delay" | "drain" | "stall_stream"
    # | "slow_stream"
    action: str = "error"
    match: dict = field(default_factory=dict)
    after: int = 0
    times: int = 1
    delay_s: float = 0.0
    jitter_s: float = 0.0  # extra seeded-uniform delay on "delay"
    message: str = "injected fault"
    # Runtime state (owned by the plan; reset by FaultPlan.reset()).
    passes: int = 0
    fired: int = 0

    def __post_init__(self) -> None:
        if self.site not in FAULT_SITES:
            raise ValueError(
                f"unknown fault site {self.site!r} — registered sites: "
                f"{', '.join(sorted(FAULT_SITES))} (see FAULT_SITES in "
                "testing/faults.py; a typo here would silently never fire)")
        if self.action not in ("error", "kill_stream", "delay", "drain",
                               "stall_stream", "slow_stream"):
            raise ValueError(f"unknown fault action {self.action!r}")


class FaultPlan:
    """A seeded, replayable set of fault rules.

    ``log`` records every fired event as ``(site, attrs, action)`` in
    firing order — tests assert on it to prove the plan did what the
    scenario claims."""

    def __init__(self, seed: int = 0, rules: list[FaultRule] | None = None):
        self.seed = seed
        self.rules: list[FaultRule] = list(rules or [])
        self._rng = random.Random(seed)
        self.log: list[tuple[str, dict, str]] = []

    def reset(self) -> None:
        """Rewind pass/fire counters and the jitter RNG to t=0."""
        self._rng = random.Random(self.seed)
        self.log.clear()
        for rule in self.rules:
            rule.passes = 0
            rule.fired = 0

    async def inject(self, site: str, **attrs) -> None:
        for rule in self.rules:
            if rule.site != site:
                continue
            if any(attrs.get(k) != v for k, v in rule.match.items()):
                continue
            idx = rule.passes
            rule.passes += 1
            if idx < rule.after:
                continue
            if rule.times and rule.fired >= rule.times:
                continue
            rule.fired += 1
            self.log.append((site, dict(attrs), rule.action))
            if rule.action in ("delay", "slow_stream"):
                jitter = (self._rng.uniform(0, rule.jitter_s)
                          if rule.jitter_s else 0.0)
                await asyncio.sleep(rule.delay_s + jitter)
            elif rule.action == "kill_stream":
                raise KillStream(f"{rule.message} @ {site}")
            elif rule.action == "drain":
                raise DrainRequested(f"{rule.message} @ {site}")
            elif rule.action == "stall_stream":
                raise StallStream(f"{rule.message} @ {site}")
            else:
                raise FaultError(f"{rule.message} @ {site}")


_active: FaultPlan | None = None


def install(plan: FaultPlan) -> FaultPlan:
    global _active
    _active = plan
    return plan


def clear() -> None:
    global _active
    _active = None


def active() -> FaultPlan | None:
    return _active


@contextmanager
def installed(plan: FaultPlan):
    """``with faults.installed(plan): ...`` — install for the block, always
    clear after (a leaked plan would fail unrelated tests)."""
    install(plan)
    try:
        yield plan
    finally:
        clear()


async def inject(site: str, **attrs) -> None:
    """The production-side hook: no-op unless a plan is installed."""
    plan = _active
    if plan is not None:
        await plan.inject(site, **attrs)
