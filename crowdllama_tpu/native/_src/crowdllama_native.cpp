// Native runtime components for crowdllama-tpu.
//
// The reference implementation's runtime (wire framing, Kademlia routing)
// is compiled Go (/root/reference/pkg/crowdllama/pbwire.go, go-libp2p-kad-dht);
// these are the TPU-framework equivalents in C++, loaded via ctypes with a
// pure-Python fallback (crowdllama_tpu/native/__init__.py).
//
// Exposed C ABI:
//   - cl_frame_scan:   batch-scan length-prefixed frames in a buffer
//   - cl_rt_*:         256-bucket XOR-metric Kademlia routing table
//
// The routing table mirrors net/dht.py's semantics bit-for-bit: bucket index
// is bit_length(xor(self, id)) - 1, buckets hold at most k entries ordered
// least-recently-seen first, refresh moves an entry to the back, insertion
// into a full bucket evicts the front (LRS).

#include <algorithm>
#include <array>
#include <cstdint>
#include <cstring>
#include <vector>

namespace {

constexpr int kIdBytes = 32;
constexpr int kIdBits = kIdBytes * 8;

using Id = std::array<uint8_t, kIdBytes>;

Id make_id(const uint8_t* p) {
    Id id;
    std::memcpy(id.data(), p, kIdBytes);
    return id;
}

Id xor_id(const Id& a, const Id& b) {
    Id out;
    for (int i = 0; i < kIdBytes; ++i) out[i] = a[i] ^ b[i];
    return out;
}

// bit_length(xor) - 1, i.e. index of the highest set bit (0-based from the
// least significant end), or 0 for a zero distance — matches
// net/dht.py RoutingTable._bucket_index.
int bucket_index(const Id& d) {
    for (int byte = 0; byte < kIdBytes; ++byte) {
        if (d[byte] != 0) {
            int msb = 31 - __builtin_clz(static_cast<uint32_t>(d[byte]));
            return (kIdBytes - 1 - byte) * 8 + msb;
        }
    }
    return 0;
}

// Big-endian lexicographic compare == numeric compare of 256-bit ints.
bool id_less(const Id& a, const Id& b) {
    return std::memcmp(a.data(), b.data(), kIdBytes) < 0;
}

struct RoutingTable {
    Id self_id;
    int k;
    std::vector<std::vector<Id>> buckets;

    RoutingTable(const Id& self, int kk) : self_id(self), k(kk), buckets(kIdBits) {}
};

}  // namespace

extern "C" {

// Scan `buf[0:len)` for complete [4-byte BE length][payload] frames.
// Writes payload offsets/sizes for up to `max_frames` frames, sets
// `*consumed` to the total bytes of the frames returned, and returns the
// frame count.  Returns -1 if any frame declares a length > max_size
// (protocol violation; connection should be dropped).
long cl_frame_scan(const uint8_t* buf, size_t len, uint32_t max_size,
                   uint32_t* offsets, uint32_t* sizes, size_t max_frames,
                   size_t* consumed) {
    size_t pos = 0;
    long n = 0;
    while (static_cast<size_t>(n) < max_frames && pos + 4 <= len) {
        uint32_t frame_len = (static_cast<uint32_t>(buf[pos]) << 24) |
                             (static_cast<uint32_t>(buf[pos + 1]) << 16) |
                             (static_cast<uint32_t>(buf[pos + 2]) << 8) |
                             static_cast<uint32_t>(buf[pos + 3]);
        if (frame_len > max_size) return -1;
        if (pos + 4 + frame_len > len) break;  // incomplete frame
        offsets[n] = static_cast<uint32_t>(pos + 4);
        sizes[n] = frame_len;
        pos += 4 + frame_len;
        ++n;
    }
    *consumed = pos;
    return n;
}

void* cl_rt_new(const uint8_t* self_id, int k) {
    return new RoutingTable(make_id(self_id), k);
}

void cl_rt_free(void* h) { delete static_cast<RoutingTable*>(h); }

// Insert or refresh `id`.  Returns 0 if id == self (ignored), 1 otherwise.
// When a full bucket evicts its least-recently-seen entry, the evicted id is
// written to evicted_out and *evicted is set to 1 (else 0).
int cl_rt_upsert(void* h, const uint8_t* id_bytes, uint8_t* evicted_out,
                 int* evicted) {
    auto* rt = static_cast<RoutingTable*>(h);
    *evicted = 0;
    Id id = make_id(id_bytes);
    if (id == rt->self_id) return 0;
    auto& bucket = rt->buckets[bucket_index(xor_id(rt->self_id, id))];
    for (size_t i = 0; i < bucket.size(); ++i) {
        if (bucket[i] == id) {  // refresh: move to most-recently-seen
            bucket.erase(bucket.begin() + i);
            bucket.push_back(id);
            return 1;
        }
    }
    if (static_cast<int>(bucket.size()) >= rt->k) {
        std::memcpy(evicted_out, bucket.front().data(), kIdBytes);
        *evicted = 1;
        bucket.erase(bucket.begin());
    }
    bucket.push_back(id);
    return 1;
}

int cl_rt_remove(void* h, const uint8_t* id_bytes) {
    auto* rt = static_cast<RoutingTable*>(h);
    Id id = make_id(id_bytes);
    auto& bucket = rt->buckets[bucket_index(xor_id(rt->self_id, id))];
    for (size_t i = 0; i < bucket.size(); ++i) {
        if (bucket[i] == id) {
            bucket.erase(bucket.begin() + i);
            return 1;
        }
    }
    return 0;
}

long cl_rt_size(void* h) {
    auto* rt = static_cast<RoutingTable*>(h);
    long n = 0;
    for (const auto& b : rt->buckets) n += static_cast<long>(b.size());
    return n;
}

// Write the (up to) `k` ids closest to `target` (by XOR distance) into
// `out` (k * 32 bytes), sorted nearest first.  Returns the count written.
long cl_rt_closest(void* h, const uint8_t* target_bytes, int k, uint8_t* out) {
    auto* rt = static_cast<RoutingTable*>(h);
    Id target = make_id(target_bytes);

    std::vector<std::pair<Id, Id>> all;  // (distance, id)
    all.reserve(64);
    for (const auto& b : rt->buckets)
        for (const auto& id : b) all.emplace_back(xor_id(id, target), id);

    size_t kk = std::min<size_t>(k, all.size());
    std::partial_sort(all.begin(), all.begin() + kk, all.end(),
                      [](const auto& a, const auto& b) {
                          return id_less(a.first, b.first);
                      });
    for (size_t i = 0; i < kk; ++i)
        std::memcpy(out + i * kIdBytes, all[i].second.data(), kIdBytes);
    return static_cast<long>(kk);
}

// Dump every id (bucket order, LRS first within a bucket).  Returns count,
// or -1 if `cap` (in ids) is too small.
long cl_rt_dump(void* h, uint8_t* out, long cap) {
    auto* rt = static_cast<RoutingTable*>(h);
    long n = 0;
    for (const auto& b : rt->buckets) {
        for (const auto& id : b) {
            if (n >= cap) return -1;
            std::memcpy(out + n * kIdBytes, id.data(), kIdBytes);
            ++n;
        }
    }
    return n;
}

}  // extern "C"
