// Native runtime components for crowdllama-tpu.
//
// The reference implementation's runtime (wire framing, Kademlia routing)
// is compiled Go (/root/reference/pkg/crowdllama/pbwire.go, go-libp2p-kad-dht);
// these are the TPU-framework equivalents in C++, loaded via ctypes with a
// pure-Python fallback (crowdllama_tpu/native/__init__.py).
//
// Exposed C ABI:
//   - cl_frame_scan:   batch-scan length-prefixed frames in a buffer
//   - cl_rt_*:         256-bucket XOR-metric Kademlia routing table
//   - cl_aead_*:       per-session AEAD seal/open with internal 96-bit
//                      big-endian nonce counters (docs/NATIVE.md).  Two
//                      flavors: 0 = the compat encrypt-then-MAC scheme
//                      (SHAKE-256 XOF keystream + HMAC-SHA256/128 tag,
//                      byte-identical to utils/crypto_compat.py), 1 =
//                      ChaCha20-Poly1305 (RFC 8439, byte-identical to the
//                      `cryptography` package net/secure.py uses when
//                      installed).
//   - cl_env_*:        llama.v1 envelope fast paths for the per-chunk arms
//                      (GenerateRequest / GenerateResponse): encode writes
//                      a complete [4-byte BE length][BaseMessage] wire
//                      frame into a caller buffer, byte-identical to
//                      upb's SerializeToString (proto3 skip-defaults,
//                      ascending field order); decode fills a flat struct
//                      of offsets/scalars, returning 0 for any shape it
//                      is not SURE about so the caller falls back to the
//                      real parser with identical semantics.
//
// The routing table mirrors net/dht.py's semantics bit-for-bit: bucket index
// is bit_length(xor(self, id)) - 1, buckets hold at most k entries ordered
// least-recently-seen first, refresh moves an entry to the back, insertion
// into a full bucket evicts the front (LRS).

#include <algorithm>
#include <array>
#include <cstdint>
#include <cstring>
#include <vector>

namespace {

constexpr int kIdBytes = 32;
constexpr int kIdBits = kIdBytes * 8;

using Id = std::array<uint8_t, kIdBytes>;

Id make_id(const uint8_t* p) {
    Id id;
    std::memcpy(id.data(), p, kIdBytes);
    return id;
}

Id xor_id(const Id& a, const Id& b) {
    Id out;
    for (int i = 0; i < kIdBytes; ++i) out[i] = a[i] ^ b[i];
    return out;
}

// bit_length(xor) - 1, i.e. index of the highest set bit (0-based from the
// least significant end), or 0 for a zero distance — matches
// net/dht.py RoutingTable._bucket_index.
int bucket_index(const Id& d) {
    for (int byte = 0; byte < kIdBytes; ++byte) {
        if (d[byte] != 0) {
            int msb = 31 - __builtin_clz(static_cast<uint32_t>(d[byte]));
            return (kIdBytes - 1 - byte) * 8 + msb;
        }
    }
    return 0;
}

// Big-endian lexicographic compare == numeric compare of 256-bit ints.
bool id_less(const Id& a, const Id& b) {
    return std::memcmp(a.data(), b.data(), kIdBytes) < 0;
}

struct RoutingTable {
    Id self_id;
    int k;
    std::vector<std::vector<Id>> buckets;

    RoutingTable(const Id& self, int kk) : self_id(self), k(kk), buckets(kIdBits) {}
};

// ===================================================================
// Crypto primitives (AEAD data plane).  Self-contained implementations —
// the container has no OpenSSL dev headers; correctness is pinned by
// byte-identity tests against hashlib/hmac (compat flavor) and the RFC
// 8439 vectors (ChaCha20-Poly1305 flavor) in tests/test_native.py.
// ===================================================================

// ----------------------------------------------------------- SHA-256

struct Sha256 {
    uint32_t h[8];
    uint64_t nbytes;
    uint8_t buf[64];
    size_t buflen;
};

constexpr uint32_t kSha256K[64] = {
    0x428a2f98, 0x71374491, 0xb5c0fbcf, 0xe9b5dba5, 0x3956c25b, 0x59f111f1,
    0x923f82a4, 0xab1c5ed5, 0xd807aa98, 0x12835b01, 0x243185be, 0x550c7dc3,
    0x72be5d74, 0x80deb1fe, 0x9bdc06a7, 0xc19bf174, 0xe49b69c1, 0xefbe4786,
    0x0fc19dc6, 0x240ca1cc, 0x2de92c6f, 0x4a7484aa, 0x5cb0a9dc, 0x76f988da,
    0x983e5152, 0xa831c66d, 0xb00327c8, 0xbf597fc7, 0xc6e00bf3, 0xd5a79147,
    0x06ca6351, 0x14292967, 0x27b70a85, 0x2e1b2138, 0x4d2c6dfc, 0x53380d13,
    0x650a7354, 0x766a0abb, 0x81c2c92e, 0x92722c85, 0xa2bfe8a1, 0xa81a664b,
    0xc24b8b70, 0xc76c51a3, 0xd192e819, 0xd6990624, 0xf40e3585, 0x106aa070,
    0x19a4c116, 0x1e376c08, 0x2748774c, 0x34b0bcb5, 0x391c0cb3, 0x4ed8aa4a,
    0x5b9cca4f, 0x682e6ff3, 0x748f82ee, 0x78a5636f, 0x84c87814, 0x8cc70208,
    0x90befffa, 0xa4506ceb, 0xbef9a3f7, 0xc67178f2};

inline uint32_t rotr32(uint32_t x, int n) { return (x >> n) | (x << (32 - n)); }

void sha256_init(Sha256* s) {
    static constexpr uint32_t iv[8] = {0x6a09e667, 0xbb67ae85, 0x3c6ef372,
                                       0xa54ff53a, 0x510e527f, 0x9b05688c,
                                       0x1f83d9ab, 0x5be0cd19};
    std::memcpy(s->h, iv, sizeof(iv));
    s->nbytes = 0;
    s->buflen = 0;
}

void sha256_compress(Sha256* s, const uint8_t* p) {
    uint32_t w[64];
    for (int i = 0; i < 16; ++i)
        w[i] = (static_cast<uint32_t>(p[4 * i]) << 24) |
               (static_cast<uint32_t>(p[4 * i + 1]) << 16) |
               (static_cast<uint32_t>(p[4 * i + 2]) << 8) |
               static_cast<uint32_t>(p[4 * i + 3]);
    for (int i = 16; i < 64; ++i) {
        uint32_t s0 = rotr32(w[i - 15], 7) ^ rotr32(w[i - 15], 18) ^ (w[i - 15] >> 3);
        uint32_t s1 = rotr32(w[i - 2], 17) ^ rotr32(w[i - 2], 19) ^ (w[i - 2] >> 10);
        w[i] = w[i - 16] + s0 + w[i - 7] + s1;
    }
    uint32_t a = s->h[0], b = s->h[1], c = s->h[2], d = s->h[3];
    uint32_t e = s->h[4], f = s->h[5], g = s->h[6], h = s->h[7];
    for (int i = 0; i < 64; ++i) {
        uint32_t S1 = rotr32(e, 6) ^ rotr32(e, 11) ^ rotr32(e, 25);
        uint32_t ch = (e & f) ^ (~e & g);
        uint32_t t1 = h + S1 + ch + kSha256K[i] + w[i];
        uint32_t S0 = rotr32(a, 2) ^ rotr32(a, 13) ^ rotr32(a, 22);
        uint32_t maj = (a & b) ^ (a & c) ^ (b & c);
        uint32_t t2 = S0 + maj;
        h = g; g = f; f = e; e = d + t1;
        d = c; c = b; b = a; a = t1 + t2;
    }
    s->h[0] += a; s->h[1] += b; s->h[2] += c; s->h[3] += d;
    s->h[4] += e; s->h[5] += f; s->h[6] += g; s->h[7] += h;
}

void sha256_update(Sha256* s, const uint8_t* p, size_t n) {
    s->nbytes += n;
    if (s->buflen) {
        size_t take = std::min<size_t>(64 - s->buflen, n);
        std::memcpy(s->buf + s->buflen, p, take);
        s->buflen += take;
        p += take;
        n -= take;
        if (s->buflen == 64) {
            sha256_compress(s, s->buf);
            s->buflen = 0;
        }
    }
    while (n >= 64) {
        sha256_compress(s, p);
        p += 64;
        n -= 64;
    }
    if (n) {
        std::memcpy(s->buf, p, n);
        s->buflen = n;
    }
}

void sha256_final(Sha256* s, uint8_t out[32]) {
    uint64_t bits = s->nbytes * 8;
    uint8_t pad = 0x80;
    sha256_update(s, &pad, 1);
    uint8_t zero = 0;
    while (s->buflen != 56) sha256_update(s, &zero, 1);
    uint8_t len[8];
    for (int i = 0; i < 8; ++i) len[i] = static_cast<uint8_t>(bits >> (56 - 8 * i));
    sha256_update(s, len, 8);
    for (int i = 0; i < 8; ++i) {
        out[4 * i] = static_cast<uint8_t>(s->h[i] >> 24);
        out[4 * i + 1] = static_cast<uint8_t>(s->h[i] >> 16);
        out[4 * i + 2] = static_cast<uint8_t>(s->h[i] >> 8);
        out[4 * i + 3] = static_cast<uint8_t>(s->h[i]);
    }
}

// HMAC-SHA256 with the padded-key block states precomputed once per
// session — the per-frame cost is two copies + the message compression,
// the same pooling trick crypto_compat applies on the Python side.
struct Hmac256 {
    Sha256 inner_base;
    Sha256 outer_base;
};

void hmac256_init(Hmac256* m, const uint8_t* key, size_t keylen) {
    uint8_t k[64] = {0};
    if (keylen > 64) {
        Sha256 s;
        sha256_init(&s);
        sha256_update(&s, key, keylen);
        sha256_final(&s, k);
    } else {
        std::memcpy(k, key, keylen);
    }
    uint8_t pad[64];
    for (int i = 0; i < 64; ++i) pad[i] = k[i] ^ 0x36;
    sha256_init(&m->inner_base);
    sha256_update(&m->inner_base, pad, 64);
    for (int i = 0; i < 64; ++i) pad[i] = k[i] ^ 0x5c;
    sha256_init(&m->outer_base);
    sha256_update(&m->outer_base, pad, 64);
}

void hmac256_tag(const Hmac256* m, const uint8_t* p1, size_t n1,
                 const uint8_t* p2, size_t n2, uint8_t out[32]) {
    Sha256 s = m->inner_base;
    if (n1) sha256_update(&s, p1, n1);
    if (n2) sha256_update(&s, p2, n2);
    uint8_t digest[32];
    sha256_final(&s, digest);
    s = m->outer_base;
    sha256_update(&s, digest, 32);
    sha256_final(&s, out);
}

// ----------------------------------------- SHAKE-256 (Keccak-f[1600])

inline uint64_t rotl64(uint64_t x, int n) { return (x << n) | (x >> (64 - n)); }

constexpr uint64_t kKeccakRC[24] = {
    0x0000000000000001ULL, 0x0000000000008082ULL, 0x800000000000808aULL,
    0x8000000080008000ULL, 0x000000000000808bULL, 0x0000000080000001ULL,
    0x8000000080008081ULL, 0x8000000000008009ULL, 0x000000000000008aULL,
    0x0000000000000088ULL, 0x0000000080008009ULL, 0x000000008000000aULL,
    0x000000008000808bULL, 0x800000000000008bULL, 0x8000000000008089ULL,
    0x8000000000008003ULL, 0x8000000000008002ULL, 0x8000000000000080ULL,
    0x000000000000800aULL, 0x800000008000000aULL, 0x8000000080008081ULL,
    0x8000000000008080ULL, 0x0000000080000001ULL, 0x8000000080008008ULL};

void keccakf(uint64_t st[25]) {
    static constexpr int R[24] = {1, 3, 6, 10, 15, 21, 28, 36, 45, 55, 2, 14,
                                  27, 41, 56, 8, 25, 43, 62, 18, 39, 61, 20, 44};
    static constexpr int P[24] = {10, 7, 11, 17, 18, 3, 5, 16, 8, 21, 24, 4,
                                  15, 23, 19, 13, 12, 2, 20, 14, 22, 9, 6, 1};
    for (int round = 0; round < 24; ++round) {
        uint64_t bc[5], t;
        for (int i = 0; i < 5; ++i)
            bc[i] = st[i] ^ st[i + 5] ^ st[i + 10] ^ st[i + 15] ^ st[i + 20];
        for (int i = 0; i < 5; ++i) {
            t = bc[(i + 4) % 5] ^ rotl64(bc[(i + 1) % 5], 1);
            for (int j = 0; j < 25; j += 5) st[j + i] ^= t;
        }
        t = st[1];
        for (int i = 0; i < 24; ++i) {
            int j = P[i];
            bc[0] = st[j];
            st[j] = rotl64(t, R[i]);
            t = bc[0];
        }
        for (int j = 0; j < 25; j += 5) {
            for (int i = 0; i < 5; ++i) bc[i] = st[j + i];
            for (int i = 0; i < 5; ++i)
                st[j + i] = bc[i] ^ (~bc[(i + 1) % 5] & bc[(i + 2) % 5]);
        }
        st[0] ^= kKeccakRC[round];
    }
}

constexpr size_t kShakeRate = 136;  // SHAKE-256

// shake_256(p1 || p2 || p3).digest(outlen) — the three segments cover the
// compat keystream's prefix || key || nonce absorb without concatenation.
void shake256_xof(const uint8_t* p1, size_t n1, const uint8_t* p2, size_t n2,
                  const uint8_t* p3, size_t n3, uint8_t* out, size_t outlen) {
    uint64_t st[25] = {0};
    uint8_t block[kShakeRate];
    size_t fill = 0;
    const uint8_t* parts[3] = {p1, p2, p3};
    size_t lens[3] = {n1, n2, n3};
    for (int k = 0; k < 3; ++k) {
        const uint8_t* p = parts[k];
        size_t n = lens[k];
        while (n) {
            size_t take = std::min(kShakeRate - fill, n);
            std::memcpy(block + fill, p, take);
            fill += take;
            p += take;
            n -= take;
            if (fill == kShakeRate) {
                for (size_t i = 0; i < kShakeRate / 8; ++i) {
                    uint64_t lane;
                    std::memcpy(&lane, block + 8 * i, 8);
                    st[i] ^= lane;
                }
                keccakf(st);
                fill = 0;
            }
        }
    }
    std::memset(block + fill, 0, kShakeRate - fill);
    block[fill] ^= 0x1f;
    block[kShakeRate - 1] ^= 0x80;
    for (size_t i = 0; i < kShakeRate / 8; ++i) {
        uint64_t lane;
        std::memcpy(&lane, block + 8 * i, 8);
        st[i] ^= lane;
    }
    while (outlen) {
        keccakf(st);
        size_t take = std::min(kShakeRate, outlen);
        std::memcpy(out, st, take);
        out += take;
        outlen -= take;
    }
}

// -------------------------------------------- ChaCha20 (RFC 8439 §2.3)

inline uint32_t le32(const uint8_t* p) {
    return static_cast<uint32_t>(p[0]) | (static_cast<uint32_t>(p[1]) << 8) |
           (static_cast<uint32_t>(p[2]) << 16) |
           (static_cast<uint32_t>(p[3]) << 24);
}

inline uint32_t rotl32(uint32_t x, int n) { return (x << n) | (x >> (32 - n)); }

void chacha20_block(const uint8_t key[32], uint32_t counter,
                    const uint8_t nonce[12], uint8_t out[64]) {
    uint32_t st[16];
    st[0] = 0x61707865; st[1] = 0x3320646e; st[2] = 0x79622d32; st[3] = 0x6b206574;
    for (int i = 0; i < 8; ++i) st[4 + i] = le32(key + 4 * i);
    st[12] = counter;
    for (int i = 0; i < 3; ++i) st[13 + i] = le32(nonce + 4 * i);
    uint32_t x[16];
    std::memcpy(x, st, sizeof(st));
    for (int i = 0; i < 10; ++i) {
#define CL_QR(a, b, c, d)                                   \
    x[a] += x[b]; x[d] ^= x[a]; x[d] = rotl32(x[d], 16);    \
    x[c] += x[d]; x[b] ^= x[c]; x[b] = rotl32(x[b], 12);    \
    x[a] += x[b]; x[d] ^= x[a]; x[d] = rotl32(x[d], 8);     \
    x[c] += x[d]; x[b] ^= x[c]; x[b] = rotl32(x[b], 7);
        CL_QR(0, 4, 8, 12) CL_QR(1, 5, 9, 13) CL_QR(2, 6, 10, 14) CL_QR(3, 7, 11, 15)
        CL_QR(0, 5, 10, 15) CL_QR(1, 6, 11, 12) CL_QR(2, 7, 8, 13) CL_QR(3, 4, 9, 14)
#undef CL_QR
    }
    for (int i = 0; i < 16; ++i) {
        uint32_t v = x[i] + st[i];
        out[4 * i] = static_cast<uint8_t>(v);
        out[4 * i + 1] = static_cast<uint8_t>(v >> 8);
        out[4 * i + 2] = static_cast<uint8_t>(v >> 16);
        out[4 * i + 3] = static_cast<uint8_t>(v >> 24);
    }
}

void chacha20_xor(const uint8_t key[32], uint32_t counter,
                  const uint8_t nonce[12], const uint8_t* in, uint8_t* out,
                  size_t len) {
    uint8_t block[64];
    while (len) {
        chacha20_block(key, counter++, nonce, block);
        size_t take = std::min<size_t>(64, len);
        for (size_t i = 0; i < take; ++i) out[i] = in[i] ^ block[i];
        in += take;
        out += take;
        len -= take;
    }
}

// ------------------------------------------- Poly1305 (RFC 8439 §2.5)

struct Poly1305 {
    uint32_t r[5];
    uint32_t h[5];
    uint32_t pad[4];
    size_t leftover;
    uint8_t buffer[16];
    int final_;
};

void poly1305_init(Poly1305* st, const uint8_t key[32]) {
    st->r[0] = le32(key + 0) & 0x3ffffff;
    st->r[1] = (le32(key + 3) >> 2) & 0x3ffff03;
    st->r[2] = (le32(key + 6) >> 4) & 0x3ffc0ff;
    st->r[3] = (le32(key + 9) >> 6) & 0x3f03fff;
    st->r[4] = (le32(key + 12) >> 8) & 0x00fffff;
    for (int i = 0; i < 5; ++i) st->h[i] = 0;
    for (int i = 0; i < 4; ++i) st->pad[i] = le32(key + 16 + 4 * i);
    st->leftover = 0;
    st->final_ = 0;
}

void poly1305_blocks(Poly1305* st, const uint8_t* m, size_t bytes) {
    const uint32_t hibit = st->final_ ? 0 : (1UL << 24);
    uint32_t r0 = st->r[0], r1 = st->r[1], r2 = st->r[2], r3 = st->r[3], r4 = st->r[4];
    uint32_t s1 = r1 * 5, s2 = r2 * 5, s3 = r3 * 5, s4 = r4 * 5;
    uint32_t h0 = st->h[0], h1 = st->h[1], h2 = st->h[2], h3 = st->h[3], h4 = st->h[4];
    while (bytes >= 16) {
        h0 += le32(m + 0) & 0x3ffffff;
        h1 += (le32(m + 3) >> 2) & 0x3ffffff;
        h2 += (le32(m + 6) >> 4) & 0x3ffffff;
        h3 += (le32(m + 9) >> 6) & 0x3ffffff;
        h4 += (le32(m + 12) >> 8) | hibit;
        uint64_t d0 = (uint64_t)h0 * r0 + (uint64_t)h1 * s4 + (uint64_t)h2 * s3 +
                      (uint64_t)h3 * s2 + (uint64_t)h4 * s1;
        uint64_t d1 = (uint64_t)h0 * r1 + (uint64_t)h1 * r0 + (uint64_t)h2 * s4 +
                      (uint64_t)h3 * s3 + (uint64_t)h4 * s2;
        uint64_t d2 = (uint64_t)h0 * r2 + (uint64_t)h1 * r1 + (uint64_t)h2 * r0 +
                      (uint64_t)h3 * s4 + (uint64_t)h4 * s3;
        uint64_t d3 = (uint64_t)h0 * r3 + (uint64_t)h1 * r2 + (uint64_t)h2 * r1 +
                      (uint64_t)h3 * r0 + (uint64_t)h4 * s4;
        uint64_t d4 = (uint64_t)h0 * r4 + (uint64_t)h1 * r3 + (uint64_t)h2 * r2 +
                      (uint64_t)h3 * r1 + (uint64_t)h4 * r0;
        uint32_t c = (uint32_t)(d0 >> 26); h0 = (uint32_t)d0 & 0x3ffffff;
        d1 += c; c = (uint32_t)(d1 >> 26); h1 = (uint32_t)d1 & 0x3ffffff;
        d2 += c; c = (uint32_t)(d2 >> 26); h2 = (uint32_t)d2 & 0x3ffffff;
        d3 += c; c = (uint32_t)(d3 >> 26); h3 = (uint32_t)d3 & 0x3ffffff;
        d4 += c; c = (uint32_t)(d4 >> 26); h4 = (uint32_t)d4 & 0x3ffffff;
        h0 += c * 5; c = h0 >> 26; h0 &= 0x3ffffff;
        h1 += c;
        m += 16;
        bytes -= 16;
    }
    st->h[0] = h0; st->h[1] = h1; st->h[2] = h2; st->h[3] = h3; st->h[4] = h4;
}

void poly1305_update(Poly1305* st, const uint8_t* m, size_t bytes) {
    if (st->leftover) {
        size_t want = std::min<size_t>(16 - st->leftover, bytes);
        std::memcpy(st->buffer + st->leftover, m, want);
        bytes -= want;
        m += want;
        st->leftover += want;
        if (st->leftover < 16) return;
        poly1305_blocks(st, st->buffer, 16);
        st->leftover = 0;
    }
    if (bytes >= 16) {
        size_t want = bytes & ~static_cast<size_t>(15);
        poly1305_blocks(st, m, want);
        m += want;
        bytes -= want;
    }
    if (bytes) {
        std::memcpy(st->buffer, m, bytes);
        st->leftover = bytes;
    }
}

void poly1305_finish(Poly1305* st, uint8_t mac[16]) {
    if (st->leftover) {
        st->buffer[st->leftover] = 1;
        for (size_t i = st->leftover + 1; i < 16; ++i) st->buffer[i] = 0;
        st->final_ = 1;
        poly1305_blocks(st, st->buffer, 16);
    }
    uint32_t h0 = st->h[0], h1 = st->h[1], h2 = st->h[2], h3 = st->h[3], h4 = st->h[4];
    uint32_t c = h1 >> 26; h1 &= 0x3ffffff;
    h2 += c; c = h2 >> 26; h2 &= 0x3ffffff;
    h3 += c; c = h3 >> 26; h3 &= 0x3ffffff;
    h4 += c; c = h4 >> 26; h4 &= 0x3ffffff;
    h0 += c * 5; c = h0 >> 26; h0 &= 0x3ffffff;
    h1 += c;
    uint32_t g0 = h0 + 5; c = g0 >> 26; g0 &= 0x3ffffff;
    uint32_t g1 = h1 + c; c = g1 >> 26; g1 &= 0x3ffffff;
    uint32_t g2 = h2 + c; c = g2 >> 26; g2 &= 0x3ffffff;
    uint32_t g3 = h3 + c; c = g3 >> 26; g3 &= 0x3ffffff;
    uint32_t g4 = h4 + c - (1UL << 26);
    uint32_t mask = (g4 >> 31) - 1;
    h0 = (h0 & ~mask) | (g0 & mask);
    h1 = (h1 & ~mask) | (g1 & mask);
    h2 = (h2 & ~mask) | (g2 & mask);
    h3 = (h3 & ~mask) | (g3 & mask);
    h4 = (h4 & ~mask) | (g4 & mask);
    uint32_t o0 = h0 | (h1 << 26);
    uint32_t o1 = (h1 >> 6) | (h2 << 20);
    uint32_t o2 = (h2 >> 12) | (h3 << 14);
    uint32_t o3 = (h3 >> 18) | (h4 << 8);
    uint64_t f = (uint64_t)o0 + st->pad[0]; o0 = (uint32_t)f;
    f = (uint64_t)o1 + st->pad[1] + (f >> 32); o1 = (uint32_t)f;
    f = (uint64_t)o2 + st->pad[2] + (f >> 32); o2 = (uint32_t)f;
    f = (uint64_t)o3 + st->pad[3] + (f >> 32); o3 = (uint32_t)f;
    uint32_t o[4] = {o0, o1, o2, o3};
    for (int i = 0; i < 4; ++i) {
        mac[4 * i] = static_cast<uint8_t>(o[i]);
        mac[4 * i + 1] = static_cast<uint8_t>(o[i] >> 8);
        mac[4 * i + 2] = static_cast<uint8_t>(o[i] >> 16);
        mac[4 * i + 3] = static_cast<uint8_t>(o[i] >> 24);
    }
}

// --------------------------------------------------- AEAD session ctx

constexpr size_t kTagLen = 16;
constexpr const char kCompatStream[] = "compat-aead-stream";
constexpr const char kCompatMac[] = "compat-aead-mac";

struct AeadCtx {
    int flavor;  // 0 = compat (SHAKE+HMAC), 1 = ChaCha20-Poly1305
    uint8_t key[32];
    uint64_t ctr;     // per-direction frame counter → 96-bit BE nonce
    Hmac256 mac;      // compat flavor: precomputed HMAC pad states
    std::vector<uint8_t> scratch;  // keystream staging (compat seal/open)
};

void aead_nonce(uint64_t ctr, uint8_t nonce[12]) {
    std::memset(nonce, 0, 4);  // counters stay far below 2^64 in practice
    for (int i = 0; i < 8; ++i)
        nonce[4 + i] = static_cast<uint8_t>(ctr >> (56 - 8 * i));
}

// Seal `pt[0:n)` with the next nonce into out = ct || tag; returns ct+tag
// length (n + 16).
size_t aead_seal_one(AeadCtx* c, const uint8_t* nonce, const uint8_t* pt,
                     size_t n, uint8_t* out) {
    if (c->flavor == 0) {
        if (n) {
            if (c->scratch.size() < n) c->scratch.resize(n);
            shake256_xof(reinterpret_cast<const uint8_t*>(kCompatStream),
                         sizeof(kCompatStream) - 1, c->key, 32, nonce, 12,
                         c->scratch.data(), n);
            for (size_t i = 0; i < n; ++i) out[i] = pt[i] ^ c->scratch[i];
        }
        uint8_t tag[32];
        uint8_t macin[12];
        std::memcpy(macin, nonce, 12);
        hmac256_tag(&c->mac, macin, 12, out, n, tag);
        std::memcpy(out + n, tag, kTagLen);
        return n + kTagLen;
    }
    // ChaCha20-Poly1305 (RFC 8439 §2.8), aad = empty.
    uint8_t poly_key[64];
    chacha20_block(c->key, 0, nonce, poly_key);
    if (n) chacha20_xor(c->key, 1, nonce, pt, out, n);
    Poly1305 p;
    poly1305_init(&p, poly_key);
    static const uint8_t zeros[16] = {0};
    poly1305_update(&p, out, n);
    if (n % 16) poly1305_update(&p, zeros, 16 - (n % 16));
    uint8_t lens[16] = {0};  // le64(aad len = 0) || le64(ct len)
    for (int i = 0; i < 8; ++i)
        lens[8 + i] = static_cast<uint8_t>((static_cast<uint64_t>(n)) >> (8 * i));
    poly1305_update(&p, lens, 16);
    poly1305_finish(&p, out + n);
    return n + kTagLen;
}

// Open one ct||tag frame; returns plaintext length, or -1 on tag failure.
long aead_open_one(AeadCtx* c, const uint8_t* nonce, const uint8_t* ct,
                   size_t ct_len, uint8_t* out) {
    if (ct_len < kTagLen) return -1;
    size_t n = ct_len - kTagLen;
    if (c->flavor == 0) {
        uint8_t tag[32];
        uint8_t macin[12];
        std::memcpy(macin, nonce, 12);
        hmac256_tag(&c->mac, macin, 12, ct, n, tag);
        uint8_t diff = 0;
        for (size_t i = 0; i < kTagLen; ++i) diff |= tag[i] ^ ct[n + i];
        if (diff) return -1;
        if (n) {
            if (c->scratch.size() < n) c->scratch.resize(n);
            shake256_xof(reinterpret_cast<const uint8_t*>(kCompatStream),
                         sizeof(kCompatStream) - 1, c->key, 32, nonce, 12,
                         c->scratch.data(), n);
            for (size_t i = 0; i < n; ++i) out[i] = ct[i] ^ c->scratch[i];
        }
        return static_cast<long>(n);
    }
    uint8_t poly_key[64];
    chacha20_block(c->key, 0, nonce, poly_key);
    Poly1305 p;
    poly1305_init(&p, poly_key);
    static const uint8_t zeros[16] = {0};
    poly1305_update(&p, ct, n);
    if (n % 16) poly1305_update(&p, zeros, 16 - (n % 16));
    uint8_t lens[16] = {0};
    for (int i = 0; i < 8; ++i)
        lens[8 + i] = static_cast<uint8_t>((static_cast<uint64_t>(n)) >> (8 * i));
    poly1305_update(&p, lens, 16);
    uint8_t tag[16];
    poly1305_finish(&p, tag);
    uint8_t diff = 0;
    for (size_t i = 0; i < kTagLen; ++i) diff |= tag[i] ^ ct[n + i];
    if (diff) return -1;
    if (n) chacha20_xor(c->key, 1, nonce, ct, out, n);
    return static_cast<long>(n);
}

// ------------------------------------------- protobuf wire primitives

inline size_t varint_len(uint64_t v) {
    size_t n = 1;
    while (v >= 0x80) {
        v >>= 7;
        ++n;
    }
    return n;
}

inline uint8_t* put_varint(uint8_t* p, uint64_t v) {
    while (v >= 0x80) {
        *p++ = static_cast<uint8_t>(v) | 0x80;
        v >>= 7;
    }
    *p++ = static_cast<uint8_t>(v);
    return p;
}

// tag byte + length varint + raw bytes (field numbers < 16 only).
inline uint8_t* put_bytes_field(uint8_t* p, uint8_t tag, const uint8_t* s,
                                size_t n) {
    *p++ = tag;
    p = put_varint(p, n);
    if (n) std::memcpy(p, s, n);
    return p + n;
}

inline size_t bytes_field_len(size_t n) { return 1 + varint_len(n) + n; }

}  // namespace

extern "C" {

// Scan `buf[0:len)` for complete [4-byte BE length][payload] frames.
// Writes payload offsets/sizes for up to `max_frames` frames, sets
// `*consumed` to the total bytes of the frames returned, and returns the
// frame count.  Returns -1 if any frame declares a length > max_size
// (protocol violation; connection should be dropped).
long cl_frame_scan(const uint8_t* buf, size_t len, uint32_t max_size,
                   uint32_t* offsets, uint32_t* sizes, size_t max_frames,
                   size_t* consumed) {
    size_t pos = 0;
    long n = 0;
    while (static_cast<size_t>(n) < max_frames && pos + 4 <= len) {
        uint32_t frame_len = (static_cast<uint32_t>(buf[pos]) << 24) |
                             (static_cast<uint32_t>(buf[pos + 1]) << 16) |
                             (static_cast<uint32_t>(buf[pos + 2]) << 8) |
                             static_cast<uint32_t>(buf[pos + 3]);
        if (frame_len > max_size) return -1;
        if (pos + 4 + frame_len > len) break;  // incomplete frame
        offsets[n] = static_cast<uint32_t>(pos + 4);
        sizes[n] = frame_len;
        pos += 4 + frame_len;
        ++n;
    }
    *consumed = pos;
    return n;
}

void* cl_rt_new(const uint8_t* self_id, int k) {
    return new RoutingTable(make_id(self_id), k);
}

void cl_rt_free(void* h) { delete static_cast<RoutingTable*>(h); }

// Insert or refresh `id`.  Returns 0 if id == self (ignored), 1 otherwise.
// When a full bucket evicts its least-recently-seen entry, the evicted id is
// written to evicted_out and *evicted is set to 1 (else 0).
int cl_rt_upsert(void* h, const uint8_t* id_bytes, uint8_t* evicted_out,
                 int* evicted) {
    auto* rt = static_cast<RoutingTable*>(h);
    *evicted = 0;
    Id id = make_id(id_bytes);
    if (id == rt->self_id) return 0;
    auto& bucket = rt->buckets[bucket_index(xor_id(rt->self_id, id))];
    for (size_t i = 0; i < bucket.size(); ++i) {
        if (bucket[i] == id) {  // refresh: move to most-recently-seen
            bucket.erase(bucket.begin() + i);
            bucket.push_back(id);
            return 1;
        }
    }
    if (static_cast<int>(bucket.size()) >= rt->k) {
        std::memcpy(evicted_out, bucket.front().data(), kIdBytes);
        *evicted = 1;
        bucket.erase(bucket.begin());
    }
    bucket.push_back(id);
    return 1;
}

int cl_rt_remove(void* h, const uint8_t* id_bytes) {
    auto* rt = static_cast<RoutingTable*>(h);
    Id id = make_id(id_bytes);
    auto& bucket = rt->buckets[bucket_index(xor_id(rt->self_id, id))];
    for (size_t i = 0; i < bucket.size(); ++i) {
        if (bucket[i] == id) {
            bucket.erase(bucket.begin() + i);
            return 1;
        }
    }
    return 0;
}

long cl_rt_size(void* h) {
    auto* rt = static_cast<RoutingTable*>(h);
    long n = 0;
    for (const auto& b : rt->buckets) n += static_cast<long>(b.size());
    return n;
}

// Write the (up to) `k` ids closest to `target` (by XOR distance) into
// `out` (k * 32 bytes), sorted nearest first.  Returns the count written.
long cl_rt_closest(void* h, const uint8_t* target_bytes, int k, uint8_t* out) {
    auto* rt = static_cast<RoutingTable*>(h);
    Id target = make_id(target_bytes);

    std::vector<std::pair<Id, Id>> all;  // (distance, id)
    all.reserve(64);
    for (const auto& b : rt->buckets)
        for (const auto& id : b) all.emplace_back(xor_id(id, target), id);

    size_t kk = std::min<size_t>(k, all.size());
    std::partial_sort(all.begin(), all.begin() + kk, all.end(),
                      [](const auto& a, const auto& b) {
                          return id_less(a.first, b.first);
                      });
    for (size_t i = 0; i < kk; ++i)
        std::memcpy(out + i * kIdBytes, all[i].second.data(), kIdBytes);
    return static_cast<long>(kk);
}

// Dump every id (bucket order, LRS first within a bucket).  Returns count,
// or -1 if `cap` (in ids) is too small.
long cl_rt_dump(void* h, uint8_t* out, long cap) {
    auto* rt = static_cast<RoutingTable*>(h);
    long n = 0;
    for (const auto& b : rt->buckets) {
        for (const auto& id : b) {
            if (n >= cap) return -1;
            std::memcpy(out + n * kIdBytes, id.data(), kIdBytes);
            ++n;
        }
    }
    return n;
}

// ------------------------------------------------------- AEAD sessions

// flavor: 0 = compat encrypt-then-MAC (SHAKE-256 stream + HMAC-SHA256
// tag), 1 = ChaCha20-Poly1305.  Must match net/secure.py's cipher choice
// for the session or the wire bytes diverge.
void* cl_aead_new(const uint8_t* key32, int flavor) {
    if (flavor != 0 && flavor != 1) return nullptr;
    auto* c = new AeadCtx();
    c->flavor = flavor;
    std::memcpy(c->key, key32, 32);
    c->ctr = 0;
    if (flavor == 0) {
        // mac_key = sha256(b"compat-aead-mac" + key)
        Sha256 s;
        sha256_init(&s);
        sha256_update(&s, reinterpret_cast<const uint8_t*>(kCompatMac),
                      sizeof(kCompatMac) - 1);
        sha256_update(&s, c->key, 32);
        uint8_t mac_key[32];
        sha256_final(&s, mac_key);
        hmac256_init(&c->mac, mac_key, 32);
    }
    return c;
}

void cl_aead_free(void* h) { delete static_cast<AeadCtx*>(h); }

uint64_t cl_aead_ctr(void* h) { return static_cast<AeadCtx*>(h)->ctr; }

void cl_aead_set_ctr(void* h, uint64_t v) { static_cast<AeadCtx*>(h)->ctr = v; }

// Seal `data[0:len)` into wire frames: plaintext is chunked at `chunk`
// bytes, each chunk sealed under the next nonce and emitted as
// [4B BE ct_len][ct||tag].  If `with_eof` an extra empty-plaintext frame
// (authenticated EOF) is appended.  Returns total bytes written to `out`,
// or -1 if `cap` is too small (counter untouched in that case).
long cl_aead_seal_frames(void* h, const uint8_t* data, size_t len,
                         size_t chunk, int with_eof, uint8_t* out,
                         size_t cap) {
    auto* c = static_cast<AeadCtx*>(h);
    if (chunk == 0) return -1;
    size_t nframes = len / chunk + ((len % chunk) ? 1 : 0) + (with_eof ? 1 : 0);
    if (len == 0 && !with_eof) return 0;
    if (len == 0) nframes = 1;  // just the EOF frame
    size_t need = len + nframes * (4 + kTagLen);
    if (need > cap) return -1;
    size_t w = 0;
    size_t off = 0;
    uint8_t nonce[12];
    while (off < len) {
        size_t n = std::min(chunk, len - off);
        aead_nonce(c->ctr, nonce);
        c->ctr++;
        size_t ct_len = n + kTagLen;
        out[w] = static_cast<uint8_t>(ct_len >> 24);
        out[w + 1] = static_cast<uint8_t>(ct_len >> 16);
        out[w + 2] = static_cast<uint8_t>(ct_len >> 8);
        out[w + 3] = static_cast<uint8_t>(ct_len);
        aead_seal_one(c, nonce, data + off, n, out + w + 4);
        w += 4 + ct_len;
        off += n;
    }
    if (with_eof) {
        aead_nonce(c->ctr, nonce);
        c->ctr++;
        out[w] = 0;
        out[w + 1] = 0;
        out[w + 2] = 0;
        out[w + 3] = kTagLen;
        aead_seal_one(c, nonce, nullptr, 0, out + w + 4);
        w += 4 + kTagLen;
    }
    return static_cast<long>(w);
}

// Open one ciphertext frame body (ct||tag, no length prefix) under the
// next nonce.  Returns plaintext length, -1 on authentication failure,
// -2 if `outcap` is too small.  The counter advances on success AND on
// tag failure — mirroring SecureReader._fill's finally block — but not
// on the -2 capacity error (caller bug, not a wire event).
long cl_aead_open(void* h, const uint8_t* ct, size_t ct_len, uint8_t* out,
                  size_t outcap) {
    auto* c = static_cast<AeadCtx*>(h);
    if (ct_len < kTagLen) return -1;
    if (ct_len - kTagLen > outcap) return -2;
    uint8_t nonce[12];
    aead_nonce(c->ctr, nonce);
    c->ctr++;
    return aead_open_one(c, nonce, ct, ct_len, out);
}

// One-shot seal with explicit nonce + aad — exists so tests can pin the
// ChaCha20-Poly1305 core to the RFC 8439 vectors (which use a nonce our
// counter scheme never produces).  Returns ct||tag length.
long cl_aead_seal_raw(const uint8_t* key32, int flavor, const uint8_t* nonce12,
                      const uint8_t* aad, size_t aad_len, const uint8_t* pt,
                      size_t pt_len, uint8_t* out, size_t cap) {
    if (pt_len + kTagLen > cap) return -1;
    if (flavor == 1) {
        uint8_t poly_key[64];
        chacha20_block(key32, 0, nonce12, poly_key);
        if (pt_len) chacha20_xor(key32, 1, nonce12, pt, out, pt_len);
        Poly1305 p;
        poly1305_init(&p, poly_key);
        static const uint8_t zeros[16] = {0};
        if (aad_len) {
            poly1305_update(&p, aad, aad_len);
            if (aad_len % 16) poly1305_update(&p, zeros, 16 - (aad_len % 16));
        }
        poly1305_update(&p, out, pt_len);
        if (pt_len % 16) poly1305_update(&p, zeros, 16 - (pt_len % 16));
        uint8_t lens[16];
        for (int i = 0; i < 8; ++i) {
            lens[i] = static_cast<uint8_t>(static_cast<uint64_t>(aad_len) >> (8 * i));
            lens[8 + i] = static_cast<uint8_t>(static_cast<uint64_t>(pt_len) >> (8 * i));
        }
        poly1305_update(&p, lens, 16);
        poly1305_finish(&p, out + pt_len);
        return static_cast<long>(pt_len + kTagLen);
    }
    // compat flavor: keystream XOR + HMAC(nonce || aad || ct) truncated tag
    AeadCtx c;
    c.flavor = 0;
    std::memcpy(c.key, key32, 32);
    Sha256 s;
    sha256_init(&s);
    sha256_update(&s, reinterpret_cast<const uint8_t*>(kCompatMac),
                  sizeof(kCompatMac) - 1);
    sha256_update(&s, c.key, 32);
    uint8_t mac_key[32];
    sha256_final(&s, mac_key);
    hmac256_init(&c.mac, mac_key, 32);
    if (pt_len) {
        if (c.scratch.size() < pt_len) c.scratch.resize(pt_len);
        shake256_xof(reinterpret_cast<const uint8_t*>(kCompatStream),
                     sizeof(kCompatStream) - 1, c.key, 32, nonce12, 12,
                     c.scratch.data(), pt_len);
        for (size_t i = 0; i < pt_len; ++i) out[i] = pt[i] ^ c.scratch[i];
    }
    uint8_t tag[32];
    uint8_t prefix[12 + 64];
    std::memcpy(prefix, nonce12, 12);
    size_t plen = 12;
    if (aad_len && aad_len <= 64) {
        std::memcpy(prefix + 12, aad, aad_len);
        plen += aad_len;
    } else if (aad_len) {
        return -1;  // oversized aad never occurs on our wire
    }
    hmac256_tag(&c.mac, prefix, plen, out, pt_len, tag);
    std::memcpy(out + pt_len, tag, kTagLen);
    return static_cast<long>(pt_len + kTagLen);
}

// ------------------------------------------------ llama.v1 envelopes

// Flat field structs mirrored by ctypes.Structure in native/__init__.py.
// Pointers reference caller-owned UTF-8 buffers valid for the call.

struct ClGenRespFields {
    const uint8_t* model; size_t model_len;
    const uint8_t* response; size_t response_len;
    const uint8_t* done_reason; size_t done_reason_len;
    const uint8_t* worker_id; size_t worker_id_len;
    const uint8_t* trace_id; size_t trace_id_len;
    const uint8_t* parent_span; size_t parent_span_len;
    int64_t created_seconds;
    int64_t total_duration;
    int32_t created_nanos;
    int32_t has_created;
    int32_t done;
    int32_t prompt_tokens;
    int32_t completion_tokens;
    int32_t _pad;
};

struct ClGenReqFields {
    const uint8_t* model; size_t model_len;
    const uint8_t* prompt; size_t prompt_len;
    const uint8_t* kv_donor; size_t kv_donor_len;
    const uint8_t* trace_id; size_t trace_id_len;
    const uint8_t* parent_span; size_t parent_span_len;
    const uint8_t* const* msg_roles; const size_t* msg_role_lens;
    const uint8_t* const* msg_contents; const size_t* msg_content_lens;
    const uint8_t* const* stops; const size_t* stop_lens;
    int32_t n_msgs;
    int32_t n_stop;
    int32_t stream;
    int32_t max_tokens;
    float temperature;
    float top_p;
    float repeat_penalty;
    int32_t top_k;
    uint64_t seed;
    int32_t migrate;
    int32_t _pad;
};

// Decode view: offsets into the caller's payload buffer (no copies).
struct ClGenRespView {
    uint32_t model_off; uint32_t model_len;
    uint32_t response_off; uint32_t response_len;
    uint32_t done_reason_off; uint32_t done_reason_len;
    uint32_t worker_id_off; uint32_t worker_id_len;
    uint32_t trace_id_off; uint32_t trace_id_len;
    uint32_t parent_span_off; uint32_t parent_span_len;
    int64_t created_seconds;
    int64_t total_duration;
    int32_t created_nanos;
    int32_t has_created;
    int32_t done;
    int32_t prompt_tokens;
    int32_t completion_tokens;
    int32_t _pad;
};

namespace {

// GenerateResponse submessage body length (proto3 skip-defaults, fields
// in ascending order — matches upb SerializeToString byte-for-byte).
size_t genresp_body_len(const ClGenRespFields* f) {
    size_t n = 0;
    if (f->model_len) n += bytes_field_len(f->model_len);
    if (f->has_created) {
        size_t ts = 0;
        if (f->created_seconds)
            ts += 1 + varint_len(static_cast<uint64_t>(f->created_seconds));
        if (f->created_nanos)
            ts += 1 + varint_len(static_cast<uint64_t>(
                          static_cast<int64_t>(f->created_nanos)));
        n += 1 + varint_len(ts) + ts;
    }
    if (f->response_len) n += bytes_field_len(f->response_len);
    if (f->done) n += 2;  // tag 0x20 + varint 1
    if (f->done_reason_len) n += bytes_field_len(f->done_reason_len);
    if (f->worker_id_len) n += bytes_field_len(f->worker_id_len);
    if (f->total_duration)
        n += 1 + varint_len(static_cast<uint64_t>(f->total_duration));
    if (f->prompt_tokens)
        n += 1 + varint_len(static_cast<uint64_t>(
                      static_cast<int64_t>(f->prompt_tokens)));
    if (f->completion_tokens)
        n += 1 + varint_len(static_cast<uint64_t>(
                      static_cast<int64_t>(f->completion_tokens)));
    return n;
}

uint8_t* genresp_body_put(uint8_t* p, const ClGenRespFields* f) {
    if (f->model_len) p = put_bytes_field(p, 0x0A, f->model, f->model_len);
    if (f->has_created) {
        size_t ts = 0;
        if (f->created_seconds)
            ts += 1 + varint_len(static_cast<uint64_t>(f->created_seconds));
        if (f->created_nanos)
            ts += 1 + varint_len(static_cast<uint64_t>(
                          static_cast<int64_t>(f->created_nanos)));
        *p++ = 0x12;
        p = put_varint(p, ts);
        if (f->created_seconds) {
            *p++ = 0x08;
            p = put_varint(p, static_cast<uint64_t>(f->created_seconds));
        }
        if (f->created_nanos) {
            *p++ = 0x10;
            p = put_varint(p, static_cast<uint64_t>(
                                  static_cast<int64_t>(f->created_nanos)));
        }
    }
    if (f->response_len) p = put_bytes_field(p, 0x1A, f->response, f->response_len);
    if (f->done) { *p++ = 0x20; *p++ = 0x01; }
    if (f->done_reason_len)
        p = put_bytes_field(p, 0x2A, f->done_reason, f->done_reason_len);
    if (f->worker_id_len)
        p = put_bytes_field(p, 0x32, f->worker_id, f->worker_id_len);
    if (f->total_duration) {
        *p++ = 0x38;
        p = put_varint(p, static_cast<uint64_t>(f->total_duration));
    }
    if (f->prompt_tokens) {
        *p++ = 0x40;
        p = put_varint(p, static_cast<uint64_t>(
                              static_cast<int64_t>(f->prompt_tokens)));
    }
    if (f->completion_tokens) {
        *p++ = 0x48;
        p = put_varint(p, static_cast<uint64_t>(
                              static_cast<int64_t>(f->completion_tokens)));
    }
    return p;
}

inline uint8_t* put_float_field(uint8_t* p, uint8_t tag, float v) {
    uint32_t bits;
    std::memcpy(&bits, &v, 4);
    if (!bits) return p;  // proto3 skips +0.0 (callers reject -0.0 upstream)
    *p++ = tag;
    std::memcpy(p, &bits, 4);
    return p + 4;
}

size_t genreq_body_len(const ClGenReqFields* f) {
    size_t n = 0;
    if (f->model_len) n += bytes_field_len(f->model_len);
    if (f->prompt_len) n += bytes_field_len(f->prompt_len);
    if (f->stream) n += 2;
    for (int32_t i = 0; i < f->n_msgs; ++i) {
        size_t body = 0;
        if (f->msg_role_lens[i]) body += bytes_field_len(f->msg_role_lens[i]);
        if (f->msg_content_lens[i])
            body += bytes_field_len(f->msg_content_lens[i]);
        n += 1 + varint_len(body) + body;
    }
    if (f->max_tokens)
        n += 1 + varint_len(static_cast<uint64_t>(
                      static_cast<int64_t>(f->max_tokens)));
    uint32_t fb;
    std::memcpy(&fb, &f->temperature, 4);
    if (fb) n += 5;
    std::memcpy(&fb, &f->top_p, 4);
    if (fb) n += 5;
    if (f->seed) n += 1 + varint_len(f->seed);
    for (int32_t i = 0; i < f->n_stop; ++i)
        n += bytes_field_len(f->stop_lens[i]);
    if (f->top_k)
        n += 1 + varint_len(static_cast<uint64_t>(
                      static_cast<int64_t>(f->top_k)));
    std::memcpy(&fb, &f->repeat_penalty, 4);
    if (fb) n += 5;
    if (f->kv_donor_len) n += bytes_field_len(f->kv_donor_len);
    if (f->migrate) n += 2;
    return n;
}

uint8_t* genreq_body_put(uint8_t* p, const ClGenReqFields* f) {
    if (f->model_len) p = put_bytes_field(p, 0x0A, f->model, f->model_len);
    if (f->prompt_len) p = put_bytes_field(p, 0x12, f->prompt, f->prompt_len);
    if (f->stream) { *p++ = 0x18; *p++ = 0x01; }
    for (int32_t i = 0; i < f->n_msgs; ++i) {
        size_t body = 0;
        if (f->msg_role_lens[i]) body += bytes_field_len(f->msg_role_lens[i]);
        if (f->msg_content_lens[i])
            body += bytes_field_len(f->msg_content_lens[i]);
        *p++ = 0x22;
        p = put_varint(p, body);
        if (f->msg_role_lens[i])
            p = put_bytes_field(p, 0x0A, f->msg_roles[i], f->msg_role_lens[i]);
        if (f->msg_content_lens[i])
            p = put_bytes_field(p, 0x12, f->msg_contents[i],
                                f->msg_content_lens[i]);
    }
    if (f->max_tokens) {
        *p++ = 0x28;
        p = put_varint(p, static_cast<uint64_t>(
                              static_cast<int64_t>(f->max_tokens)));
    }
    p = put_float_field(p, 0x35, f->temperature);
    p = put_float_field(p, 0x3D, f->top_p);
    if (f->seed) { *p++ = 0x40; p = put_varint(p, f->seed); }
    for (int32_t i = 0; i < f->n_stop; ++i)
        p = put_bytes_field(p, 0x4A, f->stops[i], f->stop_lens[i]);
    if (f->top_k) {
        *p++ = 0x50;
        p = put_varint(p, static_cast<uint64_t>(
                              static_cast<int64_t>(f->top_k)));
    }
    p = put_float_field(p, 0x5D, f->repeat_penalty);
    if (f->kv_donor_len)
        p = put_bytes_field(p, 0x62, f->kv_donor, f->kv_donor_len);
    if (f->migrate) { *p++ = 0x68; *p++ = 0x01; }
    return p;
}

// BaseMessage wrapper: oneof arm (serialized even when the submessage is
// empty — upb keeps the presence bit) + trace_id(5) + parent_span(6).
// The oneof arm comes FIRST in field order for arms 1/2; trace fields 5/6
// follow.  upb serializes in ascending field number, so arm tags 0x0A
// (generate_request) and 0x12 (generate_response) always precede 0x2A/0x32.
size_t base_wrap_len(size_t arm_body, size_t tid_len, size_t span_len) {
    size_t n = 1 + varint_len(arm_body) + arm_body;
    if (tid_len) n += bytes_field_len(tid_len);
    if (span_len) n += bytes_field_len(span_len);
    return n;
}

// varint reader: returns bytes consumed, 0 on malformed/overlong input.
inline size_t read_varint(const uint8_t* p, size_t len, uint64_t* out) {
    uint64_t v = 0;
    size_t i = 0;
    int shift = 0;
    while (i < len && i < 10) {
        uint8_t b = p[i++];
        v |= static_cast<uint64_t>(b & 0x7f) << shift;
        if (!(b & 0x80)) {
            *out = v;
            return i;
        }
        shift += 7;
    }
    return 0;
}

}  // namespace

// Encode BaseMessage{generate_response=..., trace_id, parent_span} as a
// length-prefixed wire frame ([4B BE len][payload]) into `out`.  Returns
// total bytes written or -1 if cap is insufficient.
long cl_env_encode_genresp(const ClGenRespFields* f, uint8_t* out, size_t cap) {
    size_t body = genresp_body_len(f);
    size_t total = base_wrap_len(body, f->trace_id_len, f->parent_span_len);
    if (4 + total > cap) return -1;
    out[0] = static_cast<uint8_t>(total >> 24);
    out[1] = static_cast<uint8_t>(total >> 16);
    out[2] = static_cast<uint8_t>(total >> 8);
    out[3] = static_cast<uint8_t>(total);
    uint8_t* p = out + 4;
    *p++ = 0x12;  // BaseMessage.generate_response
    p = put_varint(p, body);
    p = genresp_body_put(p, f);
    if (f->trace_id_len)
        p = put_bytes_field(p, 0x2A, f->trace_id, f->trace_id_len);
    if (f->parent_span_len)
        p = put_bytes_field(p, 0x32, f->parent_span, f->parent_span_len);
    return static_cast<long>(p - out);
}

long cl_env_encode_genreq(const ClGenReqFields* f, uint8_t* out, size_t cap) {
    size_t body = genreq_body_len(f);
    size_t total = base_wrap_len(body, f->trace_id_len, f->parent_span_len);
    if (4 + total > cap) return -1;
    out[0] = static_cast<uint8_t>(total >> 24);
    out[1] = static_cast<uint8_t>(total >> 16);
    out[2] = static_cast<uint8_t>(total >> 8);
    out[3] = static_cast<uint8_t>(total);
    uint8_t* p = out + 4;
    *p++ = 0x0A;  // BaseMessage.generate_request
    p = put_varint(p, body);
    p = genreq_body_put(p, f);
    if (f->trace_id_len)
        p = put_bytes_field(p, 0x2A, f->trace_id, f->trace_id_len);
    if (f->parent_span_len)
        p = put_bytes_field(p, 0x32, f->parent_span, f->parent_span_len);
    return static_cast<long>(p - out);
}

// Strict decoder for BaseMessage frames whose oneof arm is
// generate_response.  Fills `view` with offsets into `payload` and
// returns 1.  Returns 0 — caller must fall back to the real parser —
// for ANY shape it is not sure about: unknown fields, non-genresp arms,
// out-of-order or duplicate fields, nested unknowns, negative varint
// surprises.  Never partially trusts: 0 means "view contents undefined".
long cl_env_decode_genresp(const uint8_t* payload, size_t len,
                           ClGenRespView* v) {
    std::memset(v, 0, sizeof(*v));
    size_t i = 0;
    int seen_arm = 0;
    while (i < len) {
        uint8_t tag = payload[i];
        if (tag == 0x12 && !seen_arm) {  // generate_response
            ++i;
            uint64_t blen;
            size_t c = read_varint(payload + i, len - i, &blen);
            if (!c || blen > len - i - c) return 0;
            i += c;
            size_t end = i + blen;
            seen_arm = 1;
            uint32_t prev_tag = 0;
            while (i < end) {
                uint8_t ft = payload[i++];
                if (ft <= prev_tag) return 0;  // require ascending, no dupes
                prev_tag = ft;
                uint64_t x;
                switch (ft) {
                    case 0x0A: case 0x1A: case 0x2A: case 0x32: {
                        size_t cc = read_varint(payload + i, end - i, &x);
                        if (!cc || x > end - i - cc) return 0;
                        i += cc;
                        uint32_t off = static_cast<uint32_t>(i);
                        uint32_t flen = static_cast<uint32_t>(x);
                        if (ft == 0x0A) { v->model_off = off; v->model_len = flen; }
                        else if (ft == 0x1A) { v->response_off = off; v->response_len = flen; }
                        else if (ft == 0x2A) { v->done_reason_off = off; v->done_reason_len = flen; }
                        else { v->worker_id_off = off; v->worker_id_len = flen; }
                        i += x;
                        break;
                    }
                    case 0x12: {  // created_at Timestamp
                        size_t cc = read_varint(payload + i, end - i, &x);
                        if (!cc || x > end - i - cc) return 0;
                        i += cc;
                        size_t tend = i + x;
                        v->has_created = 1;
                        uint32_t tprev = 0;
                        while (i < tend) {
                            uint8_t tt = payload[i++];
                            if (tt <= tprev) return 0;
                            tprev = tt;
                            uint64_t tv;
                            size_t tc = read_varint(payload + i, tend - i, &tv);
                            if (!tc) return 0;
                            i += tc;
                            if (tt == 0x08) {
                                if (tv > 0x7fffffffffffffffULL) return 0;
                                v->created_seconds = static_cast<int64_t>(tv);
                            } else if (tt == 0x10) {
                                if (tv > 0x7fffffff) return 0;
                                v->created_nanos = static_cast<int32_t>(tv);
                            } else {
                                return 0;
                            }
                        }
                        if (i != tend) return 0;
                        break;
                    }
                    case 0x20: {  // done
                        size_t cc = read_varint(payload + i, end - i, &x);
                        if (!cc || x != 1) return 0;  // proto3 never encodes 0
                        i += cc;
                        v->done = 1;
                        break;
                    }
                    case 0x38: {  // total_duration
                        size_t cc = read_varint(payload + i, end - i, &x);
                        if (!cc || x > 0x7fffffffffffffffULL) return 0;
                        i += cc;
                        v->total_duration = static_cast<int64_t>(x);
                        break;
                    }
                    case 0x40: case 0x48: {  // prompt/completion tokens
                        size_t cc = read_varint(payload + i, end - i, &x);
                        if (!cc || x > 0x7fffffff) return 0;  // negatives → fallback
                        i += cc;
                        if (ft == 0x40) v->prompt_tokens = static_cast<int32_t>(x);
                        else v->completion_tokens = static_cast<int32_t>(x);
                        break;
                    }
                    default:
                        return 0;
                }
            }
            if (i != end) return 0;
        } else if (tag == 0x2A) {  // trace_id
            if (v->trace_id_len || !seen_arm) return 0;
            ++i;
            uint64_t x;
            size_t c = read_varint(payload + i, len - i, &x);
            if (!c || !x || x > len - i - c) return 0;
            i += c;
            v->trace_id_off = static_cast<uint32_t>(i);
            v->trace_id_len = static_cast<uint32_t>(x);
            i += x;
        } else if (tag == 0x32) {  // parent_span
            if (v->parent_span_len || !seen_arm) return 0;
            ++i;
            uint64_t x;
            size_t c = read_varint(payload + i, len - i, &x);
            if (!c || !x || x > len - i - c) return 0;
            i += c;
            v->parent_span_off = static_cast<uint32_t>(i);
            v->parent_span_len = static_cast<uint32_t>(x);
            i += x;
        } else {
            return 0;
        }
    }
    return seen_arm ? 1 : 0;
}

// Fused path: encode a GenerateResponse envelope frame and seal it in one
// call.  The plaintext wire frame is staged in a thread-local scratch,
// then sealed (chunked + counter-advanced) into `out`.  Returns sealed
// bytes written, or -1 on capacity failure (counter untouched).
long cl_env_seal_genresp(void* aead, const ClGenRespFields* f, size_t chunk,
                         uint8_t* out, size_t cap) {
    thread_local std::vector<uint8_t> stage;
    size_t body = genresp_body_len(f);
    size_t total = 4 + base_wrap_len(body, f->trace_id_len, f->parent_span_len);
    if (stage.size() < total) stage.resize(total);
    long n = cl_env_encode_genresp(f, stage.data(), stage.size());
    if (n < 0) return -1;
    return cl_aead_seal_frames(aead, stage.data(), static_cast<size_t>(n),
                               chunk, 0, out, cap);
}

}  // extern "C"
