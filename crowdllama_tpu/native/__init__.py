"""Native (C++) runtime components, loaded via ctypes.

The reference's runtime is compiled Go; these are the framework's C++
equivalents for the control-plane hot paths (wire frame scanning, Kademlia
routing table — see _src/crowdllama_native.cpp).  The library is compiled
on demand with g++ into ``_build/`` keyed by a source hash; every consumer
falls back to pure Python when the toolchain or a prior build is
unavailable, so the package works without a compiler.

Set CROWDLLAMA_NO_NATIVE=1 to force the Python fallbacks.
"""

from __future__ import annotations

import ctypes
import hashlib
import logging
import os
import subprocess
import threading
from pathlib import Path

from crowdllama_tpu.utils.env import env_flag

log = logging.getLogger("crowdllama.native")

_SRC = Path(__file__).parent / "_src" / "crowdllama_native.cpp"
_BUILD_DIR = Path(__file__).parent / "_build"

_lock = threading.Lock()
_lib: ctypes.CDLL | None = None
_load_attempted = False

ID_BYTES = 32


def _compile(src: Path, out: Path) -> None:
    out.parent.mkdir(parents=True, exist_ok=True)
    # Unique tmp per process: concurrent first-run compiles must not clobber
    # each other's output mid-write (the final replace is atomic).
    tmp = out.with_suffix(f".so.tmp.{os.getpid()}")
    try:
        subprocess.run(
            ["g++", "-O2", "-std=c++17", "-shared", "-fPIC", "-o", str(tmp),
             str(src)],
            check=True, capture_output=True, timeout=120,
        )
        tmp.replace(out)
    finally:
        tmp.unlink(missing_ok=True)


def _declare(lib: ctypes.CDLL) -> ctypes.CDLL:
    u8p = ctypes.POINTER(ctypes.c_uint8)
    lib.cl_frame_scan.restype = ctypes.c_long
    lib.cl_frame_scan.argtypes = [
        ctypes.c_char_p, ctypes.c_size_t, ctypes.c_uint32,
        ctypes.POINTER(ctypes.c_uint32), ctypes.POINTER(ctypes.c_uint32),
        ctypes.c_size_t, ctypes.POINTER(ctypes.c_size_t),
    ]
    lib.cl_rt_new.restype = ctypes.c_void_p
    lib.cl_rt_new.argtypes = [ctypes.c_char_p, ctypes.c_int]
    lib.cl_rt_free.restype = None
    lib.cl_rt_free.argtypes = [ctypes.c_void_p]
    lib.cl_rt_upsert.restype = ctypes.c_int
    lib.cl_rt_upsert.argtypes = [ctypes.c_void_p, ctypes.c_char_p,
                                 u8p, ctypes.POINTER(ctypes.c_int)]
    lib.cl_rt_remove.restype = ctypes.c_int
    lib.cl_rt_remove.argtypes = [ctypes.c_void_p, ctypes.c_char_p]
    lib.cl_rt_size.restype = ctypes.c_long
    lib.cl_rt_size.argtypes = [ctypes.c_void_p]
    lib.cl_rt_closest.restype = ctypes.c_long
    lib.cl_rt_closest.argtypes = [ctypes.c_void_p, ctypes.c_char_p,
                                  ctypes.c_int, u8p]
    lib.cl_rt_dump.restype = ctypes.c_long
    lib.cl_rt_dump.argtypes = [ctypes.c_void_p, u8p, ctypes.c_long]
    return lib


def load() -> ctypes.CDLL | None:
    """Build (if needed) and load the native library; None on any failure."""
    global _lib, _load_attempted
    if env_flag("CROWDLLAMA_NO_NATIVE"):
        return None
    with _lock:
        if _load_attempted:
            return _lib
        _load_attempted = True
        try:
            src_hash = hashlib.sha256(_SRC.read_bytes()).hexdigest()[:16]
            so = _BUILD_DIR / f"crowdllama_native-{src_hash}.so"
            if not so.exists():
                _compile(_SRC, so)
            try:
                _lib = _declare(ctypes.CDLL(str(so)))
            except OSError:
                # A corrupt cached artifact must not poison the cache
                # forever: drop it and rebuild once.
                so.unlink(missing_ok=True)
                _compile(_SRC, so)
                _lib = _declare(ctypes.CDLL(str(so)))
            log.debug("native library loaded: %s", so.name)
        except Exception as e:  # no g++, compile error, load error → fallback
            log.info("native library unavailable (%s); using Python fallbacks",
                     e.__class__.__name__)
            _lib = None
        return _lib
