"""Native (C++) runtime components, loaded via ctypes.

The reference's runtime is compiled Go; these are the framework's C++
equivalents for the data-plane hot paths (wire frame scanning, Kademlia
routing table, per-session AEAD seal/open, llama.v1 envelope fast paths —
see _src/crowdllama_native.cpp and docs/NATIVE.md).  The library is
compiled on demand with g++ into ``_build/`` keyed by a source hash; every
consumer falls back to pure Python when the toolchain or a prior build is
unavailable, so the package works without a compiler.

The first build can take tens of seconds.  ``load()`` therefore refuses to
compile synchronously while an asyncio event loop is running on the
calling thread — it kicks the build to a daemon thread and returns None
(Python fallback) until the artifact is ready.  Call ``ensure_built()``
from synchronous startup code (or ``make test`` / bench harnesses) to
front-load the compile.

Set CROWDLLAMA_NO_NATIVE=1 to force the Python fallbacks.
"""

from __future__ import annotations

import asyncio
import ctypes
import hashlib
import logging
import os
import subprocess
import threading
from pathlib import Path

from crowdllama_tpu.utils.env import env_flag

log = logging.getLogger("crowdllama.native")

_SRC = Path(__file__).parent / "_src" / "crowdllama_native.cpp"
_BUILD_DIR = Path(__file__).parent / "_build"

_lock = threading.Lock()
_lib: ctypes.CDLL | None = None
_load_attempted = False
_bg_build: threading.Thread | None = None

ID_BYTES = 32
TAG_LEN = 16

# AEAD flavors (must match AeadCtx.flavor in the C++ source).
FLAVOR_COMPAT = 0  # SHAKE-256 stream + HMAC-SHA256/128 (crypto_compat)
FLAVOR_CHACHA = 1  # ChaCha20-Poly1305 (RFC 8439)

# ---------------------------------------------------------------------------
# Fallback accounting (exported on /metrics by gateway + obs.http).

_fallback_lock = threading.Lock()
_fallbacks: dict[str, int] = {}


def record_fallback(component: str) -> None:
    """Count one Python-fallback dispatch for a native-capable component."""
    with _fallback_lock:
        _fallbacks[component] = _fallbacks.get(component, 0) + 1


def native_enabled() -> bool:
    """True when the native library is loaded and dispatching."""
    return _lib is not None and not env_flag("CROWDLLAMA_NO_NATIVE")


def stats() -> dict:
    """Snapshot for /metrics: enabled flag + per-component fallback counts."""
    with _fallback_lock:
        return {"enabled": native_enabled(), "fallbacks": dict(_fallbacks)}


# ---------------------------------------------------------------------------
# ctypes mirrors of the C structs (see _src/crowdllama_native.cpp).


class ClGenRespFields(ctypes.Structure):
    _fields_ = [
        ("model", ctypes.c_char_p), ("model_len", ctypes.c_size_t),
        ("response", ctypes.c_char_p), ("response_len", ctypes.c_size_t),
        ("done_reason", ctypes.c_char_p), ("done_reason_len", ctypes.c_size_t),
        ("worker_id", ctypes.c_char_p), ("worker_id_len", ctypes.c_size_t),
        ("trace_id", ctypes.c_char_p), ("trace_id_len", ctypes.c_size_t),
        ("parent_span", ctypes.c_char_p), ("parent_span_len", ctypes.c_size_t),
        ("created_seconds", ctypes.c_int64),
        ("total_duration", ctypes.c_int64),
        ("created_nanos", ctypes.c_int32),
        ("has_created", ctypes.c_int32),
        ("done", ctypes.c_int32),
        ("prompt_tokens", ctypes.c_int32),
        ("completion_tokens", ctypes.c_int32),
        ("_pad", ctypes.c_int32),
    ]


class ClGenReqFields(ctypes.Structure):
    _fields_ = [
        ("model", ctypes.c_char_p), ("model_len", ctypes.c_size_t),
        ("prompt", ctypes.c_char_p), ("prompt_len", ctypes.c_size_t),
        ("kv_donor", ctypes.c_char_p), ("kv_donor_len", ctypes.c_size_t),
        ("trace_id", ctypes.c_char_p), ("trace_id_len", ctypes.c_size_t),
        ("parent_span", ctypes.c_char_p), ("parent_span_len", ctypes.c_size_t),
        ("msg_roles", ctypes.POINTER(ctypes.c_char_p)),
        ("msg_role_lens", ctypes.POINTER(ctypes.c_size_t)),
        ("msg_contents", ctypes.POINTER(ctypes.c_char_p)),
        ("msg_content_lens", ctypes.POINTER(ctypes.c_size_t)),
        ("stops", ctypes.POINTER(ctypes.c_char_p)),
        ("stop_lens", ctypes.POINTER(ctypes.c_size_t)),
        ("n_msgs", ctypes.c_int32),
        ("n_stop", ctypes.c_int32),
        ("stream", ctypes.c_int32),
        ("max_tokens", ctypes.c_int32),
        ("temperature", ctypes.c_float),
        ("top_p", ctypes.c_float),
        ("repeat_penalty", ctypes.c_float),
        ("top_k", ctypes.c_int32),
        ("seed", ctypes.c_uint64),
        ("migrate", ctypes.c_int32),
        ("_pad", ctypes.c_int32),
    ]


class ClGenRespView(ctypes.Structure):
    _fields_ = [
        ("model_off", ctypes.c_uint32), ("model_len", ctypes.c_uint32),
        ("response_off", ctypes.c_uint32), ("response_len", ctypes.c_uint32),
        ("done_reason_off", ctypes.c_uint32), ("done_reason_len", ctypes.c_uint32),
        ("worker_id_off", ctypes.c_uint32), ("worker_id_len", ctypes.c_uint32),
        ("trace_id_off", ctypes.c_uint32), ("trace_id_len", ctypes.c_uint32),
        ("parent_span_off", ctypes.c_uint32), ("parent_span_len", ctypes.c_uint32),
        ("created_seconds", ctypes.c_int64),
        ("total_duration", ctypes.c_int64),
        ("created_nanos", ctypes.c_int32),
        ("has_created", ctypes.c_int32),
        ("done", ctypes.c_int32),
        ("prompt_tokens", ctypes.c_int32),
        ("completion_tokens", ctypes.c_int32),
        ("_pad", ctypes.c_int32),
    ]


# -O3/-march=native matter here: the AEAD keystream and tag loops run
# ~2x faster than at -O2 on the bench host (the library is built on the
# machine that runs it, so tuning for the local CPU is safe).  The flag
# set participates in the .so cache key (_so_path) so changing it
# invalidates stale artifacts.
_CXX_FLAGS = ["-O3", "-march=native", "-funroll-loops", "-std=c++17",
              "-shared", "-fPIC"]


def _compile(src: Path, out: Path) -> None:
    out.parent.mkdir(parents=True, exist_ok=True)
    # Unique tmp per process: concurrent first-run compiles must not clobber
    # each other's output mid-write (the final replace is atomic).
    tmp = out.with_suffix(f".so.tmp.{os.getpid()}")
    try:
        try:
            subprocess.run(
                ["g++", *_CXX_FLAGS, "-o", str(tmp), str(src)],
                check=True, capture_output=True, timeout=120,
            )
        except subprocess.CalledProcessError:
            # Some toolchains reject -march=native (cross compilers,
            # exotic arches); the portable flag set is still correct.
            subprocess.run(
                ["g++", *[f for f in _CXX_FLAGS if f != "-march=native"],
                 "-o", str(tmp), str(src)],
                check=True, capture_output=True, timeout=120,
            )
        tmp.replace(out)
    finally:
        tmp.unlink(missing_ok=True)


def _declare(lib: ctypes.CDLL) -> ctypes.CDLL:
    u8p = ctypes.POINTER(ctypes.c_uint8)
    lib.cl_frame_scan.restype = ctypes.c_long
    lib.cl_frame_scan.argtypes = [
        ctypes.c_char_p, ctypes.c_size_t, ctypes.c_uint32,
        ctypes.POINTER(ctypes.c_uint32), ctypes.POINTER(ctypes.c_uint32),
        ctypes.c_size_t, ctypes.POINTER(ctypes.c_size_t),
    ]
    lib.cl_rt_new.restype = ctypes.c_void_p
    lib.cl_rt_new.argtypes = [ctypes.c_char_p, ctypes.c_int]
    lib.cl_rt_free.restype = None
    lib.cl_rt_free.argtypes = [ctypes.c_void_p]
    lib.cl_rt_upsert.restype = ctypes.c_int
    lib.cl_rt_upsert.argtypes = [ctypes.c_void_p, ctypes.c_char_p,
                                 u8p, ctypes.POINTER(ctypes.c_int)]
    lib.cl_rt_remove.restype = ctypes.c_int
    lib.cl_rt_remove.argtypes = [ctypes.c_void_p, ctypes.c_char_p]
    lib.cl_rt_size.restype = ctypes.c_long
    lib.cl_rt_size.argtypes = [ctypes.c_void_p]
    lib.cl_rt_closest.restype = ctypes.c_long
    lib.cl_rt_closest.argtypes = [ctypes.c_void_p, ctypes.c_char_p,
                                  ctypes.c_int, u8p]
    lib.cl_rt_dump.restype = ctypes.c_long
    lib.cl_rt_dump.argtypes = [ctypes.c_void_p, u8p, ctypes.c_long]
    lib.cl_aead_new.restype = ctypes.c_void_p
    lib.cl_aead_new.argtypes = [ctypes.c_char_p, ctypes.c_int]
    lib.cl_aead_free.restype = None
    lib.cl_aead_free.argtypes = [ctypes.c_void_p]
    lib.cl_aead_ctr.restype = ctypes.c_uint64
    lib.cl_aead_ctr.argtypes = [ctypes.c_void_p]
    lib.cl_aead_set_ctr.restype = None
    lib.cl_aead_set_ctr.argtypes = [ctypes.c_void_p, ctypes.c_uint64]
    lib.cl_aead_seal_frames.restype = ctypes.c_long
    lib.cl_aead_seal_frames.argtypes = [
        ctypes.c_void_p, ctypes.c_char_p, ctypes.c_size_t, ctypes.c_size_t,
        ctypes.c_int, ctypes.c_char_p, ctypes.c_size_t,
    ]
    lib.cl_aead_open.restype = ctypes.c_long
    lib.cl_aead_open.argtypes = [
        ctypes.c_void_p, ctypes.c_char_p, ctypes.c_size_t, ctypes.c_char_p,
        ctypes.c_size_t,
    ]
    lib.cl_aead_seal_raw.restype = ctypes.c_long
    lib.cl_aead_seal_raw.argtypes = [
        ctypes.c_char_p, ctypes.c_int, ctypes.c_char_p, ctypes.c_char_p,
        ctypes.c_size_t, ctypes.c_char_p, ctypes.c_size_t, ctypes.c_char_p,
        ctypes.c_size_t,
    ]
    lib.cl_env_encode_genresp.restype = ctypes.c_long
    lib.cl_env_encode_genresp.argtypes = [
        ctypes.POINTER(ClGenRespFields), ctypes.c_char_p, ctypes.c_size_t,
    ]
    lib.cl_env_encode_genreq.restype = ctypes.c_long
    lib.cl_env_encode_genreq.argtypes = [
        ctypes.POINTER(ClGenReqFields), ctypes.c_char_p, ctypes.c_size_t,
    ]
    lib.cl_env_decode_genresp.restype = ctypes.c_long
    lib.cl_env_decode_genresp.argtypes = [
        ctypes.c_char_p, ctypes.c_size_t, ctypes.POINTER(ClGenRespView),
    ]
    lib.cl_env_seal_genresp.restype = ctypes.c_long
    lib.cl_env_seal_genresp.argtypes = [
        ctypes.c_void_p, ctypes.POINTER(ClGenRespFields), ctypes.c_size_t,
        ctypes.c_char_p, ctypes.c_size_t,
    ]
    return lib


def _so_path() -> Path:
    src_hash = hashlib.sha256(
        _SRC.read_bytes() + " ".join(_CXX_FLAGS).encode()).hexdigest()[:16]
    return _BUILD_DIR / f"crowdllama_native-{src_hash}.so"


def _build_and_load() -> None:
    """Compile (if needed) + dlopen + declare; sets _lib. Caller holds _lock
    or runs on the dedicated background build thread."""
    global _lib
    so = _so_path()
    if not so.exists():
        _compile(_SRC, so)
    try:
        lib = _declare(ctypes.CDLL(str(so)))
    except OSError:
        # A corrupt cached artifact must not poison the cache forever:
        # drop it and rebuild once.
        so.unlink(missing_ok=True)
        _compile(_SRC, so)
        lib = _declare(ctypes.CDLL(str(so)))
    _lib = lib
    log.debug("native library loaded: %s", so.name)


def _in_running_loop() -> bool:
    try:
        asyncio.get_running_loop()
        return True
    except RuntimeError:
        return False


def load() -> ctypes.CDLL | None:
    """Build (if needed) and load the native library; None on any failure.

    Never compiles synchronously on a thread that is running an asyncio
    event loop: a cold g++ build takes seconds and would stall every
    connection on the loop.  In that case the build is started on a daemon
    thread and this call returns None (Python fallback); once the thread
    finishes, subsequent calls return the library.
    """
    global _lib, _load_attempted, _bg_build
    if env_flag("CROWDLLAMA_NO_NATIVE"):
        return None
    if _lib is not None:
        return _lib
    # A background build holds _lock for the whole compile; hot-path
    # callers must not queue on that mutex (it would stall the loop just
    # as badly as compiling inline would).
    bg = _bg_build
    if bg is not None and bg.is_alive():
        return None
    with _lock:
        if _lib is not None or _load_attempted:
            return _lib
        so_ready = False
        try:
            so_ready = _so_path().exists()
        except OSError:
            pass
        if not so_ready and _in_running_loop():
            # First build under a live event loop: compile off-loop.
            if _bg_build is None or not _bg_build.is_alive():
                def _bg() -> None:
                    global _load_attempted
                    with _lock:
                        if _lib is not None or _load_attempted:
                            return
                        try:
                            _build_and_load()
                        except Exception as e:
                            _load_attempted = True
                            log.info(
                                "native background build failed (%s); "
                                "using Python fallbacks",
                                e.__class__.__name__)
                _bg_build = threading.Thread(
                    target=_bg, name="crowdllama-native-build", daemon=True)
                _bg_build.start()
            return None
        try:
            _build_and_load()
        except Exception as e:  # no g++, compile error, load error → fallback
            _load_attempted = True
            log.info("native library unavailable (%s); using Python fallbacks",
                     e.__class__.__name__)
            _lib = None
        else:
            _load_attempted = True
        return _lib


def ensure_built() -> bool:
    """Blocking build+load for synchronous startup paths (make test, bench,
    process main before the loop starts).  Returns True when native is
    ready."""
    if env_flag("CROWDLLAMA_NO_NATIVE"):
        return False
    if _lib is not None:
        return True
    global _load_attempted
    with _lock:
        if _lib is None and not _load_attempted:
            try:
                _build_and_load()
            except Exception as e:
                log.info("native build failed (%s); using Python fallbacks",
                         e.__class__.__name__)
            _load_attempted = True
    return _lib is not None


def _reset_for_tests() -> None:
    """Drop cached load state so tests can exercise load() transitions."""
    global _lib, _load_attempted, _bg_build
    with _lock:
        _lib = None
        _load_attempted = False
        _bg_build = None
    with _fallback_lock:
        _fallbacks.clear()


# ---------------------------------------------------------------------------
# AEAD session wrapper.


class AeadSession:
    """One direction of a secure stream: pooled native cipher context with
    an internal 96-bit big-endian nonce counter and reusable scratch
    buffers.  Construct only when ``load()`` returned a library."""

    __slots__ = ("_lib", "_h", "_out", "_pt")

    def __init__(self, lib: ctypes.CDLL, key: bytes, flavor: int) -> None:
        if len(key) != 32:
            raise ValueError("AEAD key must be 32 bytes")
        h = lib.cl_aead_new(key, flavor)
        if not h:
            raise ValueError(f"unsupported AEAD flavor {flavor}")
        self._lib = lib
        self._h = h
        self._out = ctypes.create_string_buffer(64 * 1024)
        self._pt = ctypes.create_string_buffer(64 * 1024)

    def __del__(self) -> None:  # pragma: no cover - interpreter teardown
        h = getattr(self, "_h", None)
        if h:
            try:
                self._lib.cl_aead_free(h)
            except Exception:
                pass
            self._h = None

    @property
    def counter(self) -> int:
        return int(self._lib.cl_aead_ctr(self._h))

    def seal_frames(self, data: bytes, chunk: int, with_eof: bool = False) -> bytes:
        """Chunk + seal ``data`` into concatenated wire frames
        ([4B BE len][ct||tag]...), advancing the nonce counter once per
        frame — byte-identical to SecureWriter's Python path."""
        n = len(data)
        nframes = (n + chunk - 1) // chunk + (1 if with_eof else 0)
        need = n + nframes * (4 + TAG_LEN)
        if need > len(self._out):
            self._out = ctypes.create_string_buffer(max(need, 2 * len(self._out)))
        w = self._lib.cl_aead_seal_frames(
            self._h, data, n, chunk, 1 if with_eof else 0, self._out, len(self._out))
        if w < 0:
            raise RuntimeError("native seal capacity error")
        # string_at copies exactly w bytes; .raw[:w] would memcpy the whole
        # scratch buffer (64KB+) first — dominant cost on small frames.
        return ctypes.string_at(self._out, w)

    def open(self, ct: bytes) -> bytes | None:
        """Open one ciphertext frame body (no length prefix).  Returns the
        plaintext, or None on authentication failure.  The counter advances
        in both cases, matching SecureReader's finally block."""
        n = len(ct)
        if n - TAG_LEN > len(self._pt):
            self._pt = ctypes.create_string_buffer(max(n, 2 * len(self._pt)))
        r = self._lib.cl_aead_open(self._h, ct, n, self._pt, len(self._pt))
        if r == -1:
            return None
        if r < 0:
            raise RuntimeError("native open capacity error")
        return ctypes.string_at(self._pt, r)
