"""Unified worker/consumer peer.

Counterpart of /root/reference/pkg/peer/peer.go: one node object owning the
stream host, DHT, capability metadata, peer manager and the engine seam.
Registers the inference stream handler (peer.go:177-256) and metadata handler
(peer.go:284-316); runs the metadata refresh / publish / advertise loops
(peer.go:361-504) with DHT reconnect-on-empty-routing-table (peer.go:513-525).

Where the reference hardcodes a fake RTX 4090 advertisement (peer.go:320-343),
metadata here is real: model list, measured throughput EMA and slot load from
the engine, TPU chip count / HBM / ICI topology from the JAX runtime.
"""

from __future__ import annotations

import asyncio
import logging
import time

from crowdllama_tpu.utils.crypto_compat import Ed25519PrivateKey

from crowdllama_tpu.config import Configuration
from crowdllama_tpu.core import wire
from crowdllama_tpu.core.protocol import (
    INFERENCE_PROTOCOL,
    METADATA_PROTOCOL,
    SHARD_PROTOCOL,
    metadata_key,
    namespace_key,
)
from crowdllama_tpu.core.resource import Resource
from crowdllama_tpu.engine.engine import Engine
from crowdllama_tpu.net.discovery import discover_peers, new_host_and_dht, request_peer_metadata
from crowdllama_tpu.net.host import Stream
from crowdllama_tpu.obs import NodeObs
from crowdllama_tpu.peermanager.manager import PeerHealthConfig, PeerManager
from crowdllama_tpu.utils.aio import run_every
from crowdllama_tpu.version import VERSION

log = logging.getLogger("crowdllama.peer")


def _single_process() -> bool:
    """Swarm pull hot-registers a second engine, which multi-host
    leader-replicated serving cannot represent (parallel/replicated.py)
    — the pull op is disabled on multi-process clusters at the SERVICE,
    so programmatic workers are covered, not just the CLI."""
    import jax

    return jax.process_count() == 1


def _tpu_capabilities() -> dict:
    """Real accelerator capabilities introspected from the JAX runtime.

    HBM comes from ``device.memory_stats()['bytes_limit']`` (the runtime's
    actual allocatable budget); the ICI topology from device coords when the
    platform exposes them.  Nothing is hardcoded — the reference advertises
    a fake RTX 4090 (peer.go:320-343); a capability the runtime cannot
    report is reported as 0/unknown, not invented.
    """
    try:
        import jax

        devs = jax.devices()
        if not devs:
            raise RuntimeError("no devices")
        d0 = devs[0]
        kind = getattr(d0, "device_kind", "cpu") or "cpu"
        n = len(devs)

        hbm_gb = 0.0
        try:
            stats = d0.memory_stats() or {}
            limit = stats.get("bytes_limit") or stats.get("bytes_reservable_limit")
            if limit:
                hbm_gb = round(limit / (1 << 30), 1)
        except Exception:
            pass  # platform without memory_stats (e.g. some CPU builds)

        # Physical mesh extent per axis from device coordinates; fall back
        # to a flat 1xN when the platform has no coords (CPU), a backend's
        # coords accessor misbehaves, or the extents don't cover the
        # device count.
        topology = f"1x{n}"
        try:
            coords = [getattr(d, "coords", None) for d in devs]
            if coords and all(c is not None for c in coords):
                dims = [max(c[i] for c in coords) - min(c[i] for c in coords) + 1
                        for i in range(len(coords[0]))]
                dims = [d for d in dims if d > 1]
                prod = 1
                for d in dims:
                    prod *= d
                if dims and prod == n:
                    topology = "x".join(str(d) for d in dims) if len(dims) > 1 \
                        else f"1x{dims[0]}"
        except Exception:
            pass  # keep the 1xN fallback; kind/count/HBM are already known

        return {
            "accelerator": kind.lower().replace(" ", "-"),
            "tpu_chip_count": n,
            "hbm_gb_per_chip": hbm_gb,
            "ici_topology": topology,
        }
    except Exception:  # pragma: no cover - jax always importable here
        return {"accelerator": "unknown", "tpu_chip_count": 0,
                "hbm_gb_per_chip": 0.0, "ici_topology": ""}


class Peer:
    """One swarm node (worker when ``engine`` serves real models)."""

    def __init__(
        self,
        key: Ed25519PrivateKey,
        config: Configuration,
        engine: Engine,
        worker_mode: bool,
    ):
        self.config = config
        self.key = key
        self.engine = engine
        self.worker_mode = worker_mode
        self.host = None
        self.dht = None
        self.resource = Resource(worker_mode=worker_mode, version=VERSION)
        self.peer_manager: PeerManager | None = None
        self._tasks: list[asyncio.Task] = []
        self.relay_client = None  # net/relay.py RelayClient when relaying
        self.relay_service = None  # RelayService when hosting one (public)
        self._draining = False  # graceful drain entered (docs/ROBUSTNESS.md)
        # Replicated gateway plane: consumers attach a swarm/gossip.py
        # GossipNode here; the inference serve loop hands it inbound
        # gossip_frame arms.  None on workers and single-gateway setups.
        self.gossip_node = None
        # Per-node observability plane (trace ring + histograms): served by
        # obs/http.ObsServer on workers, read directly by tests/benches.
        self.obs = NodeObs(
            trace_capacity=getattr(config, "trace_buffer", 64) or 64,
            node="worker" if worker_mode else "consumer",
            trace_ttl=getattr(config, "trace_ttl", 0.0) or 0.0,
            exemplars=bool(getattr(config, "metrics_exemplars", False)))

    # ----------------------------------------------------------- lifecycle

    async def start(self) -> None:
        self.host, self.dht = await new_host_and_dht(
            self.key,
            listen_host=self.config.listen_host,
            listen_port=self.config.listen_port,
        )
        self.resource.peer_id = self.host.peer_id
        self.update_metadata()

        self.host.set_stream_handler(METADATA_PROTOCOL, self._handle_metadata_stream)
        # Health probes and discovery prefer the pooled KAD "metadata" op
        # (one frame each way over a reused stream) — the legacy
        # read-to-EOF stream above stays served for wire parity
        # (discovery.go:186-275) and as the fallback path.
        self.dht.metadata_provider = self._metadata_snapshot
        self.host.set_stream_handler(INFERENCE_PROTOCOL, self._handle_inference_stream)
        if self.worker_mode:
            # Swarm model distribution (net/model_share.py): share local
            # checkpoints and accept pull triggers (the `ollama pull`
            # surface the reference inherits, cmd/crowdllama/main.go:49-78).
            from crowdllama_tpu.core.protocol import MODEL_PROTOCOL
            from crowdllama_tpu.net.model_share import ModelShareService

            self._model_share = ModelShareService(
                model_dir=self.engine.model_dir, pull=self.pull_model,
                allow_pull=(
                    getattr(self.config, "allow_swarm_pull", True)
                    and _single_process()))
            self.host.set_stream_handler(MODEL_PROTOCOL,
                                         self._model_share.handle)
        shard_service = getattr(self.engine, "shard_service", None)
        if shard_service is not None:
            # Sharded-model member: serve our pipeline stage to group leaders.
            self.host.set_stream_handler(SHARD_PROTOCOL, shard_service.handle)
        # The engine records worker_queue/prefill/decode_step spans and the
        # per-request histograms into this node's obs plane (engine.py
        # _obs_generate); attach BEFORE attach_peer so engine overrides see
        # a fully wired peer.
        self.engine.obs = self.obs
        self.engine.attach_peer(self)

        self.peer_manager = PeerManager(
            self_peer_id=self.host.peer_id,
            config=PeerHealthConfig(intervals=self.config.intervals),
            metadata_fetcher=self._fetch_peer_metadata,
            discovery=self._run_discovery,
            # Health-machine eviction also drops the dead peer's provider
            # records / routing entry from our DHT view immediately.
            on_peer_removed=self.dht.evict_peer,
        )
        # Served RPCs prove the caller alive (replaces the per-probe
        # metadata-stream mark_seen the RPC pool elides).
        self.dht.peer_seen = self.peer_manager.mark_seen

        if self.config.bootstrap_peers:
            n = await self.dht.bootstrap(self.config.bootstrap_peers)
            log.info("bootstrapped to %d/%d peers", n, len(self.config.bootstrap_peers))

        await self._setup_relay()

        self.peer_manager.start()
        iv = self.config.intervals
        self.dht.start_maintenance(provider_check=iv.dht_provider_check,
                                   bucket_refresh=iv.dht_bucket_refresh)
        self._tasks = [
            asyncio.create_task(
                run_every(iv.metadata_refresh, self._refresh_metadata, log, logging.DEBUG),
                name="peer-metadata-refresh"),
            asyncio.create_task(
                run_every(iv.metadata_publish, self._publish_metadata, log, logging.DEBUG),
                name="peer-publish"),
            asyncio.create_task(
                run_every(iv.advertise, self._advertise, log, logging.DEBUG),
                name="peer-advertise"),
        ]
        if self.worker_mode and self.config.relay_mode == "auto":
            self._tasks.append(asyncio.create_task(
                run_every(iv.relay_reprobe, self._reprobe_relay, log,
                          logging.DEBUG),
                name="peer-relay-reprobe"))
        log.info("peer %s up (%s) on %s",
                 self.host.peer_id[:8],
                 "worker" if self.worker_mode else "consumer",
                 self.host.contact.addr)

    async def stop_advertising(self) -> None:
        """Stop the publish/advertise/refresh loops without closing streams.

        The graceful-shutdown first step: the swarm stops learning about
        this peer (provider records TTL out, metadata goes stale, health
        probes fail over) while in-flight requests keep being served."""
        for t in self._tasks:
            t.cancel()
        for t in self._tasks:
            try:
                await t
            except asyncio.CancelledError:
                pass
        self._tasks = []

    async def _setup_relay(self) -> None:
        """NAT traversal (net/relay.py; libp2p relay/hole-punch parity,
        /root/reference/pkg/dht/dht.go:386-395, discovery.go:62): a worker
        the bootstrap node cannot dial back registers for reverse streams
        through it — with failover to any relay_capable swarm peer — and
        advertises the relay address instead of its own.  Directly
        reachable workers instead HOST a RelayService themselves and
        advertise relay_capable, so the swarm's relay capacity scales with
        its public membership instead of hanging off bootstrap_peers[0]."""
        if self.config.relay_mode == "off":
            return
        from crowdllama_tpu.net.relay import RelayClient, dialback_probe

        if not self.worker_mode:
            # Consumers never relay, but knowing whether OUR listen port
            # is publicly dialable enables connection reversal on dials
            # to relayed workers (host._new_stream_via_relay): the worker
            # dials us back and the data path skips the relay hairpin.
            if self.config.bootstrap_peers:
                try:
                    self.host.reverse_dialable = await dialback_probe(
                        self.host, self.config.bootstrap_peers[0])
                except Exception as e:
                    log.debug("consumer dialback probe unavailable (%s)", e)
            return
        if not self.config.bootstrap_peers:
            self._start_relay_service()
            return
        relay_addr = self.config.bootstrap_peers[0]
        if self.config.relay_mode == "auto":
            try:
                if await dialback_probe(self.host, relay_addr):
                    # Directly reachable: no relay needed — serve as one.
                    self.host.reverse_dialable = True
                    self._start_relay_service()
                    return
            except Exception as e:
                # No relay service at the bootstrap node (or probe error):
                # relaying through it is impossible either way — stay
                # direct rather than stall startup on doomed registration.
                log.debug("dialback probe unavailable (%s); staying "
                          "direct", e)
                return
        await self._register_relay(relay_addr)

    async def _register_relay(self, relay_addr: str) -> bool:
        """Register for reverse streams via ``relay_addr`` (with failover
        candidates); returns False when registration can't start."""
        from crowdllama_tpu.net.relay import RelayClient

        log.info("worker not directly reachable: relaying via %s", relay_addr)
        # Stop advertising the direct address BEFORE registering, so the
        # relay (and every later peer) never learns a bogus direct contact.
        self.host.hello_dialable = False
        client = RelayClient(self.host, relay_addr,
                             candidates=self._relay_candidates,
                             on_relay_change=self._on_relay_change)
        try:
            await client.start()
        except Exception:
            await client.stop()  # kill the reconnect loop too
            self.host.hello_dialable = True  # direct-only better than dead
            log.exception("relay registration failed; staying direct")
            return False
        self.relay_client = client
        self.host.reverse_dialable = False  # confirmed not dialable
        if self.relay_service is not None:
            # A NATed node can't relay for others — stop advertising it.
            self.relay_service.close()
            self.relay_service = None
            self.resource.relay_capable = False
        self._on_relay_change(client.relay_addr)
        return True

    def _start_relay_service(self) -> None:
        """Host a RelayService for NATed swarm members (public workers)."""
        from crowdllama_tpu.net.relay import RelayService

        if self.relay_service is None:
            self.relay_service = RelayService(self.host)
            # Traced relay splices record relay_splice spans into this
            # node's ring so the trace collector can fetch the relay hop.
            self.relay_service.obs = self.obs
            self.resource.relay_capable = True
            log.info("hosting relay service for NATed peers")

    def _relay_candidates(self) -> list[str]:
        """Failover relay addresses: bootstrap peers first, then every
        healthy swarm peer advertising relay_capable (resolved through the
        local DHT routing table — no network round trip)."""
        cands = list(self.config.bootstrap_peers)
        try:
            capable = {
                p.peer_id for p in self.peer_manager.get_healthy_peers()
                if getattr(p.resource, "relay_capable", False)}
            for c in self.dht.table.contacts():
                if c.peer_id in capable and not c.relay:
                    cands.append(f"{c.host}:{c.port}")
        except Exception as e:
            log.debug("relay candidate scan failed: %s", e)
        seen: set[str] = set()
        return [a for a in cands if not (a in seen or seen.add(a))]

    def _on_relay_change(self, relay_addr: str) -> None:
        """(Re-)advertise the current relay contact — fires on every
        successful registration, including failover to a new relay."""
        from crowdllama_tpu.net.host import Contact

        rhost, _, rport = relay_addr.rpartition(":")
        self.host.relay_contact = Contact(
            peer_id=self.host.peer_id, host=rhost or "127.0.0.1",
            port=int(rport), relay=True)
        self.resource.reachability = "relay"
        self.update_metadata()

    async def _reprobe_relay(self) -> None:
        """relay_mode=auto reachability tracking, BOTH directions: a
        relaying worker whose listen port became directly reachable (NAT
        opened, port-forward added) drops the relay; a direct worker whose
        port stopped being reachable (mapping expired) goes back to
        relaying — without this the upgrade would be one-way and a
        transiently-open NAT would strand the worker advertising a dead
        direct address."""
        if self.config.relay_mode != "auto":
            return
        from crowdllama_tpu.net.relay import dialback_probe

        if self.relay_client is not None:
            try:
                reachable = await dialback_probe(
                    self.host, self.relay_client.relay_addr)
            except Exception:
                return  # relay gone mid-probe: client failover handles it
            if not reachable:
                return
            log.info("direct dialback succeeded; dropping relay %s",
                     self.relay_client.relay_addr)
            await self.relay_client.stop()
            self.relay_client = None
            self.host.relay_contact = None
            self.host.hello_dialable = True
            self.host.reverse_dialable = True
            self.resource.reachability = "direct"
            self._start_relay_service()
            self.update_metadata()
            await self._publish_metadata()
            return

        # Direct worker: confirm we are still dialable via any known relay.
        cands = self._relay_candidates()
        if not cands:
            return
        try:
            reachable = await dialback_probe(self.host, cands[0])
        except Exception:
            return  # no relay service reachable to probe through
        if reachable:
            self.host.reverse_dialable = True
            return
        log.info("direct dialback stopped succeeding; returning to relay")
        if await self._register_relay(cands[0]):
            await self._publish_metadata()

    async def pull_model(self, model: str) -> str:
        """Acquire ``model`` from a swarm peer and serve it.

        Resolves a healthy worker advertising the model, streams its
        checkpoint with per-file hash verification (net/model_share.py),
        then hot-registers it on engines that support it
        (MultiEngine.add_model).  Returns the local checkpoint path."""
        from crowdllama_tpu.net.model_share import (
            fetch_model,
            safe_model_dirname,
        )

        safe_model_dirname(model)  # reject path-traversal names up front
        if model in (self.engine.models or []):
            d = self.engine.model_dir(model)
            return d or ""
        if self.peer_manager is None:
            raise RuntimeError("peer not started")
        candidates = [
            p for p in self.peer_manager.get_healthy_peers()
            if p.is_worker and model in p.resource.supported_models
            and p.peer_id != self.peer_id]
        if not candidates:
            raise RuntimeError(
                f"no swarm peer advertises model {model!r}")
        last_err: Exception | None = None
        for cand in candidates:
            try:
                contact = await self.dht.find_peer(cand.peer_id)
                if contact is None:
                    raise RuntimeError(f"cannot resolve {cand.peer_id[:8]}")
                dest = await fetch_model(self.host, contact, model,
                                         self.config.models_dir)
                break
            except Exception as e:  # source without a checkpoint, wire error
                log.warning("pull of %s from %s failed: %s", model,
                            cand.peer_id[:8], e)
                last_err = e
        else:
            raise RuntimeError(f"pull failed from every source: {last_err}")
        add = getattr(self.engine, "add_model", None)
        if add is None:
            # Succeeding here would let the gateway's /api/pull report
            # success for a model /api/chat still 503s on.
            raise RuntimeError(
                f"checkpoint downloaded to {dest} but this worker's engine "
                f"cannot hot-register models; restart with --model {model} "
                f"--model-path {dest}")
        await add(model, str(dest))
        return str(dest)

    async def drain(self) -> int:
        """Graceful drain (docs/ROBUSTNESS.md): flip this peer to the
        ``draining`` state and hand off in-flight generation.

        Idempotent (SIGTERM and POST /drain may race).  Order matters:

        1. the engine's migration is REQUESTED first — the scheduler
           flips to draining and claims every in-flight stream at its
           next safe point (within one decode dispatch).  Requesting it
           after the network advertising below raced stream completion:
           the forced DHT provide can take seconds, long enough for a
           short stream to finish with ``"stop"`` on this worker instead
           of migrating (the drain-vs-completion race the claim-or-skip
           safe point closes from the scheduler side);
        2. advertised metadata flips to ``draining: true`` and ONE forced
           metadata provide goes out while the migration completes, so
           gateways that re-probe quarantine us now rather than at the
           next reprovide tick;
        3. the migration result is awaited — each claimed stream got a
           MigrateFrame and the gateway re-routes it with this worker
           attached as KV donor.

        New GenerateRequests are rejected with a ``draining`` terminal
        frame from here on, but the serve loops STAY UP: this node keeps
        answering KvFetchRequests (the donor role) until the process
        exits at drain_timeout.  Returns how many requests were migrated.
        """
        if self._draining:
            return 0
        self._draining = True
        self.resource.draining = True
        self.resource.touch()
        if self.obs is not None:
            self.obs.metrics.drain_inc("initiated")
        t0 = time.perf_counter_ns()
        migrating = asyncio.ensure_future(self.engine.migrate())
        await self.stop_advertising()
        if self.dht is not None and self.host is not None:
            try:
                await self.dht.reconnect_if_needed()
                # min_interval=0 forces the network provide NOW — the
                # stale record from the serving era must not outlive the
                # streams it would route here.
                await asyncio.wait_for(
                    self.dht.provide(metadata_key(self.host.peer_id.encode()),
                                     min_interval=0), timeout=5.0)
            except Exception as e:
                log.warning("drain metadata publish failed: %s", e)
        migrated = await migrating
        if self.obs is not None:
            self.obs.trace.record(
                f"drain-{self.peer_id[:8]}", "drain",
                time.perf_counter_ns() - t0, migrated=migrated)
        log.info("peer %s draining: %d in-flight requests migrated",
                 self.peer_id[:8], migrated)
        return migrated

    async def stop(self) -> None:
        await self.stop_advertising()
        # Departure publish BEFORE tearing down relay + inference streams:
        # peers that re-probe metadata during the teardown window see
        # draining=true and deroute instead of racing dead streams
        # (regression-tested in tests/test_churn.py).
        if self.dht is not None and self.host is not None:
            self.resource.draining = True
            self.resource.touch()
            try:
                await asyncio.wait_for(
                    self.dht.provide(metadata_key(self.host.peer_id.encode()),
                                     min_interval=0), timeout=2.0)
            except Exception as e:
                log.debug("departure publish failed: %s", e)
        if self.relay_client is not None:
            await self.relay_client.stop()
            self.relay_client = None
        if self.relay_service is not None:
            self.relay_service.close()
            self.relay_service = None
        if self.peer_manager is not None:
            await self.peer_manager.stop()
        if self.dht is not None:
            await self.dht.stop_maintenance()
        if self.host is not None:
            await self.host.close()

    @property
    def peer_id(self) -> str:
        return self.host.peer_id if self.host else ""

    # ------------------------------------------------------------ metadata

    def update_metadata(self) -> None:
        """Refresh the advertised Resource from live engine telemetry
        (replaces the reference's hardcoded advertisement, peer.go:320-343)."""
        d = self.engine.describe()
        r = self.resource
        r.supported_models = list(d.get("models", []))
        r.tokens_throughput = float(d.get("throughput", 0.0))
        r.load = float(d.get("load", 0.0))
        r.version = VERSION
        r.worker_mode = self.worker_mode
        r.max_context_length = self.config.max_context_length
        r.embeddings = bool(d.get("embeddings", True))
        for k, v in _tpu_capabilities().items():
            setattr(r, k, v)
        sg = d.get("shard_group")
        if sg is not None:
            r.shard_group = sg
        r.touch()

    async def _refresh_metadata(self) -> None:
        self.update_metadata()

    async def _publish_metadata(self) -> None:
        """Provide the metadata reachability key (peer.go:409-447).

        Divergence from the reference: it derives the key from the metadata
        JSON (a brand-new CID every refresh — write-only churn, nothing ever
        looks content-addressed metadata up); we provide a stable per-peer
        key so the record refreshes in place instead of accumulating.
        """
        await self.dht.reconnect_if_needed()
        await self.dht.provide(metadata_key(self.host.peer_id.encode()),
                               min_interval=self.config.intervals.reprovide)

    async def _advertise(self) -> None:
        """Provide the namespace rendezvous key (peer.go:450-504).  The
        tick stays fast (reconnect watch + membership/contact-change
        detection inside provide()); the network re-provide is
        rate-limited to ``intervals.reprovide``."""
        await self.dht.reconnect_if_needed()
        await self.dht.provide(namespace_key(),
                               min_interval=self.config.intervals.reprovide)

    # ------------------------------------------------------------- streams

    def _metadata_snapshot(self) -> bytes:
        """CURRENT Resource JSON for the pooled KAD metadata op — same
        live refresh the legacy stream handler performs, or probes would
        serve load/throughput frozen at the last refresh tick and
        find_best_worker would rank saturated workers as idle."""
        self.update_metadata()
        return self.resource.to_json()

    async def _handle_metadata_stream(self, stream: Stream) -> None:
        """Serve Resource JSON and close (peer.go:284-316)."""
        stream.writer.write(self._metadata_snapshot())
        await stream.writer.drain()
        stream.writer.write_eof()
        if self.peer_manager is not None:
            self.peer_manager.mark_seen(stream.remote_peer_id)

    async def _handle_inference_stream(self, stream: Stream) -> None:
        """Serve inference requests on one stream until the client closes
        or idles out (peer.go:190-256 serves exactly one per stream; the
        loop is what lets the gateway's stream pool amortize the TCP +
        signed-hello handshake over many requests).

        Non-streaming: one request frame in, one response frame out.
        Streaming (req.stream=true): one frame per token chunk, done on last —
        the superset the reference never implements (its TTFT == total
        latency, SURVEY §3.3).
        """
        while True:
            if not await self._serve_one_inference(stream):
                return

    async def _serve_one_inference(self, stream: Stream) -> bool:
        """One request/reply exchange; False ends the stream's loop."""
        from crowdllama_tpu.net.host import STREAM_POOL_IDLE_S

        try:
            # Idle window must OUTLAST the gateway pool's (plus slack), or
            # every pooled stream the gateway still considers fresh would
            # already be dead on this side and each hit would pay a failed
            # roundtrip before the redial.
            msg = await wire.read_length_prefixed_pb(
                stream.reader,
                timeout=max(self.config.intervals.stream_read_timeout,
                            STREAM_POOL_IDLE_S + 5.0),
            )
        except (wire.WireError, asyncio.TimeoutError, OSError) as e:
            log.debug("inference stream read ended: %s", e)
            return False
        # Trace propagation: the gateway's id arrives on the envelope and is
        # echoed on every response frame, so a multi-hop consumer (relay
        # splice included) can correlate replies without holding state.
        tid = msg.trace_id
        try:
            which = msg.WhichOneof("message")
            if which == "embed_request":
                reply = await self.engine.handle(msg, worker_id=self.peer_id)
                reply.trace_id = tid
                await wire.write_length_prefixed_pb(stream.writer, reply)
                return True
            if which == "kv_fetch_request":
                await self._serve_kv_fetch(stream, msg)
                return True
            if which == "trace_fetch":
                await self._serve_trace_fetch(stream, msg)
                return True
            if which == "metrics_fetch":
                await self._serve_metrics_fetch(stream, msg)
                return True
            if which == "gossip_frame":
                # Replicated gateway anti-entropy (swarm/gossip.py): merge
                # the sender's LWW map + usage digests, reply with our own
                # full frame when sync is requested.  A node with no gossip
                # plane attached ignores the frame (back-compat: workers
                # and pre-gossip gateways just keep the stream alive).
                if self.gossip_node is not None:
                    reply = await self.gossip_node.handle_frame(msg)
                    if reply is not None:
                        reply.trace_id = tid
                        await wire.write_length_prefixed_pb(
                            stream.writer, reply)
                return True
            if which == "draft_chunk":
                # A DraftChunk outside a remote-draft stream (stale gateway
                # pump after failover, or a pre-remote-draft worker build
                # being probed): nack it terminally so the pump stops
                # instead of waiting out its RTT budget.  In-stream chunks
                # never reach here — the reader task owns the transport.
                from crowdllama_tpu.core.messages import (
                    extract_draft_chunk,
                    verify_result_msg,
                )

                dc = extract_draft_chunk(msg)
                nack = verify_result_msg(
                    chunk_id=dc.chunk_id, position=dc.position,
                    accepted=0, tokens=[], done=True,
                    draft_k=0, depth_hint=1)
                nack.trace_id = tid
                await wire.write_length_prefixed_pb(stream.writer, nack)
                return True
            req = msg.generate_request
            if which != "generate_request":
                raise ValueError("expected GenerateRequest")
            if self._draining:
                # Typed reject (docs/ROBUSTNESS.md): a draining worker
                # takes no NEW generation — the gateway fails over without
                # burning its failover budget on us — but the stream stays
                # open: we keep serving KvFetchRequests as a migration
                # donor until drain_timeout.
                from crowdllama_tpu.core.messages import genresp_frame_bytes

                if self.obs is not None:
                    self.obs.metrics.drain_inc("rejected_requests")
                reject = genresp_frame_bytes(
                    model=req.model, response="", worker_id=self.peer_id,
                    done=True, done_reason="draining", trace_id=tid)
                await wire.write_frame_bytes(stream.writer, reject)
                return True
            if req.stream:
                # Frames-first hot path: the engine yields encoded wire
                # frames (trace_id embedded); the batcher sends the first
                # frame inline (hard TTFT bound even for burst producers)
                # and coalesces every later frame produced within one
                # event-loop tick into a single sealed write
                # (wire.FrameBatcher — flushes via call_soon).
                feed = reader_task = None
                remote_draft = bool(getattr(req, "remote_draft", False))
                if remote_draft:
                    # Gateway-drafted pipeline (docs/SPECULATIVE.md): the
                    # gateway keeps sending DraftChunk frames on THIS
                    # stream while we stream responses back.  A reader
                    # task drains them into the scheduler's credit feed —
                    # or nacks each one when the engine can't verify
                    # (FakeEngine, plain runner) so the gateway degrades
                    # to an unpaced plain stream.
                    from crowdllama_tpu.core.spec_pipeline import DraftFeed

                    feed = DraftFeed()
                    consume = bool(getattr(
                        self.engine, "supports_remote_draft", False))
                    reader_task = asyncio.get_running_loop().create_task(
                        self._read_draft_chunks(stream, feed, tid, consume))
                flush_ns = 0
                batcher = wire.FrameBatcher(stream.writer)
                try:
                    async for frame in self.engine.handle_streaming_frames(
                            msg, worker_id=self.peer_id, draft_feed=feed):
                        t0 = time.perf_counter_ns()
                        batcher.write(frame)
                        await batcher.drain()
                        flush_ns += time.perf_counter_ns() - t0
                    t0 = time.perf_counter_ns()
                    await batcher.flush()
                    flush_ns += time.perf_counter_ns() - t0
                finally:
                    if reader_task is not None:
                        reader_task.cancel()
                        try:
                            await reader_task
                        except (asyncio.CancelledError, Exception):
                            pass
                        feed.close()
                if tid:
                    self.obs.trace.record(tid, "stream_flush", flush_ns,
                                          parent=msg.parent_span)
                if remote_draft:
                    # One-shot stream: the cancelled reader may have left a
                    # partial DraftChunk frame in the receive buffer — a
                    # pooled reuse would misparse it as the next request.
                    return False
            else:
                reply = await self.engine.handle(msg, worker_id=self.peer_id)
                reply.trace_id = tid
                t0 = time.perf_counter_ns()
                await wire.write_length_prefixed_pb(stream.writer, reply)
                if tid:
                    self.obs.trace.record(
                        tid, "stream_flush", time.perf_counter_ns() - t0,
                        parent=msg.parent_span)
            return True
        except Exception as e:
            from crowdllama_tpu.testing.faults import KillStream, StallStream

            if isinstance(e, StallStream):
                # Injected gray failure (testing/faults.py): the transport
                # stays OPEN but nothing is ever written again — no EOF, no
                # error frame.  From the gateway this is a worker that
                # wedged mid-stream; only its per-stream progress watchdog
                # (--stream-stall-ms) can notice.  Park until the gateway
                # gives up and closes its end (reader EOF), then drop out.
                log.warning("fault injection stalled inference stream: %s", e)
                try:
                    await asyncio.wait_for(stream.reader.read(),
                                           timeout=600.0)
                except Exception:
                    pass
                stream.close()
                return False
            if isinstance(e, KillStream):
                # Injected worker death (testing/faults.py): drop the
                # transport with NO error frame — from the gateway this is
                # indistinguishable from the worker process crashing
                # mid-stream, which is what chaos tests simulate.
                log.warning("fault injection killed inference stream: %s", e)
                stream.close()
                return False
            # Synthesize an error response (peer.go:233-243).
            log.warning("inference failed: %s", e)
            from crowdllama_tpu.core.messages import (
                create_embed_response,
                genresp_frame_bytes,
            )

            if msg.WhichOneof("message") == "embed_request":
                # "invalid:" marks deterministic client errors (bad input)
                # so the gateway returns 400 without burning a retry on
                # another worker that would fail identically.  Capability
                # gaps (NotImplementedError) stay retryable — another
                # worker may well embed — and routing avoids them anyway
                # via Resource.embeddings.
                prefix = "invalid: " if isinstance(e, ValueError) else ""
                detail = str(e) or (
                    "this worker's engine does not support embeddings"
                    if isinstance(e, NotImplementedError) else repr(e))
                err = create_embed_response(
                    model=msg.embed_request.model, embeddings=[],
                    worker_id=self.peer_id, error=prefix + detail,
                )
                err.trace_id = tid
                err_frame = wire.encode_frame(err)
            else:
                err_frame = genresp_frame_bytes(
                    model=msg.generate_request.model if msg.generate_request else "",
                    response=f"error: {e}",
                    worker_id=self.peer_id,
                    done=True,
                    done_reason="error",
                    trace_id=tid,
                )
            try:
                await wire.write_frame_bytes(stream.writer, err_frame)
            except Exception:
                return False  # writer dead: end the stream's serve loop
            return True  # error frame delivered; the exchange is complete

    async def _read_draft_chunks(self, stream: Stream, feed, tid: str,
                                 consume: bool) -> None:
        """Reader side of a remote-draft stream (docs/SPECULATIVE.md):
        drain incoming DraftChunk frames into the scheduler's credit feed
        while the engine streams responses the other way.  ``consume``
        False (engine can't verify) nacks every chunk immediately so the
        gateway's pump degrades to plain streaming instead of stalling.
        Any transport error just closes the feed — the scheduler releases
        the stream to free_run and the generation finishes on its own."""
        from crowdllama_tpu.testing import faults
        from crowdllama_tpu.testing.faults import KillStream

        try:
            while True:
                msg = await wire.read_length_prefixed_pb(
                    stream.reader, timeout=600.0)
                if msg.WhichOneof("message") != "draft_chunk":
                    log.debug("remote-draft reader: unexpected %s frame",
                              msg.WhichOneof("message"))
                    continue
                dc = msg.draft_chunk
                await faults.inject("spec.draft_chunk", worker=self.peer_id,
                                    chunk_id=int(dc.chunk_id))
                if consume:
                    feed.push(dc.chunk_id, dc.position, list(dc.tokens))
                    continue
                from crowdllama_tpu.core.messages import verify_result_msg

                await faults.inject("spec.verify", worker=self.peer_id,
                                    chunk_id=int(dc.chunk_id))
                nack = verify_result_msg(
                    chunk_id=dc.chunk_id, position=dc.position,
                    accepted=0, tokens=[], done=False,
                    draft_k=0, depth_hint=1)
                if tid:
                    nack.trace_id = tid
                # Whole-frame write: FrameBatcher seals complete frames, so
                # interleaving with the engine's response frames is safe at
                # frame granularity.
                await wire.write_length_prefixed_pb(stream.writer, nack)
        except asyncio.CancelledError:
            raise
        except KillStream as e:
            # Injected worker death mid-verify (chaos): drop the transport
            # with no error frame, exactly like the generation-path kill.
            log.warning("fault injection killed draft reader: %s", e)
            stream.close()
            feed.close()
        except (wire.WireError, asyncio.TimeoutError, OSError) as e:
            log.debug("draft chunk reader ended: %s", e)
            feed.close()
        except Exception as e:
            log.warning("draft chunk reader failed: %s", e)
            feed.close()

    _KV_FRAME_BYTES = 4 * 1024 * 1024  # page payload per KvPages frame

    async def _serve_trace_fetch(self, stream: Stream, msg) -> None:
        """Serve the trace collector's span-fragment fetch (PR 8).

        The payload is the SAME JSON record this node's own /debug/trace
        serves — schema-stable as span vocabularies evolve, and the
        collector never needs per-span proto churn.  A node that never
        saw the id answers ``found=false``: the collector's fan-out IS
        the index, so a miss is the common, cheap case."""
        import json as _json

        from crowdllama_tpu.core.messages import trace_spans_msg

        trace_id = msg.trace_fetch.trace_id
        node = f"{self.obs.trace.node or 'peer'}:{self.peer_id[:8]}"
        rec = self.obs.trace.get(trace_id) if trace_id else None
        if rec is None:
            out = trace_spans_msg(trace_id, node=node, found=False)
        else:
            out = trace_spans_msg(
                trace_id, node=node,
                payload=_json.dumps(rec).encode("utf-8"), found=True)
        out.trace_id = trace_id
        await wire.write_length_prefixed_pb(stream.writer, out)

    async def _serve_metrics_fetch(self, stream: Stream, msg) -> None:
        """Serve the gateway's cluster-scrape fetch (PR 13, swarm
        observatory).

        The payload is the SAME exposition text this node's own ObsServer
        /metrics serves — one composition (obs/http.node_metric_lines), so
        the p2p scrape and the HTTP scrape cannot drift.  ``families``
        prefix-filters the reply (TYPE headers follow their family), which
        keeps a rollup-only scrape cheap on big swarms."""
        from crowdllama_tpu.core.messages import metrics_snapshot_msg
        from crowdllama_tpu.obs.http import node_metric_lines

        node = f"{self.obs.trace.node or 'peer'}:{self.peer_id[:8]}"
        try:
            lines = node_metric_lines(self)
            prefixes = tuple(msg.metrics_fetch.families)
            if prefixes:
                lines = [ln for ln in lines
                         if ln.split()[-2 if ln.startswith("# TYPE") else 0]
                         .startswith(prefixes)]
            out = metrics_snapshot_msg(
                node=node, payload="\n".join(lines).encode("utf-8"),
                found=True)
        except Exception as e:  # a sick node still answers, flagged
            log.warning("metrics snapshot failed: %s", e)
            out = metrics_snapshot_msg(node=node, found=False, error=str(e))
        out.trace_id = msg.trace_id
        await wire.write_length_prefixed_pb(stream.writer, out)

    async def _serve_kv_fetch(self, stream: Stream, msg) -> None:
        """Serve a peer's paged-KV fetch (docs/KV_TRANSFER.md, donor side).

        Pages stream out in bounded frames well under wire.MAX_MESSAGE_SIZE;
        the exporter pins page refs only for the device→host gather, so a
        slow receiver never holds donor pool pages hostage.  All failures
        are reported in-band (KvPages.error) — the fetcher falls back to
        plain prefill, it never retries against us."""
        from crowdllama_tpu.core import pb
        from crowdllama_tpu.core.messages import kv_pages_msg
        from crowdllama_tpu.testing.faults import KillStream

        req = msg.kv_fetch_request
        tid = msg.trace_id
        # Chaos choke point (testing/faults.py): a donor hiccup here is
        # what the fetcher's retry/deadline handling defends against.
        from crowdllama_tpu.testing import faults

        await faults.inject("kv.serve", worker=self.peer_id, model=req.model)
        t0 = time.perf_counter_ns()
        try:
            payload = await asyncio.wait_for(
                self.engine.export_kv_pages(
                    req.model, list(req.chain_hashes), int(req.page_size)),
                timeout=max(1.0, self.config.kv_ship_timeout))
        except KillStream:
            raise
        except Exception as e:
            payload, err = None, f"kv export failed: {e}"
        else:
            err = "" if payload is not None else "kv export unavailable"
        if payload is None or payload["matched"] == 0:
            out = kv_pages_msg(pb.KvPages(
                model=req.model, matched=0, done=True,
                error=err or ""))
            out.trace_id = tid
            await wire.write_length_prefixed_pb(stream.writer, out)
            return
        k_pages, v_pages = payload["k_pages"], payload["v_pages"]
        k_scales, v_scales = payload["k_scales"], payload["v_scales"]
        matched = payload["matched"]
        sent_bytes = 0
        start = 0
        while start < matched:
            end, size = start, 0
            while end < matched and (size < self._KV_FRAME_BYTES
                                     or end == start):
                size += len(k_pages[end]) + len(v_pages[end])
                if k_scales:
                    size += len(k_scales[end]) + len(v_scales[end])
                end += 1
            frame = pb.KvPages(
                model=req.model, matched=matched, start=start,
                kv_dtype=payload["kv_dtype"], done=(end >= matched))
            frame.k_pages.extend(k_pages[start:end])
            frame.v_pages.extend(v_pages[start:end])
            if k_scales:
                frame.k_scales.extend(k_scales[start:end])
                frame.v_scales.extend(v_scales[start:end])
            out = kv_pages_msg(frame)
            out.trace_id = tid
            await wire.write_length_prefixed_pb(stream.writer, out)
            sent_bytes += size
            start = end
        self.obs.metrics.kv_ship_inc("bytes", sent_bytes)
        self.obs.metrics.kv_ship_inc("fetches")
        if tid:
            self.obs.trace.record(tid, "kv_export",
                                  time.perf_counter_ns() - t0,
                                  pages=matched, bytes=sent_bytes)

    # ----------------------------------------------------------- discovery

    async def _fetch_peer_metadata(self, peer_id: str) -> Resource:
        contact = await self.dht.find_peer(peer_id)
        if contact is None:
            raise LookupError(f"peer {peer_id[:8]} not resolvable")
        # Pooled KAD op first (health probes are the steady-state churn);
        # legacy metadata stream as the fallback for peers not serving it.
        raw = await self.dht.request_metadata(contact)
        if raw is not None:
            resource = Resource.from_json(raw.encode()
                                          if isinstance(raw, str) else raw)
            if resource.peer_id and resource.peer_id != contact.peer_id:
                raise ValueError(
                    f"metadata peer_id {resource.peer_id[:8]} does not "
                    f"match peer {contact.peer_id[:8]}")
            return resource
        return await request_peer_metadata(
            self.host, contact, timeout=self.config.intervals.metadata_timeout
        )

    async def _run_discovery(self, skip: set[str]) -> list[Resource]:
        return await discover_peers(
            self.host, self.dht, intervals=self.config.intervals,
            skip_peer_ids=skip,
        )
