"""Unified worker/consumer peer runtime."""

from crowdllama_tpu.peer.peer import Peer  # noqa: F401
