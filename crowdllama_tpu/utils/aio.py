"""Asyncio helpers shared across background services."""

from __future__ import annotations

import asyncio
import inspect
import logging
import random
from typing import Callable

#: Default tick jitter fraction for swarm background loops.  Every peer in
#: an N-node swarm runs the same advertise/publish/health/discovery
#: cadences; without phase jitter the ticks synchronize (all N processes
#: were started together in tests/benches, and drifting clocks re-align on
#: long sleeps), producing N-wide bursts of handshake-heavy streams that
#: spike event-loop lag — the round-3 16-worker scaling cliff's signature.
DEFAULT_JITTER = 0.25


async def run_every(interval: float, fn: Callable, log: logging.Logger,
                    level: int = logging.ERROR,
                    jitter: float = DEFAULT_JITTER) -> None:
    """Run ``fn`` (sync or async) every ``interval`` seconds forever.

    The single loop contract for every background service (peer publish /
    advertise / refresh, manager discovery / health / cleanup): errors are
    logged at ``level`` and never kill the loop; cancellation propagates.

    ``jitter`` desynchronizes fleets: the first tick waits a random
    fraction of the interval and every sleep is scaled by a per-tick
    uniform factor in [1-jitter, 1+jitter].  Pass 0 for strict cadence.
    """
    if jitter:
        await asyncio.sleep(random.random() * interval * jitter)
    while True:
        try:
            result = fn()
            if inspect.isawaitable(result):
                await result
        except asyncio.CancelledError:
            raise
        except Exception:
            log.log(level, "background loop error (%s)",
                    getattr(fn, "__name__", fn), exc_info=level >= logging.ERROR)
        sleep = interval
        if jitter:
            sleep *= 1 + jitter * (2 * random.random() - 1)
        await asyncio.sleep(sleep)
