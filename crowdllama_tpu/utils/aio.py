"""Asyncio helpers shared across background services."""

from __future__ import annotations

import asyncio
import inspect
import logging
from typing import Callable


async def run_every(interval: float, fn: Callable, log: logging.Logger,
                    level: int = logging.ERROR) -> None:
    """Run ``fn`` (sync or async) every ``interval`` seconds forever.

    The single loop contract for every background service (peer publish /
    advertise / refresh, manager discovery / health / cleanup): errors are
    logged at ``level`` and never kill the loop; cancellation propagates.
    """
    while True:
        try:
            result = fn()
            if inspect.isawaitable(result):
                await result
        except asyncio.CancelledError:
            raise
        except Exception:
            log.log(level, "background loop error (%s)",
                    getattr(fn, "__name__", fn), exc_info=level >= logging.ERROR)
        await asyncio.sleep(interval)
