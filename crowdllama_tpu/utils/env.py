"""Environment-variable helpers."""

from __future__ import annotations

import os

_FALSY = {"", "0", "false", "no", "off"}


def env_flag(name: str) -> bool:
    """Boolean env flag: unset, "", "0", "false", "no", "off" are False;
    anything else is True (so both ``FLAG=1`` and ``FLAG=0`` do what the
    operator expects)."""
    return os.environ.get(name, "").strip().lower() not in _FALSY
