"""Crypto primitives with a stdlib-only fallback.

Every module that needs asymmetric identity (Ed25519), key agreement
(X25519), AEAD framing (ChaCha20-Poly1305) or HKDF imports the names from
here instead of ``cryptography`` directly.  When the real ``cryptography``
package is installed those names ARE the real ones (zero overhead, zero
behavior change).  When it is missing — CPU-only CI containers ship the
jax_graft toolchain but not libffi/openssl wheels — the fallbacks below
keep the whole net stack importable and functional:

- X25519 and Ed25519 are REAL pure-Python implementations (RFC 7748
  Montgomery ladder, RFC 8032 Edwards arithmetic): wire-compatible with
  the C implementations, deterministic, just ~2-4 ms per operation
  instead of microseconds.  Stream pooling (net/host.py StreamPool)
  amortizes that handshake cost exactly as it does the real one.
- The AEAD fallback is encrypt-then-MAC: SHAKE-256 XOF keystream XOR +
  HMAC-SHA256/128 tag, same 16-byte tag length and same
  ``InvalidTag``-on-forgery contract as ChaCha20-Poly1305, so
  net/secure.py's frame format, empty-frame authenticated close and
  TamperError semantics are byte-layout identical.  It is NOT
  ChaCha20-Poly1305 on the wire: a fallback node can only talk to other
  fallback nodes (handshakes between mixed builds fail at the first
  frame, the same failure mode as a KDF version skew).

``HAVE_CRYPTOGRAPHY`` tells callers (and tests) which build is active.
"""

from __future__ import annotations

import hashlib
import hmac as _hmac
import os

try:  # real implementation when available
    from cryptography.exceptions import InvalidSignature, InvalidTag
    from cryptography.hazmat.primitives import serialization
    from cryptography.hazmat.primitives.asymmetric.ed25519 import (
        Ed25519PrivateKey,
        Ed25519PublicKey,
    )
    from cryptography.hazmat.primitives.asymmetric.x25519 import (
        X25519PrivateKey,
        X25519PublicKey,
    )
    from cryptography.hazmat.primitives.ciphers.aead import ChaCha20Poly1305
    from cryptography.hazmat.primitives.hashes import SHA256
    from cryptography.hazmat.primitives.kdf.hkdf import HKDF
    from cryptography.hazmat.primitives.serialization import (
        Encoding,
        NoEncryption,
        PrivateFormat,
        PublicFormat,
    )

    HAVE_CRYPTOGRAPHY = True

except ImportError:  # stdlib-only fallback
    HAVE_CRYPTOGRAPHY = False

    class InvalidSignature(Exception):
        pass

    class InvalidTag(Exception):
        pass

    # --- serialization surface (only the Raw forms the repo uses) -------

    class _RawEnum:
        Raw = "Raw"

    Encoding = _RawEnum
    PublicFormat = _RawEnum
    PrivateFormat = _RawEnum

    class NoEncryption:
        pass

    class _SerializationNS:
        Encoding = Encoding
        PublicFormat = PublicFormat
        PrivateFormat = PrivateFormat
        NoEncryption = NoEncryption

    serialization = _SerializationNS()

    # --- X25519 (RFC 7748) ---------------------------------------------

    _P = 2**255 - 19
    _A24 = 121665

    def _x25519_ladder(k: int, u: int) -> int:
        x1, x2, z2, x3, z3 = u, 1, 0, u, 1
        swap = 0
        for t in reversed(range(255)):
            kt = (k >> t) & 1
            swap ^= kt
            if swap:
                x2, x3 = x3, x2
                z2, z3 = z3, z2
            swap = kt
            a = (x2 + z2) % _P
            aa = a * a % _P
            b = (x2 - z2) % _P
            bb = b * b % _P
            e = (aa - bb) % _P
            c = (x3 + z3) % _P
            d = (x3 - z3) % _P
            da = d * a % _P
            cb = c * b % _P
            x3 = (da + cb) % _P
            x3 = x3 * x3 % _P
            z3 = (da - cb) % _P
            z3 = z3 * z3 % _P
            z3 = z3 * x1 % _P
            x2 = aa * bb % _P
            z2 = e * (aa + _A24 * e) % _P
        if swap:
            x2, x3 = x3, x2
            z2, z3 = z3, z2
        return x2 * pow(z2, _P - 2, _P) % _P

    def _x25519(scalar32: bytes, u32: bytes) -> bytes:
        k = int.from_bytes(scalar32, "little")
        k &= ~7
        k &= (1 << 254) - 1
        k |= 1 << 254
        u = int.from_bytes(u32, "little") & ((1 << 255) - 1)
        return _x25519_ladder(k, u).to_bytes(32, "little")

    class X25519PublicKey:
        def __init__(self, raw: bytes):
            self._raw = bytes(raw)

        @classmethod
        def from_public_bytes(cls, raw: bytes) -> "X25519PublicKey":
            if len(raw) != 32:
                raise ValueError("X25519 public keys are 32 bytes")
            return cls(raw)

        def public_bytes(self, encoding=None, fmt=None) -> bytes:
            return self._raw

        def public_bytes_raw(self) -> bytes:
            return self._raw

    class X25519PrivateKey:
        def __init__(self, raw: bytes):
            self._raw = bytes(raw)

        @classmethod
        def generate(cls) -> "X25519PrivateKey":
            return cls(os.urandom(32))

        @classmethod
        def from_private_bytes(cls, raw: bytes) -> "X25519PrivateKey":
            if len(raw) != 32:
                raise ValueError("X25519 private keys are 32 bytes")
            return cls(raw)

        def public_key(self) -> X25519PublicKey:
            return X25519PublicKey(_x25519(self._raw, (9).to_bytes(32, "little")))

        def private_bytes_raw(self) -> bytes:
            return self._raw

        def exchange(self, peer_public: X25519PublicKey) -> bytes:
            shared = _x25519(self._raw, peer_public._raw)
            if shared == b"\x00" * 32:
                raise ValueError("X25519 exchange produced all-zero secret")
            return shared

    # --- Ed25519 (RFC 8032) --------------------------------------------

    _L = 2**252 + 27742317777372353535851937790883648493
    _D = -121665 * pow(121666, _P - 2, _P) % _P
    _SQRT_M1 = pow(2, (_P - 1) // 4, _P)

    def _ed_add(p, q):
        x1, y1, z1, t1 = p
        x2, y2, z2, t2 = q
        a = (y1 - x1) * (y2 - x2) % _P
        b = (y1 + x1) * (y2 + x2) % _P
        c = 2 * t1 * t2 * _D % _P
        d = 2 * z1 * z2 % _P
        e, f, g, h = b - a, d - c, d + c, b + a
        return (e * f % _P, g * h % _P, f * g % _P, e * h % _P)

    def _ed_mul(s, p):
        q = (0, 1, 1, 0)
        while s:
            if s & 1:
                q = _ed_add(q, p)
            p = _ed_add(p, p)
            s >>= 1
        return q

    _GY = 4 * pow(5, _P - 2, _P) % _P

    def _recover_x(y: int, sign: int) -> int:
        if y >= _P:
            raise ValueError("bad point encoding")
        x2 = (y * y - 1) * pow(_D * y * y + 1, _P - 2, _P) % _P
        x = pow(x2, (_P + 3) // 8, _P)
        if (x * x - x2) % _P:
            x = x * _SQRT_M1 % _P
        if (x * x - x2) % _P:
            raise ValueError("not a curve point")
        if x == 0 and sign:
            raise ValueError("bad point encoding")
        if x & 1 != sign:
            x = _P - x
        return x

    _GX = _recover_x(_GY, 0)
    _G = (_GX, _GY, 1, _GX * _GY % _P)

    def _ed_encode(p) -> bytes:
        x, y, z, _ = p
        zi = pow(z, _P - 2, _P)
        x, y = x * zi % _P, y * zi % _P
        return (y | ((x & 1) << 255)).to_bytes(32, "little")

    def _ed_decode(raw: bytes):
        if len(raw) != 32:
            raise ValueError("Ed25519 points are 32 bytes")
        enc = int.from_bytes(raw, "little")
        y = enc & ((1 << 255) - 1)
        x = _recover_x(y, enc >> 255)
        return (x, y, 1, x * y % _P)

    def _ed_eq(p, q) -> bool:
        x1, y1, z1, _ = p
        x2, y2, z2, _ = q
        return (x1 * z2 - x2 * z1) % _P == 0 and (y1 * z2 - y2 * z1) % _P == 0

    def _ed_secret_expand(seed: bytes):
        h = hashlib.sha512(seed).digest()
        a = int.from_bytes(h[:32], "little")
        a &= (1 << 254) - 8
        a |= 1 << 254
        return a, h[32:]

    class Ed25519PublicKey:
        def __init__(self, raw: bytes):
            self._raw = bytes(raw)

        @classmethod
        def from_public_bytes(cls, raw: bytes) -> "Ed25519PublicKey":
            if len(raw) != 32:
                raise ValueError("Ed25519 public keys are 32 bytes")
            return cls(raw)

        def public_bytes(self, encoding=None, fmt=None) -> bytes:
            return self._raw

        def public_bytes_raw(self) -> bytes:
            return self._raw

        def verify(self, signature: bytes, data: bytes) -> None:
            if len(signature) != 64:
                raise InvalidSignature("bad signature length")
            try:
                a = _ed_decode(self._raw)
                r = _ed_decode(signature[:32])
            except ValueError as e:
                raise InvalidSignature(str(e)) from e
            s = int.from_bytes(signature[32:], "little")
            if s >= _L:
                raise InvalidSignature("non-canonical s")
            k = int.from_bytes(
                hashlib.sha512(signature[:32] + self._raw + data).digest(),
                "little") % _L
            if not _ed_eq(_ed_mul(s, _G), _ed_add(r, _ed_mul(k, a))):
                raise InvalidSignature("signature mismatch")

    class Ed25519PrivateKey:
        def __init__(self, seed: bytes):
            self._seed = bytes(seed)
            self._scalar, self._prefix = _ed_secret_expand(self._seed)
            self._pub = _ed_encode(_ed_mul(self._scalar, _G))

        @classmethod
        def generate(cls) -> "Ed25519PrivateKey":
            return cls(os.urandom(32))

        @classmethod
        def from_private_bytes(cls, raw: bytes) -> "Ed25519PrivateKey":
            if len(raw) != 32:
                raise ValueError("Ed25519 private keys are 32 bytes")
            return cls(raw)

        def public_key(self) -> Ed25519PublicKey:
            return Ed25519PublicKey(self._pub)

        def private_bytes(self, encoding=None, fmt=None, encryption=None) -> bytes:
            return self._seed

        def private_bytes_raw(self) -> bytes:
            return self._seed

        def sign(self, data: bytes) -> bytes:
            r = int.from_bytes(
                hashlib.sha512(self._prefix + data).digest(), "little") % _L
            r_enc = _ed_encode(_ed_mul(r, _G))
            k = int.from_bytes(
                hashlib.sha512(r_enc + self._pub + data).digest(),
                "little") % _L
            s = (r + k * self._scalar) % _L
            return r_enc + s.to_bytes(32, "little")

    # --- AEAD: encrypt-then-MAC stand-in for ChaCha20-Poly1305 ----------

    class ChaCha20Poly1305:
        """SHAKE-256 keystream XOR + HMAC-SHA256/128 tag.  Same (nonce,
        plaintext) -> (ciphertext || 16-byte tag) shape and same
        raise-InvalidTag-on-any-forgery contract as the real AEAD; both
        XOF and HMAC run in C, so throughput stays in the hundreds of
        MB/s and the aead_us attribution counters stay meaningful."""

        _TAG = 16

        def __init__(self, key: bytes):
            if len(key) != 32:
                raise ValueError("key must be 32 bytes")
            self._enc_key = key
            self._mac_key = hashlib.sha256(b"compat-aead-mac" + key).digest()
            # Per-session pooled hash states: the key-dependent prefix of
            # the XOF absorb and the HMAC inner/outer pads are computed
            # once here; per-frame cost is a copy() + the variable suffix.
            # Output is byte-identical to rebuilding from scratch.
            self._shake_base = hashlib.shake_256(
                b"compat-aead-stream" + key)
            self._hmac_base = _hmac.new(self._mac_key, b"", hashlib.sha256)

        def _keystream(self, nonce: bytes, n: int) -> bytes:
            shake = self._shake_base.copy()
            shake.update(nonce)
            return shake.digest(n)

        def _tag(self, nonce: bytes, aad: bytes | None, ct: bytes) -> bytes:
            mac = self._hmac_base.copy()
            mac.update(nonce)
            if aad:
                mac.update(aad)
            mac.update(ct)
            return mac.digest()[:self._TAG]

        def encrypt(self, nonce: bytes, data: bytes, aad: bytes | None) -> bytes:
            ks = self._keystream(nonce, len(data))
            ct = (int.from_bytes(data, "big") ^ int.from_bytes(ks, "big")
                  ).to_bytes(len(data), "big") if data else b""
            return ct + self._tag(nonce, aad, ct)

        def decrypt(self, nonce: bytes, data: bytes, aad: bytes | None) -> bytes:
            if len(data) < self._TAG:
                raise InvalidTag("ciphertext shorter than tag")
            ct, mac = data[:-self._TAG], data[-self._TAG:]
            if not _hmac.compare_digest(mac, self._tag(nonce, aad, ct)):
                raise InvalidTag("tag mismatch")
            ks = self._keystream(nonce, len(ct))
            return (int.from_bytes(ct, "big") ^ int.from_bytes(ks, "big")
                    ).to_bytes(len(ct), "big") if ct else b""

    # --- HKDF (RFC 5869, exact) ----------------------------------------

    class SHA256:
        pass

    class HKDF:
        def __init__(self, algorithm=None, length: int = 32,
                     salt: bytes | None = None, info: bytes | None = None):
            self._length = length
            self._salt = salt or b"\x00" * 32
            self._info = info or b""

        def derive(self, key_material: bytes) -> bytes:
            prk = _hmac.new(self._salt, key_material, hashlib.sha256).digest()
            okm = b""
            t = b""
            counter = 1
            while len(okm) < self._length:
                t = _hmac.new(prk, t + self._info + bytes([counter]),
                              hashlib.sha256).digest()
                okm += t
                counter += 1
            return okm[:self._length]


__all__ = [
    "HAVE_CRYPTOGRAPHY",
    "InvalidSignature",
    "InvalidTag",
    "Ed25519PrivateKey",
    "Ed25519PublicKey",
    "X25519PrivateKey",
    "X25519PublicKey",
    "ChaCha20Poly1305",
    "SHA256",
    "HKDF",
    "serialization",
    "Encoding",
    "PublicFormat",
    "PrivateFormat",
    "NoEncryption",
]
