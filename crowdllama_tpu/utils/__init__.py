"""Shared utilities: identity keys, logging, misc helpers."""
