"""Ed25519 identity key management.

Counterpart of /root/reference/internal/keys/keys.go: one Ed25519 identity per
component at ``~/.crowdllama-tpu/<component>.key`` (0700 dir / 0600 file),
get-or-create under a lock so concurrent starts produce exactly one key
(keys.go:36-98; concurrency contract tested at keys_test.go:252-289).  The
peer ID is derived from the public key (hex SHA-256, truncated), giving stable
node identity across restarts — the only durable state in the system, as in
the reference (SURVEY §5 checkpoint/resume note).
"""

from __future__ import annotations

import hashlib
import os
import threading
from pathlib import Path

from crowdllama_tpu.utils.crypto_compat import (
    Ed25519PrivateKey,
    Ed25519PublicKey,
    serialization,
)

DEFAULT_DIR = Path(os.environ.get("CROWDLLAMA_TPU_HOME", "~/.crowdllama-tpu")).expanduser()


def peer_id_from_public_key(pub: Ed25519PublicKey) -> str:
    """Stable peer ID: hex SHA-256 of the raw public key, truncated to 40 chars."""
    raw = pub.public_bytes(
        serialization.Encoding.Raw, serialization.PublicFormat.Raw
    )
    return hashlib.sha256(raw).hexdigest()[:40]


def peer_id_to_dht_id(peer_id: str) -> bytes:
    """Map a peer ID into the 256-bit DHT keyspace."""
    return hashlib.sha256(b"crowdllama-tpu:peer:" + peer_id.encode()).digest()


class KeyManager:
    """Get-or-create Ed25519 identities on disk (cf. keys.go:22-140)."""

    def __init__(self, base_dir: str | os.PathLike | None = None):
        self.base_dir = Path(base_dir).expanduser() if base_dir else DEFAULT_DIR
        self._mu = threading.Lock()

    def key_path(self, component: str) -> Path:
        return self.base_dir / f"{component}.key"

    def get_or_create_private_key(self, component: str) -> Ed25519PrivateKey:
        with self._mu:
            path = self.key_path(component)
            if path.exists():
                return self._load(path)
            self.base_dir.mkdir(parents=True, exist_ok=True)
            os.chmod(self.base_dir, 0o700)
            key = Ed25519PrivateKey.generate()
            raw = key.private_bytes(
                serialization.Encoding.Raw,
                serialization.PrivateFormat.Raw,
                serialization.NoEncryption(),
            )
            try:
                fd = os.open(path, os.O_WRONLY | os.O_CREAT | os.O_EXCL, 0o600)
            except FileExistsError:
                # Another *process* won the race; use its key.
                return self._load(path)
            try:
                os.write(fd, raw)
            finally:
                os.close(fd)
            return key

    def load_private_key(self, component: str) -> Ed25519PrivateKey:
        path = self.key_path(component)
        if not path.exists():
            raise FileNotFoundError(f"no key for component {component!r} at {path}")
        return self._load(path)

    @staticmethod
    def _load(path: Path) -> Ed25519PrivateKey:
        raw = path.read_bytes()
        if len(raw) != 32:
            raise ValueError(f"invalid key file {path}: expected 32 raw bytes, got {len(raw)}")
        return Ed25519PrivateKey.from_private_bytes(raw)

    def peer_id(self, component: str) -> str:
        """Peer-ID of an on-disk key, for logs (cf. keys.go:133-140)."""
        return peer_id_from_public_key(self.load_private_key(component).public_key())
