"""Gateway TTFT benchmark: p50/p95 time-to-first-token through the full
stack (BASELINE metric 2 of 3).

Topology on loopback, all real sockets: DHT bootstrap node + worker
(JaxEngine, streaming) + consumer peer + gateway.  Each request POSTs
/api/chat with stream=true and times the first NDJSON frame — the true TTFT
a client observes, crossing HTTP -> scheduler/prefill -> stream protocol ->
HTTP chunk.  The reference cannot measure this at all: its stream flag is a
no-op, so TTFT == total latency there (SURVEY §3.3).

Prints ONE JSON line {"metric", "value", "unit", "vs_baseline", "extra"}.
vs_baseline is null: the reference publishes no TTFT number (BASELINE.md).

Env overrides:
  CROWDLLAMA_BENCH_MODEL     engine model      (default tiny-test on cpu,
                             tinyllama-1.1b when a TPU is attached)
  CROWDLLAMA_BENCH_REQUESTS  timed requests    (default 20)
  CROWDLLAMA_BENCH_PROMPT    prompt length chars (default 128)
"""

from __future__ import annotations

import sys
from pathlib import Path

sys.path.insert(0, str(Path(__file__).resolve().parent))
import _common  # noqa: F401,E402 - repo path + JAX platform bootstrap

import asyncio
import json
import os
import statistics
import time


async def run() -> dict:
    import aiohttp
    import jax
    from crowdllama_tpu.utils.crypto_compat import Ed25519PrivateKey

    from crowdllama_tpu.config import Configuration, Intervals
    from crowdllama_tpu.engine.engine import FakeEngine, JaxEngine
    from crowdllama_tpu.gateway.gateway import Gateway
    from crowdllama_tpu.net.discovery import new_host_and_dht
    from crowdllama_tpu.peer.peer import Peer

    on_tpu = jax.devices()[0].platform == "tpu"
    model = os.environ.get(
        "CROWDLLAMA_BENCH_MODEL", "tinyllama-1.1b" if on_tpu else "tiny-test")
    n_requests = int(os.environ.get("CROWDLLAMA_BENCH_REQUESTS", "20"))
    prompt = "benchmark " * (int(os.environ.get("CROWDLLAMA_BENCH_PROMPT", "128")) // 10)

    def cfg(**kw):
        c = Configuration(listen_host="127.0.0.1", model=model,
                          intervals=Intervals.default())
        for k, v in kw.items():
            setattr(c, k, v)
        return c

    boot_host, _ = await new_host_and_dht(
        Ed25519PrivateKey.generate(), listen_host="127.0.0.1")
    bootstrap = f"127.0.0.1:{boot_host.listen_port}"

    # 4k context on the chip: the long-prefix phase needs a 2k-token
    # cached system prompt to demonstrate what prefix caching buys
    # (VERDICT r4 #7: at short shapes every forward is weight-stream
    # bound, so suffix-only prefill saved ~3% — the feature's value is at
    # prefill lengths where MXU time dominates the weight stream).
    engine = JaxEngine(cfg(), max_context_length=4096 if on_tpu else 256,
                       quantize="int8" if on_tpu else "",
                       kv_layout="paged", kv_page_size=32)
    await engine.start()
    worker = Peer(Ed25519PrivateKey.generate(), cfg(bootstrap_peers=[bootstrap]),
                  engine=engine, worker_mode=True)
    await worker.start()
    consumer = Peer(Ed25519PrivateKey.generate(), cfg(bootstrap_peers=[bootstrap]),
                    engine=FakeEngine(models=[]), worker_mode=False)
    await consumer.start()
    # trace_buffer sized to hold every request of the run so the span
    # aggregation below sees all phases, not the tail of the ring.
    gateway = Gateway(consumer, port=0, host="127.0.0.1", trace_buffer=256)
    await gateway.start()
    gw_port = gateway._runner.addresses[0][1]

    try:
        # Wait for discovery.
        deadline = time.monotonic() + 30
        while time.monotonic() < deadline:
            if consumer.peer_manager.find_best_worker(model) is not None:
                break
            await asyncio.sleep(0.1)
        else:
            raise RuntimeError("worker never discovered")

        def cold_body(i: int) -> dict:
            # The index leads the prompt so its FIRST page differs per
            # request: with the paged engine's prefix cache on, a repeated
            # identical prompt would turn the cold phase into a cache-hit
            # benchmark.
            return {"model": model, "stream": True,
                    "options": {"num_predict": 4},
                    "messages": [{"role": "user",
                                  "content": f"{i:04d} {prompt}"}]}

        url = f"http://127.0.0.1:{gw_port}/api/chat"

        async def timed_loop(s, make_body) -> list[float]:
            out: list[float] = []
            async with s.post(url, json=make_body(-1)) as resp:  # prime
                await resp.read()
            for i in range(n_requests):
                t0 = time.monotonic()
                async with s.post(url, json=make_body(i)) as resp:
                    assert resp.status == 200, await resp.text()
                    async for _ in resp.content:  # first NDJSON frame
                        out.append((time.monotonic() - t0) * 1000)
                        break
                    await resp.read()
            return out

        async with aiohttp.ClientSession() as s:
            ttfts = await timed_loop(s, cold_body)

            # Warm phase: a fixed long system prompt + varying questions —
            # the priming request populates the prefix cache, then only the
            # suffix prefills (the chat-with-system-prompt shape this
            # optimization exists for).
            system = ("You are a careful, concise assistant. "
                      * (16 if on_tpu else 4))  # fit tiny-test's 256 ctx
            before = dict(engine.describe().get("prefix_cache", {}))

            def warm_body(i: int) -> dict:
                return {"model": model, "stream": True,
                        "options": {"num_predict": 4},
                        "messages": [
                            {"role": "system", "content": system},
                            {"role": "user", "content": f"question {i}?"}]}

            warm = await timed_loop(s, warm_body)
            after = engine.describe().get("prefix_cache", {})
            prefix_stats = {k: after.get(k, 0) - before.get(k, 0)
                            for k in after}

            # Long-prefix phase (VERDICT r4 #7): a ~2k-token shared system
            # prompt — the RAG / long-instruction shape prefix caching
            # exists for.  Cold = unique leading page per request (no
            # cache reuse possible); warm = the same system prompt with a
            # varying question, suffix-only prefill after the prime.
            # Sized by TOKENS through the engine's own tokenizer (2048
            # characters would be ~4x fewer tokens under a BPE vocab).
            target_tokens = 2048 if on_tpu else 160
            unit = "be careful and cite sources. "
            long_system = "Policy: "
            while len(engine.tokenizer.encode(long_system)) < target_tokens:
                long_system += unit
            long_tokens = len(engine.tokenizer.encode(long_system))

            def long_cold_body(i: int) -> dict:
                return {"model": model, "stream": True,
                        "options": {"num_predict": 4},
                        "messages": [
                            {"role": "system",
                             "content": f"{i:04d} {long_system}"},
                            {"role": "user", "content": "summarize."}]}

            def long_warm_body(i: int) -> dict:
                return {"model": model, "stream": True,
                        "options": {"num_predict": 4},
                        "messages": [
                            {"role": "system", "content": long_system},
                            {"role": "user", "content": f"question {i}?"}]}

            long_before = dict(engine.describe().get("prefix_cache", {}))
            long_cold = await timed_loop(s, long_cold_body)
            mid = dict(engine.describe().get("prefix_cache", {}))
            long_warm = await timed_loop(s, long_warm_body)
            la = engine.describe().get("prefix_cache", {})
            long_prefix_stats = {k: la.get(k, 0) - long_before.get(k, 0)
                                 for k in la}
            # Warm-phase-only cache delta: tokens_reused per hit is the
            # prefix length the engine ACTUALLY materialized and reused —
            # tokenizer-side counting can overstate it (context clipping,
            # page-granular reuse).
            warm_hits = la.get("hits", 0) - mid.get("hits", 0)
            warm_reused = (la.get("tokens_reused", 0)
                           - mid.get("tokens_reused", 0))
    finally:
        for stop in (gateway.stop, consumer.stop, worker.stop, engine.stop,
                     boot_host.close):
            try:
                await stop()
            except Exception:
                pass  # teardown must not mask the benchmark's real error

    # Observability cross-check (obs/): the SAME percentile a dashboard
    # would read from the scraped crowdllama_ttft_seconds series, plus
    # per-phase means and one full span tree from the trace ring buffer.
    # In-memory state survives gateway.stop(), so this reads post-teardown.
    ttft_hist = gateway.obs.metrics.ttft_seconds
    phase_tot: dict[str, float] = {}
    phase_n: dict[str, int] = {}
    trace_sample = None
    for t in gateway.obs.trace.snapshot()["traces"]:
        for sp in t["spans"]:
            phase_tot[sp["name"]] = phase_tot.get(sp["name"], 0.0) \
                + sp["dur_us"]
            phase_n[sp["name"]] = phase_n.get(sp["name"], 0) + 1
        if t["done"]:
            trace_sample = t
    obs_extra = {
        "ttft_hist_p50_ms": round(ttft_hist.quantile(0.5) * 1000, 1),
        "ttft_hist_p95_ms": round(ttft_hist.quantile(0.95) * 1000, 1),
        "ttft_hist_count": ttft_hist.count,
        "decode_step_hist_p50_ms": round(
            gateway.obs.metrics.decode_step_seconds.quantile(0.5) * 1000, 2),
        "phase_mean_us": {k: round(phase_tot[k] / phase_n[k], 1)
                          for k in sorted(phase_tot)},
        "trace_sample": trace_sample,
    }

    ttfts.sort()
    p50 = statistics.median(ttfts)
    p95 = ttfts[max(0, int(len(ttfts) * 0.95) - 1)]
    lc50 = statistics.median(long_cold)
    lw50 = statistics.median(long_warm)
    # The phase only counts as a LONG-prefix result when the engine
    # demonstrably reused >= 75% of the target prefix per warm hit; a
    # clipped context or a cache that reuses a fraction of the prompt
    # would otherwise report short-prefix numbers under a long-prefix
    # label (the VERDICT r4 #7 failure shape this phase exists to avoid).
    materialized = round(warm_reused / warm_hits) if warm_hits else 0
    long_label = ("long_prefix" if materialized >= 0.75 * target_tokens
                  else "short_prefix")
    return {
        "metric": f"{model} gateway TTFT p50",
        "value": round(p50, 1),
        "unit": "ms",
        "vs_baseline": None,  # reference publishes no TTFT (BASELINE.md)
        "extra": {"p95_ms": round(p95, 1), "requests": n_requests,
                  "warm_prefix_p50_ms": round(statistics.median(warm), 1),
                  "prefix_cache": prefix_stats,
                  long_label: {
                      "prefix_tokens": long_tokens,
                      "target_prefix_tokens": target_tokens,
                      "materialized_prefix_tokens": materialized,
                      "cold_p50_ms": round(lc50, 1),
                      "warm_p50_ms": round(lw50, 1),
                      "ttft_reduction_pct": round(100 * (1 - lw50 / lc50), 1),
                      "prefix_cache": long_prefix_stats,
                  },
                  "obs": obs_extra,
                  "platform": "tpu" if on_tpu else "cpu"},
    }


def main() -> None:
    os.environ.setdefault("CROWDLLAMA_TPU_TEST_MODE", "1")
    result = asyncio.run(run())
    print(json.dumps(result))


if __name__ == "__main__":
    sys.exit(main())
