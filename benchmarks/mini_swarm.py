"""Real-engine mini-swarm benchmark (ROADMAP VERDICT #5, config-5 shape).

swarm_scaling.py measures the control plane with FakeEngine workers;
this phase puts 2-4 REAL tiny-model JaxEngines behind the gateway on
CPU and measures what a client actually experiences end to end under
concurrent load: sustained generated tokens/sec across the swarm and
per-request TTFT (first streamed NDJSON frame), crossing HTTP ->
routing -> p2p stream -> scheduler/prefill -> decode -> stream protocol.

The SAME topology and load is then re-run with FakeEngine workers — the
control-plane control curve: the gap between the two isolates engine
time (prefill + decode) from routing/transport, per swarm size.

Prints ONE JSON line; value is end-to-end tokens/sec at the largest
real-engine swarm, extra holds both curves.

Env overrides:
  CROWDLLAMA_BENCH_MINI_SIZES    swarm sizes      (default "2,4")
  CROWDLLAMA_BENCH_MINI_REQUESTS requests per size (default 24)
  CROWDLLAMA_BENCH_MINI_CONCURRENCY in-flight cap  (default 4)
  CROWDLLAMA_BENCH_MINI_TOKENS   tokens per request (default 16)
"""

from __future__ import annotations

import sys
from pathlib import Path

sys.path.insert(0, str(Path(__file__).resolve().parent))
import _common  # noqa: F401,E402 - repo path + JAX platform bootstrap

import asyncio
import json
import os
import statistics
import time

MODEL = "tiny-test"


async def _measure(kind: str, sizes: list[int], n_requests: int,
                   concurrency: int, num_predict: int) -> list[dict]:
    import aiohttp
    from crowdllama_tpu.utils.crypto_compat import Ed25519PrivateKey

    from crowdllama_tpu.config import Configuration, Intervals
    from crowdllama_tpu.engine.engine import FakeEngine, JaxEngine
    from crowdllama_tpu.gateway.gateway import Gateway
    from crowdllama_tpu.net.discovery import new_host_and_dht
    from crowdllama_tpu.peer.peer import Peer

    def cfg(**kw):
        c = Configuration(listen_host="127.0.0.1", model=MODEL,
                          intervals=Intervals.default())
        for k, v in kw.items():
            setattr(c, k, v)
        return c

    boot_host, _ = await new_host_and_dht(
        Ed25519PrivateKey.generate(), listen_host="127.0.0.1")
    bootstrap = f"127.0.0.1:{boot_host.listen_port}"

    consumer = Peer(Ed25519PrivateKey.generate(),
                    cfg(bootstrap_peers=[bootstrap]),
                    engine=FakeEngine(models=[]), worker_mode=False)
    await consumer.start()
    gateway = Gateway(consumer, port=0, host="127.0.0.1")
    await gateway.start()
    gw_port = gateway._runner.addresses[0][1]
    url = f"http://127.0.0.1:{gw_port}/api/chat"

    workers: list[Peer] = []
    engines: list = []
    curve: list[dict] = []

    async def add_worker() -> None:
        if kind == "real":
            eng = JaxEngine(cfg(), max_context_length=256)
            await eng.start()
            engines.append(eng)
        else:
            eng = FakeEngine(models=[MODEL])
        w = Peer(Ed25519PrivateKey.generate(),
                 cfg(bootstrap_peers=[bootstrap]), engine=eng,
                 worker_mode=True)
        workers.append(w)  # before start: finally stops partial starts
        await w.start()

    try:
        async with aiohttp.ClientSession() as session:
            for size in sizes:
                t_grow = time.monotonic()
                # Sequential: real engines compile on the same device;
                # parallel starts interleave compilations for no win.
                while len(workers) < size:
                    await add_worker()
                deadline = time.monotonic() + 120
                while time.monotonic() < deadline:
                    healthy = {p.peer_id for p in
                               consumer.peer_manager.get_healthy_peers()
                               if p.is_worker}
                    if len(healthy) >= size:
                        break
                    await asyncio.sleep(0.1)
                else:
                    raise RuntimeError(f"discovery stalled at size {size}")
                discovery_s = time.monotonic() - t_grow

                sem = asyncio.Semaphore(concurrency)
                ttfts: list[float] = []
                tokens = [0]
                hits: dict[str, int] = {}

                async def one(i: int) -> None:
                    # Unique leading tag: with the paged engines' prefix
                    # cache on, a repeated prompt would measure cache hits.
                    body = {"model": MODEL, "stream": True,
                            "options": {"num_predict": num_predict},
                            "messages": [{"role": "user",
                                          "content": f"{i:04d} mini swarm "
                                                     "load test prompt"}]}
                    async with sem:
                        t0 = time.monotonic()
                        first = True
                        async with session.post(url, json=body) as resp:
                            assert resp.status == 200, await resp.text()
                            async for line in resp.content:
                                if not line.strip():
                                    continue
                                if first:
                                    ttfts.append(
                                        (time.monotonic() - t0) * 1000)
                                    first = False
                                d = json.loads(line)
                                if d.get("done"):
                                    tokens[0] += d.get(
                                        "eval_count",
                                        num_predict)
                                    wid = d.get("worker_id", "")
                                    hits[wid] = hits.get(wid, 0) + 1

                # Prime every worker once (compile paths, warm streams)
                # before the timed window.
                await asyncio.gather(*(one(-1 - k) for k in range(size)))
                ttfts.clear(); tokens[0] = 0; hits.clear()

                t0 = time.monotonic()
                await asyncio.gather(*(one(i) for i in range(n_requests)))
                dt = time.monotonic() - t0
                ttfts.sort()
                point = {
                    "workers": size,
                    "tokens_per_sec": round(tokens[0] / dt, 1),
                    "requests_per_sec": round(n_requests / dt, 1),
                    "ttft_p50_ms": round(statistics.median(ttfts), 1),
                    "ttft_p95_ms": round(
                        ttfts[max(0, int(len(ttfts) * 0.95) - 1)], 1),
                    "tokens_generated": tokens[0],
                    "distinct_workers_hit": len(hits),
                    "discovery_s": round(discovery_s, 2),
                }
                curve.append(point)
                print(f"# {kind} size={size}: {point['tokens_per_sec']} "
                      f"tok/s, ttft p50 {point['ttft_p50_ms']}ms, "
                      f"{len(hits)} workers hit", file=sys.stderr)
    finally:
        await gateway.stop()
        await consumer.stop()
        for w in workers:
            await w.stop()
        for e in engines:
            await e.stop()
        await boot_host.close()
    return curve


async def _drain_phase(n_requests: int, concurrency: int,
                       num_predict: int) -> dict:
    """Live-migration phase (docs/ROBUSTNESS.md): 4 real engines under
    streaming load, one of them drained mid-burst.  Every in-flight
    stream must complete (migrated to a survivor with KV handoff), and
    NEW requests keep landing on the survivors — zero failed streams is
    the acceptance bar."""
    import aiohttp
    from crowdllama_tpu.utils.crypto_compat import Ed25519PrivateKey

    from crowdllama_tpu.config import Configuration, Intervals
    from crowdllama_tpu.engine.engine import FakeEngine, JaxEngine
    from crowdllama_tpu.gateway.gateway import Gateway
    from crowdllama_tpu.net.discovery import new_host_and_dht
    from crowdllama_tpu.peer.peer import Peer

    size = 4

    def cfg(**kw):
        c = Configuration(listen_host="127.0.0.1", model=MODEL,
                          intervals=Intervals.default(),
                          kv_layout="paged", kv_page_size=16,
                          kv_ship=True, kv_ship_min_tokens=16)
        for k, v in kw.items():
            setattr(c, k, v)
        return c

    boot_host, _ = await new_host_and_dht(
        Ed25519PrivateKey.generate(), listen_host="127.0.0.1")
    bootstrap = f"127.0.0.1:{boot_host.listen_port}"
    consumer = Peer(Ed25519PrivateKey.generate(),
                    cfg(bootstrap_peers=[bootstrap]),
                    engine=FakeEngine(models=[]), worker_mode=False)
    await consumer.start()
    gateway = Gateway(consumer, port=0, host="127.0.0.1", kv_ship=True)
    await gateway.start()
    gw_port = gateway._runner.addresses[0][1]
    url = f"http://127.0.0.1:{gw_port}/api/chat"

    workers: list[Peer] = []
    engines: list = []
    try:
        for _ in range(size):
            eng = JaxEngine(cfg(), max_context_length=256)
            await eng.start()
            engines.append(eng)
            w = Peer(Ed25519PrivateKey.generate(),
                     cfg(bootstrap_peers=[bootstrap]), engine=eng,
                     worker_mode=True)
            workers.append(w)
            await w.start()
        deadline = time.monotonic() + 120
        while time.monotonic() < deadline:
            healthy = {p.peer_id for p in
                       consumer.peer_manager.get_healthy_peers()
                       if p.is_worker}
            if len(healthy) >= size:
                break
            await asyncio.sleep(0.1)
        else:
            raise RuntimeError("discovery stalled in drain phase")

        sem = asyncio.Semaphore(concurrency)
        completed = [0]
        failed = [0]

        async with aiohttp.ClientSession() as session:
            async def one(i: int) -> None:
                # Multi-page prompt (page_size 16): the drained worker's
                # prefill pages are worth fetching on migration.
                body = {"model": MODEL, "stream": True,
                        "options": {"num_predict": num_predict},
                        "messages": [{"role": "user",
                                      "content": f"{i:04d} drain phase "
                                      "stream that must survive a mid-"
                                      "burst worker drain with its KV "
                                      "handed to a surviving engine"}]}
                async with sem:
                    try:
                        async with session.post(url, json=body) as resp:
                            assert resp.status == 200, await resp.text()
                            last = None
                            async for line in resp.content:
                                if line.strip():
                                    last = json.loads(line)
                            ok = (last is not None and last.get("done")
                                  and last.get("done_reason") != "error"
                                  and "error" not in last)
                            completed[0] += ok
                            failed[0] += not ok
                    except Exception:
                        failed[0] += 1

            # Prime compile paths outside the measured burst.
            await asyncio.gather(*(one(-1 - k) for k in range(size)))
            completed[0] = 0
            failed[0] = 0

            t0 = time.monotonic()
            burst = [asyncio.create_task(one(i)) for i in range(n_requests)]

            async def drain_one() -> tuple[str, float, int]:
                await asyncio.sleep(0.3)   # let streams get in flight
                # Drain the worker actually serving the burst — routing
                # may concentrate load, and draining an idle worker
                # would never exercise the mid-stream MigrateFrame path.
                def load(k: int) -> tuple:
                    g = engines[k].obs_gauges()
                    return (g.get("active_slots", 0.0),
                            g.get("pending_depth", 0.0))
                idx = max(range(size), key=load)
                td = time.monotonic()
                migrated = await workers[idx].drain()
                return (workers[idx].peer_id, time.monotonic() - td,
                        migrated)

            (drained_id, drain_s, migrated), *_ = await asyncio.gather(
                drain_one(), *burst)
            dt = time.monotonic() - t0

        gw_m = gateway.obs.metrics
        replayed = sum(e.obs.metrics.replayed_prefill_tokens
                       for e in engines)
        point = {
            "workers": size,
            "streams_total": n_requests,
            "streams_completed": completed[0],
            "streams_failed": failed[0],
            "drained_worker": drained_id[:8],
            "drain_call_s": round(drain_s, 3),
            "inflight_migrated": migrated,
            "gateway_migrated_streams": gw_m.migrated_streams,
            "replayed_prefill_tokens": replayed,
            "wall_s": round(dt, 2),
        }
        print(f"# drain phase: {completed[0]}/{n_requests} streams ok, "
              f"{migrated} migrated off {drained_id[:8]} in "
              f"{drain_s * 1000:.0f}ms, replayed_prefill={replayed}",
              file=sys.stderr)
        return point
    finally:
        await gateway.stop()
        await consumer.stop()
        for w in workers:
            await w.stop()
        for e in engines:
            await e.stop()
        await boot_host.close()


async def run() -> dict:
    sizes = [int(x) for x in os.environ.get(
        "CROWDLLAMA_BENCH_MINI_SIZES", "2,4").split(",") if x.strip()]
    n_requests = int(os.environ.get("CROWDLLAMA_BENCH_MINI_REQUESTS", "24"))
    concurrency = int(
        os.environ.get("CROWDLLAMA_BENCH_MINI_CONCURRENCY", "4"))
    num_predict = int(os.environ.get("CROWDLLAMA_BENCH_MINI_TOKENS", "16"))

    real = await _measure("real", sizes, n_requests, concurrency,
                          num_predict)
    control = await _measure("fake", sizes, n_requests, concurrency,
                             num_predict)
    drain = await _drain_phase(n_requests, concurrency, num_predict)

    head = real[-1]
    ctrl = control[-1]
    return {
        "metric": (f"mini-swarm e2e {MODEL} tokens/sec, "
                   f"{sizes[-1]} real engines behind the gateway"),
        "value": head["tokens_per_sec"],
        "unit": "tokens/sec",
        "vs_baseline": None,  # reference publishes no e2e numbers
        "extra": {
            "real_curve": real,
            "control_curve_fake_engine": control,
            # Engine share of TTFT: real minus control at the largest
            # size — what prefill+decode add on top of the control plane.
            "engine_ttft_ms": round(
                head["ttft_p50_ms"] - ctrl["ttft_p50_ms"], 1),
            "drain_phase": drain,
            "requests_per_size": n_requests,
            "concurrency": concurrency,
            "num_predict": num_predict,
            "note": "control curve = identical topology and load with "
                    "FakeEngine workers (control-plane only)",
        },
    }


def main() -> None:
    os.environ.setdefault("CROWDLLAMA_TPU_TEST_MODE", "1")
    os.environ.setdefault("JAX_PLATFORMS", "cpu")
    result = asyncio.run(run())
    print(json.dumps(result))


if __name__ == "__main__":
    sys.exit(main())
