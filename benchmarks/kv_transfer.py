"""Swarm KV shipping benchmark: prefix fetch vs prefill recompute TTFT
(docs/KV_TRANSFER.md).

Topology on loopback, all real sockets: DHT bootstrap + donor worker
(paged JaxEngine whose prefix cache holds the shared prefix) + a cold
fetcher worker.  For each prefix length the bench times the cold
worker's non-streamed serve of the SAME prompt two ways:

  recompute  plain prefill, no donor hint (the pre-KV-ship behaviour)
  fetch      kv_donor set -> the worker dials the donor over the real
             authenticated inference stream, imports the prefix pages,
             and prefills only the suffix

Loopback RTT is ~0, which understates a real swarm, so the fetch side
also SWEEPS injected RTT through the same transparent delay relay
ep_dispatch.py uses (injected RTT = 2x the one-way delay): the relay
fronts the donor's listen port and the fetcher's DHT lookup is rewired
to the relay, so only the KV-fetch dial pays the injected latency.

Each timed trial uses a UNIQUE prompt (served on the donor first) so
the fetcher is genuinely cold every time — no prefix-cache carryover
between trials, no cache clearing.

Prints ONE JSON line; value is the TTFT reduction (%) at the longest
prefix on loopback, extra carries both curves per RTT plus
``break_even_prefix_tokens`` — the regressed prefix length where fetch
starts beating recompute (per RTT point).

Env overrides:
  CROWDLLAMA_BENCH_KV_MODEL     test-scale model (default "tiny-test-gemma")
  CROWDLLAMA_BENCH_KV_PREFIXES  prefix token targets (default "64,128,240")
  CROWDLLAMA_BENCH_KV_RTTS      injected RTT sweep, ms (default "0,5,20")
  CROWDLLAMA_BENCH_KV_TRIALS    timed trials per point (default 5)
"""

from __future__ import annotations

import sys
from pathlib import Path

sys.path.insert(0, str(Path(__file__).resolve().parent))
import _common  # noqa: F401,E402 - repo path + JAX platform bootstrap

import asyncio
import json
import os
import statistics
import time
from dataclasses import replace

from crowdllama_tpu.testing.netem import DelayProxy  # noqa: E402

# tiny-test-gemma is the DEEPEST test-scale model (4 layers): prefill
# compute per token is the thing a fetch avoids, and the 2-layer toys
# price it so low that transport overhead swamps the comparison.
MODEL = os.environ.get("CROWDLLAMA_BENCH_KV_MODEL", "tiny-test-gemma")
PAGE = 16
CTX = 256  # the test-scale model configs clamp context to 256


async def run() -> dict:
    from crowdllama_tpu.utils.crypto_compat import Ed25519PrivateKey

    from crowdllama_tpu.config import Configuration, Intervals
    from crowdllama_tpu.core.messages import (
        create_generate_request,
        extract_generate_response,
    )
    from crowdllama_tpu.engine.engine import JaxEngine
    from crowdllama_tpu.net.discovery import new_host_and_dht
    from crowdllama_tpu.peer.peer import Peer

    prefixes = [int(x) for x in os.environ.get(
        "CROWDLLAMA_BENCH_KV_PREFIXES", "64,128,240").split(",") if x.strip()]
    rtts = [float(x) for x in os.environ.get(
        "CROWDLLAMA_BENCH_KV_RTTS", "0,5,20").split(",") if x.strip()]
    trials = int(os.environ.get("CROWDLLAMA_BENCH_KV_TRIALS", "5"))

    def cfg(**kw):
        c = Configuration(listen_host="127.0.0.1", model=MODEL,
                          intervals=Intervals.default(),
                          kv_layout="paged", kv_page_size=PAGE,
                          kv_ship=True, kv_ship_min_tokens=PAGE)
        for k, v in kw.items():
            setattr(c, k, v)
        return c

    boot_host, _ = await new_host_and_dht(
        Ed25519PrivateKey.generate(), listen_host="127.0.0.1")
    bootstrap = f"127.0.0.1:{boot_host.listen_port}"

    eng_a = JaxEngine(cfg(), max_context_length=CTX)          # donor
    eng_b = JaxEngine(cfg(), max_context_length=CTX)          # fetcher
    await eng_a.start()
    await eng_b.start()
    peer_a = Peer(Ed25519PrivateKey.generate(),
                  cfg(bootstrap_peers=[bootstrap]), engine=eng_a,
                  worker_mode=True)
    peer_b = Peer(Ed25519PrivateKey.generate(),
                  cfg(bootstrap_peers=[bootstrap]), engine=eng_b,
                  worker_mode=True)
    await peer_a.start()
    await peer_b.start()

    # The fetcher's donor lookup, optionally rewired through the relay.
    real_find = peer_b.dht.find_peer
    proxy_port: list[int | None] = [None]

    async def find_peer(pid):
        contact = await real_find(pid)
        if contact is not None and pid == peer_a.peer_id \
                and proxy_port[0] is not None:
            contact = replace(contact, port=proxy_port[0])
        return contact

    peer_b.dht.find_peer = find_peer

    # Prompts sized in TOKENS through the engine's own tokenizer; a unique
    # leading tag makes every page of every trial's chain distinct.
    unit = "ship pages not prefills across the swarm. "
    base = ""
    need = max(prefixes)
    while len(eng_a.tokenizer.encode("0000 " + base)) < need:
        base += unit

    def prompt_for(target: int, tag: int) -> str:
        text = f"{tag:04d} "
        while len(eng_a.tokenizer.encode(text)) < target:
            text += unit
        # Trim to the exact token target (the tokenizer may be char-level,
        # so one appended unit can overshoot by dozens of tokens).
        return eng_a.tokenizer.decode(eng_a.tokenizer.encode(text)[:target])

    tag = [0]

    def next_tag() -> int:
        tag[0] += 1
        return tag[0]

    async def serve(engine, prompt: str, donor: str = "") -> float:
        """Non-streamed serve, 1 new token: wall time ~= TTFT."""
        msg = create_generate_request(MODEL, prompt, max_tokens=1)
        if donor:
            msg.generate_request.kv_donor = donor
        t0 = time.monotonic()
        reply = await engine.handle(msg, worker_id="bench")
        dt = (time.monotonic() - t0) * 1000
        resp = extract_generate_response(reply)
        assert resp.done_reason != "error", resp.response
        return dt

    sweep: list[dict] = []
    recompute: dict[int, float] = {}
    bad_fetches = 0
    try:
        # Wait until the fetcher can resolve the donor in the DHT.
        deadline = time.monotonic() + 30
        while time.monotonic() < deadline:
            if await real_find(peer_a.peer_id) is not None:
                break
            await asyncio.sleep(0.1)
        else:
            raise RuntimeError("donor never became resolvable")

        # Warmup: pay prefill-bucket XLA compiles on both engines and the
        # import-scatter compile on the fetcher, per prefix length.
        for L in prefixes:
            p = prompt_for(L, next_tag())
            await serve(eng_a, p)
            await serve(eng_b, prompt_for(L, next_tag()))
            await serve(eng_b, p, donor=peer_a.peer_id)

        # Recompute curve: RTT-independent (no donor dial), once per L.
        for L in prefixes:
            lat = []
            for _ in range(trials):
                lat.append(await serve(eng_b, prompt_for(L, next_tag())))
            recompute[L] = statistics.median(lat)

        for rtt_ms in rtts:
            proxy = None
            if rtt_ms > 0:
                proxy = DelayProxy(peer_a.host.listen_port, rtt_ms / 2000.0)
                proxy_port[0] = await proxy.start()
            # Drop pooled donor streams from the previous point: every RTT
            # point must dial through ITS relay, then reuse that stream
            # (the steady state the fetch path runs in).
            if eng_b._kv_streams is not None:
                eng_b._kv_streams.close_key(peer_a.peer_id)
            p = prompt_for(prefixes[0], next_tag())
            await serve(eng_a, p)
            await serve(eng_b, p, donor=peer_a.peer_id)  # establish stream
            points = []
            try:
                for L in prefixes:
                    lat = []
                    for _ in range(trials):
                        p = prompt_for(L, next_tag())
                        await serve(eng_a, p)       # donor caches the prefix
                        imp0 = eng_b._runner.kv_pages_imported
                        fb0 = eng_b.obs.metrics.kv_ship["fallbacks"]
                        lat.append(await serve(eng_b, p,
                                               donor=peer_a.peer_id))
                        if (eng_b._runner.kv_pages_imported == imp0
                                or eng_b.obs.metrics.kv_ship["fallbacks"]
                                != fb0):
                            bad_fetches += 1  # fell back: not a fetch number
                    fetch_ms = statistics.median(lat)
                    points.append({
                        "prefix_tokens": L,
                        "fetch_ttft_ms": round(fetch_ms, 1),
                        "recompute_ttft_ms": round(recompute[L], 1),
                        "ttft_reduction_pct": round(
                            100 * (1 - fetch_ms / recompute[L]), 1),
                    })
                    print(f"# rtt {rtt_ms:g}ms prefix {L}: fetch "
                          f"{fetch_ms:.1f}ms vs recompute "
                          f"{recompute[L]:.1f}ms", file=sys.stderr)
            finally:
                proxy_port[0] = None
                if proxy is not None:
                    await proxy.close()

            # Break-even prefix length: least-squares lines through both
            # curves; fetch cost is ~flat in L (dial + transfer), recompute
            # grows with L, so the crossing is where shipping starts
            # winning.  None when fetch never catches up in the sweep.
            break_even = None
            if len(points) >= 2:
                xs = [p["prefix_tokens"] for p in points]
                yr = [p["recompute_ttft_ms"] for p in points]
                yf = [p["fetch_ttft_ms"] for p in points]
                mx = sum(xs) / len(xs)
                den = sum((x - mx) ** 2 for x in xs)
                br = sum((x - mx) * (y - sum(yr) / len(yr))
                         for x, y in zip(xs, yr)) / den
                bf = sum((x - mx) * (y - sum(yf) / len(yf))
                         for x, y in zip(xs, yf)) / den
                ar = sum(yr) / len(yr) - br * mx
                af = sum(yf) / len(yf) - bf * mx
                if br > bf:
                    break_even = round(max(0.0, (af - ar) / (br - bf)))
            sweep.append({"rtt_ms": rtt_ms, "points": points,
                          "break_even_prefix_tokens": break_even})
    finally:
        for stop in (peer_b.stop, peer_a.stop, eng_b.stop, eng_a.stop,
                     boot_host.close):
            try:
                await stop()
            except Exception:
                pass  # teardown must not mask the benchmark's real error

    loopback = min(sweep, key=lambda s: s["rtt_ms"])
    head = loopback["points"][-1]
    kv_hist = eng_b.obs.metrics.kv_fetch_seconds
    return {
        "metric": (f"{MODEL} KV fetch vs prefill recompute, TTFT reduction "
                   f"at {head['prefix_tokens']}-token prefix (loopback)"),
        "value": head["ttft_reduction_pct"],
        "unit": "%",
        "vs_baseline": None,  # the reference always recomputes
        "extra": {
            "page_tokens": PAGE,
            "trials": trials,
            "rtt_sweep": sweep,
            "break_even_prefix_tokens":
                loopback["break_even_prefix_tokens"],
            "fetch_hist_p50_ms": round(kv_hist.quantile(0.5) * 1000, 1),
            "fetch_hist_count": kv_hist.count,
            "bytes_shipped": eng_b.obs.metrics.kv_ship["bytes"],
            "pages_imported": eng_b._runner.kv_pages_imported,
            "fallbacks_during_timed_trials": bad_fetches,
            "note": "fetch dials the donor over the real authenticated "
                    "p2p stream; rtt>0 points run through a transparent "
                    "delay relay on the donor dial only",
        },
    }


def main() -> None:
    os.environ.setdefault("CROWDLLAMA_TPU_TEST_MODE", "1")
    os.environ.setdefault("JAX_PLATFORMS", "cpu")
    result = asyncio.run(run())
    print(json.dumps(result))


if __name__ == "__main__":
    sys.exit(main())
