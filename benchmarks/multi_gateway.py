"""Replicated gateway plane benchmark (ISSUE 7, docs/ROBUSTNESS.md).

Three phases over one shared FakeEngine worker swarm (control-plane
focus — mini_swarm.py owns real-engine e2e):

  scaling   req/s with 1 -> 4 gateway replicas round-robined by the
            client.  All replicas live in ONE process/event loop, so the
            curve measures the coordination overhead a replica adds
            (gossip rounds, shared swarm), NOT multi-core scaling.
  affinity  cross-replica affinity hit-rate: turn 1 of each conversation
            lands on a random replica, the continuation on a DIFFERENT
            one — a hit means the gossiped pin routed it to the worker
            that served turn 1 (hot KV), which random load-based routing
            would only do 1/workers of the time.
  tenants   per-tenant fair admission: a hot tenant floods past its
            token-bucket quota while a light tenant keeps its trickle.
            Reported: hot-tenant shed count and the light tenant's p95
            TTFT vs its solo baseline (the ~15% isolation bar).

Prints ONE JSON line; value is req/s at the largest replica count.

Env overrides:
  CROWDLLAMA_BENCH_MGW_SIZES     replica counts    (default "1,2,4")
  CROWDLLAMA_BENCH_MGW_REQUESTS  requests per size (default 48)
  CROWDLLAMA_BENCH_MGW_CONCURRENCY in-flight cap   (default 8)
  CROWDLLAMA_BENCH_MGW_TOKENS    tokens per request (default 8)
  CROWDLLAMA_BENCH_MGW_CONVOS    conversations in the affinity phase
                                 (default 12)
"""

from __future__ import annotations

import sys
from pathlib import Path

sys.path.insert(0, str(Path(__file__).resolve().parent))
import _common  # noqa: F401,E402 - repo path + JAX platform bootstrap

import asyncio
import json
import os
import random
import statistics
import time

MODEL = "tiny-test"
N_WORKERS = 4


def _cfg(**kw):
    from crowdllama_tpu.config import Configuration, Intervals

    c = Configuration(listen_host="127.0.0.1", model=MODEL,
                      intervals=Intervals.default())
    for k, v in kw.items():
        setattr(c, k, v)
    return c


async def _swarm(n_workers: int):
    """Boot host + FakeEngine workers; returns (bootstrap, teardown)."""
    from crowdllama_tpu.utils.crypto_compat import Ed25519PrivateKey

    from crowdllama_tpu.engine.engine import FakeEngine
    from crowdllama_tpu.net.discovery import new_host_and_dht
    from crowdllama_tpu.peer.peer import Peer

    boot_host, _ = await new_host_and_dht(
        Ed25519PrivateKey.generate(), listen_host="127.0.0.1")
    bootstrap = f"127.0.0.1:{boot_host.listen_port}"
    workers = []
    for _ in range(n_workers):
        w = Peer(Ed25519PrivateKey.generate(),
                 _cfg(bootstrap_peers=[bootstrap]),
                 engine=FakeEngine(models=[MODEL]), worker_mode=True)
        await w.start()
        workers.append(w)

    async def teardown():
        for w in workers:
            await w.stop()
        await boot_host.close()

    return bootstrap, teardown


async def _replicas(bootstrap: str, n: int, quotas_spec: str = ""):
    """N gateway replicas (consumer + gossip + gateway each), fully
    meshed; returns (gateways, gnodes, ports, teardown)."""
    from crowdllama_tpu.utils.crypto_compat import Ed25519PrivateKey

    from crowdllama_tpu.engine.engine import FakeEngine
    from crowdllama_tpu.gateway.gateway import Gateway
    from crowdllama_tpu.peer.peer import Peer
    from crowdllama_tpu.swarm.gossip import (
        GossipNode, TenantQuotas, parse_tenant_quotas)

    consumers = []
    for _ in range(n):
        c = Peer(Ed25519PrivateKey.generate(),
                 _cfg(bootstrap_peers=[bootstrap]),
                 engine=FakeEngine(models=[]), worker_mode=False)
        await c.start()
        consumers.append(c)

    gateways, gnodes = [], []
    for i, c in enumerate(consumers):
        mesh = [f"127.0.0.1:{o.host.listen_port}"
                for j, o in enumerate(consumers) if j != i]
        quotas = (TenantQuotas(parse_tenant_quotas(quotas_spec),
                               node_id=c.peer_id) if quotas_spec else None)
        node = GossipNode(c, peers=mesh, interval=0.3, quotas=quotas)
        gw = Gateway(c, port=0, host="127.0.0.1", gossip=node,
                     tenant_quotas=quotas)
        node.metrics = gw.obs.metrics
        await node.start()
        await gw.start()
        gnodes.append(node)
        gateways.append(gw)
    ports = [g._runner.addresses[0][1] for g in gateways]

    deadline = time.monotonic() + 120
    while time.monotonic() < deadline:
        if all(len({p.peer_id for p in c.peer_manager.get_healthy_peers()
                    if p.is_worker}) >= N_WORKERS for c in consumers):
            break
        await asyncio.sleep(0.1)
    else:
        raise RuntimeError("discovery stalled")

    async def teardown():
        for node in gnodes:
            await node.stop(save=False)
        for gw in gateways:
            await gw.stop()
        for c in consumers:
            await c.stop()

    return gateways, gnodes, ports, teardown


async def _one(session, port: int, body: dict,
               headers: dict | None = None) -> tuple[float, dict]:
    """One streamed chat; returns (ttft_ms, final_frame)."""
    t0 = time.monotonic()
    ttft = None
    last = {}
    async with session.post(f"http://127.0.0.1:{port}/api/chat",
                            json=body, headers=headers or {}) as resp:
        if resp.status != 200:
            await resp.read()
            return -1.0, {"status": resp.status}
        async for line in resp.content:
            if not line.strip():
                continue
            if ttft is None:
                ttft = (time.monotonic() - t0) * 1000
            last = json.loads(line)
    return (ttft if ttft is not None else -1.0), last


def _chat(content: str, n: int, messages=None) -> dict:
    return {"model": MODEL, "stream": True,
            "options": {"num_predict": n},
            "messages": messages or [{"role": "user", "content": content}]}


async def _scaling_phase(bootstrap, sizes, n_requests, concurrency,
                         num_predict) -> list[dict]:
    import aiohttp

    curve = []
    for size in sizes:
        gateways, _gn, ports, teardown = await _replicas(bootstrap, size)
        try:
            sem = asyncio.Semaphore(concurrency)
            ttfts: list[float] = []

            async def one(i: int) -> None:
                async with sem:
                    ttft, last = await _one(
                        s, ports[i % size],
                        _chat(f"{i:04d} multi gateway load", num_predict))
                    assert last.get("done"), last
                    ttfts.append(ttft)

            async with aiohttp.ClientSession() as s:
                await asyncio.gather(*(one(-1 - k) for k in range(size)))
                ttfts.clear()
                t0 = time.monotonic()
                await asyncio.gather(*(one(i) for i in range(n_requests)))
                dt = time.monotonic() - t0
            ttfts.sort()
            point = {
                "replicas": size,
                "requests_per_sec": round(n_requests / dt, 1),
                "ttft_p50_ms": round(statistics.median(ttfts), 1),
                "ttft_p95_ms": round(
                    ttfts[max(0, int(len(ttfts) * 0.95) - 1)], 1),
            }
            curve.append(point)
            print(f"# scaling replicas={size}: "
                  f"{point['requests_per_sec']} req/s, "
                  f"ttft p50 {point['ttft_p50_ms']}ms", file=sys.stderr)
        finally:
            await teardown()
    return curve


async def _affinity_phase(bootstrap, n_replicas, n_convos,
                          num_predict) -> dict:
    import aiohttp

    from crowdllama_tpu.gateway.gateway import Gateway

    gateways, gnodes, ports, teardown = await _replicas(
        bootstrap, n_replicas)
    try:
        rng = random.Random(7)
        cross_hits = 0
        continuations = 0
        async with aiohttp.ClientSession() as s:
            for c in range(n_convos):
                content = f"conversation {c:03d} about replicated gateways"
                turn1 = [{"role": "user", "content": content}]
                first = rng.randrange(n_replicas)
                _, last = await _one(s, ports[first],
                                     _chat(content, num_predict))
                worker1 = last.get("worker_id", "")

                # Wait for the pin to gossip to every OTHER replica.
                akey, _ = Gateway._affinity_key(MODEL, turn1, "")
                deadline = time.monotonic() + 10
                while time.monotonic() < deadline:
                    if all(n.lookup_affinity(akey) for i, n in
                           enumerate(gnodes) if i != first):
                        break
                    await asyncio.sleep(0.05)

                other = rng.choice(
                    [i for i in range(n_replicas) if i != first])
                cont = turn1 + [
                    {"role": "assistant",
                     "content": last.get("message", {}).get("content", "")},
                    {"role": "user", "content": "continue"}]
                _, last2 = await _one(
                    s, ports[other], _chat("", num_predict, messages=cont))
                continuations += 1
                cross_hits += last2.get("worker_id", "") == worker1
        gossip_hits = sum(g._gossip_affinity_hits for g in gateways)
        point = {
            "replicas": n_replicas,
            "conversations": n_convos,
            "continuations_cross_replica": continuations,
            "same_worker_hits": cross_hits,
            "cross_replica_hit_rate": round(cross_hits / continuations, 3),
            "gossip_affinity_lookups_hit": gossip_hits,
            "random_routing_expectation": round(1 / N_WORKERS, 3),
        }
        print(f"# affinity: {cross_hits}/{continuations} continuations "
              f"pinned cross-replica (random would be "
              f"~{point['random_routing_expectation']})", file=sys.stderr)
        return point
    finally:
        await teardown()


async def _tenant_phase(bootstrap, num_predict) -> dict:
    """Hot tenant floods 2 replicas past its quota; the light tenant's
    p95 TTFT must stay near its solo baseline (the isolation bar)."""
    import aiohttp

    n_light = 16
    quotas = "default=1000,hot=8"
    gateways, _gn, ports, teardown = await _replicas(
        bootstrap, 2, quotas_spec=quotas)
    try:
        async def light_run(s) -> list[float]:
            ttfts = []
            for i in range(n_light):
                ttft, last = await _one(
                    s, ports[i % 2], _chat(f"light {i:03d}", num_predict),
                    headers={"X-Tenant": "light"})
                if last.get("done"):
                    ttfts.append(ttft)
                await asyncio.sleep(0.02)
            ttfts.sort()
            return ttfts

        def p95(ttfts: list[float]) -> float:
            return ttfts[max(0, int(len(ttfts) * 0.95) - 1)]

        async with aiohttp.ClientSession() as s:
            solo = await light_run(s)

            stop = asyncio.Event()
            flood_sent = [0]

            async def flood(k: int) -> None:
                i = 0
                while not stop.is_set():
                    await _one(s, ports[(k + i) % 2],
                               _chat(f"hot {k}:{i}", num_predict),
                               headers={"X-Tenant": "hot"})
                    flood_sent[0] += 1
                    i += 1

            flooders = [asyncio.create_task(flood(k)) for k in range(8)]
            try:
                loaded = await light_run(s)
            finally:
                stop.set()
                for t in flooders:
                    t.cancel()
                await asyncio.gather(*flooders, return_exceptions=True)

        shed = sum(g.obs.metrics.tenant_shed.get("hot", 0)
                   for g in gateways)
        admitted = sum(g.obs.metrics.tenant_admitted.get("hot", 0)
                       for g in gateways)
        point = {
            "quotas": quotas,
            "hot_requests_sent": flood_sent[0],
            "hot_admitted": admitted,
            "hot_shed": shed,
            "light_requests": n_light,
            "light_completed_under_load": len(loaded),
            "light_ttft_p95_solo_ms": round(p95(solo), 1),
            "light_ttft_p95_loaded_ms": round(p95(loaded), 1),
            "light_p95_ratio": round(p95(loaded) / max(p95(solo), 1e-9), 2),
        }
        print(f"# tenants: hot shed {shed}/{flood_sent[0]}, light p95 "
              f"{point['light_ttft_p95_loaded_ms']}ms vs solo "
              f"{point['light_ttft_p95_solo_ms']}ms "
              f"(x{point['light_p95_ratio']})", file=sys.stderr)
        return point
    finally:
        await teardown()


async def run() -> dict:
    sizes = [int(x) for x in os.environ.get(
        "CROWDLLAMA_BENCH_MGW_SIZES", "1,2,4").split(",") if x.strip()]
    n_requests = int(os.environ.get("CROWDLLAMA_BENCH_MGW_REQUESTS", "48"))
    concurrency = int(
        os.environ.get("CROWDLLAMA_BENCH_MGW_CONCURRENCY", "8"))
    num_predict = int(os.environ.get("CROWDLLAMA_BENCH_MGW_TOKENS", "8"))
    n_convos = int(os.environ.get("CROWDLLAMA_BENCH_MGW_CONVOS", "12"))

    bootstrap, teardown = await _swarm(N_WORKERS)
    try:
        scaling = await _scaling_phase(bootstrap, sizes, n_requests,
                                       concurrency, num_predict)
        affinity = await _affinity_phase(bootstrap, max(sizes), n_convos,
                                         num_predict)
        tenants = await _tenant_phase(bootstrap, num_predict)
    finally:
        await teardown()

    head = scaling[-1]
    return {
        "metric": (f"multi-gateway req/s, {head['replicas']} replicas "
                   f"over {N_WORKERS} FakeEngine workers"),
        "value": head["requests_per_sec"],
        "unit": "requests/sec",
        "vs_baseline": None,  # reference has a single, unreplicated gateway
        "extra": {
            "scaling_curve": scaling,
            "affinity_phase": affinity,
            "tenant_phase": tenants,
            "requests_per_size": n_requests,
            "concurrency": concurrency,
            "num_predict": num_predict,
            "note": "replicas share one process/event loop: the scaling "
                    "curve bounds per-replica coordination overhead, not "
                    "multi-core speedup; tenant bar = light p95 within "
                    "~15% of solo while the hot tenant is shed",
        },
    }


def main() -> None:
    os.environ.setdefault("CROWDLLAMA_TPU_TEST_MODE", "1")
    os.environ.setdefault("JAX_PLATFORMS", "cpu")
    result = asyncio.run(run())
    print(json.dumps(result))


if __name__ == "__main__":
    sys.exit(main())
