"""Cross-worker expert-parallel dispatch benchmark (BASELINE config 4).

A 2-member MoE expert group on real loopback streams: the leader runs
attention/router and dispatches per-layer (token, expert) batches to a
remote expert bank over SHARD_PROTOCOL — one DCN round trip per MoE
layer per decode step, the intrinsic cost of cross-worker EP.  This
measures the CONTROL-PLANE price of that hop (framing, AEAD, asyncio)
with a tiny model so compute does not mask it; the dominant term on a
real deployment is the same per-layer round trip over real DCN RTTs.

Prints ONE JSON line; value is decode steps/sec through the 2-worker
pipeline, extra carries per-step latency and the single-worker (local
banks only) comparison.

Env overrides:
  CROWDLLAMA_BENCH_EP_STEPS   timed decode steps (default 64)
"""

from __future__ import annotations

import sys
from pathlib import Path

sys.path.insert(0, str(Path(__file__).resolve().parent))
import _common  # noqa: F401,E402 - repo path + JAX platform bootstrap

import asyncio
import json
import os
import time


async def run() -> dict:
    import jax
    import jax.numpy as jnp
    import numpy as np
    from cryptography.hazmat.primitives.asymmetric.ed25519 import (
        Ed25519PrivateKey,
    )

    from crowdllama_tpu.core.protocol import SHARD_PROTOCOL
    from crowdllama_tpu.engine.expert_service import (
        EPLeaderRunner,
        EPPipeline,
        ExpertBankRunner,
        ExpertBankService,
        LocalExpertBank,
        RemoteExpertBank,
        assign_experts,
    )
    from crowdllama_tpu.models import transformer as T
    from crowdllama_tpu.models.config import get_config
    from crowdllama_tpu.net.host import Host

    steps = int(os.environ.get("CROWDLLAMA_BENCH_EP_STEPS", "64"))
    cfg = get_config("tiny-test-moe", max_context_length=256)
    params = T.init_params(cfg, jax.random.PRNGKey(0), dtype=jnp.float32)
    prompt = [3, 1, 4, 1, 5, 9, 2, 6]

    async def decode_run(pipe, sid: str) -> tuple[float, list[float]]:
        logits = await pipe.prefill(sid, prompt, bucket=16)
        tok = int(np.argmax(logits))
        n = len(prompt)
        # Warmup (compile) steps, then timed.
        for _ in range(4):
            logits = await pipe.decode(sid, tok, n, n + 1)
            tok = int(np.argmax(logits))
            n += 1
        lat: list[float] = []
        t0 = time.monotonic()
        for _ in range(steps):
            t1 = time.monotonic()
            logits = await pipe.decode(sid, tok, n, n + 1)
            tok = int(np.argmax(logits))
            n += 1
            lat.append((time.monotonic() - t1) * 1000)
        dt = time.monotonic() - t0
        await pipe.release(sid)
        return dt, lat

    # Cross-worker: remote bank behind a REAL authenticated stream.
    remote_runner = ExpertBankRunner(cfg, params, assign_experts(4, 2, 1),
                                     dtype=jnp.float32)
    worker_host = Host(Ed25519PrivateKey.generate(), listen_host="127.0.0.1")
    worker_host.set_stream_handler(
        SHARD_PROTOCOL, ExpertBankService(remote_runner).handle)
    await worker_host.start()
    leader_host = Host(Ed25519PrivateKey.generate(), listen_host="127.0.0.1")
    await leader_host.start()
    pipe = None
    try:
        stream = await leader_host.new_stream(worker_host.contact,
                                              SHARD_PROTOCOL)
        leader = EPLeaderRunner(cfg, params, max_seq=256, dtype=jnp.float32)
        local = LocalExpertBank(
            ExpertBankRunner(cfg, params, assign_experts(4, 2, 0),
                             dtype=jnp.float32))
        pipe = EPPipeline(cfg, leader, [
            local, RemoteExpertBank(stream, remote_runner.expert_ids)])
        dt, lat = await decode_run(pipe, "bench-ep")
    finally:
        if pipe is not None:
            pipe.close()
        await leader_host.close()
        await worker_host.close()

    # Single-worker comparison: both banks local (no DCN hop) — the
    # delta per step IS the cross-worker dispatch price.
    leader2 = EPLeaderRunner(cfg, params, max_seq=256, dtype=jnp.float32)
    pipe2 = EPPipeline(cfg, leader2, [
        LocalExpertBank(ExpertBankRunner(cfg, params,
                                         assign_experts(4, 2, 0),
                                         dtype=jnp.float32)),
        LocalExpertBank(ExpertBankRunner(cfg, params,
                                         assign_experts(4, 2, 1),
                                         dtype=jnp.float32)),
    ])
    try:
        dt_local, lat_local = await decode_run(pipe2, "bench-ep-local")
    finally:
        pipe2.close()

    lat.sort()
    lat_local.sort()
    p50 = lat[len(lat) // 2]
    p50_local = lat_local[len(lat_local) // 2]
    n_moe = cfg.num_layers  # every tiny-test-moe layer is MoE
    return {
        "metric": "cross-worker EP decode (2 expert banks over loopback "
                  "streams), steps/sec",
        "value": round(steps / dt, 1),
        "unit": "steps/sec",
        "vs_baseline": None,  # the reference has no model parallelism
        "extra": {
            "step_p50_ms": round(p50, 2),
            "local_only_step_p50_ms": round(p50_local, 2),
            "dispatch_overhead_ms_per_step": round(p50 - p50_local, 2),
            "moe_layers_per_step": n_moe,
            "dispatch_overhead_ms_per_layer_hop": round(
                (p50 - p50_local) / max(1, n_moe), 3),
            "timed_steps": steps,
            "model": cfg.name,
            "note": "loopback RTT; a real deployment adds its DCN RTT "
                    "per MoE layer per step on top of this floor",
        },
    }


def main() -> None:
    os.environ.setdefault("CROWDLLAMA_TPU_TEST_MODE", "1")
    os.environ.setdefault("JAX_PLATFORMS", "cpu")
    result = asyncio.run(run())
    print(json.dumps(result))


if __name__ == "__main__":
    sys.exit(main())
