"""Cross-worker expert-parallel dispatch benchmark (BASELINE config 4).

A 2-member MoE expert group on real loopback streams: the leader runs
attention/router and dispatches per-layer (token, expert) batches to a
remote expert bank over SHARD_PROTOCOL — one DCN round trip per MoE
layer per decode step, the intrinsic cost of cross-worker EP.  This
measures the CONTROL-PLANE price of that hop (framing, AEAD, asyncio)
with a tiny model so compute does not mask it; the dominant term on a
real deployment is the same per-layer round trip over real DCN RTTs.

Loopback RTT is ~0, which understates a real deployment, so the bench
also SWEEPS injected RTT: a transparent TCP delay relay sits between
leader and expert bank and delivers each chunk one-way-delay late
(injected RTT = 2x the one-way delay).  The sweep reports steps/sec vs
RTT and the break-even RTT against the local-only pipeline — the
injected RTT at which dispatch overhead equals the whole local-only
step cost (i.e. cross-worker EP halves decode throughput).

Prints ONE JSON line; value is decode steps/sec through the 2-worker
pipeline at RTT 0, extra carries the RTT sweep, per-step latency and
the single-worker (local banks only) comparison.

Env overrides:
  CROWDLLAMA_BENCH_EP_STEPS   timed decode steps per point (default 64)
  CROWDLLAMA_BENCH_EP_RTTS    injected RTT sweep, ms (default "0,1,5,10,20")
"""

from __future__ import annotations

import sys
from pathlib import Path

sys.path.insert(0, str(Path(__file__).resolve().parent))
import _common  # noqa: F401,E402 - repo path + JAX platform bootstrap

import asyncio  # noqa: F401 - used by the bench body below
import json
import os
import time
from dataclasses import replace

# Shared injected-latency relay (factored out of this file once the
# spec-pipeline bench became its third consumer).
from crowdllama_tpu.testing.netem import DelayProxy  # noqa: E402,F401


async def run() -> dict:
    import jax
    import jax.numpy as jnp
    import numpy as np
    from crowdllama_tpu.utils.crypto_compat import Ed25519PrivateKey

    from crowdllama_tpu.core.protocol import SHARD_PROTOCOL
    from crowdllama_tpu.engine.expert_service import (
        EPLeaderRunner,
        EPPipeline,
        ExpertBankRunner,
        ExpertBankService,
        LocalExpertBank,
        RemoteExpertBank,
        assign_experts,
    )
    from crowdllama_tpu.models import transformer as T
    from crowdllama_tpu.models.config import get_config
    from crowdllama_tpu.net.host import Host

    steps = int(os.environ.get("CROWDLLAMA_BENCH_EP_STEPS", "64"))
    rtts = [float(x) for x in os.environ.get(
        "CROWDLLAMA_BENCH_EP_RTTS", "0,1,5,10,20").split(",") if x.strip()]
    cfg = get_config("tiny-test-moe", max_context_length=256)
    params = T.init_params(cfg, jax.random.PRNGKey(0), dtype=jnp.float32)
    prompt = [3, 1, 4, 1, 5, 9, 2, 6]

    async def decode_run(pipe, sid: str) -> tuple[float, list[float]]:
        logits = await pipe.prefill(sid, prompt, bucket=16)
        tok = int(np.argmax(logits))
        n = len(prompt)
        # Warmup (compile) steps, then timed.
        for _ in range(4):
            logits = await pipe.decode(sid, tok, n, n + 1)
            tok = int(np.argmax(logits))
            n += 1
        lat: list[float] = []
        t0 = time.monotonic()
        for _ in range(steps):
            t1 = time.monotonic()
            logits = await pipe.decode(sid, tok, n, n + 1)
            tok = int(np.argmax(logits))
            n += 1
            lat.append((time.monotonic() - t1) * 1000)
        dt = time.monotonic() - t0
        await pipe.release(sid)
        return dt, lat

    # Cross-worker: remote bank behind a REAL authenticated stream, once
    # per injected RTT.  Leader runner, local bank, hosts and the remote
    # bank runner are shared across sweep points (compiled fns are reused,
    # so only the first point pays XLA compilation); each point dials a
    # fresh stream — through a DelayProxy when rtt > 0.
    remote_runner = ExpertBankRunner(cfg, params, assign_experts(4, 2, 1),
                                     dtype=jnp.float32)
    worker_host = Host(Ed25519PrivateKey.generate(), listen_host="127.0.0.1")
    worker_host.set_stream_handler(
        SHARD_PROTOCOL, ExpertBankService(remote_runner).handle)
    await worker_host.start()
    leader_host = Host(Ed25519PrivateKey.generate(), listen_host="127.0.0.1")
    await leader_host.start()
    leader = EPLeaderRunner(cfg, params, max_seq=256, dtype=jnp.float32)
    local = LocalExpertBank(
        ExpertBankRunner(cfg, params, assign_experts(4, 2, 0),
                         dtype=jnp.float32))
    sweep: list[dict] = []
    lat: list[float] = []
    dt = 1.0
    try:
        for rtt_ms in rtts:
            proxy = None
            target = worker_host.contact
            if rtt_ms > 0:
                proxy = DelayProxy(worker_host.listen_port, rtt_ms / 2000.0)
                target = replace(target, port=await proxy.start())
            pipe = None
            try:
                stream = await leader_host.new_stream(target, SHARD_PROTOCOL)
                pipe = EPPipeline(cfg, leader, [
                    local,
                    RemoteExpertBank(stream, remote_runner.expert_ids)])
                dt_i, lat_i = await decode_run(pipe, f"bench-ep-rtt{rtt_ms:g}")
            finally:
                if pipe is not None:
                    pipe.close()
                if proxy is not None:
                    await proxy.close()
            lat_i.sort()
            point = {"rtt_ms": rtt_ms,
                     "steps_per_sec": round(steps / dt_i, 1),
                     "step_p50_ms": round(lat_i[len(lat_i) // 2], 2)}
            sweep.append(point)
            print(f"# rtt {rtt_ms:g}ms: {point['steps_per_sec']} steps/s, "
                  f"p50 {point['step_p50_ms']}ms", file=sys.stderr)
            if rtt_ms == 0:
                dt, lat = dt_i, lat_i  # headline = no injected RTT
        if not lat:  # sweep didn't include 0: headline = first point
            dt, lat = steps / sweep[0]["steps_per_sec"], [
                sweep[0]["step_p50_ms"]]
    finally:
        await leader_host.close()
        await worker_host.close()

    # Single-worker comparison: both banks local (no DCN hop) — the
    # delta per step IS the cross-worker dispatch price.
    leader2 = EPLeaderRunner(cfg, params, max_seq=256, dtype=jnp.float32)
    pipe2 = EPPipeline(cfg, leader2, [
        LocalExpertBank(ExpertBankRunner(cfg, params,
                                         assign_experts(4, 2, 0),
                                         dtype=jnp.float32)),
        LocalExpertBank(ExpertBankRunner(cfg, params,
                                         assign_experts(4, 2, 1),
                                         dtype=jnp.float32)),
    ])
    try:
        dt_local, lat_local = await decode_run(pipe2, "bench-ep-local")
    finally:
        pipe2.close()

    lat.sort()
    lat_local.sort()
    p50 = lat[len(lat) // 2]
    p50_local = lat_local[len(lat_local) // 2]
    n_moe = cfg.num_layers  # every tiny-test-moe layer is MoE

    # Least-squares slope of step p50 vs injected RTT: measured ms of step
    # latency added per ms of RTT (should approach the MoE hop count).
    # Break-even vs local-only: the injected RTT at which dispatch overhead
    # equals the entire local-only step cost — cross-worker EP then halves
    # decode throughput, p50_0 + slope*rtt = 2*p50_local.
    slope_ms_per_rtt_ms = None
    break_even_rtt_ms = None
    if len(sweep) >= 2:
        xs = [p["rtt_ms"] for p in sweep]
        ys = [p["step_p50_ms"] for p in sweep]
        mx, my = sum(xs) / len(xs), sum(ys) / len(ys)
        denom = sum((x - mx) ** 2 for x in xs)
        if denom > 0:
            slope = sum((x - mx) * (y - my) for x, y in zip(xs, ys)) / denom
            slope_ms_per_rtt_ms = round(slope, 3)
            if slope > 0:
                break_even_rtt_ms = round(
                    max(0.0, 2 * p50_local - p50) / slope, 2)

    return {
        "metric": "cross-worker EP decode (2 expert banks over loopback "
                  "streams), steps/sec",
        "value": round(steps / dt, 1),
        "unit": "steps/sec",
        "vs_baseline": None,  # the reference has no model parallelism
        "extra": {
            "step_p50_ms": round(p50, 2),
            "local_only_step_p50_ms": round(p50_local, 2),
            "dispatch_overhead_ms_per_step": round(p50 - p50_local, 2),
            "moe_layers_per_step": n_moe,
            "dispatch_overhead_ms_per_layer_hop": round(
                (p50 - p50_local) / max(1, n_moe), 3),
            "rtt_sweep": sweep,
            "slope_ms_per_rtt_ms": slope_ms_per_rtt_ms,
            "break_even_rtt_ms": break_even_rtt_ms,
            "timed_steps": steps,
            "model": cfg.name,
            "note": "value is the RTT-0 loopback point; rtt_sweep injects "
                    "DCN-like RTT via a delay relay, break_even_rtt_ms is "
                    "where dispatch overhead halves throughput vs "
                    "local-only",
        },
    }


def main() -> None:
    os.environ.setdefault("CROWDLLAMA_TPU_TEST_MODE", "1")
    os.environ.setdefault("JAX_PLATFORMS", "cpu")
    result = asyncio.run(run())
    print(json.dumps(result))


if __name__ == "__main__":
    sys.exit(main())
