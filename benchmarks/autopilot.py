"""Autopilot pricing: grid-search-best static dials vs autotune-from-defaults.

Three scenario shapes (docs/AUTOTUNE.md "Pricing the autopilot"), each run
two ways on the CPU reference path (tiny-test):

- ``static``  — offline grid search over the scenario's dial grid, every
  point measured with the autopilot OFF; the best point is what an
  operator with unlimited tuning time would hand-set.
- ``autopilot`` — the same workload starting from the unflagged defaults
  with ``AutoTuner`` attached at an aggressive cadence, steady-state
  throughput measured over the tail waves after the walk settles.

Scenarios:

- ``decode_heavy``  — short prompts, long generations: megastep K is the
  dial that matters (docs/MEGASTEP.md).
- ``mixed_ragged``  — a long chunk-prefilling prompt riding each wave of
  decodes: step_token_budget / prefill_chunk trade against K
  (docs/RAGGED_BATCH.md).
- ``spec_heavy``    — repetitive prompts on the ngram spec runner: the
  draft-cap dial bounds the acceptance-adaptive controller
  (docs/SPECULATIVE.md).

Per scenario the JSON reports grid-best and autopilot steps/sec, their
ratio (the acceptance bar is ~0.9: within 10% of the grid optimum with
zero hand-set flags), moves-to-converge, and the full dial trajectory.

Prints ONE JSON line (bench.py's ``autopilot`` phase parses it) and also
writes the ``benchmarks/results/AUTOTUNE_cpu_<date>.json`` artifact.

Run (repo root, CPU):
    JAX_PLATFORMS=cpu python benchmarks/autopilot.py
"""

import _common  # noqa: F401  (repo-root sys.path + platform re-pin)

import argparse
import asyncio
import datetime
import json
import time
from pathlib import Path

# Measurement shape: every (scenario, dial point) run drives WAVES waves
# of requests through a fresh Scheduler on a SHARED runner (compiled
# programs cache across points — same idiom as tests/test_megastep.py),
# timing only the tail so compile cost and tuner search both amortize out.
STATIC_WAVES = 6          # warmup wave + 5 measured waves per dial point
AUTOPILOT_WAVES = 16      # enough windows for the walk to settle
# Retire windows per measurement phase.  Aggressive next to the
# production default (32) so the walk fits the bench budget, but long
# enough that a phase score averages real signal — at 2 the keep/revert
# decision is wave-jitter, not the dial.
TUNER_INTERVAL = 6


def _set_dials(runner, budget: int, chunk: int) -> None:
    """Pin the runner-side dials, re-deriving the page-aligned ragged
    chunk exactly like engine/paged.py construction does."""
    runner.step_token_budget = budget
    runner.prefill_chunk = chunk
    page = runner.page_size
    c = min(chunk, max(budget - runner.max_slots, page))
    runner.ragged_chunk = max(page, (c // page) * page)


def _waves(scenario: str, vocab: int):
    """One wave of GenRequests; a fresh list per call (queues are
    single-use)."""
    from crowdllama_tpu.engine.scheduler import GenRequest

    if scenario == "decode_heavy":
        return [GenRequest(prompt_ids=[(7 * i + j) % vocab
                                       for j in range(8)],
                           max_tokens=64, seed=i + 1) for i in range(4)]
    if scenario == "mixed_ragged":
        reqs = [GenRequest(prompt_ids=[(5 * i + j) % vocab
                                       for j in range(6)],
                           max_tokens=24, seed=i + 1) for i in range(3)]
        reqs.append(GenRequest(prompt_ids=[(j * 3 + 1) % vocab
                                           for j in range(160)],
                               max_tokens=8, seed=9))
        return reqs
    # spec_heavy: repetitive prompts the bigram proposer can extend.
    return [GenRequest(prompt_ids=[5, 9, 5, 9, 5, 9, 5],
                       max_tokens=48, seed=1),
            GenRequest(prompt_ids=[2, 7, 2, 7, 2, 7],
                       max_tokens=48, seed=2)]


async def _drain(sched, reqs):
    from crowdllama_tpu.engine.scheduler import DONE

    for r in reqs:
        await sched.submit(r)
    total = 0
    for r in reqs:
        while True:
            tok, _ = await asyncio.wait_for(r.out.get(), 120)
            if tok is DONE:
                break
            total += 1
    return total


async def _run(runner, scenario: str, vocab: int, *, sched_kw,
               tuner_kw=None, waves: int, decode_chunk: int = 4):
    """Drive `waves` waves; returns (per-wave tok/s, trajectory, tuner)."""
    from crowdllama_tpu.engine.scheduler import Scheduler

    sched = Scheduler(runner, decode_chunk=decode_chunk, **sched_kw)
    tuner = None
    if tuner_kw is not None:
        from crowdllama_tpu.engine.autotune import AutoTuner

        tuner = AutoTuner(sched, model_id="tiny-test",
                          interval=TUNER_INTERVAL, **tuner_kw)
        sched.attach_autotuner(tuner)
    sched.start()
    traj, rates = [], []
    try:
        for w in range(waves):
            t0 = time.monotonic()
            toks = await _drain(sched, _waves(scenario, vocab))
            rates.append(toks / max(1e-9, time.monotonic() - t0))
            if tuner is not None:
                d = tuner.describe()
                traj.append({"wave": w, "moves": d["moves"],
                             "reverts": d["reverts"],
                             "backoffs": d["backoffs"],
                             "dials": d["dials"],
                             "last_good": dict(tuner._last_good)})
        return rates, traj, tuner
    finally:
        await sched.stop()


async def _measure_point(runner, scenario: str, vocab: int, point: dict,
                         decode_chunk: int = 4) -> float:
    """Measure one static dial point: one warmup wave, then the median
    of the timed waves (host jitter on the CPU reference path is the
    same order as one tiny-model wave; the median ignores the outlier
    waves instead of crowning them)."""
    import statistics

    if "step_token_budget" in point:
        _set_dials(runner, point["step_token_budget"],
                   point.get("prefill_chunk", runner.prefill_chunk))
    elif "prefill_chunk" in point:
        runner.prefill_chunk = point["prefill_chunk"]
    sched_kw = {"megastep_k": point.get("megastep_k", 0)}
    if "draft_k" in point:
        sched_kw["spec_draft_max"] = point["draft_k"]
        runner.set_draft_len(min(point["draft_k"], 4))
    rates, _, _ = await _run(runner, scenario, vocab, sched_kw=sched_kw,
                             tuner_kw=None, waves=STATIC_WAVES,
                             decode_chunk=decode_chunk)
    return statistics.median(rates[1:])


async def _paired(runner, scenario: str, vocab: int, converged: dict,
                  best_point: dict,
                  decode_chunk: int = 4) -> tuple[float, float]:
    """Measure the converged and grid-best points back to back.  When
    the autopilot landed ON the grid-best point the comparison is an
    identity — one measurement serves as both sides, instead of letting
    host jitter report a fake gap between two runs of the same config."""
    tok_s = await _measure_point(runner, scenario, vocab, converged,
                                 decode_chunk)
    if all(converged.get(k) == v for k, v in best_point.items()):
        return tok_s, tok_s
    best_now = await _measure_point(runner, scenario, vocab, best_point,
                                    decode_chunk)
    return tok_s, best_now


def _moves_to_converge(traj) -> int:
    """Moves spent up to the last wave that still improved the
    last-known-good point (later probes keep running — that is the
    autopilot's steady state — but they no longer change the answer)."""
    last_change = 0
    for i in range(1, len(traj)):
        if traj[i]["last_good"] != traj[i - 1]["last_good"]:
            last_change = i
    return traj[last_change]["moves"] if traj else 0


async def _scenario_paged(runner, scenario: str, vocab: int) -> dict:
    """decode_heavy / mixed_ragged: grid over (megastep K, budget, chunk)
    vs the autopilot from the unflagged defaults (K=0, 96, 64).

    Both arms run per-step dispatch (decode_chunk=1, the same control
    arm `make bench-megastep` prices against): the megastep dial then
    amortizes host turnarounds monotonically, which is the axis this
    scenario prices — K riding on a multi-step legacy chunk would bury
    the dial's effect under the chunk's own amortization."""
    if scenario == "decode_heavy":
        grid = [(k, 96, 64) for k in (0, 2, 4, 8)] + [(4, 164, 64)]
    else:
        grid = [(k, b, c) for k in (0, 4) for b in (96, 164)
                for c in (64, 128)]
    static = []
    for k, budget, chunk in grid:
        tok_s = await _measure_point(
            runner, scenario, vocab,
            {"megastep_k": k, "step_token_budget": budget,
             "prefill_chunk": chunk}, decode_chunk=1)
        static.append({"megastep_k": k, "step_token_budget": budget,
                       "prefill_chunk": chunk,
                       "steps_per_sec": round(tok_s, 2)})
    best = max(static, key=lambda p: p["steps_per_sec"])

    _set_dials(runner, 96, 64)  # autopilot starts from the defaults
    _, traj, tuner = await _run(
        runner, scenario, vocab, sched_kw={"megastep_k": 0},
        tuner_kw={"bounds": {"megastep_k": 8, "step_token_budget": 164,
                             "prefill_chunk": 128}},
        waves=AUTOPILOT_WAVES, decode_chunk=1)
    # Steady state = the converged point, measured like the grid points.
    # (With the deliberately aggressive cadence above, probe phases still
    # visit fresh compile signatures during the tail waves — measuring
    # through them would price XLA compiles, not the operating point.)
    # The grid-best point is RE-measured back to back with it: host-load
    # drift over the run would otherwise dominate the ratio.
    converged = dict(tuner._last_good)
    best_point = {k: best[k] for k in ("megastep_k", "step_token_budget",
                                       "prefill_chunk")}
    tok_s, best_now = await _paired(runner, scenario, vocab, converged,
                                    best_point, decode_chunk=1)
    return _report(scenario, static, best, tok_s, best_now, traj, tuner,
                   converged)


async def _scenario_spec(spec, vocab: int) -> dict:
    """spec_heavy: grid over the draft cap vs the autopilot walking it."""
    static = []
    for cap in (1, 2, 4, 8):
        tok_s = await _measure_point(spec, "spec_heavy", vocab,
                                     {"draft_k": cap})
        static.append({"draft_k": cap, "steps_per_sec": round(tok_s, 2)})
    best = max(static, key=lambda p: p["steps_per_sec"])

    spec.set_draft_len(2)
    # Pin the non-scenario dials through their ceiling bounds (single-
    # value grids are skipped by the walk): this scenario prices the
    # draft-cap coordinate against the same space the grid explored.
    spec.prefill_chunk = 64
    _, traj, tuner = await _run(
        spec, "spec_heavy", vocab, sched_kw={"spec_draft_max": 2},
        tuner_kw={"bounds": {"draft_k": 8, "megastep_k": 0,
                             "prefill_chunk": 64}},
        waves=AUTOPILOT_WAVES)
    converged = dict(tuner._last_good)
    tok_s, best_now = await _paired(spec, "spec_heavy", vocab, converged,
                                    {"draft_k": best["draft_k"]})
    return _report("spec_heavy", static, best, tok_s, best_now, traj,
                   tuner, converged)


def _report(scenario, static, best, tok_s, best_now, traj, tuner,
            converged) -> dict:
    d = tuner.describe()
    return {
        "scenario": scenario,
        "grid": static,
        "grid_best": best,
        "grid_best_steps_per_sec_paired": round(best_now, 2),
        "autopilot_point": converged,
        "autopilot_steps_per_sec": round(tok_s, 2),
        "ratio_vs_grid_best": round(tok_s / max(1e-9, best_now), 3),
        "moves_to_converge": _moves_to_converge(traj),
        "moves": d["moves"], "reverts": d["reverts"],
        "backoffs": d["backoffs"],
        "trajectory": traj,
    }


async def _main_async() -> dict:
    import jax
    import jax.numpy as jnp

    from crowdllama_tpu.engine.paged import PagedModelRunner
    from crowdllama_tpu.engine.spec import SpecModelRunner
    from crowdllama_tpu.models import transformer as T
    from crowdllama_tpu.models.config import get_config

    cfg = get_config("tiny-test", max_context_length=256)
    params = T.init_params(cfg, jax.random.PRNGKey(0), dtype=jnp.float32)
    paged = PagedModelRunner(cfg, params=params, max_slots=4, max_seq=256,
                             page_size=32, mesh_spec="1",
                             step_token_budget=96, prefix_cache=False)
    _set_dials(paged, 96, 64)
    scfg = get_config("tiny-test", max_context_length=128)
    sparams = T.init_params(scfg, jax.random.PRNGKey(0), dtype=jnp.float32)
    spec = SpecModelRunner(scfg, params=sparams, max_slots=2, max_seq=128,
                           dtype=jnp.float32, draft_len=2)
    vocab = cfg.vocab_size

    scenarios = [await _scenario_paged(paged, "decode_heavy", vocab),
                 await _scenario_paged(paged, "mixed_ragged", vocab),
                 await _scenario_spec(spec, vocab)]
    return {
        "bench": "autopilot",
        "platform": jax.devices()[0].platform,
        "tuner_interval": TUNER_INTERVAL,
        "scenarios": scenarios,
        "min_ratio_vs_grid_best": min(s["ratio_vs_grid_best"]
                                      for s in scenarios),
    }


def main() -> None:
    ap = argparse.ArgumentParser()
    ap.add_argument("--out", default="")
    args = ap.parse_args()
    result = asyncio.run(_main_async())
    out = args.out
    if not out:
        date = datetime.date.today().isoformat()
        out = str(Path(__file__).resolve().parent / "results" /
                  f"AUTOTUNE_{result['platform']}_{date}.json")
    Path(out).parent.mkdir(parents=True, exist_ok=True)
    Path(out).write_text(json.dumps(result, indent=1) + "\n")
    print(json.dumps(result))


if __name__ == "__main__":
    main()
