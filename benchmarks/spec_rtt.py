"""Gateway-drafted speculative pipeline vs injected swarm RTT
(docs/SPECULATIVE.md, gateway drafting section).

Full serving topology on loopback, all real sockets: DHT bootstrap + one
spec-draft JaxEngine worker + consumer peer + HTTP gateway.  The draft
checkpoint equals the main model (same init seed), so acceptance sits at
the self-draft ceiling and the sweep isolates the ONE variable under
test: where the draft model lives relative to the RTT.

Three arms, all serving the identical streamed /api/chat request:

  no_spec        spec_pipeline=off — no remote-draft sub-protocol; the
                 worker speculates locally (PR 4) and free-runs, so RTT
                 is paid once at dial time (flat control arm)
  worker_draft   spec_pipeline=worker — remote-draft wire with pure ack
                 credits: the worker drafts, every verify round waits one
                 RTT for its credit (stop-and-wait; linear in RTT)
  gateway_draft  spec_pipeline=gateway — the gateway drafts and keeps
                 depth-controller-many chunks in flight, so verify rounds
                 overlap the wire (sub-linear in RTT)

RTT is injected with the shared DelayProxy relay
(crowdllama_tpu/testing/netem.py): the relay fronts the worker's listen
port and the consumer's DHT lookup is rewired to it, so every gateway
dial pays the latency.  Client streams must be byte-identical across all
arms and RTT points (greedy verify is exact); the bench hard-fails
otherwise.

Prints ONE JSON line; value is the gateway-draft / worker-draft decode
tokens/s ratio at the LARGEST injected RTT (the acceptance bar is 1.5x
at 20 ms), extra carries the full sweep and per-arm RTT-degradation
slopes.  Also writes benchmarks/results/SPEC_RTT_cpu_<date>.json.

Env overrides:
  CROWDLLAMA_BENCH_SPEC_RTTS    injected RTT sweep, ms (default "0,5,10,20")
  CROWDLLAMA_BENCH_SPEC_TOKENS  tokens generated per request (default 96)
  CROWDLLAMA_BENCH_SPEC_TRIALS  timed trials per cell (default 3)
"""

from __future__ import annotations

import sys
from pathlib import Path

sys.path.insert(0, str(Path(__file__).resolve().parent))
import _common  # noqa: F401,E402 - repo path + JAX platform bootstrap

import asyncio  # noqa: E402
import json  # noqa: E402
import os  # noqa: E402
import statistics  # noqa: E402
import tempfile  # noqa: E402
import time  # noqa: E402
from dataclasses import replace  # noqa: E402

from crowdllama_tpu.testing.modelgen import permutation_params  # noqa: E402
from crowdllama_tpu.testing.netem import DelayProxy  # noqa: E402

MODEL = "tiny-test"
CTX = 256
ARMS = ("no_spec", "worker_draft", "gateway_draft")
_MODE = {"no_spec": "off", "worker_draft": "worker",
         "gateway_draft": "gateway"}


async def run() -> dict:
    import aiohttp

    from crowdllama_tpu.config import Configuration, Intervals
    from crowdllama_tpu.engine.engine import FakeEngine, JaxEngine
    from crowdllama_tpu.engine.weights import save_params
    from crowdllama_tpu.gateway.gateway import Gateway
    from crowdllama_tpu.models.config import get_config
    from crowdllama_tpu.net.discovery import new_host_and_dht
    from crowdllama_tpu.peer.peer import Peer
    from crowdllama_tpu.utils.crypto_compat import Ed25519PrivateKey

    rtts = [float(x) for x in os.environ.get(
        "CROWDLLAMA_BENCH_SPEC_RTTS", "0,5,10,20").split(",") if x.strip()]
    n_tokens = int(os.environ.get("CROWDLLAMA_BENCH_SPEC_TOKENS", "96"))
    trials = int(os.environ.get("CROWDLLAMA_BENCH_SPEC_TRIALS", "3"))

    def cfg(**kw):
        c = Configuration(listen_host="127.0.0.1",
                          intervals=Intervals.default())
        for k, v in kw.items():
            setattr(c, k, v)
        return c

    # Both engines (worker main+draft, gateway draft) load the SAME
    # checkpoint: a constructed token-permutation model.  Random-init
    # weights have near-tie logits, so the paged verify path and the
    # gateway's contiguous draft path flip argmax on ulp-level noise and
    # acceptance collapses into numeric lottery; this model's logit gaps
    # are O(1), so greedy decode is path-stable, acceptance sits at the
    # ceiling, EOS never fires, and arm deltas isolate the one variable
    # under test — RTT x pipelining.  (Draft-model QUALITY is priced by
    # benchmarks/spec_decode.py, not here.)
    mcfg = get_config(MODEL, max_context_length=CTX)
    params = permutation_params(mcfg)
    ckpt = tempfile.mkdtemp(prefix="spec-rtt-draft-")
    save_params(mcfg, params, ckpt, {"note": "spec_rtt permutation model"})

    boot_host, _ = await new_host_and_dht(
        Ed25519PrivateKey.generate(), listen_host="127.0.0.1")
    bootstrap = f"127.0.0.1:{boot_host.listen_port}"
    engine = JaxEngine(
        cfg(bootstrap_peers=[bootstrap], model=MODEL, model_path=ckpt,
            spec_decode="draft", spec_draft=3, spec_draft_model=MODEL,
            spec_draft_path=ckpt, max_batch_slots=2, warmup=False),
        max_context_length=CTX)
    await engine.start()
    worker = Peer(Ed25519PrivateKey.generate(),
                  cfg(bootstrap_peers=[bootstrap], model=MODEL),
                  engine=engine, worker_mode=True)
    await worker.start()
    consumer = Peer(Ed25519PrivateKey.generate(),
                    cfg(bootstrap_peers=[bootstrap]),
                    engine=FakeEngine(models=[]), worker_mode=False)
    await consumer.start()
    gateway = Gateway(consumer, port=0, host="127.0.0.1",
                      spec_pipeline="off", spec_draft_path=ckpt)
    await gateway.start()
    gw_port = gateway._runner.addresses[0][1]

    # The gateway's worker lookup, optionally rewired through the relay
    # (same idiom as kv_transfer.py) so every inference dial pays the
    # injected latency.
    real_find = consumer.dht.find_peer
    proxy_port: list[int | None] = [None]

    async def find_peer(pid):
        contact = await real_find(pid)
        if contact is not None and pid == worker.peer_id \
                and proxy_port[0] is not None:
            contact = replace(contact, port=proxy_port[0])
        return contact

    consumer.dht.find_peer = find_peer

    deadline = time.monotonic() + 60
    while time.monotonic() < deadline:
        if consumer.peer_manager.find_best_worker(MODEL) is not None:
            break
        await asyncio.sleep(0.1)
    else:
        raise RuntimeError("worker never became routable")

    body = {"model": MODEL, "stream": True,
            "options": {"num_predict": n_tokens},
            "messages": [{"role": "user",
                          "content": "tell me a story about the swarm"}]}
    url = f"http://127.0.0.1:{gw_port}/api/chat"

    async def ask(http) -> tuple[str, float, int]:
        """One streamed request -> (text, decode tokens/s, eval_count).
        Rate spans first content frame to the done frame, so dial +
        prefill + the injected handshake RTT (TTFT) stay out of the
        decode number; token count comes from the final frame's
        eval_count (frames batch multiple tokens under flush coalescing,
        so counting frames would undercount)."""
        t_first = t_done = None
        n_eval = 0
        parts: list[str] = []
        async with http.post(url, json=body) as resp:
            assert resp.status == 200, await resp.text()
            async for raw in resp.content:
                raw = raw.strip()
                if not raw:
                    continue
                d = json.loads(raw)
                if t_first is None:
                    t_first = time.monotonic()
                parts.append(d.get("message", {}).get("content", ""))
                if d.get("done"):
                    t_done = time.monotonic()
                    n_eval = int(d.get("eval_count", 0))
                    assert d.get("done_reason") == "length", d
        text = "".join(parts)
        span = (t_done - t_first) if (t_first and t_done) else 0.0
        tps = (n_eval - 1) / span if span > 0 and n_eval > 1 else 0.0
        return text, tps, n_eval

    sweep: list[dict] = []
    expected_text: str | None = None
    async with aiohttp.ClientSession() as http:
        # Warmup at RTT 0: XLA compiles (engine decode buckets, hosted
        # verify program, gateway drafter prefill/step) all paid here.
        for arm in ARMS:
            gateway.spec_pipeline = _MODE[arm]
            text, _, _ = await ask(http)
            if expected_text is None:
                expected_text = text
            assert text == expected_text, \
                f"warmup stream diverged in arm {arm}"

        for rtt_ms in rtts:
            proxy = None
            if rtt_ms > 0:
                proxy = DelayProxy(worker.host.listen_port,
                                   rtt_ms / 2000.0)
                proxy_port[0] = await proxy.start()
            try:
                for arm in ARMS:
                    gateway.spec_pipeline = _MODE[arm]
                    # Pooled plain streams from the previous point would
                    # bypass this point's relay; drop them so every arm
                    # dials through the current wire.
                    gateway._stream_pool.close_key(worker.peer_id)
                    rates = []
                    for _ in range(trials):
                        text, tps, n = await ask(http)
                        assert text == expected_text, (
                            f"stream NOT byte-identical: arm {arm} at "
                            f"rtt {rtt_ms}ms")
                        rates.append(tps)
                    point = {"arm": arm, "rtt_ms": rtt_ms,
                             "decode_tok_s": round(
                                 statistics.median(rates), 1),
                             "tokens": n, "trials": trials}
                    sweep.append(point)
                    print(f"# rtt {rtt_ms:g}ms {arm}: "
                          f"{point['decode_tok_s']} tok/s",
                          file=sys.stderr)
            finally:
                proxy_port[0] = None
                if proxy is not None:
                    await proxy.close()
        spec_stats = dict(gateway._spec_stats)
    await gateway.stop()
    await consumer.stop()
    await worker.stop()
    await engine.stop()
    await boot_host.close()

    def cells(arm):
        return {p["rtt_ms"]: p["decode_tok_s"]
                for p in sweep if p["arm"] == arm}

    # Per-arm RTT sensitivity: least-squares slope of seconds-per-token
    # vs injected RTT.  A stop-and-wait arm that pays the full RTT every
    # verify round lands near 1/(k+1) s of token latency per s of RTT;
    # a pipelined arm lands near 0.
    def slope(arm):
        pts = [(r / 1000.0, 1.0 / t) for r, t in cells(arm).items()
               if t > 0]
        if len(pts) < 2:
            return None
        mx = sum(x for x, _ in pts) / len(pts)
        my = sum(y for _, y in pts) / len(pts)
        den = sum((x - mx) ** 2 for x, _ in pts)
        if den <= 0:
            return None
        return round(sum((x - mx) * (y - my) for x, y in pts) / den, 3)

    max_rtt = max(rtts)
    gw_at_max = cells("gateway_draft").get(max_rtt, 0.0)
    wk_at_max = cells("worker_draft").get(max_rtt, 0.0)
    ratio = round(gw_at_max / wk_at_max, 2) if wk_at_max > 0 else None

    def degradation(arm):
        c = cells(arm)
        lo, hi = c.get(min(rtts), 0.0), c.get(max_rtt, 0.0)
        return round(100 * (1 - hi / lo), 1) if lo > 0 else None

    return {
        "metric": "gateway-draft / worker-draft decode tokens/s at "
                  f"{max_rtt:g}ms injected RTT",
        "value": ratio,
        "unit": "x",
        "vs_baseline": None,  # the reference has no speculative pipeline
        "extra": {
            "sweep": sweep,
            "tok_latency_slope_s_per_s_rtt": {
                arm: slope(arm) for arm in ARMS},
            "degradation_pct_0_to_max_rtt": {
                arm: degradation(arm) for arm in ARMS},
            "byte_identical_all_cells": True,  # hard-asserted above
            "draft_chunk_stats": spec_stats,
            "tokens_per_request": n_tokens,
            "trials_per_cell": trials,
            "model": MODEL,
            "note": "draft == main checkpoint (acceptance ceiling), so "
                    "arm deltas isolate RTT x pipelining; worker_draft "
                    "is credit stop-and-wait (linear in RTT), "
                    "gateway_draft keeps depth-controller-many chunks "
                    "in flight",
        },
    }


def main() -> None:
    os.environ.setdefault("CROWDLLAMA_TPU_TEST_MODE", "1")
    os.environ.setdefault("JAX_PLATFORMS", "cpu")
    result = asyncio.run(run())
    out = json.dumps(result)
    print(out)
    res_dir = Path(__file__).resolve().parent / "results"
    res_dir.mkdir(parents=True, exist_ok=True)
    stamp = time.strftime("%Y-%m-%d")
    (res_dir / f"SPEC_RTT_cpu_{stamp}.json").write_text(out + "\n")


if __name__ == "__main__":
    sys.exit(main())
