"""Swarm scaling benchmark: gateway throughput as workers grow 1 -> 16
(BASELINE metric 3 of 3).

All-in-one-process topology on loopback (the reference's integration-test
strategy, /root/reference/test/integration_test.go): DHT bootstrap + N
FakeEngine workers + consumer/gateway.  For each swarm size the bench fires
concurrent /api/chat requests and measures sustained requests/sec plus how
long discovery took to see all N workers.  FakeEngine isolates the
control-plane cost — discovery, scheduling, stream dial/handshake, PB codec
— which is exactly what "swarm scaling" measures (engine throughput is
bench.py's job).

Prints ONE JSON line; value is requests/sec at the largest swarm, extra
holds the full scaling curve.

Env overrides:
  CROWDLLAMA_BENCH_SIZES       comma list        (default "1,2,4,8,16")
  CROWDLLAMA_BENCH_REQUESTS    requests per size (default 150)
  CROWDLLAMA_BENCH_CONCURRENCY in-flight cap     (default 8)
"""

from __future__ import annotations

import sys
from pathlib import Path

sys.path.insert(0, str(Path(__file__).resolve().parent))
import _common  # noqa: F401,E402 - repo path + JAX platform bootstrap

import asyncio
import json
import os
import time


async def run() -> dict:
    import aiohttp
    from crowdllama_tpu.utils.crypto_compat import Ed25519PrivateKey

    from crowdllama_tpu.config import Configuration, Intervals
    from crowdllama_tpu.engine.engine import FakeEngine
    from crowdllama_tpu.gateway.gateway import Gateway
    from crowdllama_tpu.net.discovery import new_host_and_dht
    from crowdllama_tpu.obs.metrics import quantile_from_counts
    from crowdllama_tpu.peer.peer import Peer

    sizes = [int(x) for x in os.environ.get(
        "CROWDLLAMA_BENCH_SIZES", "1,2,4,8,16").split(",")]
    # 150: at ~1000 req/s the 60-request window was ~60 ms — too short
    # for a stable per-size number on the 1-core host.
    n_requests = int(os.environ.get("CROWDLLAMA_BENCH_REQUESTS", "150"))
    concurrency = int(os.environ.get("CROWDLLAMA_BENCH_CONCURRENCY", "8"))
    model = "bench-model"

    def cfg(**kw):
        c = Configuration(listen_host="127.0.0.1", model=model,
                          intervals=Intervals.default())
        for k, v in kw.items():
            setattr(c, k, v)
        return c

    boot_host, _ = await new_host_and_dht(
        Ed25519PrivateKey.generate(), listen_host="127.0.0.1")
    bootstrap = f"127.0.0.1:{boot_host.listen_port}"

    consumer = Peer(Ed25519PrivateKey.generate(),
                    cfg(bootstrap_peers=[bootstrap]),
                    engine=FakeEngine(models=[]), worker_mode=False)
    await consumer.start()
    gateway = Gateway(consumer, port=0, host="127.0.0.1")
    await gateway.start()
    gw_port = gateway._runner.addresses[0][1]
    url = f"http://127.0.0.1:{gw_port}/api/chat"
    body = {"model": model,
            "messages": [{"role": "user", "content": "scale test"}]}

    workers: list[Peer] = []
    curve = []

    def total_streams() -> int:
        """Control-plane chatter counter: streams opened across EVERY host
        in the topology (handshake-priced events)."""
        hosts = [boot_host, consumer.host] + [w.host for w in workers]
        return sum(h.stats.get("streams_in", 0) + h.stats.get("streams_out", 0)
                   for h in hosts if h is not None)

    class LagSampler:
        """Event-loop lag: overshoot of a 20 ms sleep.  Max + mean over the
        window attribute the cliff (loop saturation vs remote slowness)."""

        def __init__(self):
            self.samples: list[float] = []
            self._task: asyncio.Task | None = None

        async def _run(self):
            while True:
                t0 = time.monotonic()
                await asyncio.sleep(0.02)
                self.samples.append(time.monotonic() - t0 - 0.02)

        def __enter__(self):
            self.samples = []
            self._task = asyncio.create_task(self._run())
            return self

        def __exit__(self, *exc):
            self._task.cancel()

        @property
        def stats(self) -> dict:
            s = self.samples or [0.0]
            return {"max_ms": round(max(s) * 1e3, 1),
                    "mean_ms": round(sum(s) / len(s) * 1e3, 2)}

    try:
        async with aiohttp.ClientSession() as session:
            for size in sizes:
                t_grow = time.monotonic()
                new = [Peer(Ed25519PrivateKey.generate(),
                            cfg(bootstrap_peers=[bootstrap]),
                            engine=FakeEngine(models=[model]),
                            worker_mode=True)
                       for _ in range(size - len(workers))]
                # Start the joiners concurrently — real swarm growth is
                # parallel, and sequential starts inflate discovery_s with
                # pure startup serialization.  Extend FIRST so the finally
                # block stops partially-started peers if a start raises.
                workers.extend(new)
                await asyncio.gather(*(w.start() for w in new))
                # Wait until the gateway's manager sees all of them.
                deadline = time.monotonic() + 60
                while time.monotonic() < deadline:
                    healthy = {p.peer_id for p in
                               consumer.peer_manager.get_healthy_peers()
                               if p.is_worker}
                    if len(healthy) >= size:
                        break
                    await asyncio.sleep(0.1)
                else:
                    raise RuntimeError(f"discovery stalled at size {size}")
                discovery_s = time.monotonic() - t_grow
                # Let join-transient control traffic (re-provides, first
                # health probes, discovery metadata fetches) settle: the
                # phase measures steady-state serving throughput, and the
                # fixed 1 s sleep let 16-join transients bleed into the
                # measurement window (VERDICT r4 weak #1 — the curve bent
                # from convergence churn, not request-path cost).
                # Convergence cost itself is reported as discovery_s.
                settle_deadline = time.monotonic() + 10.0
                while time.monotonic() < settle_deadline:
                    s0 = total_streams()
                    await asyncio.sleep(0.5)
                    if total_streams() - s0 <= max(2, size // 4):
                        break

                sem = asyncio.Semaphore(concurrency)
                hits: dict[str, int] = {}

                async def one():
                    async with sem:
                        async with session.post(url, json=body) as resp:
                            assert resp.status == 200, await resp.text()
                            d = await resp.json()
                            hits[d["worker_id"]] = hits.get(d["worker_id"], 0) + 1

                streams0 = total_streams()
                pool0 = gateway._stream_pool.hits
                hp0 = gateway.hotpath_snapshot()
                req_hist = gateway.obs.metrics.request_seconds.labels(model)
                hist0 = req_hist.snapshot_counts()
                cpu0 = time.process_time()
                t0 = time.monotonic()
                with LagSampler() as lag:
                    await asyncio.gather(*(one() for _ in range(n_requests)))
                dt = time.monotonic() - t0
                cpu_s = time.process_time() - cpu0
                cpu_util = cpu_s / dt
                hp1 = gateway.hotpath_snapshot()
                # Per-request phase attribution (ISSUE 1 tentpole d): delta
                # of the gateway's monotonic hot-path counters over the
                # window, divided by requests.  aead_us is process-wide
                # (gateway + in-process workers share net/secure.py).
                hp_req = max(1, hp1["requests"] - hp0["requests"])
                breakdown = {
                    k: round((hp1[k] - hp0[k]) / hp_req, 1)
                    for k in ("route_us", "serde_us", "aead_us", "io_wait_us")
                }
                snapshot_rebuilds = (hp1["route_snapshot_rebuilds"]
                                     - hp0["route_snapshot_rebuilds"])
                # Histogram-derived per-size latency: the window's delta of
                # the gateway's crowdllama_request_seconds series — the
                # number a dashboard would show for this swarm size.
                hist_delta = [b - a for a, b in
                              zip(hist0, req_hist.snapshot_counts())]
                req_p50_ms = round(quantile_from_counts(
                    req_hist.buckets, hist_delta, 0.5) * 1e3, 2)
                req_p95_ms = round(quantile_from_counts(
                    req_hist.buckets, hist_delta, 0.95) * 1e3, 2)
                pool_hits = gateway._stream_pool.hits - pool0
                # With the gateway stream pool, only pool MISSES open an
                # inference stream (counted on both endpoints).
                req_streams = 2 * (n_requests - pool_hits)
                bg_streams = total_streams() - streams0 - req_streams
                curve.append({
                    "workers": size,
                    "requests_per_sec": round(n_requests / dt, 1),
                    "discovery_s": round(discovery_s, 2),
                    "distinct_workers_hit": len(hits),
                    # Attribution (VERDICT r3 weak #2 / r4 weak #1):
                    # process CPU share of the window (1.0 = the bench
                    # host's single core is saturated), the per-request
                    # CPU floor that share implies, control-plane streams
                    # opened during the window beyond the request streams
                    # themselves, stream-pool hits, and event-loop lag.
                    "cpu_utilization": round(cpu_util, 2),
                    "cpu_us_per_request": round(cpu_s / n_requests * 1e6),
                    # Gateway hot-path phase breakdown, µs per request.
                    **breakdown,
                    "request_hist_p50_ms": req_p50_ms,
                    "request_hist_p95_ms": req_p95_ms,
                    "route_snapshot_rebuilds": snapshot_rebuilds,
                    "stream_pool_hits": pool_hits,
                    "background_streams": max(0, bg_streams),
                    "loop_lag": lag.stats,
                })
                print(f"# size={size}: {n_requests/dt:.1f} req/s, "
                      f"discovery {discovery_s:.2f}s, "
                      f"{len(hits)} workers hit, cpu {cpu_util:.2f}, "
                      f"{cpu_s / n_requests * 1e6:.0f}us/req "
                      f"(route {breakdown['route_us']} serde "
                      f"{breakdown['serde_us']} aead {breakdown['aead_us']} "
                      f"io {breakdown['io_wait_us']}), "
                      f"rebuilds {snapshot_rebuilds}, "
                      f"pool hits {pool_hits}, "
                      f"bg streams {max(0, bg_streams)}, "
                      f"lag max {lag.stats['max_ms']}ms", file=sys.stderr)
    finally:
        await gateway.stop()
        await consumer.stop()
        for w in workers:
            await w.stop()
        await boot_host.close()

    # One completed span tree from the trace ring buffer: shows where a
    # representative largest-swarm request spent its time (route/serde/
    # aead/io_wait on the gateway side).
    trace_sample = next(
        (t for t in reversed(gateway.obs.trace.snapshot()["traces"])
         if t["done"]), None)

    from crowdllama_tpu import native

    return {
        "metric": f"swarm scaling 1->{sizes[-1]} workers, gateway requests/sec",
        "value": curve[-1]["requests_per_sec"],
        "unit": "requests/sec",
        "vs_baseline": None,  # reference publishes no scaling numbers
        "extra": {"curve": curve, "concurrency": concurrency,
                  "native_enabled": native.native_enabled(),
                  "native_fallbacks": dict(native.stats()["fallbacks"]),
                  "trace_sample": trace_sample},
    }


def _arm_summary(result: dict) -> dict:
    """Per-arm digest for the artifact: curve-wide medians plus the
    serde+aead share the native plane is meant to collapse.

    Medians across swarm sizes, not the single-replica point: on the
    1-core bench host the per-size numbers jitter by +/-50% (discovery
    timing, scheduler noise), and the per-request phase costs are roughly
    size-independent, so the median is the stable estimator.

    ``cpu_us_per_request`` is *process-wide* CPU (the bench runs the
    gateway, all workers, the boot host AND the load generator in one
    process), so the gateway replica's own data-plane cost is reported
    separately as ``gateway_dataplane_us_per_request`` (route+serde+aead
    from the hot-path attribution) together with the single-replica
    capacity it implies.
    """
    import statistics

    curve = result["extra"]["curve"]
    med = lambda k: round(statistics.median(p[k] for p in curve), 1)  # noqa: E731
    dataplane = round(med("route_us") + med("serde_us") + med("aead_us"), 1)
    return {
        "native_enabled": result["extra"]["native_enabled"],
        "requests_per_sec_single_replica": curve[0]["requests_per_sec"],
        "peak_requests_per_sec": max(p["requests_per_sec"] for p in curve),
        "cpu_us_per_request_median": med("cpu_us_per_request"),
        "gateway_dataplane_us_per_request": dataplane,
        "implied_replica_capacity_req_s": (
            round(1e6 / dataplane) if dataplane else None),
        "serde_us": med("serde_us"),
        "aead_us": med("aead_us"),
        "route_us": med("route_us"),
        "loop_lag_max_ms": max(p["loop_lag"]["max_ms"] for p in curve),
        "request_hist_p95_ms": med("request_hist_p95_ms"),
        "curve": curve,
    }


def run_arms() -> dict:
    """Native-vs-CROWDLLAMA_NO_NATIVE=1 arm pair (one subprocess each, so
    every arm gets a clean library state) -> SWARM_SCALING_cpu_<date>.json."""
    import subprocess

    from crowdllama_tpu import native

    native.ensure_built()  # native arm must not pay the g++ run mid-bench
    script = str(Path(__file__).resolve())
    arms: dict[str, dict] = {}
    for arm in ("native", "no_native"):
        env = dict(os.environ)
        env.setdefault("CROWDLLAMA_TPU_TEST_MODE", "1")
        env.setdefault("JAX_PLATFORMS", "cpu")
        if arm == "no_native":
            env["CROWDLLAMA_NO_NATIVE"] = "1"
        else:
            env.pop("CROWDLLAMA_NO_NATIVE", None)
        print(f"# arm={arm}", file=sys.stderr)
        proc = subprocess.run(
            [sys.executable, script], env=env, capture_output=True,
            text=True, timeout=float(
                os.environ.get("CROWDLLAMA_BENCH_SUBPROC_TIMEOUT", "900")))
        sys.stderr.write(proc.stderr)
        line = next(
            (ln for ln in reversed(proc.stdout.splitlines())
             if ln.strip().startswith("{")), None)
        if line is None:
            raise RuntimeError(
                f"arm {arm}: rc={proc.returncode}, no JSON line "
                f"(stdout tail: {proc.stdout[-300:]!r})")
        arms[arm] = _arm_summary(json.loads(line))

    nat, py = arms["native"], arms["no_native"]
    serde_aead_native = round(nat["serde_us"] + nat["aead_us"], 1)
    serde_aead_python = round(py["serde_us"] + py["aead_us"], 1)
    artifact = {
        "metric": "swarm scaling, native vs CROWDLLAMA_NO_NATIVE=1 arms",
        "unit": "requests/sec",
        "date": time.strftime("%Y-%m-%d"),
        "host": {"cpus": os.cpu_count()},
        "config": {
            "sizes": os.environ.get("CROWDLLAMA_BENCH_SIZES", "1,2,4,8,16"),
            "requests_per_size": int(os.environ.get(
                "CROWDLLAMA_BENCH_REQUESTS", "150")),
            "concurrency": int(os.environ.get(
                "CROWDLLAMA_BENCH_CONCURRENCY", "8")),
        },
        "note": (
            "chat-shaped traffic (payloads < wire.NATIVE_ENVELOPE_MIN_BYTES)"
            " intentionally converges between arms: the size-aware dispatch"
            " routes tiny envelopes through upb in both, so arm deltas here"
            " bound host noise; the native wins live on >=4KB payloads"
            " (KV shipping, long responses) and in the AEAD frame path"),
        "arms": arms,
        "comparison": {
            "serde_aead_us_native": serde_aead_native,
            "serde_aead_us_python": serde_aead_python,
            "serde_aead_collapse_x": (
                round(serde_aead_python / serde_aead_native, 2)
                if serde_aead_native else None),
            "dataplane_us_native":
                nat["gateway_dataplane_us_per_request"],
            "dataplane_us_python":
                py["gateway_dataplane_us_per_request"],
        },
        "acceptance": {
            "gateway_dataplane_us_per_request_lt_200":
                nat["gateway_dataplane_us_per_request"] < 200,
            "implied_replica_capacity_ge_5k":
                (nat["implied_replica_capacity_req_s"] or 0) >= 5000,
        },
    }
    out_dir = Path(__file__).resolve().parent / "results"
    out_dir.mkdir(parents=True, exist_ok=True)
    out = out_dir / f"SWARM_SCALING_cpu_{artifact['date']}.json"
    out.write_text(json.dumps(artifact, indent=2) + "\n")
    print(f"# wrote {out}", file=sys.stderr)
    return artifact


def main() -> None:
    os.environ.setdefault("CROWDLLAMA_TPU_TEST_MODE", "1")
    os.environ.setdefault("JAX_PLATFORMS", "cpu")
    if "--arms" in sys.argv[1:]:
        print(json.dumps(run_arms()))
        return
    if not os.environ.get("CROWDLLAMA_NO_NATIVE"):
        from crowdllama_tpu import native
        native.ensure_built()  # pay the g++ run before the loop starts
    result = asyncio.run(run())
    print(json.dumps(result))


if __name__ == "__main__":
    sys.exit(main())
