"""Speculative-decode proposer sweep on a GENERATIVE workload.

Sweeps {ngram, random-draft, distilled-draft} x draft_len k on natural-text
prompts the distillation corpus never saw, and reports emitted tokens per
verify step positioned against the bracket the r5 bench measured: 1.12
(random-init draft — speculation priced at ~zero acceptance) and 4.79
(self-draft — every proposal accepts).  The distilled cell is the number
that matters: it is what a real deployment gets from
``crowdllama-tpu distill-draft`` + ``--spec-decode draft``.

The distilled checkpoint comes from ``CROWDLLAMA_TPU_SPEC_DRAFT_PATH``
when set (bench.py's ``decode_spec_draft`` phase sets it when the
operator has one); otherwise the script distills one here, at tiny scale
on CPU, from the repo's own prose (README + ROADMAP) — the eval prompts
below are NOT drawn from those files, so acceptance is held-out.

Prints ONE JSON line like every benchmarks/ script; ``--out`` also writes
it to a file (benchmarks/results/ convention).

Run (repo root, CPU):
    JAX_PLATFORMS=cpu python benchmarks/spec_decode.py
"""

import _common  # noqa: F401  (repo-root sys.path + platform re-pin)

import argparse
import hashlib
import json
import os
import sys
import tempfile
import time
from dataclasses import replace
from pathlib import Path

# Bracket from the r5 bench artifact (BENCH_r05 decode_spec draft cells;
# ROADMAP VERDICT #7): tokens/verify-step of the random-init draft floor
# and the self-draft ceiling on the natural workload.
FLOOR_RANDOM_DRAFT = 1.12
CEILING_SELF_DRAFT = 4.79

# Held-out generative prompts: English prose, byte-tokenized, deliberately
# absent from README/ROADMAP (the default distillation corpus).
_EVAL_PROMPTS = (
    b"The scheduler retires in-flight chunks before dispatching the next "
    b"batch of decode work.",
    b"Acceptance-adaptive speculation tunes the draft length from the "
    b"measured acceptance rate.",
)


def _sha256_dir(path: str) -> str:
    h = hashlib.sha256()
    for f in sorted(Path(path).rglob("*")):
        if f.is_file():
            h.update(f.name.encode())
            h.update(f.read_bytes())
    return h.hexdigest()


def _distill_default(out_dir: str) -> str:
    """Distill a draft from the repo's own prose (held out from the eval
    prompts above) — the zero-setup CPU path."""
    from crowdllama_tpu.train.distill import DistillConfig, distill_draft

    root = Path(__file__).resolve().parent.parent
    corpus = os.path.join(out_dir, "corpus.txt")
    with open(corpus, "wb") as f:
        f.write((root / "README.md").read_bytes())
        f.write((root / "ROADMAP.md").read_bytes())
    ckpt = os.path.join(out_dir, "draft")
    distill_draft(DistillConfig(teacher="tiny-test", corpus_path=corpus,
                                out=ckpt, log_every=0))
    return ckpt


def _measure(runner, prompt_tokens, steps: int) -> dict:
    import jax
    import numpy as np

    state = runner.init_state()
    key = jax.random.PRNGKey(0)
    for slot in range(runner.max_slots):
        key, sub = jax.random.split(key)
        first, ks, vs, plen = runner.prefill(prompt_tokens, 0.0, 1.0, sub,
                                             state=state)
        state = runner.insert(state, slot, ks, vs, plen, first, 0.0, 1.0,
                              prompt_tokens=prompt_tokens)
    chunk = min(8, steps)
    packed, state = runner.decode_steps(state, chunk)  # warmup + compile
    t0 = time.monotonic()
    chunks, done = [], 0
    while done + chunk <= steps:
        packed, state = runner.decode_steps_device(state, chunk)
        chunks.append(packed)
        done += chunk
    rows = [np.asarray(p) for p in chunks]  # sync
    dt = time.monotonic() - t0
    counts = np.concatenate([r[:, 0, :] for r in rows])
    srcs = np.concatenate([r[:, -1, :] for r in rows])
    accepted = np.maximum(counts - 1, 0)
    emitted = int(counts.sum())
    for slot in range(runner.max_slots):
        state = runner.release(state, slot)
    return {
        "emitted_tok_s": round(emitted / dt, 2),
        "verify_steps": done * runner.max_slots,
        "tokens_per_step": round(emitted / max(1, done * runner.max_slots),
                                 3),
        "accepted_prompt_echo": int((accepted * (srcs == 1)).sum()),
        "accepted_generative": int((accepted * (srcs == 2)).sum()),
    }


def run_sweep(model: str = "tiny-test", draft_path: str = "",
              ks=(1, 2, 3, 4), steps: int = 24, slots: int = 2) -> dict:
    """The sweep as a callable (bench.py's decode_spec_draft phase):
    returns the one-line JSON dict instead of printing it."""
    import jax

    from crowdllama_tpu.engine.spec import (
        DraftSpecPagedModelRunner,
        SpecPagedModelRunner,
    )
    from crowdllama_tpu.engine.weights import (
        load_or_init_params,
        native_config_from_dir,
    )
    from crowdllama_tpu.models import transformer as T
    from crowdllama_tpu.models.config import get_config

    ctx = 256
    cfg = get_config(model, max_context_length=ctx)
    params = T.init_params(cfg, jax.random.PRNGKey(0))
    platform = jax.devices()[0].platform
    ks = list(ks)

    tmp = None
    if not draft_path:
        tmp = tempfile.TemporaryDirectory(prefix="spec-decode-bench-")
        print("# no draft checkpoint given: distilling one from repo "
              "prose (held out from eval prompts)", file=sys.stderr)
        draft_path = _distill_default(tmp.name)
    draft_sha = _sha256_dir(draft_path)
    draft_cfg = replace(native_config_from_dir(draft_path),
                        max_context_length=ctx)
    draft_params = load_or_init_params(draft_cfg, draft_path)

    prompts = [[t % cfg.vocab_size for t in p] for p in _EVAL_PROMPTS]
    # Budget: each verify step can advance 1+k tokens; keep the longest
    # run inside the context window (warmup chunk included).
    steps = min(steps,
                (ctx - max(len(p) for p in prompts) - 2
                 - 8 * (1 + max(ks))) // (1 + max(ks)))

    def cell(make_runner) -> dict:
        per_prompt = [_measure(make_runner(), p, steps) for p in prompts]
        agg = {
            "tokens_per_step": round(
                sum(r["tokens_per_step"] for r in per_prompt)
                / len(per_prompt), 3),
            "emitted_tok_s": round(
                sum(r["emitted_tok_s"] for r in per_prompt)
                / len(per_prompt), 2),
            "accepted_prompt_echo": sum(r["accepted_prompt_echo"]
                                        for r in per_prompt),
            "accepted_generative": sum(r["accepted_generative"]
                                       for r in per_prompt),
            "verify_steps": sum(r["verify_steps"] for r in per_prompt),
        }
        return agg

    kw = dict(params=params, max_slots=slots, max_seq=ctx)
    sweep: dict[str, dict] = {}
    for k in ks:
        sweep[f"ngram_k{k}"] = cell(lambda: SpecPagedModelRunner(
            cfg, draft_len=k, **kw))
        sweep[f"draft_random_k{k}"] = cell(
            lambda: DraftSpecPagedModelRunner(
                cfg, draft_cfg=replace(
                    cfg, name=cfg.name + "-rand2l",
                    num_layers=min(2, cfg.num_layers)),
                draft_params=None, draft_seed=12345, draft_len=k, **kw))
        sweep[f"draft_distilled_k{k}"] = cell(
            lambda: DraftSpecPagedModelRunner(
                cfg, draft_cfg=draft_cfg, draft_params=draft_params,
                draft_len=k, **kw))

    best_k, best = max(
        ((k, sweep[f"draft_distilled_k{k}"]) for k in ks),
        key=lambda kv: kv[1]["tokens_per_step"])
    ngram_best = max(sweep[f"ngram_k{k}"]["tokens_per_step"] for k in ks)
    line = {
        "metric": f"{cfg.name} distilled-draft speculation, emitted tokens "
                  f"per verify step (generative workload, best k)",
        "value": best["tokens_per_step"],
        "unit": "tokens/verify-step",
        "vs_baseline": None,
        "extra": {
            "platform": platform,
            "best_k": best_k,
            "floor_random_draft": FLOOR_RANDOM_DRAFT,
            "ceiling_self_draft": CEILING_SELF_DRAFT,
            "position_in_bracket": round(
                (best["tokens_per_step"] - FLOOR_RANDOM_DRAFT)
                / (CEILING_SELF_DRAFT - FLOOR_RANDOM_DRAFT), 3),
            "ngram_best_tokens_per_step": ngram_best,
            "draft_checkpoint": draft_path,
            "draft_checkpoint_sha256": draft_sha,
            "timed_steps_per_cell": steps,
            "slots": slots,
            "workload": "generative (held-out natural text; no prompt "
                        "echo by construction)",
            "sweep": sweep,
        },
    }
    if tmp is not None:
        tmp.cleanup()
    return line


def main() -> int:
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("--model", default="tiny-test")
    ap.add_argument("--draft-path",
                    default=os.environ.get("CROWDLLAMA_TPU_SPEC_DRAFT_PATH",
                                           ""))
    ap.add_argument("--ks", default="1,2,3,4",
                    help="comma-separated draft lengths to sweep")
    ap.add_argument("--steps", type=int, default=24,
                    help="timed verify steps per cell")
    ap.add_argument("--slots", type=int, default=2)
    ap.add_argument("--out", default="", help="also write the JSON here")
    args = ap.parse_args()
    line = run_sweep(model=args.model, draft_path=args.draft_path,
                     ks=[int(k) for k in args.ks.split(",") if k],
                     steps=args.steps, slots=args.slots)
    out = json.dumps(line)
    print(out)
    if args.out:
        Path(args.out).parent.mkdir(parents=True, exist_ok=True)
        Path(args.out).write_text(out + "\n")
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
