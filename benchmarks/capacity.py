"""Largest-model-per-chip capacity report (BASELINE config 2/3 feasibility).

Static accounting of parameter + KV-cache bytes for every registry model
against the attached accelerator's HBM, in bf16 and int8 (ops/quant.py).
Answers "which BASELINE configs fit one chip" without downloading weights —
the same accounting the scheduler needs for placement.

Prints ONE JSON line; value is the largest-servable model's parameter count
(billions) on one chip under int8.
"""

from __future__ import annotations

import sys
from pathlib import Path

sys.path.insert(0, str(Path(__file__).resolve().parent))
import _common  # noqa: F401,E402 - repo path + JAX platform bootstrap

import json
import os


def model_bytes(cfg, quant: bool, bits: int = 8) -> tuple[int, int]:
    """(param_bytes, kv_bytes_per_slot_at_max_ctx)."""
    d, f, v = cfg.hidden_size, cfg.intermediate_size, cfg.vocab_size
    h, hkv, nl = cfg.num_heads, cfg.num_kv_heads, cfg.num_layers
    dh = cfg.resolved_head_dim()
    attn = d * h * dh + 2 * d * hkv * dh + h * dh * d
    if cfg.is_moe:
        mlp = cfg.num_experts * 3 * d * f + d * cfg.num_experts
    else:
        mlp = 3 * d * f
    norms = 2 * d + (2 * d if cfg.post_norms else 0)
    per_layer = attn + mlp + norms
    embed = v * d
    head = 0 if cfg.tie_word_embeddings else d * v
    matmul_params = nl * (attn + mlp)  # quantizable
    other_params = nl * norms + embed + head + d
    wbytes = (bits / 8) if quant else 2
    param_bytes = int(matmul_params * wbytes) + other_params * 2
    if quant:
        if bits == 8:  # per-output-channel bf16 scales
            per_ch = nl * (h * dh + 2 * hkv * dh + d
                           + (3 * f if not cfg.is_moe
                              else cfg.num_experts * 3 * f))
            param_bytes += per_ch * 2
        else:  # int4: one bf16 scale per 64-weight group
            param_bytes += (matmul_params // 64) * 2
    kv_bytes = nl * hkv * cfg.max_context_length * dh * 2 * 2  # k+v bf16
    return param_bytes, kv_bytes


def main() -> None:
    from crowdllama_tpu.models.config import get_config, list_models
    from crowdllama_tpu.peer.peer import _tpu_capabilities

    caps = _tpu_capabilities()
    hbm_gb = caps.get("hbm_gb_per_chip") or 0.0
    if not hbm_gb:
        hbm_gb = 16.0  # assume one v5e chip when introspection unavailable
    budget = hbm_gb * (1 << 30) * 0.9  # leave 10% for XLA scratch
    slots = int(os.environ.get("CROWDLLAMA_BENCH_SLOTS", "8"))

    rows, best = [], None
    for name in list_models():
        if name.startswith("tiny-test"):
            continue
        cfg = get_config(name)
        pb16, kv = model_bytes(cfg, quant=False)
        pb8, _ = model_bytes(cfg, quant=True)
        pb4, _ = model_bytes(cfg, quant=True, bits=4)
        kv_per_tok = kv / cfg.max_context_length
        fits16 = pb16 + slots * kv < budget
        fits8 = pb8 + slots * kv < budget
        # Largest power-of-two context at which params + slots*KV fit (int8).
        ctx_fit = 0
        c = cfg.max_context_length
        while c >= 128:
            if pb8 + slots * kv_per_tok * c < budget:
                ctx_fit = c
                break
            c //= 2
        params_b = round((pb16 / 2) / 1e9, 2)
        rows.append({"model": name, "params_b": params_b,
                     "bf16_gb": round(pb16 / 2**30, 1),
                     "int8_gb": round(pb8 / 2**30, 1),
                     "int4_gb": round(pb4 / 2**30, 1),
                     "fits_int4": pb4 + slots * kv < budget,
                     "kv_gb_at_max_ctx_x%d" % slots: round(slots * kv / 2**30, 1),
                     "fits_bf16": fits16, "fits_int8": fits8,
                     "max_ctx_fit_int8": ctx_fit})
        if ctx_fit and (best is None or params_b > best[1]):
            best = (name, params_b)
        print(f"# {name}: {params_b}B params, bf16 {pb16/2**30:.1f} GiB "
              f"(fits={fits16}), int8 {pb8/2**30:.1f} GiB (fits={fits8}, "
              f"ctx<={ctx_fit})", file=sys.stderr)

    print(json.dumps({
        "metric": f"largest model servable on one chip ({hbm_gb:.0f} GiB HBM, int8)",
        "value": best[1] if best else 0.0,
        "unit": "B params",
        "vs_baseline": None,
        "extra": {"model": best[0] if best else None, "slots": slots,
                  "accelerator": caps.get("accelerator"), "rows": rows},
    }))


if __name__ == "__main__":
    main()
