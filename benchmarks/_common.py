"""Shared benchmark bootstrap: repo-root import path + JAX platform re-pin.

Imported for its side effects at the top of every benchmark script —
keeping the platform-override workaround in exactly one place.
"""

import os
import sys
from pathlib import Path

sys.path.insert(0, str(Path(__file__).resolve().parent.parent))  # repo root

# Honor JAX_PLATFORMS even when the interpreter pre-imported jax pinned to
# another platform (see cli/main.py) — must run before any backend init.
if os.environ.get("JAX_PLATFORMS"):
    try:
        import jax

        jax.config.update("jax_platforms", os.environ["JAX_PLATFORMS"])
    except Exception:  # pragma: no cover - jax absent or already initialized
        pass
