# Developer entry points.  Every test target pins JAX to CPU (tests
# virtualize 8 devices via XLA flags in tests/conftest.py).

PY ?= python
PYTEST = env JAX_PLATFORMS=cpu $(PY) -m pytest -p no:cacheprovider

.PHONY: test tier1 chaos distill-smoke bench-kv

# Full suite (slow soaks included).  Runs the chaos matrix FIRST: the
# fault-injection scenarios are the cheapest way to catch a request-
# plane regression, so they gate the long tail instead of trailing it.
test: chaos
	$(PYTEST) tests/ -q -m 'not chaos'

# The tier-1 gate: what CI (and ROADMAP.md) holds the repo to.
tier1:
	$(PYTEST) tests/ -q -m 'not slow' --continue-on-collection-errors

# Deterministic fault-injection matrix (docs/ROBUSTNESS.md): seeded
# FaultPlans from crowdllama_tpu/testing/faults.py kill streams, fail
# handshakes, exhaust budgets, and drain workers mid-stream; assertions
# check the request plane heals (mid-stream failover, live migration
# with KV handoff, 504 budgets, 503 shedding).
chaos:
	$(PYTEST) tests/ -q -m chaos

# Draft-distillation training tests (docs/SPECULATIVE.md): 30-step CPU
# distillation smoke + native-checkpoint round-trip + the trained-draft
# greedy-exactness regression.  Runs in tier 1 too; this target is the
# standalone loop for iterating on train/distill.py.
distill-smoke:
	$(PYTEST) tests/ -q -m train

# KV-shipping benchmark (docs/KV_TRANSFER.md): fetch-vs-recompute TTFT
# over real p2p streams with an injected-RTT sweep; writes the artifact
# under benchmarks/results/.
bench-kv:
	env JAX_PLATFORMS=cpu CROWDLLAMA_BENCH_PHASES=kv_transfer $(PY) bench.py
