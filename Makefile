# Developer entry points.  Every test target pins JAX to CPU (tests
# virtualize 8 devices via XLA flags in tests/conftest.py).

PY ?= python
PYTEST = env JAX_PLATFORMS=cpu $(PY) -m pytest -p no:cacheprovider

.PHONY: test tier1 lint chaos chaos-multi-gateway chaos-soak \
	distill-smoke bench-kv bench-mixed bench-megastep bench-fused \
	bench-autopilot bench-swarm bench-spec-rtt trace-demo obs-demo

# Full suite (slow soaks included).  Runs lint + the chaos matrix FIRST:
# swarmlint finishes in seconds and the fault-injection scenarios are the
# cheapest way to catch a request-plane regression, so they gate the
# long tail instead of trailing it.
test: lint chaos chaos-soak
	$(PYTEST) tests/ -q -m 'not chaos'

# The tier-1 gate: what CI (and ROADMAP.md) holds the repo to.
tier1: lint
	$(PYTEST) tests/ -q -m 'not slow' --continue-on-collection-errors

# swarmlint (docs/STATIC_ANALYSIS.md): async-hotpath / jax-purity /
# contract-exhaustiveness checkers over the package.  Exit 1 on any
# finding not waived by crowdllama_tpu/analysis/baseline.toml.
lint:
	env JAX_PLATFORMS=cpu $(PY) -m crowdllama_tpu.analysis

# Deterministic fault-injection matrix (docs/ROBUSTNESS.md): seeded
# FaultPlans from crowdllama_tpu/testing/faults.py kill streams, fail
# handshakes, exhaust budgets, drain workers mid-stream, and drop/delay/
# partition gossip frames; assertions check the request plane heals
# (mid-stream failover, live migration with KV handoff, 504 budgets,
# jittered 503 shedding, gateway-crash failover across replicas).
chaos: chaos-multi-gateway
	$(PYTEST) tests/ -q -m chaos

# Replicated-gateway slice of the matrix (tests/test_gossip.py): a
# gateway replica killed mid-burst with survivors byte-identical plus
# the gossiped-pin continuation, gossip convergence through a seeded
# drop/delay/partition plan, and per-tenant shedding over HTTP.
chaos-multi-gateway:
	$(PYTEST) tests/test_gossip.py -q \
		-k 'two_gateways or converges_under or tenant_quota_sheds'

# Seeded chaos soak (docs/ROBUSTNESS.md "Gray failures"): 200 streams
# against a 5-worker loopback swarm under a mixed kill/stall/slow/
# hedge-delay/drain/partition schedule; every stream must come back
# byte-identical to its fault-free control with exactly one clean
# terminal, stalled streams must recover within the stall budget +
# failover slack, and hedge_launched == hedge_won + hedge_cancelled.
# Deterministic schedule, < 120 s; artifact under benchmarks/results/.
chaos-soak:
	env JAX_PLATFORMS=cpu $(PY) -m crowdllama_tpu.testing.soak \
		--seed 42 --streams 200

# Draft-distillation training tests (docs/SPECULATIVE.md): 30-step CPU
# distillation smoke + native-checkpoint round-trip + the trained-draft
# greedy-exactness regression.  Runs in tier 1 too; this target is the
# standalone loop for iterating on train/distill.py.
distill-smoke:
	$(PYTEST) tests/ -q -m train

# Stitched-trace demo (docs/OBSERVABILITY.md): boots a loopback relay
# swarm in process, sends one chat request, and prints its cross-node
# trace as a waterfall — gateway, relay hop, and worker on one timeline.
trace-demo:
	env JAX_PLATFORMS=cpu PYTHONPATH=. $(PY) examples/trace_demo.py

# Swarm-observatory demo (docs/OBSERVABILITY.md): boots a loopback
# 2-worker swarm in process, pushes a few requests, and prints the
# `crowdllama-tpu top` table plus a /metrics/cluster excerpt.
obs-demo:
	env JAX_PLATFORMS=cpu PYTHONPATH=. $(PY) examples/obs_demo.py

# KV-shipping benchmark (docs/KV_TRANSFER.md): fetch-vs-recompute TTFT
# over real p2p streams with an injected-RTT sweep; writes the artifact
# under benchmarks/results/.
bench-kv:
	env JAX_PLATFORMS=cpu CROWDLLAMA_BENCH_PHASES=kv_transfer $(PY) bench.py

# Gateway-drafted speculative pipeline vs worker-paced stop-and-wait vs
# plain streaming across injected swarm RTT (docs/SPECULATIVE.md).
bench-spec-rtt:
	env JAX_PLATFORMS=cpu CROWDLLAMA_BENCH_PHASES=spec_rtt $(PY) bench.py

# Unified-ragged-batch benchmark (docs/RAGGED_BATCH.md): decode-step p95
# while a long prefill chunks through the same jitted step (swept over
# step_token_budget, with the retired alternating loop as the control),
# plus a 32k-token prefill the monolithic one-shot path could not fit.
bench-mixed:
	env JAX_PLATFORMS=cpu CROWDLLAMA_BENCH_PHASES=mixed_batch,ctx32k \
		$(PY) bench.py

# Kernel-looped decode megastep (docs/MEGASTEP.md): decode steps/sec and
# host dispatches per token, swept over K in {1,2,4,8} against the
# per-step dispatch+readback control.
bench-megastep:
	env JAX_PLATFORMS=cpu CROWDLLAMA_BENCH_PHASES=decode_megastep \
		$(PY) bench.py

# Fused ragged megastep (docs/MEGASTEP.md "Fused ragged megastep"): the
# mixed-batch phase's fused-vs-gated arms (decode-step p95 during a long
# prefill, tokens per dispatch, host-gap share) plus the megastep K
# sweep — the two phases that price megastep x ragged fusion.
bench-fused:
	env JAX_PLATFORMS=cpu \
		CROWDLLAMA_BENCH_PHASES=mixed_batch,decode_megastep \
		$(PY) bench.py

# Closed-loop performance autopilot (docs/AUTOTUNE.md): three scenario
# shapes under grid-search-best static dials vs the autotuner walking
# from defaults — steps/sec ratio, moves-to-converge, dial trajectory
# (artifact: benchmarks/results/AUTOTUNE_cpu_*.json).
# Native data-plane arms (docs/NATIVE.md): the swarm_scaling phase run
# twice — native fast path vs CROWDLLAMA_NO_NATIVE=1 — one subprocess
# per arm; writes benchmarks/results/SWARM_SCALING_cpu_<date>.json with
# req/s, cpu_us_per_request, loop lag, and the serde+aead share per arm.
bench-swarm:
	env JAX_PLATFORMS=cpu $(PY) benchmarks/swarm_scaling.py --arms

bench-autopilot:
	env JAX_PLATFORMS=cpu CROWDLLAMA_BENCH_PHASES=autopilot \
		$(PY) bench.py
