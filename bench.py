"""Driver benchmark suite: the full BASELINE metric set as JSON lines.

Emits MULTIPLE JSON lines (one per phase), each
{"metric", "value", "unit", "vs_baseline", ...}, flushed as soon as the
phase finishes and mirrored to BENCH_partial.jsonl — a later phase dying
(or the TPU tunnel dropping mid-run) cannot erase earlier results.

Phases (CROWDLLAMA_BENCH_PHASES to select, comma-separated):
  decode       TinyLlama-1.1B int8 decode throughput (headline parity config)
  decode_paged same config on the paged KV pool + fused pallas paged-decode
               kernel (the serving default) — must land within ~5% of decode
  decode8b     Llama-3-8B int8 decode throughput (BASELINE config 2 headline)
  decode8b_paged  the same 8B config on the PRODUCTION-DEFAULT serving path
               (paged KV + fused pallas kernel), swept over batch slots
               (CROWDLLAMA_BENCH_SLOTS_SWEEP, default 16,32,64)
  decode_kv8   TinyLlama int8 weights + int8 KV cache (the halved cache read)
  decode8b_int4  Llama-3-8B int4 weights — Ollama's own 8B default is 4-bit
               GGUF, so int4-vs-Q4 is the parity-honest quantization cell
  decode_spec  speculative decode on the paged pool: n-gram on a NATURAL
               workload (headline) + repetitive best case, with the
               prompt-echo/generative acceptance split, plus draft-MODEL
               bounds (self-draft ceiling, untrained-draft floor)
  decode_spec_draft  DISTILLED-draft speculation: benchmarks/spec_decode.py's
               {ngram, random-draft, distilled-draft} x k sweep on a
               held-out generative workload vs the 1.12/4.79 bracket
               (checkpoint via CROWDLLAMA_TPU_SPEC_DRAFT_PATH, sha256
               recorded; distills a tiny draft in-phase when unset)
  kernel    Pallas flash prefill+decode numeric parity vs the jnp reference
            ops, on the attached device (interpret-mode on CPU fallback)
  ttft      gateway p50 TTFT through the full loopback stack
            (benchmarks/ttft.py as a subprocess)
  swarm     swarm scaling 1->16 FakeEngine workers
            (benchmarks/swarm_scaling.py as a subprocess, CPU)
  ep_dispatch  cross-worker expert-parallel decode through a 2-bank MoE
            group on real loopback streams — the per-MoE-layer dispatch
            hop price (BASELINE config 4; subprocess, CPU)
  kv_transfer  swarm KV shipping: prefix-page fetch vs prefill recompute
            TTFT across injected RTT, with the break-even prefix length
            (benchmarks/kv_transfer.py as a subprocess, CPU)
  spec_rtt  gateway-drafted speculative pipeline vs worker-paced
            stop-and-wait vs plain streaming across injected RTT
            (benchmarks/spec_rtt.py as a subprocess, CPU)
  mini_swarm  REAL tiny engines behind the gateway on CPU — end-to-end
            tok/s + TTFT under concurrent load, with a FakeEngine
            control curve (VERDICT #5; subprocess, CPU)
  multi_gateway  replicated gateway plane — req/s 1->4 replicas,
            cross-replica affinity hit-rate through the gossip map, and
            tenant isolation under a hot-tenant flood (subprocess, CPU)
  capacity  static params+KV HBM accounting per registry model against
            the attached chip (largest-servable report; subprocess)
  mixed_batch  unified ragged batch (docs/RAGGED_BATCH.md): decode-step
            p95 while a LONG prefill is in flight, with vs without
            unification, swept over step_token_budget — the knob that
            trades prefill completion time for decode smoothness
  ctx32k    a 32768-token prefill COMPLETED through ragged chunking — a
            context whose monolithic one-shot prefill step cannot fit
            (the reference attention path would materialize an
            [H, 32k, 32k] fp32 score matrix, beyond the chip's HBM)
  decode_megastep  kernel-looped decode (docs/MEGASTEP.md): K full decode
            steps per host dispatch with on-device sampling, swept over
            K in {1,2,4,8} against a per-step dispatch+readback control —
            decode steps/sec and host dispatches per token
  autopilot  closed-loop dial autopilot (docs/AUTOTUNE.md): three
            scenario shapes, each under grid-search-best static dials vs
            the autotuner from defaults — steps/sec ratio, moves to
            converge, and the dial trajectory (subprocess, CPU)

The reference publishes no measured numbers (SURVEY §6); the only
throughput figure in its tree is the hardcoded 150 tokens/sec a worker
*advertises* (/root/reference/pkg/peer/peer.go:323-333).  ``vs_baseline``
is therefore measured tokens/sec/chip divided by that advertised 150 tok/s
where comparable, null elsewhere.

Resilience: the chip sits behind a network tunnel that can drop for many
minutes (BENCH_r02 lost the whole round to a 300 s budget; BENCH_r04 fell
back to CPU at startup and never looked again — VERDICT r4 #1).  The
suite now:
  - waits a bounded slice of the budget at startup, then falls back to
    CPU so the run always produces a parseable artifact;
  - RE-PROBES the tunnel (bounded subprocess) at every phase boundary —
    a mid-run tunnel-up window flips the suite back to TPU, runs the
    deferred TPU-only phases in BASELINE-priority order (decode8b first),
    and re-runs the phases that executed on the CPU fallback;
  - defers TPU-only phases behind the CPU-runnable ones instead of
    skipping them at startup, so the tunnel gets the whole run's
    duration to come back;
  - on final skip, emits markers carrying the per-phase probe evidence
    and the newest builder-session TPU artifact's path + sha256, so the
    provenance chain to the last real on-chip numbers is explicit.

Env knobs:
  BENCH_DEADLINE_S            overall wall-clock deadline for the WHOLE
                              suite (default 1200).  Checked before every
                              phase and every tunnel re-probe; on expiry
                              the remaining phases emit provenance-bearing
                              skip markers and the run exits rc 0 — the
                              artifact always has a line per phase, tunnel
                              up or down (VERDICT r5 next-round #1).
  CROWDLLAMA_BENCH_BUDGET_S   device-wait budget seconds (default 1500;
                              up to 120 s of it waits at startup, and the
                              full budget then backs per-phase re-probes)
  CROWDLLAMA_BENCH_SLOTS_SWEEP  decode8b_paged slot sweep (default 16,32,64)
  CROWDLLAMA_BENCH_PHASES     comma list (default all)
  CROWDLLAMA_BENCH_SLOTS      batch slots        (default 8; 16 for the
                              decode8b phase, whose weight-bandwidth-bound
                              throughput scales with batch)
  CROWDLLAMA_BENCH_SLOTS_8B   decode8b-only slots override
  CROWDLLAMA_BENCH_STEPS      timed decode steps (default 512)
  CROWDLLAMA_BENCH_CTX        max context        (default 1024)
  CROWDLLAMA_BENCH_QUANTIZE   "int8" | "int4" | "none"  (default int8)
  CROWDLLAMA_BENCH_KV         "bf16" | "int8"    KV cache dtype (default bf16)
  CROWDLLAMA_BENCH_MODEL      override the `decode` phase model
  CROWDLLAMA_BENCH_SUBPROC_TIMEOUT  ttft/swarm subprocess timeout (default 900)
"""

from __future__ import annotations

import json
import os
import subprocess
import sys
import time
import traceback
from dataclasses import replace
from pathlib import Path

BASELINE_ADVERTISED_TOKS = 150.0  # reference worker's hardcoded claim
PARTIAL_PATH = Path(__file__).resolve().parent / "BENCH_partial.jsonl"
# kernel runs FIRST: it proves the Mosaic-compiled kernels on this chip;
# if it fails, later phases run with CROWDLLAMA_NO_PALLAS=1 so a kernel
# regression degrades to the XLA paths instead of zeroing the artifact.
# BASELINE-metric phases run FIRST (decode configs, ttft, swarm): if the
# run is cut short, the partials already hold the scoreboard; the
# quantization/context variants are the long tail (each 8B phase pays
# ~3 min of on-chip param init alone).
_ALL_PHASES = ("kernel", "decode", "decode_paged", "decode8b",
               "decode8b_paged", "decode8b_ctx4k", "ttft", "swarm",
               "ep_dispatch", "kv_transfer", "mini_swarm", "multi_gateway",
               "capacity", "mixed_batch", "ctx32k", "decode_megastep",
               "obs_overhead", "autopilot", "spec_rtt", "decode_spec",
               "decode_spec_draft", "decode_kv8", "decode8b_int4")

# Phases meaningless on the CPU fallback (real-size or quantized decode).
_TPU_ONLY_PHASES = frozenset(
    {"decode8b", "decode8b_paged", "decode8b_int4", "decode8b_ctx4k",
     "decode_kv8"})
# When a tunnel-up window opens mid-run, spend it on the BASELINE
# scoreboard first: kernel parity FIRST (its CPU run was interpret-mode;
# the on-chip Mosaic compile must validate the kernels before any phase
# relies on them — the suite's standing kernel-gate invariant), then the
# 8B headline, then the production-default paged 8B (whose int8 params
# are then already resident for ctx4k).
_TPU_WINDOW_PRIORITY = {"kernel": -1, "decode8b": 0, "decode8b_paged": 1,
                        "decode8b_ctx4k": 2, "decode_kv8": 3,
                        "decode8b_int4": 4, "decode_megastep": 5,
                        "mixed_batch": 6}
# CPU-fallback executions of these phases are re-run when the tunnel
# returns (their CPU numbers are tiny-model stand-ins); swarm is a
# control-plane metric and CPU by design.  mixed_batch and
# decode_megastep joined the list with the fused ragged megastep: their
# CPU numbers price the ref path's additive chunk flops, and the claim
# that the chunk rides in the decode step's idle compute (and that K
# dispatches amortize over the tunnel's ~70 ms round trip) is only
# provable on-chip.
_RERUN_ON_TPU = frozenset({"kernel", "decode", "decode_paged",
                           "decode_spec", "ttft", "mixed_batch",
                           "decode_megastep"})

# Honor JAX_PLATFORMS even though the image's sitecustomize pre-imports jax
# pinned to the axon (TPU tunnel) platform — env vars alone are read too
# early to win; jax.config.update must run before any backend initializes
# (same workaround as benchmarks/_common.py and tests/conftest.py).
if os.environ.get("JAX_PLATFORMS"):
    try:
        import jax as _jax

        _jax.config.update("jax_platforms", os.environ["JAX_PLATFORMS"])
    except Exception:  # pragma: no cover
        pass


def _emit(result: dict) -> None:
    """Print one metric line and persist it immediately."""
    line = json.dumps(result)
    print(line, flush=True)
    try:
        with PARTIAL_PATH.open("a") as f:
            f.write(line + "\n")
    except OSError as e:  # pragma: no cover - readonly fs
        print(f"# partial persist failed: {e}", file=sys.stderr)


class _Platform:
    """Tracks intended vs current jax platform across the run.

    The chip sits behind a network tunnel that occasionally drops and
    needs minutes to recover; probes run in SUBPROCESSES with a hard
    per-attempt timeout because a downed tunnel can make backend init
    HANG indefinitely inside the C extension (observed 20+ min,
    uninterruptible in-process).  After the startup budget the suite
    falls back to CPU — but keeps RE-PROBING at phase boundaries
    (VERDICT r4 #1: BENCH_r04 fell back at startup and missed the
    mid-run tunnel-up window the builder's own session caught)."""

    def __init__(self):
        import jax

        self.original = jax.config.jax_platforms  # axon/TPU unless pinned
        self.want_tpu = (self.original or "") != "cpu"
        self.on_cpu_fallback = False
        self.probe_attempts = 0
        self.probe_log: list[str] = []  # ISO timestamps of failed re-probes

    @staticmethod
    def _subprocess_probe(timeout_s: float) -> tuple[bool, str]:
        try:
            probe = subprocess.run(
                [sys.executable, "-c",
                 "import jax; d = jax.devices(); "
                 "print(d[0].platform, len(d))"],
                timeout=timeout_s, capture_output=True, text=True)
        except subprocess.TimeoutExpired:
            return False, "backend init hung (tunnel down)"
        out = probe.stdout.strip().split()
        if probe.returncode == 0 and out and out[0] == "tpu":
            return True, ""
        detail = (probe.stderr or "").strip().splitlines()
        return False, (detail[-1] if detail
                       else f"rc={probe.returncode} out={out}")

    def _fall_back_to_cpu(self):
        import jax

        jax.config.update("jax_platforms", "cpu")
        _clear_backends()
        self.on_cpu_fallback = True
        return jax.devices()

    def startup_wait(self, budget_s: float):
        """Bounded wait for the TPU backend; CPU fallback after it."""
        import jax

        if not self.want_tpu:
            return jax.devices()  # explicitly pinned (tests / CPU runs)
        deadline = time.monotonic() + budget_s
        delay = 5.0
        while True:
            remaining = deadline - time.monotonic()
            if remaining <= 0:
                print("# startup device budget exhausted; falling back to "
                      "CPU (will re-probe at phase boundaries)",
                      file=sys.stderr)
                return self._fall_back_to_cpu()
            self.probe_attempts += 1
            ok, detail = self._subprocess_probe(
                min(120.0, max(remaining, 10.0)))
            if ok:
                try:
                    # Tunnel is up per the probe: init in-process.  A drop
                    # in the gap between probe and init must re-enter the
                    # retry loop, not crash the run.
                    return jax.devices()
                except RuntimeError as e:
                    _clear_backends()
                    detail = f"post-probe init failed: {e}"
            print(f"# devices unavailable ({detail}); retrying in "
                  f"{delay:.0f}s", file=sys.stderr)
            time.sleep(delay)
            delay = min(delay * 2, 60.0)

    def reprobe(self, timeout_s: float = 60.0) -> bool:
        """One bounded attempt to regain the TPU at a phase boundary.
        True when the suite is (back) on the real chip."""
        import jax

        if not self.want_tpu:
            return False
        if not self.on_cpu_fallback:
            return True
        self.probe_attempts += 1
        ok, detail = self._subprocess_probe(timeout_s)
        if not ok:
            self.probe_log.append(
                time.strftime("%Y-%m-%dT%H:%M:%S") + f" {detail}")
            return False
        try:
            jax.config.update("jax_platforms", self.original)
            _clear_backends()
            if jax.devices()[0].platform == "tpu":
                self.on_cpu_fallback = False
                print("# tunnel back up: TPU backend restored",
                      file=sys.stderr)
                return True
        except Exception as e:  # dropped again in the probe→init gap
            print(f"# post-probe TPU init failed: {e}", file=sys.stderr)
        self._fall_back_to_cpu()
        return False


def _clear_backends() -> None:
    # Failed init is cached; reset it or the retry re-raises the stale
    # error.  (jax.clear_backends was removed from the top-level API;
    # jax.extend.backend carries it in jax 0.9.)
    try:
        import jax.extend.backend as _jeb

        _jeb.clear_backends()
    except Exception as ce:  # pragma: no cover
        print(f"# clear_backends unavailable: {ce}", file=sys.stderr)


# ----------------------------------------------------------------- decode

#: One quantized parameter tree, keyed (platform, model, mode): 8B param
#: init costs ~3 min of the tunnel window, and consecutive 8B phases
#: (decode8b -> decode8b_paged slot sweep -> ctx4k) share the same int8
#: weights.  Single-entry: two 8B trees cannot coexist on a 16 GB chip.
_PARAM_CACHE: dict[tuple, object] = {}


def _quantized_params(cfg, model: str, quantize: str, platform: str):
    import jax

    from crowdllama_tpu.ops.quant import random_quantized_params

    key = (platform, model, quantize)
    if key not in _PARAM_CACHE:
        _PARAM_CACHE.clear()  # free the previous tree BEFORE allocating
        t0 = time.monotonic()
        # Leaf-by-leaf quantized init: never materializes the bf16 tree, so
        # an 8B model (16 GB bf16) can be benched on the 16 GB chip it
        # serves from.  Throughput-identical to quantize_params(init(...)).
        _PARAM_CACHE[key] = random_quantized_params(
            cfg, jax.random.PRNGKey(0), mode=quantize)
        print(f"# param init ({model}, {quantize}): "
              f"{time.monotonic() - t0:.0f}s", file=sys.stderr)
    return _PARAM_CACHE[key]


def _decode_phase(model: str, layout: str = "contiguous",
                  slots: int = 0, quantize: str | None = None,
                  kv: str | None = None, ctx_override: int = 0) -> dict:
    """Saturated-batch decode throughput (tokens/sec/chip) for ``model``.

    ``quantize``/``kv`` override the env knobs for phases that pin a
    specific config (decode_kv8, decode8b_int4)."""
    import jax
    import numpy as np

    from crowdllama_tpu.engine.runner import ModelRunner
    from crowdllama_tpu.models.config import get_config

    platform = jax.devices()[0].platform
    if platform != "tpu":
        # CPU fallback: a real-size model would take hours; bench the tiny
        # model so the artifact still proves the serving path end-to-end.
        model, steps, slots = "tiny-test", 64, 4
        quantize, kv_dtype, ctx = "", "bf16", 256
    else:
        slots = slots or int(os.environ.get("CROWDLLAMA_BENCH_SLOTS", "8"))
        steps = int(os.environ.get("CROWDLLAMA_BENCH_STEPS", "512"))
        ctx = ctx_override or int(os.environ.get("CROWDLLAMA_BENCH_CTX",
                                                 "1024"))
        quantize = (quantize if quantize is not None
                    else os.environ.get("CROWDLLAMA_BENCH_QUANTIZE", "int8"))
        kv_dtype = kv or os.environ.get("CROWDLLAMA_BENCH_KV", "bf16")
        if quantize in ("none", "", "0"):
            quantize = ""

    cfg = get_config(model)
    if ctx < cfg.max_context_length:
        cfg = replace(cfg, max_context_length=ctx)
    n_chips = max(1, len(jax.devices()))

    print(f"# bench[{model}]: slots={slots} steps={steps} "
          f"ctx={cfg.max_context_length} devices={n_chips} "
          f"quantize={quantize or 'bf16'} kv={kv_dtype} platform={platform}",
          file=sys.stderr)

    t0 = time.monotonic()
    params = None
    if quantize in ("int8", "int4"):
        params = _quantized_params(cfg, model, quantize, platform)
    if layout == "paged":
        from crowdllama_tpu.engine.paged import PagedModelRunner

        # Size the pool for what this run actually touches (prompt page +
        # warmup + timed steps + one page of margin) instead of
        # slots x max_seq: the slot sweep's bs=64 x 8B config only fits the
        # 16 GB chip because pages the run can never reach are not
        # allocated.  Growth past the pool raises PagesExhausted loudly.
        per_slot = min(cfg.max_context_length, 128 + steps + 32 + 128)
        runner = PagedModelRunner(cfg, params=params, max_slots=slots,
                                  max_seq=cfg.max_context_length,
                                  kv_dtype=kv_dtype,
                                  pool_tokens=slots * per_slot)
    else:
        runner = ModelRunner(cfg, params=params, max_slots=slots,
                             max_seq=cfg.max_context_length,
                             kv_dtype=kv_dtype)
    state = runner.init_state()

    # Fill every slot with a short prompt so the decode batch is saturated.
    rng = np.random.default_rng(0)
    key = jax.random.PRNGKey(0)
    for slot in range(runner.max_slots):
        prompt = rng.integers(1, cfg.vocab_size, size=24).tolist()
        key, sub = jax.random.split(key)
        first, ks, vs, plen = runner.prefill(prompt, 0.7, 0.95, sub,
                                             state=state)
        state = runner.insert(state, slot, ks, vs, plen, first, 0.7, 0.95)
    print(f"# setup+prefill: {time.monotonic() - t0:.1f}s", file=sys.stderr)

    # Warmup compile of the timed decode program.
    chunk = min(32, steps)
    tokens, state = runner.decode_steps(state, chunk)  # warmup + compile

    # Timed: chain chunks on device (each dispatch overlaps the previous
    # chunk's execution) and read back ONCE — the serial state dependency
    # means the final readback observes every chunk finished.  Per-chunk
    # readbacks would add a host round trip (~70 ms over the tunnel) per
    # chunk to what is a pure device-throughput metric.
    t0 = time.monotonic()
    done = 0
    while chunk > 0 and done + chunk <= steps:  # equal chunks: one program
        tokens, state = runner.decode_steps_device(state, chunk)
        done += chunk
    tokens = np.asarray(tokens)  # sync
    dt = time.monotonic() - t0

    per_chip = done * runner.max_slots / dt / n_chips
    on_tpu = platform == "tpu"
    name = model if layout == "contiguous" else f"{model} (paged KV)"
    if kv_dtype == "int8":
        name += " (int8 KV)"
    if quantize == "int4":
        name += " (int4 weights)"
    if ctx_override:
        name += f" (ctx {ctx})"
    # Mean decode context during the timed window (prompt + warmup chunk +
    # half the timed steps) — the KV-read term of the step's byte budget.
    mean_len = min(24 + chunk + done / 2, cfg.max_context_length)
    return {
        "metric": f"{name} decode throughput",
        "value": round(per_chip, 2),
        "unit": "tokens/sec/chip",
        "vs_baseline": (round(per_chip / BASELINE_ADVERTISED_TOKS, 3)
                        if on_tpu else None),
        "extra": {"platform": platform, "slots": runner.max_slots,
                  "steps": done, "ctx": cfg.max_context_length,
                  "quantize": quantize or "bf16", "kv_dtype": kv_dtype,
                  "kv_layout": layout,
                  # Artifact must be self-describing: a paged number from
                  # the jnp gather fallback is not a fused-kernel number.
                  "no_pallas": bool(os.environ.get("CROWDLLAMA_NO_PALLAS")),
                  "roofline": _roofline_accounting(
                      runner, cfg, kv_dtype, mean_len, done, dt, n_chips,
                      on_tpu)},
    }


def _decode8b_paged_phase() -> dict:
    """8B on the PRODUCTION-DEFAULT path: paged KV + fused pallas kernel +
    int8 weights — the serving plan every Configuration resolves to —
    swept over batch slots (VERDICT r4 #2: the only 8B numbers ever
    captured were contiguous with pallas disabled; and at 59% of the
    practical HBM ceiling, bigger batches should push the amortized
    weight stream toward it).  Emits the best config as the headline with
    the whole sweep in extra; configs that do not fit the chip record
    "oom" instead of killing the phase.  The int8 param tree is shared
    across the sweep (and with decode8b / decode8b_ctx4k) via
    _PARAM_CACHE, so each extra config costs ~15 s, not ~3 min."""
    import jax

    sweep_env = os.environ.get("CROWDLLAMA_BENCH_SLOTS_SWEEP", "16,32,64")
    sweep = [int(s) for s in sweep_env.split(",") if s.strip()]
    results: dict[str, object] = {}
    best: dict | None = None
    for slots in sweep:
        try:
            r = _decode_phase("llama-3-8b", layout="paged", slots=slots)
        except Exception as e:
            # OOM (RESOURCE_EXHAUSTED) at bs=64 x bf16 KV is a plausible
            # outcome on a 16 GiB chip — record it, keep the smaller
            # configs' numbers.
            results[str(slots)] = f"failed: {type(e).__name__}: {e}"[:200]
            print(f"# paged-8B slots={slots} failed: {e}", file=sys.stderr)
            continue
        results[str(slots)] = {
            "tok_s_chip": r["value"],
            "pct_of_practical_ceiling":
                r["extra"]["roofline"]["pct_of_practical_ceiling"],
        }
        if best is None or (r["value"] or 0) > (best["value"] or 0):
            best = r
            best["extra"]["slots"] = slots
        if jax.devices()[0].platform != "tpu":
            break  # CPU fallback benches tiny-test; one copy is enough
    if best is None:
        raise RuntimeError(f"every sweep config failed: {results}")
    best["metric"] = "llama-3-8b (paged KV + fused kernel) decode throughput"
    best["extra"]["slots_sweep"] = results
    return best


def _latest_session_artifact() -> dict | None:
    """Newest builder-session on-chip artifact, for skip-marker provenance
    (VERDICT r4 #1: make the chain to the last real TPU numbers explicit
    when the tunnel stays down for the whole driver run)."""
    import hashlib

    results_dir = Path(__file__).resolve().parent / "benchmarks" / "results"
    candidates = sorted(results_dir.glob("BENCH_tpu_*.jsonl"))
    if not candidates:
        return None
    newest = candidates[-1]
    data = newest.read_bytes()
    return {"path": str(newest.relative_to(Path(__file__).resolve().parent)),
            "sha256": hashlib.sha256(data).hexdigest(),
            "lines": data.count(b"\n")}


#: Practical HBM ceiling measured on the attached v5e for B=8 skinny GEMMs
#: (benchmarks/ROOFLINE.md "Measured ceilings": 596 GB/s = 73% of the
#: 819 GB/s spec).  Decode is HBM-bound, so effective GB/s vs this number
#: IS the MFU-style utilization figure for the decode phases.
PRACTICAL_HBM_GBPS_V5E = 596.0


def _roofline_accounting(runner, cfg, kv_dtype: str, mean_len: float,
                         steps: int, dt: float, n_chips: int,
                         on_tpu: bool) -> dict:
    """Machine-readable per-phase perf accounting (VERDICT r3 #8): every
    decode step streams the full parameter set plus each slot's live KV —
    effective GB/s against the measured practical ceiling turns the next
    TPU run directly into roofline evidence instead of prose."""
    import jax

    from crowdllama_tpu.ops.quant import QTensor

    param_bytes = 0
    for leaf in jax.tree_util.tree_leaves(
            runner.params, is_leaf=lambda x: isinstance(x, QTensor)):
        if isinstance(leaf, QTensor):
            param_bytes += leaf.q.size * leaf.q.dtype.itemsize
            param_bytes += leaf.s.size * leaf.s.dtype.itemsize
        else:
            param_bytes += leaf.size * leaf.dtype.itemsize
    hkv, dh = cfg.num_kv_heads, cfg.resolved_head_dim()
    kv_item = 1 if kv_dtype == "int8" else 2
    kv_bytes = int(2 * cfg.num_layers * runner.max_slots * hkv * mean_len
                   * (dh * kv_item + (2 if kv_dtype == "int8" else 0)))
    step_bytes = param_bytes + kv_bytes
    eff_gbps = step_bytes * steps / dt / 1e9 / n_chips
    return {
        "param_bytes": int(param_bytes),
        "kv_bytes_per_step": kv_bytes,
        "effective_gbps_per_chip": round(eff_gbps, 1),
        "practical_ceiling_gbps": PRACTICAL_HBM_GBPS_V5E,
        # Only meaningful on the chip the ceiling was measured on.
        "pct_of_practical_ceiling": (
            round(100 * eff_gbps / PRACTICAL_HBM_GBPS_V5E, 1)
            if on_tpu else None),
    }


#: A natural-text prompt (byte-tokenized English prose, no templating):
#: bigram lookup has no echo to replay, so this measures the dividend a
#: NON-templated workload actually gets (VERDICT r4 #4: the repetitive
#: workload is speculation's best case and must not be the headline).
_NATURAL_TEXT = (b"The quick brown fox jumps over the lazy dog while "
                 b"autumn rain taps gently on the old tin roof.")


def _spec_phase() -> dict:
    """Speculative decode (ngram, paged pools) on TWO workloads: the
    headline is a NATURAL (non-repetitive) prompt — the honest number —
    with the repetitive best case and the prompt-echo vs generative
    acceptance split in extra.  `decode_paged` is the no-spec floor the
    uplift compares against."""
    import jax
    import numpy as np

    from crowdllama_tpu.engine.spec import SpecPagedModelRunner
    from crowdllama_tpu.models.config import get_config

    platform = jax.devices()[0].platform
    draft = 4
    if platform != "tpu":
        model, steps, slots, ctx = "tiny-test", 24, 4, 256
        quantize, kv_dtype = "", "bf16"
    else:
        model = os.environ.get("CROWDLLAMA_BENCH_MODEL", "tinyllama-1.1b")
        slots = int(os.environ.get("CROWDLLAMA_BENCH_SLOTS", "8"))
        ctx = int(os.environ.get("CROWDLLAMA_BENCH_CTX", "1024"))
        quantize = os.environ.get("CROWDLLAMA_BENCH_QUANTIZE", "int8")
        kv_dtype = os.environ.get("CROWDLLAMA_BENCH_KV", "bf16")
        if quantize in ("none", "", "0"):
            quantize = ""
        steps = int(os.environ.get("CROWDLLAMA_BENCH_STEPS", "512"))
    cfg = get_config(model)
    if ctx < cfg.max_context_length:
        cfg = replace(cfg, max_context_length=ctx)
    n_chips = max(1, len(jax.devices()))

    if quantize in ("int8", "int4"):
        params = _quantized_params(cfg, model, quantize, platform)
    else:
        # Explicit (not runner-internal) init so the draft-ceiling cell
        # below can provably share the main model's exact weights.
        from crowdllama_tpu.models import transformer as T_

        params = T_.init_params(cfg, jax.random.PRNGKey(0))
    base_runner = SpecPagedModelRunner(cfg, params=params, max_slots=slots,
                                       max_seq=cfg.max_context_length,
                                       kv_dtype=kv_dtype, draft_len=draft)

    motif = [7, 3, 11, 2]
    workloads = {
        "natural": [t % cfg.vocab_size for t in _NATURAL_TEXT],
        "repetitive_best_case": (motif * 8)[:24],
    }
    # Worst case every verify step (INCLUDING the untimed warmup chunk of
    # 8) advances 1+draft tokens — budget the longest prompt + first
    # token + warmup against the context window or the tail of the run
    # silently clamp-overwrites the last KV position.
    prompt_max = max(len(p) for p in workloads.values())
    steps = min(steps, max(4, (ctx - prompt_max - 2
                               - 8 * (1 + draft)) // (1 + draft)))

    def run_workload(prompt, r=None):
        runner = r if r is not None else base_runner
        state = runner.init_state()
        key = jax.random.PRNGKey(0)
        for slot in range(runner.max_slots):
            key, sub = jax.random.split(key)
            first, ks, vs, plen = runner.prefill(prompt, 0.0, 1.0, sub,
                                                 state=state)
            state = runner.insert(state, slot, ks, vs, plen, first,
                                  0.0, 1.0, prompt_tokens=prompt)
        chunk = min(8, steps)
        packed, state = runner.decode_steps(state, chunk)  # warmup+compile
        t0 = time.monotonic()
        chunks, done = [], 0
        while chunk > 0 and done + chunk <= steps:
            packed, state = runner.decode_steps_device(state, chunk)
            chunks.append(packed)
            done += chunk
        rows = [np.asarray(p) for p in chunks]  # sync
        dt = time.monotonic() - t0
        counts = np.concatenate([r[:, 0, :] for r in rows])
        srcs = np.concatenate([r[:, -1, :] for r in rows])
        accepted = np.maximum(counts - 1, 0)
        emitted = int(counts.sum())
        for slot in range(runner.max_slots):
            state = runner.release(state, slot)
        return {
            "emitted_tok_s_chip": round(emitted / dt / n_chips, 2),
            "verify_steps": done,
            "tokens_per_step": round(
                emitted / max(1, done * runner.max_slots), 2),
            "accepted_prompt_echo": int((accepted * (srcs == 1)).sum()),
            "accepted_generative": int((accepted * (srcs == 2)).sum()),
        }

    results = {name: run_workload(p) for name, p in workloads.items()}
    # Echo-vs-generative labels (ISSUE 4): which acceptance source each
    # workload can even exercise — natural prose has no prompt to replay,
    # so its acceptance is all generative; the repetitive prompt's wins
    # are mostly echo.
    results["natural"]["workload_kind"] = "generative"
    results["repetitive_best_case"]["workload_kind"] = "echo"

    # Draft-MODEL speculation (VERDICT r4 weak #4: no throughput number
    # anywhere): two labeled cells bound the feature.  CEILING = a draft
    # with the main model's own weights (greedy proposals always accept:
    # 1+draft tokens per verify step, minus the draft-rollout cost);
    # FLOOR = an independently-initialized depth-truncated draft (random
    # weights agree ~never, so it prices the draft-rollout overhead at
    # zero acceptance).  A trained draft lands between them.
    from crowdllama_tpu.engine.spec import DraftSpecPagedModelRunner

    def run_draft(draft_cfg, draft_params, draft_seed=0):
        r = DraftSpecPagedModelRunner(
            cfg, draft_cfg=draft_cfg, draft_params=draft_params,
            draft_seed=draft_seed, params=params, max_slots=slots,
            max_seq=cfg.max_context_length, kv_dtype=kv_dtype,
            draft_len=draft)
        return run_workload(workloads["natural"], r=r)

    try:
        # Self-draft: identical weights, greedy proposals always accept.
        results["draft_ceiling_self"] = run_draft(
            replace(cfg, name=cfg.name + "-selfdraft"), params)
    except Exception as e:
        results["draft_ceiling_self"] = f"failed: {e}"[:200]
        print(f"# draft ceiling failed: {e}", file=sys.stderr)
    try:
        # Untrained 2-layer draft: prices the rollout overhead at ~zero
        # acceptance.
        # draft_seed differs from the main init seed: a same-seed
        # truncation of a tiny main model would share its exact weights
        # and collapse the floor into the ceiling.
        results["draft_floor_random"] = run_draft(
            replace(cfg, name=cfg.name + "-draft2l",
                    num_layers=min(2, cfg.num_layers)), None,
            draft_seed=12345)
    except Exception as e:
        results["draft_floor_random"] = f"failed: {e}"[:200]
        print(f"# draft floor failed: {e}", file=sys.stderr)

    nat = results["natural"]
    on_tpu = platform == "tpu"
    return {
        "metric": f"{model} speculative (ngram, paged) emitted tokens/sec"
                  f" — natural workload",
        "value": nat["emitted_tok_s_chip"],
        "unit": "tokens/sec/chip",
        "vs_baseline": (round(nat["emitted_tok_s_chip"]
                              / BASELINE_ADVERTISED_TOKS, 3)
                        if on_tpu else None),
        "extra": {"platform": platform, "slots": base_runner.max_slots,
                  "draft_len": draft, "ctx": cfg.max_context_length,
                  "quantize": quantize or "bf16", "kv_dtype": kv_dtype,
                  "workloads": results,
                  "reading": "tokens_per_step 1.0 = no dividend (spec "
                             "pays only when > the ~same-cost plain "
                             "paged decode); echo acceptance exists only "
                             "on traffic that replays its prompt"},
    }


def _spec_draft_phase() -> dict:
    """Distilled-draft speculation (ISSUE 4): benchmarks/spec_decode.py's
    {ngram, random-draft, distilled-draft} x k sweep on a held-out
    generative workload, positioned against the r5 bracket (1.12 random
    floor / 4.79 self-draft ceiling).  Consumes
    CROWDLLAMA_TPU_SPEC_DRAFT_PATH (a `crowdllama-tpu distill-draft`
    checkpoint) and records its sha256; without one it distills a
    tiny-scale draft in-phase from repo prose (CPU: ~1 min)."""
    bench_dir = str(Path(__file__).resolve().parent / "benchmarks")
    if bench_dir not in sys.path:
        sys.path.insert(0, bench_dir)
    import spec_decode

    return spec_decode.run_sweep(
        draft_path=os.environ.get("CROWDLLAMA_TPU_SPEC_DRAFT_PATH", ""))


# ------------------------------------- unified ragged batch (RAGGED_BATCH)


def _latency_stats(samples: list[float]) -> dict:
    import numpy as np

    a = np.asarray(samples, float) * 1e3
    return {"n": len(samples),
            "p50_ms": round(float(np.percentile(a, 50)), 2),
            "p95_ms": round(float(np.percentile(a, 95)), 2)}


def _mixed_batch_phase() -> dict:
    """Decode-step latency while a LONG prefill is in flight
    (docs/RAGGED_BATCH.md).  Short decode streams keep every slot but one
    busy; the free slot admits a long prompt.  WITHOUT unification the
    pre-ragged scheduler alternated one prefill-chunk dispatch with one
    decode dispatch, so every decode token during the prefill paid a full
    512-token chunk on top of its step; WITH it the ragged step carries
    the decode tokens and the chunk in ONE dispatch, and
    ``step_token_budget`` bounds the chunk — the knob trading prefill
    completion time for decode-step smoothness.  Each budget also runs
    the FUSED arm (docs/MEGASTEP.md): ragged_megastep folds K=4 unified
    steps into ONE host dispatch with on-device sampling, so the
    per-step dispatch+readback the gated arm pays per token amortizes
    K×.  Swept over budgets; headline = FUSED decode-step p95 /
    decode-only p95 at the tightest budget, with the gated (per-dispatch)
    ratio alongside as the control (on the memory-bound TPU the chunk
    rides in the decode step's idle compute; on the CPU fallback the
    chunk's flops are additive, so only the tight budgets approach
    decode-only latency)."""
    import jax
    import numpy as np

    from crowdllama_tpu.engine.paged import PagedModelRunner
    from crowdllama_tpu.models.config import get_config

    platform = jax.devices()[0].platform
    if platform != "tpu":
        model, slots, ctx, page = "tiny-test", 4, 2048, 16
        long_len, rounds, chunks, base_n = 1536, 4, (512, 64, 16), 48
    else:
        model = os.environ.get("CROWDLLAMA_BENCH_MODEL", "tinyllama-1.1b")
        slots = int(os.environ.get("CROWDLLAMA_BENCH_SLOTS", "8"))
        ctx, page = 4096, 128
        long_len, rounds, chunks, base_n = 3072, 4, (512, 128), 64
    cfg = get_config(model)
    cfg = replace(cfg, max_context_length=ctx)
    rng = np.random.default_rng(0)
    long_slot = slots - 1  # the long prompt's slot; the rest decode

    def timed_decode_steps(runner, state, n):
        out = []
        for _ in range(n):
            t0 = time.monotonic()
            toks, state = runner.decode_steps_device(state, 1)
            np.asarray(toks)  # sync: per-step latency, not throughput
            out.append(time.monotonic() - t0)
        return out, state

    sweep: dict[str, object] = {}
    legacy: dict | None = None
    headline: dict | None = None
    for chunk in chunks:
        # budget = chunk + slots yields exactly ``chunk`` prefill tokens
        # per unified step; 0 keeps the identity-preserving default
        # (ragged_chunk == prefill_chunk).
        budget = 0 if chunk >= PagedModelRunner.prefill_chunk else \
            chunk + slots
        runner = PagedModelRunner(cfg, max_slots=slots, max_seq=ctx,
                                  page_size=page, step_token_budget=budget)
        state = runner.init_state()
        key = jax.random.PRNGKey(0)
        for slot in range(slots - 1):
            p = rng.integers(1, cfg.vocab_size, size=24).tolist()
            key, sub = jax.random.split(key)
            first, ks, vs, plen = runner.prefill(p, 0.7, 0.95, sub,
                                                 state=state)
            state = runner.insert(state, slot, ks, vs, plen, first,
                                  0.7, 0.95)
        _, state = runner.decode_steps(state, 1)  # decode compile
        base, state = timed_decode_steps(runner, state, base_n)

        unified: list[float] = []
        totals: list[float] = []
        g_busy = g_gap = 0.0
        g_disp = 0
        for rnd in range(rounds):  # round 0 is the compile warmup
            p = rng.integers(1, cfg.vocab_size, size=long_len).tolist()
            job = runner.ragged_begin(p, long_slot, state)
            t_r = time.monotonic()
            prev_end = t_r
            while not job.finished:
                t0 = time.monotonic()
                toks, state = runner.ragged_step(state, job, 1)
                np.asarray(toks)
                t1 = time.monotonic()
                if rnd:
                    unified.append(t1 - t0)
                    g_busy += t1 - t0
                    g_gap += t0 - prev_end
                    g_disp += 1
                prev_end = t1
            if rnd:
                totals.append(time.monotonic() - t_r)
            key, sub = jax.random.split(key)
            _, state = runner.ragged_finish(state, job, 0.7, 0.95, sub)
            state = runner.release(state, long_slot)

        # FUSED arm: ragged_megastep(state, job, K) — K unified steps
        # per host dispatch, ONE device_get of the packed [K, B] block +
        # done-flags per flight.  host_gap_share = time the device sat
        # idle between dispatches / total; decode_tokens_per_dispatch is
        # what the crowdllama_engine_tokens_per_dispatch gauge shows
        # during a fused admission (K × live decode slots).
        fused_k = 4
        fsteps: list[float] = []
        ftotals: list[float] = []
        f_busy = f_gap = 0.0
        f_disp = 0
        for rnd in range(rounds):  # round 0 compiles the fused program
            p = rng.integers(1, cfg.vocab_size, size=long_len).tolist()
            job = runner.ragged_begin(p, long_slot, state)
            t_r = time.monotonic()
            prev_end = t_r
            while not job.finished:
                t0 = time.monotonic()
                tokens, done, state = runner.ragged_megastep(
                    state, job, fused_k)
                jax.device_get((tokens, done))
                t1 = time.monotonic()
                if rnd:
                    fsteps.append((t1 - t0) / fused_k)
                    f_busy += t1 - t0
                    f_gap += t0 - prev_end
                    f_disp += 1
                prev_end = t1
            if rnd:
                ftotals.append(time.monotonic() - t_r)
            key, sub = jax.random.split(key)
            _, state = runner.ragged_finish(state, job, 0.7, 0.95, sub)
            state = runner.release(state, long_slot)

        base_p95 = float(np.percentile(np.asarray(base), 95))
        entry = {
            "ragged_chunk": runner.ragged_chunk,
            "step_token_budget": runner.step_token_budget,
            "decode_only": _latency_stats(base),
            "unified_step": _latency_stats(unified),
            "p95_vs_decode_only": round(
                float(np.percentile(np.asarray(unified), 95))
                / base_p95, 3),
            "long_prefill_complete_s": round(float(np.mean(totals)), 3),
            "decode_tokens_per_dispatch": slots - 1,
            "host_gap_share": round(g_gap / max(g_gap + g_busy, 1e-9), 4),
            "fused": {
                "megastep_k": fused_k,
                "unified_step": _latency_stats(fsteps),
                "p95_vs_decode_only": round(
                    float(np.percentile(np.asarray(fsteps), 95))
                    / base_p95, 3),
                "long_prefill_complete_s": round(
                    float(np.mean(ftotals)), 3),
                "decode_tokens_per_dispatch": fused_k * (slots - 1),
                "host_dispatches_vs_gated": round(
                    g_disp / max(f_disp, 1), 2),
                "host_gap_share": round(
                    f_gap / max(f_gap + f_busy, 1e-9), 4),
            },
        }
        sweep[f"chunk{runner.ragged_chunk}"] = entry
        headline = entry  # tightest budget last in the sweep

        if legacy is None:
            # WITHOUT unification: the legacy interleave — one
            # prefill-chunk dispatch, then one decode dispatch — priced
            # per decode token produced during the long prefill.
            lts: list[float] = []
            for rnd in range(3):
                p = rng.integers(1, cfg.vocab_size, size=long_len).tolist()
                job = runner.prefill_begin(p, state)
                done = False
                while not done:
                    t0 = time.monotonic()
                    done = runner.prefill_step(job)
                    toks, state = runner.decode_steps_device(state, 1)
                    np.asarray(toks)
                    if rnd:
                        lts.append(time.monotonic() - t0)
                key, sub = jax.random.split(key)
                first, ks, vs, plen = runner.prefill_finish(job, 0.7, 0.95,
                                                            sub)
                state = runner.insert(state, long_slot, ks, vs, plen,
                                      first, 0.7, 0.95, prompt_tokens=p)
                state = runner.release(state, long_slot)
            legacy = {"prefill_chunk": runner.prefill_chunk,
                      "decode_step_during_prefill": _latency_stats(lts)}

    return {
        "metric": f"{model} mixed-batch decode-step p95 "
                  f"(fused ragged megastep vs decode-only)",
        "value": headline["fused"]["p95_vs_decode_only"],
        "unit": "x decode-only p95",
        "vs_baseline": None,
        "extra": {
            "platform": platform, "slots": slots, "ctx": ctx,
            "long_prompt_tokens": long_len, "page_size": page,
            "gated_p95_vs_decode_only": headline["p95_vs_decode_only"],
            "budget_sweep": sweep,
            "without_unification": legacy,
            "reading": "1.0 = a decode stream cannot tell a long prefill "
                       "is sharing its batch; the fused arm folds K "
                       "unified steps into one dispatch (one readback "
                       "per flight), the gated arm is the per-dispatch "
                       "control, without_unification is the retired "
                       "alternating loop, where every decode token "
                       "during the prefill waits a full chunk",
        },
    }


def _decode_megastep_phase() -> dict:
    """Kernel-looped decode megastep (docs/MEGASTEP.md): K full decode
    steps per host dispatch with on-device sampling + done-flags.

    Control = the per-step loop: ONE decode_steps_device(1) dispatch and
    one host readback per token row — the dispatch economy the megastep
    retires.  The sweep dispatches decode_megastep(state, K) for
    K ∈ {1,2,4,8}, reading the packed [K, B] token block + done-flags
    back ONCE per flight with jax.device_get.  Headline = decode
    steps/sec at K=4 over the control; each sweep entry also records
    host dispatches per token, the quantity K exists to shrink (the
    ISSUE acceptance wants it reduced ≥ K/2 at K=4 on the CPU ref
    path).  Byte-identity of the streams is the test suite's job
    (tests/test_megastep.py); this phase prices the win."""
    import jax
    import numpy as np

    from crowdllama_tpu.engine.paged import PagedModelRunner
    from crowdllama_tpu.models.config import get_config

    platform = jax.devices()[0].platform
    if platform != "tpu":
        model, slots, ctx, page, steps = "tiny-test", 4, 512, 32, 96
    else:
        model = os.environ.get("CROWDLLAMA_BENCH_MODEL", "tinyllama-1.1b")
        slots = int(os.environ.get("CROWDLLAMA_BENCH_SLOTS", "8"))
        ctx, page, steps = 1024, 128, 256
    cfg = get_config(model)
    cfg = replace(cfg, max_context_length=ctx)

    def fresh():
        rng = np.random.default_rng(0)
        runner = PagedModelRunner(cfg, max_slots=slots, max_seq=ctx,
                                  page_size=page)
        state = runner.init_state()
        key = jax.random.PRNGKey(0)
        for slot in range(slots):
            p = rng.integers(1, cfg.vocab_size, size=24).tolist()
            key, sub = jax.random.split(key)
            first, ks, vs, plen = runner.prefill(p, 0.0, 1.0, sub,
                                                 state=state)
            state = runner.insert(state, slot, ks, vs, plen, first,
                                  0.0, 1.0)
        return runner, state

    # Per-step control: dispatch + sync per token row.
    runner, state = fresh()
    _, state = runner.decode_steps(state, 1)  # decode compile
    t0 = time.monotonic()
    for _ in range(steps):
        toks, state = runner.decode_steps_device(state, 1)
        np.asarray(toks)
    ctrl_dt = time.monotonic() - t0
    ctrl_sps = steps / ctrl_dt
    control = {
        "steps_per_s": round(ctrl_sps, 2),
        "host_dispatches": steps,
        "host_dispatches_per_token": round(1.0 / slots, 5),
    }

    sweep: dict[str, object] = {}
    headline = None
    for k in (1, 2, 4, 8):
        runner, state = fresh()
        _, _, state = runner.decode_megastep(state, k)  # megastep compile
        flights = max(1, steps // k)
        t0 = time.monotonic()
        for _ in range(flights):
            tokens, done, state = runner.decode_megastep(state, k)
            jax.device_get((tokens, done))  # ONE readback per flight
        dt = time.monotonic() - t0
        n_steps = flights * k
        sps = n_steps / dt
        entry = {
            "steps_per_s": round(sps, 2),
            "steps_per_s_vs_per_step": round(sps / ctrl_sps, 3),
            "host_dispatches": flights,
            "host_dispatches_per_token": round(
                flights / (n_steps * slots), 5),
            "dispatch_reduction_x": round(n_steps / flights, 2),
        }
        sweep[f"k{k}"] = entry
        if k == 4:
            headline = entry

    return {
        "metric": f"{model} decode megastep steps/sec (K=4 vs per-step)",
        "value": headline["steps_per_s_vs_per_step"],
        "unit": "x per-step decode throughput",
        "vs_baseline": None,
        "extra": {
            "platform": platform, "slots": slots, "ctx": ctx,
            "page_size": page, "timed_steps": steps,
            "per_step_control": control,
            "k_sweep": sweep,
            "reading": "dispatch_reduction_x is host dispatches per "
                       "token, control over megastep — K by "
                       "construction; steps_per_s_vs_per_step is the "
                       "wall-clock win from retiring K-1 host "
                       "round-trips per K tokens",
        },
    }


def _obs_overhead_phase() -> dict:
    """Prices the swarm observatory on the decode hot path (PR 13).

    Control = the bare per-step decode loop.  Observed = the identical
    loop carrying the observatory's full per-flight cost — the
    duty-cycle accounting the scheduler now does at every retire (extra
    monotonic reads, the host-gap histogram observe, the EWMA update) —
    while a background thread renders the whole scrape surface (engine
    gauges + telemetry + SLO burn gauges + a 2-worker cluster merge) at
    20 Hz, ~300x a real Prometheus 15 s interval.  The acceptance bar is
    <2% decode-throughput cost; both loops run twice interleaved and the
    best of each is compared, so a one-off GC pause cannot fake a
    regression."""
    import threading

    import jax
    import numpy as np

    from crowdllama_tpu.engine.paged import PagedModelRunner
    from crowdllama_tpu.models.config import get_config
    from crowdllama_tpu.obs.metrics import (
        ENGINE_TELEMETRY,
        engine_gauge_lines,
    )
    from crowdllama_tpu.obs.slo import SloEngine

    platform = jax.devices()[0].platform
    if platform != "tpu":
        model, slots, ctx, page, steps = "tiny-test", 4, 512, 32, 96
    else:
        model = os.environ.get("CROWDLLAMA_BENCH_MODEL", "tinyllama-1.1b")
        slots = int(os.environ.get("CROWDLLAMA_BENCH_SLOTS", "8"))
        ctx, page, steps = 1024, 128, 192
    cfg = get_config(model)
    cfg = replace(cfg, max_context_length=ctx)

    rng = np.random.default_rng(0)
    runner = PagedModelRunner(cfg, max_slots=slots, max_seq=ctx,
                              page_size=page)
    state = runner.init_state()
    key = jax.random.PRNGKey(0)
    for slot in range(slots):
        p = rng.integers(1, cfg.vocab_size, size=24).tolist()
        key, sub = jax.random.split(key)
        first, ks, vs, plen = runner.prefill(p, 0.0, 1.0, sub, state=state)
        state = runner.insert(state, slot, ks, vs, plen, first, 0.0, 1.0)
    _, state = runner.decode_steps(state, 1)  # compile outside the timers

    def bare(state):
        t0 = time.monotonic()
        for _ in range(steps):
            toks, state = runner.decode_steps_device(state, 1)
            np.asarray(toks)
        return time.monotonic() - t0, state

    def observed(state):
        # The scheduler's per-flight duty-cycle accounting, verbatim.
        duty: dict[str, float] = {}
        last_retire = 0.0
        t0 = time.monotonic()
        for _ in range(steps):
            dispatched_at = time.monotonic()
            toks, state = runner.decode_steps_device(state, 1)
            np.asarray(toks)
            now = time.monotonic()
            gap = (max(0.0, dispatched_at - last_retire)
                   if last_retire else 0.0)
            dt = max(now - dispatched_at, 1e-6)
            ENGINE_TELEMETRY.host_gap_seconds.labels("plain").observe(gap)
            d = dt / max(dt + gap, 1e-9)
            prev = duty.get("plain")
            duty["plain"] = d if prev is None else 0.9 * prev + 0.1 * d
            last_retire = now
        return time.monotonic() - t0, state

    slo = SloEngine(ttft_ms=500.0, decode_ms=200.0)
    for _ in range(64):
        slo.observe_ttft(0.1)
        slo.observe_decode(0.05)
    gauges = {"pending_depth": 3.0, "active_slots": float(slots),
              "batch_occupancy": 0.8, "kv_cache_utilization": 0.4,
              "duty_cycle|dispatch=plain": 0.9}
    stop = threading.Event()
    scrapes = [0]

    def scrape_loop():
        from crowdllama_tpu.obs.cluster import merge_snapshots

        while not stop.is_set():
            text = "\n".join(engine_gauge_lines(dict(gauges))
                             + ENGINE_TELEMETRY.expose() + slo.expose())
            merge_snapshots([("w1", "n1", text), ("w2", "n2", text)])
            scrapes[0] += 1
            stop.wait(0.05)  # 20 Hz

    # Interleave A/B/A/B; best-of-2 per arm absorbs one-off stalls.
    bare_dts, obs_dts = [], []
    for _ in range(2):
        dt, state = bare(state)
        bare_dts.append(dt)
        t = threading.Thread(target=scrape_loop, daemon=True)
        stop.clear()
        t.start()
        try:
            dt, state = observed(state)
        finally:
            stop.set()
            t.join(timeout=2.0)
        obs_dts.append(dt)

    bare_sps = steps / min(bare_dts)
    obs_sps = steps / min(obs_dts)
    overhead_pct = max(0.0, (bare_sps - obs_sps) / bare_sps * 100.0)
    return {
        "metric": f"{model} observatory decode overhead",
        "value": round(overhead_pct, 2),
        "unit": "% decode throughput lost under scrape load",
        "vs_baseline": None,
        "extra": {
            "platform": platform, "slots": slots, "timed_steps": steps,
            "bare_steps_per_s": round(bare_sps, 2),
            "observed_steps_per_s": round(obs_sps, 2),
            "scrape_renders": scrapes[0],
            "scrape_hz": 20,
            "reading": "per-flight duty-cycle accounting + a 20 Hz "
                       "full-surface scrape thread vs the bare decode "
                       "loop; acceptance bar is < 2%",
        },
    }


def _ctx32k_phase() -> dict:
    """A 32k-token prefill COMPLETED through the unified ragged path.

    The monolithic path cannot take this prompt in one step: one-shot
    prefill pads to a 32768-wide bucket, and the reference attention
    path materializes an [H, 32768, 32768] fp32 score matrix — more
    bytes than the serving chip's 16 GiB HBM for every registry model.
    Ragged chunking bounds live scores to [H, chunk, ctx] and streams
    the prompt into the paged pool in page-multiple chunks, so the
    context a worker can serve is set by its KV pool, not by the widest
    prefill program it can compile."""
    import jax
    import numpy as np

    from crowdllama_tpu.engine.paged import PagedModelRunner
    from crowdllama_tpu.models.config import get_config

    platform = jax.devices()[0].platform
    model = ("tiny-test" if platform != "tpu"
             else os.environ.get("CROWDLLAMA_BENCH_MODEL", "tinyllama-1.1b"))
    target = int(os.environ.get("CROWDLLAMA_BENCH_CTX32K", "32768"))
    cfg = replace(get_config(model), max_context_length=target + 256)
    runner = PagedModelRunner(cfg, max_slots=1, max_seq=target + 256,
                              page_size=128, pool_tokens=target + 512)
    state = runner.init_state()
    rng = np.random.default_rng(0)
    prompt = rng.integers(1, cfg.vocab_size, size=target).tolist()

    job = runner.ragged_begin(prompt, 0, state)
    t0 = time.monotonic()
    toks, state = runner.ragged_step(state, job, 1)
    np.asarray(toks)
    compile_s = time.monotonic() - t0
    t0 = time.monotonic()
    dispatches = 1
    while not job.finished:
        toks, state = runner.ragged_step(state, job, 1)
        dispatches += 1
    np.asarray(toks)  # sync the chained dispatches
    steady_s = time.monotonic() - t0
    first, state = runner.ragged_finish(state, job, 0.7, 0.95,
                                        jax.random.PRNGKey(1))
    decode_toks, state = runner.decode_steps(state, 4)  # slot is LIVE
    assert job.finished and decode_toks.shape[0] == 4
    assert int(np.asarray(state.seq_lens)[0]) == target + 4

    # What the one-shot program would have needed: ref-path prefill
    # scores for the padded bucket, fp32.
    bucket = runner.bucket_for(target)
    mono_scores = cfg.num_heads * bucket * bucket * 4
    chunk_scores = (cfg.num_heads * runner.ragged_chunk
                    * runner.max_pages_per_slot * runner.page_size * 4)
    hbm = 16 * 2 ** 30  # the attached v5e
    tok_s = (target - runner.ragged_chunk) / steady_s
    return {
        "metric": f"{model} 32k-context ragged chunked prefill",
        "value": round(tok_s, 1),
        "unit": "prefill tokens/sec",
        "vs_baseline": None,
        "extra": {
            "platform": platform, "prompt_tokens": target,
            "ragged_chunk": runner.ragged_chunk,
            "dispatches": dispatches,
            "compile_s": round(compile_s, 2),
            "steady_s": round(steady_s, 2),
            "completed": True, "first_token": int(first),
            "decode_after_prefill_ok": True,
            "monolithic_one_step": {
                "bucket": bucket,
                "ref_scores_bytes": int(mono_scores),
                "chip_hbm_bytes": hbm,
                "fits": mono_scores < hbm,
            },
            "ragged_step_scores_bytes": int(chunk_scores),
        },
    }


# ----------------------------------------------------------------- kernel


def _kernel_parity_phase() -> dict:
    """Flash Pallas kernels vs the jnp reference ops, on this device.

    tests/test_pallas.py only ever runs the kernels in CPU interpret mode
    (VERDICT r2 weak #5); this phase compiles them with Mosaic on the real
    chip and asserts numeric agreement, so every BENCH artifact proves the
    kernels still run on TPU.  On the CPU fallback it runs interpret mode
    (labeled) so the line exists either way.
    """
    import jax
    import jax.numpy as jnp
    import numpy as np

    from crowdllama_tpu.ops import attention as A
    from crowdllama_tpu.ops.pallas import flash

    platform = jax.devices()[0].platform
    mode = "mosaic" if platform == "tpu" else "interpret"

    key = jax.random.PRNGKey(7)
    b, t, h, hkv, dh = 2, 512, 8, 4, 128
    scale = dh ** -0.5
    ks = jax.random.split(key, 8)
    q = jax.random.normal(ks[0], (b, t, h, dh), jnp.bfloat16)
    k = jax.random.normal(ks[1], (b, hkv, t, dh), jnp.bfloat16)
    v = jax.random.normal(ks[2], (b, hkv, t, dh), jnp.bfloat16)
    positions = jnp.broadcast_to(jnp.arange(t)[None, :], (b, t))

    checks: dict[str, float] = {}

    def err(a, b_):
        return float(jnp.max(jnp.abs(a.astype(jnp.float32)
                                     - b_.astype(jnp.float32))))

    # Interpret-mode fallback must not leak into os.environ: the ttft
    # subprocess inherits the environment, and interpret-mode Pallas in a
    # latency benchmark would be absurd.
    prev = os.environ.get("CROWDLLAMA_PALLAS_INTERPRET")
    if mode == "interpret":
        os.environ["CROWDLLAMA_PALLAS_INTERPRET"] = "1"
    try:
        got = flash.flash_prefill_attention(q, k, v, positions, scale)
        want = A.prefill_attention_ref(q, k, v, positions, scale)
        checks["prefill"] = err(got, want)

        # Sliding window + softcap (the Gemma-2 shape).
        got = flash.flash_prefill_attention(q, k, v, positions, scale,
                                            softcap=50.0, sliding_window=128)
        want = A.prefill_attention_ref(q, k, v, positions, scale,
                                       softcap=50.0, sliding_window=128)
        checks["prefill_window_softcap"] = err(got, want)

        qd = jax.random.normal(ks[3], (b, h, dh), jnp.bfloat16)
        kc = jax.random.normal(ks[4], (b, hkv, t, dh), jnp.bfloat16)
        vc = jax.random.normal(ks[5], (b, hkv, t, dh), jnp.bfloat16)
        seq_lens = jnp.asarray(np.array([t, t // 2]), jnp.int32)
        got = flash.flash_decode_attention(qd, kc, vc, seq_lens, scale)
        want = A.decode_attention_ref(qd, kc, vc, seq_lens, scale)
        checks["decode"] = err(got, want)

        # Fused paged-decode kernel vs the gather reference (bf16 + int8).
        from crowdllama_tpu.ops.pallas.paged import (
            flash_paged_decode_attention,
        )
        from crowdllama_tpu.ops.quant import quantize_kv

        page, np_, pool_pages = 128, t // 128, 2 * (t // 128) + 1
        rng = np.random.default_rng(3)
        pool_k = jax.random.normal(ks[6], (pool_pages, hkv, page, dh),
                                   jnp.bfloat16)
        pool_v = jax.random.normal(ks[7], (pool_pages, hkv, page, dh),
                                   jnp.bfloat16)
        table = jnp.asarray(
            rng.permutation(pool_pages)[: b * np_].reshape(b, np_), jnp.int32)
        kg = pool_k[table].transpose(0, 2, 1, 3, 4).reshape(b, hkv, t, dh)
        vg = pool_v[table].transpose(0, 2, 1, 3, 4).reshape(b, hkv, t, dh)
        got = flash_paged_decode_attention(qd, pool_k, pool_v, table,
                                           seq_lens, scale)
        want = A.decode_attention_ref(qd, kg, vg, seq_lens, scale)
        checks["paged_decode"] = err(got, want)

        k_i8, k_sc = quantize_kv(pool_k)
        v_i8, v_sc = quantize_kv(pool_v)
        got = flash_paged_decode_attention(qd, k_i8, v_i8, table, seq_lens,
                                           scale, k_scale=k_sc, v_scale=v_sc)
        ksg = k_sc[table].transpose(0, 2, 1, 3).reshape(b, hkv, t)
        vsg = v_sc[table].transpose(0, 2, 1, 3).reshape(b, hkv, t)
        want = A.decode_attention_q(qd, k_i8[table].transpose(0, 2, 1, 3, 4)
                                    .reshape(b, hkv, t, dh), ksg,
                                    v_i8[table].transpose(0, 2, 1, 3, 4)
                                    .reshape(b, hkv, t, dh), vsg,
                                    seq_lens, scale)
        checks["paged_decode_int8"] = err(got, want)
    finally:
        if mode == "interpret":
            if prev is None:
                os.environ.pop("CROWDLLAMA_PALLAS_INTERPRET", None)
            else:
                os.environ["CROWDLLAMA_PALLAS_INTERPRET"] = prev

    tol = 2e-2  # bf16 inputs, fp32 accumulation in both paths
    ok = all(e <= tol for e in checks.values())
    return {
        "metric": "pallas kernel parity (flash + paged decode vs jnp)",
        "value": 1.0 if ok else 0.0,
        "unit": "pass",
        "vs_baseline": None,
        "extra": {"mode": mode, "platform": platform, "tolerance": tol,
                  "max_abs_err": {k_: round(v_, 5)
                                  for k_, v_ in checks.items()}},
    }


# ------------------------------------------------------------ subprocesses


def _subprocess_phase(script: str, extra_env: dict[str, str]) -> dict:
    """Run a benchmarks/ script and parse its final JSON stdout line."""
    timeout = float(os.environ.get("CROWDLLAMA_BENCH_SUBPROC_TIMEOUT", "900"))
    env = dict(os.environ)
    env.setdefault("CROWDLLAMA_TPU_TEST_MODE", "1")
    env.update(extra_env)
    path = Path(__file__).resolve().parent / "benchmarks" / script
    proc = subprocess.run(
        [sys.executable, str(path)], env=env, timeout=timeout,
        capture_output=True, text=True)
    if proc.stderr:
        sys.stderr.write(proc.stderr[-2000:])
    for line in reversed(proc.stdout.splitlines()):
        line = line.strip()
        if line.startswith("{"):
            return json.loads(line)
    raise RuntimeError(
        f"{script} rc={proc.returncode}, no JSON line in stdout "
        f"(tail: {proc.stdout[-300:]!r})")


def _ttft_phase() -> dict:
    import jax

    env = {}
    if jax.devices()[0].platform != "tpu":
        env["JAX_PLATFORMS"] = "cpu"  # don't re-wait on the dead tunnel
    return _subprocess_phase("ttft.py", env)


def _swarm_phase() -> dict:
    # Control-plane metric: FakeEngine workers, CPU platform by design.
    return _subprocess_phase("swarm_scaling.py", {"JAX_PLATFORMS": "cpu"})


def _ep_dispatch_phase() -> dict:
    # Control-plane metric (the per-MoE-layer DCN hop price): CPU by
    # design, like swarm.
    return _subprocess_phase("ep_dispatch.py", {"JAX_PLATFORMS": "cpu"})


def _kv_transfer_phase() -> dict:
    # Control-plane-vs-compute crossover (fetch TTFT against recompute):
    # CPU by design, like swarm/ep_dispatch.
    return _subprocess_phase("kv_transfer.py", {"JAX_PLATFORMS": "cpu"})


def _mini_swarm_phase() -> dict:
    # Real tiny engines behind the gateway (VERDICT #5): CPU by design —
    # the point is e2e serving behaviour, not chip throughput.
    return _subprocess_phase("mini_swarm.py", {"JAX_PLATFORMS": "cpu"})


def _spec_rtt_phase() -> dict:
    # Gateway-drafted speculative pipeline across injected RTT (ISSUE 20):
    # a control-plane ratio like ep_dispatch/kv_transfer, CPU by design.
    return _subprocess_phase("spec_rtt.py", {"JAX_PLATFORMS": "cpu"})


def _autopilot_phase() -> dict:
    # Closed-loop autopilot vs offline grid search (docs/AUTOTUNE.md):
    # a control-plane ratio like swarm/mini_swarm, CPU by design.
    return _subprocess_phase("autopilot.py", {"JAX_PLATFORMS": "cpu"})


def _multi_gateway_phase() -> dict:
    # Replicated gateway plane (ISSUE 7): req/s scaling across in-process
    # replicas, cross-replica affinity hit-rate via gossip, and tenant
    # isolation under a hot-tenant flood.  Control plane — CPU by design.
    return _subprocess_phase("multi_gateway.py", {"JAX_PLATFORMS": "cpu"})


def _capacity_phase() -> dict:
    # Static HBM accounting per registry model (BASELINE config 2/3
    # feasibility); reads the attached chip's HBM, assumes one v5e on
    # the CPU fallback.
    return _subprocess_phase("capacity.py", {})


# ------------------------------------------------------------------- main


def _skip_metric(phase: str) -> str:
    """Skip markers must carry the SAME metric name a real run of the
    phase emits, so artifact consumers can correlate the series across
    runs (decode_kv8's name includes the configured model)."""
    kv8_model = os.environ.get("CROWDLLAMA_BENCH_MODEL", "tinyllama-1.1b")
    return {
        "decode8b": "llama-3-8b decode throughput",
        "decode8b_paged":
            "llama-3-8b (paged KV + fused kernel) decode throughput",
        "decode8b_int4": "llama-3-8b (int4 weights) decode throughput",
        "decode8b_ctx4k": "llama-3-8b (ctx 4096) decode throughput",
        "decode_kv8": f"{kv8_model} (int8 KV) decode throughput",
    }.get(phase, phase)


def _skip_line(phase: str, plat: "_Platform", reason: str,
               deferred: bool = False) -> dict:
    """A provenance-bearing skip marker for ``phase`` (same metric name a
    real run emits, probe evidence, pointer to the newest on-chip
    artifact).  Emitted at DEFER time too, so the artifact carries a line
    for every phase from the moment the suite knows it may not run — a
    later real execution of the phase simply supersedes it (consumers
    take the last line per metric)."""
    return {"metric": _skip_metric(phase), "value": None,
            "unit": ("tokens/sec/chip" if phase in _TPU_ONLY_PHASES
                     else None),
            "vs_baseline": None, "skipped": True,
            "extra": {
                "platform": "cpu" if plat.on_cpu_fallback
                            or not plat.want_tpu else "tpu",
                "reason": reason,
                "deferred": deferred,
                "tunnel_probe_attempts": plat.probe_attempts,
                "failed_probes_tail": plat.probe_log[-5:],
                # The newest builder-session on-chip artifact: the
                # explicit provenance chain to the last real numbers.
                "last_session_artifact": _latest_session_artifact(),
            }}


def main() -> None:
    budget = float(os.environ.get("CROWDLLAMA_BENCH_BUDGET_S", "1500"))
    # Overall wall-clock deadline: the suite must produce its full
    # scoreboard (values or skip markers) and exit rc 0 inside it
    # (BENCH_r02/r04/r05 burned 25-60 min in device waits; VERDICT r5 #1).
    deadline = time.monotonic() + float(
        os.environ.get("BENCH_DEADLINE_S", "1200"))
    phases = [p.strip() for p in os.environ.get(
        "CROWDLLAMA_BENCH_PHASES", ",".join(_ALL_PHASES)).split(",")
        if p.strip()]
    try:
        PARTIAL_PATH.unlink(missing_ok=True)  # fresh artifact per run
    except OSError:
        pass

    plat = _Platform()
    # Spend at most 2 min of the budget waiting up front; the rest backs
    # the per-phase re-probes (the CPU-runnable phases keep the run
    # productive while the tunnel gets the whole run's duration to heal).
    plat.startup_wait(min(budget, 120.0))
    probe_deadline = time.monotonic() + budget

    runners = {
        "decode": lambda: _decode_phase(
            os.environ.get("CROWDLLAMA_BENCH_MODEL", "tinyllama-1.1b")),
        "decode_paged": lambda: _decode_phase(
            os.environ.get("CROWDLLAMA_BENCH_MODEL", "tinyllama-1.1b"),
            layout="paged"),
        # 8B decode is weight-bandwidth-bound: 16 slots amortize the same
        # ~8.5 GB weight stream over 2x the tokens (KV at bs16/ctx1024
        # adds ~2.1 GB — still well inside a 16 GiB chip).
        "decode8b": lambda: _decode_phase(
            "llama-3-8b",
            slots=int(os.environ.get("CROWDLLAMA_BENCH_SLOTS_8B")
                      or os.environ.get("CROWDLLAMA_BENCH_SLOTS") or 16)),
        # The production-default serving path, swept over batch slots.
        "decode8b_paged": _decode8b_paged_phase,
        # The quantized variants the scoreboard tracks separately: int8 KV
        # (halves the cache read) and int4 weights (Ollama's own 8B
        # default is 4-bit GGUF, so int4-vs-Q4 is the parity-honest cell).
        "decode_kv8": lambda: _decode_phase(
            os.environ.get("CROWDLLAMA_BENCH_MODEL", "tinyllama-1.1b"),
            kv="int8"),
        "decode8b_int4": lambda: _decode_phase(
            "llama-3-8b", quantize="int4",
            slots=int(os.environ.get("CROWDLLAMA_BENCH_SLOTS_8B")
                      or os.environ.get("CROWDLLAMA_BENCH_SLOTS") or 16)),
        # Long-context evidence: 4k context quadruples the per-step KV
        # read (2.6 GB/step at bs=8) on top of the 8.5 GB weight stream.
        "decode8b_ctx4k": lambda: _decode_phase(
            "llama-3-8b", slots=8, ctx_override=4096),
        "decode_spec": _spec_phase,
        "decode_spec_draft": _spec_draft_phase,
        "kernel": _kernel_parity_phase,
        "ttft": _ttft_phase,
        "swarm": _swarm_phase,
        "ep_dispatch": _ep_dispatch_phase,
        "kv_transfer": _kv_transfer_phase,
        "mini_swarm": _mini_swarm_phase,
        "multi_gateway": _multi_gateway_phase,
        "capacity": _capacity_phase,
        "mixed_batch": _mixed_batch_phase,
        "ctx32k": _ctx32k_phase,
        "decode_megastep": _decode_megastep_phase,
        "obs_overhead": _obs_overhead_phase,
        "autopilot": _autopilot_phase,
        "spec_rtt": _spec_rtt_phase,
    }

    remaining = [p for p in phases if p in runners]
    for p in phases:
        if p not in runners:
            print(f"# unknown phase {p!r} (skipped)", file=sys.stderr)
    ran_on_cpu: list[str] = []  # re-run candidates if the tunnel returns
    deferred: set[str] = set()
    ok = 0
    while remaining:
        phase = remaining.pop(0)
        if time.monotonic() >= deadline:
            # Wall-clock deadline: the artifact still gets a line for this
            # phase and every other remaining one, and the run exits rc 0
            # — a bench that silently times out is indistinguishable from
            # one that never ran (VERDICT r5 next-round #1).
            for p in [phase] + remaining:
                _emit(_skip_line(p, plat, "BENCH_DEADLINE_S exceeded",
                                 deferred=p in deferred))
            print(f"# deadline hit: skipped {1 + len(remaining)} phases "
                  f"({[phase] + remaining})", file=sys.stderr)
            # A deadline cut with a marker per phase is a COMPLETE
            # artifact: rc 0.
            ok = ok or 1
            remaining = []
            break
        # Phase-boundary re-probe: a mid-run tunnel-up window must not be
        # missed (VERDICT r4 #1).  Bounded to one subprocess attempt so a
        # dead tunnel costs ~45 s per boundary, within the probe budget.
        if (plat.want_tpu and plat.on_cpu_fallback
                and time.monotonic() < min(probe_deadline, deadline)
                and plat.reprobe(45.0)):
            # Window open: re-enqueue the phases whose CPU executions were
            # stand-ins, then order the whole window by BASELINE priority
            # (kernel parity first — it gates the fused-kernel phases).
            for p in ran_on_cpu:
                if p in _RERUN_ON_TPU and p not in remaining:
                    remaining.append(p)
            ran_on_cpu = []
            remaining.sort(key=lambda p: _TPU_WINDOW_PRIORITY.get(p, 50))
            print(f"# TPU window open: phase order now "
                  f"{[phase] + remaining}", file=sys.stderr)
        if phase in _TPU_ONLY_PHASES and (plat.on_cpu_fallback
                                          or not plat.want_tpu):
            if (plat.want_tpu and phase not in deferred
                    and any(p not in _TPU_ONLY_PHASES for p in remaining)
                    and time.monotonic() < min(probe_deadline, deadline)):
                # Push behind the CPU-runnable phases: every boundary in
                # between is another probe, so the tunnel gets the whole
                # run's duration to come back before we give up.  The skip
                # marker goes out NOW, not at final give-up: if the run is
                # cut short (crash, operator ^C, deadline) the artifact
                # already has this phase's line; a later real execution
                # simply supersedes it.
                deferred.add(phase)
                remaining.append(phase)
                _emit(_skip_line(
                    phase, plat,
                    "requires TPU; deferred behind CPU-runnable phases "
                    "(tunnel re-probed at each boundary)", deferred=True))
                print(f"# phase {phase} deferred (tunnel down; re-probing "
                      f"at each phase boundary)", file=sys.stderr)
                continue
            _emit(_skip_line(
                phase, plat,
                "requires TPU (real-size/quantized decode on CPU fallback "
                "is meaningless)", deferred=phase in deferred))
            continue
        t0 = time.monotonic()
        print(f"# phase {phase} starting (platform="
              f"{'tpu' if plat.want_tpu and not plat.on_cpu_fallback else 'cpu'})",
              file=sys.stderr)
        kernel_ok = True
        try:
            result = runners[phase]()
            _emit(result)
            ok += 1
            print(f"# phase {phase} done in {time.monotonic() - t0:.0f}s",
                  file=sys.stderr)
            kernel_ok = phase != "kernel" or result.get("value") == 1.0
            if plat.on_cpu_fallback:
                ran_on_cpu.append(phase)
        except Exception:
            print(f"# phase {phase} FAILED after "
                  f"{time.monotonic() - t0:.0f}s:", file=sys.stderr)
            traceback.print_exc()
            kernel_ok = phase != "kernel"
        if not kernel_ok:
            # Mosaic parity/compile failure on this chip: keep the rest of
            # the suite on the XLA paths (each later phase records the
            # degradation in its own extra.no_pallas field).
            os.environ["CROWDLLAMA_NO_PALLAS"] = "1"
            print("# kernel phase failed: later phases run with "
                  "CROWDLLAMA_NO_PALLAS=1", file=sys.stderr)
    sys.exit(0 if ok else 1)


if __name__ == "__main__":
    main()
