"""Headline benchmark: decode throughput (tokens/sec/chip) of the JAX engine.

Prints ONE JSON line: {"metric", "value", "unit", "vs_baseline"}.

The reference publishes no measured numbers (SURVEY §6); the only throughput
figure in its tree is the hardcoded 150 tokens/sec a worker *advertises*
(/root/reference/pkg/peer/peer.go:323-333).  ``vs_baseline`` is therefore
measured tokens/sec/chip divided by that advertised 150 tok/s.

Model defaults to TinyLlama-1.1B (BASELINE config 1, randomly initialized —
throughput does not depend on weight values).  Weights are int8 by default
(weight-only, ops/quant.py) — the parity-honest configuration: the
reference's engine (Ollama) serves quantized GGUF by default, and decode is
bandwidth-bound either way.  Overridables via env:
  CROWDLLAMA_BENCH_MODEL     (default tinyllama-1.1b)
  CROWDLLAMA_BENCH_SLOTS     batch slots        (default 8)
  CROWDLLAMA_BENCH_STEPS     timed decode steps (default 512)
  CROWDLLAMA_BENCH_CTX       max context        (default 1024)
  CROWDLLAMA_BENCH_QUANTIZE  "int8" | "int4" | "none"  (default int8)
  CROWDLLAMA_BENCH_KV        "bf16" | "int8"    KV cache dtype (default bf16)
"""

from __future__ import annotations

import json
import os
import sys
import time
from dataclasses import replace

import jax
import numpy as np

BASELINE_ADVERTISED_TOKS = 150.0  # reference worker's hardcoded claim


def _wait_for_devices(budget_s: float = 300.0):
    """The chip sits behind a network tunnel that occasionally drops and
    needs minutes to recover; retry backend init instead of failing the
    whole benchmark run on a transient."""
    deadline = time.monotonic() + budget_s
    delay = 5.0
    while True:
        try:
            return jax.devices()
        except RuntimeError as e:
            if time.monotonic() >= deadline:
                raise
            print(f"# devices unavailable ({e}); retrying in {delay:.0f}s",
                  file=sys.stderr)
            try:
                # Failed init is cached; reset it or the retry re-raises the
                # stale error.  (jax.clear_backends was removed from the
                # top-level API; jax.extend.backend carries it in jax 0.9.)
                import jax.extend.backend as _jeb

                _jeb.clear_backends()
            except Exception as ce:
                print(f"# clear_backends unavailable: {ce}", file=sys.stderr)
            time.sleep(delay)
            delay = min(delay * 2, 60.0)


def main() -> None:
    from crowdllama_tpu.engine.runner import ModelRunner
    from crowdllama_tpu.models.config import get_config

    _wait_for_devices()

    model = os.environ.get("CROWDLLAMA_BENCH_MODEL", "tinyllama-1.1b")
    slots = int(os.environ.get("CROWDLLAMA_BENCH_SLOTS", "8"))
    steps = int(os.environ.get("CROWDLLAMA_BENCH_STEPS", "512"))
    ctx = int(os.environ.get("CROWDLLAMA_BENCH_CTX", "1024"))
    quantize = os.environ.get("CROWDLLAMA_BENCH_QUANTIZE", "int8")
    kv_dtype = os.environ.get("CROWDLLAMA_BENCH_KV", "bf16")

    cfg = get_config(model)
    if ctx < cfg.max_context_length:
        cfg = replace(cfg, max_context_length=ctx)
    n_chips = max(1, len(jax.devices()))

    print(f"# bench: model={model} slots={slots} steps={steps} "
          f"ctx={cfg.max_context_length} devices={n_chips} "
          f"quantize={quantize} kv={kv_dtype} "
          f"platform={jax.devices()[0].platform}",
          file=sys.stderr)

    t0 = time.monotonic()
    params = None
    if quantize in ("int8", "int4"):
        from crowdllama_tpu.ops.quant import random_quantized_params

        # Leaf-by-leaf quantized init: never materializes the bf16 tree, so
        # an 8B model (16 GB bf16) can be benched on the 16 GB chip it
        # serves from.  Throughput-identical to quantize_params(init(...)).
        params = random_quantized_params(cfg, jax.random.PRNGKey(0),
                                         mode=quantize)
    runner = ModelRunner(cfg, params=params, max_slots=slots,
                         max_seq=cfg.max_context_length, kv_dtype=kv_dtype)
    state = runner.init_state()

    # Fill every slot with a short prompt so the decode batch is saturated.
    rng = np.random.default_rng(0)
    key = jax.random.PRNGKey(0)
    for slot in range(runner.max_slots):
        prompt = rng.integers(1, cfg.vocab_size, size=24).tolist()
        key, sub = jax.random.split(key)
        first, ks, vs, plen = runner.prefill(prompt, 0.7, 0.95, sub)
        state = runner.insert(state, slot, ks, vs, plen, first, 0.7, 0.95)
    print(f"# setup+prefill: {time.monotonic() - t0:.1f}s", file=sys.stderr)

    # Warmup compile of the timed decode program.
    chunk = min(32, steps)
    tokens, state = runner.decode_steps(state, chunk)  # warmup + compile (syncs)

    # Timed: chain chunks on device (each dispatch overlaps the previous
    # chunk's execution) and read back ONCE — the serial state dependency
    # means the final readback observes every chunk finished.  Per-chunk
    # readbacks would add a host round trip (~70 ms over the tunnel) per
    # chunk to what is a pure device-throughput metric.
    t0 = time.monotonic()
    done = 0
    while chunk > 0 and done + chunk <= steps:  # equal chunks: one program
        tokens, state = runner.decode_steps_device(state, chunk)
        done += chunk
    tokens = np.asarray(tokens)  # sync
    dt = time.monotonic() - t0

    toks_per_sec = done * runner.max_slots / dt
    per_chip = toks_per_sec / n_chips
    result = {
        "metric": f"{model} decode throughput",
        "value": round(per_chip, 2),
        "unit": "tokens/sec/chip",
        "vs_baseline": round(per_chip / BASELINE_ADVERTISED_TOKS, 3),
    }
    print(json.dumps(result))


if __name__ == "__main__":
    main()
