"""Swarm churn: workers die and join; discovery must converge and dead
providers must be evicted promptly (VERDICT round-1 missing #6).

The reference bootstrap server evicts on raw TCP disconnect
(/root/reference/pkg/dht/dht.go:370-383); the per-RPC transport here gets
the same effect from three eviction paths exercised below: the DHT
server's active liveness probe, RPC-failure eviction, and the health
machine's on_peer_removed hook into the local DHT view.
"""

import asyncio
import random

from crowdllama_tpu.utils.crypto_compat import Ed25519PrivateKey

from crowdllama_tpu.config import Configuration, Intervals
from crowdllama_tpu.core.protocol import namespace_key
from crowdllama_tpu.engine.engine import FakeEngine
from crowdllama_tpu.net.discovery import new_host_and_dht
from crowdllama_tpu.peer.peer import Peer


def _cfg(bootstrap):
    return Configuration(
        listen_host="127.0.0.1",
        bootstrap_peers=[bootstrap],
        model="churn-model",
        intervals=Intervals.default(),
    )


async def _wait_for(cond, timeout=45.0, interval=0.2, what="condition"):
    loop = asyncio.get_running_loop()
    deadline = loop.time() + timeout
    while loop.time() < deadline:
        if cond():
            return
        await asyncio.sleep(interval)
    raise AssertionError(f"timed out waiting for {what}")


async def _worker(bootstrap):
    w = Peer(Ed25519PrivateKey.generate(), _cfg(bootstrap),
             engine=FakeEngine(models=["churn-model"]), worker_mode=True)
    await w.start()
    return w


async def test_churn_converges_and_dead_providers_evicted():
    rng = random.Random(42)
    boot_host, boot_dht = await new_host_and_dht(
        Ed25519PrivateKey.generate(), listen_host="127.0.0.1")
    iv = Intervals.default()
    boot_dht.start_maintenance(provider_check=iv.dht_provider_check,
                               bucket_refresh=iv.dht_bucket_refresh)
    bootstrap = f"127.0.0.1:{boot_host.listen_port}"

    workers = [await _worker(bootstrap) for _ in range(3)]
    consumer = Peer(Ed25519PrivateKey.generate(), _cfg(bootstrap),
                    engine=FakeEngine(models=[]), worker_mode=False)
    await consumer.start()
    alive = list(workers)
    try:
        def healthy_ids():
            return {p.peer_id for p in consumer.peer_manager.get_healthy_peers()
                    if p.is_worker}

        await _wait_for(lambda: healthy_ids() >= {w.peer_id for w in alive},
                        what="initial discovery of 3 workers")

        # Churn rounds: kill a random worker, start a replacement.
        for round_no in range(2):
            victim = alive.pop(rng.randrange(len(alive)))
            victim_id = victim.peer_id
            await victim.stop()
            replacement = await _worker(bootstrap)
            alive.append(replacement)

            await _wait_for(
                lambda: replacement.peer_id in healthy_ids(),
                what=f"round {round_no}: replacement discovered")
            await _wait_for(
                lambda: victim_id not in healthy_ids(),
                what=f"round {round_no}: victim evicted from consumer")
            # Consumer's DHT view dropped the victim's provider records via
            # the health machine's on_peer_removed hook.
            await _wait_for(
                lambda: all(
                    c.peer_id != victim_id
                    for c in consumer.dht.providers.get(namespace_key())),
                what=f"round {round_no}: victim providers gone from consumer")
            # The bootstrap DHT server's liveness probe evicts the victim
            # well before the 30-minute record TTL.
            await _wait_for(
                lambda: all(
                    c.peer_id != victim_id
                    for c in boot_dht.providers.get(namespace_key())),
                what=f"round {round_no}: victim providers gone from server")

        # Steady state after churn: exactly the living workers are healthy
        # and routable.
        await _wait_for(
            lambda: healthy_ids() == {w.peer_id for w in alive},
            what="post-churn steady state")
        best = consumer.peer_manager.find_best_worker("churn-model")
        assert best is not None and best.peer_id in {w.peer_id for w in alive}
    finally:
        await consumer.stop()
        for w in alive:
            await w.stop()
        await boot_dht.stop_maintenance()
        await boot_host.close()


async def test_stop_publishes_departure_before_stream_teardown():
    """Ordered shutdown (docs/ROBUSTNESS.md): Peer.stop() must publish the
    draining departure record BEFORE tearing down relay/host streams, so a
    peer that re-probes metadata during the teardown window sees
    draining=true and deroutes instead of racing dead streams."""
    boot_host, _ = await new_host_and_dht(
        Ed25519PrivateKey.generate(), listen_host="127.0.0.1")
    bootstrap = f"127.0.0.1:{boot_host.listen_port}"
    try:
        w = await _worker(bootstrap)
        order = []
        real_provide = w.dht.provide
        real_close = w.host.close

        async def provide(*a, **kw):
            order.append("provide")
            return await real_provide(*a, **kw)

        async def close(*a, **kw):
            order.append("host_close")
            return await real_close(*a, **kw)

        w.dht.provide = provide
        w.host.close = close
        await w.stop()
        assert "provide" in order, "no departure publish during stop()"
        assert "host_close" in order
        assert order.index("provide") < order.index("host_close")
        # And the record it published said draining.
        assert w.resource.draining is True
    finally:
        await boot_host.close()
