"""Swarm-stitched traces + flight recorder (obs/collector.py): cross-node
trace assembly over a REAL relay-spliced loopback swarm, flight-recorder
capture on an injected mid-stream worker kill, and the XLA compile-counter
contract that a speculative draft_len retune claims exactly one new
program bucket."""

import asyncio
import json
from types import SimpleNamespace

import aiohttp
import pytest
from crowdllama_tpu.utils.crypto_compat import Ed25519PrivateKey

from crowdllama_tpu.config import Configuration, Intervals
from crowdllama_tpu.engine.engine import FakeEngine
from crowdllama_tpu.gateway.gateway import Gateway
from crowdllama_tpu.peer.peer import Peer
from crowdllama_tpu.testing import faults
from crowdllama_tpu.testing.faults import FaultPlan, FaultRule


def _cfg(bootstrap=None, **kw):
    cfg = Configuration(
        listen_host="127.0.0.1",
        bootstrap_peers=[bootstrap] if bootstrap else [],
        intervals=Intervals.default(),
    )
    for k, v in kw.items():
        setattr(cfg, k, v)
    return cfg


async def _wait_for(cond, timeout=30.0, interval=0.1, what="condition"):
    loop = asyncio.get_running_loop()
    deadline = loop.time() + timeout
    while loop.time() < deadline:
        if cond():
            return
        await asyncio.sleep(interval)
    raise AssertionError(f"timed out waiting for {what}")


def _chat_body(stream=False):
    return {"model": "tiny-test", "stream": stream,
            "messages": [{"role": "user",
                          "content": "tell me a long story about the "
                                     "swarm and its peers"}]}


async def test_stitched_trace_across_relay_spliced_swarm(monkeypatch,
                                                         capsys):
    """Tentpole e2e: two relayed workers behind a relay-hosting peer; a
    routed request's trace stitches gateway + relay + worker fragments
    into ONE orphan-free tree served at /debug/trace/<id>, and the
    ``crowdllama-tpu trace`` CLI renders it as a waterfall."""
    # Pin the relay SPLICE data path: reversal/punch would win on loopback
    # and the relay hop (and its relay_splice span) would never exist.
    monkeypatch.setenv("CROWDLLAMA_TPU_NO_PUNCH", "1")
    monkeypatch.setenv("CROWDLLAMA_TPU_NO_REVERSE", "1")

    # The bootstrap node is a full Peer (not a bare host): with no
    # bootstrap peers of its own it hosts the RelayService, and being a
    # Peer it has the obs plane + TraceFetch serving the collector needs
    # to pull the relay hop's fragment.
    # NB: FakeEngine(models=[]) falls back to tiny-test; a decoy name keeps
    # the relay host out of the tiny-test routing pool while still letting
    # the collector fan out to it (it IS a worker to the peer manager).
    relay_peer = Peer(Ed25519PrivateKey.generate(), _cfg(),
                      engine=FakeEngine(models=["relay-noop"]),
                      worker_mode=True)
    await relay_peer.start()
    assert relay_peer.relay_service is not None
    assert relay_peer.relay_service.obs is relay_peer.obs
    bootstrap = f"127.0.0.1:{relay_peer.host.listen_port}"

    workers = [Peer(Ed25519PrivateKey.generate(),
                    _cfg(bootstrap, relay_mode="always"),
                    engine=FakeEngine(models=["tiny-test"]),
                    worker_mode=True)
               for _ in range(2)]
    for w in workers:
        await w.start()
        assert w.resource.reachability == "relay"

    consumer = Peer(Ed25519PrivateKey.generate(), _cfg(bootstrap),
                    engine=FakeEngine(models=[]), worker_mode=False)
    await consumer.start()
    gateway = Gateway(consumer, port=0, host="127.0.0.1")
    await gateway.start()
    gw_port = gateway._runner.addresses[0][1]

    try:
        await _wait_for(
            lambda: len([p for p in consumer.peer_manager.get_workers()
                         if "tiny-test" in p.resource.supported_models]) == 2
            and len(consumer.peer_manager.get_workers()) == 3,
            what="both relayed workers + the relay peer discovered")

        async with aiohttp.ClientSession() as s:
            async with s.post(f"http://127.0.0.1:{gw_port}/api/chat",
                              json=_chat_body()) as resp:
                assert resp.status == 200, await resp.text()
                served_by = (await resp.json())["worker_id"]

            traces = gateway.obs.trace.snapshot()["traces"]
            assert traces, "gateway recorded no trace"
            tid = traces[-1]["trace_id"]

            async with s.get(f"http://127.0.0.1:{gw_port}"
                             f"/debug/trace/{tid}") as resp:
                assert resp.status == 200, await resp.text()
                stitched = await resp.json()

            # Unknown ids 404 with a JSON error, not a stack trace.
            async with s.get(f"http://127.0.0.1:{gw_port}"
                             f"/debug/trace/feedbeefdeadbeef") as resp:
                assert resp.status == 404
                assert "error" in await resp.json()

        assert stitched["stitched"] is True
        assert stitched["trace_id"] == tid
        # Three processes touched the request: the gateway root fragment,
        # the relay hop, and the serving worker.  The idle second worker
        # answered found=false and is absent.
        assert len(stitched["nodes"]) == 3, stitched["nodes"]
        assert stitched["nodes"][0] == "gateway"
        names = {sp["name"] for sp in stitched["spans"]}
        assert "relay_splice" in names, names
        assert {"route", "serde", "aead", "io_wait"} <= names
        assert {"worker_queue", "prefill"} <= names

        worker_nodes = {sp["node"] for sp in stitched["spans"]
                        if sp["name"] in ("worker_queue", "prefill")}
        assert worker_nodes == {f"worker:{served_by[:8]}"}

        # Orphan-free tree: every parent resolves to a rendered span, and
        # every span window nests inside the gateway request window.
        total = stitched["total_us"]
        for sp in stitched["spans"]:
            assert sp["parent"] in names | {""}, f"orphan span {sp}"
            assert sp["start_us"] >= 0.0
            assert sp["start_us"] <= total + 1e-6, sp

        # The CLI surface: `crowdllama-tpu trace <id>` prints the same
        # stitched tree as an indented waterfall.
        from crowdllama_tpu.cli.main import _trace

        rc = await _trace(SimpleNamespace(
            trace_id=tid, gateway=f"http://127.0.0.1:{gw_port}"))
        assert rc == 0
        out = capsys.readouterr().out
        assert tid in out
        assert "relay_splice" in out
        assert "▇" in out  # bars actually rendered
    finally:
        await gateway.stop()
        await consumer.stop()
        for w in workers:
            await w.stop()
        await relay_peer.stop()


async def test_flight_recorder_captures_killed_worker_failover():
    """A seeded kill_stream mid-stream forces a failover; the flight
    recorder must capture that request's COMPLETE stitched trace with the
    failover span intact, served at /debug/flightrecorder."""
    boot = Peer(Ed25519PrivateKey.generate(), _cfg(),
                engine=FakeEngine(models=["boot-noop"]), worker_mode=True)
    await boot.start()
    bootstrap = f"127.0.0.1:{boot.host.listen_port}"

    workers = [Peer(Ed25519PrivateKey.generate(), _cfg(bootstrap),
                    engine=FakeEngine(models=["tiny-test"]),
                    worker_mode=True)
               for _ in range(2)]
    for w in workers:
        await w.start()
    consumer = Peer(Ed25519PrivateKey.generate(), _cfg(bootstrap),
                    engine=FakeEngine(models=[]), worker_mode=False)
    await consumer.start()
    gateway = Gateway(consumer, port=0, host="127.0.0.1",
                      flight_recorder=8)
    await gateway.start()
    gw_port = gateway._runner.addresses[0][1]

    try:
        await _wait_for(
            lambda: len([p for p in consumer.peer_manager.get_workers()
                         if "tiny-test" in p.resource.supported_models]) == 2,
            what="both workers discovered")
        plan = FaultPlan(seed=42, rules=[
            FaultRule(site="engine.stream_chunk", action="kill_stream",
                      after=3, times=1)])
        async with aiohttp.ClientSession() as s:
            with faults.installed(plan):
                async with s.post(f"http://127.0.0.1:{gw_port}/api/chat",
                                  json=_chat_body(stream=True)) as resp:
                    assert resp.status == 200
                    raw = await resp.text()
            lines = [json.loads(l) for l in raw.splitlines() if l.strip()]
            assert lines[-1]["done"] is True
            assert plan.log and plan.log[0][2] == "kill_stream"

            failover_tids = [
                t["trace_id"]
                for t in gateway.obs.trace.snapshot()["traces"]
                if any(sp["name"] == "failover" for sp in t["spans"])]
            assert len(failover_tids) == 1
            tid = failover_tids[0]

            # The capture stitches asynchronously off the request path.
            await _wait_for(lambda: gateway.flight.get(tid) is not None,
                            timeout=15.0, what="flight-recorder capture")

            async with s.get(f"http://127.0.0.1:{gw_port}"
                             f"/debug/flightrecorder") as resp:
                assert resp.status == 200
                snap = await resp.json()

        assert snap["capacity"] == 8
        assert snap["captured_total"] >= 1
        entry = next(e for e in snap["traces"] if e["trace_id"] == tid)
        assert "failover" in entry["reasons"]
        # The failover span survived into the stitched capture, under the
        # gateway root, naming both sides of the move.
        fo = [sp for sp in entry["trace"]["spans"]
              if sp["name"] == "failover"]
        assert len(fo) == 1
        assert fo[0]["parent"] == "gateway"
        assert fo[0]["meta"]["from_worker"] != fo[0]["meta"]["to_worker"]
        # A boring request (no failover, sub-p99) was NOT captured.
        boring = [t["trace_id"]
                  for t in gateway.obs.trace.snapshot()["traces"]
                  if t["trace_id"] != tid]
        assert all(gateway.flight.get(t) is None for t in boring)
    finally:
        faults.clear()
        await gateway.stop()
        await consumer.stop()
        for w in workers:
            await w.stop()
        await boot.stop()


def test_spec_draft_retune_claims_one_new_compile_bucket():
    """Acceptance: draft_len is a STATIC argument of the speculative
    decode program, so an acceptance-driven retune compiles a NEW XLA
    program — the compile counter must grow by exactly one new
    (program, bucket) signature, and re-running at the old length must
    not recompile."""
    import jax
    import jax.numpy as jnp

    from crowdllama_tpu.engine.spec import SpecModelRunner
    from crowdllama_tpu.models import transformer as T
    from crowdllama_tpu.models.config import get_config
    from crowdllama_tpu.obs.metrics import ENGINE_TELEMETRY

    cfg = get_config("tiny-test", max_context_length=128)
    params = T.init_params(cfg, jax.random.PRNGKey(0), dtype=jnp.float32)
    spec = SpecModelRunner(cfg, params=params, max_slots=2, max_seq=128,
                           dtype=jnp.float32, draft_len=2)
    state = spec.init_state()
    prompt = [1, 5, 9, 5, 9, 5]
    first, ks, vs, plen = spec.prefill(prompt, 0.0, 1.0,
                                       jax.random.PRNGKey(7))
    state = spec.insert(state, 0, ks, vs, plen, first, 0.0, 1.0,
                        prompt_tokens=prompt)

    _, state = spec.decode_steps(state, 2)  # claims ("spec_decode", "2x2")
    before = ENGINE_TELEMETRY.snapshot_compiles()
    assert before.get(("spec_decode", "2x2"), 0) >= 1

    spec.set_draft_len(3)  # the acceptance-adaptive retune signal
    _, state = spec.decode_steps(state, 2)
    after = ENGINE_TELEMETRY.snapshot_compiles()

    new_keys = {k for k in after if k not in before
                and k[0].startswith("spec_decode")}
    assert new_keys == {("spec_decode", "2x3")}, new_keys
    assert after[("spec_decode", "2x3")] == 1

    # Back at the old length: the program is cached, no new compile.
    spec.set_draft_len(2)
    _, state = spec.decode_steps(state, 2)
    again = ENGINE_TELEMETRY.snapshot_compiles()
    assert again[("spec_decode", "2x2")] == before[("spec_decode", "2x2")]
