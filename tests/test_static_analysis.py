"""Tier-1 gate for swarmlint (crowdllama_tpu/analysis/): the repo itself
must be finding-free modulo the committed baseline, every checker must
still CATCH its bug class (seeded-violation fixtures — a checker that
rots into a no-op is worse than none), must stay quiet on the matching
clean idioms (true-negative fixtures), and the whole run must fit the
CI lint budget.  `make lint` runs the same checkers standalone.
"""

import json
import textwrap
import time

import pytest

from crowdllama_tpu.analysis import load_baseline, repo_root, run_all
from crowdllama_tpu.analysis.async_hotpath import check_async_hotpath
from crowdllama_tpu.analysis.base import Baseline, parse_baseline_toml
from crowdllama_tpu.analysis.contracts import (
    check_config_parity,
    check_fault_sites,
    check_metrics_docs,
    check_oneof,
    collect_metric_families,
)
from crowdllama_tpu.analysis.jax_purity import check_jax_purity
from crowdllama_tpu.testing.faults import FAULT_SITES


def _fake_repo(tmp_path, files):
    """Write {relpath: source} under tmp_path and return it as a root."""
    for rel, text in files.items():
        p = tmp_path / rel
        p.parent.mkdir(parents=True, exist_ok=True)
        p.write_text(textwrap.dedent(text), encoding="utf-8")
    return str(tmp_path)


# ------------------------------------------------------------ the repo


def _repo_run():
    """One timed run of every checker over the real repo, shared by the
    repo-level tests (the full sweep costs seconds; no need to pay it
    per assertion)."""
    if "result" not in _repo_run.__dict__:
        baseline = load_baseline()
        t0 = time.perf_counter()
        findings = run_all(repo_root(), baseline)
        _repo_run.result = (findings, baseline, time.perf_counter() - t0)
    return _repo_run.result


def test_repo_is_clean_within_budget():
    """Zero non-baseline findings across all checkers, inside the lint
    runtime budget ISSUE/CI hold the repo to (<30s; it runs in every
    `make test` / `make tier1`)."""
    findings, _, elapsed = _repo_run()
    assert not findings, "new swarmlint findings:\n" + "\n".join(
        f.render() for f in findings)
    assert elapsed < 30.0, f"lint took {elapsed:.1f}s — over the 30s budget"


def test_baseline_policy():
    """At most 10 waivers, every one with a non-empty reason, none stale."""
    _, baseline, _ = _repo_run()
    assert len(baseline.entries) <= 10, "baseline grew past 10 waivers — " \
        "fix findings instead of waiving them"
    for e in baseline.entries:
        assert e["reason"].strip(), f"waiver without justification: {e}"
    assert not baseline.stale(), f"stale waivers: {baseline.stale()}"


# ------------------------------------------------- baseline machinery


def test_baseline_parser_rejects_reasonless_waivers():
    good = parse_baseline_toml(
        '# comment\n[[waiver]]\nchecker = "async-hotpath"\n'
        'code = "blocking-call"\npath = "crowdllama_tpu/x.py"\n'
        'symbol = "f"\nreason = "startup-only read"\n')
    assert good[0]["symbol"] == "f"
    with pytest.raises(ValueError, match="missing keys"):
        parse_baseline_toml('[[waiver]]\nchecker = "x"\n')
    with pytest.raises(ValueError, match="empty"):
        parse_baseline_toml(
            '[[waiver]]\nchecker = "c"\ncode = "k"\npath = "p"\n'
            'symbol = "s"\nreason = "  "\n')
    with pytest.raises(ValueError, match="unparseable"):
        parse_baseline_toml("checker = 3\n")


def test_baseline_waives_by_key_and_reports_stale():
    from crowdllama_tpu.analysis.base import Finding

    b = Baseline(entries=[
        {"checker": "c", "code": "k", "path": "p.py", "symbol": "f",
         "reason": "r"},
        {"checker": "c", "code": "k", "path": "other.py", "symbol": "g",
         "reason": "r"},
    ])
    hit = Finding("c", "k", "p.py", 42, "f", "m")
    miss = Finding("c", "k", "p.py", 42, "h", "m")
    assert b.waives(hit) and not b.waives(miss)
    # Line number is NOT part of the key: same finding moved 100 lines
    # down is still waived; the unmatched entry reports stale.
    assert b.waives(Finding("c", "k", "p.py", 142, "f", "m"))
    assert [e["path"] for e in b.stale()] == ["other.py"]


# ------------------------------------------------ async-hotpath seeds


_ASYNC_FIXTURE = """
    import asyncio
    import time


    class Manager:
        def __init__(self):
            self._lock = asyncio.Lock()
            self.table = {}

        async def locked_update(self, k, v):
            async with self._lock:
                self.table = {k: v}

        async def racy_update(self):
            self.table = {}


    async def do_work():
        await asyncio.sleep(0)


    async def bad_sleep():
        time.sleep(0.1)


    async def bad_open(path):
        with open(path) as f:
            return f.read()


    async def bad_result(fut):
        return fut.result()


    async def lost():
        do_work()


    async def fine():
        await do_work()
        asyncio.create_task(do_work())
        loop = asyncio.get_running_loop()

        def _blocking():
            time.sleep(1)

        await loop.run_in_executor(None, _blocking)
"""


def test_async_hotpath_catches_seeded_violations(tmp_path):
    root = _fake_repo(tmp_path,
                      {"crowdllama_tpu/gateway/fx.py": _ASYNC_FIXTURE})
    hits = {(f.code, f.symbol)
            for f in check_async_hotpath(root, ("gateway",))}
    assert ("blocking-call", "bad_sleep") in hits
    assert ("blocking-call", "bad_open") in hits
    assert ("blocking-result", "bad_result") in hits
    assert ("unawaited-coroutine", "lost") in hits
    assert ("unlocked-mutation", "Manager.racy_update") in hits


def test_async_hotpath_true_negatives(tmp_path):
    root = _fake_repo(tmp_path,
                      {"crowdllama_tpu/gateway/fx.py": _ASYNC_FIXTURE})
    symbols = {f.symbol for f in check_async_hotpath(root, ("gateway",))}
    # Awaited/task-wrapped coroutines, executor-nested sleep, and the
    # lock-guarded mutation are all clean idioms — zero findings.
    assert "fine" not in symbols
    assert "Manager.locked_update" not in symbols


# --------------------------------------------------- jax-purity seeds


_PURITY_FIXTURE = """
    import time

    import jax
    import numpy as np


    @jax.jit
    def traced_bad(x):
        y = float(x)
        z = np.asarray(x)
        t = time.time()
        x.block_until_ready()
        return x


    @jax.jit
    def traced_ok(x):
        n = int(x.shape[0])
        return x * n


    def untraced(x):
        return float(np.asarray(x).item())
"""

_DONATE_FIXTURE = """
    import jax


    def _step_impl(params, pool):
        return pool


    _step = jax.jit(_step_impl, donate_argnums=(1,))


    def drive_bad(params, pool):
        out = _step(params, pool)
        return pool.tokens


    def drive_ok(params, pool):
        pool = _step(params, pool)
        return pool
"""


def test_jax_purity_catches_seeded_violations(tmp_path):
    root = _fake_repo(tmp_path,
                      {"crowdllama_tpu/engine/fx.py": _PURITY_FIXTURE})
    hits = [(f.code, f.symbol, f.line)
            for f in check_jax_purity(root, ("engine",))]
    codes = [(c, s) for c, s, _ in hits]
    assert codes.count(("host-sync", "traced_bad")) == 3  # float/asarray/bur
    assert ("impure-host-state", "traced_bad") in codes


def test_jax_purity_true_negatives(tmp_path):
    root = _fake_repo(tmp_path,
                      {"crowdllama_tpu/engine/fx.py": _PURITY_FIXTURE})
    symbols = {f.symbol for f in check_jax_purity(root, ("engine",))}
    # Static shape math under trace and host work in untraced helpers
    # are both fine.
    assert "traced_ok" not in symbols
    assert "untraced" not in symbols


def test_use_after_donate_seeded_and_rebind_negative(tmp_path):
    root = _fake_repo(tmp_path,
                      {"crowdllama_tpu/engine/fx.py": _DONATE_FIXTURE})
    findings = [f for f in check_jax_purity(root, ("engine",))
                if f.code == "use-after-donate"]
    assert [f.symbol for f in findings] == ["drive_bad"]
    assert "pool" in findings[0].message


_LOOP_SYNC_FIXTURE = """
    import jax
    import numpy as np


    class Sched:
        async def loop_bad(self, state):
            while True:
                tokens_dev, state = self.runner.decode_steps_device(state, 8)
                tokens = np.asarray(tokens_dev)
                last = tokens[-1, 0].item()

        async def loop_bad_executor(self, loop, state):
            for _ in range(4):
                tokens_dev, state = await loop.run_in_executor(
                    self._exec, self.runner.decode_steps_device, state, 8)
                tokens = await loop.run_in_executor(
                    self._exec, np.asarray, tokens_dev)

        async def loop_bad_fused(self, loop, state, job):
            while not job.finished:
                tokens_dev, done_dev, state = self.runner.ragged_megastep(
                    state, job, 8)
                done = np.asarray(done_dev)

        async def loop_ok(self, loop, state):
            while True:
                tokens_dev, done_dev, state = self.runner.decode_megastep(
                    state, 8)
                tokens, done = await loop.run_in_executor(
                    self._exec, jax.device_get, (tokens_dev, done_dev))

        async def loop_ok_fused(self, loop, state, job):
            while not job.finished:
                tokens_dev, done_dev, state = self.runner.ragged_megastep(
                    state, job, 8)
                tokens, done = await loop.run_in_executor(
                    self._exec, jax.device_get, (tokens_dev, done_dev))

        def retire_ok(self, fl):
            tokens = np.asarray(fl.tokens_dev)
            for step in range(tokens.shape[0]):
                self.emit(int(tokens[step, 0]))
"""


def test_host_sync_in_decode_loop_seeded(tmp_path):
    root = _fake_repo(tmp_path,
                      {"crowdllama_tpu/engine/fx.py": _LOOP_SYNC_FIXTURE})
    hits = {(f.code, f.symbol) for f in check_jax_purity(root, ("engine",))}
    # Direct per-step readback AND the executor-wrapped form (np.asarray
    # handed to run_in_executor) are both the seeded bug class, and the
    # fused ragged flight (ragged_megastep) is covered the same way — a
    # per-flight sync there forfeits the dispatches the fusion reclaimed.
    assert ("host-sync-in-decode-loop", "loop_bad") in hits
    assert ("host-sync-in-decode-loop", "loop_bad_executor") in hits
    assert ("host-sync-in-decode-loop", "loop_bad_fused") in hits


def test_host_sync_in_decode_loop_true_negatives(tmp_path):
    root = _fake_repo(tmp_path,
                      {"crowdllama_tpu/engine/fx.py": _LOOP_SYNC_FIXTURE})
    loop_hits = {f.symbol for f in check_jax_purity(root, ("engine",))
                 if f.code == "host-sync-in-decode-loop"}
    # The sanctioned megastep pattern (one jax.device_get of the packed
    # block per flight) — plain or fused ragged — and a dispatch-free
    # emit loop stay clean.
    assert "loop_ok" not in loop_hits
    assert "loop_ok_fused" not in loop_hits
    assert "retire_ok" not in loop_hits


# ----------------------------------------------------- contract seeds


def test_config_parity_catches_seeded_violations(tmp_path):
    root = _fake_repo(tmp_path, {"crowdllama_tpu/config.py": """
        import os


        class Configuration:
            alpha: int = 1
            beta: str = ""
            gamma: int = 2

            @classmethod
            def from_environment(cls, **overrides):
                env = os.environ
                cfg = cls()
                cfg.alpha = int(env.get("CROWDLLAMA_TPU_ALPHA", cfg.alpha))
                cfg.gamma = int(env.get("CROWDLLAMA_TPU_GAMMA", cfg.gamma))
                return cfg

            @classmethod
            def add_flags(cls, ap):
                ap.add_argument("--alpha", type=int)
                ap.add_argument("--gamma", type=int)
                ap.add_argument("--delta", type=int)

            @classmethod
            def from_flags(cls, args):
                cfg = cls.from_environment()
                for name in ("alpha",):
                    setattr(cfg, name, getattr(args, name))
                return cfg
    """})
    hits = {(f.code, f.symbol) for f in check_config_parity(root)}
    assert ("config-no-env", "beta") in hits          # field without env
    assert ("config-unknown-dest", "delta") in hits   # flag without field
    assert ("config-flag-unconsumed", "gamma") in hits
    assert not any(s == "alpha" for _, s in hits)     # fully wired: clean


def test_metrics_docs_catches_seeded_violations(tmp_path):
    root = _fake_repo(tmp_path, {
        "crowdllama_tpu/obs/fx.py": '''
            def expose(key):
                lines = ["# TYPE crowdllama_documented_total counter",
                         "# TYPE crowdllama_undocumented_total counter"]
                lines.append(f"crowdllama_dyn_{key}_total 1")
                return lines
        ''',
        "docs/OBSERVABILITY.md": (
            "`crowdllama_documented_total` and the `crowdllama_dyn_fast`\n"
            "family; `crowdllama_vanished_total` (no longer emitted).\n"),
    })
    hits = {(f.code, f.symbol) for f in check_metrics_docs(root)}
    assert ("metrics-undocumented", "crowdllama_undocumented_total") in hits
    assert ("metrics-stale-doc", "crowdllama_vanished_total") in hits
    # documented exact family + dynamic prefix with a documented member
    # are both clean.
    assert not any("documented_total" == s.replace("crowdllama_", "")
                   for c, s in hits if c == "metrics-undocumented"
                   and "un" not in s)
    assert not any(s.startswith("crowdllama_dyn_") for _, s in hits)


def test_fault_sites_catches_seeded_violations(tmp_path):
    inject_all = "\n".join(
        f'    await faults.inject("{s}")' for s in FAULT_SITES)
    root = _fake_repo(tmp_path, {
        "crowdllama_tpu/fx.py": (
            "from crowdllama_tpu.testing import faults\n\n\n"
            "async def run():\n"
            f"{inject_all}\n"
            '    await faults.inject("bogus.site")\n'),
        "tests/test_fx.py": """
            import pytest

            from crowdllama_tpu.testing.faults import FaultRule


            def test_seed():
                FaultRule(site="nope.site")
                with pytest.raises(ValueError):
                    FaultRule(site="deliberately.bad")
        """,
    })
    hits = {(f.code, f.symbol) for f in check_fault_sites(root)}
    assert ("fault-site-unregistered", "bogus.site") in hits
    assert ("fault-site-unknown-in-test", "nope.site") in hits
    # The pytest.raises-wrapped rule is a deliberate negative fixture —
    # never flagged; with every registered site instrumented above,
    # nothing reports uninstrumented either.
    assert not any(s == "deliberately.bad" for _, s in hits)
    assert not any(c == "fault-site-uninstrumented" for c, _ in hits)


def test_oneof_catches_missing_wiring(tmp_path):
    from crowdllama_tpu.analysis.contracts import RESPONSE_ARMS
    from crowdllama_tpu.core import llama_v1_pb2 as pb

    arms = [f.name for f in
            pb.BaseMessage.DESCRIPTOR.oneofs_by_name["message"].fields]
    requests = [a for a in arms if a not in RESPONSE_ARMS]
    drop_extract, drop_dispatch = arms[0], requests[-1]
    messages = "\n".join(
        [f"mk = lambda: BaseMessage({a}=None)" for a in arms]
        + [f'WHICH = "{a}"' for a in arms if a != drop_extract])
    peer = "\n".join(f'ok = which == "{a}"' for a in requests
                     if a != drop_dispatch)
    root = _fake_repo(tmp_path, {
        "crowdllama_tpu/core/messages.py": messages,
        "crowdllama_tpu/peer/peer.py": peer,
    })
    hits = {(f.code, f.symbol) for f in check_oneof(root)}
    assert ("oneof-extractor", drop_extract) in hits
    assert ("oneof-dispatch", drop_dispatch) in hits
    # Everything still wired stays clean, and no response arm ever
    # demands a dispatch arm.
    assert not any(c == "oneof-dispatch" and s in RESPONSE_ARMS
                   for c, s in hits)
    assert not any(c == "oneof-constructor" for c, _ in hits)


def test_collected_families_look_sane():
    """The static family collector (the doc-parity checker's foundation
    AND test_metrics_lint's completeness source) sees the core families
    and classifies dynamic f-string families as prefixes."""
    exact, prefixes = collect_metric_families(repo_root())
    for fam in ("crowdllama_request_seconds", "crowdllama_ttft_seconds",
                "crowdllama_workers_total",
                "crowdllama_device_memory_bytes_limit"):
        assert fam in exact, fam
    for pref in ("crowdllama_engine_", "crowdllama_kv_ship_",
                 "crowdllama_gossip_", "crowdllama_drain_"):
        assert pref in prefixes, pref
    # Module/protocol identifiers never masquerade as families.
    assert not any(f.startswith("crowdllama_tpu") for f in exact)


# ------------------------------------------------ ffi-contract seeds


_FFI_CPP_FIXTURE = """
    #include <cstdint>
    #include <cstddef>

    // cl_-named but NOT exported: internal linkage, outside extern "C" —
    // must not demand a ctypes declaration (true negative).
    static long cl_fx_internal(int a) { return a; }

    extern "C" {

    void* cl_fx_ok(const uint8_t* key, int flavor) { (void)key; return 0; }

    void cl_fx_void(void* h) { (void)h; }

    long cl_fx_arity(void* h, const uint8_t* buf, size_t len) { return 0; }

    long cl_fx_restype(void* h) { return 0; }

    long cl_fx_undeclared(void* h) { return 0; }

    long cl_fx_half(void* h) { return 0; }

    }  // extern "C"
"""

_FFI_PY_FIXTURE = """
    import ctypes


    def _declare(lib):
        u8p = ctypes.POINTER(ctypes.c_uint8)
        lib.cl_fx_ok.restype = ctypes.c_void_p
        lib.cl_fx_ok.argtypes = [ctypes.c_char_p, ctypes.c_int]
        lib.cl_fx_void.restype = None
        lib.cl_fx_void.argtypes = [ctypes.c_void_p]
        # Seeded: one argtypes entry short of the three C parameters.
        lib.cl_fx_arity.restype = ctypes.c_long
        lib.cl_fx_arity.argtypes = [ctypes.c_void_p, u8p]
        # Seeded: C returns long, declared c_int (truncation).
        lib.cl_fx_restype.restype = ctypes.c_int
        lib.cl_fx_restype.argtypes = [ctypes.c_void_p]
        # Seeded: argtypes half missing.
        lib.cl_fx_half.restype = ctypes.c_long
        # Seeded: no such extern "C" symbol.
        lib.cl_fx_ghost.restype = ctypes.c_long
        lib.cl_fx_ghost.argtypes = [ctypes.c_void_p]
        return lib
"""


def _ffi_fixture_root(tmp_path):
    return _fake_repo(tmp_path, {
        "crowdllama_tpu/native/_src/fx.cpp": _FFI_CPP_FIXTURE,
        "crowdllama_tpu/native/__init__.py": _FFI_PY_FIXTURE,
    })


def test_ffi_contract_catches_seeded_violations(tmp_path):
    from crowdllama_tpu.analysis.ffi_contract import check_ffi_contract

    hits = {(f.code, f.symbol)
            for f in check_ffi_contract(_ffi_fixture_root(tmp_path))}
    assert ("ffi-undeclared", "cl_fx_undeclared") in hits
    assert ("ffi-undeclared", "cl_fx_half") in hits
    assert ("ffi-arity", "cl_fx_arity") in hits
    assert ("ffi-restype", "cl_fx_restype") in hits
    assert ("ffi-unknown-symbol", "cl_fx_ghost") in hits


def test_ffi_contract_true_negatives(tmp_path):
    from crowdllama_tpu.analysis.ffi_contract import check_ffi_contract

    symbols = {f.symbol
               for f in check_ffi_contract(_ffi_fixture_root(tmp_path))}
    # Fully-declared functions (incl. restype None for void) are clean;
    # a static cl_-named helper outside extern "C" is not part of the ABI.
    assert "cl_fx_ok" not in symbols
    assert "cl_fx_void" not in symbols
    assert "cl_fx_internal" not in symbols


def test_ffi_contract_repo_has_zero_waivers():
    """ISSUE 19 policy: the ABI seam is never waived — both repo baseline
    hygiene and the checker being clean on the real tree."""
    from crowdllama_tpu.analysis.ffi_contract import (
        c_exports,
        check_ffi_contract,
        py_declarations,
    )

    assert not any(e.get("checker") == "ffi-contract"
                   for e in load_baseline().entries)
    root = repo_root()
    findings = check_ffi_contract(root)
    assert not findings, "\n".join(f.render() for f in findings)
    # The contract is non-trivially exercised: every native symbol the
    # data plane uses is visible to both sides of the seam.
    exports, decls = c_exports(root), py_declarations(root)
    assert len(exports) >= 15 and set(exports) == set(decls)


# ------------------------------------------------------------ the CLI


def test_cli_json_format_is_clean_on_repo(capsys):
    from crowdllama_tpu.analysis.__main__ import main

    rc = main(["--format", "json"])
    data = json.loads(capsys.readouterr().out)
    assert rc == 0
    assert data["findings"] == []
    assert data["checkers"] == ["async-hotpath", "contracts",
                                "ffi-contract", "jax-purity"]
    assert data["elapsed_s"] < 30.0


def test_cli_exits_nonzero_on_seeded_violation(tmp_path, capsys):
    """The `make lint` contract: injecting a violation flips the exit
    code (CI fails), and the finding renders with path:line."""
    from crowdllama_tpu.analysis.__main__ import main

    root = _fake_repo(tmp_path,
                      {"crowdllama_tpu/gateway/fx.py": _ASYNC_FIXTURE})
    rc = main(["--root", root, "--checker", "async-hotpath"])
    out = capsys.readouterr().out
    assert rc == 1
    assert "[async-hotpath/blocking-call] bad_sleep" in out


def test_cli_rejects_malformed_baseline(tmp_path, capsys):
    from crowdllama_tpu.analysis.__main__ import main

    bad = tmp_path / "baseline.toml"
    bad.write_text('[[waiver]]\nchecker = "c"\n', encoding="utf-8")
    assert main(["--baseline", str(bad)]) == 2
