"""IPC server tests mirroring /root/reference/pkg/ipc/ipc_test.go: real unix
socket, mock engine at the seam, length-prefixed PB and JSON clients."""

import asyncio
import json
import struct

from crowdllama_tpu.core import wire
from crowdllama_tpu.core.messages import create_generate_request, extract_generate_response
from crowdllama_tpu.engine.engine import FakeEngine
from crowdllama_tpu.ipc.server import IPCServer


async def _client(path):
    return await asyncio.open_unix_connection(path)


async def test_pb_roundtrip(tmp_path):
    sock = str(tmp_path / "ipc.sock")
    srv = IPCServer(sock, FakeEngine(models=["m"]))
    await srv.start()
    try:
        reader, writer = await _client(sock)
        msg = create_generate_request("m", "hello ipc")
        writer.write(wire.encode_frame(msg))
        await writer.drain()
        reply = await wire.read_length_prefixed_pb(reader, timeout=5)
        resp = extract_generate_response(reply)
        assert resp.response == "echo: hello ipc"
        assert resp.done
        writer.close()
    finally:
        await srv.stop()


async def test_json_ping_initialize_prompt_status(tmp_path):
    sock = str(tmp_path / "ipc.sock")
    srv = IPCServer(sock, FakeEngine(models=["m"]))
    await srv.start()
    try:
        reader, writer = await _client(sock)

        async def ask(obj):
            writer.write(json.dumps(obj).encode() + b"\n")
            await writer.drain()
            return json.loads(await asyncio.wait_for(reader.readline(), 5))

        assert (await ask({"type": "ping"}))["type"] == "pong"
        init = await ask({"type": "initialize", "mode": "worker"})
        assert init["type"] == "initialized" and init["mode"] == "worker"
        resp = await ask({"type": "prompt", "text": "hi"})
        assert resp["type"] == "response" and "hi" in resp["response"]
        st = await ask({"type": "status"})
        assert st["type"] == "status"
        err = await ask({"type": "bogus"})
        assert err["type"] == "error"
        writer.close()
    finally:
        await srv.stop()


async def test_socket_permissions(tmp_path):
    import stat
    sock = str(tmp_path / "ipc.sock")
    srv = IPCServer(sock, FakeEngine())
    await srv.start()
    try:
        mode = stat.S_IMODE((tmp_path / "ipc.sock").stat().st_mode)
        assert mode == 0o600
    finally:
        await srv.stop()


async def test_garbage_line(tmp_path):
    sock = str(tmp_path / "ipc.sock")
    srv = IPCServer(sock, FakeEngine())
    await srv.start()
    try:
        reader, writer = await _client(sock)
        writer.write(b"{garbage that is not json\n")
        await writer.drain()
        reply = json.loads(await asyncio.wait_for(reader.readline(), 5))
        assert reply["type"] == "error"
        writer.close()
    finally:
        await srv.stop()


async def test_json_embed(tmp_path):
    sock = str(tmp_path / "ipc.sock")
    srv = IPCServer(sock, FakeEngine(models=["m"]))
    await srv.start()
    try:
        reader, writer = await _client(sock)

        async def ask(obj):
            writer.write(json.dumps(obj).encode() + b"\n")
            await writer.drain()
            return json.loads(await asyncio.wait_for(reader.readline(), 5))

        reply = await ask({"type": "embed", "model": "m",
                           "input": ["alpha", "beta"]})
        assert reply["type"] == "embeddings"
        assert len(reply["embeddings"]) == 2
        assert reply["embeddings"][0] != reply["embeddings"][1]
        assert reply["prompt_tokens"] > 0
        writer.close()
    finally:
        await srv.stop()
