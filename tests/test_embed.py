"""Embeddings surface: runner pooling numerics, engine seam, and the
gateway /api/embed + /api/embeddings endpoints over a real loopback swarm.

The reference exposes Ollama's embeddings API only by delegation; here it is
a first-class path (hidden-state forward without the unembed matmul).
"""

import asyncio

import aiohttp
import jax
import jax.numpy as jnp
import numpy as np
from crowdllama_tpu.utils.crypto_compat import Ed25519PrivateKey

from crowdllama_tpu.models import transformer as T
from crowdllama_tpu.models.config import get_config


def test_embed_prompt_matches_unpadded_pooling():
    """Bucket padding must not leak into the pooled embedding."""
    from crowdllama_tpu.engine.runner import ModelRunner

    cfg = get_config("tiny-test", max_context_length=64)
    r = ModelRunner(cfg, max_slots=2, max_seq=64, dtype=jnp.float32)
    prompt = [7, 3, 11, 2, 9]  # len 5 → bucket 32 (27 padding positions)
    got = r.embed_prompt(prompt)
    assert got.shape == (cfg.hidden_size,)
    np.testing.assert_allclose(np.linalg.norm(got), 1.0, atol=1e-5)
    # Reference: exact-length forward, no padding anywhere.
    tokens = jnp.asarray([prompt])
    pos = jnp.arange(len(prompt))[None, :]
    h = T.hidden_states(r.params, cfg, tokens, pos)
    ref = np.asarray(h[0], np.float32).mean(axis=0)
    ref = ref / np.linalg.norm(ref)
    np.testing.assert_allclose(got, ref, atol=2e-3)
    # Deterministic.
    np.testing.assert_array_equal(got, r.embed_prompt(prompt))


def test_embed_on_pp_and_sp_meshes():
    """Embeddings must work on pp and sp meshes (VERDICT r3 missing #5:
    runner.embed_prompts raised NotImplementedError there) and agree with
    the single-device embedding."""
    from crowdllama_tpu.engine.runner import ModelRunner

    cfg = get_config("tiny-test", max_context_length=64)
    params = T.init_params(cfg, jax.random.PRNGKey(0), dtype=jnp.float32)
    base = ModelRunner(cfg, params=params, max_slots=2, max_seq=64,
                       mesh_spec="1", dtype=jnp.float32)
    prompts = [[7, 3, 11, 2, 9], list(range(1, 40))]
    ref = base.embed_prompts(prompts)

    # pp2 (microbatch pipeline forward), sp2 (ring-attention forward).
    for spec in ("1x2x1x1x1", "1x1x2x1x1"):
        r = ModelRunner(cfg, params=params, max_slots=2, max_seq=64,
                        mesh_spec=spec, dtype=jnp.float32)
        assert (r.pp, r.sp) != (1, 1), spec
        got = r.embed_prompts(prompts)
        np.testing.assert_allclose(got, ref, atol=2e-3, err_msg=spec)


async def test_jax_engine_embed_seam():
    from crowdllama_tpu.core import messages
    from crowdllama_tpu.engine.engine import JaxEngine

    eng = JaxEngine(model="tiny-test", max_slots=2)
    await eng.start()
    try:
        vecs, n_tokens = await eng.embed(
            ["hello world", "hello world", "different"])
        assert len(vecs) == 3
        assert n_tokens > 0
        assert vecs[0] == vecs[1]  # deterministic
        assert vecs[0] != vecs[2]
        # truncate=False must reject an over-length input, not clip it.
        too_long = "x" * (eng._runner.max_seq * 4)
        try:
            await eng.embed([too_long], truncate=False)
            raise AssertionError("expected ValueError for truncate=false")
        except ValueError:
            pass
        # Through the BaseMessage seam (what the peer stream handler calls).
        msg = messages.create_embed_request("tiny-test", ["swarm"])
        reply = await eng.handle(msg, worker_id="w1")
        resp = messages.extract_embed_response(reply)
        assert not resp.error
        assert len(resp.embeddings) == 1
        assert len(resp.embeddings[0].values) == get_config("tiny-test").hidden_size
        assert resp.worker_id == "w1"
        assert resp.total_duration > 0
        assert resp.prompt_tokens > 0
    finally:
        await eng.stop()


async def test_gateway_embed_endpoints():
    """Full loopback swarm: /api/embed and /api/embeddings route to a worker
    and return Ollama-shaped JSON (FakeEngine's deterministic vectors)."""
    from tests.test_integration import _topology, _wait_for

    worker, consumer, gateway, gw_port, teardown = await _topology()
    try:
        await _wait_for(
            lambda: any(
                p.peer_id == worker.peer_id
                for p in consumer.peer_manager.get_healthy_peers()
            ),
            what="consumer discovering worker",
        )
        base = f"http://127.0.0.1:{gw_port}"
        async with aiohttp.ClientSession() as http:
            # /api/embed with a list input.
            async with http.post(f"{base}/api/embed", json={
                "model": "tiny-test", "input": ["a", "b", "a"],
            }) as resp:
                assert resp.status == 200, await resp.text()
                body = await resp.json()
            assert body["model"] == "tiny-test"
            embs = body["embeddings"]
            assert len(embs) == 3 and embs[0] == embs[2] != embs[1]

            # Legacy /api/embeddings with a single prompt.
            async with http.post(f"{base}/api/embeddings", json={
                "model": "tiny-test", "prompt": "a",
            }) as resp:
                assert resp.status == 200, await resp.text()
                legacy = await resp.json()
            np.testing.assert_allclose(legacy["embedding"], embs[0], atol=1e-6)

            # Unknown model → 503 with error JSON, not a hang.
            async with http.post(f"{base}/api/embed", json={
                "model": "nope", "input": "x",
            }) as resp:
                assert resp.status == 503
                assert "error" in await resp.json()
    finally:
        await teardown()


async def test_gateway_model_management_surface():
    """/api/pull succeeds for swarm-served models (NDJSON like Ollama),
    404s with guidance otherwise; delete/create/copy/push are clean 501s."""
    from tests.test_integration import _topology, _wait_for

    worker, consumer, gateway, gw_port, teardown = await _topology()
    try:
        await _wait_for(
            lambda: any(
                p.peer_id == worker.peer_id
                for p in consumer.peer_manager.get_healthy_peers()
            ),
            what="consumer discovering worker",
        )
        base = f"http://127.0.0.1:{gw_port}"
        async with aiohttp.ClientSession() as http:
            async with http.post(f"{base}/api/pull",
                                 json={"model": "tiny-test"}) as resp:
                assert resp.status == 200
                lines = [l for l in (await resp.text()).splitlines() if l]
                import json as _json
                assert _json.loads(lines[-1])["status"] == "success"
            async with http.post(f"{base}/api/pull",
                                 json={"model": "absent"}) as resp:
                assert resp.status == 404
                assert "worker" in (await resp.json())["error"]
            async with http.post(f"{base}/api/delete",
                                 json={"model": "tiny-test"}) as resp:
                assert resp.status == 501
    finally:
        await teardown()


async def test_gateway_pull_non_streaming():
    """stream:false pull must return ONE JSON body (ollama-python default)."""
    from tests.test_integration import _topology, _wait_for

    worker, consumer, gateway, gw_port, teardown = await _topology()
    try:
        await _wait_for(
            lambda: any(p.peer_id == worker.peer_id
                        for p in consumer.peer_manager.get_healthy_peers()),
            what="discovery",
        )
        async with aiohttp.ClientSession() as http:
            async with http.post(
                f"http://127.0.0.1:{gw_port}/api/pull",
                json={"model": "tiny-test", "stream": False},
            ) as resp:
                assert resp.status == 200
                assert (await resp.json())["status"] == "success"
    finally:
        await teardown()
