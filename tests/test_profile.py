"""jax.profiler hooks (SURVEY §5): engine trace capture + IPC surface."""

import asyncio
import json
from pathlib import Path

import pytest

from crowdllama_tpu.config import Configuration, Intervals
from crowdllama_tpu.engine.engine import JaxEngine
from crowdllama_tpu.ipc.server import IPCServer


async def test_capture_profile_writes_trace(tmp_path):
    cfg = Configuration(model="tiny-test", max_context_length=64,
                        max_batch_slots=2, warmup=False,
                        profile_dir=str(tmp_path / "traces"),
                        intervals=Intervals.default())
    engine = JaxEngine(cfg)
    await engine.start()
    try:
        async def generate():
            async for _ in engine.generate("profile me", max_tokens=24):
                pass

        gen = asyncio.create_task(generate())
        trace_dir = await engine.capture_profile(seconds=0.5)
        await gen
        files = list(Path(trace_dir).rglob("*"))
        assert any(f.is_file() for f in files), "no trace artifacts written"
    finally:
        await engine.stop()


async def test_capture_profile_requires_config():
    cfg = Configuration(model="tiny-test", intervals=Intervals.default())
    engine = JaxEngine(cfg)  # not started; capture checks config first
    with pytest.raises(RuntimeError, match="profiling disabled"):
        await engine.capture_profile()


async def test_ipc_profile_op(tmp_path):
    cfg = Configuration(model="tiny-test", max_context_length=64,
                        max_batch_slots=2, warmup=False,
                        profile_dir=str(tmp_path / "traces"),
                        intervals=Intervals.default())
    engine = JaxEngine(cfg)
    await engine.start()
    sock = str(tmp_path / "ipc.sock")
    server = IPCServer(sock, engine)
    await server.start()
    try:
        reader, writer = await asyncio.open_unix_connection(sock)
        writer.write(json.dumps({"type": "profile", "seconds": 0.2}).encode() + b"\n")
        await writer.drain()
        reply = json.loads(await asyncio.wait_for(reader.readline(), 30))
        assert reply["type"] == "profile", reply
        assert Path(reply["trace_dir"]).exists()
        writer.close()
    finally:
        await server.stop()
        await engine.stop()
