"""Live request migration (docs/ROBUSTNESS.md): graceful worker drain with
KV handoff.

Worker side: drain() flips the peer to ``draining`` (typed reject for new
requests, forced metadata publish) and the scheduler retires in-flight
streams with a MigrateFrame at its next safe point, keeping the node
alive as a KV donor.  Gateway side: a MigrateFrame (or draining reject)
re-routes the stream through the failover/replay machinery with the
drained worker attached as ``kv_donor`` + ``migrate=True``, so the
successor imports the prompt's pages instead of re-running prefill — the
client sees one uninterrupted, byte-identical stream.
"""

import asyncio
import json
import time

import aiohttp
import pytest
from crowdllama_tpu.utils.crypto_compat import Ed25519PrivateKey

from crowdllama_tpu.config import Configuration, Intervals
from crowdllama_tpu.core import wire
from crowdllama_tpu.core.messages import (
    create_generate_request,
    extract_migrate_frame,
    migrate_frame_msg,
)
from crowdllama_tpu.engine.engine import FakeEngine
from crowdllama_tpu.engine.scheduler import _DONE, GenRequest, Scheduler
from crowdllama_tpu.gateway.gateway import Gateway
from crowdllama_tpu.net.discovery import new_host_and_dht
from crowdllama_tpu.obs.http import ObsServer
from crowdllama_tpu.peer.peer import Peer
from crowdllama_tpu.testing import faults
from crowdllama_tpu.testing.faults import FaultPlan, FaultRule

MODEL = "tiny-test"


# ------------------------------------------------------------------- units


async def test_scheduler_migrate_retires_pending_with_migrate():
    """migrate() hands back every queued request with the "migrate" done
    reason (the loop-less unit path; the loop path is covered end to end
    below) and leaves the scheduler usable as a drain donor."""

    class _StubRunner:
        max_slots = 2
        max_seq = 128

        def init_state(self):
            return None

    sched = Scheduler(_StubRunner())
    try:
        reqs = [GenRequest(prompt_ids=[1, 2, 3]),
                GenRequest(prompt_ids=[4, 5])]
        for r in reqs:
            await sched.submit(r)
        moved = await sched.migrate()
        assert moved == 2
        for r in reqs:
            tok, reason = r.out.get_nowait()
            assert tok is _DONE and reason == "migrate"
        # Idempotent: nothing left to move.
        assert await sched.migrate() == 0
    finally:
        await sched.stop()


async def test_fake_engine_migrate_emits_migrate_frame():
    """Mid-stream migrate() turns the terminal frame into a MigrateFrame
    carrying delivered/prompt token counts (the gateway consumes it as the
    re-route trigger)."""
    eng = FakeEngine(models=[MODEL])
    msg = create_generate_request(
        MODEL, "one two three four five six seven eight", stream=True)
    stream = eng.handle_streaming(msg, worker_id="w-drain")
    frames = []
    async for frame in stream:
        frames.append(frame)
        if len(frames) == 2:
            assert await eng.migrate() == 1
    assert frames[-1].WhichOneof("message") == "migrate_frame"
    mf = extract_migrate_frame(frames[-1])
    assert mf.worker_id == "w-drain"
    assert mf.reason == "drain"
    assert mf.delivered_tokens >= 1
    assert mf.prompt_tokens == 8
    # Every earlier frame was an ordinary streamed GenerateResponse.
    assert all(f.WhichOneof("message") == "generate_response"
               for f in frames[:-1])


def test_migrate_frame_wire_roundtrip():
    """MigrateFrame and GenerateRequest.migrate survive the length-prefixed
    wire encoding — and a frame without them decodes as before (the field
    numbers extend the proto, nothing was renumbered)."""
    msg = migrate_frame_msg(
        model=MODEL, worker_id="w1", delivered_tokens=7, prompt_tokens=42,
        chain_hashes=[b"\x01" * 32, b"\x02" * 32], page_size=16,
        reason="drain")
    out = wire.decode_payload(wire.encode_frame(msg)[4:])
    assert out.WhichOneof("message") == "migrate_frame"
    mf = extract_migrate_frame(out)
    assert (mf.delivered_tokens, mf.prompt_tokens, mf.page_size) == (7, 42, 16)
    assert list(mf.chain_hashes) == [b"\x01" * 32, b"\x02" * 32]

    req = create_generate_request(MODEL, "p", stream=True)
    req.generate_request.migrate = True
    req.generate_request.kv_donor = "w1"
    back = wire.decode_payload(wire.encode_frame(req)[4:])
    assert back.generate_request.migrate is True
    # Default stays False: old senders never set the field.
    plain = create_generate_request(MODEL, "p")
    assert plain.generate_request.migrate is False


def test_affinity_drop_worker_repoints_and_evicts():
    """Affinity hygiene (drain/removal): entries pinned to the leaving
    worker re-point to the migration successor when one is known,
    otherwise evict — and the repoint counter moves."""
    from types import SimpleNamespace

    gw = Gateway(SimpleNamespace(peer_manager=None), port=0)
    gw._affinity_put("conv-a", "w-old")
    gw._affinity_put("conv-b", "w-old")
    gw._affinity_put("conv-c", "w-other")
    gw._affinity_drop_worker("w-old", successor="w-new")
    assert gw._affinity["conv-a"][0] == "w-new"
    assert gw._affinity["conv-b"][0] == "w-new"
    assert gw._affinity["conv-c"][0] == "w-other"
    assert gw._affinity_repointed == 2
    # Removal with no successor: evict.
    gw._affinity_drop_worker("w-other")
    assert "conv-c" not in gw._affinity
    assert gw._affinity_repointed == 2


def test_peermanager_mark_draining_quarantines_routing():
    from crowdllama_tpu.core.resource import Resource
    from crowdllama_tpu.peermanager.manager import PeerManager

    pm = PeerManager(self_peer_id="self")
    r = Resource(worker_mode=True, peer_id="w1", supported_models=[MODEL],
                 tokens_throughput=10.0)
    r.touch()
    pm.add_or_update_peer(r)
    assert pm.find_best_worker(MODEL) is not None
    epoch = pm.routing_epoch
    assert pm.mark_draining("w1") is True
    assert pm.routing_epoch == epoch + 1          # snapshot invalidated
    assert pm.find_best_worker(MODEL) is None       # quarantined
    assert pm.is_routable("w1", MODEL) is None
    assert pm.mark_draining("w1") is False          # idempotent
    assert pm.mark_draining("missing") is False


# ----------------------------------------------------- fake-engine topology


class _SlowEngine(FakeEngine):
    """Word-paced echo engine: slow enough that an HTTP POST /drain lands
    while the stream is verifiably in flight."""

    async def generate(self, prompt, **kw):  # type: ignore[override]
        async for chunk in super().generate(prompt, **kw):
            yield chunk
            if not chunk.done:
                await asyncio.sleep(0.05)


def _cfg(bootstrap, **kw):
    cfg = Configuration(
        listen_host="127.0.0.1",
        bootstrap_peers=[bootstrap],
        intervals=Intervals.default(),
    )
    for k, v in kw.items():
        setattr(cfg, k, v)
    return cfg


async def _wait_for(cond, timeout=30.0, interval=0.1, what="condition"):
    loop = asyncio.get_running_loop()
    deadline = loop.time() + timeout
    while loop.time() < deadline:
        if cond():
            return
        await asyncio.sleep(interval)
    raise AssertionError(f"timed out waiting for {what}")


def _ndjson_lines(raw: str) -> list[dict]:
    return [json.loads(line) for line in raw.splitlines() if line.strip()]


def _content(lines: list[dict]) -> str:
    return "".join(l.get("message", {}).get("content", "") for l in lines)


async def _topology(engine_factory, n_workers=2, obs=False, cfg_kw=None,
                    **gw_kwargs):
    boot_host, _ = await new_host_and_dht(
        Ed25519PrivateKey.generate(), listen_host="127.0.0.1")
    bootstrap = f"127.0.0.1:{boot_host.listen_port}"
    cfg_kw = cfg_kw or {}

    engines = [engine_factory(_cfg(bootstrap, **cfg_kw))
               for _ in range(n_workers)]
    for e in engines:
        await e.start()
    workers = [Peer(Ed25519PrivateKey.generate(), _cfg(bootstrap, **cfg_kw),
                    engine=e, worker_mode=True) for e in engines]
    for w in workers:
        await w.start()
    obs_servers = []
    if obs:
        for w in workers:
            srv = ObsServer(w, port=0)
            await srv.start()
            obs_servers.append(srv)
    consumer = Peer(Ed25519PrivateKey.generate(), _cfg(bootstrap, **cfg_kw),
                    engine=FakeEngine(models=[]), worker_mode=False)
    await consumer.start()
    gateway = Gateway(consumer, port=0, host="127.0.0.1", **gw_kwargs)
    await gateway.start()
    gw_port = gateway._runner.addresses[0][1]

    await _wait_for(
        lambda: len({p.peer_id for p in
                     consumer.peer_manager.get_healthy_peers()
                     if p.is_worker}) == n_workers,
        what=f"all {n_workers} workers discovered")

    async def teardown():
        faults.clear()
        await gateway.stop()
        await consumer.stop()
        for srv in obs_servers:
            await srv.stop()
        for w in workers:
            try:
                await w.stop()
            except Exception:
                pass
        for e in engines:
            await e.stop()
        await boot_host.close()

    return workers, engines, obs_servers, consumer, gateway, gw_port, teardown


def _chat_body(content, stream=True, **options):
    return {"model": MODEL, "stream": stream,
            "messages": [{"role": "user", "content": content}],
            "options": options}


@pytest.mark.chaos
async def test_http_drain_midstream_migrates_fake_engines():
    """Acceptance: POST /drain on the serving worker of a 2-worker swarm
    mid-stream — the client's stream completes byte-identically on the
    successor, the draining worker leaves the routing snapshot, and a
    follow-up request still lands 200."""
    workers, engines, obs_servers, consumer, gateway, gw_port, teardown = \
        await _topology(lambda cfg: _SlowEngine(models=[MODEL]), obs=True)
    try:
        url = f"http://127.0.0.1:{gw_port}/api/chat"
        content = ("drain me gracefully please, one word at a time, "
                   "so the handoff has a stream to move")
        async with aiohttp.ClientSession() as s:
            # Baseline from a fault-free run (echo engines are identical).
            async with s.post(url, json=_chat_body(content)) as resp:
                assert resp.status == 200
                base_text = _content(_ndjson_lines(await resp.text()))

            drain_reply = {}
            buf = b""
            lines: list[dict] = []
            async with s.post(url, json=_chat_body(content)) as resp:
                assert resp.status == 200
                drained = False
                async for chunk in resp.content.iter_any():
                    buf += chunk
                    while b"\n" in buf:
                        raw, buf = buf.split(b"\n", 1)
                        if raw.strip():
                            lines.append(json.loads(raw))
                    if len(lines) >= 2 and not drained:
                        drained = True
                        # Find the serving worker and drain it over HTTP.
                        idx = next(i for i, e in enumerate(engines)
                                   if e._active > 0)
                        async with s.post(
                                f"http://127.0.0.1:{obs_servers[idx].port}"
                                f"/drain") as dresp:
                            assert dresp.status == 200
                            drain_reply = await dresp.json()
            assert drained, "stream finished before /drain could land"
            assert drain_reply["draining"] is True
            assert drain_reply["migrated_streams"] == 1

            # One uninterrupted, byte-identical stream.
            assert lines[-1]["done"] is True
            assert lines[-1].get("done_reason") == "stop"
            assert _content(lines) == base_text

            drained_peer = workers[idx]
            other = workers[1 - idx]
            # Gateway counted the migration and quarantined the worker.
            assert gateway.obs.metrics.migrated_streams == 1
            assert consumer.peer_manager.is_routable(
                drained_peer.peer_id, MODEL) is None
            best = consumer.peer_manager.find_best_worker(MODEL)
            assert best is not None and best.peer_id == other.peer_id

            # Draining worker rejects NEW requests with the typed frame,
            # so a fresh request still lands 200 on the survivor.
            async with s.post(url, json=_chat_body(content,
                                                   stream=False)) as resp:
                assert resp.status == 200
                d = await resp.json()
            assert d["worker_id"] == other.peer_id
            assert workers[idx].obs.metrics.drain["initiated"] == 1

            # /drain is idempotent.
            async with s.post(f"http://127.0.0.1:{obs_servers[idx].port}"
                              f"/drain") as dresp:
                d2 = await dresp.json()
            assert d2["already_draining"] is True
            assert d2["migrated_streams"] == 0

            # The migrate span landed under the gateway root.
            traces = gateway.obs.trace.snapshot()["traces"]
            spans = [sp for t in traces for sp in t["spans"]
                     if sp["name"] == "migrate"]
            assert len(spans) == 1
            assert spans[0]["meta"]["from_worker"] == \
                drained_peer.peer_id[:8]

            # Exposition surfaces: gateway counts the migrated stream, the
            # drained worker its initiated drain + migrated slot.
            async with s.get(
                    f"http://127.0.0.1:{gw_port}/metrics") as resp:
                gw_text = await resp.text()
            assert "crowdllama_migrated_streams_total 1" in gw_text
            async with s.get(f"http://127.0.0.1:{obs_servers[idx].port}"
                             f"/metrics") as resp:
                wk_text = await resp.text()
            assert 'crowdllama_drain_initiated_total 1' in wk_text
    finally:
        await teardown()


# ------------------------------------------------- real-engine KV handoff


# Byte-level tokenizer: ~1 token per char.  Flattened chat adds ~18
# tokens of role tags; keep content + 32 decode tokens under the 256
# context while still spanning many 16-token pages.
LONG_CONTENT = (
    "Live migration moves an in-flight stream to a successor without "
    "redoing prefill: the drained worker stays up as a KV donor and "
    "the successor imports the paged prefix instead of recomputing it.")


@pytest.mark.chaos
async def test_drain_midstream_kv_handoff_end_to_end():
    """Acceptance: a drain landing mid-stream (the 'drain' chaos action —
    the exact code path SIGTERM / POST /drain take) on 1 of 2 REAL engines
    migrates the stream with fetch-instead-of-recompute: byte-identical
    output, kv pages imported on the successor, and
    replayed_prefill_tokens == 0 for the migrated stream.  Tail section:
    a deadline budget expiring mid-KV-fetch still yields the standard 504
    contract (satellite: budget coverage across kv-ship)."""
    from crowdllama_tpu.engine.engine import JaxEngine

    kv_cfg = dict(model=MODEL, kv_layout="paged", kv_page_size=16,
                  kv_ship=True, kv_ship_min_tokens=16, kv_ship_timeout=2.0)
    workers, engines, _obs, consumer, gateway, gw_port, teardown = \
        await _topology(
            lambda cfg: JaxEngine(cfg, max_context_length=256, warmup=False),
            cfg_kw=kv_cfg, kv_ship=True)
    try:
        by_id = {w.peer_id: (w, e) for w, e in zip(workers, engines)}
        url = f"http://127.0.0.1:{gw_port}/api/chat"
        body = _chat_body(LONG_CONTENT, num_predict=32)
        # Drain lands on the FIRST streamed chunk: the scheduler still has
        # ~31 decode steps ahead of it, so the migrate safe point is
        # reached with the request verifiably in flight.
        plan = FaultPlan(seed=11, rules=[
            FaultRule(site="engine.stream_chunk", action="drain",
                      after=1, times=1)])
        async with aiohttp.ClientSession() as s:
            with faults.installed(plan):
                async with s.post(url, json=body) as resp:
                    assert resp.status == 200
                    lines = _ndjson_lines(await resp.text())
            assert plan.log and plan.log[0][2] == "drain"
            donor_id = plan.log[0][1]["worker"]
            donor_peer, donor_eng = by_id[donor_id]
            succ_id = next(p for p in by_id if p != donor_id)
            succ_peer, succ_eng = by_id[succ_id]

            # The stream completed cleanly on the successor...
            assert lines[-1]["done"] is True
            assert lines[-1].get("done_reason") in ("stop", "length")
            assert lines[-1]["worker_id"] == succ_id
            migrated_text = _content(lines)
            assert migrated_text

            # ...and byte-identically: a post-drain rerun of the same
            # request (same weights, greedy decode) is the reference.
            async with s.post(url, json=body) as resp:
                assert resp.status == 200
                reference = _content(_ndjson_lines(await resp.text()))
            assert migrated_text == reference

            # Fetch-instead-of-recompute: the successor imported the
            # donor's pages and counted ZERO replayed prefill tokens.
            assert succ_eng._runner.kv_pages_imported > 0
            assert donor_eng._runner.kv_pages_exported > 0
            assert succ_eng.obs.metrics.replayed_prefill_tokens == 0
            assert succ_eng.obs.metrics.kv_ship["fetches"] == 1

            # Worker-side drain accounting + gateway-side migration.
            assert donor_peer.obs.metrics.drain["initiated"] == 1
            assert donor_peer.obs.metrics.drain["migrated_slots"] >= 1
            assert gateway.obs.metrics.migrated_streams == 1
            assert consumer.peer_manager.is_routable(donor_id, MODEL) is None

            # --------- budget expiring MID-KV-FETCH: standard 504 contract
            gateway._kv_donor_for = lambda akey, model, chosen: donor_id
            slow = FaultPlan(rules=[
                FaultRule(site="kv.serve", action="delay", delay_s=3.0,
                          match={"worker": donor_id}, times=0)])
            budget_body = {
                "model": MODEL, "stream": False,
                "messages": [
                    {"role": "user", "content": "fetch the pages for this "
                     "brand new prompt nobody has cached yet, via a donor "
                     "whose serve path is artificially slow"},
                    {"role": "assistant", "content": "understood"},
                    {"role": "user", "content": "decode now"}],
                "options": {"num_predict": 8}}
            t0 = time.monotonic()
            with faults.installed(slow):
                async with s.post(url, json=budget_body,
                                  headers={"X-Request-Timeout": "1"}) as resp:
                    assert resp.status == 504
                    d = await resp.json()
            elapsed = time.monotonic() - t0
            assert slow.log, "kv.serve delay never fired"
            assert elapsed < 2.5, f"504 took {elapsed:.1f}s on a 1s budget"
            assert "deadline exceeded" in d["error"]
    finally:
        await teardown()


# A prompt LONGER than the budget-shrunk ragged admission chunk
# (byte-level tokenizer: ~1 token per char), so the worker takes the
# unified ragged chunked-prefill path and a drain can land with the
# prompt half-built inside tiny-test's 256-token context.
RAGGED_CONTENT = (
    "A drain landing mid-chunked-prefill must not forfeit the work: the "
    "donor keeps every completed page in its prefix index and the "
    "successor resumes chunking from where the donor stopped.")


@pytest.mark.chaos
async def test_drain_mid_chunked_prefill_resumes_on_successor():
    """Acceptance (ISSUE 9): a drain landing MID-CHUNKED-PREFILL (the
    "scheduler.ragged_chunk" chaos site) migrates the request before a
    single token streamed — the MigrateFrame carries the prompt's chain
    hashes, the successor fetches the pages the donor already computed
    and resumes chunking the tail, and replayed_prefill_tokens counts
    ONLY the unshipped tail (0 < replayed < prompt)."""
    from crowdllama_tpu.engine.engine import JaxEngine

    # step_token_budget 48 on 16-token pages → 32-token ragged chunks;
    # decode_chunk 1 → 32 prompt tokens per dispatch, so the ~200-token
    # prompt needs ~7 dispatches and the after=1 drain rule fires with
    # most of the prompt still unbuilt.
    kv_cfg = dict(model=MODEL, kv_layout="paged", kv_page_size=16,
                  kv_ship=True, kv_ship_min_tokens=16, kv_ship_timeout=2.0,
                  step_token_budget=48, decode_chunk=1)
    workers, engines, _obs, consumer, gateway, gw_port, teardown = \
        await _topology(
            lambda cfg: JaxEngine(cfg, max_context_length=256,
                                  warmup=False),
            cfg_kw=kv_cfg, kv_ship=True)
    try:
        by_id = {w.peer_id: (w, e) for w, e in zip(workers, engines)}
        url = f"http://127.0.0.1:{gw_port}/api/chat"
        body = _chat_body(RAGGED_CONTENT, num_predict=16)
        # The delay rule (listed first so the drain's raise cannot skip
        # its pass counts) parks the scheduler loop between the next two
        # chunk dispatches, guaranteeing the drain task reaches its
        # migrate safe point while the job is still mid-prefill.
        plan = FaultPlan(seed=13, rules=[
            FaultRule(site="scheduler.ragged_chunk", action="delay",
                      delay_s=0.3, after=2, times=2),
            FaultRule(site="scheduler.ragged_chunk", action="drain",
                      after=1, times=1)])
        async with aiohttp.ClientSession() as s:
            with faults.installed(plan):
                async with s.post(url, json=body) as resp:
                    assert resp.status == 200
                    lines = _ndjson_lines(await resp.text())
            # The drain fired mid-prefill: some tokens built, most not.
            assert plan.log and plan.log[0][2] == "drain"
            attrs = plan.log[0][1]
            assert 0 < attrs["done"] < attrs["total"], attrs

            donor_id = next(w.peer_id for w in workers
                            if w.obs.metrics.drain["initiated"])
            donor_peer, donor_eng = by_id[donor_id]
            succ_id = next(p for p in by_id if p != donor_id)
            succ_peer, succ_eng = by_id[succ_id]

            # The stream completed cleanly on the successor — no token had
            # streamed yet, so the client sees one uninterrupted stream.
            assert lines[-1]["done"] is True
            assert lines[-1].get("done_reason") in ("stop", "length")
            assert lines[-1]["worker_id"] == succ_id
            migrated_text = _content(lines)
            assert migrated_text

            # Partial handoff: pages moved donor → successor, and the
            # replay counter holds ONLY the unshipped tail — more than
            # zero (the drain interrupted the prefill) but strictly less
            # than the prompt (the shipped prefix was NOT recomputed).
            assert donor_eng._runner.kv_pages_exported > 0
            assert succ_eng._runner.kv_pages_imported > 0
            replayed = succ_eng.obs.metrics.replayed_prefill_tokens
            assert 0 < replayed < attrs["total"], (replayed, attrs)

            # Both sides chunked: the donor before the drain, the
            # successor resuming the tail (the unshipped remainder is
            # longer than one admission chunk, so it re-enters the ragged
            # path rather than the monolithic fallback).
            assert donor_eng.scheduler.ragged_chunks > 0
            assert succ_eng.scheduler.ragged_chunks > 0

            # Worker-side drain accounting + gateway-side migration.
            assert donor_peer.obs.metrics.drain["initiated"] == 1
            assert donor_peer.obs.metrics.drain["migrated_slots"] >= 1
            assert gateway.obs.metrics.migrated_streams == 1
            assert consumer.peer_manager.is_routable(donor_id, MODEL) is None

            # Byte-identity: a rerun of the same request (greedy, same
            # weights) on the surviving worker is the reference.
            async with s.post(url, json=body) as resp:
                assert resp.status == 200
                reference = _content(_ndjson_lines(await resp.text()))
            assert migrated_text == reference
    finally:
        await teardown()
