"""Draft-model distillation smoke (train/distill.py): the loop learns on
CPU at tier-1 scale, and its checkpoint round-trips through the native
checkpoint path (engine/weights.py) that --spec-draft-path loads."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from crowdllama_tpu.models import transformer as T
from crowdllama_tpu.models.config import get_config
from crowdllama_tpu.train.distill import (
    DistillConfig,
    corpus_from_text,
    distill_draft,
    draft_config_for,
    rollout_corpus,
)

pytestmark = pytest.mark.train


def _smoke_config(out=""):
    # 30 steps / tiny corpus: seconds on CPU, enough for the loss to move.
    return DistillConfig(teacher="tiny-test", steps=30, batch=8, seq_len=32,
                         corpus_seqs=16, out=out, log_every=0)


def test_distill_smoke_loss_decreases(tmp_path):
    res = distill_draft(_smoke_config(out=str(tmp_path / "ckpt")))
    losses = res["losses"]
    assert len(losses) == 30
    assert losses[-1] < losses[0], (losses[0], losses[-1])
    assert res["draft_config"].num_layers == 2
    assert res["checkpoint"]


def test_distill_checkpoint_roundtrips(tmp_path):
    from crowdllama_tpu.engine.weights import (
        is_native_checkpoint,
        load_or_init_params,
        native_config_from_dir,
    )

    out = str(tmp_path / "ckpt")
    res = distill_draft(_smoke_config(out=out))
    assert is_native_checkpoint(out)

    cfg = native_config_from_dir(out)
    assert cfg.num_layers == 2
    assert cfg.vocab_size == res["draft_config"].vocab_size

    # The exact load path --spec-draft-path takes (factory.py), at the
    # trainer's dtype so values compare exactly.
    loaded = load_or_init_params(cfg, out, dtype=jnp.float32)
    ref_flat = jax.tree_util.tree_leaves_with_path(res["draft_params"])
    got_flat = jax.tree_util.tree_leaves_with_path(loaded)
    assert len(ref_flat) == len(got_flat)
    for (rp, rv), (gp, gv) in zip(ref_flat, got_flat):
        assert rp == gp
        assert rv.shape == gv.shape, rp
        np.testing.assert_allclose(np.asarray(rv, np.float32),
                                   np.asarray(gv, np.float32), rtol=1e-6)


def test_rollout_corpus_prefix_pool_shapes():
    cfg = get_config("tiny-test", max_context_length=64)
    params = T.init_params(cfg, jax.random.PRNGKey(0), dtype=jnp.float32)
    pool = np.arange(500, dtype=np.int32) % cfg.vocab_size
    out = rollout_corpus(cfg, params, jax.random.PRNGKey(1), 4, 24, 0.0,
                         prefix_pool=pool, max_prefix=8)
    assert out.shape == (4, 24)
    assert out.dtype == np.int32 or np.issubdtype(out.dtype, np.integer)
    assert (out >= 0).all() and (out < cfg.vocab_size).all()
    # Prefix tokens really come from the pool: row starts are pool slices.
    assert all(int(out[i, 0]) in pool for i in range(4))


def test_corpus_from_text_chunks(tmp_path):
    p = tmp_path / "corpus.txt"
    p.write_bytes(b"hello speculative world " * 20)
    arr = corpus_from_text(str(p), 512, 32)
    assert arr.ndim == 2 and arr.shape[1] == 32
    assert (arr < 512).all()


def test_draft_config_for_truncates_layers():
    cfg = get_config("tiny-test", max_context_length=128)
    d = draft_config_for(cfg, 1)
    assert d.num_layers == 1
    assert d.vocab_size == cfg.vocab_size
    assert d.name.endswith("-draft1l")
