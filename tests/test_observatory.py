"""Swarm observatory (PR 13, docs/OBSERVABILITY.md): cluster metric
fan-in over a real 2-worker loopback swarm (partial snapshot when a
worker dies mid-scrape — never a 500), SLO burn-rate window math on a
fake clock, duty-cycle gauges under a real megastep scheduler run, shed
requests landing in the flight recorder, and the `top` table renderer.
"""

import asyncio
import re

import aiohttp
import pytest
from crowdllama_tpu.utils.crypto_compat import Ed25519PrivateKey

from crowdllama_tpu.config import Configuration, Intervals
from crowdllama_tpu.engine.engine import FakeEngine
from crowdllama_tpu.gateway.gateway import Gateway
from crowdllama_tpu.net.discovery import new_host_and_dht
from crowdllama_tpu.obs.slo import (
    FAST_BURN,
    BurnRateTracker,
    SloEngine,
)
from crowdllama_tpu.peer.peer import Peer
from crowdllama_tpu.testing import faults
from crowdllama_tpu.testing.faults import FaultPlan, FaultRule


def _cfg(bootstrap):
    return Configuration(listen_host="127.0.0.1",
                         bootstrap_peers=[bootstrap],
                         intervals=Intervals.default())


async def _wait_for(cond, timeout=20.0, what="condition"):
    loop = asyncio.get_running_loop()
    deadline = loop.time() + timeout
    while loop.time() < deadline:
        if cond():
            return
        await asyncio.sleep(0.1)
    raise AssertionError(f"timed out waiting for {what}")


# ------------------------------------------------- SLO burn-rate math


class _Clock:
    def __init__(self):
        self.t = 1000.0

    def __call__(self):
        return self.t


def test_burn_rate_good_traffic_is_zero():
    clk = _Clock()
    t = BurnRateTracker("ttft", objective_ms=100.0, clock=clk)
    for _ in range(50):
        assert t.observe(0.05) is False  # 50ms < 100ms objective
        clk.t += 1.0
    assert t.burn_rates() == {"5m": 0.0, "1h": 0.0}
    assert not t.in_fast_burn()
    assert t.good_total == 50 and t.bad_total == 0


def test_burn_rate_is_bad_fraction_over_budget():
    clk = _Clock()
    t = BurnRateTracker("ttft", objective_ms=100.0, budget=0.05, clock=clk)
    for i in range(20):
        t.observe(0.5 if i < 2 else 0.05)  # 2 bad of 20
        clk.t += 1.0
    # bad_fraction 0.1 / budget 0.05 = 2x burn, identical on both
    # windows while everything fits inside the short one.
    rates = t.burn_rates()
    assert rates["5m"] == pytest.approx(2.0)
    assert rates["1h"] == pytest.approx(2.0)
    assert not t.in_fast_burn()  # 2x is a leak, not an incident


def test_burn_rate_windows_roll_independently():
    clk = _Clock()
    t = BurnRateTracker("ttft", objective_ms=100.0, clock=clk)
    for _ in range(10):
        t.observe(1.0)  # all bad
        clk.t += 1.0
    # Step past the short window: the 5m rate empties, the 1h window
    # still remembers the burst.
    clk.t += 301.0
    rates = t.burn_rates()
    assert rates["5m"] == 0.0
    assert rates["1h"] > 0.0
    # Step past the long window too (observe() prunes dead cells).
    clk.t += 3600.0
    t.observe(0.05)
    assert t.burn_rates() == {"5m": 0.0, "1h": pytest.approx(0.0)}
    assert len(t._cells) == 1  # the old burst's cells were pruned


def test_fast_burn_requires_both_windows():
    clk = _Clock()
    t = BurnRateTracker("ttft", objective_ms=100.0, budget=0.05, clock=clk)
    for _ in range(10):
        t.observe(1.0)  # 100% bad -> 20x burn on both windows
        clk.t += 1.0
    assert t.burn_rates()["5m"] >= FAST_BURN
    assert t.in_fast_burn()
    # The 5m window recovering ends the fast burn even though the 1h
    # window still carries the burst.
    clk.t += 301.0
    for _ in range(200):
        t.observe(0.05)
        clk.t += 1.0
    assert not t.in_fast_burn()


def test_slo_engine_edge_triggered_episodes():
    clk = _Clock()
    eng = SloEngine(ttft_ms=100.0, clock=clk)
    assert eng.enabled
    for _ in range(10):
        eng.observe_ttft(1.0)
        clk.t += 1.0
    assert eng.fast_burn() is True
    assert eng.fast_burn_episodes_total == 1
    assert eng.fast_burn() is True  # level stays up...
    assert eng.fast_burn_episodes_total == 1  # ...the edge counted once
    clk.t += 4000.0  # everything ages out of both windows
    eng.observe_ttft(0.05)
    assert eng.fast_burn() is False
    for _ in range(10):
        eng.observe_ttft(1.0)
        clk.t += 1.0
    eng.fast_burn()
    assert eng.fast_burn_episodes_total == 2  # second rising edge


def test_slo_engine_disabled_is_inert():
    eng = SloEngine()  # both objectives 0
    assert not eng.enabled
    assert eng.observe_ttft(99.0) is False
    assert eng.observe_decode(99.0) is False
    assert eng.expose() == []
    assert eng.fast_burn() is False


def test_autoscale_parses_worst_burn_rate():
    from crowdllama_tpu.swarm.autoscale import parse_gauges

    text = (
        'crowdllama_engine_pending_depth 4\n'
        'crowdllama_slo_burn_rate{objective="ttft",window="5m"} 15.5\n'
        'crowdllama_slo_burn_rate{objective="ttft",window="1h"} 2.25\n'
        'crowdllama_slo_burn_rate{objective="decode",window="5m"} 1.0\n')
    g = parse_gauges(text)
    assert g["slo_burn_rate"] == pytest.approx(15.5)
    assert g["pending_depth"] == 4.0
    # SLO plane off -> no key; the controller reads it with .get().
    assert "slo_burn_rate" not in parse_gauges(
        "crowdllama_engine_pending_depth 1\n")


# ------------------------------------------------- duty-cycle profiler


async def test_duty_cycle_gauges_under_megastep_run():
    """A real megastep scheduler run moves ONLY the megastep duty-cycle
    gauge (per-step control moves only `plain`), both stay in (0, 1],
    and the host-gap histogram collects per-class samples."""
    import jax
    import jax.numpy as jnp

    from crowdllama_tpu.engine.paged import PagedModelRunner
    from crowdllama_tpu.engine.scheduler import DONE, Scheduler
    from crowdllama_tpu.models import transformer as T
    from crowdllama_tpu.models.config import get_config
    from crowdllama_tpu.obs.metrics import ENGINE_TELEMETRY

    cfg = get_config("tiny-test", max_context_length=256)
    params = T.init_params(cfg, jax.random.PRNGKey(0), dtype=jnp.bfloat16)
    runner = PagedModelRunner(cfg, params=params, max_slots=2, max_seq=256,
                              page_size=32, mesh_spec="1")

    async def _run(megastep_k):
        from crowdllama_tpu.engine.scheduler import GenRequest

        sched = Scheduler(runner, megastep_k=megastep_k, decode_chunk=1)
        sched.start()
        try:
            reqs = [GenRequest(prompt_ids=[3, 1, 4], max_tokens=12, seed=7),
                    GenRequest(prompt_ids=[2, 7], max_tokens=9, seed=5)]
            for r in reqs:
                await sched.submit(r)
            for r in reqs:
                while True:
                    tok, _ = await asyncio.wait_for(r.out.get(), 120)
                    if tok is DONE:
                        break
            return sched.telemetry_gauges()
        finally:
            await sched.stop()

    mega_before = ENGINE_TELEMETRY.host_gap_seconds.labels("megastep").count
    plain_before = ENGINE_TELEMETRY.host_gap_seconds.labels("plain").count

    mega = await _run(8)
    plain = await _run(0)

    for g in (mega, plain):  # all four classes always present
        for cls in ("plain", "megastep", "ragged", "spec"):
            assert f"duty_cycle|dispatch={cls}" in g
    assert 0.0 < mega["duty_cycle|dispatch=megastep"] <= 1.0
    assert mega["duty_cycle|dispatch=plain"] == 0.0
    assert 0.0 < plain["duty_cycle|dispatch=plain"] <= 1.0
    assert plain["duty_cycle|dispatch=megastep"] == 0.0
    # The host-gap histogram collected per-class samples from both runs.
    assert ENGINE_TELEMETRY.host_gap_seconds.labels("megastep").count \
        > mega_before
    assert ENGINE_TELEMETRY.host_gap_seconds.labels("plain").count \
        > plain_before


def test_multi_engine_max_merges_duty_cycle():
    """Duty cycle is a ratio: MultiEngine must max-merge it across
    children, not sum it past 1.0."""
    from crowdllama_tpu.engine.multi import MultiEngine

    class _Child:
        def __init__(self, duty):
            self._g = {"pending_depth": 1.0,
                       "duty_cycle|dispatch=megastep": duty}

        def obs_gauges(self):
            return dict(self._g)

    me = MultiEngine.__new__(MultiEngine)
    me._engines = {"a": _Child(0.9), "b": _Child(0.4)}
    g = me.obs_gauges()
    assert g["duty_cycle|dispatch=megastep"] == pytest.approx(0.9)
    assert g["pending_depth"] == pytest.approx(2.0)  # depths still sum


# --------------------------------------------- cluster metric fan-in e2e


async def _swarm(n_workers=2, **gw_kw):
    boot_host, _ = await new_host_and_dht(
        Ed25519PrivateKey.generate(), listen_host="127.0.0.1")
    bootstrap = f"127.0.0.1:{boot_host.listen_port}"
    workers = [Peer(Ed25519PrivateKey.generate(), _cfg(bootstrap),
                    engine=FakeEngine(models=["tiny-test"]),
                    worker_mode=True)
               for _ in range(n_workers)]
    for w in workers:
        await w.start()
    consumer = Peer(Ed25519PrivateKey.generate(), _cfg(bootstrap),
                    engine=FakeEngine(models=[]), worker_mode=False)
    await consumer.start()
    gateway = Gateway(consumer, port=0, host="127.0.0.1", **gw_kw)
    await gateway.start()
    gw_port = gateway._runner.addresses[0][1]
    await _wait_for(
        lambda: len(consumer.peer_manager.get_workers()) == n_workers,
        what=f"{n_workers} workers discovered")
    return boot_host, workers, consumer, gateway, gw_port


async def _teardown(boot_host, workers, consumer, gateway):
    await gateway.stop()
    await consumer.stop()
    for w in workers:
        try:
            await w.stop()
        except Exception:
            pass
    await boot_host.close()


async def test_cluster_scrape_two_workers():
    """/metrics/cluster returns worker-labeled families for BOTH workers
    plus the swarm rollups, and the family filter narrows the payload."""
    boot_host, workers, consumer, gateway, gw_port = await _swarm()
    try:
        async with aiohttp.ClientSession() as s:
            async with s.get(f"http://127.0.0.1:{gw_port}"
                             f"/metrics/cluster") as resp:
                assert resp.status == 200
                text = await resp.text()

        for w in workers:
            label = w.peer_id[:16]
            assert (f'crowdllama_engine_pending_depth{{worker="{label}"}}'
                    in text), f"no engine block for worker {label}"
            # The gateway's routing view joins on the same id head.
            assert f'crowdllama_worker_healthy{{peer="{label}"}} 1' in text
        assert "crowdllama_cluster_workers_total 2" in text
        assert "crowdllama_cluster_workers_scraped 2" in text
        assert re.search(r"crowdllama_cluster_tokens_per_second \S+", text)
        assert re.search(r"crowdllama_cluster_inflight \S+", text)
        # Worker histograms merged with exactly one TYPE per family.
        assert text.count(
            "# TYPE crowdllama_decode_step_seconds histogram") == 1
        assert " # {" not in text  # exemplars stripped from the merge

        # Family filter: only crowdllama_engine_* survives per worker.
        async with aiohttp.ClientSession() as s:
            async with s.get(
                    f"http://127.0.0.1:{gw_port}/metrics/cluster"
                    f"?family=crowdllama_engine_") as resp:
                assert resp.status == 200
                narrowed = await resp.text()
        assert 'crowdllama_engine_pending_depth{worker="' in narrowed
        assert 'crowdllama_request_seconds' not in narrowed
    finally:
        await _teardown(boot_host, workers, consumer, gateway)


async def test_cluster_scrape_partial_on_worker_death():
    """A worker dying mid-scrape (obs.scrape fault + a stopped peer)
    degrades /metrics/cluster to a partial snapshot — 200, the live
    worker's block intact, misses counted.  Never a 500."""
    boot_host, workers, consumer, gateway, gw_port = await _swarm()
    try:
        dead, alive = workers[0], workers[1]
        plan = FaultPlan(seed=7, rules=[
            FaultRule(site="obs.scrape", action="error",
                      match={"worker": dead.peer_id}, times=0),
        ])
        with faults.installed(plan):
            async with aiohttp.ClientSession() as s:
                async with s.get(f"http://127.0.0.1:{gw_port}"
                                 f"/metrics/cluster") as resp:
                    assert resp.status == 200
                    text = await resp.text()
        assert plan.log, "obs.scrape fault never fired"
        alive_label = alive.peer_id[:16]
        dead_label = dead.peer_id[:16]
        assert (f'crowdllama_engine_pending_depth{{worker="{alive_label}"}}'
                in text)
        assert (f'crowdllama_engine_pending_depth{{worker="{dead_label}"}}'
                not in text)
        assert "crowdllama_cluster_workers_scraped 1" in text
        assert re.search(
            r"crowdllama_cluster_scrape_misses_total [1-9]", text)

        # Harder death: the worker process is GONE (socket closed).  The
        # p2p fetch times out / errors; the surface still answers 200.
        await dead.stop()
        async with aiohttp.ClientSession() as s:
            async with s.get(f"http://127.0.0.1:{gw_port}"
                             f"/metrics/cluster") as resp:
                assert resp.status == 200
                text = await resp.text()
        assert (f'crowdllama_engine_pending_depth{{worker="{alive_label}"}}'
                in text)
    finally:
        await _teardown(boot_host, workers, consumer, gateway)


async def test_shed_request_lands_in_flight_recorder():
    """A shed 503 mints a gateway-only trace and the flight recorder
    captures it with reason `shed` (ISSUE 13 satellite)."""
    boot_host, workers, consumer, gateway, gw_port = await _swarm(
        n_workers=1, admission_max_inflight=1)
    try:
        gateway._inflight = 1  # the cap is reached
        body = {"model": "tiny-test", "stream": False,
                "messages": [{"role": "user", "content": "shed me"}]}
        async with aiohttp.ClientSession() as s:
            async with s.post(f"http://127.0.0.1:{gw_port}/api/chat",
                              json=body) as resp:
                assert resp.status == 503
                assert "Retry-After" in resp.headers
        gateway._inflight = 0
        await _wait_for(
            lambda: any("shed" in t["reasons"]
                        for t in gateway.flight.snapshot()["traces"]),
            timeout=10.0, what="shed capture in the flight recorder")
        cap = [t for t in gateway.flight.snapshot()["traces"]
               if "shed" in t["reasons"]][0]
        names = {sp.get("name") for sp in cap["trace"].get("spans", [])}
        assert "shed" in names
    finally:
        await _teardown(boot_host, workers, consumer, gateway)


# --------------------------------------------------------- top renderer


def test_render_top_joins_routing_and_engine_views():
    from crowdllama_tpu.cli.main import render_top

    text = "\n".join([
        "# TYPE crowdllama_cluster_workers_total gauge",
        "crowdllama_cluster_workers_total 2",
        "crowdllama_cluster_workers_scraped 2",
        "crowdllama_cluster_tokens_per_second 123.5",
        "crowdllama_cluster_batch_occupancy 0.5",
        "crowdllama_cluster_kv_cache_utilization 0.25",
        "crowdllama_cluster_inflight 3",
        'crowdllama_worker_load{peer="aaaa"} 0.4',
        'crowdllama_worker_healthy{peer="aaaa"} 1',
        'crowdllama_worker_throughput_tokens_per_sec{peer="aaaa"} 100',
        'crowdllama_worker_healthy{peer="bbbb"} 0',
        'crowdllama_engine_batch_occupancy{worker="aaaa"} 0.75',
        'crowdllama_engine_pending_depth{worker="aaaa"} 2',
        'crowdllama_engine_duty_cycle{worker="aaaa",dispatch="megastep"}'
        ' 0.93',
        'crowdllama_engine_duty_cycle{worker="aaaa",dispatch="plain"} 0.1',
    ])
    out = render_top(text)
    lines = out.splitlines()
    assert "workers 2 (scraped 2)" in lines[0]
    assert "tok/s 123.5" in lines[0]
    row_a = next(ln for ln in lines if ln.startswith("aaaa"))
    assert " y " in row_a or row_a.split()[1] == "y"
    assert "0.93" in row_a  # max duty across classes
    assert "0.75" in row_a
    row_b = next(ln for ln in lines if ln.startswith("bbbb"))
    assert row_b.split()[1] == "n"


def test_render_top_empty_swarm():
    from crowdllama_tpu.cli.main import render_top

    out = render_top("crowdllama_cluster_workers_total 0\n")
    assert "(no workers visible)" in out
