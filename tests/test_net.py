"""Stream host + DHT tests on loopback — the TPU translation of the
reference's real-libp2p-on-loopback strategy (SURVEY §4): no network mocks,
real sockets, compressed intervals."""

import asyncio

from crowdllama_tpu.utils.crypto_compat import Ed25519PrivateKey

from crowdllama_tpu.config import Intervals
from crowdllama_tpu.core.protocol import namespace_key
from crowdllama_tpu.core.resource import Resource
from crowdllama_tpu.net.dht import DHTNode, RoutingTable, ProviderStore
from crowdllama_tpu.net.discovery import (
    Advertiser,
    discover_peers,
    new_host_and_dht,
    request_peer_metadata,
)
from crowdllama_tpu.net.host import Contact, Host, HandshakeError
from crowdllama_tpu.core.protocol import METADATA_PROTOCOL
from crowdllama_tpu.utils.keys import peer_id_to_dht_id


def _key():
    return Ed25519PrivateKey.generate()


async def _mknode(bootstrap=None):
    host, dht = await new_host_and_dht(_key(), listen_host="127.0.0.1")
    if bootstrap:
        await dht.bootstrap([bootstrap])
    return host, dht


async def test_stream_handshake_and_echo():
    a = Host(_key(), listen_host="127.0.0.1")
    b = Host(_key(), listen_host="127.0.0.1")
    await a.start()
    await b.start()
    got = asyncio.Future()

    async def handler(stream):
        data = await stream.reader.readexactly(5)
        got.set_result((stream.remote_peer_id, data))
        stream.writer.write(b"world")
        await stream.writer.drain()

    b.set_stream_handler("/test/1.0.0", handler)
    s = await a.new_stream(b.contact, "/test/1.0.0")
    assert s.remote_peer_id == b.peer_id
    s.writer.write(b"hello")
    await s.writer.drain()
    assert await s.reader.readexactly(5) == b"world"
    peer, data = await asyncio.wait_for(got, 5)
    assert peer == a.peer_id and data == b"hello"
    s.close()
    await a.close()
    await b.close()


async def test_unknown_protocol_rejected():
    a = Host(_key(), listen_host="127.0.0.1")
    b = Host(_key(), listen_host="127.0.0.1")
    await a.start()
    await b.start()
    try:
        try:
            await a.new_stream(b.contact, "/nope/1.0.0")
            raise AssertionError("expected HandshakeError")
        except HandshakeError as e:
            assert "unknown protocol" in str(e) or "rejected" in str(e)
    finally:
        await a.close()
        await b.close()


async def test_identity_mismatch_rejected():
    a = Host(_key(), listen_host="127.0.0.1")
    b = Host(_key(), listen_host="127.0.0.1")
    await a.start()
    await b.start()
    b.set_stream_handler("/t/1", lambda s: asyncio.sleep(0))
    wrong = Contact(peer_id="f" * 40, host="127.0.0.1", port=b.listen_port)
    try:
        try:
            await a.new_stream(wrong, "/t/1")
            raise AssertionError("expected HandshakeError")
        except HandshakeError as e:
            assert "mismatch" in str(e)
    finally:
        await a.close()
        await b.close()


def test_routing_table_basics():
    rt = RoutingTable(peer_id_to_dht_id("self"), k=3)
    contacts = [Contact(f"peer-{i}", "127.0.0.1", 1000 + i) for i in range(10)]
    for c in contacts:
        rt.update(c)
    assert len(rt) <= 10
    target = peer_id_to_dht_id("peer-3")
    closest = rt.closest(target, 5)
    assert closest and closest[0].peer_id == "peer-3"
    rt.remove("peer-3")
    assert all(c.peer_id != "peer-3" for c in rt.contacts())


def test_provider_store_ttl():
    ps = ProviderStore(ttl=0.05)
    c = Contact("p", "127.0.0.1", 1)
    ps.add(b"k" * 32, c)
    assert ps.get(b"k" * 32) == [c]
    import time
    time.sleep(0.08)
    assert ps.get(b"k" * 32) == []


async def test_dht_provide_and_find_providers():
    """Three nodes: bootstrap + two peers; provider records propagate."""
    boot_host, boot_dht = await _mknode()
    addr = f"127.0.0.1:{boot_host.listen_port}"
    h1, d1 = await _mknode(bootstrap=addr)
    h2, d2 = await _mknode(bootstrap=addr)
    try:
        key = namespace_key()
        await d1.provide(key)
        # h2 discovers h1 as provider through the bootstrap node
        providers = await d2.find_providers(key)
        ids = {c.peer_id for c in providers}
        assert h1.peer_id in ids
    finally:
        for h in (boot_host, h1, h2):
            await h.close()


async def test_provide_rate_limit_and_churn_floor():
    """provide(min_interval=...) skips the network while nothing changed,
    re-provides after a membership change but no faster than the
    min_interval/20 churn floor (N joins must not cascade into a
    re-provide storm), and never memoizes a rejected-everywhere provide."""
    import asyncio

    boot_host, boot_dht = await _mknode()
    addr = f"127.0.0.1:{boot_host.listen_port}"
    h1, d1 = await _mknode(bootstrap=addr)
    try:
        key = namespace_key()
        rpcs = []
        orig = d1._rpc

        async def counting(c, payload):
            if payload.get("op") == "add_provider":
                rpcs.append(1)
            return await orig(c, payload)

        d1._rpc = counting
        # A wide interval (floor = 10/20 = 0.5 s) keeps the in-floor
        # assertions below from racing wall-clock work like node startup
        # on a loaded box; floor expiry is simulated by rewinding the memo
        # timestamp rather than sleeping it out.
        await d1.provide(key, min_interval=10.0)
        first = len(rpcs)
        assert first >= 1
        # Unchanged fingerprint within min_interval: no network traffic.
        await d1.provide(key, min_interval=10.0)
        assert len(rpcs) == first
        # Membership change within the churn floor: still suppressed...
        h2, d2 = await _mknode(bootstrap=addr)
        d1.table.update(h2.contact)  # simulate learning the joiner
        await d1.provide(key, min_interval=10.0)
        assert len(rpcs) == first
        # ...but after the floor elapses, the change re-provides.
        t, fp, accepted = d1._last_provide[key]
        d1._last_provide[key] = (t - 0.6, fp, accepted)
        await d1.provide(key, min_interval=10.0)
        assert len(rpcs) > first
        await h2.close()
    finally:
        for h in (boot_host, h1):
            await h.close()


async def test_find_providers_keeps_walking_past_dead_closest():
    """An all-failed alpha round is NOT steady state: the lookup must keep
    walking toward live record holders instead of breaking after one
    round (the crashed-closest-peers case)."""
    boot_host, boot_dht = await _mknode()
    addr = f"127.0.0.1:{boot_host.listen_port}"
    h1, d1 = await _mknode(bootstrap=addr)   # provider
    h2, d2 = await _mknode(bootstrap=addr)   # searcher
    dead = []
    try:
        key = namespace_key()
        await d1.provide(key)
        # Poison the searcher's routing table with dead contacts so its
        # closest candidates all fail before it reaches live nodes.
        from crowdllama_tpu.net.host import Contact

        for i in range(3):
            c = Contact(peer_id=f"{'%040x' % (i + 1)}", host="127.0.0.1",
                        port=1)  # nothing listens on port 1
            d2.table.update(c)
            dead.append(c)
        providers = await d2.find_providers(key)
        ids = {c.peer_id for c in providers}
        assert h1.peer_id in ids, "lookup stopped at the dead closest peers"
    finally:
        for h in (boot_host, h1, h2):
            await h.close()


async def test_dht_find_peer():
    boot_host, _ = await _mknode()
    addr = f"127.0.0.1:{boot_host.listen_port}"
    h1, d1 = await _mknode(bootstrap=addr)
    h2, d2 = await _mknode(bootstrap=addr)
    try:
        c = await d2.find_peer(h1.peer_id)
        assert c is not None and c.port == h1.listen_port
    finally:
        for h in (boot_host, h1, h2):
            await h.close()


async def test_metadata_fetch_and_discover():
    boot_host, _ = await _mknode()
    addr = f"127.0.0.1:{boot_host.listen_port}"
    worker_host, worker_dht = await _mknode(bootstrap=addr)
    consumer_host, consumer_dht = await _mknode(bootstrap=addr)

    resource = Resource(
        peer_id=worker_host.peer_id,
        supported_models=["tinyllama-1.1b"],
        tokens_throughput=100.0,
        worker_mode=True,
        accelerator="tpu-v5e",
        tpu_chip_count=1,
    )
    resource.touch()

    async def serve_metadata(stream):
        stream.writer.write(resource.to_json())
        await stream.writer.drain()
        stream.writer.write_eof()

    worker_host.set_stream_handler(METADATA_PROTOCOL, serve_metadata)
    try:
        await worker_dht.provide(namespace_key())
        # Direct metadata fetch
        got = await request_peer_metadata(consumer_host, worker_host.contact)
        assert got.supported_models == ["tinyllama-1.1b"]
        # Full discovery path
        found = await discover_peers(consumer_host, consumer_dht)
        assert any(r.peer_id == worker_host.peer_id for r in found)
        # Stale metadata is rejected
        resource.last_updated -= 7200
        found = await discover_peers(consumer_host, consumer_dht)
        assert not any(r.peer_id == worker_host.peer_id for r in found)
    finally:
        for h in (boot_host, worker_host, consumer_host):
            await h.close()


async def test_advertiser_loop_and_reconnect():
    boot_host, boot_dht = await _mknode()
    addr = f"127.0.0.1:{boot_host.listen_port}"
    h1, d1 = await _mknode(bootstrap=addr)
    try:
        adv = Advertiser(d1, Intervals(advertise=0.1))
        adv.start()
        await asyncio.sleep(0.35)
        assert boot_dht.providers.get(namespace_key())
        # Simulate routing-table loss; advertiser must re-bootstrap
        d1.table = type(d1.table)(d1.node_id)
        assert not d1.is_connected()
        await asyncio.sleep(0.3)
        assert d1.is_connected()
        await adv.stop()
    finally:
        await boot_host.close()
        await h1.close()


def test_addr_classification():
    from crowdllama_tpu.net.host import _addr_class

    assert _addr_class("127.0.0.1") == "loopback"
    assert _addr_class("::1") == "loopback"
    assert _addr_class("10.1.2.3") == "private"
    assert _addr_class("192.168.0.9") == "private"
    assert _addr_class("169.254.0.1") == "private"
    assert _addr_class("8.8.8.8") == "public"
    assert _addr_class("example.com") == "hostname"


async def test_inbound_addr_class_stats():
    """The accepting host classifies inbound peers (ref dht.go:279-321)."""
    from crowdllama_tpu.utils.crypto_compat import Ed25519PrivateKey

    from crowdllama_tpu.net.host import Host

    a = Host(Ed25519PrivateKey.generate(), listen_host="127.0.0.1")
    b = Host(Ed25519PrivateKey.generate(), listen_host="127.0.0.1")
    await a.start()
    await b.start()
    a.set_stream_handler("/t/1", lambda s: _echo(s))
    try:
        s = await b.new_stream(a.contact, "/t/1")
        s.close()
        # Deduped by peer: a second stream from the same peer doesn't
        # inflate the count.
        s2 = await b.new_stream(a.contact, "/t/1")
        s2.close()
        assert a.stats_by_addr_class == {"loopback": 1}
    finally:
        await a.close()
        await b.close()


async def _echo(stream):
    stream.writer.write(b"ok")
    await stream.writer.drain()
    stream.writer.write_eof()


async def test_kad_rpc_stream_pool_reuse():
    """Sequential RPCs to the same peer ride ONE pooled stream: the
    steady-state control plane must not pay a TCP + signed-hello
    handshake per exchange (measured at ~214 streams/s across a
    16-worker swarm before pooling)."""
    boot_host, boot_dht = await _mknode()
    addr = f"127.0.0.1:{boot_host.listen_port}"
    h1, d1 = await _mknode(bootstrap=addr)
    try:
        contact = boot_host.contact
        before = h1.stats["streams_out"]
        for _ in range(5):
            resp = await d1._rpc(contact, {"op": "ping"})
            assert resp and resp.get("ok")
        assert h1.stats["streams_out"] - before <= 1, (
            "pings opened a fresh stream each — the RPC pool is not "
            "reusing streams")

        # Stale pooled stream (remote closed it): the RPC retries on a
        # fresh dial instead of failing, and the peer is NOT evicted.
        for s, _ts in d1._rpc_pool._pools.get(boot_host.peer_id, []):
            s.close()
        resp = await d1._rpc(contact, {"op": "ping"})
        assert resp and resp.get("ok")
        assert any(c.peer_id == boot_host.peer_id
                   for c in d1.table.contacts()), "peer was evicted"
    finally:
        for h in (boot_host, h1):
            await h.close()


async def test_pooled_metadata_rpc():
    """Health probes fetch metadata over the pooled KAD op when the peer
    serves it, with the legacy read-to-EOF stream as fallback."""
    boot_host, boot_dht = await _mknode()
    addr = f"127.0.0.1:{boot_host.listen_port}"
    h1, d1 = await _mknode(bootstrap=addr)
    h2, d2 = await _mknode(bootstrap=addr)
    try:
        resource = Resource(peer_id=h1.peer_id,
                            supported_models=["tinyllama-1.1b"],
                            worker_mode=True)
        resource.touch()
        d1.metadata_provider = lambda: resource.to_json()
        raw = await d2.request_metadata(h1.contact)
        assert raw is not None
        got = Resource.from_json(raw.encode())
        assert got.supported_models == ["tinyllama-1.1b"]
        # A peer without the op (provider unset) yields None -> fallback.
        assert await d1.request_metadata(h2.contact) is None
    finally:
        for h in (boot_host, h1, h2):
            await h.close()
