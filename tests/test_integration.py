"""Full-topology integration test, the analog of the reference's
TestFullIntegration (/root/reference/test/integration_test.go): DHT bootstrap
node + worker peer (FakeEngine at the engine seam) + consumer peer + gateway,
all real sockets on loopback with compressed intervals; drive through HTTP
and validate the Ollama-shaped reply."""

import asyncio
import json

import aiohttp
from crowdllama_tpu.utils.crypto_compat import Ed25519PrivateKey

from crowdllama_tpu.config import Configuration, Intervals
from crowdllama_tpu.engine.engine import FakeEngine
from crowdllama_tpu.gateway.gateway import Gateway
from crowdllama_tpu.net.discovery import new_host_and_dht
from crowdllama_tpu.peer.peer import Peer


def _cfg(bootstrap, **kw):
    cfg = Configuration(
        listen_host="127.0.0.1",
        bootstrap_peers=[bootstrap],
        intervals=Intervals.default(),  # test mode: compressed
    )
    for k, v in kw.items():
        setattr(cfg, k, v)
    return cfg


async def _wait_for(cond, timeout=20.0, interval=0.1, what="condition"):
    """Poll-with-deadline, the reference's synchronization style
    (integration_test.go:421-488)."""
    loop = asyncio.get_running_loop()
    deadline = loop.time() + timeout
    while loop.time() < deadline:
        if cond():
            return
        await asyncio.sleep(interval)
    raise AssertionError(f"timed out waiting for {what}")


async def _topology():
    boot_host, boot_dht = await new_host_and_dht(
        Ed25519PrivateKey.generate(), listen_host="127.0.0.1")
    bootstrap = f"127.0.0.1:{boot_host.listen_port}"

    worker = Peer(Ed25519PrivateKey.generate(), _cfg(bootstrap),
                  engine=FakeEngine(models=["tiny-test"]), worker_mode=True)
    await worker.start()

    consumer = Peer(Ed25519PrivateKey.generate(), _cfg(bootstrap),
                    engine=FakeEngine(models=[]), worker_mode=False)
    await consumer.start()

    gateway = Gateway(consumer, port=0, host="127.0.0.1")
    await gateway.start()
    gw_port = gateway._runner.addresses[0][1]

    async def teardown():
        await gateway.stop()
        await consumer.stop()
        await worker.stop()
        await boot_host.close()

    return worker, consumer, gateway, gw_port, teardown


async def test_full_integration_chat():
    worker, consumer, gateway, gw_port, teardown = await _topology()
    try:
        # Mutual discovery: consumer's manager must see the worker as healthy.
        await _wait_for(
            lambda: any(
                p.peer_id == worker.peer_id
                for p in consumer.peer_manager.get_healthy_peers()
            ),
            what="consumer discovering worker",
        )

        base = f"http://127.0.0.1:{gw_port}"
        async with aiohttp.ClientSession() as s:
            # Non-streaming chat (the reference's only mode).
            body = {"model": "tiny-test",
                    "messages": [{"role": "user", "content": "hello swarm"}]}
            async with s.post(f"{base}/api/chat", json=body) as resp:
                assert resp.status == 200, await resp.text()
                d = await resp.json()
            assert d["model"] == "tiny-test"
            assert d["done"] is True
            assert d["message"]["role"] == "assistant"
            assert "hello swarm" in d["message"]["content"]
            assert d["worker_id"] == worker.peer_id
            assert d["total_duration"] >= 0

            # Streaming chat (NDJSON superset).
            body["stream"] = True
            async with s.post(f"{base}/api/chat", json=body) as resp:
                assert resp.status == 200
                assert resp.headers["Content-Type"].startswith("application/x-ndjson")
                lines = [json.loads(l) for l in (await resp.text()).splitlines()]
            assert lines[-1]["done"] is True
            assert all(not l["done"] for l in lines[:-1])
            text = "".join(l["message"]["content"] for l in lines)
            assert "hello swarm" in text

            # /api/generate
            async with s.post(f"{base}/api/generate",
                              json={"model": "tiny-test", "prompt": "ping"}) as resp:
                assert resp.status == 200
                d = await resp.json()
            assert "ping" in d["response"]

            # /api/health shows the worker with TPU-era fields
            async with s.get(f"{base}/api/health") as resp:
                h = await resp.json()
            assert h["status"] == "ok"
            assert worker.peer_id in h["workers"]
            w = h["workers"][worker.peer_id]
            assert w["is_healthy"] is True
            assert w["supported_models"] == ["tiny-test"]

            # /api/tags lists the model
            async with s.get(f"{base}/api/tags") as resp:
                tags = await resp.json()
            assert any(m["name"] == "tiny-test" for m in tags["models"])

            # Unknown model -> 503 with error body
            async with s.post(f"{base}/api/chat", json={
                "model": "nope", "messages": [{"role": "user", "content": "x"}]
            }) as resp:
                assert resp.status == 503

            # Malformed bodies -> 400
            async with s.post(f"{base}/api/chat", data=b"{not json") as resp:
                assert resp.status == 400
            async with s.post(f"{base}/api/chat", json={"model": "m"}) as resp:
                assert resp.status == 400
    finally:
        await teardown()


async def test_worker_death_detected():
    worker, consumer, gateway, gw_port, teardown = await _topology()
    try:
        await _wait_for(
            lambda: any(
                p.peer_id == worker.peer_id
                for p in consumer.peer_manager.get_healthy_peers()
            ),
            what="consumer discovering worker",
        )
        wid = worker.peer_id
        await worker.stop()
        # Health machine (3 strikes / stale eviction) must drop the worker.
        await _wait_for(
            lambda: not any(
                p.peer_id == wid for p in consumer.peer_manager.get_healthy_peers()
            ),
            timeout=40.0,
            what="worker eviction after death",
        )
        # Routing now fails cleanly.
        async with aiohttp.ClientSession() as s:
            async with s.post(f"http://127.0.0.1:{gw_port}/api/chat", json={
                "model": "tiny-test",
                "messages": [{"role": "user", "content": "x"}],
            }) as resp:
                assert resp.status == 503
    finally:
        await teardown()


async def test_ollama_surface_endpoints():
    """/api/version, /api/show, /api/ps complete the Ollama client surface."""
    worker, consumer, gateway, gw_port, teardown = await _topology()
    try:
        await _wait_for(
            lambda: any(
                p.peer_id == worker.peer_id
                for p in consumer.peer_manager.get_healthy_peers()
            ),
            what="consumer discovering worker",
        )
        base = f"http://127.0.0.1:{gw_port}"
        async with aiohttp.ClientSession() as s:
            async with s.get(f"{base}/api/version") as resp:
                assert resp.status == 200
                assert (await resp.json())["version"]

            async with s.get(f"{base}/api/ps") as resp:
                ps = await resp.json()
            assert any(m["model"] == "tiny-test" and m["workers"] == 1
                       for m in ps["models"])

            # Registry model: full details.
            async with s.post(f"{base}/api/show",
                              json={"model": "tiny-test"}) as resp:
                assert resp.status == 200
                d = await resp.json()
            assert d["details"]["family"] == "llama"
            assert d["model_info"]["general.parameter_count"] > 0
            assert worker.peer_id in d["workers_serving"]

            # Unknown model -> 404.
            async with s.post(f"{base}/api/show",
                              json={"model": "nope"}) as resp:
                assert resp.status == 404
    finally:
        await teardown()


async def test_openai_compat_surface():
    """The /v1 OpenAI-compatible endpoints (Ollama serves the same
    aliases): chat completions (non-stream + SSE stream), legacy
    completions, model list, embeddings — stock openai clients work."""
    worker, consumer, gateway, gw_port, teardown = await _topology()
    try:
        await _wait_for(
            lambda: any(p.peer_id == worker.peer_id
                        for p in consumer.peer_manager.get_healthy_peers()),
            what="consumer discovering worker")
        base = f"http://127.0.0.1:{gw_port}"
        async with aiohttp.ClientSession() as s:
            # Non-streaming chat completion.
            body = {"model": "tiny-test",
                    "messages": [{"role": "user", "content": "hello v1"}]}
            async with s.post(f"{base}/v1/chat/completions",
                              json=body) as resp:
                assert resp.status == 200, await resp.text()
                d = await resp.json()
            assert d["object"] == "chat.completion"
            assert d["id"].startswith("chatcmpl-")
            ch = d["choices"][0]
            assert ch["message"]["role"] == "assistant"
            assert "hello v1" in ch["message"]["content"]
            assert ch["finish_reason"] in ("stop", "length")
            assert d["usage"]["total_tokens"] == (
                d["usage"]["prompt_tokens"] + d["usage"]["completion_tokens"])

            # Streaming chat completion (SSE + [DONE] terminator).
            body["stream"] = True
            async with s.post(f"{base}/v1/chat/completions",
                              json=body) as resp:
                assert resp.status == 200
                assert resp.headers["Content-Type"].startswith(
                    "text/event-stream")
                raw = await resp.text()
            events = [line[len("data: "):] for line in raw.splitlines()
                      if line.startswith("data: ")]
            assert events[-1] == "[DONE]"
            chunks = [json.loads(e) for e in events[:-1]]
            assert all(c["object"] == "chat.completion.chunk"
                       for c in chunks)
            assert len({c["id"] for c in chunks}) == 1  # stable id
            # First-chunk contract: role arrives on the opening delta.
            assert chunks[0]["choices"][0]["delta"]["role"] == "assistant"
            text = "".join(c["choices"][0]["delta"].get("content", "")
                           for c in chunks)
            assert "hello v1" in text
            assert chunks[-1]["choices"][0]["finish_reason"] in (
                "stop", "length")

            # Content-parts messages (framework-emitted shape) and null
            # params must work, not 500.
            async with s.post(f"{base}/v1/chat/completions", json={
                "model": "tiny-test", "temperature": None,
                "messages": [{"role": "user", "content": [
                    {"type": "text", "text": "parts "},
                    {"type": "text", "text": "work"}]}]}) as resp:
                assert resp.status == 200, await resp.text()
                d = await resp.json()
            assert "parts work" in d["choices"][0]["message"]["content"]

            # Wrong-typed params: OpenAI-shaped 400, not an aiohttp 500.
            async with s.post(f"{base}/v1/chat/completions", json={
                "model": "tiny-test", "n": "two",
                "messages": [{"role": "user",
                              "content": "x"}]}) as resp:
                assert resp.status == 400
                assert (await resp.json())["error"]["type"] == (
                    "invalid_request_error")

            # Legacy completions.
            async with s.post(f"{base}/v1/completions",
                              json={"model": "tiny-test",
                                    "prompt": "ping"}) as resp:
                assert resp.status == 200
                d = await resp.json()
            assert d["object"] == "text_completion"
            assert "ping" in d["choices"][0]["text"]

            # Model list.
            async with s.get(f"{base}/v1/models") as resp:
                assert resp.status == 200
                d = await resp.json()
            assert d["object"] == "list"
            assert any(m["id"] == "tiny-test" for m in d["data"])

            # Embeddings.
            async with s.post(f"{base}/v1/embeddings",
                              json={"model": "tiny-test",
                                    "input": ["a", "b"]}) as resp:
                assert resp.status == 200
                d = await resp.json()
            assert d["object"] == "list" and len(d["data"]) == 2
            assert d["data"][1]["index"] == 1
            assert isinstance(d["data"][0]["embedding"], list)

            # OpenAI-shaped errors.
            async with s.post(f"{base}/v1/chat/completions",
                              json={"model": "no-such",
                                    "messages": [
                                        {"role": "user",
                                         "content": "x"}]}) as resp:
                assert resp.status == 503
                d = await resp.json()
            assert d["error"]["type"] == "server_error"
            async with s.post(f"{base}/v1/chat/completions",
                              json={"model": "tiny-test", "n": 2,
                                    "messages": [
                                        {"role": "user",
                                         "content": "x"}]}) as resp:
                assert resp.status == 400
    finally:
        await teardown()


async def test_seeded_generation_reproducible_through_gateway():
    """Request ``seed`` is honored end-to-end (VERDICT r2 missing #5):
    identical seeded SAMPLED requests through the full HTTP → gateway →
    stream → JaxEngine path return identical text; a different seed
    diverges.  The reference inherits this from Ollama's seed option;
    proto/llama_v1.proto carries the field, gateway.py:379 parses it, and
    the scheduler folds it into the slot's private sampling stream."""
    from crowdllama_tpu.engine.engine import JaxEngine

    boot_host, _ = await new_host_and_dht(
        Ed25519PrivateKey.generate(), listen_host="127.0.0.1")
    bootstrap = f"127.0.0.1:{boot_host.listen_port}"

    engine = JaxEngine(_cfg(bootstrap, model="tiny-test"),
                       max_context_length=256, warmup=False)
    await engine.start()
    worker = Peer(Ed25519PrivateKey.generate(),
                  _cfg(bootstrap, model="tiny-test"),
                  engine=engine, worker_mode=True)
    await worker.start()
    consumer = Peer(Ed25519PrivateKey.generate(), _cfg(bootstrap),
                    engine=FakeEngine(models=[]), worker_mode=False)
    await consumer.start()
    gateway = Gateway(consumer, port=0, host="127.0.0.1")
    await gateway.start()
    gw_port = gateway._runner.addresses[0][1]

    try:
        await _wait_for(
            lambda: consumer.peer_manager.find_best_worker("tiny-test")
            is not None,
            what="consumer discovering JaxEngine worker",
        )

        async def ask(seed):
            body = {
                "model": "tiny-test", "stream": False,
                "options": {"temperature": 1.0, "num_predict": 12,
                            "seed": seed},
                "messages": [{"role": "user", "content": "tell me a story"}],
            }
            async with aiohttp.ClientSession() as s:
                async with s.post(f"http://127.0.0.1:{gw_port}/api/chat",
                                  json=body) as resp:
                    assert resp.status == 200, await resp.text()
                    d = await resp.json()
                    return d["message"]["content"]

        a = await ask(1234)
        b = await ask(1234)
        c = await ask(99)
        assert a == b, f"same seed diverged: {a!r} vs {b!r}"
        # Random-init tiny model at temperature 1.0: different seeds
        # agreeing on all 12 tokens would be astronomically unlikely.
        assert a != c, "different seeds produced identical output"
    finally:
        await gateway.stop()
        await consumer.stop()
        await worker.stop()
        await engine.stop()
        await boot_host.close()


async def test_metrics_endpoint():
    """GET /metrics: Prometheus text exposition with request counters and
    swarm worker gauges (no reference counterpart — SURVEY §5 notes the
    reference has no metrics endpoint)."""
    worker, consumer, gateway, gw_port, teardown = await _topology()
    try:
        await _wait_for(
            lambda: any(p.peer_id == worker.peer_id
                        for p in consumer.peer_manager.get_healthy_peers()),
            what="discovery",
        )
        async with aiohttp.ClientSession() as s:
            body = {"model": "tiny-test", "stream": False,
                    "messages": [{"role": "user", "content": "hi"}]}
            async with s.post(f"http://127.0.0.1:{gw_port}/api/chat",
                              json=body) as resp:
                assert resp.status == 200
            # One STREAMED request feeds the time-to-first-frame histogram.
            async with s.post(f"http://127.0.0.1:{gw_port}/api/chat",
                              json={**body, "stream": True}) as resp:
                assert resp.status == 200
                await resp.read()
            async with s.get(f"http://127.0.0.1:{gw_port}/metrics") as resp:
                assert resp.status == 200
                text = await resp.text()
        assert ('crowdllama_gateway_requests_total{path="/api/chat",'
                'status="200"} 2') in text
        assert "crowdllama_workers_healthy 1" in text
        assert "crowdllama_worker_load{" in text
        assert "crowdllama_gateway_request_seconds_total{" in text
        assert "crowdllama_gateway_ttfb_seconds_count 1" in text
        assert 'crowdllama_gateway_ttfb_seconds_bucket{le="+Inf"} 1' in text
        # Round-5 series: stream-pool reuse, affinity, per-path host
        # counters, and the rejected counter split out of streams_total.
        assert "crowdllama_gateway_stream_pool_hits_total" in text
        assert "crowdllama_gateway_stream_pool_misses_total" in text
        assert "crowdllama_gateway_affinity_hits_total" in text
        assert "crowdllama_host_rejected_total" in text
        assert 'crowdllama_host_streams_total{kind="rejected"}' not in text
    finally:
        await teardown()


async def test_gateway_options_stop_parsed():
    """options.stop reaches the worker through the REAL gateway parse
    path, in both Ollama spellings (string and list): FakeEngine echoes
    the prompt, so a stop sequence drawn from the prompt truncates the
    echo."""
    worker, consumer, gateway, gw_port, teardown = await _topology()
    try:
        await _wait_for(
            lambda: any(p.peer_id == worker.peer_id
                        for p in consumer.peer_manager.get_healthy_peers()),
            what="discovery",
        )
        async with aiohttp.ClientSession() as s:
            for stop_val in ("wor", ["wor"]):
                body = {"model": "tiny-test", "stream": False,
                        "options": {"stop": stop_val},
                        "messages": [{"role": "user",
                                      "content": "hello world"}]}
                async with s.post(f"http://127.0.0.1:{gw_port}/api/chat",
                                  json=body) as resp:
                    assert resp.status == 200, await resp.text()
                    d = await resp.json()
                # The chat flattens to "user: hello world\nassistant:";
                # the echo must truncate just before "wor".
                full = "echo: user: hello world\nassistant:"
                assert d["message"]["content"] == full[:full.find("wor")]
                assert d["done_reason"] == "stop"
    finally:
        await teardown()


async def test_pooled_inference_stream_reuse_and_stale_redial():
    """Sequential chats reuse ONE pooled inference stream (no per-request
    handshake), and a stale pooled entry (worker closed it) is detected
    and redialed transparently instead of failing the request."""
    worker, consumer, gateway, gw_port, teardown = await _topology()
    try:
        await _wait_for(
            lambda: consumer.peer_manager.find_best_worker("tiny-test")
            is not None, what="worker discovery")
        from crowdllama_tpu.core.protocol import INFERENCE_PROTOCOL

        url = f"http://127.0.0.1:{gw_port}/api/chat"
        body = {"model": "tiny-test",
                "messages": [{"role": "user", "content": "hi"}]}

        def inference_streams_in() -> int:
            # Worker-side inbound count for the inference protocol only:
            # host-wide streams_out on the consumer would race with its
            # background control-plane dials.
            return worker.host.stats_by_protocol.get(INFERENCE_PROTOCOL, 0)

        async with aiohttp.ClientSession() as s:
            async with s.post(url, json=body) as resp:
                assert resp.status == 200
            in0 = inference_streams_in()
            hits0 = gateway._stream_pool.hits
            for _ in range(3):
                async with s.post(url, json=body) as resp:
                    assert resp.status == 200
            assert gateway._stream_pool.hits - hits0 == 3
            assert inference_streams_in() == in0, (
                "pooled requests must not open new inference streams")

            # Stale-redial path: feed EOF into the pooled streams' READER
            # side so the pool's is_closing() pre-check still passes, the
            # write succeeds, and the subsequent read fails — exactly the
            # worker-went-away shape the redial branch exists for (a
            # local transport abort would be caught by the pre-check and
            # never exercise it).  pause_reading first: the worker's
            # reply to the stale write would otherwise hit asyncio's
            # feed_data-after-feed_eof assertion on the live transport.
            severed = []
            for pool in list(gateway._stream_pool._pools.values()):
                for st, _ts in pool:
                    st.writer._w.transport.pause_reading()
                    st.reader._r.feed_eof()
                    severed.append(st)
            async with s.post(url, json=body) as resp:
                assert resp.status == 200
                d = await resp.json()
                assert d["done"] is True
            assert inference_streams_in() > in0, (
                "the stale roundtrip must have redialed a fresh stream")
            for st in severed:
                st.writer._w.transport.abort()
    finally:
        await teardown()


async def test_prefix_affinity_routes_conversation_to_same_worker():
    """Multi-turn conversations (same leading message, growing tail) must
    land on ONE worker so its prefix cache pays; a dead affinity worker
    falls back to scoring."""
    boot_host, _ = await new_host_and_dht(
        Ed25519PrivateKey.generate(), listen_host="127.0.0.1")
    bootstrap = f"127.0.0.1:{boot_host.listen_port}"
    workers = [Peer(Ed25519PrivateKey.generate(), _cfg(bootstrap),
                    engine=FakeEngine(models=["tiny-test"]),
                    worker_mode=True) for _ in range(2)]
    for w in workers:
        await w.start()
    consumer = Peer(Ed25519PrivateKey.generate(), _cfg(bootstrap),
                    engine=FakeEngine(models=[]), worker_mode=False)
    await consumer.start()
    gateway = Gateway(consumer, port=0, host="127.0.0.1")
    await gateway.start()
    gw_port = gateway._runner.addresses[0][1]
    try:
        await _wait_for(
            lambda: len({p.peer_id for p in
                         consumer.peer_manager.get_healthy_peers()
                         if p.is_worker}) == 2,
            what="both workers discovered")

        def body(turn: int) -> dict:
            msgs = [{"role": "system", "content": "You are a helpful bot."}]
            for t in range(turn + 1):
                msgs.append({"role": "user", "content": f"question {t}"})
            return {"model": "tiny-test", "messages": msgs, "stream": False}

        async with aiohttp.ClientSession() as s:
            url = f"http://127.0.0.1:{gw_port}/api/chat"
            hit: list[str] = []
            for turn in range(6):
                async with s.post(url, json=body(turn)) as resp:
                    assert resp.status == 200
                    hit.append((await resp.json())["worker_id"])
            assert len(set(hit)) == 1, (
                f"conversation turns scattered across workers: {hit}")
            assert gateway._affinity_hits >= 5

            # The affinity worker dies: the conversation fails over.
            dead = hit[0]
            for w in workers:
                if w.peer_id == dead:
                    await w.stop()
            await _wait_for(
                lambda: all(p.peer_id != dead for p in
                            consumer.peer_manager.get_healthy_peers()),
                timeout=40.0, what="dead worker evicted")
            async with s.post(url, json=body(6)) as resp:
                assert resp.status == 200
                assert (await resp.json())["worker_id"] != dead
    finally:
        await gateway.stop()
        await consumer.stop()
        for w in workers:
            try:
                await w.stop()
            except Exception:
                pass
        await boot_host.close()


async def test_trace_propagates_across_two_worker_swarm():
    """Tentpole acceptance: a routed request through a 2-worker swarm
    shows up with the SAME trace id in the gateway's and the serving
    worker's /debug/trace, worker spans are children of the gateway root
    span, and the gateway's phase spans account for the request wall
    clock to within 20%."""
    from crowdllama_tpu.obs.http import ObsServer

    boot_host, _ = await new_host_and_dht(
        Ed25519PrivateKey.generate(), listen_host="127.0.0.1")
    bootstrap = f"127.0.0.1:{boot_host.listen_port}"

    # delay makes engine compute dominate HTTP/loopback overhead, so the
    # io_wait span (which envelopes the worker's work) carries the wall
    # clock and the 20% bound is insensitive to scheduler jitter.
    workers, obs_servers = [], []
    for _ in range(2):
        w = Peer(Ed25519PrivateKey.generate(), _cfg(bootstrap),
                 engine=FakeEngine(models=["tiny-test"], delay=0.25),
                 worker_mode=True)
        await w.start()
        workers.append(w)
        srv = ObsServer(w, port=0)
        await srv.start()
        obs_servers.append(srv)

    consumer = Peer(Ed25519PrivateKey.generate(), _cfg(bootstrap),
                    engine=FakeEngine(models=[]), worker_mode=False)
    await consumer.start()
    gateway = Gateway(consumer, port=0, host="127.0.0.1", trace_buffer=16)
    await gateway.start()
    gw_port = gateway._runner.addresses[0][1]

    try:
        await _wait_for(
            lambda: len(consumer.peer_manager.get_workers()) == 2,
            what="consumer discovering both workers")

        async with aiohttp.ClientSession() as s:
            body = {"model": "tiny-test", "stream": False,
                    "messages": [{"role": "user", "content": "trace me"}]}
            async with s.post(f"http://127.0.0.1:{gw_port}/api/chat",
                              json=body) as resp:
                assert resp.status == 200, await resp.text()
                served_by = (await resp.json())["worker_id"]

            async with s.get(
                    f"http://127.0.0.1:{gw_port}/debug/trace") as resp:
                assert resp.status == 200
                gw_dump = await resp.json()
        assert gw_dump["node"] == "gateway"
        assert gw_dump["capacity"] == 16
        gw_trace = gw_dump["traces"][-1]
        tid = gw_trace["trace_id"]
        assert len(tid) == 16 and gw_trace["done"]

        # The serving worker holds the same trace; the idle one does not.
        idx = next(i for i, w in enumerate(workers)
                   if w.peer_id == served_by)
        async with aiohttp.ClientSession() as s:
            async with s.get(f"http://127.0.0.1:{obs_servers[idx].port}"
                             f"/debug/trace") as resp:
                assert resp.status == 200
                wk_dump = await resp.json()
        wk_trace = next((t for t in wk_dump["traces"]
                         if t["trace_id"] == tid), None)
        assert wk_trace is not None, (
            f"trace {tid} missing from serving worker's ring buffer")
        other = obs_servers[1 - idx].peer.obs.trace
        assert other.get(tid) is None, "idle worker recorded the trace"

        # Span taxonomy + parentage.
        gw_spans = {sp["name"]: sp for sp in gw_trace["spans"]}
        assert {"route", "serde", "aead", "io_wait"} <= set(gw_spans)
        wk_spans = {sp["name"]: sp for sp in wk_trace["spans"]}
        assert {"worker_queue", "prefill", "decode_step",
                "stream_flush"} <= set(wk_spans)
        assert all(sp.get("parent") == "gateway"
                   for sp in wk_spans.values())

        # Phase accounting: gateway spans sum to the request wall clock
        # (trace total) within 20%; the worker's compute fits inside it.
        wall_us = gw_trace["total_us"]
        gw_sum = sum(sp["dur_us"] for sp in gw_trace["spans"])
        assert 0.8 * wall_us <= gw_sum <= 1.2 * wall_us, (
            f"gateway spans {gw_sum:.0f}us vs wall {wall_us:.0f}us")
        wk_sum = sum(sp["dur_us"] for sp in wk_trace["spans"])
        assert wk_sum <= 1.2 * wall_us, (
            f"worker spans {wk_sum:.0f}us exceed wall {wall_us:.0f}us")
    finally:
        await gateway.stop()
        await consumer.stop()
        for srv in obs_servers:
            await srv.stop()
        for w in workers:
            await w.stop()
        await boot_host.close()
