"""Unified ragged-paged batch (engine/paged.py ragged_* + scheduler
unified dispatch): chunked-ragged prefill + decode must be BYTE-identical
to the monolithic prefill path for the same prompt/seed — including while
other slots decode in the same dispatch, with a distilled spec draft
active, and across a mid-prefill draft-len retune.  bf16 pools make the
pool round-trip exact, so every assertion here is array_equal, not
allclose."""

import asyncio

import jax
import jax.numpy as jnp
import numpy as np

from crowdllama_tpu.engine.paged import PagedModelRunner
from crowdllama_tpu.models import transformer as T
from crowdllama_tpu.models.config import get_config

KEY = jax.random.PRNGKey(0)


def _mono_insert(runner, state, slot, prompt):
    first, ks, vs, plen = runner.prefill(prompt, 0.0, 1.0, KEY)
    state = runner.insert(state, slot, ks, vs, plen, first, 0.0, 1.0,
                          prompt_tokens=prompt)
    return first, state


def _ragged_insert(runner, state, slot, prompt, num_steps=1):
    """Drive one prompt through ragged_begin/step/finish; returns the
    first token, the new state, and the number of chunk dispatches."""
    job = runner.ragged_begin(prompt, slot, state=state)
    n = 0
    while not job.finished:
        _, state = runner.ragged_step(state, job, num_steps=num_steps)
        n += 1
    first, state = runner.ragged_finish(state, job, 0.0, 1.0, KEY)
    return first, state, n


def test_ragged_mixed_batch_matches_monolithic():
    """Decode slots keep advancing while a third slot chunk-prefills in
    the SAME dispatches, and every row — the concurrent decode rows, the
    ex-prefill slot's stream — is byte-identical to the monolithic
    sequence of the same events."""
    cfg = get_config("tiny-test", max_context_length=512)
    params = T.init_params(cfg, KEY, dtype=jnp.bfloat16)
    short = [[3, 1, 4, 1, 5, 9, 2, 6], [2, 7, 1, 8, 2, 8]]
    long_prompt = [int(x) % cfg.vocab_size for x in range(17, 17 + 200)]

    mr = PagedModelRunner(cfg, params=params, max_slots=4, max_seq=512,
                          page_size=32, mesh_spec="1")
    ms = mr.init_state()
    for slot, p in enumerate(short):
        _, ms = _mono_insert(mr, ms, slot, p)
    toks, ms = mr.decode_steps(ms, 4)
    mono_pre = np.asarray(toks)
    tL, ms = _mono_insert(mr, ms, 2, long_prompt)
    toks, ms = mr.decode_steps(ms, 4)
    mono_post = np.asarray(toks)

    rr = PagedModelRunner(cfg, params=params, max_slots=4, max_seq=512,
                          page_size=32, mesh_spec="1")
    rs = rr.init_state()
    for slot, p in enumerate(short):
        _, rs = _mono_insert(rr, rs, slot, p)
    toks, rs = rr.decode_steps(rs, 4)
    np.testing.assert_array_equal(np.asarray(toks), mono_pre)

    job = rr.ragged_begin(long_prompt, 2, state=rs)
    chunk_rows = []
    while not job.finished:
        toks, rs = rr.ragged_step(rs, job, num_steps=1)
        chunk_rows.append(np.asarray(toks))
    first, rs = rr.ragged_finish(rs, job, 0.0, 1.0, KEY)
    assert first == tL, (first, tL)
    toks, rs = rr.decode_steps(rs, 4)

    # Rows 0/1 of the chunk dispatches are the decode slots advancing —
    # they must continue the exact monolithic decode streams.
    ragged_rows = np.concatenate(
        [t[:, :2] for t in chunk_rows] + [np.asarray(toks)[:, :2]], axis=0)
    extra, ms = mr.decode_steps(ms, ragged_rows.shape[0] - 4)
    mono_rows = np.concatenate([mono_post[:, :2],
                                np.asarray(extra)[:, :2]], axis=0)
    np.testing.assert_array_equal(ragged_rows, mono_rows)
    # The ex-prefill slot's own decode stream matches too.
    np.testing.assert_array_equal(np.asarray(toks)[:4, 2], mono_post[:4, 2])


def test_ragged_multi_chunk_batching_and_prefix_reuse():
    """A 1200-token prompt needs ceil(1200/512)=3 chunk dispatches; the
    result is byte-identical to one-shot monolithic prefill whether the
    chunks go one per dispatch or batched num_steps=2 per dispatch.  An
    abort mid-prefill leaves the indexed pages prefix-cached, so the
    resubmit reuses every completed full page."""
    cfg = get_config("tiny-test", max_context_length=2048)
    params = T.init_params(cfg, KEY, dtype=jnp.bfloat16)
    prompt = [int(x) % cfg.vocab_size for x in range(23, 23 + 1200)]

    mr = PagedModelRunner(cfg, params=params, max_slots=2, max_seq=2048,
                          page_size=64, mesh_spec="1")
    ms = mr.init_state()
    tM, ms = _mono_insert(mr, ms, 0, prompt)
    mtoks = np.asarray(mr.decode_steps(ms, 6)[0])[:, 0]

    rr = PagedModelRunner(cfg, params=params, max_slots=2, max_seq=2048,
                          page_size=64, mesh_spec="1")
    rs = rr.init_state()
    first, rs, n = _ragged_insert(rr, rs, 0, prompt)
    assert n == 3, n
    assert first == tM, (first, tM)
    rtoks = np.asarray(rr.decode_steps(rs, 6)[0])[:, 0]
    np.testing.assert_array_equal(rtoks, mtoks)

    # num_steps=2: two chunks per dispatch, same bytes.
    rs = rr.init_state()
    first, rs, n = _ragged_insert(rr, rs, 0, prompt, num_steps=2)
    assert n == 2, n
    assert first == tM
    np.testing.assert_array_equal(
        np.asarray(rr.decode_steps(rs, 6)[0])[:, 0], mtoks)

    # Abort after one chunk; resubmit reuses the completed full pages
    # ((512-1)//64 = 7 pages = 448 tokens) and still matches bytewise.
    rs = rr.init_state()
    job = rr.ragged_begin(prompt, 0, state=rs)
    _, rs = rr.ragged_step(rs, job, num_steps=1)
    rr.ragged_abort(job)
    assert rr._ragged_slot is None
    reused0 = rr.prefix_tokens_reused
    job = rr.ragged_begin(prompt, 1, state=rs)
    assert rr.prefix_tokens_reused - reused0 >= 448
    while not job.finished:
        _, rs = rr.ragged_step(rs, job, num_steps=1)
    first, rs = rr.ragged_finish(rs, job, 0.0, 1.0, KEY)
    assert first == tM
    np.testing.assert_array_equal(
        np.asarray(rr.decode_steps(rs, 6)[0])[:, 1], mtoks)


def _spec_decode_toks(runner, state, steps):
    """Unpack the spec runners' packed [K, 2+J, B] emission block for
    slot 0 (same walk the scheduler does)."""
    packed, state = runner.decode_steps(state, steps)
    toks = []
    for step in range(packed.shape[0]):
        n = int(packed[step, 0, 0])
        toks.extend(int(t) for t in packed[step, 1:1 + n, 0])
    return toks, state


def test_ragged_with_draft_spec_matches_monolithic():
    """Chunked-ragged prefill under a distilled-draft spec runner: the
    draft cache is filled at ragged_finish exactly as insert() fills it,
    so the verify stream is byte-identical to the monolithic path.  A
    small step_token_budget forces multi-chunk on a short prompt (and
    covers the budget plumbing)."""
    from crowdllama_tpu.engine.spec import DraftSpecPagedModelRunner

    cfg = get_config("tiny-test", max_context_length=256)
    params = T.init_params(cfg, KEY, dtype=jnp.bfloat16)
    prompt = [int(x) % cfg.vocab_size for x in range(5, 5 + 150)]
    kw = dict(draft_cfg=cfg, draft_params=params, draft_len=3,
              max_slots=2, max_seq=256, page_size=32, mesh_spec="1",
              step_token_budget=96)

    mspec = DraftSpecPagedModelRunner(cfg, params=params, **kw)
    assert mspec.ragged_chunk == 64, mspec.ragged_chunk
    ms = mspec.init_state()
    tM, ms = _mono_insert(mspec, ms, 0, prompt)
    mono, ms = _spec_decode_toks(mspec, ms, 6)

    rspec = DraftSpecPagedModelRunner(cfg, params=params, **kw)
    rs = rspec.init_state()
    first, rs, n = _ragged_insert(rspec, rs, 0, prompt)
    assert n == 3, n  # ceil(150/64)
    assert first == tM, (first, tM)
    rag, rs = _spec_decode_toks(rspec, rs, 6)
    assert rag == mono, (rag, mono)
    # draft == main params: the draft cache must be warm enough to accept
    # beyond one token per dispatch (the whole point of the draft).
    assert len(rag) > 6, rag


def test_ragged_across_mid_prefill_retune():
    """An adaptive-k retune landing BETWEEN chunk dispatches (speculation
    is paused batch-wide during ragged prefill, so that is the only place
    one can land) must not change a single emitted byte."""
    from crowdllama_tpu.engine.spec import SpecPagedModelRunner

    cfg = get_config("tiny-test", max_context_length=256)
    params = T.init_params(cfg, KEY, dtype=jnp.bfloat16)
    prompt = [5, 9] * 75  # repetitive: the bigram proposer will accept
    kw = dict(max_slots=2, max_seq=256, page_size=32, mesh_spec="1",
              draft_len=3, step_token_budget=96)

    mspec = SpecPagedModelRunner(cfg, params=params, **kw)
    ms = mspec.init_state()
    tM, ms = _mono_insert(mspec, ms, 0, prompt)
    mono, ms = _spec_decode_toks(mspec, ms, 6)

    rspec = SpecPagedModelRunner(cfg, params=params, **kw)
    rs = rspec.init_state()
    job = rspec.ragged_begin(prompt, 0, state=rs)
    retunes = [0, 2, 3]  # pause, shrink, restore — one per chunk gap
    while not job.finished:
        rspec.set_draft_len(retunes.pop(0) if retunes else 3)
        _, rs = rspec.ragged_step(rs, job, num_steps=1)
    rspec.set_draft_len(3)
    first, rs = rspec.ragged_finish(rs, job, 0.0, 1.0, KEY)
    assert first == tM, (first, tM)
    rag, rs = _spec_decode_toks(rspec, rs, 6)
    assert rag == mono, (rag, mono)


async def test_ragged_scheduler_streams_identical():
    """End to end: the scheduler's unified ragged admission must produce
    the same token streams as the legacy chunked-prefill path, populate
    the new gauges, and observe the chunk histogram."""
    from crowdllama_tpu.engine.scheduler import DONE, GenRequest, Scheduler
    from crowdllama_tpu.obs.metrics import ENGINE_TELEMETRY

    cfg = get_config("tiny-test", max_context_length=2048)
    params = T.init_params(cfg, KEY, dtype=jnp.bfloat16)

    async def run_once(ragged):
        runner = PagedModelRunner(cfg, params=params, max_slots=4,
                                  max_seq=2048, page_size=64, mesh_spec="1")
        sched = Scheduler(runner, decode_chunk=4, ragged=ragged)
        sched.start()
        try:
            reqs = [
                GenRequest(prompt_ids=[3, 1, 4, 1, 5], max_tokens=12,
                           seed=7),
                GenRequest(prompt_ids=list(range(11, 11 + 900)),
                           max_tokens=12, seed=9),
                GenRequest(prompt_ids=[2, 7, 1, 8], max_tokens=12, seed=5),
            ]
            for r in reqs:
                await sched.submit(r)
            outs = []
            for r in reqs:
                toks = []
                while True:
                    tok, reason = await asyncio.wait_for(r.out.get(), 120)
                    if tok is DONE:
                        outs.append((toks, reason))
                        break
                    toks.append(tok)
            return outs, sched.telemetry_gauges(), sched.ragged_chunks
        finally:
            await sched.stop()

    a, gauges, chunks = await run_once(ragged=True)
    assert chunks >= 2, chunks  # the 900-token prompt alone needs 2
    assert gauges["prefill_chunk_slots"] == 0.0  # idle again when drained
    assert "step_token_budget_used" in gauges
    b, _, legacy_chunks = await run_once(ragged=False)
    assert legacy_chunks == 0
    for (ta, ra), (tb, rb) in zip(a, b):
        assert ra == rb, (ra, rb)
        assert ta == tb, (ta, tb)
    lines = [ln for ln in ENGINE_TELEMETRY.expose()
             if "prefill_chunk_seconds" in ln and "_count" in ln]
    assert lines and not lines[0].endswith(" 0"), lines
