"""Feature composition matrix (VERDICT r3 #7): every cell of
layout × kv_dtype × quantize × spec × mesh-kind either serves, falls back
LOUDLY, or errors — exactly as `crowdllama_tpu/engine/plan.py` (the
engine's real decision path) declares.

The oracle below restates the composition rules independently of plan.py,
so a rule change must be made twice deliberately (code + test) and the
README table regenerated (`python -m crowdllama_tpu.engine.plan`).
"""

import pytest

from crowdllama_tpu.config import Configuration
from crowdllama_tpu.engine.plan import (
    MESH_KINDS,
    render_markdown,
    resolve_serving_plan,
    sweep,
)

AXES = [
    (mesh_kind, mesh, layout, kv_dtype, quantize, spec)
    for mesh_kind, mesh in MESH_KINDS
    for layout in ("paged", "contiguous")
    for kv_dtype in ("bf16", "int8")
    for quantize in ("", "int8")
    for spec in ("", "ngram", "draft")
]


def expected(mesh_kind, layout, kv_dtype, spec):
    """Independent restatement of the matrix rules.

    Returns ("ok"|"fallback", runner_name) or ("error", None).
    Weight quantization composes with every cell (not part of the oracle).
    """
    # multihost-tp composes exactly like tp: leader-replicated dispatch
    # frames every runner surface, spec included.
    sharded_kv = mesh_kind in ("dp", "pp", "sp")  # axes the pool can't use
    if spec == "draft" and (layout != "paged" or sharded_kv):
        return ("error", None)  # draft speculation is paged-only
    if layout == "contiguous" or sharded_kv:
        # Effective layout is contiguous (paged falls back on dp/pp/sp).
        if spec == "ngram" and kv_dtype == "int8":
            return ("error", None)  # contiguous spec needs the bf16 cache
        if mesh_kind in ("pp", "sp"):
            if kv_dtype == "int8" or spec == "ngram":
                return ("error", None)
        runner = "SpecModelRunner" if spec == "ngram" else "ModelRunner"
        status = "fallback" if (layout == "paged" and sharded_kv) else "ok"
        return (status, runner)
    runner = {"ngram": "SpecPagedModelRunner",
              "draft": "DraftSpecPagedModelRunner",
              "": "PagedModelRunner"}[spec]
    return ("ok", runner)


@pytest.mark.parametrize(
    "mesh_kind,mesh,layout,kv_dtype,quantize,spec", AXES,
    ids=[f"{m}-{l}-{k}-{q or 'bf16'}-{s or 'nospec'}"
         for m, _, l, k, q, s in AXES])
def test_matrix_cell(mesh_kind, mesh, layout, kv_dtype, quantize, spec):
    want_status, want_runner = expected(mesh_kind, layout, kv_dtype, spec)
    try:
        cfg = Configuration.from_environment(
            kv_layout=layout, kv_dtype=kv_dtype, quantize=quantize,
            spec_decode=spec,
            spec_draft_model="tiny-test" if spec == "draft" else "",
            mesh_shape=mesh)
        plan = resolve_serving_plan(
            cfg, n_devices=8,
            n_processes=2 if mesh_kind == "multihost-tp" else 1)
    except ValueError:
        assert want_status == "error", (
            f"unexpected startup error for {mesh_kind}/{layout}/"
            f"{kv_dtype}/{spec}")
        return
    assert want_status != "error", (
        f"{mesh_kind}/{layout}/{kv_dtype}/{spec} must refuse, got {plan}")
    assert plan.runner == want_runner
    assert (plan.fallback) == (want_status == "fallback")
    if plan.fallback:
        # Loud: the note names the mesh and the fallback layout.
        assert plan.kv_layout == "contiguous" and plan.notes
    else:
        assert plan.kv_layout == layout
    assert plan.kv_dtype == kv_dtype and plan.quantize == quantize


@pytest.mark.parametrize("runner_name,mesh_spec,kv_dtype", [
    ("SpecModelRunner", "2x1x1x1x1", "bf16"),      # spec on dp2
    ("SpecPagedModelRunner", "2", "int8"),          # paged spec on tp2
    ("DraftSpecPagedModelRunner", "2", "bf16"),     # draft spec on tp2
])
def test_matrix_promises_construct_and_decode(runner_name, mesh_spec,
                                              kv_dtype):
    """Cells the matrix marks ✓ that no other suite constructs must really
    serve — a README promise that fails at runtime is exactly what this
    matrix exists to prevent."""
    import jax
    import jax.numpy as jnp

    from crowdllama_tpu.engine.spec import (
        DraftSpecPagedModelRunner,
        SpecModelRunner,
        SpecPagedModelRunner,
    )
    from crowdllama_tpu.models.config import get_config

    cls = {"SpecModelRunner": SpecModelRunner,
           "SpecPagedModelRunner": SpecPagedModelRunner,
           "DraftSpecPagedModelRunner": DraftSpecPagedModelRunner}[
        runner_name]
    cfg = get_config("tiny-test", max_context_length=128)
    kw = dict(max_slots=2, max_seq=128, mesh_spec=mesh_spec,
              draft_len=3)
    if cls is DraftSpecPagedModelRunner:
        kw.update(page_size=32, kv_dtype=kv_dtype,
                  draft_cfg=get_config("tiny-test", max_context_length=128))
    elif cls is SpecPagedModelRunner:
        kw.update(page_size=32, kv_dtype=kv_dtype)
    else:
        kw.update(dtype=jnp.float32)
    r = cls(cfg, **kw)
    st = r.init_state()
    prompt = [5, 9, 5, 9, 5]
    t, ks, vs, plen = r.prefill(prompt, 0.0, 1.0, jax.random.PRNGKey(0))
    st = r.insert(st, 0, ks, vs, plen, t, 0.0, 1.0, prompt_tokens=prompt)
    packed, st = r.decode_steps(st, 4)
    # [K, 2 + J, B]: count row + (pending + draft_len) emit rows + the
    # acceptance-source row (0 none / 1 prompt-echo / 2 generative).
    assert packed.shape[0] == 4 and packed.shape[1] == 1 + (1 + 3) + 1
    assert int(packed[0, 0, 0]) >= 1  # slot 0 emitted at least the pending


def test_sweep_covers_every_cell_and_renders():
    cells = list(sweep())
    assert len(cells) == len(AXES) == 144
    table = render_markdown()
    # Every outcome kind appears and the table has one row per cell.
    assert table.count("\n") == 145  # header + separator + 144 rows
    for marker in ("✓", "⚠", "✗"):
        assert marker in table
    # The v2 flip: multi-host serves the paged runner (was a ⚠ fallback).
    assert any(a["mesh_kind"] == "multihost-tp"
               and a["layout"] == "paged" and not a["spec"]
               and s == "ok" and p.runner == "PagedModelRunner"
               for a, (s, p) in cells if s != "error")
