"""Engine tests: runner decode state, continuous batching, streaming, and the
BaseMessage handler seam — on the tiny model, virtual CPU devices."""

import asyncio

import numpy as np
import pytest

from crowdllama_tpu.config import Configuration
from crowdllama_tpu.core.messages import create_generate_request, extract_generate_response
from crowdllama_tpu.engine.engine import FakeEngine, JaxEngine
from crowdllama_tpu.engine.tokenizer import ByteTokenizer, get_tokenizer


def _mkengine(**kw) -> JaxEngine:
    cfg = Configuration.from_environment()
    cfg.model = "tiny-test"
    cfg.model_path = ""
    cfg.max_batch_slots = kw.pop("slots", 4)
    cfg.max_context_length = 128
    cfg.mesh_shape = kw.pop("mesh", "2x1x2")
    return JaxEngine(cfg)


async def test_generate_streams_tokens():
    eng = _mkengine()
    await eng.start()
    try:
        chunks = []
        async for c in eng.generate("hello world", max_tokens=8, temperature=0.0):
            chunks.append(c)
        assert chunks[-1].done
        assert chunks[-1].completion_tokens <= 8
        assert chunks[-1].prompt_tokens == len(ByteTokenizer().encode("hello world"))
        # deterministic under greedy: same prompt -> same text
        text1 = "".join(c.text for c in chunks)
        chunks2 = [c async for c in eng.generate("hello world", max_tokens=8)]
        assert "".join(c.text for c in chunks2) == text1
    finally:
        await eng.stop()


async def test_concurrent_requests_batched():
    eng = _mkengine(slots=4)
    await eng.start()
    try:
        async def run(i):
            out = []
            async for c in eng.generate(f"prompt {i}", max_tokens=6, temperature=0.5):
                out.append(c)
            return out

        results = await asyncio.gather(*(run(i) for i in range(6)))  # > slots
        for out in results:
            assert out[-1].done
            assert out[-1].completion_tokens <= 6
        assert eng.scheduler.requests_served == 6
        assert eng.scheduler.load == 0.0  # all retired
    finally:
        await eng.stop()


async def test_handler_seam_roundtrip():
    eng = _mkengine()
    await eng.start()
    try:
        msg = create_generate_request("tiny-test", "abc", max_tokens=5)
        reply = await eng.handle(msg, worker_id="w1")
        resp = extract_generate_response(reply)
        assert resp.done
        assert resp.worker_id == "w1"
        assert resp.total_duration > 0
        assert resp.completion_tokens <= 5

        frames = []
        async for frame in eng.handle_streaming(msg, worker_id="w1"):
            frames.append(extract_generate_response(frame))
        assert frames[-1].done
        assert all(not f.done for f in frames[:-1])
    finally:
        await eng.stop()


async def test_prompt_too_long_rejected():
    eng = _mkengine()
    await eng.start()
    try:
        with pytest.raises(ValueError):
            async for _ in eng.generate("x" * 500, max_tokens=4):
                pass
    finally:
        await eng.stop()


async def test_wrong_model_rejected():
    eng = _mkengine()
    await eng.start()
    try:
        with pytest.raises(ValueError):
            async for _ in eng.generate("hi", model="other-model"):
                pass
    finally:
        await eng.stop()


async def test_fake_engine_seam():
    eng = FakeEngine()
    reply = await eng.handle(create_generate_request("m", "hi there"))
    resp = extract_generate_response(reply)
    assert resp.response == "echo: hi there"
    assert resp.done


def test_byte_tokenizer_roundtrip():
    tok = ByteTokenizer()
    ids = tok.encode("héllo ✓")
    assert ids[0] == tok.bos_id
    assert tok.decode(ids) == "héllo ✓"
    # streaming decoder handles split multibyte sequences
    dec = tok.stream_decoder()
    out = "".join(dec.feed(i) for i in ids)
    assert out == "héllo ✓"


def test_get_tokenizer_fallback(tmp_path):
    assert isinstance(get_tokenizer(""), ByteTokenizer)
    assert isinstance(get_tokenizer(str(tmp_path / "nope")), ByteTokenizer)


def test_prefill_padding_invariance():
    """Bucket padding must not leak into attention: the same prompt prefilled
    into different bucket sizes yields the same greedy first token and the
    same KV for the real positions."""
    import jax
    from crowdllama_tpu.engine.runner import ModelRunner
    from crowdllama_tpu.models.config import get_config

    import jax.numpy as jnp
    from crowdllama_tpu.models import transformer as T

    cfg = get_config("tiny-test")
    r = ModelRunner(cfg, mesh_spec="1x1x1", max_slots=2, max_seq=128)
    prompt = [1, 7, 42, 99, 3]  # len 5 → bucket 32 (27 padding keys)
    tok_bucketed, ks_bucketed, _, _ = r.prefill(prompt, 0.0, 1.0, jax.random.PRNGKey(0))

    # Exact-length forward, no padding at all.
    pos = jnp.arange(5)[None, :]
    logits, ks_exact, _ = T.prefill(r.params, cfg, jnp.asarray([prompt]), pos)
    assert int(logits[0, -1].argmax()) == tok_bucketed
    np.testing.assert_allclose(
        np.asarray(ks_bucketed[:, :, :, :5], np.float32),
        np.asarray(ks_exact, np.float32), atol=2e-2)


async def test_event_loop_free_during_dispatch():
    """The control plane must stay responsive while a decode chunk / prefill
    blocks in the dispatch thread (VERDICT r1: an 8-step chunk on a big model
    froze DHT RPCs and health probes for its whole duration)."""
    import time

    from crowdllama_tpu.engine.scheduler import GenRequest, Scheduler

    class _SlowRunner:
        max_slots = 2
        max_seq = 128

        def init_state(self):
            return {}

        def prefill(self, ids, temp, top_p, key, state=None, **kw):
            time.sleep(0.4)  # blocking device wait
            return 5, None, None, len(ids)

        def insert(self, state, slot, ks, vs, plen, tok, t, p, **kw):
            return state

        def release(self, state, slot):
            return state

        def decode_steps_device(self, state, k):
            time.sleep(0.6)  # blocking device wait
            return np.zeros((k, self.max_slots), np.int32), state

    sched = Scheduler(_SlowRunner(), decode_chunk=4)
    sched.start()
    try:
        req = GenRequest(prompt_ids=[1, 2, 3], max_tokens=8, eos_id=-1)
        await sched.submit(req)
        max_gap, last = 0.0, time.monotonic()
        for _ in range(150):  # ~1.5 s of ticking while prefill+chunks run
            await asyncio.sleep(0.01)
            now = time.monotonic()
            max_gap = max(max_gap, now - last)
            last = now
        assert max_gap < 0.25, f"event loop stalled {max_gap:.2f}s"
        # Guard against the decode path silently erroring out (a fake that
        # doesn't match the runner protocol would make this test vacuous):
        # the request must have actually received tokens.
        assert not req.out.empty(), "no tokens emitted — decode never ran"
        tok, reason = req.out.get_nowait()
        assert reason == "" and isinstance(tok, int)
    finally:
        await sched.stop()


async def test_scheduler_churn_no_token_crosstalk():
    """Double-buffered decode under churn: many concurrent requests with
    mixed lengths and early EOS must each get a self-consistent stream —
    no request may receive tokens dispatched for another slot's occupant
    (the retire/readmit race the chunk snapshots exist to prevent)."""
    import jax

    from crowdllama_tpu.engine.runner import ModelRunner
    from crowdllama_tpu.engine.scheduler import DONE, GenRequest, Scheduler
    from crowdllama_tpu.models.config import get_config

    cfg = get_config("tiny-test", max_context_length=128)
    runner = ModelRunner(cfg, max_slots=2, max_seq=128)
    sched = Scheduler(runner, decode_chunk=4)
    sched.start()
    try:
        async def one(i):
            req = GenRequest(prompt_ids=[1 + i, 2, 3 + i],
                             max_tokens=3 + (i % 5), eos_id=-1)
            await sched.submit(req)
            toks = []
            while True:
                tok, reason = await asyncio.wait_for(req.out.get(), 30)
                if tok is DONE:
                    return toks, reason
                toks.append(tok)

        results = await asyncio.gather(*(one(i) for i in range(12)))
        for i, (toks, reason) in enumerate(results):
            want = 3 + (i % 5)
            assert reason in ("stop", "length"), reason
            # Exactly the budgeted number of tokens: crosstalk or dropped
            # chunks would show up as over- or under-emission.
            assert len(toks) == want, (i, len(toks), want)
        assert sched.requests_served == 12
        # All slots drained; scheduler is idle and reusable.
        assert all(s is None for s in sched.slots)
        toks, reason = await one(99)
        assert len(toks) == 3 + (99 % 5)
    finally:
        await sched.stop()


def test_sampling_shapes():
    import jax
    import jax.numpy as jnp
    from crowdllama_tpu.engine.sampling import sample_tokens

    logits = jnp.asarray(np.random.default_rng(0).normal(size=(4, 50)), jnp.float32)
    # greedy rows match argmax
    toks = sample_tokens(logits, jnp.zeros(4), jnp.ones(4), jax.random.PRNGKey(0))
    np.testing.assert_array_equal(np.asarray(toks), np.asarray(logits.argmax(-1)))
    # top_p=0.01 with temp>0 collapses to argmax too
    toks = sample_tokens(logits, jnp.ones(4), jnp.full(4, 0.01), jax.random.PRNGKey(1))
    np.testing.assert_array_equal(np.asarray(toks), np.asarray(logits.argmax(-1)))


def test_chat_template_preferred_over_flattening():
    """JaxEngine renders chats with the tokenizer's template when it has
    one, and falls back to the generic flattening when it doesn't."""
    from crowdllama_tpu.engine.engine import JaxEngine

    eng = JaxEngine.__new__(JaxEngine)  # formatting needs no started engine

    class Templated:
        def format_chat(self, messages):
            return "<tmpl>" + messages[-1]["content"]

    eng.tokenizer = Templated()
    msgs = [{"role": "user", "content": "hi"}]
    assert eng._format_chat(msgs) == "<tmpl>hi"

    class Untemplated:
        def format_chat(self, messages):
            raise ValueError("tokenizer has no chat template")

    eng.tokenizer = Untemplated()
    assert "user: hi" in eng._format_chat(msgs)


async def test_client_disconnect_frees_slot():
    """Closing the generate stream mid-flight (client disconnect) must free
    the decode slot — not keep generating until max_tokens."""
    from crowdllama_tpu.engine.runner import ModelRunner
    from crowdllama_tpu.engine.scheduler import Scheduler, GenRequest, DONE
    from crowdllama_tpu.models.config import get_config

    cfg = get_config("tiny-test", max_context_length=128)
    runner = ModelRunner(cfg, max_slots=2, max_seq=128)
    sched = Scheduler(runner, decode_chunk=2)
    sched.start()
    try:
        req = GenRequest(prompt_ids=[1, 2, 3], max_tokens=10_000, eos_id=-1)
        await sched.submit(req)
        await asyncio.wait_for(req.out.get(), 30)  # first token arrived
        sched.cancel(req)
        # The loop frees the slot at its next safe point.
        for _ in range(600):
            if all(s is None for s in sched.slots):
                break
            await asyncio.sleep(0.05)
        assert all(s is None for s in sched.slots)
        # Scheduler keeps serving new requests after the cancellation.
        req2 = GenRequest(prompt_ids=[4, 5], max_tokens=3, eos_id=-1)
        await sched.submit(req2)
        toks = []
        while True:
            tok, reason = await asyncio.wait_for(req2.out.get(), 30)
            if tok is DONE:
                break
            toks.append(tok)
        assert len(toks) == 3 and reason == "length"
        # A cancelled request still in the pending queue is dropped too.
        req3 = GenRequest(prompt_ids=[6], max_tokens=5, eos_id=-1)
        req3.cancelled = True
        await sched.submit(req3)
        req4 = GenRequest(prompt_ids=[7, 8], max_tokens=2, eos_id=-1)
        await sched.submit(req4)
        while True:
            tok, reason = await asyncio.wait_for(req4.out.get(), 30)
            if tok is DONE:
                break
        assert req3.out.empty()
    finally:
        await sched.stop()


async def test_scheduler_drain():
    from crowdllama_tpu.engine.runner import ModelRunner
    from crowdllama_tpu.engine.scheduler import Scheduler, GenRequest, DONE
    from crowdllama_tpu.models.config import get_config

    cfg = get_config("tiny-test", max_context_length=128)
    runner = ModelRunner(cfg, max_slots=2, max_seq=128)
    sched = Scheduler(runner, decode_chunk=2)
    sched.start()
    try:
        req = GenRequest(prompt_ids=[1, 2], max_tokens=6, eos_id=-1)
        await sched.submit(req)

        async def consume():
            got_done = False
            while True:
                tok, reason = await asyncio.wait_for(req.out.get(), 60)
                if tok is DONE:
                    return True
        consumer = asyncio.create_task(consume())
        assert await asyncio.wait_for(sched.drain(60), 90) is True
        # Drained means the request completed AND its stream was consumed.
        assert await consumer is True
        # A draining scheduler rejects new work so clients fail over.
        try:
            await sched.submit(GenRequest(prompt_ids=[9], max_tokens=1))
            raise AssertionError("submit during drain should raise")
        except RuntimeError:
            pass
    finally:
        await sched.stop()

    # Timeout path: a runner too slow to finish within the grace reports
    # False (tiny models finish 100k tokens in under the shortest useful
    # timeout, so use a deliberately slow fake).
    import time as _time

    class _Slow:
        max_slots = 1
        max_seq = 10_000

        def init_state(self):
            return {}

        def prefill(self, ids, temp, top_p, key, state=None, **kw):
            return 5, None, None, len(ids)

        def insert(self, state, slot, ks, vs, plen, tok, t, p, **kw):
            return state

        def release(self, state, slot):
            return state

        def decode_steps_device(self, state, k):
            _time.sleep(0.2)
            return np.zeros((k, 1), np.int32), state

    slow = Scheduler(_Slow(), decode_chunk=1)
    slow.start()
    try:
        req2 = GenRequest(prompt_ids=[3], max_tokens=100_000, eos_id=-1)
        await slow.submit(req2)
        await asyncio.wait_for(req2.out.get(), 30)
        assert await slow.drain(0.5) is False
    finally:
        await slow.stop()


def test_chunked_prefill_matches_monolithic():
    """prefill_begin/step/finish must produce the same first token and the
    same KV as one monolithic prefill."""
    import jax
    import jax.numpy as jnp
    from crowdllama_tpu.engine.runner import ModelRunner
    from crowdllama_tpu.models.config import get_config

    cfg = get_config("tiny-test", max_context_length=256)
    r = ModelRunner(cfg, max_slots=2, max_seq=256, dtype=jnp.float32)
    r.prefill_chunk = 32  # force several chunks
    rng = np.random.default_rng(0)
    prompt = rng.integers(1, 500, 100).tolist()  # 4 chunks (32/32/32/4)

    tok_ref, ks_ref, vs_ref, plen = r.prefill(prompt, 0.0, 1.0,
                                              jax.random.PRNGKey(3))
    job = r.prefill_begin(prompt)
    steps = 0
    while not r.prefill_step(job):
        steps += 1
    assert steps + 1 == 4
    tok, ks, vs, plen2 = r.prefill_finish(job, 0.0, 1.0, jax.random.PRNGKey(3))
    assert (tok, plen2) == (tok_ref, plen)
    np.testing.assert_allclose(
        np.asarray(ks[:, :, :, :plen], np.float32),
        np.asarray(ks_ref[:, :, :, :plen], np.float32), atol=2e-3)


async def test_chunked_admission_end_to_end():
    """A long prompt admits chunk-by-chunk through the scheduler and decodes
    the same greedy tokens as monolithic admission."""
    import jax
    import jax.numpy as jnp
    from crowdllama_tpu.engine.runner import ModelRunner
    from crowdllama_tpu.engine.scheduler import DONE, GenRequest, Scheduler
    from crowdllama_tpu.models.config import get_config

    cfg = get_config("tiny-test", max_context_length=256)
    rng = np.random.default_rng(1)
    prompt = rng.integers(1, 500, 90).tolist()

    async def serve(chunked: bool):
        r = ModelRunner(cfg, max_slots=2, max_seq=256, dtype=jnp.float32)
        if chunked:
            r.prefill_chunk = 32
        else:
            r.prefill_chunk = 0
        sched = Scheduler(r, decode_chunk=2)
        sched.start()
        try:
            req = GenRequest(prompt_ids=prompt, max_tokens=8, eos_id=-1)
            await sched.submit(req)
            toks = []
            while True:
                tok, reason = await asyncio.wait_for(req.out.get(), 60)
                if tok is DONE:
                    return toks, reason
                toks.append(tok)
        finally:
            await sched.stop()

    mono, r1 = await serve(False)
    chun, r2 = await serve(True)
    assert r1 == r2 == "length"
    assert mono == chun, (mono, chun)


async def test_short_requests_interleave_with_chunked_admission():
    """A short prompt submitted AFTER a long one must finish first: chunked
    admission reserves one slot and leaves the rest admitting."""
    import time as _time

    import jax.numpy as jnp
    from crowdllama_tpu.engine.runner import ModelRunner
    from crowdllama_tpu.engine.scheduler import DONE, GenRequest, Scheduler
    from crowdllama_tpu.models.config import get_config

    cfg = get_config("tiny-test", max_context_length=256)
    r = ModelRunner(cfg, max_slots=2, max_seq=256, dtype=jnp.float32)
    r.prefill_chunk = 32
    sched = Scheduler(r, decode_chunk=2)
    sched.start()
    try:
        rng = np.random.default_rng(7)
        long_req = GenRequest(prompt_ids=rng.integers(1, 500, 200).tolist(),
                              max_tokens=4, eos_id=-1)
        short_req = GenRequest(prompt_ids=[1, 2, 3], max_tokens=4, eos_id=-1)
        await sched.submit(long_req)
        await sched.submit(short_req)

        async def finish_time(req):
            while True:
                tok, _ = await asyncio.wait_for(req.out.get(), 120)
                if tok is DONE:
                    return _time.monotonic()

        t_long, t_short = await asyncio.gather(finish_time(long_req),
                                               finish_time(short_req))
        assert t_short <= t_long, "short request waited behind chunked prefill"
        assert sched.requests_served == 2
    finally:
        await sched.stop()


async def test_deferred_long_prompts_keep_fifo_and_dont_block_shorts():
    """Two long prompts + a short one: the short admits during the first
    long's chunked prefill, and the longs complete in submission order."""
    import time as _time

    import jax.numpy as jnp
    from crowdllama_tpu.engine.runner import ModelRunner
    from crowdllama_tpu.engine.scheduler import DONE, GenRequest, Scheduler
    from crowdllama_tpu.models.config import get_config

    cfg = get_config("tiny-test", max_context_length=256)
    r = ModelRunner(cfg, max_slots=4, max_seq=256, dtype=jnp.float32)
    r.prefill_chunk = 32
    sched = Scheduler(r, decode_chunk=2)
    sched.start()
    try:
        rng = np.random.default_rng(8)
        long1 = GenRequest(prompt_ids=rng.integers(1, 500, 180).tolist(),
                           max_tokens=3, eos_id=-1)
        long2 = GenRequest(prompt_ids=rng.integers(1, 500, 180).tolist(),
                           max_tokens=3, eos_id=-1)
        short = GenRequest(prompt_ids=[1, 2], max_tokens=3, eos_id=-1)
        for req in (long1, long2, short):
            await sched.submit(req)

        async def finish_time(req):
            while True:
                tok, _ = await asyncio.wait_for(req.out.get(), 120)
                if tok is DONE:
                    return _time.monotonic()

        t1, t2, ts = await asyncio.gather(finish_time(long1),
                                          finish_time(long2),
                                          finish_time(short))
        assert ts <= t1 <= t2, (ts, t1, t2)
        assert sched.requests_served == 3
    finally:
        await sched.stop()


def test_chunk_size_only_shrinks_while_admittable():
    """A non-empty queue must NOT force per-token dispatch when every slot
    is occupied: at saturation there is nothing to admit into, and chunk=1
    would starve decode amortization until the queue drained (VERDICT r4
    weak #3)."""
    from crowdllama_tpu.engine.scheduler import (
        GenRequest,
        Scheduler,
        _SlotInfo,
    )

    class _Stub:
        max_slots = 2
        max_seq = 128

        def init_state(self):
            return {}

    sched = Scheduler(_Stub(), decode_chunk=8)
    req = GenRequest(prompt_ids=[1])
    # Idle queue, free slots: full chunk.
    assert sched._chunk_size() == 8
    # Waiting request + a free slot: admission latency wins.
    sched.pending.put_nowait(req)
    assert sched._chunk_size() == 1
    # Same queue, but saturated: amortization wins.
    sched.slots = [_SlotInfo(req=req), _SlotInfo(req=req)]
    assert sched._chunk_size() == 8
    # Deferred long prompts count as waiting work too (once a slot frees).
    sched.pending.get_nowait()
    sched.slots[0] = None
    sched._deferred.append(req)
    assert sched._chunk_size() == 1


async def test_cancelled_chunked_admission_aborts_runner_job():
    """Cancelling a request mid-chunked-admission must tell the runner the
    job is abandoned (multi-host followers pin the job's KV accumulators
    until a PREFILL_ABORT frame arrives — ADVICE r4)."""
    import jax.numpy as jnp
    from crowdllama_tpu.engine.runner import ModelRunner
    from crowdllama_tpu.engine.scheduler import GenRequest, Scheduler
    from crowdllama_tpu.models.config import get_config

    cfg = get_config("tiny-test", max_context_length=256)
    r = ModelRunner(cfg, max_slots=2, max_seq=256, dtype=jnp.float32)
    r.prefill_chunk = 32
    aborted = []
    r.prefill_abort = aborted.append  # runners without it are a no-op
    # Slow each chunk down so the cancel lands mid-admission.
    real_step = r.prefill_step

    def slow_step(job):
        import time

        time.sleep(0.05)
        return real_step(job)

    r.prefill_step = slow_step
    sched = Scheduler(r, decode_chunk=2)
    sched.start()
    try:
        rng = np.random.default_rng(11)
        req = GenRequest(prompt_ids=rng.integers(1, 500, 220).tolist(),
                         max_tokens=4, eos_id=-1)
        await sched.submit(req)
        for _ in range(600):
            if sched._chunking is not None:
                break
            await asyncio.sleep(0.01)
        assert sched._chunking is not None, "chunked admission never started"
        sched.cancel(req)
        # _chunking clears before the abort's executor hop completes —
        # poll for the abort itself, not just the cleared reservation.
        for _ in range(600):
            if aborted and sched._chunking is None:
                break
            await asyncio.sleep(0.01)
        assert sched._chunking is None
        assert len(aborted) == 1, "runner was not told the job was abandoned"
        assert all(s is None for s in sched.slots)
    finally:
        await sched.stop()


async def test_chunked_admission_failure_recovers():
    """A prefill_step crash mid-chunked-admission fails that request cleanly
    and the scheduler keeps serving."""
    import jax.numpy as jnp
    from crowdllama_tpu.engine.runner import ModelRunner
    from crowdllama_tpu.engine.scheduler import DONE, GenRequest, Scheduler
    from crowdllama_tpu.models.config import get_config

    cfg = get_config("tiny-test", max_context_length=256)
    r = ModelRunner(cfg, max_slots=2, max_seq=256, dtype=jnp.float32)
    r.prefill_chunk = 32
    boom = {"armed": True}
    real_step = r.prefill_step

    def failing_step(job):
        if boom["armed"] and job.done_tokens >= 32:
            boom["armed"] = False
            raise RuntimeError("injected chunk failure")
        return real_step(job)

    r.prefill_step = failing_step
    sched = Scheduler(r, decode_chunk=2)
    sched.start()
    try:
        rng = np.random.default_rng(9)
        req = GenRequest(prompt_ids=rng.integers(1, 500, 120).tolist(),
                         max_tokens=4, eos_id=-1)
        await sched.submit(req)
        tok, reason = await asyncio.wait_for(req.out.get(), 60)
        assert tok is DONE and reason.startswith("error")
        # Scheduler recovered: a fresh request serves normally.
        req2 = GenRequest(prompt_ids=rng.integers(1, 500, 90).tolist(),
                          max_tokens=3, eos_id=-1)
        await sched.submit(req2)
        toks = []
        while True:
            tok, reason = await asyncio.wait_for(req2.out.get(), 60)
            if tok is DONE:
                break
            toks.append(tok)
        assert len(toks) == 3 and reason == "length"
        assert all(s is None for s in sched.slots)
    finally:
        await sched.stop()


async def test_stop_sequences():
    """Ollama options.stop parity: generation halts at the first stop
    sequence; the matched text (and anything after) is never emitted —
    including stops that span two decoded token chunks."""
    eng = _mkengine()
    await eng.start()
    try:
        # Greedy tiny-test output is deterministic; capture a baseline.
        base = []
        async for c in eng.generate("stop test", max_tokens=16):
            base.append(c.text)
        full = "".join(base)
        assert len(full) >= 4
        # Use a mid-output substring as the stop sequence (spans whatever
        # chunk boundary the decoder happened to produce).
        stop_seq = full[2:5]
        out, final = [], None
        async for c in eng.generate("stop test", max_tokens=16,
                                    stop=[stop_seq]):
            out.append(c.text)
            if c.done:
                final = c
        text = "".join(out)
        assert final is not None and final.done_reason == "stop"
        assert stop_seq not in text
        assert text == full[:full.find(stop_seq)]
    finally:
        await eng.stop()


async def test_top_k_sampling():
    """Ollama options.top_k parity: top_k=1 at high temperature must
    reproduce greedy decoding exactly (the distribution collapses to the
    argmax), where unrestricted sampling at that temperature diverges."""
    eng = _mkengine(mesh="1x1x1")
    await eng.start()
    try:
        async def run(**kw):
            out = []
            async for c in eng.generate("topk test", max_tokens=10, **kw):
                out.append(c.text)
            return "".join(out)

        greedy = await run(temperature=0.0)
        k1 = await run(temperature=5.0, top_k=1, seed=7)
        assert k1 == greedy, (k1, greedy)
        # Sanity: without the top_k restriction, t=5 sampling diverges
        # from greedy (astronomically unlikely to match for 10 tokens).
        free = await run(temperature=5.0, seed=7)
        assert free != greedy
    finally:
        await eng.stop()


async def test_repeat_penalty():
    """Ollama options.repeat_penalty parity: with a massive penalty over
    the last-64 window, greedy decode cannot emit the same token twice in
    a row (self-repetition is suppressed), while unpenalized greedy on the
    random tiny model typically loops."""
    eng = _mkengine(mesh="1x1x1")
    await eng.start()
    try:
        async def run_tokens(**kw):
            toks = []
            async for c in eng.generate("rp test", max_tokens=20, **kw):
                if not c.done:
                    toks.append(c.text)
            return toks

        plain = await run_tokens()
        pen = await run_tokens(repeat_penalty=1e9)
        # The huge penalty crushes any previously-seen token's logit, so
        # consecutive duplicates are impossible (window 64 > 20 tokens);
        # also verify it CHANGED something relative to plain greedy, which
        # repeats on this random model (guards against silent no-op).
        assert all(a != b for a, b in zip(pen, pen[1:])), pen
        assert len(set(pen)) == len(pen), pen  # no repeats at all in 20
        assert pen != plain or len(set(plain)) == len(plain)
    finally:
        await eng.stop()
