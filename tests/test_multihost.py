"""Multi-host (multi-process) mesh support: a 2-process × 4-virtual-CPU-
device cluster forms ONE 8-device global mesh and serves in SPMD lockstep
(parallel/multihost.py).

The reference cannot express this at all — its unit of distribution is a
whole single-host worker (/root/reference/pkg/peermanager/manager.go:338).
Here a logical worker spans processes the way a TPU pod slice spans
hosts, with the same jitted programs running on every process and
host-side inputs broadcast from the leader.

Run as real subprocesses: jax.distributed needs one coordinator and N
OS processes — in-process simulation would not cover the DCN/gRPC
control plane at all.
"""

import os
import socket
import subprocess
import sys
import textwrap
from pathlib import Path

import pytest

REPO = Path(__file__).resolve().parent.parent

_CHILD = textwrap.dedent("""
    import os, sys
    os.environ["JAX_PLATFORMS"] = "cpu"
    os.environ["XLA_FLAGS"] = "--xla_force_host_platform_device_count=4"
    import jax
    jax.config.update("jax_platforms", "cpu")

    from crowdllama_tpu.config import Configuration
    from crowdllama_tpu.parallel import multihost

    cfg = Configuration(
        dist_coordinator=sys.argv[1],
        dist_num_processes=2,
        dist_process_id=int(sys.argv[2]),
    )
    assert multihost.initialize_from_config(cfg) is True
    assert len(jax.devices()) == 8 and len(jax.local_devices()) == 4
    assert multihost.process_count() == 2
    assert multihost.is_leader() == (int(sys.argv[2]) == 0)

    # Leader-replicated dispatch: the admission decision (prompt tokens)
    # is made on process 0 and broadcast; every process then issues the
    # identical prefill/insert/decode stream on the GLOBAL dp4 x tp2 mesh.
    import jax.numpy as jnp
    import numpy as np
    from jax.experimental import multihost_utils

    from crowdllama_tpu.engine.runner import ModelRunner
    from crowdllama_tpu.models.config import get_config

    leader_prompt = jnp.asarray(
        [list(range(7, 19))] if multihost.is_leader() else [[0] * 12],
        jnp.int32)
    prompt = list(np.asarray(
        multihost.broadcast_from_leader(leader_prompt))[0])

    mcfg = get_config("tiny-test", max_context_length=64)
    runner = ModelRunner(mcfg, max_slots=4, max_seq=64, mesh_spec="4x2",
                         seed=0)
    state = runner.init_state()
    key = jax.random.PRNGKey(0)
    first, ks, vs, plen = runner.prefill(prompt, 0.0, 1.0, key, state=state)
    state = runner.insert(state, 0, ks, vs, plen, first, 0.0, 1.0)
    toks, state = runner.decode_steps_device(state, 6)
    # Every process must hold the same device-global result.
    gathered = multihost_utils.process_allgather(toks, tiled=True)
    flat = np.asarray(gathered).reshape(1, -1)
    multihost.barrier("done")
    print(f"MH_OK proc={sys.argv[2]} tokens={flat[0, :6].tolist()}",
          flush=True)
""")


def test_two_process_global_mesh_lockstep(tmp_path):
    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        coord = f"127.0.0.1:{s.getsockname()[1]}"
    script = tmp_path / "child.py"
    script.write_text(_CHILD)
    env = {**os.environ, "PYTHONPATH": str(REPO)}
    env.pop("JAX_PLATFORMS", None)
    procs = [
        subprocess.Popen([sys.executable, str(script), coord, str(i)],
                         cwd=REPO, env=env, stdout=subprocess.PIPE,
                         stderr=subprocess.STDOUT, text=True)
        for i in range(2)
    ]
    outs = []
    try:
        for p in procs:
            out, _ = p.communicate(timeout=240)
            outs.append(out)
    finally:
        for p in procs:
            p.kill()
    for i, (p, out) in enumerate(zip(procs, outs)):
        assert p.returncode == 0, f"proc {i} failed:\n{out[-3000:]}"
        assert "MH_OK" in out, out[-2000:]
    # Both processes decoded the same token stream off the global mesh.
    t0 = [ln for ln in outs[0].splitlines() if "MH_OK" in ln][0]
    t1 = [ln for ln in outs[1].splitlines() if "MH_OK" in ln][0]
    assert t0.split("tokens=")[1] == t1.split("tokens=")[1]
