"""Multi-worker sharded serving, end-to-end (BASELINE config 5 wiring).

Two ShardedEngine workers (stage 0 = leader, stage 1) + consumer/gateway +
DHT bootstrap node, all real sockets on loopback: the gateway routes
/api/chat for the sharded model to the group leader, which drives the
pipeline over SHARD_PROTOCOL streams to the member.  Killing the member
makes the group incomplete and the model unroutable — the live exercise of
the scheduler's group logic (peermanager/manager.py complete-groups rule).

The reference can only route whole requests to single workers
(/root/reference/pkg/peermanager/manager.go:338-387); there is no analog.
"""

import asyncio
import json

import aiohttp
from crowdllama_tpu.utils.crypto_compat import Ed25519PrivateKey

from crowdllama_tpu.config import Configuration, Intervals
from crowdllama_tpu.engine.engine import FakeEngine
from crowdllama_tpu.engine.sharded import ShardedEngine
from crowdllama_tpu.gateway.gateway import Gateway
from crowdllama_tpu.net.discovery import new_host_and_dht
from crowdllama_tpu.peer.peer import Peer

MODEL = "tiny-test"
GROUP = "tiny-test/pp2"


def _cfg(bootstrap, **kw):
    cfg = Configuration(
        listen_host="127.0.0.1",
        bootstrap_peers=[bootstrap],
        model=MODEL,
        max_context_length=64,
        intervals=Intervals.default(),
    )
    for k, v in kw.items():
        setattr(cfg, k, v)
    return cfg


async def _wait_for(cond, timeout=30.0, interval=0.1, what="condition"):
    loop = asyncio.get_running_loop()
    deadline = loop.time() + timeout
    while loop.time() < deadline:
        if cond():
            return
        await asyncio.sleep(interval)
    raise AssertionError(f"timed out waiting for {what}")


async def test_sharded_model_served_and_group_failure():
    boot_host, boot_dht = await new_host_and_dht(
        Ed25519PrivateKey.generate(), listen_host="127.0.0.1")
    bootstrap = f"127.0.0.1:{boot_host.listen_port}"

    # Stage workers: same group, same (seeded random) weights.
    leader_cfg = _cfg(bootstrap, shard_group=GROUP, shard_index=0, shard_count=2)
    member_cfg = _cfg(bootstrap, shard_group=GROUP, shard_index=1, shard_count=2)
    leader_engine = ShardedEngine(leader_cfg)
    member_engine = ShardedEngine(member_cfg)
    await leader_engine.start()
    await member_engine.start()

    leader = Peer(Ed25519PrivateKey.generate(), leader_cfg,
                  engine=leader_engine, worker_mode=True)
    member = Peer(Ed25519PrivateKey.generate(), member_cfg,
                  engine=member_engine, worker_mode=True)
    await leader.start()
    await member.start()

    consumer = Peer(Ed25519PrivateKey.generate(), _cfg(bootstrap),
                    engine=FakeEngine(models=[]), worker_mode=False)
    await consumer.start()
    gateway = Gateway(consumer, port=0, host="127.0.0.1")
    await gateway.start()
    gw_port = gateway._runner.addresses[0][1]
    member_stopped = False
    try:
        # Consumer must route to the leader only once the group is complete;
        # the leader must see the member (peer tables exclude self) for
        # stage dialing.
        await _wait_for(
            lambda: (
                (best := consumer.peer_manager.find_best_worker(MODEL)) is not None
                and best.peer_id == leader.peer_id
                and any(
                    p.peer_id == member.peer_id
                    for p in leader.peer_manager.group_members(GROUP)
                )
            ),
            what="complete shard group discovered",
        )
        # The member alone is never routable.
        assert all(
            p.peer_id != member.peer_id
            for p in [consumer.peer_manager.find_best_worker(MODEL)]
            if p is not None
        )

        base = f"http://127.0.0.1:{gw_port}"
        async with aiohttp.ClientSession() as s:
            body = {"model": MODEL, "max_tokens": 8,
                    "messages": [{"role": "user", "content": "hi"}]}
            async with s.post(f"{base}/api/chat", json=body) as resp:
                assert resp.status == 200, await resp.text()
                d = await resp.json()
            assert d["done"] is True
            assert d["worker_id"] == leader.peer_id
            # Random weights produce arbitrary ids; the engine still reports
            # real token accounting.
            assert d.get("eval_count", 0) >= 1 or d["message"] is not None

            # Streaming through the full pipeline.
            body["stream"] = True
            async with s.post(f"{base}/api/chat", json=body) as resp:
                assert resp.status == 200
                lines = [json.loads(l) for l in (await resp.text()).splitlines()]
            assert lines[-1]["done"] is True
            assert lines[-1]["worker_id"] == leader.peer_id

            # Member KV sessions were released after each request.
            assert member_engine.runner.session_count == 0

            # Kill the member: group incomplete -> model unroutable.
            await member.stop()
            member_stopped = True
            await _wait_for(
                lambda: consumer.peer_manager.find_best_worker(MODEL) is None,
                timeout=45.0,
                what="group unroutable after member death",
            )
            async with s.post(f"{base}/api/chat", json={
                "model": MODEL,
                "messages": [{"role": "user", "content": "x"}],
            }) as resp:
                assert resp.status == 503
    finally:
        await gateway.stop()
        await consumer.stop()
        if not member_stopped:
            await member.stop()
        await leader.stop()
        await leader_engine.stop()
        await member_engine.stop()
        await boot_host.close()


async def test_ep_sharded_model_served_through_gateway():
    """BASELINE config 4 end-to-end: 2 ShardedEngine(strategy=ep) workers
    hosting Mixtral-style expert banks + gateway; /api/chat routes to the
    leader which dispatches expert batches to the member over
    SHARD_PROTOCOL."""
    model, group = "tiny-test-moe", "tiny-test-moe/ep2"
    boot_host, _ = await new_host_and_dht(
        Ed25519PrivateKey.generate(), listen_host="127.0.0.1")
    bootstrap = f"127.0.0.1:{boot_host.listen_port}"

    leader_cfg = _cfg(bootstrap, model=model, shard_group=group,
                      shard_index=0, shard_count=2, shard_strategy="ep")
    member_cfg = _cfg(bootstrap, model=model, shard_group=group,
                      shard_index=1, shard_count=2, shard_strategy="ep")
    leader_engine = ShardedEngine(leader_cfg)
    member_engine = ShardedEngine(member_cfg)
    await leader_engine.start()
    await member_engine.start()
    assert leader_engine.expert_ids == [0, 2]
    assert member_engine.expert_ids == [1, 3]

    leader = Peer(Ed25519PrivateKey.generate(), leader_cfg,
                  engine=leader_engine, worker_mode=True)
    member = Peer(Ed25519PrivateKey.generate(), member_cfg,
                  engine=member_engine, worker_mode=True)
    await leader.start()
    await member.start()
    consumer = Peer(Ed25519PrivateKey.generate(), _cfg(bootstrap, model=model),
                    engine=FakeEngine(models=[]), worker_mode=False)
    await consumer.start()
    gateway = Gateway(consumer, port=0, host="127.0.0.1")
    await gateway.start()
    gw_port = gateway._runner.addresses[0][1]
    try:
        await _wait_for(
            lambda: (
                (best := consumer.peer_manager.find_best_worker(model)) is not None
                and best.peer_id == leader.peer_id
                and any(p.peer_id == member.peer_id
                        for p in leader.peer_manager.group_members(group))
            ),
            what="complete ep group discovered",
        )
        # expert_ids survive the metadata round trip.
        info = consumer.peer_manager.get_peer(member.peer_id)
        assert info.resource.shard_group.expert_ids == [1, 3]

        async with aiohttp.ClientSession() as s:
            body = {"model": model, "options": {"num_predict": 4},
                    "messages": [{"role": "user", "content": "hi"}]}
            async with s.post(f"http://127.0.0.1:{gw_port}/api/chat",
                              json=body) as resp:
                assert resp.status == 200, await resp.text()
                d = await resp.json()
            assert d["done"] is True
            assert d["worker_id"] == leader.peer_id
            assert d["eval_count"] >= 1
        assert leader_engine.runner.session_count == 0
    finally:
        await gateway.stop()
        await consumer.stop()
        await member.stop()
        await leader.stop()
        await leader_engine.stop()
        await member_engine.stop()
        await boot_host.close()


async def test_sharded_engine_pipeline_matches_dense_greedy():
    """Leader+member over real streams greedily decode the same ids as the
    dense single-process forward (numeric wiring check at the engine level)."""
    import jax
    import jax.numpy as jnp
    import numpy as np

    from crowdllama_tpu.engine.shard_service import (
        LocalStage,
        ShardStageRunner,
        SwarmPipeline,
    )
    from crowdllama_tpu.engine.weights import load_or_init_params
    from crowdllama_tpu.models import transformer as T
    from crowdllama_tpu.models.config import get_config

    cfg = get_config(MODEL, max_context_length=64)
    params = load_or_init_params(cfg, "")  # seed 0, like ShardedEngine.start
    # Dense greedy continuation.
    prompt = [257, 104, 105]
    tokens = jnp.asarray([prompt])
    pos = jnp.arange(len(prompt))[None, :]
    logits, _, _ = T.prefill(
        jax.tree_util.tree_map(lambda a: a.astype(jnp.float32), params),
        cfg, tokens, pos)
    dense_first = int(logits[0, -1].argmax())

    # In-process two-stage pipeline with the engine's own param loading.
    stages = [
        LocalStage(ShardStageRunner(cfg, params, 0, 2, max_seq=64)),
        LocalStage(ShardStageRunner(cfg, params, 1, 2, max_seq=64)),
    ]
    pipe = SwarmPipeline(cfg, {k: v for k, v in params.items() if k != "layers"},
                         stages)
    got = await pipe.prefill("s", prompt, bucket=16)
    assert int(np.argmax(got)) == dense_first
    await pipe.release("s")
