"""N-gram speculative decoding (engine/spec.py): greedy exactness, multi-
token acceptance on repetitive text, sampled slots unaffected, and the
scheduler's packed-emission path end to end."""

import asyncio

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from crowdllama_tpu.engine.runner import ModelRunner
from crowdllama_tpu.engine.spec import SpecModelRunner
from crowdllama_tpu.models import transformer as T
from crowdllama_tpu.models.config import get_config


def _runners(draft_len=4):
    cfg = get_config("tiny-test", max_context_length=128)
    params = T.init_params(cfg, jax.random.PRNGKey(0), dtype=jnp.float32)
    base = ModelRunner(cfg, params=params, max_slots=2, max_seq=128,
                       dtype=jnp.float32)
    spec = SpecModelRunner(cfg, params=params, max_slots=2, max_seq=128,
                           dtype=jnp.float32, draft_len=draft_len)
    return base, spec


def _spec_rollout(spec, prompt, steps, temperature=0.0):
    state = spec.init_state()
    first, ks, vs, plen = spec.prefill(prompt, temperature, 1.0,
                                       jax.random.PRNGKey(7))
    state = spec.insert(state, 0, ks, vs, plen, first, temperature, 1.0,
                        prompt_tokens=prompt)
    toks = [first]
    packed, state = spec.decode_steps(state, steps)
    for step in range(packed.shape[0]):
        n = int(packed[step, 0, 0])
        toks.extend(int(t) for t in packed[step, 1:1 + n, 0])
    return toks, packed


def test_spec_greedy_exactness():
    """Greedy spec decode must emit the exact tokens plain greedy decode
    does — drafts change how many tokens per dispatch, never which."""
    base, spec = _runners()
    prompt = [5, 9, 5, 9, 5, 9, 5]  # repetitive: drafts will accept

    state = base.init_state()
    first, ks, vs, plen = base.prefill(prompt, 0.0, 1.0, jax.random.PRNGKey(7))
    state = base.insert(state, 0, ks, vs, plen, first, 0.0, 1.0)
    out, state = base.decode_steps(state, 24)
    ref = [first] + [int(t) for t in out[:, 0]]

    toks, packed = _spec_rollout(spec, prompt, 24)
    n = min(len(ref), len(toks))
    assert toks[:n] == ref[:n], (toks[:n], ref[:n])


def test_spec_accepts_on_repetitive_model():
    """When the model's own greedy output repeats, drafts accept and one
    verify dispatch emits multiple tokens (the whole point).  A zeroed
    model decodes a constant token — fully predictable by its bigram."""
    cfg = get_config("tiny-test", max_context_length=128)
    params = jax.tree_util.tree_map(
        lambda a: a * 0, T.init_params(cfg, jax.random.PRNGKey(0),
                                       dtype=jnp.float32))
    spec = SpecModelRunner(cfg, params=params, max_slots=2, max_seq=128,
                           dtype=jnp.float32, draft_len=4)
    toks, packed = _spec_rollout(spec, [3, 1, 4, 1, 5], steps=6)
    counts = packed[:, 0, 0]
    assert counts.max() == 5, counts.tolist()  # 1 pending + 4 drafts
    assert sum(counts) == len(toks) - 1


def test_spec_sampled_slots_one_token_per_step():
    _, spec = _runners()
    toks, packed = _spec_rollout(spec, [3, 1, 4, 1, 5], steps=6,
                                 temperature=0.8)
    assert (packed[:, 0, 0] == 1).all()
    assert len(toks) == 7  # first + 6 steps x 1


def test_spec_history_proposals():
    """The bigram proposer drafts the continuation of the latest match."""
    _, spec = _runners(draft_len=3)
    hist = jnp.asarray([[7, 8, 21, 22, 23, 7, 8, 0, 0, 0]
                        + [0] * 118], jnp.int32)
    # cur=6: pending bigram (7, 8) matches positions 0-1 → draft 21, 22, 23.
    drafts, from_prompt = spec._propose(hist, jnp.asarray([6]),
                                        jnp.asarray([7]), spec.draft_len)
    assert drafts.tolist() == [[21, 22, 23]]
    assert bool(from_prompt[0]) is True  # matched inside the prompt region


async def test_spec_scheduler_end_to_end():
    from crowdllama_tpu.engine.scheduler import DONE, GenRequest, Scheduler

    _, spec = _runners()
    sched = Scheduler(spec, decode_chunk=4)
    sched.start()
    try:
        req = GenRequest(prompt_ids=[5, 9, 5, 9, 5], max_tokens=10, eos_id=-1)
        await sched.submit(req)
        toks = []
        while True:
            tok, reason = await asyncio.wait_for(req.out.get(), 60)
            if tok is DONE:
                break
            toks.append(tok)
        # Budget respected exactly despite multi-token spec steps.
        assert reason == "length"
        assert len(toks) == 10, toks
        assert req.out.empty()
    finally:
        await sched.stop()


async def test_spec_engine_config_path():
    from crowdllama_tpu.config import Configuration, Intervals
    from crowdllama_tpu.engine.engine import JaxEngine

    cfg = Configuration(model="tiny-test", max_context_length=128,
                        spec_decode="ngram", spec_draft=3,
                        max_batch_slots=2, warmup=False,
                        kv_layout="contiguous",
                        intervals=Intervals.default())
    eng = JaxEngine(cfg)
    await eng.start()
    try:
        n = 0
        async for c in eng.generate("abcabcabc", max_tokens=8):
            n += 1
            if c.done:
                assert c.completion_tokens == 8
                break
        d = eng.describe()
        # 8 completion tokens = 1 from prefill + >=7 from verify steps.
        assert d["spec_decode"]["tokens_emitted"] >= 7
        assert d["spec_decode"]["verify_steps"] > 0
    finally:
        await eng.stop()


# ------------------------- paged speculative decode (VERDICT r3 #4) --------


def _paged_spec_runner(params, cfg, kv_dtype="bf16", draft_len=4):
    from crowdllama_tpu.engine.spec import SpecPagedModelRunner

    return SpecPagedModelRunner(cfg, params=params, max_slots=2, max_seq=128,
                                page_size=32, mesh_spec="1",
                                kv_dtype=kv_dtype, draft_len=draft_len)


def test_paged_spec_matches_contiguous_spec():
    """Seeded greedy paged+ngram must equal contiguous+ngram token-for-token
    (same drafts, same verify results), bf16 pools."""
    cfg = get_config("tiny-test", max_context_length=128)
    params = T.init_params(cfg, jax.random.PRNGKey(0), dtype=jnp.float32)
    spec = SpecModelRunner(cfg, params=params, max_slots=2, max_seq=128,
                           dtype=jnp.float32, draft_len=4)
    prompt = [5, 9, 5, 9, 5, 9, 5]
    ref, _ = _spec_rollout(spec, prompt, 24)

    pspec = _paged_spec_runner(params, cfg)
    toks, packed = _spec_rollout(pspec, prompt, 24)
    n = min(len(ref), len(toks))
    assert toks[:n] == ref[:n], (toks[:n], ref[:n])


def test_paged_spec_accepts_on_repetitive_model():
    """A zeroed model decodes a constant token — fully predictable by its
    bigram — so the paged verify must accept whole draft windows (the
    acceptance machinery, through the page indirection)."""
    cfg = get_config("tiny-test", max_context_length=128)
    params = jax.tree_util.tree_map(
        lambda a: a * 0, T.init_params(cfg, jax.random.PRNGKey(0),
                                       dtype=jnp.float32))
    pspec = _paged_spec_runner(params, cfg, draft_len=4)
    toks, packed = _spec_rollout(pspec, [3, 1, 4, 1, 5], steps=6)
    counts = packed[:, 0, 0]
    assert counts.max() == 5, counts.tolist()  # 1 pending + 4 drafts
    assert sum(counts) == len(toks) - 1


def test_paged_spec_int8_matches_paged_greedy():
    """int8 pools: paged spec greedy tokens must equal the plain paged
    runner's greedy tokens on the SAME int8 pools (drafts change how many
    tokens per dispatch, never which — the dequantized verify context must
    agree with the int8 decode attention)."""
    from crowdllama_tpu.engine.paged import PagedModelRunner

    cfg = get_config("tiny-test", max_context_length=128)
    params = T.init_params(cfg, jax.random.PRNGKey(0), dtype=jnp.float32)
    prompt = [5, 9, 5, 9, 5, 9, 5]

    base = PagedModelRunner(cfg, params=params, max_slots=2, max_seq=128,
                            page_size=32, mesh_spec="1", kv_dtype="int8")
    state = base.init_state()
    first, ks, vs, plen = base.prefill(prompt, 0.0, 1.0,
                                       jax.random.PRNGKey(7))
    state = base.insert(state, 0, ks, vs, plen, first, 0.0, 1.0)
    out, state = base.decode_steps(state, 24)
    ref = [first] + [int(t) for t in out[:, 0]]

    pspec = _paged_spec_runner(params, cfg, kv_dtype="int8")
    toks, _ = _spec_rollout(pspec, prompt, 24)
    n = min(len(ref), len(toks))
    assert toks[:n] == ref[:n], (toks[:n], ref[:n])


# --------------------- draft-model speculation (VERDICT r3 #4 stretch) ----


def _draft_runner(params, cfg, draft_cfg, draft_params, draft_len=3):
    from crowdllama_tpu.engine.spec import DraftSpecPagedModelRunner

    return DraftSpecPagedModelRunner(
        cfg, params=params, draft_cfg=draft_cfg, draft_params=draft_params,
        max_slots=2, max_seq=128, page_size=32, mesh_spec="1",
        draft_len=draft_len)


def test_draft_spec_greedy_exactness():
    """With an UNRELATED draft model, greedy tokens still match the plain
    paged runner exactly (drafts only decide how many emit per dispatch)."""
    from crowdllama_tpu.engine.paged import PagedModelRunner

    cfg = get_config("tiny-test", max_context_length=128)
    params = T.init_params(cfg, jax.random.PRNGKey(0), dtype=jnp.float32)
    draft_cfg = get_config("tiny-test", max_context_length=128)
    draft_params = T.init_params(draft_cfg, jax.random.PRNGKey(99),
                                 dtype=jnp.float32)  # different weights
    prompt = [5, 9, 5, 9, 5, 9, 5]

    base = PagedModelRunner(cfg, params=params, max_slots=2, max_seq=128,
                            page_size=32, mesh_spec="1")
    state = base.init_state()
    first, ks, vs, plen = base.prefill(prompt, 0.0, 1.0,
                                       jax.random.PRNGKey(7))
    state = base.insert(state, 0, ks, vs, plen, first, 0.0, 1.0)
    out, state = base.decode_steps(state, 20)
    ref = [first] + [int(t) for t in out[:, 0]]

    spec = _draft_runner(params, cfg, draft_cfg, draft_params)
    toks, _ = _spec_rollout(spec, prompt, 20)
    n = min(len(ref), len(toks))
    assert toks[:n] == ref[:n], (toks[:n], ref[:n])


def test_draft_spec_accepts_when_draft_is_main():
    """Draft == main model ⇒ the draft's greedy proposals ARE the main
    model's greedy continuations, so every verify step accepts the whole
    window (the acceptance machinery through the draft cache)."""
    cfg = get_config("tiny-test", max_context_length=128)
    params = T.init_params(cfg, jax.random.PRNGKey(0), dtype=jnp.float32)
    spec = _draft_runner(params, cfg, cfg, params, draft_len=4)
    toks, packed = _spec_rollout(spec, [3, 1, 4, 1, 5], steps=6)
    counts = packed[:, 0, 0]
    assert counts.max() == 5, counts.tolist()  # 1 pending + 4 drafts
    # Full acceptance every step (identical models, greedy).
    assert all(c == 5 for c in counts.tolist()), counts.tolist()
    assert sum(counts) == len(toks) - 1


async def test_draft_spec_engine_config_path():
    """spec_decode=draft end to end: the engine builds the draft runner,
    serves, and reports acceptance telemetry with the draft model name."""
    from crowdllama_tpu.config import Configuration, Intervals
    from crowdllama_tpu.engine.engine import JaxEngine
    from crowdllama_tpu.engine.spec import DraftSpecPagedModelRunner

    cfg = Configuration(model="tiny-test", max_context_length=128,
                        spec_decode="draft", spec_draft=3,
                        spec_draft_model="tiny-test",
                        max_batch_slots=2, warmup=False,
                        intervals=Intervals.default())
    eng = JaxEngine(cfg)
    await eng.start()
    try:
        assert isinstance(eng._runner, DraftSpecPagedModelRunner)
        async for c in eng.generate("abcabcabc", max_tokens=8):
            if c.done:
                assert c.completion_tokens == 8
                break
        d = eng.describe()
        sd = d["spec_decode"]
        assert sd["mode"] == "draft"
        assert sd["draft_model"] == "tiny-test"
        assert 0.0 <= sd["acceptance_rate"] <= 1.0
        assert sd["tokens_emitted"] >= 7
    finally:
        await eng.stop()


async def test_paged_spec_engine_config_path():
    """The out-of-the-box config (kv_layout defaults to paged) + spec no
    longer downgrades the layout: the engine builds SpecPagedModelRunner
    and serves with acceptance telemetry."""
    from crowdllama_tpu.config import Configuration, Intervals
    from crowdllama_tpu.engine.engine import JaxEngine
    from crowdllama_tpu.engine.spec import SpecPagedModelRunner

    cfg = Configuration(model="tiny-test", max_context_length=128,
                        spec_decode="ngram", spec_draft=3,
                        max_batch_slots=2, warmup=False,
                        intervals=Intervals.default())
    assert cfg.kv_layout == "paged"  # the default survives
    eng = JaxEngine(cfg)
    await eng.start()
    try:
        assert isinstance(eng._runner, SpecPagedModelRunner)
        async for c in eng.generate("abcabcabc", max_tokens=8):
            if c.done:
                assert c.completion_tokens == 8
                break
        d = eng.describe()
        assert d["spec_decode"]["tokens_emitted"] >= 7
        assert d["spec_decode"]["verify_steps"] > 0
    finally:
        await eng.stop()


def test_ngram_acceptance_source_attribution():
    """propose_ngram_drafts attributes matches to prompt-echo (bigram
    inside the prompt region) vs generative (match arose in generated
    history) — the telemetry split operators read before enabling spec
    (VERDICT r4 weak #4)."""
    from crowdllama_tpu.engine.spec import propose_ngram_drafts

    s = 16
    # Slot 0: prompt [1,2,9,1], pending token 2 at position 4 — bigram
    # (1,2) matches at j=0, inside plen=5.
    # Slot 1: prompt [9,8] then generated 1,2,9,1, pending 2 at pos 6 —
    # the (1,2) match (j=2) lies past plen=2: generative.
    hist = np.zeros((2, s), np.int32)
    hist[0, :5] = [1, 2, 9, 1, 2]
    hist[1, :7] = [9, 8, 1, 2, 9, 1, 2]
    seq_lens = jnp.asarray([4, 6], jnp.int32)
    plens = jnp.asarray([5, 2], jnp.int32)
    drafts, from_prompt = propose_ngram_drafts(
        jnp.asarray(hist), seq_lens, 3, s, plens)
    assert bool(from_prompt[0]) is True
    assert bool(from_prompt[1]) is False
    # Drafts follow the matched bigram: slot 0 j=0 -> row[2:5] = 9,1,2.
    np.testing.assert_array_equal(np.asarray(drafts[0]), [9, 1, 2])


def test_packed_source_row_marks_echo_acceptance():
    """End to end: a repetitive PROMPT makes accepting steps carry source
    code 1 (prompt-echo) in the packed block's last row."""
    _, spec = _runners()
    toks, packed = _spec_rollout(spec, [3, 1, 4, 1, 5] * 4, steps=6)
    counts = packed[:, 0, 0]
    srcs = packed[:, -1, 0]
    # Wherever a draft was accepted, the source must be attributed (1 or
    # 2, never 0); steps with no acceptance must carry 0.
    assert ((counts > 1) == (srcs > 0)).all(), (counts, srcs)

# ------- distilled draft + acceptance-adaptive draft length (ISSUE 4) -----


def _unpack_into(packed, toks):
    """Append a decode chunk's tokens: packed spec layout [K, 2+J, B] or
    plain [K, B] (speculation paused) — the same branch the scheduler's
    _retire_inflight takes."""
    if packed.ndim == 3:
        for step in range(packed.shape[0]):
            n = int(packed[step, 0, 0])
            toks.extend(int(t) for t in packed[step, 1:1 + n, 0])
    else:
        toks.extend(int(t) for t in packed[:, 0])


@pytest.mark.train
def test_trained_draft_exactness_across_k_changes():
    """A DISTILLED draft through the paged draft runner emits byte-identical
    greedy tokens vs the plain paged runner — including across mid-stream
    ``set_draft_len`` retunes (3 -> 1 -> 0 pause -> 4 resume), exactly the
    transitions the scheduler's adaptive controller applies."""
    from crowdllama_tpu.engine.paged import PagedModelRunner
    from crowdllama_tpu.train.distill import DistillConfig, distill_draft

    cfg = get_config("tiny-test", max_context_length=128)
    params = T.init_params(cfg, jax.random.PRNGKey(0), dtype=jnp.float32)
    res = distill_draft(
        DistillConfig(steps=30, batch=8, seq_len=32, corpus_seqs=16,
                      log_every=0),
        teacher_cfg=cfg, teacher_params=params)
    prompt = [5, 9, 5, 9, 5, 9, 5]

    base = PagedModelRunner(cfg, params=params, max_slots=2, max_seq=128,
                            page_size=32, mesh_spec="1")
    state = base.init_state()
    first, ks, vs, plen = base.prefill(prompt, 0.0, 1.0,
                                       jax.random.PRNGKey(7))
    state = base.insert(state, 0, ks, vs, plen, first, 0.0, 1.0)
    out, state = base.decode_steps(state, 40)
    ref = [first] + [int(t) for t in out[:, 0]]

    spec = _draft_runner(params, cfg, res["draft_config"],
                         res["draft_params"], draft_len=3)
    sstate = spec.init_state()
    sfirst, ks, vs, plen = spec.prefill(prompt, 0.0, 1.0,
                                        jax.random.PRNGKey(7))
    sstate = spec.insert(sstate, 0, ks, vs, plen, sfirst, 0.0, 1.0,
                         prompt_tokens=prompt)
    toks = [sfirst]
    for steps, k in ((8, 3), (6, 1), (6, 0), (6, 4)):
        spec.set_draft_len(k)
        packed, sstate = spec.decode_steps(sstate, steps)
        _unpack_into(packed, toks)
    n = min(len(ref), len(toks))
    assert n > 20
    assert toks[:n] == ref[:n], (toks[:n], ref[:n])


def test_adaptive_retune_shrinks_geometrically_to_pause():
    """Zero acceptance shrinks draft_len geometrically (4 -> 2 -> 1 -> 0)
    once each window holds >= 2k offered draft tokens; at 0 the runner
    dispatches the plain program (speculation paused)."""
    from crowdllama_tpu.engine.scheduler import Scheduler

    _, spec = _runners(draft_len=4)
    sched = Scheduler(spec, spec_draft_max=8)
    assert sched._spec_adaptive
    for expect in (2, 1, 0):
        sched._spec_retune(0, 2 * max(1, spec.draft_len))
        assert spec.draft_len == expect, expect
    assert sched.spec_retunes == 3
    # Below-threshold evidence must NOT move k.
    spec.set_draft_len(4)
    sched._spec_retune(0, 3)  # < 2*4 offered
    assert spec.draft_len == 4


def test_adaptive_retune_grows_toward_max():
    """Full acceptance grows draft_len linearly, capped at spec_draft_max."""
    from crowdllama_tpu.engine.scheduler import Scheduler

    _, spec = _runners(draft_len=2)
    sched = Scheduler(spec, spec_draft_max=4)
    for expect in (3, 4, 4):  # capped at max
        off = 2 * max(1, spec.draft_len)
        sched._spec_retune(off, off)
        assert spec.draft_len == expect, expect
    assert sched.spec_retunes == 2


async def test_adaptive_pause_probe_arming():
    """Paused speculation re-samples acceptance: after spec_probe_interval
    plain decode steps the controller arms a k=1 probe and shrinks the
    next dispatch to a single step."""
    import time as _time

    from crowdllama_tpu.engine.scheduler import Scheduler, _InFlightChunk

    _, spec = _runners(draft_len=4)
    sched = Scheduler(spec, spec_draft_max=8)
    spec.set_draft_len(0)  # as if the controller paused it
    loop = asyncio.get_running_loop()
    plain = np.zeros((sched.spec_probe_interval, 2), np.int32)  # [K, B]
    sched._inflight = _InFlightChunk(plain, [None, None], _time.monotonic())
    await sched._retire_inflight(loop)
    assert sched._spec_probing
    assert sched.spec_probes == 1
    assert spec.draft_len == 1
    assert sched._chunk_size() == 1
    # The probe's retune decision clears the probing state either way.
    sched._spec_retune(2, 2)
    assert not sched._spec_probing
    assert spec.draft_len == 2  # probe accepted -> resume and grow


async def test_adaptive_k_grows_end_to_end():
    """Scheduler end to end on a fully-predictable (zeroed) model: the
    controller grows draft_len from 1 toward spec_draft_max as windows
    fully accept."""
    from crowdllama_tpu.engine.scheduler import DONE, GenRequest, Scheduler

    cfg = get_config("tiny-test", max_context_length=128)
    params = jax.tree_util.tree_map(
        lambda a: a * 0, T.init_params(cfg, jax.random.PRNGKey(0),
                                       dtype=jnp.float32))
    spec = SpecModelRunner(cfg, params=params, max_slots=2, max_seq=128,
                           dtype=jnp.float32, draft_len=1)
    sched = Scheduler(spec, decode_chunk=4, spec_draft_max=3)
    sched.start()
    try:
        req = GenRequest(prompt_ids=[3, 1, 4, 1, 5], max_tokens=48,
                         eos_id=-1)
        await sched.submit(req)
        while True:
            tok, _ = await asyncio.wait_for(req.out.get(), 60)
            if tok is DONE:
                break
        assert spec.draft_len > 1
        assert spec.draft_len <= 3
        assert sched.spec_retunes >= 1
        g = sched.telemetry_gauges()
        assert g["spec_draft_len"] == float(spec.draft_len)
        assert g["spec_accept_echo"] + g["spec_accept_gen"] > 0
    finally:
        await sched.stop()


def test_paused_spec_throughput_matches_plain_paged():
    """The ISSUE 4 cost guard: with a USELESS (random) draft the adaptive
    controller pauses speculation, and the paused runner's decode must
    stay within 10% of the plain paged runner's tok/s — it dispatches the
    parent's own program, so any gap is pure host overhead."""
    import time as _time

    from crowdllama_tpu.engine.paged import PagedModelRunner

    cfg = get_config("tiny-test", max_context_length=128)
    params = T.init_params(cfg, jax.random.PRNGKey(0), dtype=jnp.float32)
    draft_cfg = get_config("tiny-test", max_context_length=128)
    draft_params = T.init_params(draft_cfg, jax.random.PRNGKey(99),
                                 dtype=jnp.float32)
    prompt = [5, 9, 5, 9, 5, 9, 5]

    def _setup(runner):
        state = runner.init_state()
        first, ks, vs, plen = runner.prefill(prompt, 0.0, 1.0,
                                             jax.random.PRNGKey(7))
        kw = {"prompt_tokens": prompt} if hasattr(runner, "set_draft_len") \
            else {}
        return runner.insert(state, 0, ks, vs, plen, first, 0.0, 1.0, **kw)

    def _best_time(runner, state, steps=16, reps=2):
        best = float("inf")
        for _ in range(reps):
            t0 = _time.monotonic()
            out, state = runner.decode_steps(state, steps)
            best = min(best, _time.monotonic() - t0)
        return best, state

    plain = PagedModelRunner(cfg, params=params, max_slots=2, max_seq=128,
                             page_size=32, mesh_spec="1")
    pstate = _setup(plain)
    _, pstate = _best_time(plain, pstate, steps=4, reps=1)  # compile warmup
    t_plain, _ = _best_time(plain, pstate)

    spec = _draft_runner(params, cfg, draft_cfg, draft_params, draft_len=3)
    spec.set_draft_len(0)  # what the controller converges to here
    sstate = _setup(spec)
    _, sstate = _best_time(spec, sstate, steps=4, reps=1)
    t_spec, _ = _best_time(spec, sstate)

    # best-of-2 on identical step counts; 10% + a 2ms floor for timer noise.
    assert t_spec <= t_plain * 1.10 + 0.002, (t_spec, t_plain)
